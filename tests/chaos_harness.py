"""Chaos-mesh builder shared by tests/test_chaos.py and tools/soak.py.

Builds in-proc validator nodes over real encrypted p2p (the same shape as
test_consensus_reactor.build_p2p_node) wrapped in `chaos.NodeHandle`s,
with a restart_fn that rebuilds transport/switch around the surviving
consensus state — the "restart" scenario action.
"""

from __future__ import annotations

import asyncio

from tendermint_tpu.chaos import NodeHandle
from tendermint_tpu.consensus.reactor import ConsensusReactor
from tendermint_tpu.p2p.key import NodeKey
from tendermint_tpu.p2p.node_info import NodeInfo
from tendermint_tpu.p2p.switch import Switch
from tendermint_tpu.p2p.transport import MultiplexTransport, NetAddress

from tests.helpers import (
    make_genesis,
    make_validators,
    make_weighted_validators,
)
from tests.test_consensus import make_node

NETWORK = "chaos-chain"


def _wire_node(cs, nk, ping_interval: float = 10.0):
    """Fresh transport + switch + consensus reactor for one node."""
    transport = None
    sw = None

    def node_info():
        return NodeInfo(
            node_id=nk.id,
            listen_addr=f"127.0.0.1:{transport.listen_port}",
            network=NETWORK,
            channels=sw.channels() if sw else b"",
        )

    transport = MultiplexTransport(nk, node_info)
    sw = Switch(transport, ping_interval=ping_interval)
    sw.add_reactor("consensus", ConsensusReactor(cs))
    return transport, sw


def build_chaos_handles(
    n: int = 4,
    tracer_factory=None,
    ping_interval: float = 10.0,
    powers=None,
    config=None,
) -> list[NodeHandle]:
    """n validator NodeHandles (not yet listening/started).

    `tracer_factory(name) -> Tracer` gives each node its OWN span ring
    (cluster tracing: obs.cluster merges the per-node dumps); default
    None keeps every node on the process-wide tracer. A small
    `ping_interval` makes the peer clock-offset EWMAs converge inside a
    short run. `powers` gives per-validator voting powers (n_i holds the
    key of validator index i in the sorted set). `config` overrides the
    per-node ConsensusConfig (adaptive-pacing scenarios)."""
    if powers is not None:
        vs, pvs = make_weighted_validators(powers)
        n = len(powers)
    else:
        vs, pvs = make_validators(n)
    genesis = make_genesis(vs)
    handles: list[NodeHandle] = []
    for i, pv in enumerate(pvs):
        tracer = tracer_factory(f"n{i}") if tracer_factory else None
        cs, app, l2, bs, ss = make_node(
            vs, pv, genesis, tracer=tracer, config=config
        )
        nk = NodeKey.generate()
        transport, sw = _wire_node(cs, nk, ping_interval=ping_interval)
        handles.append(
            NodeHandle(
                name=f"n{i}",
                cs=cs,
                node_key=nk,
                transport=transport,
                switch=sw,
                block_store=bs,
                restart_fn=_make_restart(handles),
            )
        )
    return handles


def _make_restart(handles: list[NodeHandle]):
    async def restart(handle: NodeHandle, net) -> None:
        """Rebuild p2p around the same consensus state (restart
        semantics: same privval + stores, fresh node key) and rejoin."""
        handle.node_key = NodeKey.generate()
        handle.transport, handle.switch = _wire_node(
            handle.cs,
            handle.node_key,
            ping_interval=handle.switch.ping_interval,
        )
        net.install(handle)
        await handle.transport.listen()
        await handle.switch.start()
        handle.switch.dial_peers_async(
            [
                NetAddress(h.node_key.id, "127.0.0.1", h.transport.listen_port)
                for h in handles
                if h is not handle and h.alive
            ],
            persistent=True,
        )
        await handle.cs.start()

    return restart


async def start_mesh(handles: list[NodeHandle]) -> None:
    """Listen, start switches, wire a persistent full mesh, start
    consensus. Chaos must already be installed (ScenarioRunner/
    ChaosNetwork.install) so transports wrap their connections."""
    for h in handles:
        await h.transport.listen()
        await h.switch.start()
    for h in handles:
        h.switch.dial_peers_async(
            [
                NetAddress(o.node_key.id, "127.0.0.1", o.transport.listen_port)
                for o in handles
                if o is not h
            ],
            persistent=True,
        )
    for h in handles:
        await h.cs.start()


async def stop_mesh(handles: list[NodeHandle]) -> None:
    for h in handles:
        if not h.alive:
            continue
        await h.cs.stop()
        await h.switch.stop()


def node_dump(handle: NodeHandle) -> dict:
    """A `dump_traces`-shaped dict for one in-proc node — the input
    obs.cluster/tools/cluster_trace.py consume. Only meaningful when the
    mesh was built with per-node tracers (tracer_factory)."""
    tracer = handle.cs.tracer
    return {
        "node_id": handle.node_key.id,
        "moniker": handle.name,
        "epoch_wall_ns": tracer.epoch_wall_ns,
        "records": [r.to_json() for r in tracer.records()],
        "peer_clock": handle.switch.peer_clock_table(),
    }


async def chain_hashes(handles: list[NodeHandle], height: int) -> set:
    return {
        h.block_store.load_block(height).hash()
        for h in handles
        if h.alive and h.block_store.height >= height
    }
