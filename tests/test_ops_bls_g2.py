"""Device G2 kernel vs the host oracle (crypto/bls12_381.py).

Same strategy as test_ops_bls_g1.py one tower level up: Fp2 arithmetic
against python ints, the masked group law against the host Jacobian
oracle on generic/equal/opposite/infinity inputs, and the aggregation
tree against aggregate_public_keys' serial sum."""

import random

import numpy as np

import jax
import jax.numpy as jnp

from tendermint_tpu.crypto import bls12_381 as c
from tendermint_tpu.ops import bls_g2 as k

fe = k.fe
P = k.P
rng = random.Random(99)

_f2mulc = jax.jit(lambda a, b: k.f2_canonical(k.f2_mul(a, b)))
_f2sqrc = jax.jit(lambda a: k.f2_canonical(k.f2_sqr(a)))
_f2subc = jax.jit(lambda a, b: k.f2_canonical(k.f2_sub(a, b)))


def _rand_f2():
    return (rng.randrange(P), rng.randrange(P))


def _host_f2mul(a, b):
    return c.f2_mul(a, b)


def test_fp2_arithmetic_matches_host():
    for _ in range(8):
        a, b = _rand_f2(), _rand_f2()
        ja = jnp.asarray(k.f2_from_host(a))
        jb = jnp.asarray(k.f2_from_host(b))
        got = k.f2_to_host(np.asarray(_f2mulc(ja, jb)))
        assert got == c.f2_mul(a, b)
        assert k.f2_to_host(np.asarray(_f2sqrc(ja))) == c.f2_mul(a, a)
        assert k.f2_to_host(np.asarray(_f2subc(ja, jb))) == c.f2_sub(a, b)


def test_fp48_field_worst_case_bounds():
    """Worst-case bound stress for the make_field(P, 48) instance (the
    vecfield docstring's per-instance pinning; the secp instance has its
    own in test_ops_secp.py): all limbs at the loose bound through a mul
    chain must keep the invariant and exact values."""
    _mul48 = jax.jit(fe.mul)
    _canon48 = jax.jit(fe.canonical)
    worst = jnp.full((fe.NLIMBS,), (1 << 11) - 1, dtype=jnp.int32)
    wv = fe.to_int(np.asarray(worst))
    x = worst
    val = wv
    for _ in range(6):
        x = _mul48(x, x)
        val = val * val % P
        assert int(np.asarray(x).max()) < (1 << 11), "loose bound violated"
    assert fe.to_int(np.asarray(_canon48(x))) == val
    # sub/neg at the bound (exercises the 128p BIAS construction)
    _subc48 = jax.jit(lambda a, b: fe.canonical(fe.sub(a, b)))
    z = jnp.zeros((fe.NLIMBS,), dtype=jnp.int32)
    assert fe.to_int(np.asarray(_subc48(z, worst))) == (-wv) % P


def test_g2_group_law_matches_host():
    pts = [c.g2_mul(c.G2_GEN, rng.randrange(1, c.R)) for _ in range(4)]
    affs = [c.g2_from_affine(c.g2_to_affine(p)) for p in pts]
    for a in affs[:2]:
        for b in affs[2:]:
            ja = jnp.asarray(k.g2_from_host(a))
            jb = jnp.asarray(k.g2_from_host(b))
            got = k.g2_to_host(np.asarray(k.g2_add_jit(ja, jb)))
            want_aff = c.g2_to_affine(c.g2_add(a, b))
            got_aff = c.g2_to_affine(got)
            assert got_aff == want_aff
    # doubling two ways + identities + cancellation
    a = affs[0]
    ja = jnp.asarray(k.g2_from_host(a))
    dbl_host = c.g2_to_affine(c.g2_double(a))
    assert c.g2_to_affine(k.g2_to_host(np.asarray(k.g2_double_jit(ja)))) == dbl_host
    assert c.g2_to_affine(k.g2_to_host(np.asarray(k.g2_add_jit(ja, ja)))) == dbl_host
    inf = k.g2_identity(())
    assert c.g2_to_affine(
        k.g2_to_host(np.asarray(k.g2_add_jit(ja, inf)))
    ) == c.g2_to_affine(a)
    neg = c.g2_neg(a)
    jn_ = jnp.asarray(k.g2_from_host(neg))
    assert bool(np.asarray(jax.jit(k.g2_is_inf)(k.g2_add_jit(ja, jn_))))


def test_aggregate_public_keys_device_path(monkeypatch):
    """With the native library unavailable and N >= the device
    threshold, aggregate_public_keys rides ops/bls_g2 and must agree
    with the exact host loop (same preference-order contract as
    aggregate_signatures)."""
    from tendermint_tpu.crypto import bls_native, bls_signatures as bls

    monkeypatch.setattr(bls_native, "native_lib", lambda build=True: None)
    monkeypatch.setattr(bls, "DEVICE_AGGREGATE_MIN", 4)
    # 13 keys -> pad 16: the same tree level shapes the aggregate test
    # compiles, so this test adds no new XLA programs
    pubs = [
        bls.new_trusted_public_key(c.g2_mul(c.G2_GEN, 7 + i))
        for i in range(13)
    ]
    got = bls.aggregate_public_keys(pubs)
    acc = c.G2_INF
    for pk in pubs:
        acc = c.g2_add(acc, pk.key)
    assert c.g2_to_affine(got.key) == c.g2_to_affine(acc)


def test_g2_aggregate_matches_serial_sum():
    n = 13  # odd, forces identity padding in the tree
    pts = [c.g2_mul(c.G2_GEN, 1000 + i) for i in range(n)]
    affs = [c.g2_from_affine(c.g2_to_affine(p)) for p in pts]
    stack = jnp.asarray(np.stack([k.g2_from_host(p) for p in affs]))
    got = k.g2_to_host(np.asarray(k.g2_aggregate(stack)))
    acc = c.G2_INF
    for p in affs:
        acc = c.g2_add(acc, p)
    assert c.g2_to_affine(got) == c.g2_to_affine(acc)
