"""Device SHA-256 / SHA-512 kernels vs hashlib + the mod-L reduction
vs the host oracle (crypto/ed25519.py challenge)."""

import hashlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tendermint_tpu.ops import sha256 as dsha256
from tendermint_tpu.ops import sha512 as dsha512

LENGTHS = [0, 1, 3, 55, 56, 63, 64, 100, 111, 112, 127, 128, 200, 300]


def test_sha256_batch_matches_hashlib():
    msgs = [bytes([i & 0xFF] * n) for i, n in enumerate(LENGTHS)]
    buf, counts = dsha256.pad_messages(msgs)
    out = np.asarray(
        dsha256.sha256_batch_jit(jnp.asarray(buf), jnp.asarray(counts))
    )
    for i, m in enumerate(msgs):
        assert out[i].tobytes() == hashlib.sha256(m).digest(), f"len {len(m)}"


def test_sha512_batch_matches_hashlib():
    msgs = [bytes([(7 * i) & 0xFF] * n) for i, n in enumerate(LENGTHS)]
    buf, counts = dsha512.pad_messages(msgs)
    out = np.asarray(
        dsha512.sha512_batch_jit(jnp.asarray(buf), jnp.asarray(counts))
    )
    for i, m in enumerate(msgs):
        assert out[i].tobytes() == hashlib.sha512(m).digest(), f"len {len(m)}"


def test_reduce_mod_l_edges():
    """Adversarial 512-bit values: 0, 1, L-1, L, L+1, 2^252±1, all-FF —
    canonical k = v mod L, bit-for-bit."""
    L = dsha512.L
    vals = [0, 1, L - 1, L, L + 1, (1 << 252) - 1, (1 << 252),
            (1 << 256) - 1, (1 << 512) - 1, 12345 * L + 999]
    digests = np.stack(
        [
            np.frombuffer(v.to_bytes(64, "little"), dtype=np.uint8)
            for v in vals
        ]
    )
    out = np.asarray(jax.jit(dsha512.reduce_mod_l)(jnp.asarray(digests)))
    for i, v in enumerate(vals):
        want = (v % L).to_bytes(32, "little")
        assert out[i].tobytes() == want, f"value index {i}"


def test_challenge_batch_matches_host_oracle():
    """k = SHA-512(R||A||M) mod L fused on device == host challenge()."""
    from tendermint_tpu.crypto import ed25519 as host

    rng = np.random.RandomState(7)
    rows = []
    for n in (13, 80, 120, 121, 122, 200):
        r = rng.randint(0, 256, 32, dtype=np.uint8).tobytes()
        a = rng.randint(0, 256, 32, dtype=np.uint8).tobytes()
        m = rng.randint(0, 256, n, dtype=np.uint8).tobytes()
        rows.append((r, a, m))
    buf, counts = dsha512.pad_messages(
        [m for _, _, m in rows], prefix_pairs=[r + a for r, a, _ in rows]
    )
    out = np.asarray(
        dsha512.challenge_batch_jit(jnp.asarray(buf), jnp.asarray(counts))
    )
    for i, (r, a, m) in enumerate(rows):
        want = host.challenge(r, a, m).to_bytes(32, "little")
        assert out[i].tobytes() == want, f"row {i}"


def test_merkle_device_matches_host():
    from tendermint_tpu.crypto import merkle

    leaves = [bytes([i] * 32) for i in range(8)]
    arr = jnp.asarray(np.stack([np.frombuffer(x, np.uint8) for x in leaves]))
    # leaf rule
    dev_leaves = np.asarray(jax.jit(dsha256.merkle_leaf_hash)(arr))
    for i, x in enumerate(leaves):
        assert dev_leaves[i].tobytes() == merkle.leaf_hash(x)
    # full power-of-two tree
    root = np.asarray(jax.jit(dsha256.merkle_root_pow2)(arr)).tobytes()
    assert root == merkle.hash_from_byte_slices(leaves)


def test_device_merkle_production_route_matches_host(monkeypatch):
    """crypto/merkle.hash_from_byte_slices routes bulk leaf hashing to the
    device when TM_TPU_DEVICE_MERKLE_MIN is set (the silicon knob); roots
    must be identical to the all-host recursion for ragged, non-power-of-2
    leaf sets — this is the production call site VERDICT r2 row 44 flagged
    as missing."""
    from tendermint_tpu.crypto import merkle

    cases = [
        [b"a"],
        [b"tx-%d" % i + b"y" * (i % 57) for i in range(5)],
        [b"tx-%d" % i + b"z" * (i % 91) for i in range(33)],
        [b"" for _ in range(8)],
    ]
    host_roots = [merkle.hash_from_byte_slices(c) for c in cases]
    monkeypatch.setattr(merkle, "DEVICE_LEAF_MIN", 2)
    dev_roots = [merkle.hash_from_byte_slices(c) for c in cases]
    assert dev_roots == host_roots
    # the leaf kernel really is what ran for the big case
    leaves = merkle._device_leaf_hashes(cases[2])
    assert leaves == [merkle.leaf_hash(x) for x in cases[2]]


def test_scan_and_unrolled_compression_agree(monkeypatch):
    """The two compression forms (scan for CPU compile tractability,
    straight-line for the TPU executor) must be bit-exact. Run both in
    EAGER mode — op-by-op dispatch, no XLA program build — so CI never
    pays the unrolled form's hour-class CPU compile."""
    rng = np.random.default_rng(7)
    st512 = jnp.asarray(rng.integers(0, 1 << 32, (3, 8), dtype=np.uint32))
    sl512 = jnp.asarray(rng.integers(0, 1 << 32, (3, 8), dtype=np.uint32))
    wh = jnp.asarray(rng.integers(0, 1 << 32, (3, 16), dtype=np.uint32))
    wl = jnp.asarray(rng.integers(0, 1 << 32, (3, 16), dtype=np.uint32))

    monkeypatch.setenv("TM_TPU_SHA_SCAN", "0")
    uh, ul = dsha512._compress512(st512, sl512, wh, wl)
    monkeypatch.setenv("TM_TPU_SHA_SCAN", "1")
    sh, sl = dsha512._compress512(st512, sl512, wh, wl)
    np.testing.assert_array_equal(np.asarray(uh), np.asarray(sh))
    np.testing.assert_array_equal(np.asarray(ul), np.asarray(sl))

    st256 = jnp.asarray(rng.integers(0, 1 << 32, (3, 8), dtype=np.uint32))
    blk = jnp.asarray(rng.integers(0, 1 << 32, (3, 16), dtype=np.uint32))
    monkeypatch.setenv("TM_TPU_SHA_SCAN", "0")
    u256 = dsha256._compress(st256, blk)
    monkeypatch.setenv("TM_TPU_SHA_SCAN", "1")
    s256 = dsha256._compress(st256, blk)
    np.testing.assert_array_equal(np.asarray(u256), np.asarray(s256))
