"""UPnP port mapping against an in-process fake IGD gateway.

Drives the full reference flow (p2p/upnp/upnp.go): SSDP discovery,
description fetch, WANIPConnection control-URL resolution, and the SOAP
AddPortMapping / GetExternalIPAddress / DeletePortMapping actions — all
against a loopback UDP responder + HTTP server, no real gateway."""

import asyncio
import socket
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

from tendermint_tpu.p2p import upnp

_DESC_XML = """<?xml version="1.0"?>
<root xmlns="urn:schemas-upnp-org:device-1-0">
 <device>
  <deviceType>urn:schemas-upnp-org:device:InternetGatewayDevice:1</deviceType>
  <deviceList><device>
   <serviceList>
    <service>
     <serviceType>urn:schemas-upnp-org:service:WANIPConnection:1</serviceType>
     <controlURL>/ctl/IPConn</controlURL>
    </service>
   </serviceList>
  </device></deviceList>
 </device>
</root>"""


class _FakeIGD:
    """SSDP responder + description/SOAP HTTP endpoint on loopback."""

    def __init__(self):
        self.mappings: dict[int, tuple[int, str]] = {}
        self.deleted: list[int] = []

        class Handler(BaseHTTPRequestHandler):
            igd = self

            def log_message(self, *a):
                pass

            def do_GET(self):
                body = _DESC_XML.encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n).decode()

                def field(tag):
                    a = body.find(f"<{tag}>") + len(tag) + 2
                    b = body.find(f"</{tag}>")
                    return body[a:b]

                action = self.headers.get("SOAPAction", "")
                if "AddPortMapping" in action:
                    ext = int(field("NewExternalPort"))
                    self.igd.mappings[ext] = (
                        int(field("NewInternalPort")),
                        field("NewInternalClient"),
                    )
                    resp = "<ok/>"
                elif "DeletePortMapping" in action:
                    ext = int(field("NewExternalPort"))
                    self.igd.mappings.pop(ext, None)
                    self.igd.deleted.append(ext)
                    resp = "<ok/>"
                elif "GetExternalIPAddress" in action:
                    resp = (
                        "<NewExternalIPAddress>203.0.113.7"
                        "</NewExternalIPAddress>"
                    )
                else:
                    self.send_response(500)
                    self.end_headers()
                    return
                data = resp.encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self.http = HTTPServer(("127.0.0.1", 0), Handler)
        self.http_port = self.http.server_port
        threading.Thread(target=self.http.serve_forever, daemon=True).start()

        # SSDP responder on a loopback UDP port
        self.udp = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.udp.bind(("127.0.0.1", 0))
        self.ssdp_addr = self.udp.getsockname()

        def respond():
            try:
                while True:
                    data, addr = self.udp.recvfrom(4096)
                    if b"M-SEARCH" not in data:
                        continue
                    resp = (
                        "HTTP/1.1 200 OK\r\n"
                        f"LOCATION: http://127.0.0.1:{self.http_port}/desc\r\n"
                        "ST: urn:schemas-upnp-org:device:"
                        "InternetGatewayDevice:1\r\n\r\n"
                    ).encode()
                    self.udp.sendto(resp, addr)
            except OSError:
                pass

        threading.Thread(target=respond, daemon=True).start()

    def close(self):
        self.http.shutdown()
        self.udp.close()


def test_discover_map_unmap_roundtrip():
    igd = _FakeIGD()
    try:
        gw = upnp.discover(timeout=3.0, ssdp_addr=igd.ssdp_addr)
        assert gw.service_type.endswith("WANIPConnection:1")
        assert gw.control_url.endswith("/ctl/IPConn")
        gw.add_port_mapping(26656, 26656)
        assert 26656 in igd.mappings
        assert igd.mappings[26656][0] == 26656
        assert gw.get_external_ip() == "203.0.113.7"
        gw.delete_port_mapping(26656)
        assert 26656 not in igd.mappings
        assert igd.deleted == [26656]
    finally:
        igd.close()


def test_async_map_listen_port_best_effort():
    igd = _FakeIGD()

    async def run():
        gw = await upnp.map_listen_port(
            26700, timeout=3.0, ssdp_addr=igd.ssdp_addr
        )
        assert gw is not None
        assert 26700 in igd.mappings
        await upnp.unmap_listen_port(gw, 26700)
        assert 26700 not in igd.mappings
        # no gateway at a dead address: returns None, never raises
        dead = await upnp.map_listen_port(
            26701, timeout=0.3, ssdp_addr=("127.0.0.1", 1)
        )
        assert dead is None

    try:
        asyncio.run(run())
    finally:
        igd.close()
