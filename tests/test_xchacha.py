"""XChaCha20-Poly1305 + armored key-at-rest (reference
crypto/xchacha20poly1305/xchachapoly.go; vectors from
draft-irtf-cfrg-xchacha)."""

import pytest

from tendermint_tpu.crypto import xchacha


def test_hchacha20_draft_vector():
    # draft-irtf-cfrg-xchacha §2.2.1 (cross-validated transitively by the
    # independent full §A.3 AEAD vector below, which routes through
    # hchacha20 and matches ciphertext+tag byte-for-byte)
    key = bytes.fromhex(
        "000102030405060708090a0b0c0d0e0f101112131415161718191a1b1c1d1e1f"
    )
    nonce = bytes.fromhex("000000090000004a0000000031415927")
    out = xchacha.hchacha20(key, nonce)
    assert out == bytes.fromhex(
        "82413b4227b27bfed30e42508a877d73a0f9e4d58a74a853c12ec41326d3ecdc"
    )


def test_xchacha_aead_draft_vector():
    # draft-irtf-cfrg-xchacha §A.3
    key = bytes(range(0x80, 0xA0))
    nonce = bytes.fromhex("404142434445464748494a4b4c4d4e4f5051525354555657")
    ad = bytes.fromhex("50515253c0c1c2c3c4c5c6c7")
    pt = (
        b"Ladies and Gentlemen of the class of '99: If I could offer you "
        b"only one tip for the future, sunscreen would be it."
    )
    ct = xchacha.seal(key, nonce, pt, ad)
    assert ct[-16:] == bytes.fromhex("c0875924c1c7987947deafd8780acf49")
    assert xchacha.open_(key, nonce, ct, ad) == pt
    # tampering is caught
    bad = ct[:5] + bytes([ct[5] ^ 1]) + ct[6:]
    with pytest.raises(Exception):
        xchacha.open_(key, nonce, bad, ad)


def test_armor_roundtrip_and_checksum():
    payload = b"\x01\x02secret-material" * 5
    text = xchacha.armor_encode(payload, {"kdf": "scrypt"})
    got, headers = xchacha.armor_decode(text)
    assert got == payload and headers["kdf"] == "scrypt"
    # corrupt a base64 body char: CRC24 catches it
    lines = text.splitlines()
    body_i = next(
        i for i, ln in enumerate(lines)
        if ln and ":" not in ln and not ln.startswith(("-", "="))
    )
    lines[body_i] = ("B" if lines[body_i][0] != "B" else "C") + lines[body_i][1:]
    with pytest.raises(ValueError):
        xchacha.armor_decode("\n".join(lines))


def test_encrypt_decrypt_key_at_rest():
    priv = bytes(range(64))
    armored = xchacha.encrypt_key(priv, "correct horse")
    assert "BEGIN TENDERMINT PRIVATE KEY" in armored
    assert xchacha.decrypt_key(armored, "correct horse") == priv
    with pytest.raises(ValueError):
        xchacha.decrypt_key(armored, "wrong pass")
