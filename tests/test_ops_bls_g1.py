"""Device BLS12-381 G1 kernel vs the host oracle (crypto/bls12_381.py).

Includes the loose-invariant stress the module docstring promises: the
carry-pass bound chain is pinned empirically at adversarial extremes.
All device entry points go through jit — per-op eager dispatch of
48-limb vectors is dispatch-bound on the CPU test backend.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tendermint_tpu.crypto import bls12_381 as host
from tendermint_tpu.ops import bls_g1 as dev

P = host.P


def _rand_fe(rng):
    return rng.randrange(P)


# --- field layer ----------------------------------------------------------


def test_fp_mul_matches_host_and_keeps_invariant():
    import random

    rng = random.Random(1)
    vals = [0, 1, P - 1, P - 2, (1 << 380) - 1] + [
        _rand_fe(rng) for _ in range(11)
    ]
    a = jnp.asarray(np.stack([dev.from_int(v) for v in vals]))
    b = jnp.asarray(np.stack([dev.from_int(v) for v in reversed(vals)]))
    out = dev.mul_jit(a, b)
    arr = np.asarray(out)
    assert arr.max() < (1 << 11), f"loose invariant broken: {arr.max()}"
    assert arr.min() >= 0
    can = np.asarray(dev.canonical_jit(out))
    for i, (x, y) in enumerate(zip(vals, reversed(vals))):
        assert dev.to_int(can[i]) == x * y % P, f"row {i}"


@jax.jit
def _stress_step(x):
    x = dev.mul(x, x)
    y = dev.sub(dev.add(x, x), x)
    return x, y


def test_fp_stress_iterated_worst_case():
    """Iterate mul/add/sub on all-max loose inputs: limbs must stay
    inside the loose invariant and values must track Python ints."""
    worst = jnp.full((2, dev.NLIMBS), (1 << 11) - 1, dtype=jnp.int32)
    vx = [dev.to_int(np.asarray(worst)[i]) % P for i in range(2)]
    y = worst
    for it in range(3):
        x, y = _stress_step(y)
        for arr in (np.asarray(x), np.asarray(y)):
            assert arr.max() < (1 << 11), f"iter {it}: {arr.max()}"
            assert arr.min() >= 0, f"iter {it}: negative limb"
        vx = [v * v % P for v in vx]  # y == x value-wise (x + x - x)
    can = np.asarray(dev.canonical_jit(y))
    for i in range(2):
        assert dev.to_int(can[i]) == vx[i]


def test_fp_canonical_extremes():
    cases = [0, 1, P - 1, P, P + 1, 2 * P - 1, (1 << 384) - 1]
    # feed raw (possibly > p) limb vectors: value mod p must come back
    arrs = [
        np.array([int(b) for b in v.to_bytes(48, "little")], dtype=np.int32)
        for v in cases
    ]
    can = np.asarray(dev.canonical_jit(jnp.asarray(np.stack(arrs))))
    for i, v in enumerate(cases):
        assert dev.to_int(can[i]) == v % P, f"case {i}"


# --- group layer ----------------------------------------------------------


def _host_points(n, seed=3):
    import random

    rng = random.Random(seed)
    pts = []
    for _ in range(n):
        k = rng.randrange(1, host.R)
        pts.append(host.g1_mul(host.G1_GEN, k))
    return pts


def test_g1_add_double_and_edges_match_host():
    """Regular adds, doubling-via-add, inf handling, p + (-p) — one
    batch through the branch-free kernel (host oracle g1_add)."""
    pts = _host_points(3)
    p1, p2, p3 = pts
    inf = host.G1_INF
    rows_a = [p1, p2, inf, p1, p1, p1]
    rows_b = [p2, p3, p1, inf, p1, host.g1_neg(p1)]
    a = jnp.asarray(np.stack([dev.g1_from_host(p) for p in rows_a]))
    b = jnp.asarray(np.stack([dev.g1_from_host(p) for p in rows_b]))
    out = dev.g1_add_jit(a, b)
    wants = [
        host.g1_add(x, y) for x, y in zip(rows_a, rows_b)
    ]
    for i, w in enumerate(wants):
        assert host.g1_eq(dev.g1_to_host(out[i]), w), f"row {i}"

    dbl = dev.g1_double_jit(a[:2])
    for i in range(2):
        assert host.g1_eq(
            dev.g1_to_host(dbl[i]), host.g1_double(pts[i])
        ), f"dbl row {i}"


def test_g1_aggregate_matches_host_sum():
    """The aggregation workload: device tree-sum == host serial sum,
    non-power-of-two batch (pads with identity)."""
    pts = _host_points(3, seed=5)
    arr = jnp.asarray(np.stack([dev.g1_from_host(p) for p in pts]))
    got = dev.g1_to_host(dev.g1_aggregate_jit(arr))
    want = host.G1_INF
    for p in pts:
        want = host.g1_add(want, p)
    assert host.g1_eq(got, want)
