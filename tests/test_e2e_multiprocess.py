"""Multi-process e2e: real OS processes, real sockets, kill -9, restart.

Reference: test/e2e/runner (start/perturb/wait) + runner/perturb.go's
kill perturbation — compressed to a pytest: `testnet` CLI output is booted
as N separate `python -m tendermint_tpu start` processes on localhost,
heights converge over RPC, one validator dies by SIGKILL (no cleanup, no
flush — the WAL+gossip recovery path must cope), the survivors keep
committing, and the restarted process catches back up.

This exercises the ASSEMBLED Node end-to-end across process boundaries —
the class of test that catches wiring gaps in-proc harnesses can't
(VERDICT r2: the unwired BLS signer would have been caught here).
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
N = 4  # BFT floor: killing 1 of 4 leaves >2/3 power (3 of 3 would not)


def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _rpc(port: int, method: str, timeout: float = 3.0, **params):
    body = json.dumps(
        {"jsonrpc": "2.0", "method": method, "params": params, "id": 1}
    ).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/",
        data=body,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        out = json.loads(resp.read())
    if "error" in out and out["error"]:
        raise RuntimeError(str(out["error"]))
    return out["result"]


def _height(port: int) -> int:
    return int(_rpc(port, "status")["sync_info"]["latest_block_height"])


def _wait_heights(ports, target: int, deadline_s: float) -> None:
    t0 = time.monotonic()
    last = {}
    while time.monotonic() - t0 < deadline_s:
        done = 0
        for p in ports:
            try:
                last[p] = _height(p)
            except Exception:
                last[p] = last.get(p, -1)
            if last.get(p, -1) >= target:
                done += 1
        if done == len(ports):
            return
        time.sleep(1.0)
    raise TimeoutError(f"heights {last} never reached {target}")


def _spawn(home: str):
    env = dict(os.environ)
    # the spawned nodes verify 4-validator batches (host fast path); the
    # CPU backend keeps them off the single tunnelled TPU chip — four
    # processes warming big-tier tables through one tunnel at startup is
    # the measured flake source for the stage deadlines
    env["JAX_PLATFORMS"] = "cpu"
    env["TM_TPU_SKIP_WARM"] = "1"
    # pure-host verification: a 4-validator net's batches never earn a
    # JAX compile, and a blocksync window must not trigger one either
    env["TM_TPU_MIN_DEVICE_BATCH"] = str(1 << 30)
    log = open(os.path.join(home, "node.log"), "ab")
    try:
        return subprocess.Popen(
            [sys.executable, "-m", "tendermint_tpu", "--home", home, "start"],
            cwd=REPO,
            env=env,
            stdout=log,
            stderr=log,
            start_new_session=True,  # survives pytest's signal handling
        )
    finally:
        log.close()  # the child holds its own inherited descriptor


def _boot_testnet(base, chain_id, configure_node=None):
    """Generate an N-node testnet, rewrite its fixed ports to free
    ephemeral ones (parallel CI runs must not collide), apply the
    per-node `configure_node(i, cfg, homes)` hook, and return
    (homes, rpc_ports, peers)."""
    from tendermint_tpu.config import Config
    from tendermint_tpu.p2p.key import NodeKey

    rc = subprocess.run(
        [
            sys.executable,
            "-m",
            "tendermint_tpu",
            "testnet",
            "--v",
            str(N),
            "--output",
            base,
            "--chain-id",
            chain_id,
        ],
        cwd=REPO,
        capture_output=True,
        timeout=120,
    )
    assert rc.returncode == 0, rc.stderr.decode()

    ports = _free_ports(2 * N)
    p2p_ports = ports[:N]
    rpc_ports = ports[N:]
    homes = [os.path.join(base, f"node{i}") for i in range(N)]
    ids = [
        NodeKey.load_or_generate(os.path.join(h, "config", "node_key.json")).id
        for h in homes
    ]
    peers = ",".join(
        f"{ids[i]}@127.0.0.1:{p2p_ports[i]}" for i in range(N)
    )
    for i, h in enumerate(homes):
        cfg = Config.load(h)
        cfg.root_dir = h
        cfg.p2p.laddr = f"tcp://127.0.0.1:{p2p_ports[i]}"
        cfg.rpc.laddr = f"tcp://127.0.0.1:{rpc_ports[i]}"
        cfg.p2p.persistent_peers = peers
        if configure_node is not None:
            configure_node(i, cfg, homes)
        cfg.save()
    return homes, rpc_ports, peers


def test_multiprocess_testnet_kill9_restart(tmp_path):
    base = str(tmp_path / "net")
    homes, rpc_ports, peers = _boot_testnet(base, "mp-e2e")

    procs = {i: _spawn(homes[i]) for i in range(N)}
    try:
        # all nodes commit (JAX import + dial storms are slow on 1 core)
        _wait_heights(rpc_ports, 3, deadline_s=150)

        # perturb: SIGKILL the last validator — no flush, no goodbye
        victim = N - 1
        os.kill(procs[victim].pid, signal.SIGKILL)
        procs[victim].wait(timeout=30)

        # BFT with (N-1)/N: survivors keep committing
        survivors = rpc_ports[:victim]
        target = max(_height(p) for p in survivors) + 2
        _wait_heights(survivors, target, deadline_s=120)

        # restart the victim from its (possibly torn) on-disk state:
        # WAL replay + handshake + gossip catchup
        procs[victim] = _spawn(homes[victim])
        catchup = max(_height(p) for p in survivors) + 1
        _wait_heights([rpc_ports[victim]], catchup, deadline_s=150)

        # all agree on the chain at a common height
        h = min(_height(p) for p in rpc_ports)
        hashes = {
            _rpc(p, "block", height=h)["block_id"]["hash"]
            for p in rpc_ports
        }
        assert len(hashes) == 1, f"nodes diverged at height {h}"

        def spawn_observer(name, configure=None):
            """Boot a fresh NON-validator node home (key not in genesis,
            empty store) joined to the live net; returns its rpc port."""
            import shutil

            from tendermint_tpu.config import Config as _C

            home = os.path.join(base, name)
            cfg = _C()
            cfg.root_dir = home
            cfg.ensure_dirs()
            shutil.copy(
                os.path.join(homes[0], "config", "genesis.json"),
                os.path.join(home, "config", "genesis.json"),
            )
            op2p, orpc = _free_ports(2)
            cfg.p2p.laddr = f"tcp://127.0.0.1:{op2p}"
            cfg.rpc.laddr = f"tcp://127.0.0.1:{orpc}"
            cfg.p2p.persistent_peers = peers
            if configure is not None:
                configure(cfg)
            cfg.save()
            procs[name] = _spawn(home)
            return orpc

        # a FRESH full node joins and blocksyncs the whole chain from the
        # live net — the observer role (reference e2e "full" node mode)
        frpc = spawn_observer("fullnode")
        target = max(_height(p) for p in rpc_ports)
        _wait_heights([frpc], target, deadline_s=150)
        hf = _rpc(frpc, "block", height=h)["block_id"]["hash"]
        assert hf in hashes, "full node synced a different chain"

        # a STATESYNC node bootstraps from a snapshot (light-client trust
        # root over the survivors' RPC + chunks over p2p) instead of
        # replaying blocks — reference test/e2e statesync node mode
        trust_h = max(2, _height(rpc_ports[0]) - 3)
        commit = _rpc(rpc_ports[0], "commit", height=trust_h)
        trust_hash = commit["signed_header"]["commit"]["block_id"]["hash"]

        def _cfg_statesync(cfg):
            cfg.statesync.enable = True
            cfg.statesync.rpc_servers = (
                f"127.0.0.1:{rpc_ports[0]},127.0.0.1:{rpc_ports[1]}"
            )
            cfg.statesync.trust_height = trust_h
            cfg.statesync.trust_hash = trust_hash.lower()
            cfg.statesync.discovery_time = 3.0

        srpc = spawn_observer("statesyncnode", _cfg_statesync)
        target = max(_height(p) for p in rpc_ports)
        _wait_heights([srpc], target, deadline_s=180)
        # proof it STATE-synced: its store has no early blocks
        try:
            _rpc(srpc, "block", height=1)
            assert False, "statesync node has genesis-era blocks"
        except RuntimeError:
            pass  # -32000 no block — expected
    finally:
        for p in procs.values():
            if p.poll() is None:
                os.killpg(p.pid, signal.SIGKILL)


def test_multiprocess_upgrade_switch_to_sequencer(tmp_path):
    """The Morph upgrade across real processes (reference upgrade/ +
    sequencer handoff): a 4-validator net commits through switch_height,
    every node stops BFT, the keyed node becomes THE sequencer producing
    ECDSA-signed BlockV2s, and the other three follow via the broadcast
    reactor over p2p — asserted through the new status RPC fields."""
    from tendermint_tpu.crypto import secp256k1
    from tendermint_tpu.sequencer import LocalSigner

    seq_key = secp256k1.PrivKey.from_secret(b"mp-sequencer")
    seq_addr = LocalSigner(seq_key).address().hex()
    SWITCH = 4

    def configure(i, cfg, homes):
        cfg.consensus.switch_height = SWITCH
        cfg.sequencer.block_interval = 0.2
        cfg.sequencer.sequencer_addresses = seq_addr
        if i == 0:
            with open(
                os.path.join(homes[i], "config", "sequencer_key"), "w"
            ) as f:
                f.write(seq_key.bytes().hex())
            cfg.sequencer.sequencer_key_file = "config/sequencer_key"

    base = str(tmp_path / "net")
    homes, rpc_ports, peers = _boot_testnet(
        base, "mp-upgrade", configure_node=configure
    )

    procs = {i: _spawn(homes[i]) for i in range(N)}
    try:
        # BFT runs to the switch; then every node reports sequencer mode
        # and the V2 chain advances past the BFT head on ALL nodes
        t0 = time.monotonic()
        last = {}
        while time.monotonic() - t0 < 210:
            # a crashed node must not keep counting via stale samples
            assert all(
                pr.poll() is None for pr in procs.values()
            ), "a node process died during the switch"
            done = 0
            for p in rpc_ports:
                try:
                    si = _rpc(p, "status")["sync_info"]
                    last[p] = (
                        si["latest_block_height"],
                        si["sequencer_mode"],
                        si["v2_height"],
                    )
                except Exception:
                    last[p] = last.get(p, (0, False, 0))
                h_, seq, v2 = last[p]
                if seq and v2 >= SWITCH + 3:
                    done += 1
            if done == len(rpc_ports):
                break
            time.sleep(1.0)
        else:
            raise TimeoutError(f"sequencer switch never converged: {last}")

        # BFT stopped at the switch height everywhere
        for p in rpc_ports:
            assert last[p][0] <= SWITCH, last
    finally:
        for p in procs.values():
            if p.poll() is None:
                os.killpg(p.pid, signal.SIGKILL)


def test_multiprocess_statesync_external_grpc_app(tmp_path):
    """VERDICT r4 missing #3: the reference's statesync shape — a fresh
    node bootstrapping from peers while its app is a SEPARATE process
    (statesync/syncer.go:141-409 drives the app's snapshot conns) — run
    end-to-end: 4-validator net commits, a new node with
    proxy_app=tcp://... --abci grpc statesyncs a snapshot, the chunks
    are restored INTO the external `abci-cli kvstore --transport grpc`
    process, and the node follows the live chain."""
    base = str(tmp_path / "net")
    homes, rpc_ports, peers = _boot_testnet(base, "mp-ss-grpc")

    procs = {i: _spawn(homes[i]) for i in range(N)}
    app_proc = None
    try:
        # snapshots exist once the chain commits a few heights
        _wait_heights(rpc_ports, 5, deadline_s=180)

        # the external ABCI app: its own OS process, empty state
        (app_port,) = _free_ports(1)
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        app_log = open(os.path.join(base, "app.log"), "ab")
        try:
            app_proc = subprocess.Popen(
                [
                    sys.executable,
                    "-m",
                    "tendermint_tpu",
                    "abci-cli",
                    "kvstore",
                    "--transport",
                    "grpc",
                    "--port",
                    str(app_port),
                ],
                cwd=REPO,
                env=env,
                stdout=app_log,
                stderr=app_log,
                start_new_session=True,
            )
        finally:
            app_log.close()

        trust_h = max(2, _height(rpc_ports[0]) - 3)
        commit = _rpc(rpc_ports[0], "commit", height=trust_h)
        trust_hash = commit["signed_header"]["commit"]["block_id"]["hash"]

        import shutil

        from tendermint_tpu.config import Config as _C

        home = os.path.join(base, "grpcstatesync")
        cfg = _C()
        cfg.root_dir = home
        cfg.ensure_dirs()
        shutil.copy(
            os.path.join(homes[0], "config", "genesis.json"),
            os.path.join(home, "config", "genesis.json"),
        )
        op2p, orpc = _free_ports(2)
        cfg.p2p.laddr = f"tcp://127.0.0.1:{op2p}"
        cfg.rpc.laddr = f"tcp://127.0.0.1:{orpc}"
        cfg.p2p.persistent_peers = peers
        cfg.base.proxy_app = f"tcp://127.0.0.1:{app_port}"
        cfg.base.abci = "grpc"
        cfg.statesync.enable = True
        cfg.statesync.rpc_servers = (
            f"127.0.0.1:{rpc_ports[0]},127.0.0.1:{rpc_ports[1]}"
        )
        cfg.statesync.trust_height = trust_h
        cfg.statesync.trust_hash = trust_hash.lower()
        cfg.statesync.discovery_time = 3.0
        cfg.save()
        procs["grpcstatesync"] = _spawn(home)

        target = max(_height(p) for p in rpc_ports)
        _wait_heights([orpc], target, deadline_s=240)

        # statesynced, not replayed: no genesis-era blocks
        try:
            _rpc(orpc, "block", height=1)
            assert False, "grpc statesync node has genesis-era blocks"
        except RuntimeError:
            pass

        # the EXTERNAL app process (started empty) now holds restored
        # state: its abci_info reports the post-snapshot height
        info = _rpc(orpc, "abci_info")["response"]
        assert info["data"] == "kvstore"
        assert int(info["last_block_height"]) >= trust_h, info

        # and the chain it serves matches the net — compare at a height
        # the statesync node actually stores (its store starts at the
        # snapshot base, above trust_h)
        ho = _height(orpc)
        got = _rpc(orpc, "block", height=ho)["block_id"]["hash"]
        _wait_heights(rpc_ports, ho, deadline_s=60)
        want = {
            _rpc(p, "block", height=ho)["block_id"]["hash"]
            for p in rpc_ports
        }
        assert got in want, "grpc statesync node on a different chain"
    finally:
        if app_proc is not None and app_proc.poll() is None:
            os.killpg(app_proc.pid, signal.SIGKILL)
        for p in procs.values():
            if p.poll() is None:
                os.killpg(p.pid, signal.SIGKILL)
