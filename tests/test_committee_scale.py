"""Committee-scale vote plane: vectorized bitsets, batched vote gossip,
commit-catchup budgets, mixed-version interop, and the 32/100-validator
smoke nets (ISSUE 9 / ROADMAP item 5).

The property tests pin the word-wise libs/bits.py ops bit-for-bit
against a per-bit reference implementation (the pre-vectorization code),
and the batch-path tests pin the acceptance contract: a VoteBatchMessage
chunk lands in HeightVoteSet exactly the vote set the trickled
single-vote path would."""

import asyncio
import random

import numpy as np
import pytest

from tendermint_tpu.consensus.messages import (
    VoteBatchMessage,
    VoteMessage,
    decode_msg,
    encode_msg,
)
from tendermint_tpu.consensus.reactor import (
    COMMIT_CATCHUP_BUDGET,
    VOTE_BATCH_CHANNEL,
    VOTE_CHANNEL,
    ConsensusReactor,
    PeerRoundState,
)
from tendermint_tpu.consensus.vote_batcher import VoteBatcher
from tendermint_tpu.libs.bits import BitArray
from tendermint_tpu.types.vote import Vote, VoteType

from .helpers import (
    CHAIN_ID,
    T0,
    make_genesis,
    make_validators,
    make_weighted_validators,
    sign_commit,
)
from .test_consensus import make_node

pytestmark = pytest.mark.committee


# --- per-bit reference implementation (the pre-vectorization BitArray) ----


class _RefBits:
    """The old O(size)-per-call enumeration semantics, kept as the
    property-test oracle."""

    def __init__(self, size: int, bits: int = 0):
        self.size = size
        self.bits = bits

    def _mask(self):
        return (1 << self.size) - 1

    @classmethod
    def from_indices(cls, size, indices):
        r = cls(size)
        for i in indices:
            r.set(i, True)
        return r

    def get(self, i):
        if not 0 <= i < self.size:
            return False
        return bool((self.bits >> i) & 1)

    def set(self, i, v):
        if not 0 <= i < self.size:
            return False
        if v:
            self.bits |= 1 << i
        else:
            self.bits &= ~(1 << i)
        return True

    def sub(self, other):
        return _RefBits(
            self.size, self.bits & ~other.bits & self._mask()
        )

    def ones(self):
        return [i for i in range(self.size) if self.get(i)]

    def num_set(self):
        return bin(self.bits & self._mask()).count("1")


EDGE_SIZES = (0, 1, 63, 64, 65, 127, 128, 130, 200)


def _random_indices(rng, size, density=0.4):
    return [i for i in range(size) if rng.random() < density]


def test_bits_property_vs_reference():
    """Random op sequences: every vectorized op agrees with the per-bit
    reference, including word-boundary sizes."""
    rng = random.Random(20260803)
    for size in EDGE_SIZES:
        for _ in range(20):
            idx_a = _random_indices(rng, size)
            idx_b = _random_indices(rng, size)
            a = BitArray.from_indices(size, idx_a)
            b = BitArray.from_indices(size, idx_b)
            ra = _RefBits.from_indices(size, idx_a)
            rb = _RefBits.from_indices(size, idx_b)
            assert a.ones() == ra.ones()
            assert a.num_set() == ra.num_set()
            assert a.sub(b).ones() == ra.sub(rb).ones()
            assert b.sub(a).ones() == rb.sub(ra).ones()
            assert a.not_().ones() == [
                i for i in range(size) if not ra.get(i)
            ]
            assert a.and_(b).ones() == sorted(
                set(ra.ones()) & set(rb.ones())
            )
            assert a.or_(b).ones() == sorted(
                set(ra.ones()) | set(rb.ones())
            )
            # mutation parity
            if size:
                i = rng.randrange(size)
                a.set(i, True)
                ra.set(i, True)
                a.set((i * 7) % size, False)
                ra.set((i * 7) % size, False)
                assert a.ones() == ra.ones()


def test_bits_from_indices_edges():
    # out-of-range indices are ignored, same as the per-bit set() path
    a = BitArray.from_indices(8, [-1, 0, 3, 7, 8, 100])
    assert a.ones() == [0, 3, 7]
    assert BitArray.from_indices(0, [0, 1]).ones() == []
    assert BitArray.from_indices(1, [0]).ones() == [0]
    # word-boundary sizes round-trip through bytes
    for size in (63, 64, 65):
        a = BitArray.from_indices(size, [0, size - 1])
        rt = BitArray.from_bytes(size, a.to_bytes())
        assert rt == a


def test_bits_pick_random_membership_and_emptiness():
    assert BitArray(0).pick_random() == (0, False)
    assert BitArray(4).pick_random() == (0, False)
    a = BitArray.from_indices(130, [0, 63, 64, 65, 129])
    seen = set()
    for _ in range(200):
        i, ok = a.pick_random()
        assert ok and a.get(i)
        seen.add(i)
    assert seen == {0, 63, 64, 65, 129}  # all set bits reachable


def test_bits_pick_chunk():
    a = BitArray.from_indices(200, range(0, 200, 3))
    all_ones = a.ones()
    assert a.pick_chunk(0) == []
    assert sorted(a.pick_chunk(10_000)) == all_ones
    for limit in (1, 7, 64):
        chunk = a.pick_chunk(limit)
        assert len(chunk) == min(limit, len(all_ones))
        assert len(set(chunk)) == len(chunk)
        assert all(a.get(i) for i in chunk)
    assert BitArray(5).pick_chunk(3) == []
    # every set bit can lead a chunk (rotation fairness)
    b = BitArray.from_indices(6, [1, 3, 5])
    leads = {b.pick_chunk(2)[0] for _ in range(200)}
    assert leads == {1, 3, 5}


def test_bits_update_batch_set():
    a = BitArray(70)
    a.update([0, 64, 69, -1, 70, 200])
    assert a.ones() == [0, 64, 69]
    a.update([])
    assert a.ones() == [0, 64, 69]


# --- VoteBatchMessage codec ------------------------------------------------


def _make_votes(n=5, height=3, round_=0, vtype=VoteType.PRECOMMIT):
    vs, pvs = make_validators(n)
    from tendermint_tpu.types.block_id import BlockID
    from tendermint_tpu.types.part_set import PartSetHeader

    bid = BlockID(b"h" * 32, PartSetHeader(1, b"p" * 32))
    votes = []
    for i, pv in enumerate(pvs):
        v = Vote(
            type=vtype,
            height=height,
            round=round_,
            block_id=bid,
            timestamp_ns=T0 + i,
            validator_address=pv.get_pub_key().address(),
            validator_index=i,
            bls_signature=b"B" * 96 if i % 2 else b"",
        )
        pv.sign_vote(CHAIN_ID, v)
        votes.append(v)
    return vs, pvs, votes


def test_vote_batch_message_roundtrip():
    _, _, votes = _make_votes(5)
    msg = VoteBatchMessage(3, 0, VoteType.PRECOMMIT, votes,
                           pre_verified=[True] * 5)
    dec = decode_msg(encode_msg(msg))
    assert isinstance(dec, VoteBatchMessage)
    assert (dec.height, dec.round, dec.type) == (3, 0, VoteType.PRECOMMIT)
    assert len(dec.votes) == 5
    for a, b in zip(votes, dec.votes):
        assert a.signature == b.signature
        assert a.bls_signature == b.bls_signature
        assert a.validator_index == b.validator_index
        assert a.sign_bytes(CHAIN_ID) == b.sign_bytes(CHAIN_ID)
    # the in-proc verdict flags never ride the wire
    assert dec.pre_verified is None and dec.bls_pre_verified is None
    flags = list(dec.iter_flags())
    assert all(p is False and b is False for _, p, b in flags)
    # empty batch round-trips
    empty = decode_msg(encode_msg(VoteBatchMessage(9, 2, VoteType.PREVOTE, [])))
    assert empty.votes == [] and empty.round == 2


# --- semantics: batch path == trickled path into HeightVoteSet -------------


def test_height_vote_set_batch_equals_trickled():
    """Feeding a HeightVoteSet whole VoteBatchMessage chunks accepts
    exactly the same vote set bit-for-bit as one-at-a-time adds."""
    from tendermint_tpu.consensus.height_vote_set import HeightVoteSet
    from tendermint_tpu.obs import Tracer

    n = 32
    vs, pvs, votes = _make_votes(n, height=1)
    trickled = HeightVoteSet(CHAIN_ID, 1, vs, tracer=Tracer(enabled=False))
    batched = HeightVoteSet(CHAIN_ID, 1, vs, tracer=Tracer(enabled=False))
    for v in votes:
        assert trickled.add_vote(v, "peer", verified=True)
    # chunked like the gossip plane ships them (pick_chunk order)
    missing = BitArray.from_indices(n, range(n))
    fed = 0
    while fed < n:
        chunk_idx = missing.pick_chunk(7)
        if not chunk_idx:
            break
        chunk = VoteBatchMessage(
            1, 0, VoteType.PRECOMMIT, [votes[i] for i in chunk_idx],
            pre_verified=[True] * len(chunk_idx),
        )
        for vote, pre, _ in chunk.iter_flags():
            assert batched.add_vote(vote, "peer", verified=pre)
        for i in chunk_idx:
            missing.set(i, False)
        fed += len(chunk_idx)
    t_set = trickled.precommits(0)
    b_set = batched.precommits(0)
    assert t_set.bit_array() == b_set.bit_array()
    assert t_set.bit_array().num_set() == n
    for i in range(n):
        assert t_set.get_by_index(i) == b_set.get_by_index(i)
    assert b_set.has_two_thirds_majority()


# --- reactor unit paths ----------------------------------------------------


class _FakePeer:
    def __init__(self, peer_id="fakepeer", batch=True, capacity=10_000):
        self.id = peer_id
        self.sent: list[tuple[int, bytes]] = []
        self.capacity = capacity

        class _Info:
            channels = (
                bytes([0x20, 0x21, 0x22, 0x23, VOTE_BATCH_CHANNEL])
                if batch
                else bytes([0x20, 0x21, 0x22, 0x23])
            )

        self.node_info = _Info()

    def send(self, channel_id, msg):
        if len(self.sent) >= self.capacity:
            return False
        self.sent.append((channel_id, msg))
        return True


class _FakeSwitch:
    def __init__(self, peers=None):
        self.stopped: list[tuple[object, str]] = []
        self.peers = dict(peers or {})

    async def stop_peer_for_error(self, peer, reason):
        self.stopped.append((peer, reason))


def _reactor_fixture(n=32):
    vs, pvs = make_validators(n)
    genesis = make_genesis(vs)
    cs, *_ = make_node(vs, pvs[0], genesis)
    reactor = ConsensusReactor(cs)
    reactor.switch = _FakeSwitch()
    return cs, reactor, vs, pvs


def test_commit_catchup_sends_up_to_budget_legacy():
    """The old code returned after ONE reconstructed vote; the legacy
    single-vote path now ships up to COMMIT_CATCHUP_BUDGET per tick."""
    n = 40
    cs, reactor, vs, pvs = _reactor_fixture(n)
    from tendermint_tpu.types.block_id import BlockID
    from tendermint_tpu.types.part_set import PartSetHeader

    bid = BlockID(b"c" * 32, PartSetHeader(1, b"q" * 32))
    commit = sign_commit(vs, pvs, 1, 0, bid)
    peer = _FakePeer(batch=False)
    prs = PeerRoundState(height=1)
    sent = reactor._send_commit_votes(peer, prs, commit, batch_ok=False)
    assert sent == COMMIT_CATCHUP_BUDGET
    assert all(ch == VOTE_CHANNEL for ch, _ in peer.sent)
    assert len(peer.sent) == COMMIT_CATCHUP_BUDGET
    # next tick ships the remainder, no re-sends
    sent2 = reactor._send_commit_votes(peer, prs, commit, batch_ok=False)
    assert sent2 == n - COMMIT_CATCHUP_BUDGET
    assert reactor._send_commit_votes(peer, prs, commit, batch_ok=False) == 0


def test_commit_catchup_batches_whole_chunk():
    n = 40
    cs, reactor, vs, pvs = _reactor_fixture(n)
    from tendermint_tpu.types.block_id import BlockID
    from tendermint_tpu.types.part_set import PartSetHeader

    bid = BlockID(b"c" * 32, PartSetHeader(1, b"q" * 32))
    commit = sign_commit(vs, pvs, 1, 0, bid)
    peer = _FakePeer(batch=True)
    prs = PeerRoundState(height=1)
    sent = reactor._send_commit_votes(peer, prs, commit, batch_ok=True)
    assert sent == n  # n <= vote_batch_max: one chunk carries the commit
    assert len(peer.sent) == 1
    ch, raw = peer.sent[0]
    assert ch == VOTE_BATCH_CHANNEL
    msg = decode_msg(raw)
    assert isinstance(msg, VoteBatchMessage) and len(msg.votes) == n
    # the peer's bits are marked: nothing left to send
    assert reactor._send_commit_votes(peer, prs, commit, batch_ok=True) == 0


def test_send_missing_votes_batches_and_marks():
    n = 32
    cs, reactor, vs, pvs = _reactor_fixture(n)
    _, _, votes = _make_votes(n, height=1)
    from tendermint_tpu.types.vote_set import VoteSet

    vset = VoteSet(CHAIN_ID, 1, 0, VoteType.PRECOMMIT, vs)
    for v in votes:
        vset.add_vote(v, verified=True)
    peer = _FakePeer(batch=True)
    prs = PeerRoundState(height=1)
    sent = reactor._send_missing_votes(peer, prs, vset, batch_ok=True)
    assert sent == n
    assert len(peer.sent) == 1 and peer.sent[0][0] == VOTE_BATCH_CHANNEL
    assert reactor._send_missing_votes(peer, prs, vset, batch_ok=True) == 0
    # legacy peer still gets exactly one vote per call
    peer2 = _FakePeer(batch=False)
    prs2 = PeerRoundState(height=1)
    assert reactor._send_missing_votes(peer2, prs2, vset, batch_ok=False) == 1
    assert peer2.sent[0][0] == VOTE_CHANNEL


def test_receive_vote_batch_one_submission_one_queue_put():
    """A received chunk costs ONE micro-batcher submission (=> one
    scheduler dispatch round) and ONE state-machine queue put."""
    n = 32
    vs, pvs, votes = _make_votes(n, height=1)
    genesis = make_genesis(vs)

    calls = []

    class _StubVerifier:
        def verify(self, items):
            calls.append(len(items))
            return np.ones(len(items), dtype=bool)

    async def run():
        cs, *_ = make_node(vs, pvs[0], genesis)
        cs.rs.height = 1  # pubkey_for_vote resolves against validators
        reactor = ConsensusReactor(
            cs, vote_batcher=VoteBatcher(verifier=_StubVerifier())
        )
        reactor.switch = _FakeSwitch()
        peer = _FakePeer()
        prs = PeerRoundState(height=1)
        msg = VoteBatchMessage(1, 0, VoteType.PRECOMMIT, votes)
        await reactor._receive_vote_batch(peer, prs, msg)
        assert calls == [n]  # one coalesced verification
        assert cs.peer_msg_queue.qsize() == 1
        queued, peer_id = cs.peer_msg_queue.get_nowait()
        assert isinstance(queued, VoteBatchMessage)
        assert len(queued.votes) == n
        assert queued.pre_verified == [True] * n
        assert peer_id == peer.id
        # the peer's possession bits were recorded for every vote
        bits = prs.get_votes_bits(1, 0, VoteType.PRECOMMIT, n)
        assert bits.num_set() == n
        reactor.vote_batcher.stop()
        reactor.bls_batcher.stop()

    asyncio.run(run())


def test_receive_vote_batch_invalid_sig_stops_peer():
    n = 8
    vs, pvs, votes = _make_votes(n, height=1)
    votes[3].signature = b"\x00" * 64  # corrupt one
    genesis = make_genesis(vs)

    class _StubVerifier:
        def verify(self, items):
            # reject the all-zero signature like the device would
            return np.array(
                [it.sig != b"\x00" * 64 for it in items], dtype=bool
            )

    async def run():
        cs, *_ = make_node(vs, pvs[0], genesis)
        cs.rs.height = 1
        reactor = ConsensusReactor(
            cs, vote_batcher=VoteBatcher(verifier=_StubVerifier())
        )
        sw = _FakeSwitch()
        reactor.switch = sw
        peer = _FakePeer()
        prs = PeerRoundState(height=1)
        await reactor._receive_vote_batch(
            peer, prs, VoteBatchMessage(1, 0, VoteType.PRECOMMIT, votes)
        )
        assert sw.stopped and sw.stopped[0][0] is peer
        assert cs.peer_msg_queue.qsize() == 0  # nothing fed downstream
        reactor.vote_batcher.stop()
        reactor.bls_batcher.stop()

    asyncio.run(run())


def test_receive_vote_batch_dedups_known_votes():
    """Votes the node already holds verbatim skip signature work; a
    fully-known chunk feeds nothing downstream."""
    n = 8
    vs, pvs, votes = _make_votes(n, height=1)
    genesis = make_genesis(vs)

    calls = []

    class _StubVerifier:
        def verify(self, items):
            calls.append(len(items))
            return np.ones(len(items), dtype=bool)

    async def run():
        from tendermint_tpu.consensus.height_vote_set import HeightVoteSet
        from tendermint_tpu.obs import Tracer

        cs, *_ = make_node(vs, pvs[0], genesis)
        cs.rs.height = 1
        cs.rs.votes = HeightVoteSet(
            CHAIN_ID, 1, vs, tracer=Tracer(enabled=False)
        )
        reactor = ConsensusReactor(
            cs, vote_batcher=VoteBatcher(verifier=_StubVerifier())
        )
        reactor.switch = _FakeSwitch()
        # seed half the votes directly into the height vote set
        for v in votes[: n // 2]:
            cs.rs.votes.add_vote(v, "seed", verified=True)
        peer = _FakePeer()
        prs = PeerRoundState(height=1)
        await reactor._receive_vote_batch(
            peer, prs, VoteBatchMessage(1, 0, VoteType.PRECOMMIT, votes)
        )
        assert calls == [n - n // 2]  # only the fresh half verified
        queued, _ = cs.peer_msg_queue.get_nowait()
        assert len(queued.votes) == n - n // 2
        reactor.vote_batcher.stop()
        reactor.bls_batcher.stop()

    asyncio.run(run())


def test_has_votes_digest_roundtrip_and_merge():
    """HasVotesMessage codec + receive-side merge: a digest ORs into
    the stored per-peer bitmap in place (never unsets), so the gossip
    plane stops re-shipping votes the peer already holds."""
    from tendermint_tpu.consensus.messages import HasVotesMessage

    bits = BitArray.from_indices(100, [0, 5, 64, 99])
    msg = HasVotesMessage(7, 1, VoteType.PREVOTE, bits)
    dec = decode_msg(encode_msg(msg))
    assert isinstance(dec, HasVotesMessage)
    assert (dec.height, dec.round, dec.type) == (7, 1, VoteType.PREVOTE)
    assert dec.votes == bits
    prs = PeerRoundState(height=7)
    stored = prs.get_votes_bits(7, 1, VoteType.PREVOTE, 100)
    stored.set(3, True)
    stored.merge(dec.votes)
    assert stored.ones() == [0, 3, 5, 64, 99]
    # a second, smaller digest never unsets
    stored.merge(BitArray.from_indices(100, [5]))
    assert stored.ones() == [0, 3, 5, 64, 99]
    # the stored object identity is preserved (shared with the gossip
    # routines' sub() reads)
    assert prs.get_votes_bits(7, 1, VoteType.PREVOTE, 100) is stored


def test_eager_forward_relays_chunk_to_missing_peers():
    """An accepted chunk forwards immediately to batch-capable peers
    that miss >= VOTE_BATCH_MIN_FILL of it — and not back to the
    source, not to peers that (by our bookkeeping) already hold it."""
    n = 16
    vs, pvs, votes = _make_votes(n, height=1)
    genesis = make_genesis(vs)

    class _StubVerifier:
        def verify(self, items):
            return np.ones(len(items), dtype=bool)

    async def run():
        cs, *_ = make_node(vs, pvs[0], genesis)
        cs.rs.height = 1
        reactor = ConsensusReactor(
            cs, vote_batcher=VoteBatcher(verifier=_StubVerifier())
        )
        src = _FakePeer("src")
        covered = _FakePeer("covered")
        gap = _FakePeer("gap")
        legacy = _FakePeer("legacy", batch=False)
        reactor.switch = _FakeSwitch(
            {p.id: p for p in (src, covered, gap, legacy)}
        )
        for p in (src, covered, gap, legacy):
            reactor._peer_states[p.id] = PeerRoundState(height=1)
        # 'covered' already holds everything
        reactor._peer_states["covered"].get_votes_bits(
            1, 0, VoteType.PRECOMMIT, n
        ).update(range(n))
        # an unresolvable vote (validator_index outside the set) can
        # never be pre-verified, marked, or deduped — it must reach the
        # state machine (which rejects it, legacy parity) but NEVER the
        # relay plane, or one hostile chunk would circulate forever
        bogus = Vote(
            type=VoteType.PRECOMMIT,
            height=1,
            round=0,
            block_id=votes[0].block_id,
            timestamp_ns=T0,
            validator_address=b"\x00" * 20,
            validator_index=999,
            signature=b"x" * 64,
        )
        await reactor._receive_vote_batch(
            src,
            reactor._peer_states["src"],
            VoteBatchMessage(1, 0, VoteType.PRECOMMIT, votes + [bogus]),
        )
        gap_batches = [
            decode_msg(raw)
            for ch, raw in gap.sent
            if ch == VOTE_BATCH_CHANNEL
        ]
        assert len(gap_batches) == 1 and len(gap_batches[0].votes) == n
        assert all(v.validator_index < n for v in gap_batches[0].votes)
        # the bogus vote still reached the state machine, unverified
        queued, _ = cs.peer_msg_queue.get_nowait()
        assert len(queued.votes) == n + 1
        assert queued.pre_verified.count(False) == 1
        assert not covered.sent  # nothing to forward
        assert not src.sent  # never back to the source
        assert not legacy.sent  # legacy peers are pull-only
        # forward marked the peer's bits: a second identical chunk from
        # another path forwards nothing
        await reactor._receive_vote_batch(
            src,
            reactor._peer_states["src"],
            VoteBatchMessage(1, 0, VoteType.PRECOMMIT, votes),
        )
        assert len(
            [1 for ch, _ in gap.sent if ch == VOTE_BATCH_CHANNEL]
        ) == 1
        reactor.vote_batcher.stop()
        reactor.bls_batcher.stop()

    asyncio.run(run())


# --- label cardinality at 200 validators -----------------------------------


def test_200_validator_quorum_metrics_bounded():
    """consensus_quorum_closer_total{validator=} and friends must ride
    bounded_label top-K admission: 200 distinct closers over many
    heights cannot raise MetricCardinalityError or grow the exposition
    unbounded."""
    from tendermint_tpu.consensus.height_vote_set import HeightVoteSet
    from tendermint_tpu.libs.metrics import ConsensusMetrics, Registry
    from tendermint_tpu.obs import Tracer
    from tendermint_tpu.types.block_id import BlockID
    from tendermint_tpu.types.part_set import PartSetHeader

    n = 200
    vs, pvs = make_validators(n)
    reg = Registry("t_committee_card")
    metrics = ConsensusMetrics(reg)
    bid = BlockID(b"m" * 32, PartSetHeader(1, b"m" * 32))
    # rotate which validator closes the quorum so every index would be
    # a distinct label without bounding
    for height in range(1, 8):
        hvs = HeightVoteSet(
            CHAIN_ID, height, vs, tracer=Tracer(enabled=False),
            metrics=metrics,
        )
        order = list(range(n))
        random.Random(height).shuffle(order)
        for i in order:
            pv = pvs[i]
            v = Vote(
                type=VoteType.PRECOMMIT,
                height=height,
                round=0,
                block_id=bid,
                timestamp_ns=T0,
                validator_address=pv.get_pub_key().address(),
                validator_index=i,
            )
            hvs.add_vote(v, "p", verified=True)  # no MetricCardinalityError
    closer = metrics.quorum_closer
    # admitted series bounded by the top-K filter (64) + overflow
    assert 0 < len(closer._values) <= 65
    reg.render()  # exposition stays renderable


# --- committee-scale nets over real p2p ------------------------------------


# the committee nets measure the GOSSIP plane: signature verification
# is stubbed via the shared harness helpers (real device verifies —
# and their first-dispatch XLA compiles — block the one in-proc event
# loop for every node at once)
from .chaos_harness import (  # noqa: E402
    AllTrueVerifier as _AllTrueVerifier,
    stub_default_verifier as _stub_default_verifier,
)


def _build_committee_net(n, vote_batch=None, degree=4, powers=None):
    """n-validator real-p2p net with stubbed signature verification.
    vote_batch: per-node list of bools (None = all batch-capable)."""
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.p2p.node_info import NodeInfo
    from tendermint_tpu.p2p.switch import Switch
    from tendermint_tpu.p2p.transport import MultiplexTransport, NetAddress
    from tendermint_tpu.consensus.state_machine import ConsensusConfig

    if powers is not None:
        vs, pvs = make_weighted_validators(powers)
        n = len(powers)
    else:
        vs, pvs = make_validators(n)
    genesis = make_genesis(vs)
    # in-proc nets share ONE event loop: scale the static timeouts with
    # the committee so loop contention can't fire propose/prevote
    # timeouts and churn rounds while messages are still queued
    scale = 1.0 + n / 25.0
    cfg = ConsensusConfig(
        timeout_propose=8.0 * scale,
        timeout_propose_delta=2.0 * scale,
        timeout_prevote=8.0 * scale,
        timeout_prevote_delta=2.0 * scale,
        timeout_precommit=8.0 * scale,
        timeout_precommit_delta=2.0 * scale,
        timeout_commit=0.05,
        skip_timeout_commit=True,
    )
    def build_one(pv, batch):
        cs, *_ = make_node(
            vs, pv, genesis, config=cfg, verifier=_AllTrueVerifier()
        )
        nk = NodeKey.generate()
        transport = None
        sw = None

        def node_info():
            return NodeInfo(
                node_id=nk.id,
                listen_addr=f"127.0.0.1:{transport.listen_port}",
                network="committee-chain",
                channels=sw.channels() if sw else b"",
            )

        transport = MultiplexTransport(nk, node_info)
        sw = Switch(transport, ping_interval=60.0)
        reactor = ConsensusReactor(
            cs,
            vote_batcher=VoteBatcher(verifier=_AllTrueVerifier()),
            vote_batch=batch,
        )
        sw.add_reactor("consensus", reactor)
        return cs, nk, transport, sw, reactor

    nodes = [
        build_one(pv, True if vote_batch is None else vote_batch[i])
        for i, pv in enumerate(pvs)
    ]
    return nodes, NetAddress


async def _start_committee_net(nodes, NetAddress, degree):
    from .chaos_harness import ring_peer_indices

    n = len(nodes)
    for _, _, t, sw, _ in nodes:
        await t.listen()
        await sw.start()
    for i, (_, _, _, sw, _) in enumerate(nodes):
        peers = (
            ring_peer_indices(i, n, degree)
            if 0 < degree < n - 1
            else [j for j in range(n) if j != i]
        )
        sw.dial_peers_async(
            [
                NetAddress(
                    nodes[j][1].id, "127.0.0.1", nodes[j][2].listen_port
                )
                for j in peers
            ],
            persistent=True,
        )
    for cs, *_ in nodes:
        await cs.start()


async def _stop_committee_net(nodes):
    for cs, _, _, sw, _ in nodes:
        await cs.stop()
        await sw.stop()


def test_32_validator_smoke_batched_gossip():
    """Quick committee smoke: 32 weighted validators over a degree-4
    ring+chords p2p mesh close heights through the batched vote plane,
    with votes-per-gossip-tick well above the one-vote-per-tick
    baseline's 1.0."""
    from .chaos_harness import zipf_powers

    nodes, NetAddress = _build_committee_net(32, powers=zipf_powers(32))

    async def run():
        await _start_committee_net(nodes, NetAddress, degree=4)
        try:
            await asyncio.gather(
                *(cs.wait_for_height(2, timeout=120) for cs, *_ in nodes)
            )
        finally:
            stats = [
                (r.gossip_ticks, r.gossip_votes_sent, r.gossip_batches_sent)
                for *_, r in nodes
            ]
            await _stop_committee_net(nodes)
        return stats

    with _stub_default_verifier():
        stats = asyncio.run(run())
    hashes = {
        cs.block_store.load_block(2).hash()
        for cs, *_ in nodes
        if cs.block_store.height >= 2
    }
    assert len(hashes) == 1, "committee disagrees on block 2"
    ticks = sum(s[0] for s in stats)
    votes = sum(s[1] for s in stats)
    batches = sum(s[2] for s in stats)
    assert batches > 0, "no vote batches were gossiped"
    # emergent chunking is arrival-rate-bound (the controlled >=10x
    # ratio lives in test_round_dissemination_10x_fewer_ticks); even so
    # the live mesh must beat the baseline's structural 1.0
    assert votes / max(1, ticks) > 1.5, (
        f"batched gossip should ship >1.5 votes/tick on a sparse mesh, "
        f"got {votes}/{ticks}"
    )


def test_mixed_version_net_converges():
    """A legacy one-vote-per-tick peer (no VOTE_BATCH_CHANNEL in its
    NodeInfo) interoperates with batch-capable nodes: the net converges
    and no connection dies on an unknown channel."""
    nodes, NetAddress = _build_committee_net(
        4, vote_batch=[True, True, True, False]
    )

    async def run():
        await _start_committee_net(nodes, NetAddress, degree=0)
        try:
            await asyncio.gather(
                *(cs.wait_for_height(3, timeout=60) for cs, *_ in nodes)
            )
            legacy_sw = nodes[3][3]
            assert len(legacy_sw.peers) == 3, (
                "legacy peer lost connections mid-run"
            )
        finally:
            await _stop_committee_net(nodes)

    with _stub_default_verifier():
        asyncio.run(run())
    hashes = {cs.block_store.load_block(3).hash() for cs, *_ in nodes}
    assert len(hashes) == 1
    # the legacy reactor never advertised or shipped batches
    assert nodes[3][4].gossip_batches_sent == 0


def test_late_batch_node_catches_up_via_batched_commits():
    """Catchup for a fresh batch-capable node rides VoteBatchMessage
    commit chunks (one message per height's commit, not one per vote)."""
    nodes, NetAddress = _build_committee_net(4)
    early, late = nodes[:3], nodes[3]

    async def run():
        from .chaos_harness import ring_peer_indices  # noqa: F401

        for _, _, t, sw, _ in early:
            await t.listen()
            await sw.start()
        for i, (_, _, _, sw, _) in enumerate(early):
            sw.dial_peers_async(
                [
                    NetAddress(
                        early[j][1].id,
                        "127.0.0.1",
                        early[j][2].listen_port,
                    )
                    for j in range(len(early))
                    if j != i
                ],
                persistent=True,
            )
        for cs, *_ in early:
            await cs.start()
        await asyncio.gather(
            *(cs.wait_for_height(3, timeout=60) for cs, *_ in early)
        )
        for *_, r in early:
            r.gossip_batches_sent = 0
        cs_l, nk_l, t_l, sw_l, r_l = late
        await t_l.listen()
        await sw_l.start()
        sw_l.dial_peers_async(
            [
                NetAddress(nk.id, "127.0.0.1", t.listen_port)
                for _, nk, t, _, _ in early
            ],
            persistent=True,
        )
        await cs_l.start()
        await cs_l.wait_for_height(3, timeout=60)
        served_batches = sum(r.gossip_batches_sent for *_, r in early)
        await _stop_committee_net(nodes)
        return served_batches

    with _stub_default_verifier():
        served = asyncio.run(run())
    assert served > 0, "catchup never used the batched vote path"
    b3_late = late[0].block_store.load_block(3)
    b3_early = early[0][0].block_store.load_block(3)
    assert b3_late.hash() == b3_early.hash()


def test_round_dissemination_10x_fewer_ticks():
    """The acceptance ratio, measured in the controlled regime the
    one-vote-per-tick model describes: shipping a full committee round
    to a peer takes >=10x (structurally ~n/chunk = ~50x) fewer gossip
    ticks than the baseline at 100 and 200 validators."""
    from .chaos_harness import round_dissemination_ticks

    for n in (100, 200):
        batched = asyncio.run(round_dissemination_ticks(n, True))
        base = asyncio.run(round_dissemination_ticks(n, False))
        assert batched["complete"] and base["complete"]
        assert base["gossip_ticks"] >= n  # one vote per tick, at best
        ratio = base["gossip_ticks"] / max(1, batched["gossip_ticks"])
        assert ratio >= 10.0, (
            f"n={n}: {base['gossip_ticks']} baseline ticks vs "
            f"{batched['gossip_ticks']} batched = {ratio:.1f}x"
        )
        # every vote arrived exactly through the counted sends
        assert batched["votes_sent"] == n


@pytest.mark.slow
def test_100_validator_committee_closes_heights():
    """The 100-validator acceptance net: a real-p2p zipf-weighted
    committee on a degree-4 ring+chords mesh closes heights and agrees
    — the wall is event-loop-bound in a single process, so the tick
    economics are asserted by test_round_dissemination_10x_fewer_ticks
    and the bench artifact; here the batched plane must carry a live
    committee to agreement."""
    from .chaos_harness import zipf_powers

    nodes, NetAddress = _build_committee_net(100, powers=zipf_powers(100))

    async def run():
        await _start_committee_net(nodes, NetAddress, degree=4)
        try:
            await asyncio.gather(
                *(cs.wait_for_height(2, timeout=600) for cs, *_ in nodes)
            )
        finally:
            stats = [
                (r.gossip_ticks, r.gossip_votes_sent) for *_, r in nodes
            ]
            await _stop_committee_net(nodes)
        return stats

    with _stub_default_verifier():
        stats = asyncio.run(run())
    hashes = {
        cs.block_store.load_block(2).hash()
        for cs, *_ in nodes
        if cs.block_store.height >= 2
    }
    assert len(hashes) == 1, "100-validator committee disagrees"
    ticks = sum(s[0] for s in stats)
    votes = sum(s[1] for s in stats)
    # emergent (arrival-rate-bound) batching still beats one-per-tick
    assert votes / max(1, ticks) > 1.3, f"got {votes}/{ticks}"


# --- BLS batch points at committee scale -----------------------------------


def test_bls_batcher_committee_chunk_one_round():
    """150 real dual-signs over one batch hash submitted as a chunk
    verify in ONE fn-lane round, recorded under the committee-scale
    bls_agg rung."""
    from tendermint_tpu.consensus.bls_batcher import BLSBatcher
    from tendermint_tpu.crypto import bls_signatures as bls
    from tendermint_tpu.crypto.shape_registry import default_shape_registry
    from tendermint_tpu.l2node.mock import MockL2Node

    n = 150
    registry = bls.BLSKeyRegistry()
    batch_hash = b"committee-batch-hash-0123456789ab"
    tm_keys, sigs = [], []
    for i in range(n):
        priv = 60013 + i
        tm_pk = b"tm-%04d" % i + b"\x00" * 25
        registry.register(tm_pk, bls.pubkey_from_priv(priv))
        tm_keys.append(tm_pk)
        sigs.append(bls.signer_for(priv)(batch_hash))
    l2 = MockL2Node(
        bls_verifier=registry.verifier(),
        bls_batch_verifier=registry.batch_verifier(),
    )
    reg = default_shape_registry()
    before = reg.snapshot()

    async def run():
        batcher = BLSBatcher(l2)
        verdicts = await batcher.submit_many(
            list(zip(tm_keys, [batch_hash] * n, sigs))
        )
        rounds = len(batcher.batch_sizes)
        batcher.stop()
        return verdicts, rounds

    verdicts, rounds = asyncio.run(run())
    assert verdicts == [True] * n
    assert rounds == 1, f"committee chunk took {rounds} fn-lane rounds"
    after = reg.snapshot()
    assert (
        after["device_dispatch_count"] - before["device_dispatch_count"] >= 1
    )
    agg_buckets = {
        b for b, _, _ in map(tuple, after["shapes_by_tier"].get("bls_agg", []))
    }
    assert 256 in agg_buckets, (
        f"150 signers should land the 256 committee rung, got {agg_buckets}"
    )

    # a corrupted signature in the chunk is rejected without poisoning
    # the rest
    sigs[7] = sigs[8]

    async def run_bad():
        batcher = BLSBatcher(l2)
        verdicts = await batcher.submit_many(
            list(zip(tm_keys, [batch_hash] * n, sigs))
        )
        batcher.stop()
        return verdicts

    bad = asyncio.run(run_bad())
    assert bad[7] is False
    assert all(v is True for i, v in enumerate(bad) if i != 7)


# --- tools: generator + prewarm coverage -----------------------------------


def test_testnet_generator_committee_manifest():
    import tools.testnet_generator as gen

    m = gen.generate_manifest(42, n_validators=150, power_dist="zipf")
    vals = [n for n in m["nodes"] if n["mode"] == "validator"]
    assert len(vals) == 150
    assert m["topology"] == "ring"  # past the full-mesh knee
    powers = [v["power"] for v in vals]
    assert powers[0] == 1000 and powers[1] == 500 and powers[149] == 6
    assert min(powers) >= 1
    # deterministic: same seed + args -> same manifest
    assert m == gen.generate_manifest(42, n_validators=150, power_dist="zipf")
    # equal dist + explicit small committee keeps random topology choices
    m2 = gen.generate_manifest(7, n_validators=4)
    assert all(
        v["power"] == 1000
        for v in m2["nodes"]
        if v["mode"] == "validator"
    )
    with pytest.raises(ValueError):
        gen.generate_manifest(1, power_dist="pareto")


def test_prewarm_committee_rung_coverage():
    from tools.prewarm import COMMITTEE_BUCKETS, check_committee_rungs

    good = {
        "entries": [
            {"tier": "small", "bucket": b} for b in (8, 32, 128, 256, 512)
        ]
        + [{"tier": "big", "bucket": 2048}]
    }
    assert check_committee_rungs(good) == []
    partial = {
        "entries": [
            {"tier": "small", "bucket": 8},
            {"tier": "generic", "bucket": 256},  # wrong tier
        ]
    }
    problems = check_committee_rungs(partial)
    assert problems and "256" in problems[0]
    assert set(COMMITTEE_BUCKETS) <= {8, 32, 128, 256, 512}


def test_default_ladder_has_committee_rung():
    from tendermint_tpu.crypto.shape_registry import (
        DEFAULT_BUCKET_LADDER,
        ShapeRegistry,
    )

    assert 256 in DEFAULT_BUCKET_LADDER
    reg = ShapeRegistry()
    # 100-200 signer committee chunks land on 128/256, not 512
    assert reg.bucket_for(100) == 128
    assert reg.bucket_for(150) == 256
    assert reg.bucket_for(200) == 256
