"""Observability e2e: the flight recorder over a live 4-validator net.

Acceptance surface of the obs PR: every committed height shows a
complete Propose→Prevote→Precommit→Commit span chain in `dump_traces`,
the step-duration histogram count equals the traced step transitions,
the Chrome trace_event export round-trips through json.loads, a
chaos-injected partition lands as an annotation inside the affected
height's timeline, and a tracer-disabled run allocates nothing new on
the vote hot path."""

import asyncio
import json
from types import SimpleNamespace

import pytest

from tendermint_tpu import obs
from tendermint_tpu.consensus.state_machine import Step
from tendermint_tpu.libs.metrics import ConsensusMetrics, Registry
from tendermint_tpu.rpc.core import RPCCore

from .helpers import make_genesis, make_validators
from .test_consensus import make_node, wire_net

pytestmark = pytest.mark.obs

STEP_SPANS = {f"cs.{s.name.lower()}" for s in Step}


# --- tracer unit behavior --------------------------------------------------


def test_tracer_span_event_and_ring_bound():
    t = obs.Tracer(enabled=True, ring_size=32)
    with t.span("outer", height=1):
        with t.span("inner", height=1):
            pass
        t.event("mark", height=1, why="x")
    recs = t.records()
    names = [r.name for r in recs]
    # inner closes before outer; the event carries its fields
    assert names == ["inner", "mark", "outer"]
    assert recs[0].fields.get("parent") == "outer"
    assert recs[1].kind == "event" and recs[1].fields["why"] == "x"
    for i in range(100):
        t.event("spam", height=2)
    assert len(t.records()) == 32  # fixed-size ring


def test_tracer_disabled_is_noop_singleton():
    t = obs.Tracer(enabled=False)
    s1 = t.span("a", height=1)
    s2 = t.span("b", height=2)
    assert s1 is s2  # shared no-op: no per-call allocation
    with s1:
        pass
    t.event("x")
    t.add_span("y", 0.0, 1.0)
    assert t.records() == []


def test_flight_bins_heightless_events_by_time():
    t = obs.Tracer(enabled=True)
    base = t.epoch
    t.add_span("cs.propose", base + 1.0, 0.5, height=5)
    t.add_span("cs.commit", base + 1.5, 0.5, height=5)
    t.add_span("cs.propose", base + 3.0, 0.5, height=6)
    # heightless record (a WAL fsync doesn't know the consensus height)
    # inside height 5's [1.0, 2.0] window
    t.add_span("wal.fsync", base + 1.2, 0.0)
    flight = t.flight(10)
    assert [r["name"] for r in flight[5]] == [
        "cs.propose", "wal.fsync", "cs.commit"
    ]
    assert all(r["name"] != "wal.fsync" for r in flight[6])


# --- the live-net acceptance test -----------------------------------------


def test_four_validator_flight_recorder():
    vs, pvs = make_validators(4)
    genesis = make_genesis(vs)
    tracer = obs.Tracer(enabled=True, ring_size=1 << 15)
    reg = Registry()
    metrics = ConsensusMetrics(reg)
    prev_default = obs.default_tracer()
    obs.set_default_tracer(tracer)

    async def run():
        from tendermint_tpu.chaos.network import ChaosNetwork

        nodes = [
            make_node(vs, pv, genesis, metrics=metrics, tracer=tracer)
            for pv in pvs
        ]
        css = [n[0] for n in nodes]
        wire_net(css)
        for cs in css:
            await cs.start()
        await asyncio.gather(
            *(cs.wait_for_height(1, timeout=30) for cs in css)
        )
        # chaos annotation mid-run: with no switches installed this is
        # pure annotation (the in-proc net gossips via broadcast hooks),
        # landing in whatever height is in progress
        net = ChaosNetwork(seed=7)
        await net.partition("split", [["n0", "n1"], ["n2", "n3"]])
        await asyncio.gather(
            *(cs.wait_for_height(3, timeout=30) for cs in css)
        )
        for cs in css:
            await cs.stop()
        return css

    try:
        css = asyncio.run(run())
    finally:
        obs.set_default_tracer(prev_default)
    assert all(cs.state.last_block_height >= 3 for cs in css)

    core = RPCCore(SimpleNamespace(tracer=tracer))
    dump = core.dump_traces()
    assert dump["enabled"] is True
    records = dump["records"]

    # 1) complete step chain for every committed height
    for h in (1, 2, 3):
        names = {
            r["name"]
            for r in records
            if r["kind"] == "span" and r["height"] == h
        }
        for want in ("cs.propose", "cs.prevote", "cs.precommit", "cs.commit"):
            assert want in names, f"height {h} missing {want}: {names}"

    # 2) histogram count equals traced step transitions
    n_step_spans = sum(
        1
        for r in records
        if r["kind"] == "span" and r["name"] in STEP_SPANS
    )
    assert n_step_spans > 0
    assert metrics.step_duration.total_count() == n_step_spans

    # 3) Chrome trace export round-trips through json.loads
    chrome = core.dump_traces(format="chrome")
    decoded = json.loads(json.dumps(chrome))
    events = decoded["trace"]["traceEvents"]
    assert events and any(e["ph"] == "X" for e in events)
    assert any(e["name"] == "chaos.partition" for e in events)

    # 4) the injected partition is an annotation in the affected
    # height's timeline
    flight = dump["flight"]
    hit = [
        int(h)
        for h, rows in flight.items()
        if any(r["name"] == "chaos.partition" for r in rows)
    ]
    assert hit, f"partition annotation missing from flight view: {list(flight)}"
    assert all(1 <= h <= 4 for h in hit)

    # the attribution table covers the consensus steps
    att = dump["attribution"]
    assert att["heights"] >= 3
    assert "cs.propose" in att["steps"]
    assert att["steps"]["cs.propose"]["p95_ms"] >= att["steps"][
        "cs.propose"
    ]["p50_ms"] >= 0


def test_disabled_tracer_no_allocations_on_vote_path():
    """Tracing off: the run records nothing and creates no new metric
    objects on the vote hot path (the metric set is fully allocated at
    construction)."""
    vs, pvs = make_validators(4)
    genesis = make_genesis(vs)
    tracer = obs.Tracer(enabled=False)
    reg = Registry()
    metrics = ConsensusMetrics(reg)
    n_metrics_before = len(reg._metrics)

    async def run():
        nodes = [
            make_node(vs, pv, genesis, metrics=metrics, tracer=tracer)
            for pv in pvs
        ]
        css = [n[0] for n in nodes]
        wire_net(css)
        for cs in css:
            await cs.start()
        await asyncio.gather(
            *(cs.wait_for_height(2, timeout=30) for cs in css)
        )
        for cs in css:
            await cs.stop()
        return css

    css = asyncio.run(run())
    assert all(cs.state.last_block_height >= 2 for cs in css)
    assert len(tracer.records()) == 0
    assert len(reg._metrics) == n_metrics_before
    # metrics still flowed while tracing was off
    assert metrics.step_duration.total_count() > 0
    assert metrics.votes_verified.value(path="inline") > 0


# --- dump_traces / report plumbing ----------------------------------------


def test_trace_report_renders_dump(tmp_path):
    tracer = obs.Tracer(enabled=True)
    base = tracer.epoch
    # span window [0, 0.2] covers the event() timestamp (~now ≈ 0)
    tracer.add_span("cs.propose", base, 0.05, height=1)
    tracer.add_span("cs.commit", base + 0.05, 0.15, height=1)
    tracer.event("chaos.partition", name="split")
    core = RPCCore(SimpleNamespace(tracer=tracer))
    dump = core.dump_traces()

    import subprocess
    import sys

    p = tmp_path / "dump.json"
    p.write_text(json.dumps(dump))
    out = subprocess.run(
        [sys.executable, "tools/trace_report.py", str(p)],
        capture_output=True,
        text=True,
        cwd="/root/repo",
        timeout=60,
    )
    assert out.returncode == 0, out.stderr
    assert "height 1" in out.stdout
    assert "cs.propose" in out.stdout
    assert "! chaos.partition" in out.stdout
    assert "latency attribution" in out.stdout

    # the chrome-format dump renders through the same tool
    from tools.trace_report import extract_records

    chrome = core.dump_traces(format="chrome")
    recs = extract_records(json.loads(json.dumps(chrome)))
    assert any(r["name"] == "cs.propose" for r in recs)
