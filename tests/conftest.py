"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on a virtual 8-device CPU platform (the driver separately
dry-run-compiles the multi-chip path via __graft_entry__.dryrun_multichip).
Env vars must be set before the first `import jax` anywhere in the test
process, hence this happens at conftest import time.
"""

import os

# Neutralize the tunnel's PJRT plugin BEFORE any backend init: the
# .axon_site sitecustomize imports jax and registers the axon backend at
# interpreter startup; while the tunnel endpoint is down, initializing
# that backend hangs every jax.devices() — even when tests only want CPU
# (round-4/5 outage mode: ~25 min hang, then "Unable to initialize
# backend"). Tests are hermetic on the virtual CPU mesh by design, so
# drop the factory from the registry; the suite then runs identically
# with the tunnel up, down, or absent.
try:
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
except Exception:
    pass

# Hard-force the CPU platform and the 8-device count. The env vars alone
# are DEAD LETTERS here: sitecustomize already imported jax, and jax
# snapshots env-derived config at import — so pin everything that has a
# config knob via jax.config.update too. XLA_FLAGS is still read from
# the environment at backend creation (which has not happened yet), so
# setting it here remains effective.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Persistent compilation cache: the crypto kernels are deep programs and
# CPU compiles dominate test wall time; cache across runs.
from tendermint_tpu.libs.jax_cache import set_compile_cache_env  # noqa: E402

set_compile_cache_env()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update(
    "jax_compilation_cache_dir", os.environ["JAX_COMPILATION_CACHE_DIR"]
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

# node tests: skip the background validator-table warm thread — killing the
# process mid-XLA-compile in a daemon thread aborts noisily at teardown
os.environ.setdefault("TM_TPU_SKIP_WARM", "1")


# --- test tiers ------------------------------------------------------------
# Modules dominated by device compiles or real-network e2e get the `slow`
# marker automatically; `pytest -m "not slow"` is the quick tier (the
# VERDICT r2 suggestion: hot-path tests shouldn't wait on 20-min runs).
_SLOW_MODULES = {
    "test_e2e_multiprocess",
    "test_e2e_perturb",
    "test_multichip",
    "test_ops_curve25519",
    "test_ops_field25519",
    "test_ops_sha",
    "test_ops_bls_g1",
    "test_ops_bls_g2",
    "test_ops_bls_pairing",
    "test_bench_scenarios",
    "test_ops_secp",
    "test_blocksync",
    "test_light",
    "test_statesync",
    "test_consensus_reactor",
    "test_batch_verifier",
}


def pytest_collection_modifyitems(config, items):
    import pytest as _pytest

    for item in items:
        if item.module.__name__.rsplit(".", 1)[-1] in _SLOW_MODULES:
            item.add_marker(_pytest.mark.slow)
