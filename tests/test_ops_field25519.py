"""Differential tests: JAX GF(2^255-19) limb arithmetic vs Python bigints."""

import numpy as np
import pytest

import jax.numpy as jnp

from tendermint_tpu.ops import field25519 as fe

import functools
import jax


@functools.cache
def _j(f):
    return jax.jit(f)

P = fe.P
rng = np.random.default_rng(1234)


def rand_ints(n):
    vals = [int.from_bytes(rng.bytes(32), "little") % P for _ in range(n)]
    # include edge cases
    vals[:6] = [0, 1, 2, P - 1, P - 19, P // 2]
    return [v % P for v in vals]


def pack(vals):
    return jnp.asarray(np.stack([fe.from_int(v) for v in vals]))


def unpack_canonical(limbs):
    arr = np.asarray(limbs)
    return [fe.to_int(row) for row in arr]


N = 16
A_INTS = rand_ints(N)
B_INTS = rand_ints(N)[::-1]
A = pack(A_INTS)
B = pack(B_INTS)


def assert_loose(x):
    arr = np.asarray(x)
    assert arr.min() >= 0 and arr.max() < 512, (arr.min(), arr.max())


def test_roundtrip():
    assert unpack_canonical(_j(fe.canonical)(A)) == [a % P for a in A_INTS]


def test_add():
    out = _j(fe.add)(A, B)
    assert_loose(out)
    assert unpack_canonical(_j(fe.canonical)(out)) == [
        (a + b) % P for a, b in zip(A_INTS, B_INTS)
    ]


def test_sub():
    out = _j(fe.sub)(A, B)
    assert_loose(out)
    assert unpack_canonical(_j(fe.canonical)(out)) == [
        (a - b) % P for a, b in zip(A_INTS, B_INTS)
    ]


def test_neg():
    out = _j(fe.neg)(A)
    assert_loose(out)
    assert unpack_canonical(_j(fe.canonical)(out)) == [(-a) % P for a in A_INTS]


def test_mul():
    out = _j(fe.mul)(A, B)
    assert_loose(out)
    assert unpack_canonical(_j(fe.canonical)(out)) == [
        (a * b) % P for a, b in zip(A_INTS, B_INTS)
    ]


def test_mul_loose_inputs():
    # worst-case loose inputs: all limbs 511
    x = jnp.full((4, 32), 511, dtype=jnp.int32)
    xv = fe.to_int(np.full(32, 511, dtype=np.int64)) % P
    out = _j(fe.mul)(x, x)
    assert_loose(out)
    assert unpack_canonical(_j(fe.canonical)(out)) == [(xv * xv) % P] * 4


def test_sqr_chain():
    # repeated squaring keeps the invariant and matches bigint
    x = A
    ref = list(A_INTS)
    for _ in range(8):
        x = _j(fe.sqr)(x)
        ref = [(v * v) % P for v in ref]
        assert_loose(x)
    assert unpack_canonical(_j(fe.canonical)(x)) == ref


def test_mul_small():
    out = fe.mul_small(A, 121666)
    assert_loose(out)
    assert unpack_canonical(_j(fe.canonical)(out)) == [
        (a * 121666) % P for a in A_INTS
    ]


def test_invert():
    out = _j(fe.invert)(A)
    got = unpack_canonical(_j(fe.canonical)(out))
    for a, g in zip(A_INTS, got):
        if a == 0:
            assert g == 0
        else:
            assert g == pow(a, P - 2, P)


def test_pow22523():
    out = _j(fe.pow22523)(A)
    got = unpack_canonical(_j(fe.canonical)(out))
    for a, g in zip(A_INTS, got):
        assert g == pow(a, (P - 5) // 8, P)


@pytest.mark.parametrize(
    "v",
    [0, 1, 19, P - 1, P, P + 1, 2 * P - 1, 2 * P, 2**255 - 1, 2**256 - 1],
)
def test_canonical_edge_values(v):
    # feed raw (possibly >= p, >= 2^255) limb encodings of v
    limbs = np.array(
        [int(b) for b in (v % 2**256).to_bytes(32, "little")], dtype=np.int32
    )
    out = _j(fe.canonical)(jnp.asarray(limbs)[None])
    assert unpack_canonical(out) == [(v % 2**256) % P]


def test_eq_and_parity():
    assert bool(np.asarray(_j(fe.eq)(A, A)).all())
    assert not bool(np.asarray(_j(fe.eq)(A, B)).any())
    par = np.asarray(_j(fe.parity)(A))
    assert par.tolist() == [a % 2 for a in A_INTS]


def test_select():
    cond = jnp.asarray([True, False] * (N // 2))
    out = fe.select(cond, A, B)
    got = unpack_canonical(_j(fe.canonical)(out))
    want = [a if i % 2 == 0 else b for i, (a, b) in enumerate(zip(A_INTS, B_INTS))]
    assert got == [w % P for w in want]


def test_invert_many_matches_invert():
    vals = rand_ints(9)
    vals[3] = 0  # zero row must invert to 0 without poisoning the batch
    x = pack(vals)
    got = unpack_canonical(_j(fe.canonical)(_j(fe.invert_many)(x)))
    want = [pow(v, P - 2, P) if v else 0 for v in vals]
    assert got == want
