"""Golden wire vectors — pin every signing/hashing encoding to committed
fixtures so a refactor cannot silently change sign-bytes or hashes and
fork the chain from itself.

The framework deliberately defines its own wire (types/block.py:14-16);
this is the price: nothing external pins the encodings, so these vectors
do (the reference pins via protobuf + spec — types/canonical.go:18-57,
spec/core/encoding.md).

Regenerate deliberately after an INTENTIONAL wire change with:
    GOLDEN_REGEN=1 python -m pytest tests/test_golden.py
and commit the diff. A failure here without an intentional change means
the encoding drifted — that is a consensus-breaking bug, not a stale
fixture.
"""

from __future__ import annotations

import json
import os

from tendermint_tpu.consensus.wal import (
    KIND_END_HEIGHT,
    WALMessage,
    encode_record,
)
from tendermint_tpu.libs import protoio as pio
from tendermint_tpu.types.block import (
    Block,
    BlockIDFlag,
    Commit,
    CommitSig,
    Data,
    Header,
)
from tendermint_tpu.types.block_id import BlockID, PartSetHeader
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.vote import Vote, VoteType

FIXTURE = os.path.join(os.path.dirname(__file__), "golden_vectors.json")

CHAIN_ID = "golden-chain"
T0 = 1_700_000_000_123_456_789
ADDR = bytes(range(20))
HASH32 = bytes(range(32))
HASH32B = bytes(range(1, 33))


def _block_id() -> BlockID:
    return BlockID(HASH32, PartSetHeader(3, HASH32B))


def _vote(vtype, bls: bool = False) -> Vote:
    return Vote(
        type=vtype,
        height=12345,
        round=2,
        block_id=_block_id(),
        timestamp_ns=T0,
        validator_address=ADDR,
        validator_index=7,
        signature=bytes(64),
        bls_signature=b"\xbb" * 96 if bls else b"",
    )


def _nil_vote() -> Vote:
    return Vote(
        type=VoteType.PREVOTE,
        height=12345,
        round=0,
        block_id=BlockID(),
        timestamp_ns=T0,
        validator_address=ADDR,
        validator_index=0,
        signature=bytes(64),
    )


def _proposal() -> Proposal:
    return Proposal(
        height=12345,
        round=2,
        pol_round=-1,
        block_id=_block_id(),
        timestamp_ns=T0,
        signature=bytes(64),
    )


def _commit() -> Commit:
    return Commit(
        height=12344,
        round=1,
        block_id=_block_id(),
        signatures=[
            CommitSig(BlockIDFlag.COMMIT, ADDR, T0, b"\x01" * 64),
            CommitSig(BlockIDFlag.NIL, bytes(reversed(ADDR)), T0, b"\x02" * 64),
            CommitSig.absent(),
            CommitSig(
                BlockIDFlag.COMMIT,
                ADDR,
                T0,
                b"\x03" * 64,
                bls_signature=b"\xbb" * 96,
            ),
        ],
    )


def _header(batch: bool = False) -> Header:
    return Header(
        chain_id=CHAIN_ID,
        height=12345,
        time_ns=T0,
        last_block_id=_block_id(),
        last_commit_hash=HASH32,
        data_hash=HASH32B,
        validators_hash=HASH32,
        next_validators_hash=HASH32B,
        consensus_hash=HASH32,
        app_hash=b"\xaa" * 32,
        last_results_hash=HASH32,
        evidence_hash=HASH32B,
        proposer_address=ADDR,
        batch_hash=HASH32 if batch else b"",
    )


def _block() -> Block:
    return Block(
        header=_header(batch=True),
        data=Data(
            txs=[b"tx-one", b"tx-two=value", b""],
            l2_block_meta=b"l2meta:\x01\x02",
            l2_batch_header=b"batch-header-bytes",
        ),
        last_commit=_commit(),
    )


def compute_vectors() -> dict:
    v = _vote(VoteType.PRECOMMIT, bls=True)
    nv = _nil_vote()
    pv = _vote(VoteType.PREVOTE)
    prop = _proposal()
    commit = _commit()
    block = _block()
    parts = block.make_part_set()
    wal_msgs = [
        encode_record(WALMessage("vote", b"payload-bytes", timestamp_ns=T0)),
        encode_record(
            WALMessage(
                KIND_END_HEIGHT, pio.write_uvarint(12345), timestamp_ns=T0
            )
        ),
    ]
    vec = {
        "vote_sign_bytes_precommit": v.sign_bytes(CHAIN_ID).hex(),
        "vote_sign_bytes_prevote": pv.sign_bytes(CHAIN_ID).hex(),
        "vote_sign_bytes_nil": nv.sign_bytes(CHAIN_ID).hex(),
        "vote_encode": v.encode().hex(),
        "proposal_sign_bytes": prop.sign_bytes(CHAIN_ID).hex(),
        "proposal_encode": prop.encode().hex(),
        "commit_hash": commit.hash().hex(),
        "commit_encode": commit.encode().hex(),
        "header_hash": _header().hash().hex(),
        "header_hash_batch_point": _header(batch=True).hash().hex(),
        "block_hash": block.hash().hex(),
        "block_encode": block.encode().hex(),
        "part_set_header_hash": parts.header.hash.hex(),
        "part0_encode": parts.get_part(0).encode().hex(),
        "wal_record_msg": wal_msgs[0].hex(),
        "wal_record_end_height": wal_msgs[1].hex(),
        "block_id_encode": _block_id().encode().hex(),
    }
    return vec


def test_golden_vectors():
    got = compute_vectors()
    if os.environ.get("GOLDEN_REGEN") == "1" or not os.path.exists(FIXTURE):
        with open(FIXTURE, "w") as f:
            json.dump(got, f, indent=2, sort_keys=True)
            f.write("\n")
    with open(FIXTURE) as f:
        want = json.load(f)
    assert set(got) == set(want), (
        f"vector set changed: +{set(got) - set(want)} -{set(want) - set(got)}"
    )
    for k in sorted(want):
        assert got[k] == want[k], (
            f"WIRE DRIFT in {k}:\n  fixture: {want[k][:80]}...\n"
            f"  current: {got[k][:80]}...\n"
            "If this change is intentional, regenerate with GOLDEN_REGEN=1 "
            "and note the consensus break."
        )


def test_golden_roundtrips():
    """The pinned encodings must also decode back to equal values."""
    v = _vote(VoteType.PRECOMMIT, bls=True)
    assert Vote.decode(v.encode()) == v
    prop = _proposal()
    assert Proposal.decode(prop.encode()) == prop
    commit = _commit()
    assert Commit.decode(commit.encode()).hash() == commit.hash()
    block = _block()
    assert Block.decode(block.encode()).hash() == block.hash()
