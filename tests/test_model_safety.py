"""Machine-check of spec/tla/ConsensusSafety.tla (VERDICT r3 next-round #3).

No TLC/Apalache ships in this image, so this is a small explicit-state
explorer over the module's 4-validator / 3-round / 2-value instance
(VALIDATORS={v0..v3}, FAULTY={v3}, ROUNDS=0..2, VALUES={A,B}) asserting
the Agreement theorem over the FULL reachable state space.

Soundness of the reductions (each only ADDS behaviors or is exact, so a
clean pass proves Agreement for the TLA model's instance):

- Byzantine wildcard: the module lets the faulty validator overwrite its
  vote slots at any time, so at any evaluation instant its slot can hold
  any value. We drop it from the state and credit it to EVERY quorum
  count (TwoThirds over 4 needs 3 votes -> 2 honest + the wildcard).
  This is attack-maximal: a superset of the module's byzantine
  schedules.
- Nil-vote merging: an honest Nil prevote/precommit contributes to no
  polka/decision and (for precommits) leaves the lock unchanged; we
  merge it with "not voted" (slot stays empty, the validator may still
  vote a value there later). Strictly more behaviors than the module's
  write-once Nil slot.
- Locks are tracked explicitly as (value, round) set by each
  value-precommit, exactly as HonestPrecommit does — including the
  module's allowance for out-of-round-order precommits.
- Symmetry: honest validators have equal power (state is a sorted
  multiset of per-validator local states) and VALUES is a symmetric
  constant set (canonicalize under the A<->B swap). Both are exact
  quotients.

The checker is validated against itself: removing the POL lock rule or
the polka gate (the two guards Agreement rests on) must produce a
violation (`test_checker_detects_*`) — the pass is not vacuous.
"""

from collections import deque

# value encoding: 0 = empty (no vote / nil), 1 = A, 2 = B
EMPTY, A, B = 0, 1, 2
ROUNDS = (0, 1, 2)
VALUES = (A, B)
N_HONEST = 3
# quorum over 4 equal-power validators is 3; the byzantine wildcard
# always contributes one, so an honest count of 2 completes any quorum
HONEST_QUORUM = 2

# local state: (pv0, pv1, pv2, pc0, pc1, pc2, lock_val, lock_round)
INIT_LOCAL = (EMPTY, EMPTY, EMPTY, EMPTY, EMPTY, EMPTY, EMPTY, -1)

_SWAP = {EMPTY: EMPTY, A: B, B: A}


def _canon(locals_):
    """Sorted multiset of local states, minimized under the A<->B swap."""
    direct = tuple(sorted(locals_))
    swapped = tuple(
        sorted(tuple(_SWAP[x] for x in ls[:7]) + (ls[7],) for ls in locals_)
    )
    return min(direct, swapped)


def _polka(locals_, r, val):
    return sum(1 for ls in locals_ if ls[r] == val) >= HONEST_QUORUM


def _decided(locals_, r, val):
    return sum(1 for ls in locals_ if ls[3 + r] == val) >= HONEST_QUORUM


def _agreement_violated(locals_):
    decided = set()
    for r in ROUNDS:
        for val in VALUES:
            if _decided(locals_, r, val):
                decided.add(val)
    return len(decided) > 1


def _no_later_votes(ls, r):
    """Round monotonicity (NoLaterVotes in the TLA module): validators
    participate in increasing rounds. Safety-relevant — removing this
    guard reproduces the genuine Agreement violation the r4 machine
    check found in the module as originally written (see module
    comment and test_checker_detects_violation_without_monotonicity)."""
    return all(
        ls[r2] == EMPTY and ls[3 + r2] == EMPTY
        for r2 in ROUNDS
        if r2 > r
    )


def _successors(locals_, lock_rule=True, polka_gate=True, monotone=True):
    """All one-vote honest moves (the byzantine validator is the
    wildcard and has no state)."""
    for i, ls in enumerate(locals_):
        pv = ls[0:3]
        pc = ls[3:6]
        lock_val, lock_round = ls[6], ls[7]
        # HonestPrevote(v, r, val)
        for r in ROUNDS:
            if pv[r] != EMPTY:
                continue
            if monotone and not _no_later_votes(ls, r):
                continue
            for val in VALUES:
                if lock_rule and lock_val != EMPTY and lock_val != val:
                    # unlock-on-higher-POL: a polka for val strictly
                    # between the lock round and r
                    if not any(
                        lock_round < pr < r and _polka(locals_, pr, val)
                        for pr in ROUNDS
                    ):
                        continue
                nl = list(ls)
                nl[r] = val
                yield i, tuple(nl)
        # HonestPrecommit(v, r, val) — value precommits only (nil
        # precommits merge into "no vote" and change nothing)
        for r in ROUNDS:
            if pc[r] != EMPTY:
                continue
            if monotone and not _no_later_votes(ls, r):
                continue
            for val in VALUES:
                if polka_gate and not _polka(locals_, r, val):
                    continue
                nl = list(ls)
                nl[3 + r] = val
                nl[6] = val
                nl[7] = r
                yield i, tuple(nl)


def _explore(lock_rule=True, polka_gate=True, monotone=True,
             state_cap=20_000_000):
    """BFS over the full reachable space. Returns (violation_found,
    states_visited); also structurally asserts HonestNoEquivocation
    (write-once honest slots) on every transition."""
    init = _canon([INIT_LOCAL] * N_HONEST)
    seen = {init}
    frontier = deque([init])
    while frontier:
        state = frontier.popleft()
        if _agreement_violated(state):
            return True, len(seen)
        for i, nl in _successors(state, lock_rule, polka_gate, monotone):
            # HonestNoEquivocation: only empty slots were written
            old = state[i]
            for k in range(6):
                assert old[k] == EMPTY or old[k] == nl[k], (
                    "honest vote overwritten — checker transition bug"
                )
            nxt = _canon(state[:i] + (nl,) + state[i + 1 :])
            if nxt not in seen:
                seen.add(nxt)
                frontier.append(nxt)
        assert len(seen) <= state_cap, "state space exceeded cap"
    return False, len(seen)


def test_agreement_holds_4val_3round():
    """The Agreement theorem, checked over the full reachable space of
    the 4-validator / 3-round / 2-value instance."""
    violated, n = _explore()
    assert not violated, "Agreement violated — POL locking rules broken"
    # the space is non-trivial (sanity that reductions didn't collapse
    # it; the full instance explores ~47k canonical states)
    assert n > 10_000, f"suspiciously small explored space: {n}"


def test_checker_detects_violation_without_lock_rule():
    """Dropping the POL lock guard must break Agreement: a validator
    that precommitted A in round 0 can freely prevote B later, letting a
    B quorum form at a higher round. Proves the explorer can find
    violations at all."""
    violated, _ = _explore(lock_rule=False)
    assert violated, "explorer failed to find the known lock-rule attack"


def test_checker_detects_violation_without_polka_gate():
    """Dropping the polka gate on precommits must break Agreement
    immediately (validators precommit arbitrary values)."""
    violated, _ = _explore(polka_gate=False)
    assert violated, "explorer failed to find the known polka-gate attack"


def test_checker_detects_violation_without_monotonicity():
    """The bug this machine check originally caught in the TLA module:
    without per-validator round monotonicity, an honest validator can
    prevote B at round 1 BEFORE acting in round 0, lock A at round 0,
    and the stale round-1 polka later unlocks another A-locked validator
    toward a B quorum at round 2 — two decisions, two values. Keeping
    this regression test pins the NoLaterVotes guard as load-bearing."""
    violated, _ = _explore(monotone=False)
    assert violated, "the round-order attack disappeared — model changed?"
