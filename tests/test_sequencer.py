"""Sequencer mode: BlockV2 production, signed gossip, sync catchup.

Mirrors the reference's sequencer suite (sequencer/state_v2_test.go,
block_cache_test.go — 27 tests) plus an end-to-end net over real p2p,
and the PR 10 streaming-plane suite: event-driven apply/sync (no
polling-tick reliance), windowed catchup with request expiry,
encode-once backpressure-aware fan-out, coalesced off-loop signature
verification, and the live upgrade-height crossing under chaos.
"""

import asyncio

import pytest

from tendermint_tpu.crypto import secp256k1

pytestmark = pytest.mark.sequencer
from tendermint_tpu.l2node.mock import MockL2Node
from tendermint_tpu.p2p.key import NodeKey
from tendermint_tpu.p2p.node_info import NodeInfo
from tendermint_tpu.p2p.switch import Switch
from tendermint_tpu.p2p.transport import MultiplexTransport, NetAddress
from tendermint_tpu.sequencer import (
    BlockBroadcastReactor,
    BlockRingBuffer,
    HashSet,
    LocalSigner,
    PendingBlockCache,
    StateV2,
    StaticSequencerVerifier,
)
from tendermint_tpu.types.block_v2 import BlockV2

NETWORK = "seq-chain"


# --- caches ----------------------------------------------------------------


def test_ring_buffer_eviction():
    rb = BlockRingBuffer(capacity=3)
    for n in range(5):
        rb.add(BlockV2(number=n, hash=bytes([n]) * 32))
    assert rb.get_by_height(1) is None
    assert rb.get_by_height(4).number == 4
    assert len(rb) == 3


def test_hash_set_dedup_and_eviction():
    s = HashSet(capacity=2)
    assert not s.add(b"a")
    assert s.add(b"a")  # duplicate
    s.add(b"b")
    s.add(b"c")  # evicts "a"
    assert b"a" not in s
    assert b"c" in s


def test_pending_cache_longest_chain():
    c = PendingBlockCache()
    root = b"\x00" * 32

    def blk(n, h, parent):
        return BlockV2(number=n, hash=h * 32, parent_hash=parent)

    # two forks off root: [a1] and [b1 <- b2]
    a1 = blk(1, b"\x0a", root)
    b1 = blk(1, b"\x0b", root)
    b2 = blk(2, b"\x0c", b1.hash)
    for b in (a1, b1, b2):
        assert c.add(b, local_height=0)
    chain = c.get_longest_chain(root)
    assert [b.number for b in chain] == [1, 2]
    assert chain[0].hash == b1.hash
    c.prune_below(1)
    assert c.get(a1.hash) is None and c.get(b1.hash) is None
    assert c.get(b2.hash) is not None


def test_pending_cache_height_window():
    c = PendingBlockCache()
    far = BlockV2(number=500, hash=b"\x01" * 32, parent_hash=b"\x02" * 32)
    assert not c.add(far, local_height=10)  # too far ahead
    assert c.add(far, local_height=450)


# --- BlockV2 signature semantics -------------------------------------------


def test_block_v2_sign_recover_roundtrip():
    key = secp256k1.PrivKey.from_secret(b"seq-key")
    signer = LocalSigner(key)
    l2 = MockL2Node()
    block, _ = l2.request_block_data_v2(l2.get_latest_block_v2().hash)
    block.signature = signer.sign(block.hash)
    assert block.recover_signer() == signer.address()
    # wire roundtrip preserves recoverability
    rt = BlockV2.decode(block.encode())
    assert rt.recover_signer() == signer.address()
    assert rt.transactions == block.transactions
    # a flipped signature byte recovers a different (or no) signer
    bad = BlockV2.decode(block.encode())
    bad.signature = bytes([block.signature[0] ^ 1]) + block.signature[1:]
    assert bad.recover_signer() != signer.address()


# --- StateV2 production -----------------------------------------------------


def test_state_v2_produces_signed_blocks():
    key = secp256k1.PrivKey.from_secret(b"producer")
    signer = LocalSigner(key)
    l2 = MockL2Node()
    verifier = StaticSequencerVerifier([signer.address()])

    async def run():
        sv = StateV2(l2, block_interval=0.01, signer=signer, verifier=verifier)
        await sv.start()
        b1 = await sv.produce_block()
        b2 = await sv.produce_block()
        await sv.stop()
        return b1, b2

    b1, b2 = asyncio.run(run())
    assert b2.parent_hash == b1.hash
    assert b1.recover_signer() == signer.address()
    assert l2.get_latest_block_v2().hash == b2.hash


# --- end-to-end over p2p ----------------------------------------------------


def _build_seq_node(
    signer, verifier, *, wait_sync=False, l2=None, intervals=0.1
):
    l2 = l2 or MockL2Node()
    sv = StateV2(l2, block_interval=0.05, signer=signer, verifier=verifier)
    nk = NodeKey.generate()
    transport = None
    sw = None

    def node_info():
        return NodeInfo(
            node_id=nk.id,
            listen_addr=f"127.0.0.1:{transport.listen_port}",
            network=NETWORK,
            channels=sw.channels() if sw else b"",
        )

    transport = MultiplexTransport(nk, node_info)
    sw = Switch(transport)
    reactor = BlockBroadcastReactor(
        sv,
        verifier,
        wait_sync=wait_sync,
        apply_interval=intervals,
        sync_interval=intervals,
    )
    sw.add_reactor("sequencer", reactor)
    return sv, reactor, nk, transport, sw


async def _start_and_connect(nodes):
    for _, _, _, t, sw in nodes:
        await t.listen()
        await sw.start()
    for i, (_, _, nk_i, t_i, sw_i) in enumerate(nodes):
        for j, (_, _, nk_j, t_j, _) in enumerate(nodes):
            if j <= i:
                continue
            await sw_i.dial_peer(NetAddress(nk_j.id, "127.0.0.1", t_j.listen_port))


def test_sequencer_gossip_and_follower_apply():
    key = secp256k1.PrivKey.from_secret(b"seq-e2e")
    signer = LocalSigner(key)
    verifier = StaticSequencerVerifier([signer.address()])

    async def run():
        seq = _build_seq_node(signer, verifier)
        fol = _build_seq_node(None, verifier)
        nodes = [seq, fol]
        await _start_and_connect(nodes)
        for _, r, *_ in nodes:
            await r.on_start()
        # wait until the follower applied a few gossiped blocks
        for _ in range(100):
            await asyncio.sleep(0.05)
            if fol[0].latest_height() >= 3:
                break
        seq_h = seq[0].latest_height()
        fol_h = fol[0].latest_height()
        assert fol_h >= 3, f"follower stuck at {fol_h} (seq at {seq_h})"
        assert (
            fol[0].latest_block.recover_signer() == signer.address()
        )
        for _, r, _, _, sw in nodes:
            await r.on_stop()
            await sw.stop()

    asyncio.run(run())


def test_follower_rejects_wrong_signer():
    seq_key = secp256k1.PrivKey.from_secret(b"real-seq")
    rogue_key = secp256k1.PrivKey.from_secret(b"rogue")
    signer = LocalSigner(rogue_key)  # rogue signs blocks
    verifier = StaticSequencerVerifier(
        [LocalSigner(seq_key).address()]
    )  # ...but only real-seq is allowed

    async def run():
        seq = _build_seq_node(signer, verifier)
        fol = _build_seq_node(None, verifier)
        nodes = [seq, fol]
        await _start_and_connect(nodes)
        for _, r, *_ in nodes:
            await r.on_start()
        await asyncio.sleep(0.5)
        h = fol[0].latest_height()
        for _, r, _, _, sw in nodes:
            await r.on_stop()
            await sw.stop()
        return h

    assert asyncio.run(run()) == 0, "follower applied a rogue-signed block"


def test_sync_gap_catchup():
    """A follower joining far behind fetches blocks over the sync channel
    (reference checkSyncGap + requestMissingBlocks :351-383)."""
    key = secp256k1.PrivKey.from_secret(b"seq-gap")
    signer = LocalSigner(key)
    verifier = StaticSequencerVerifier([signer.address()])

    async def run():
        seq = _build_seq_node(signer, verifier)
        # pre-produce 30 blocks (> SMALL_GAP_THRESHOLD) before follower joins
        await seq[0].start()
        for _ in range(30):
            await seq[0].produce_block()
        fol = _build_seq_node(None, verifier)
        nodes = [seq, fol]
        await _start_and_connect(nodes)
        seq[1].sequencer_started = True  # StateV2 already started above
        seq[1]._tasks.append(
            asyncio.create_task(seq[1]._broadcast_routine())
        )
        await fol[1].on_start()
        for _ in range(200):
            await asyncio.sleep(0.05)
            if fol[0].latest_height() >= 30:
                break
        h = fol[0].latest_height()
        for _, r, _, _, sw in nodes:
            await r.on_stop()
            await sw.stop()
        return h

    assert asyncio.run(run()) >= 30


def test_bft_upgrade_hands_off_to_sequencer():
    """A BFT chain crossing upgrade_height switches to sequencer mode and
    keeps producing BlockV2s (reference node.go:1612-1632
    switchToSequencerMode wired from consensus/state.go:1921-1938)."""
    from .helpers import make_genesis, make_validators
    from .test_consensus import make_node

    vs, pvs = make_validators(1)
    genesis = make_genesis(vs)
    key = secp256k1.PrivKey.from_secret(b"upgrade-seq")
    signer = LocalSigner(key)
    verifier = StaticSequencerVerifier([signer.address()])
    l2 = MockL2Node()

    async def run():
        sv = StateV2(l2, block_interval=999, signer=signer, verifier=verifier)
        produced = []

        async def on_upgrade(state):
            # mirror switchToSequencerMode: seed L2 to the BFT height and
            # start StateV2 production
            l2.seed_v2_height(state.last_block_height)
            await sv.start()
            produced.append(await sv.produce_block())
            produced.append(await sv.produce_block())

        cs, app, _, bs, ss = make_node(
            vs, pvs[0], genesis, l2=l2, upgrade_height=2, on_upgrade=on_upgrade
        )
        await cs.start()
        await cs.wait_for_height(2, timeout=30)
        await asyncio.sleep(0.2)
        await cs.stop()
        await sv.stop()
        return produced

    produced = asyncio.run(run())
    assert len(produced) == 2
    assert produced[0].number == 3  # continues above the BFT chain
    assert produced[1].number == 4
    assert produced[0].recover_signer() == signer.address()


def test_out_of_order_blocks_buffered_in_pending_cache():
    """Future blocks land in the pending cache and apply once the gap
    closes (reference onBlockV2 future-block caching + tryApplyFromCache)."""
    key = secp256k1.PrivKey.from_secret(b"seq-ooo")
    signer = LocalSigner(key)
    verifier = StaticSequencerVerifier([signer.address()])

    async def run():
        l2 = MockL2Node()
        sv = StateV2(l2, block_interval=999, signer=None, verifier=verifier)
        await sv.start()
        reactor = BlockBroadcastReactor(sv, verifier)

        # build a 3-block signed chain out-of-band
        src_l2 = MockL2Node()
        chain = []
        parent = src_l2.get_latest_block_v2().hash
        for _ in range(3):
            b, _ = src_l2.request_block_data_v2(parent)
            b.signature = signer.sign(b.hash)
            src_l2.apply_block_v2(b)
            chain.append(b)
            parent = b.hash

        class FakePeer:
            id = "fake-peer"

            def try_send(self, ch, msg):
                return True

        peer = FakePeer()
        # deliver 3, 2 (buffered), then 1 (applies; cache drains the rest)
        await reactor._on_block_v2(chain[2], peer, verify_sig=True)
        await reactor._on_block_v2(chain[1], peer, verify_sig=True)
        assert sv.latest_height() == 0
        assert reactor.pending_cache.size() == 2
        await reactor._on_block_v2(chain[0], peer, verify_sig=True)
        assert sv.latest_height() == 3
        await sv.stop()

    asyncio.run(run())


# --- PR 10: event-driven streaming plane ------------------------------------


def _signed_chain(signer, n, l2=None):
    """n signed linked blocks from a fresh mock chain (+ the source l2)."""
    src = l2 or MockL2Node()
    chain = []
    parent = src.get_latest_block_v2().hash
    for _ in range(n):
        b, _ = src.request_block_data_v2(parent)
        b.signature = signer.sign(b.hash)
        src.apply_block_v2(b)
        chain.append(b)
        parent = b.hash
    return chain, src


class _FakePeer:
    """try_send-only peer double with an adjustable send-queue headroom
    (None = no queue_headroom attribute semantics: always send)."""

    def __init__(self, pid="fake-peer", headroom=None):
        self.id = pid
        self._headroom = headroom
        self.sent: list[tuple[int, bytes]] = []

    def try_send(self, ch, msg):
        if self._headroom is not None and self._headroom <= 0:
            return False
        self.sent.append((ch, msg))
        return True

    def queue_headroom(self, ch):
        return 1000 if self._headroom is None else self._headroom


class _FakeSwitch:
    def __init__(self, peers):
        self.peers = {p.id: p for p in peers}


def test_event_driven_apply_no_polling_tick():
    """With the apply/sync fallback tick cranked to 60 s, gossiped
    blocks must still apply promptly — receipt wakes the plane, the
    interval is only a fallback (the polled original would sit for up
    to 10 s)."""
    key = secp256k1.PrivKey.from_secret(b"seq-event")
    signer = LocalSigner(key)
    verifier = StaticSequencerVerifier([signer.address()])

    async def run():
        seq = _build_seq_node(signer, verifier, intervals=60.0)
        fol = _build_seq_node(None, verifier, intervals=60.0)
        nodes = [seq, fol]
        await _start_and_connect(nodes)
        for _, r, *_ in nodes:
            await r.on_start()
        import time as _time

        t0 = _time.perf_counter()
        for _ in range(200):
            await asyncio.sleep(0.02)
            if fol[0].latest_height() >= 3:
                break
        wall = _time.perf_counter() - t0
        h = fol[0].latest_height()
        lats = list(fol[1].apply_latencies)
        for _, r, _, _, sw in nodes:
            await r.on_stop()
            await sw.stop()
        return h, wall, lats

    h, wall, lats = asyncio.run(run())
    assert h >= 3, f"follower stuck at {h} with 60 s fallback ticks"
    # 3 blocks at 0.05 s production cadence: event-driven apply keeps
    # pace with production, nowhere near even ONE fallback tick
    assert wall < 10.0, f"took {wall:.1f}s — rode the fallback tick?"
    assert lats and max(lats) < 2.0, f"apply latencies {lats[:5]}..."


def test_windowed_catchup_event_driven():
    """A follower joining 30+ blocks behind catches up through the
    0x51 window without polling ticks: each landed response refills the
    request window (sync_interval is 60 s — the polled original needed
    >= 2 ten-second cycles for a 30-block gap)."""
    key = secp256k1.PrivKey.from_secret(b"seq-window")
    signer = LocalSigner(key)
    verifier = StaticSequencerVerifier([signer.address()])

    async def run():
        seq = _build_seq_node(signer, verifier, intervals=60.0)
        await seq[0].start()
        for _ in range(30):
            await seq[0].produce_block()
        fol = _build_seq_node(None, verifier, intervals=60.0)
        nodes = [seq, fol]
        await _start_and_connect(nodes)
        seq[1].sequencer_started = True  # StateV2 already started above
        seq[1]._tasks.append(
            asyncio.create_task(seq[1]._broadcast_routine())
        )
        await fol[1].on_start()
        import time as _time

        t0 = _time.perf_counter()
        for _ in range(400):
            await asyncio.sleep(0.02)
            if fol[0].latest_height() >= 30:
                break
        wall = _time.perf_counter() - t0
        h = fol[0].latest_height()
        outstanding = len(fol[1].requested_heights)
        for _, r, _, _, sw in nodes:
            await r.on_stop()
            await sw.stop()
        return h, wall, outstanding

    h, wall, outstanding = asyncio.run(run())
    assert h >= 30, f"follower caught up only to {h}"
    assert wall < 8.0, f"catchup took {wall:.1f}s with 60 s sync ticks"
    # landed heights left the window (satellite: no lifetime accumulation)
    assert outstanding <= 5, f"{outstanding} stale requested heights"


def test_requested_heights_expire():
    """Satellite: requested_heights entries answered by NoBlockResponse
    or belonging to a departed peer expire instead of accumulating for
    the life of the node (and a TTL covers silent peers)."""
    key = secp256k1.PrivKey.from_secret(b"seq-expire")
    signer = LocalSigner(key)
    verifier = StaticSequencerVerifier([signer.address()])

    async def run():
        sv = StateV2(MockL2Node(), signer=None, verifier=verifier)
        await sv.start()
        reactor = BlockBroadcastReactor(sv, verifier, sync_interval=0.1)
        p1 = _FakePeer("p1")
        p2 = _FakePeer("p2")
        reactor.switch = _FakeSwitch([p1, p2])
        reactor.peer_heights = {"p1": 100, "p2": 100}
        await reactor._request_missing_blocks(1, 100)
        assert len(reactor.requested_heights) == reactor.catchup_window
        # NoBlockResponse from the asked peer expires that height
        h0 = next(iter(reactor.requested_heights))
        asked = reactor.requested_heights[h0][0]
        reactor._on_no_block(h0, p1 if asked == "p1" else p2)
        assert h0 not in reactor.requested_heights
        # ...and clamps the peer's advertised height below the miss
        assert reactor.peer_heights[asked] == h0 - 1
        # a departed peer's in-flight requests expire with it
        victim = p1 if any(
            pid == "p1" for pid, _ in reactor.requested_heights.values()
        ) else p2
        await reactor.remove_peer(victim, "bye")
        assert all(
            pid != victim.id
            for pid, _ in reactor.requested_heights.values()
        )
        # TTL: silent peers' entries age out on the next sync pass
        import time as _time

        stale_t = _time.monotonic() - reactor.request_ttl - 1
        old = {
            h: (pid, stale_t)
            for h, (pid, _t) in reactor.requested_heights.items()
        }
        reactor.requested_heights = dict(old)
        await reactor.check_sync_gap()
        # expired entries were dropped and immediately RE-requested with
        # fresh timestamps (the event-driven window refills itself)
        assert all(
            t > stale_t for _pid, t in reactor.requested_heights.values()
        ), "TTL-expired requests survived the sync pass"
        await sv.stop()

    asyncio.run(run())


def test_encode_once_fanout_many_peers():
    """Tentpole: gossiping one block to N subscriber peers costs ONE
    BlockV2 serialization (memoized encode shared by every framed
    send), and serving the same block on the sync channel reuses it."""
    from tendermint_tpu.types import block_v2 as bv2

    key = secp256k1.PrivKey.from_secret(b"seq-encode-once")
    signer = LocalSigner(key)
    verifier = StaticSequencerVerifier([signer.address()])

    async def run():
        sv = StateV2(MockL2Node(), signer=None, verifier=verifier)
        await sv.start()
        reactor = BlockBroadcastReactor(sv, verifier)
        peers = [_FakePeer(f"p{i}") for i in range(8)]
        reactor.switch = _FakeSwitch(peers)
        chain, _src = _signed_chain(signer, 1)
        block = chain[0]
        before = bv2.serializations()
        reactor._gossip_block(block, from_peer="")
        assert bv2.serializations() - before == 1
        sent = [p for p in peers if p.sent]
        assert len(sent) == 8
        # all eight sends share the identical framed message object/bytes
        msgs = {p.sent[0][1] for p in peers}
        assert len(msgs) == 1
        # a sync-channel serve of the same block is another cache hit
        reactor.recent_blocks.add(block)
        await reactor._on_block_request(block.number, peers[0])
        assert bv2.serializations() - before == 1
        # mutation invalidates: a re-signed block re-serializes once
        block.signature = signer.sign(block.hash)
        block.encode()
        assert bv2.serializations() - before == 2
        await sv.stop()

    asyncio.run(run())


def test_backpressure_skips_and_revisits_slow_subscriber():
    """Tentpole: a peer with a full 0x50 send queue is skipped (fan-out
    never blocks behind it) and revisited once its queue drains; the
    healthy peers get the block immediately."""
    key = secp256k1.PrivKey.from_secret(b"seq-backpressure")
    signer = LocalSigner(key)
    verifier = StaticSequencerVerifier([signer.address()])

    async def run():
        sv = StateV2(MockL2Node(), signer=None, verifier=verifier)
        await sv.start()
        reactor = BlockBroadcastReactor(sv, verifier)
        slow = _FakePeer("slow", headroom=0)
        fast = _FakePeer("fast")
        reactor.switch = _FakeSwitch([slow, fast])
        chain, _src = _signed_chain(signer, 1)
        block = chain[0]
        reactor._gossip_block(block, from_peer="")
        assert fast.sent and not slow.sent
        assert "slow" in reactor._fanout_pending
        # queue drains -> the revisit task delivers without a re-gossip
        slow._headroom = 10
        for _ in range(100):
            await asyncio.sleep(0.02)
            if slow.sent:
                break
        assert slow.sent, "deferred block never revisited"
        assert not reactor._fanout_pending
        # bookkeeping: the slow peer is now marked sent (no duplicate)
        reactor._gossip_block(block, from_peer="")
        assert len(slow.sent) == 1 and len(fast.sent) == 1
        # teardown the lazily-spawned revisit task
        await reactor.on_stop()

    asyncio.run(run())


def test_verify_batcher_coalesces_burst_into_one_round():
    """Tentpole: a burst of follower-side ECDSA checks coalesces into
    fn-lane scheduler rounds under the `sequencer` class instead of one
    on-loop recover per block."""
    from tendermint_tpu.parallel.scheduler import (
        CLASS_ORDER,
        VerifyScheduler,
        set_default_scheduler,
    )

    # lane position: directly below live consensus, above every backfill
    assert CLASS_ORDER.index("sequencer") == CLASS_ORDER.index("consensus") + 1

    key = secp256k1.PrivKey.from_secret(b"seq-batcher")
    signer = LocalSigner(key)
    verifier = StaticSequencerVerifier([signer.address()])
    chain, _src = _signed_chain(signer, 16)
    forged = BlockV2.decode(chain[0].encode())
    forged.signature = bytes([chain[0].signature[0] ^ 1]) + chain[0].signature[1:]

    async def run():
        sched = VerifyScheduler()
        await sched.start()
        set_default_scheduler(sched)
        try:
            from tendermint_tpu.sequencer import SequencerVerifyBatcher

            batcher = SequencerVerifyBatcher(verifier)
            verdicts = await batcher.submit_items(chain + [forged])
            batcher.stop()
            rounds = [
                d for d in sched.dispatch_log
                if d.get("fn") and d["classes"] == ["sequencer"]
            ]
            return verdicts, rounds
        finally:
            set_default_scheduler(None)
            await sched.stop()

    verdicts, rounds = asyncio.run(run())
    assert verdicts[:16] == [True] * 16
    assert verdicts[16] is False
    # 17 checks -> a handful of coalesced fn rounds (first may dispatch
    # alone while the rest accumulate), every one under `sequencer`
    assert rounds and len(rounds) <= 3
    assert sum(d["n"] for d in rounds) == 17


@pytest.mark.chaos
def test_upgrade_crossing_partitioned_follower_heals_via_sync(tmp_path):
    """Satellite: a live in-proc full-Node net (1 sequencer validator +
    2 subscriber followers) crosses UpgradeBlockHeight; one follower is
    then partitioned while the net streams past the small-gap
    threshold, and after heal it must catch back up via the 0x51 sync
    channel's windowed requests."""
    import time as _time

    from tendermint_tpu.chaos import ChaosNetwork, NodeHandle
    from tendermint_tpu.crypto import secp256k1 as _secp
    from tendermint_tpu.libs.metrics import (
        SequencerMetrics,
        default_metrics,
    )
    from tendermint_tpu.node import init_files as _init
    from tendermint_tpu.p2p.transport import NetAddress as _Addr
    from tendermint_tpu.sequencer.broadcast_reactor import (
        SMALL_GAP_THRESHOLD,
    )
    from tendermint_tpu.config import Config
    from tools.loadtime import _build_stream_node, _wait

    switch_height = 2
    seq_key = _secp.PrivKey.from_secret(b"chaos-upgrade-seq")
    seq_addr_hex = "0x" + LocalSigner(seq_key).address().hex()
    seq_home = str(tmp_path / "seq")
    seq_cfg = Config.test_config()
    seq_cfg.root_dir = seq_home
    seq_cfg.base.db_backend = "memory"
    seq_cfg.rpc.laddr = ""
    seq_cfg.p2p.laddr = "tcp://127.0.0.1:0"
    genesis = _init(seq_cfg)

    async def run():
        seq_node, _seq_l2 = _build_stream_node(
            seq_home,
            genesis,
            switch_height=switch_height,
            block_interval=0.05,
            seq_key_hex=seq_key.bytes().hex(),
        )
        followers = []
        for i in range(2):
            node, _ = _build_stream_node(
                str(tmp_path / f"f{i}"),
                genesis,
                switch_height=switch_height,
                block_interval=0.05,
                seq_addr_hex=seq_addr_hex,
            )
            followers.append(node)
        nodes = [seq_node] + followers
        names = ["seq", "f0", "f1"]
        net = ChaosNetwork(seed=3)
        for name, node in zip(names, nodes):
            net.install(
                NodeHandle(
                    name=name,
                    cs=node.consensus,
                    node_key=node.node_key,
                    transport=node.transport,
                    switch=node.switch,
                    block_store=node.block_store,
                )
            )
        try:
            for node in nodes:
                await node.start()
            port = seq_node.transport.listen_port
            for f in followers:
                f.switch.dial_peers_async(
                    [_Addr(seq_node.node_key.id, "127.0.0.1", port)],
                    persistent=True,
                )
            # cross the upgrade: every node switches to sequencer mode
            await _wait(
                lambda: all(
                    n.sequencer_reactor.sequencer_started for n in nodes
                ),
                90.0,
                "all nodes to cross UpgradeBlockHeight",
            )
            lagger = followers[1]
            healthy = followers[0]
            await net.partition("cut", [["seq", "f0"], ["f1"]])
            cut_at = lagger.state_v2.latest_height()
            # build a backlog past the small-gap threshold
            await _wait(
                lambda: healthy.state_v2.latest_height()
                >= cut_at + SMALL_GAP_THRESHOLD + 10,
                90.0,
                "a post-partition backlog past the small-gap threshold",
            )
            assert lagger.state_v2.latest_height() <= cut_at + 2, (
                "partitioned follower kept advancing"
            )
            reqs0 = default_metrics(SequencerMetrics).catchup_requests.value()
            await net.heal("cut")
            t0 = _time.perf_counter()
            await _wait(
                lambda: lagger.state_v2.latest_height()
                >= healthy.state_v2.latest_height() - SMALL_GAP_THRESHOLD,
                90.0,
                "the healed follower to catch up over 0x51",
            )
            wall = _time.perf_counter() - t0
            reqs = (
                default_metrics(SequencerMetrics).catchup_requests.value()
                - reqs0
            )
            # the catch-up rode the windowed sync channel, event-driven:
            # well under one 10 s polling cycle for the whole gap
            assert reqs > 0, "no 0x51 catchup requests after heal"
            assert wall < 30.0, f"catchup took {wall:.1f}s"
        finally:
            for node in nodes:
                try:
                    await node.stop()
                except Exception:
                    pass

    asyncio.run(run())


def test_prewarm_sequencer_family_coverage():
    """Satellite: the `sequencer` scheduler class is a first-class
    prewarm family — manifests record covering it, and --verify fails
    a requirement against a manifest whose recorded coverage predates
    the class (even though its reachable ladder-tier set is empty:
    host-native ECDSA rides the fn lane, not the ladder)."""
    from tools.prewarm import FAMILY_TIERS, check_families

    assert FAMILY_TIERS["sequencer"] == ()
    entries = [
        {"tier": "small", "bucket": 8},
        {"tier": "big", "bucket": 8192},
    ]
    covering = {"entries": entries, "families": sorted(FAMILY_TIERS)}
    assert check_families(covering, families=["sequencer"]) == []
    # a manifest built before the class existed recorded its coverage
    # without `sequencer` -> the requirement fails loudly
    legacy = {
        "entries": entries,
        "families": ["blocksync", "consensus", "evidence", "light",
                     "lightserve"],
    }
    problems = check_families(legacy, families=["sequencer"])
    assert problems and "not covered by this manifest build" in problems[0]
    # a pre-coverage manifest (no `families` key at all) cannot
    # vacuously pass an empty-tier family: there is no tier evidence
    nokey = {"entries": entries}
    problems = check_families(nokey, families=["sequencer"])
    assert problems and "records no family coverage" in problems[0]
    # ...while tier-backed families keep the legacy tier-evidence path
    assert check_families(nokey, families=["lightserve"]) == []
    # unknown names still fail (typo guard unchanged)
    typo = check_families(covering, families=["sequencerr"])
    assert typo and "not a known verify class" in typo[0]
