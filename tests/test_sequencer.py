"""Sequencer mode: BlockV2 production, signed gossip, sync catchup.

Mirrors the reference's sequencer suite (sequencer/state_v2_test.go,
block_cache_test.go — 27 tests) plus an end-to-end net over real p2p.
"""

import asyncio

from tendermint_tpu.crypto import secp256k1
from tendermint_tpu.l2node.mock import MockL2Node
from tendermint_tpu.p2p.key import NodeKey
from tendermint_tpu.p2p.node_info import NodeInfo
from tendermint_tpu.p2p.switch import Switch
from tendermint_tpu.p2p.transport import MultiplexTransport, NetAddress
from tendermint_tpu.sequencer import (
    BlockBroadcastReactor,
    BlockRingBuffer,
    HashSet,
    LocalSigner,
    PendingBlockCache,
    StateV2,
    StaticSequencerVerifier,
)
from tendermint_tpu.types.block_v2 import BlockV2

NETWORK = "seq-chain"


# --- caches ----------------------------------------------------------------


def test_ring_buffer_eviction():
    rb = BlockRingBuffer(capacity=3)
    for n in range(5):
        rb.add(BlockV2(number=n, hash=bytes([n]) * 32))
    assert rb.get_by_height(1) is None
    assert rb.get_by_height(4).number == 4
    assert len(rb) == 3


def test_hash_set_dedup_and_eviction():
    s = HashSet(capacity=2)
    assert not s.add(b"a")
    assert s.add(b"a")  # duplicate
    s.add(b"b")
    s.add(b"c")  # evicts "a"
    assert b"a" not in s
    assert b"c" in s


def test_pending_cache_longest_chain():
    c = PendingBlockCache()
    root = b"\x00" * 32

    def blk(n, h, parent):
        return BlockV2(number=n, hash=h * 32, parent_hash=parent)

    # two forks off root: [a1] and [b1 <- b2]
    a1 = blk(1, b"\x0a", root)
    b1 = blk(1, b"\x0b", root)
    b2 = blk(2, b"\x0c", b1.hash)
    for b in (a1, b1, b2):
        assert c.add(b, local_height=0)
    chain = c.get_longest_chain(root)
    assert [b.number for b in chain] == [1, 2]
    assert chain[0].hash == b1.hash
    c.prune_below(1)
    assert c.get(a1.hash) is None and c.get(b1.hash) is None
    assert c.get(b2.hash) is not None


def test_pending_cache_height_window():
    c = PendingBlockCache()
    far = BlockV2(number=500, hash=b"\x01" * 32, parent_hash=b"\x02" * 32)
    assert not c.add(far, local_height=10)  # too far ahead
    assert c.add(far, local_height=450)


# --- BlockV2 signature semantics -------------------------------------------


def test_block_v2_sign_recover_roundtrip():
    key = secp256k1.PrivKey.from_secret(b"seq-key")
    signer = LocalSigner(key)
    l2 = MockL2Node()
    block, _ = l2.request_block_data_v2(l2.get_latest_block_v2().hash)
    block.signature = signer.sign(block.hash)
    assert block.recover_signer() == signer.address()
    # wire roundtrip preserves recoverability
    rt = BlockV2.decode(block.encode())
    assert rt.recover_signer() == signer.address()
    assert rt.transactions == block.transactions
    # a flipped signature byte recovers a different (or no) signer
    bad = BlockV2.decode(block.encode())
    bad.signature = bytes([block.signature[0] ^ 1]) + block.signature[1:]
    assert bad.recover_signer() != signer.address()


# --- StateV2 production -----------------------------------------------------


def test_state_v2_produces_signed_blocks():
    key = secp256k1.PrivKey.from_secret(b"producer")
    signer = LocalSigner(key)
    l2 = MockL2Node()
    verifier = StaticSequencerVerifier([signer.address()])

    async def run():
        sv = StateV2(l2, block_interval=0.01, signer=signer, verifier=verifier)
        await sv.start()
        b1 = await sv.produce_block()
        b2 = await sv.produce_block()
        await sv.stop()
        return b1, b2

    b1, b2 = asyncio.run(run())
    assert b2.parent_hash == b1.hash
    assert b1.recover_signer() == signer.address()
    assert l2.get_latest_block_v2().hash == b2.hash


# --- end-to-end over p2p ----------------------------------------------------


def _build_seq_node(signer, verifier, *, wait_sync=False, l2=None):
    l2 = l2 or MockL2Node()
    sv = StateV2(l2, block_interval=0.05, signer=signer, verifier=verifier)
    nk = NodeKey.generate()
    transport = None
    sw = None

    def node_info():
        return NodeInfo(
            node_id=nk.id,
            listen_addr=f"127.0.0.1:{transport.listen_port}",
            network=NETWORK,
            channels=sw.channels() if sw else b"",
        )

    transport = MultiplexTransport(nk, node_info)
    sw = Switch(transport)
    reactor = BlockBroadcastReactor(sv, verifier, wait_sync=wait_sync)
    reactor.apply_interval = 0.1
    reactor.sync_interval = 0.1
    sw.add_reactor("sequencer", reactor)
    return sv, reactor, nk, transport, sw


async def _start_and_connect(nodes):
    for _, _, _, t, sw in nodes:
        await t.listen()
        await sw.start()
    for i, (_, _, nk_i, t_i, sw_i) in enumerate(nodes):
        for j, (_, _, nk_j, t_j, _) in enumerate(nodes):
            if j <= i:
                continue
            await sw_i.dial_peer(NetAddress(nk_j.id, "127.0.0.1", t_j.listen_port))


def test_sequencer_gossip_and_follower_apply():
    key = secp256k1.PrivKey.from_secret(b"seq-e2e")
    signer = LocalSigner(key)
    verifier = StaticSequencerVerifier([signer.address()])

    async def run():
        seq = _build_seq_node(signer, verifier)
        fol = _build_seq_node(None, verifier)
        nodes = [seq, fol]
        await _start_and_connect(nodes)
        for _, r, *_ in nodes:
            await r.on_start()
        # wait until the follower applied a few gossiped blocks
        for _ in range(100):
            await asyncio.sleep(0.05)
            if fol[0].latest_height() >= 3:
                break
        seq_h = seq[0].latest_height()
        fol_h = fol[0].latest_height()
        assert fol_h >= 3, f"follower stuck at {fol_h} (seq at {seq_h})"
        assert (
            fol[0].latest_block.recover_signer() == signer.address()
        )
        for _, r, _, _, sw in nodes:
            await r.on_stop()
            await sw.stop()

    asyncio.run(run())


def test_follower_rejects_wrong_signer():
    seq_key = secp256k1.PrivKey.from_secret(b"real-seq")
    rogue_key = secp256k1.PrivKey.from_secret(b"rogue")
    signer = LocalSigner(rogue_key)  # rogue signs blocks
    verifier = StaticSequencerVerifier(
        [LocalSigner(seq_key).address()]
    )  # ...but only real-seq is allowed

    async def run():
        seq = _build_seq_node(signer, verifier)
        fol = _build_seq_node(None, verifier)
        nodes = [seq, fol]
        await _start_and_connect(nodes)
        for _, r, *_ in nodes:
            await r.on_start()
        await asyncio.sleep(0.5)
        h = fol[0].latest_height()
        for _, r, _, _, sw in nodes:
            await r.on_stop()
            await sw.stop()
        return h

    assert asyncio.run(run()) == 0, "follower applied a rogue-signed block"


def test_sync_gap_catchup():
    """A follower joining far behind fetches blocks over the sync channel
    (reference checkSyncGap + requestMissingBlocks :351-383)."""
    key = secp256k1.PrivKey.from_secret(b"seq-gap")
    signer = LocalSigner(key)
    verifier = StaticSequencerVerifier([signer.address()])

    async def run():
        seq = _build_seq_node(signer, verifier)
        # pre-produce 30 blocks (> SMALL_GAP_THRESHOLD) before follower joins
        await seq[0].start()
        for _ in range(30):
            await seq[0].produce_block()
        fol = _build_seq_node(None, verifier)
        nodes = [seq, fol]
        await _start_and_connect(nodes)
        seq[1].sequencer_started = True  # StateV2 already started above
        seq[1]._tasks.append(
            asyncio.create_task(seq[1]._broadcast_routine())
        )
        await fol[1].on_start()
        for _ in range(200):
            await asyncio.sleep(0.05)
            if fol[0].latest_height() >= 30:
                break
        h = fol[0].latest_height()
        for _, r, _, _, sw in nodes:
            await r.on_stop()
            await sw.stop()
        return h

    assert asyncio.run(run()) >= 30


def test_bft_upgrade_hands_off_to_sequencer():
    """A BFT chain crossing upgrade_height switches to sequencer mode and
    keeps producing BlockV2s (reference node.go:1612-1632
    switchToSequencerMode wired from consensus/state.go:1921-1938)."""
    from .helpers import make_genesis, make_validators
    from .test_consensus import make_node

    vs, pvs = make_validators(1)
    genesis = make_genesis(vs)
    key = secp256k1.PrivKey.from_secret(b"upgrade-seq")
    signer = LocalSigner(key)
    verifier = StaticSequencerVerifier([signer.address()])
    l2 = MockL2Node()

    async def run():
        sv = StateV2(l2, block_interval=999, signer=signer, verifier=verifier)
        produced = []

        async def on_upgrade(state):
            # mirror switchToSequencerMode: seed L2 to the BFT height and
            # start StateV2 production
            l2.seed_v2_height(state.last_block_height)
            await sv.start()
            produced.append(await sv.produce_block())
            produced.append(await sv.produce_block())

        cs, app, _, bs, ss = make_node(
            vs, pvs[0], genesis, l2=l2, upgrade_height=2, on_upgrade=on_upgrade
        )
        await cs.start()
        await cs.wait_for_height(2, timeout=30)
        await asyncio.sleep(0.2)
        await cs.stop()
        await sv.stop()
        return produced

    produced = asyncio.run(run())
    assert len(produced) == 2
    assert produced[0].number == 3  # continues above the BFT chain
    assert produced[1].number == 4
    assert produced[0].recover_signer() == signer.address()


def test_out_of_order_blocks_buffered_in_pending_cache():
    """Future blocks land in the pending cache and apply once the gap
    closes (reference onBlockV2 future-block caching + tryApplyFromCache)."""
    key = secp256k1.PrivKey.from_secret(b"seq-ooo")
    signer = LocalSigner(key)
    verifier = StaticSequencerVerifier([signer.address()])

    async def run():
        l2 = MockL2Node()
        sv = StateV2(l2, block_interval=999, signer=None, verifier=verifier)
        await sv.start()
        reactor = BlockBroadcastReactor(sv, verifier)

        # build a 3-block signed chain out-of-band
        src_l2 = MockL2Node()
        chain = []
        parent = src_l2.get_latest_block_v2().hash
        for _ in range(3):
            b, _ = src_l2.request_block_data_v2(parent)
            b.signature = signer.sign(b.hash)
            src_l2.apply_block_v2(b)
            chain.append(b)
            parent = b.hash

        class FakePeer:
            id = "fake-peer"

            def try_send(self, ch, msg):
                return True

        peer = FakePeer()
        # deliver 3, 2 (buffered), then 1 (applies; cache drains the rest)
        await reactor._on_block_v2(chain[2], peer, verify_sig=True)
        await reactor._on_block_v2(chain[1], peer, verify_sig=True)
        assert sv.latest_height() == 0
        assert reactor.pending_cache.size() == 2
        await reactor._on_block_v2(chain[0], peer, verify_sig=True)
        assert sv.latest_height() == 3
        await sv.stop()

    asyncio.run(run())
