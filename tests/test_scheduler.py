"""Unified verification dispatch scheduler: coalescing, priority,
per-submitter FIFO, clean drain, thread bridges, metrics.

Device-path bit-exactness (pad-to-bucket inertness vs the host oracle)
lives in test_batch_verifier.py — these tests pin the scheduling
contracts with deterministic stubs and the host fast path, so they stay
in the quick tier."""

import asyncio
import threading
import time

import numpy as np

from tendermint_tpu.crypto import ed25519 as host
from tendermint_tpu.crypto.batch_verifier import BatchVerifier, SigItem
from tendermint_tpu.libs.metrics import Registry, SchedulerMetrics
from tendermint_tpu.parallel.scheduler import (
    VerifyScheduler,
    default_dispatch,
    set_default_scheduler,
)

BAD = b"\x00" * 64


def _item(i: int, ok: bool = True) -> SigItem:
    return SigItem(b"\x01" * 32, b"m%d" % i, b"\x02" * 64 if ok else BAD)


class StubVerifier:
    """Deterministic stand-in: records each dispatched batch, optional
    device-ish latency so submissions coalesce into the next round."""

    def __init__(self, delay: float = 0.0):
        self.delay = delay
        self.batches: list[list[SigItem]] = []

    def verify(self, items):
        if self.delay:
            time.sleep(self.delay)
        self.batches.append(list(items))
        return np.array([it.sig != BAD for it in items])


def _sched(stub=None, **kw) -> VerifyScheduler:
    return VerifyScheduler(
        verifier=stub or StubVerifier(),
        metrics=SchedulerMetrics(Registry("test")),
        **kw,
    )


def test_cross_subsystem_coalescing():
    """Items from different classes merge into ONE padded dispatch while
    a round is in flight, and each submission's verdicts stay aligned."""
    stub = StubVerifier(delay=0.02)
    s = _sched(stub)

    async def run():
        await s.start()
        # first submission occupies the device; the rest queue and must
        # coalesce into one follow-up round
        first = asyncio.create_task(s.submit([_item(0)], "consensus"))
        await asyncio.sleep(0.005)
        outs = await asyncio.gather(
            s.submit([_item(1), _item(2, ok=False)], "consensus"),
            s.submit([_item(3)], "blocksync"),
            s.submit([_item(4)], "light"),
            first,
        )
        await s.stop()
        return outs

    a, b, c, first = asyncio.run(run())
    assert a.tolist() == [True, False]
    assert b.tolist() == [True]
    assert c.tolist() == [True]
    assert first.tolist() == [True]
    sizes = sorted(len(batch) for batch in stub.batches)
    assert sizes == [1, 4], f"expected one coalesced round, got {sizes}"
    coalesced = [d for d in s.dispatch_log if d["subs"] >= 2]
    assert coalesced and set(coalesced[0]["classes"]) == {
        "consensus", "blocksync", "light",
    }
    assert s.metrics.dispatch_coalesced.value() == 1


def test_consensus_preempts_bulk_flood():
    """A blocksync flood must not starve consensus: a consensus item
    submitted mid-flood rides the very next round."""
    stub = StubVerifier(delay=0.01)
    s = _sched(stub, max_batch=64)

    async def run():
        await s.start()
        flood = [
            asyncio.create_task(
                s.submit([_item(1000 + 64 * j + i) for i in range(64)],
                         "blocksync")
            )
            for j in range(8)
        ]
        await asyncio.sleep(0.015)  # flood is mid-flight
        t0 = time.perf_counter()
        ok = await s.submit([_item(0)], "consensus")
        consensus_wait = time.perf_counter() - t0
        await asyncio.gather(*flood)
        await s.stop()
        return ok, consensus_wait

    ok, wait = asyncio.run(run())
    assert ok.tolist() == [True]
    # serial drain of the remaining flood would be ~6 rounds x 10 ms;
    # preemption bounds the wait to ~1-2 rounds
    assert wait < 0.04, f"consensus starved behind flood: {wait:.3f}s"
    # and the round carrying the consensus item ran before the flood end
    idx = next(
        i for i, batch in enumerate(stub.batches)
        if any(it.msg == b"m0" for it in batch)
    )
    assert idx < len(stub.batches) - 1


def test_per_submitter_fifo_order():
    """Verdicts resolve strictly in submission order within a class,
    including when a large submission spans multiple rounds."""
    stub = StubVerifier(delay=0.002)
    s = _sched(stub, max_batch=16)
    resolved = []

    async def one(tag, items):
        await s.submit(items, "blocksync")
        resolved.append(tag)

    async def run():
        await s.start()
        tasks = [
            asyncio.create_task(one(0, [_item(i) for i in range(40)])),
        ]
        await asyncio.sleep(0)  # deterministic enqueue order
        tasks += [
            asyncio.create_task(one(1, [_item(100 + i) for i in range(4)])),
            asyncio.create_task(one(2, [_item(200)])),
        ]
        await asyncio.gather(*tasks)
        await s.stop()

    asyncio.run(run())
    assert resolved == [0, 1, 2]
    # the 40-item submission split across max_batch=16 rounds
    assert max(len(b) for b in stub.batches) <= 16


def test_clean_drain_on_stop():
    """stop() dispatches everything already queued — no submission is
    abandoned or failed."""
    stub = StubVerifier(delay=0.01)
    s = _sched(stub)

    async def run():
        await s.start()
        subs = [
            asyncio.create_task(s.submit([_item(i)], "consensus"))
            for i in range(24)
        ]
        await asyncio.sleep(0)  # enqueue, then immediately drain
        await s.stop()
        return await asyncio.gather(*subs)

    outs = asyncio.run(run())
    assert all(o.tolist() == [True] for o in outs)
    assert sum(len(b) for b in stub.batches) == 24


def test_threadsafe_bridge_and_fallbacks():
    """submit_sync coalesces from worker threads; degrades to direct
    dispatch on an event-loop thread, before start, and after stop."""
    stub = StubVerifier(delay=0.005)
    s = _sched(stub)

    # not started: direct
    out = s.submit_sync([_item(0)], "blocksync")
    assert out.tolist() == [True] and len(stub.batches) == 1

    async def run():
        await s.start()
        loop = asyncio.get_running_loop()
        outs = await asyncio.gather(
            *(
                loop.run_in_executor(
                    None, s.submit_sync, [_item(10 + i)], "blocksync"
                )
                for i in range(6)
            )
        )
        # on the loop thread: direct dispatch, never a deadlock
        onloop = s.classed("light").verify([_item(99)])
        await s.stop()
        return outs, onloop

    outs, onloop = asyncio.run(run())
    assert all(o.tolist() == [True] for o in outs)
    assert onloop.tolist() == [True]
    # after stop: direct again
    assert s.submit_sync([_item(1)], "blocksync").tolist() == [True]


def test_fn_lane_serializes_with_priority():
    """A private-engine (BLS-style) submission dispatches as its own
    round on the shared dispatch thread, under the same class order."""
    stub = StubVerifier(delay=0.01)
    s = _sched(stub)
    fn_batches = []

    def bls_like(items):
        fn_batches.append(list(items))
        return [True for _ in items]

    async def run():
        await s.start()
        sig = asyncio.create_task(s.submit([_item(0)], "blocksync"))
        await asyncio.sleep(0.003)
        fn = asyncio.create_task(
            s.submit_fn([("pk", "msg", "sig")], bls_like, "consensus")
        )
        out = await asyncio.gather(sig, fn)
        await s.stop()
        return out

    sig_out, fn_out = asyncio.run(run())
    assert sig_out.tolist() == [True]
    assert fn_out == [True]
    assert fn_batches == [[("pk", "msg", "sig")]]
    assert any(d.get("fn") for d in s.dispatch_log)


def test_failed_partial_submission_drops_remainder():
    """When a round carrying one slice of a multi-round submission
    fails, the queued remainder is discarded — the scheduler must not
    burn device rounds on a future that already holds the exception."""

    class FailFirst(StubVerifier):
        def __init__(self):
            super().__init__()
            self.calls = 0

        def verify(self, items):
            self.calls += 1
            if self.calls == 1:
                raise RuntimeError("boom")
            return super().verify(items)

    stub = FailFirst()
    s = _sched(stub, max_batch=8)

    async def run():
        await s.start()
        big = asyncio.create_task(
            s.submit([_item(i) for i in range(40)], "blocksync")
        )
        try:
            raised = not (await big)
        except RuntimeError:
            raised = True
        # after the failure settles, a fresh submission still verifies
        ok = await s.submit([_item(100)], "consensus")
        await s.stop()
        return raised, ok

    raised, ok = asyncio.run(run())
    assert raised, "failed submission must surface its exception"
    assert ok.tolist() == [True]
    # round 1 (8 items) failed; at most ONE already-pipelined residual
    # round (8 items) may have executed before the failure was observed;
    # the remaining >=24 items were dropped at the queue head
    dead = sum(
        len(b) for b in stub.batches if any(it.sig != BAD for it in b)
        and any(it.msg != b"m100" for it in b)
    )
    assert dead <= 8, f"dead rounds kept dispatching: {dead} items"
    assert sum(len(b) for b in stub.batches) <= 9


def test_shape_registry_rows_dimension():
    """A grown table store is a new program even at the same bucket:
    the registry keys shapes on (bucket, rows, devices)."""
    from tendermint_tpu.crypto.shape_registry import ShapeRegistry

    reg = ShapeRegistry()
    assert reg.record_dispatch("small", 8, rows=128) is True
    assert reg.record_dispatch("small", 8, rows=128) is False
    assert reg.record_dispatch("small", 8, rows=256) is True  # regrown
    assert reg.record_dispatch("generic", 8) is True
    assert reg.distinct_shapes("small") == 2
    assert reg.buckets_by_tier()["small"] == (8,)
    assert reg.shapes_by_tier()["small"] == ((8, 128, 1), (8, 256, 1))
    assert reg.dispatch_count() == 4
    # a sharded round is a distinct program even at the same bucket/rows
    assert reg.record_dispatch("small", 8, rows=128, devices=4) is True
    assert reg.record_dispatch("small", 8, rows=128, devices=4) is False
    assert reg.distinct_shapes("small") == 3
    assert reg.sharded_dispatch_count() == 2
    snap = reg.snapshot()
    assert snap["sharded_dispatch_count"] == 2
    delta = ShapeRegistry.delta(
        snap, (reg.record_dispatch("small", 8, rows=128, devices=4),
               reg.snapshot())[1]
    )
    assert delta["sharded_dispatch_count"] == 1
    assert delta["device_dispatch_count"] == 1
    assert delta["distinct_program_shapes"] == 0


def test_verifier_failure_resolves_futures_and_recovers():
    """A verifier exception fails the affected submissions (the sync
    bridge then falls back to direct dispatch) without killing the
    worker — later rounds still verify."""

    class FlakyVerifier(StubVerifier):
        def __init__(self):
            super().__init__()
            self.fail_next = True

        def verify(self, items):
            if self.fail_next:
                self.fail_next = False
                raise RuntimeError("injected device fault")
            return super().verify(items)

    s = _sched(FlakyVerifier())

    async def run():
        await s.start()
        loop = asyncio.get_running_loop()
        # bridge path: scheduler round fails -> direct fallback verifies
        out1 = await loop.run_in_executor(
            None, s.submit_sync, [_item(0)], "blocksync"
        )
        out2 = await s.submit([_item(1)], "consensus")
        await s.stop()
        return out1, out2

    out1, out2 = asyncio.run(run())
    assert out1.tolist() == [True]
    assert out2.tolist() == [True]


def test_metrics_and_queue_depth_accounting():
    stub = StubVerifier(delay=0.01)
    s = _sched(stub)

    async def run():
        await s.start()
        first = asyncio.create_task(s.submit([_item(0)], "consensus"))
        await asyncio.sleep(0.003)
        queued = asyncio.create_task(
            s.submit([_item(i) for i in range(1, 5)], "blocksync")
        )
        await asyncio.sleep(0)
        depth_mid = s.metrics.queue_depth.value(klass="blocksync")
        await asyncio.gather(first, queued)
        await s.stop()
        return depth_mid

    depth_mid = asyncio.run(run())
    assert depth_mid == 4  # queued while round 1 was in flight
    assert s.metrics.queue_depth.value(klass="blocksync") == 0
    assert s.metrics.dispatches.value() >= 2
    assert 0 < s.metrics.batch_fill_ratio.value() <= 1.0


def test_real_host_verifier_through_scheduler():
    """End-to-end with the real BatchVerifier host fast path: verdicts
    through the scheduler are bit-identical to the serial host oracle,
    adversarial rows included."""
    v = BatchVerifier(min_device_batch=1 << 30)
    s = VerifyScheduler(
        verifier=v, metrics=SchedulerMetrics(Registry("test2"))
    )
    keys = [host.PrivKey.from_secret(b"sched%d" % i) for i in range(8)]
    items, want = [], []
    for i, k in enumerate(keys):
        msg = b"vote-%d" % i
        sig = k.sign(msg)
        if i % 3 == 1:
            sig = BAD
        if i % 3 == 2:
            msg = msg + b"!"
        items.append(SigItem(k.public_key().data, msg, sig))
        want.append(host.verify(items[-1].pubkey, msg, items[-1].sig))

    async def run():
        await s.start()
        loop = asyncio.get_running_loop()
        got = await loop.run_in_executor(
            None, s.submit_sync, items, "blocksync"
        )
        await s.stop()
        return got

    got = asyncio.run(run())
    assert got.tolist() == want


def test_default_dispatch_plumbing():
    """default_dispatch returns the raw verifier with no scheduler
    installed, and a classed adapter (self-degrading while stopped)
    when one is."""
    from tendermint_tpu.crypto.batch_verifier import default_verifier

    set_default_scheduler(None)
    assert default_dispatch("light") is default_verifier()
    s = _sched()
    set_default_scheduler(s)
    try:
        adapter = default_dispatch("light")
        assert adapter is not default_verifier()
        # not started -> degrades to direct dispatch on the stub
        assert adapter.verify([_item(0)]).tolist() == [True]
    finally:
        set_default_scheduler(None)


def test_vote_batcher_routes_via_scheduler():
    """VoteBatcher bound to the shared verifier rides the installed
    scheduler; its batches appear in the scheduler's dispatch log under
    the consensus class."""
    from tendermint_tpu.consensus.vote_batcher import VoteBatcher

    stub = StubVerifier()
    s = _sched(stub)
    set_default_scheduler(s)
    try:
        batcher = VoteBatcher()  # no explicit verifier -> routable
        batcher._route_scheduler = True

        async def run():
            await s.start()
            outs = await asyncio.gather(
                *(
                    batcher.submit(b"\x01" * 32, b"m%d" % i, b"\x02" * 64)
                    for i in range(6)
                )
            )
            batcher.stop()
            await s.stop()
            return outs

        outs = asyncio.run(run())
        assert all(outs)
        assert sum(len(b) for b in stub.batches) == 6
        assert all(
            d["classes"] == ["consensus"] for d in s.dispatch_log
        )
    finally:
        set_default_scheduler(None)
