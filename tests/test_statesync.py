"""Statesync: chunk queue, syncer against the kvstore app, p2p bootstrap.

Mirrors the reference suite shape (statesync/ 35 tests) in compressed form.
"""

import asyncio
import hashlib

import pytest

from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.statesync import ChunkQueue, StateSyncReactor, Syncer
from tendermint_tpu.statesync.chunks import Chunk
from tendermint_tpu.statesync.syncer import ErrNoSnapshots


class FakePeer:
    def __init__(self, pid="peer-0"):
        self.id = pid
        self.sent = []

    def try_send(self, ch, msg):
        self.sent.append((ch, msg))
        return True


# --- chunk queue -----------------------------------------------------------


def test_chunk_queue_allocation_and_completion():
    q = ChunkQueue(3)
    assert q.allocate() == 0
    assert q.allocate() == 1
    assert q.allocate() == 2
    assert q.allocate() is None
    for i in range(3):
        assert q.add(Chunk(1, 1, i, b"c%d" % i, sender="p"))
    assert not q.add(Chunk(1, 1, 1, b"dup", sender="p"))  # duplicate
    assert not q.add(Chunk(1, 1, 99, b"oob", sender="p"))  # out of range
    assert q.complete


def test_chunk_queue_retry_and_sender_discard():
    q = ChunkQueue(3)
    q.add(Chunk(1, 1, 0, b"a", sender="good"))
    q.add(Chunk(1, 1, 1, b"b", sender="evil"))
    q.add(Chunk(1, 1, 2, b"c", sender="evil"))
    assert sorted(q.discard_sender("evil")) == [1, 2]
    assert not q.complete
    assert q.allocate() == 1  # freed for refetch


# --- state provider + syncer over a real app ------------------------------


class DirectStateProvider:
    """Test double standing in for the light-client provider: serves the
    trusted app hash / state / commit recorded from the source node."""

    def __init__(self, app_hash, state=None, commit=None):
        self._app_hash = app_hash
        self._state = state
        self._commit = commit

    async def app_hash(self, height):
        return self._app_hash

    async def state(self, height):
        return self._state

    async def commit(self, height):
        return self._commit


def _run_source_app(n_txs=30):
    """A kvstore app with some committed state + snapshots."""
    app = KVStoreApplication()
    app.SNAPSHOT_CHUNK_SIZE = 64  # force multiple chunks
    for i in range(n_txs):
        app.deliver_tx(b"key%d=value%d" % (i, i))
        app.commit()
    return app


def test_syncer_restores_kvstore_snapshot():
    src = _run_source_app()
    snaps = src.list_snapshots()
    assert snaps and snaps[-1].chunks > 1
    snap = snaps[-1]

    dst = KVStoreApplication()
    dst.SNAPSHOT_CHUNK_SIZE = 64
    provider = DirectStateProvider(
        src.info().last_block_app_hash, state="STATE", commit="COMMIT"
    )

    sent_requests = []

    def request_chunk(peer, height, fmt, index):
        sent_requests.append(index)
        # serve synchronously from the source app
        data = src.load_snapshot_chunk(height, fmt, index)
        syncer.add_chunk(Chunk(height, fmt, index, data, sender=peer.id))

    syncer = Syncer(dst, provider, request_chunk)
    peer = FakePeer()
    assert syncer.add_snapshot(peer, snap)

    async def run():
        return await syncer.sync_any(discovery_time=0.1)

    state, commit = asyncio.run(run())
    assert state == "STATE" and commit == "COMMIT"
    assert dst._state == src._state
    assert dst.info().last_block_app_hash == src.info().last_block_app_hash
    assert len(set(sent_requests)) == snap.chunks


def test_syncer_restores_snapshot_over_grpc_external_app():
    """The external-app wiring end to end: the DESTINATION app lives in
    another 'process' behind the gRPC transport (node.py routes the
    statesync snapshot connection through _ConnProxy -> GRPCClient), so
    offer_snapshot/apply_snapshot_chunk cross the wire as async client
    calls — the coroutine-tolerant path in syncer.py."""
    from tendermint_tpu.abci.grpc_transport import GRPCClient, GRPCServer

    src = _run_source_app()
    snap = src.list_snapshots()[-1]
    dst = KVStoreApplication()
    dst.SNAPSHOT_CHUNK_SIZE = 64
    provider = DirectStateProvider(
        src.info().last_block_app_hash, state="STATE", commit="COMMIT"
    )

    async def run():
        server = GRPCServer(dst, port=0)
        await server.start()
        client = GRPCClient(port=server.port)
        await client.connect()

        def request_chunk(peer, height, fmt, index):
            data = src.load_snapshot_chunk(height, fmt, index)
            syncer.add_chunk(
                Chunk(height, fmt, index, data, sender=peer.id)
            )

        syncer = Syncer(client, provider, request_chunk)
        assert syncer.add_snapshot(FakePeer(), snap)
        state, commit = await syncer.sync_any(discovery_time=0.1)
        await client.close()
        await server.stop()
        return state, commit

    state, commit = asyncio.run(run())
    assert state == "STATE" and commit == "COMMIT"
    assert dst._state == src._state
    assert dst.info().last_block_app_hash == src.info().last_block_app_hash


def test_syncer_rejects_corrupted_snapshot_then_no_snapshots():
    src = _run_source_app()
    snap = src.list_snapshots()[-1]
    dst = KVStoreApplication()
    dst.SNAPSHOT_CHUNK_SIZE = 64
    provider = DirectStateProvider(b"\x00" * 32)  # wrong trusted hash

    def request_chunk(peer, height, fmt, index):
        data = src.load_snapshot_chunk(height, fmt, index)
        syncer.add_chunk(Chunk(height, fmt, index, data, sender=peer.id))

    syncer = Syncer(dst, provider, request_chunk)
    syncer.add_snapshot(FakePeer(), snap)

    async def run():
        with pytest.raises(ErrNoSnapshots):
            # the snapshot gets rejected (restored hash != trusted), and
            # with no other snapshots and no discovery budget SyncAny bails
            await syncer.sync_any(discovery_time=0)

    asyncio.run(run())


def test_statesync_over_p2p_bootstrap():
    """Full path: fresh node discovers the snapshot over 0x60, fetches
    chunks over 0x61, restores, and the app states match."""
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.p2p.node_info import NodeInfo
    from tendermint_tpu.p2p.switch import Switch
    from tendermint_tpu.p2p.transport import MultiplexTransport, NetAddress

    src = _run_source_app()
    dst = KVStoreApplication()
    dst.SNAPSHOT_CHUNK_SIZE = 64
    provider = DirectStateProvider(
        src.info().last_block_app_hash, state="STATE", commit="COMMIT"
    )

    def build(app, syncer):
        nk = NodeKey.generate()
        transport = None
        sw = None

        def node_info():
            return NodeInfo(
                node_id=nk.id,
                listen_addr=f"127.0.0.1:{transport.listen_port}",
                network="ss-chain",
                channels=sw.channels() if sw else b"",
            )

        transport = MultiplexTransport(nk, node_info)
        sw = Switch(transport)
        reactor = StateSyncReactor(app, syncer)
        sw.add_reactor("statesync", reactor)
        return reactor, nk, transport, sw

    async def run():
        server_r, server_nk, server_t, server_sw = build(src, None)
        syncer_holder = []

        def request_chunk(peer, height, fmt, index):
            client_r.request_chunk(peer, height, fmt, index)

        syncer = Syncer(dst, provider, request_chunk)
        client_r, client_nk, client_t, client_sw = build(dst, syncer)
        for t, sw in ((server_t, server_sw), (client_t, client_sw)):
            await t.listen()
            await sw.start()
        await client_sw.dial_peer(
            NetAddress(server_nk.id, "127.0.0.1", server_t.listen_port)
        )
        await asyncio.sleep(0.2)  # snapshot discovery round-trip
        state, commit = await asyncio.wait_for(
            syncer.sync_any(discovery_time=1.0), 20
        )
        for sw in (server_sw, client_sw):
            await sw.stop()
        return state, commit

    state, commit = asyncio.run(run())
    assert state == "STATE" and commit == "COMMIT"
    assert dst._state == src._state
