"""Evidence pool + verification + gossip + consensus integration.

Mirrors the reference suite shape (evidence/pool_test.go, verify_test.go,
reactor_test.go) in compressed form.
"""

import asyncio

import pytest

from tendermint_tpu.evidence import EvidencePool, EvidenceReactor
from tendermint_tpu.evidence.verify import verify_duplicate_vote
from tendermint_tpu.store.kv import MemKV
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.evidence import DuplicateVoteEvidence
from tendermint_tpu.types.part_set import PartSetHeader
from tendermint_tpu.types.vote import Vote, VoteType

from .helpers import CHAIN_ID, T0, make_genesis, make_validators


def _conflicting_votes(pv, index, height, round_=0, ts=T0):
    """Two precommits from one validator for different blocks."""
    def mk(h):
        v = Vote(
            type=VoteType.PRECOMMIT,
            height=height,
            round=round_,
            block_id=BlockID(
                hash=h, part_set_header=PartSetHeader(1, h)
            ),
            timestamp_ns=ts,
            validator_address=pv.get_pub_key().address(),
            validator_index=index,
        )
        pv.sign_vote(CHAIN_ID, v)
        return v

    return mk(b"\x01" * 32), mk(b"\x02" * 32)


def test_verify_duplicate_vote_rules():
    vs, pvs = make_validators(4)
    va, vb = _conflicting_votes(pvs[0], 0, height=3)
    ev = DuplicateVoteEvidence.from_votes(
        va, vb, vs.total_voting_power(), 10, T0
    )
    ev.validate_basic()
    verify_duplicate_vote(ev, CHAIN_ID, vs)

    # wrong total power
    bad = DuplicateVoteEvidence.from_votes(va, vb, 999, 10, T0)
    with pytest.raises(ValueError, match="total voting power"):
        verify_duplicate_vote(bad, CHAIN_ID, vs)

    # tampered signature
    va2, vb2 = _conflicting_votes(pvs[0], 0, height=3)
    vb2.signature = bytes([vb2.signature[0] ^ 1]) + vb2.signature[1:]
    bad2 = DuplicateVoteEvidence.from_votes(
        va2, vb2, vs.total_voting_power(), 10, T0
    )
    with pytest.raises(ValueError, match="invalid signature"):
        verify_duplicate_vote(bad2, CHAIN_ID, vs)

    # same block id -> not conflicting
    with pytest.raises(ValueError):
        ev_same = DuplicateVoteEvidence.from_votes(
            va, va, vs.total_voting_power(), 10, T0
        )
        ev_same.validate_basic()


def _run_chain_to(cs, h, timeout=60):
    return cs.wait_for_height(h, timeout=timeout)


def test_equivocation_lands_in_committed_block():
    """The full loop (reference pool_test + e2e evidence test): consensus
    captures conflicting votes -> pool constructs evidence on Update ->
    proposer includes it -> it commits -> pool marks it committed."""
    from tendermint_tpu.state.execution import BlockExecutor
    from tendermint_tpu.state.state import State
    from tendermint_tpu.state.store import StateStore
    from tendermint_tpu.store.block_store import BlockStore
    from tendermint_tpu.abci.client import LocalClient
    from tendermint_tpu.abci.kvstore import KVStoreApplication
    from tendermint_tpu.consensus.state_machine import (
        ConsensusConfig,
        ConsensusState,
    )
    from tendermint_tpu.l2node.mock import MockL2Node

    vs, pvs = make_validators(1)
    genesis = make_genesis(vs)

    l2 = MockL2Node()
    app = KVStoreApplication()
    state = State.from_genesis(genesis)
    state_store = StateStore(MemKV())
    state_store.bootstrap(state)
    block_store = BlockStore(MemKV())
    pool = EvidencePool(MemKV(), state_store, block_store)
    executor = BlockExecutor(
        state_store, block_store, LocalClient(app), l2, evidence_pool=pool
    )
    cs = ConsensusState(
        ConsensusConfig.test_config(),
        state,
        executor,
        block_store,
        l2,
        priv_validator=pvs[0],
        evidence_pool=pool,
    )

    # a second signer for the same validator key to craft the equivocation
    rogue_a, rogue_b = _conflicting_votes(pvs[0], 0, height=1)

    async def run():
        await cs.start()
        await cs.wait_for_height(1, timeout=30)
        # feed the conflicting precommits for an already-decided height
        # through the vote path (as if gossiped by a peer)

        # pool must know about them via the consensus conflict capture:
        # report directly (the net path is exercised in the reactor test)
        pool.report_conflicting_votes(rogue_a, rogue_b)
        # next committed height triggers pool.update -> evidence built
        await cs.wait_for_height(3, timeout=30)
        for h in range(2, 4):
            blk = block_store.load_block(h)
            if blk and blk.evidence:
                return blk
        # one more height in case inclusion lagged
        await cs.wait_for_height(4, timeout=30)
        blk = block_store.load_block(4)
        await cs.stop()
        return blk

    blk = asyncio.run(run())
    assert blk is not None and blk.evidence, "evidence never committed"
    ev = blk.evidence[0]
    assert isinstance(ev, DuplicateVoteEvidence)
    assert ev.vote_a.validator_address == pvs[0].get_pub_key().address()
    assert pool.size() == 0, "evidence still pending after commit"


def test_pool_rejects_old_and_unknown_evidence():
    from tendermint_tpu.state.state import State
    from tendermint_tpu.state.store import StateStore
    from tendermint_tpu.store.block_store import BlockStore

    vs, pvs = make_validators(2)
    genesis = make_genesis(vs)
    state = State.from_genesis(genesis)
    state_store = StateStore(MemKV())
    state_store.bootstrap(state)
    block_store = BlockStore(MemKV())
    pool = EvidencePool(MemKV(), state_store, block_store)

    va, vb = _conflicting_votes(pvs[0], 0, height=99)
    ev = DuplicateVoteEvidence.from_votes(
        va, vb, vs.total_voting_power(), 10, T0
    )
    with pytest.raises(ValueError, match="don't have header"):
        pool.add_evidence(ev)


def test_reactor_gossips_evidence_between_peers():
    """Evidence added on node A reaches node B's pool over p2p channel
    0x38 (reference reactor_test.go TestReactorBroadcastEvidence)."""
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.p2p.node_info import NodeInfo
    from tendermint_tpu.p2p.switch import Switch
    from tendermint_tpu.p2p.transport import MultiplexTransport, NetAddress
    from tendermint_tpu.state.execution import BlockExecutor
    from tendermint_tpu.state.state import State
    from tendermint_tpu.state.store import StateStore
    from tendermint_tpu.store.block_store import BlockStore
    from tendermint_tpu.abci.client import LocalClient
    from tendermint_tpu.abci.kvstore import KVStoreApplication
    from tendermint_tpu.consensus.state_machine import (
        ConsensusConfig,
        ConsensusState,
    )
    from tendermint_tpu.l2node.mock import MockL2Node

    vs, pvs = make_validators(1)
    genesis = make_genesis(vs)

    def build():
        l2 = MockL2Node()
        app = KVStoreApplication()
        state = State.from_genesis(genesis)
        ss = StateStore(MemKV())
        ss.bootstrap(state)
        bs = BlockStore(MemKV())
        pool = EvidencePool(MemKV(), ss, bs)
        executor = BlockExecutor(
            ss, bs, LocalClient(app), l2, evidence_pool=pool
        )
        cs = ConsensusState(
            ConsensusConfig.test_config(),
            state,
            executor,
            bs,
            l2,
            priv_validator=pvs[0] if not built else None,
            evidence_pool=pool,
        )
        nk = NodeKey.generate()
        transport = None
        sw = None

        def node_info():
            return NodeInfo(
                node_id=nk.id,
                listen_addr=f"127.0.0.1:{transport.listen_port}",
                network="ev-chain",
                channels=sw.channels() if sw else b"",
            )

        transport = MultiplexTransport(nk, node_info)
        sw = Switch(transport)
        sw.add_reactor("evidence", EvidenceReactor(pool))
        built.append(1)
        return cs, pool, bs, ss, nk, transport, sw

    built = []

    async def run():
        a = build()
        b = build()
        for n in (a, b):
            await n[5].listen()
            await n[6].start()
        await a[6].dial_peer(
            NetAddress(b[4].id, "127.0.0.1", b[5].listen_port)
        )
        # node A runs the chain so both stores have height-1 metadata;
        # replicate A's blocks into B's stores so verification passes
        cs_a = a[0]
        await cs_a.start()
        await cs_a.wait_for_height(1, timeout=30)
        # stop A's chain BEFORE adding evidence: a live proposer would
        # commit the evidence into its own next block within ~one round,
        # draining it from the pending list before the gossip tick fires
        # (that fast path is exactly what
        # test_equivocation_lands_in_committed_block covers)
        await cs_a.stop()
        blk = a[2].load_block(1)
        parts = blk.make_part_set()
        b[2].save_block(blk, parts, a[2].load_seen_commit(1))
        b[3].save(a[3].load())
        b[1]._state = a[3].load()

        va, vb = _conflicting_votes(pvs[0], 0, height=1, ts=blk.header.time_ns)
        ev = DuplicateVoteEvidence.from_votes(
            va, vb, vs.total_voting_power(), 10, blk.header.time_ns
        )
        a[1]._state = a[3].load()
        a[1].add_evidence(ev)
        for _ in range(100):
            await asyncio.sleep(0.05)
            if b[1].size() > 0:
                break
        got = b[1].size()
        for n in (a, b):
            await n[6].stop()
        return got

    assert asyncio.run(run()) == 1, "evidence did not gossip to peer"
