"""Wall-clock conservation + cross-process causal tracing (PR 15).

Covers: the exhaustive per-height bucket decomposition
(obs.report.wall_conservation — buckets sum to measured wall by
construction, residue = dark_time), the dark_time health detector and
its tracer pull seam, the UDS trace-context propagation (client stamps
span context on each verify submission; the service records
queue/device sub-spans under it into its own ring with a dump
endpoint), the cluster merge of service dumps alongside validator dumps
(wall-anchor fallback for nodes outside the NTP peer graph), the
bench_trend conservation schema validation + dark-time gate, and the
4-validator acceptance: attribution buckets cover >= 95% of measured
wall per height on a live net with tracing on."""

import asyncio
import json
import subprocess
import sys
from types import SimpleNamespace

import numpy as np
import pytest

from tendermint_tpu import obs
from tendermint_tpu.obs.health import (
    CRITICAL,
    OK,
    BurnRateSLO,
    DarkTimeDetector,
    HealthMonitor,
)
from tendermint_tpu.obs.report import (
    CONSERVATION_BUCKETS,
    check_conservation,
    conservation_table,
    wall_conservation,
)

from .helpers import make_genesis, make_validators
from .test_consensus import make_node, wire_net

pytestmark = pytest.mark.conservation


def _span(name, t0, dur, height=0, round_=0, **fields):
    return {
        "name": name,
        "t0": t0,
        "dur": dur,
        "height": height,
        "round": round_,
        "kind": "span",
        "fields": fields,
    }


def _height_records(h, base):
    """One height's step spans tiling [base, base+1.0] exactly."""
    return [
        _span("cs.new_height", base + 0.0, 0.3, height=h),
        _span("cs.propose", base + 0.3, 0.3, height=h),
        _span("cs.prevote", base + 0.6, 0.2, height=h),
        _span("cs.commit", base + 0.8, 0.2, height=h),
    ]


# --- the conservation invariant --------------------------------------------


def test_conservation_buckets_sum_to_wall():
    recs = _height_records(5, 0.0) + [
        # WAL fsync inside cs.commit: carved out of compute
        _span("wal.fsync", 0.85, 0.05),
        # verify round trip inside cs.prevote, with the device slice
        # nested inside it — the sweep must NOT double-count the
        # overlap (device claims its segment, ipc keeps the rest)
        _span("verify.ipc", 0.62, 0.1, height=5),
        _span("scheduler.device_round", 0.65, 0.05),
    ]
    cons = wall_conservation(recs)
    row = cons["heights"][5]
    assert row["wall_ms"] == pytest.approx(1000.0)
    assert row["verify_device_ms"] == pytest.approx(50.0)
    assert row["verify_ipc_ms"] == pytest.approx(50.0)  # 100 - 50 overlap
    assert row["wal_fsync_ms"] == pytest.approx(50.0)
    assert row["compute_ms"] == pytest.approx(150.0)  # 200 - 50 fsync
    assert row["gossip_ms"] == pytest.approx(400.0)  # propose + prevote...
    assert row["dark_time_ms"] == pytest.approx(0.0)
    covered = sum(row[f"{b}_ms"] for b in CONSERVATION_BUCKETS)
    assert covered == pytest.approx(row["wall_ms"], abs=1e-6)
    assert cons["aggregate"]["conserved"] is True
    assert cons["aggregate"]["dark_fraction"] == 0.0
    assert check_conservation(cons) == []


def test_conservation_dark_residue_named():
    # a 200 ms hole between prevote and commit that NO span owns —
    # exactly the latency class the audit exists to surface
    recs = [
        _span("cs.new_height", 0.0, 0.3, height=9),
        _span("cs.propose", 0.3, 0.3, height=9),
        _span("cs.prevote", 0.6, 0.2, height=9),
        _span("cs.commit", 1.0, 0.2, height=9),  # gap [0.8, 1.0]
    ]
    cons = wall_conservation(recs)
    row = cons["heights"][9]
    assert row["wall_ms"] == pytest.approx(1200.0)
    assert row["dark_time_ms"] == pytest.approx(200.0)
    assert row["dark_fraction"] == pytest.approx(200.0 / 1200.0, abs=1e-3)
    assert cons["aggregate"]["dark_fraction_max"] == row["dark_fraction"]


def test_conservation_carves_clip_to_window():
    # a bulk blocksync device round half outside the height window only
    # bills the overlapping slice; a fully-disjoint one bills nothing
    recs = _height_records(3, 10.0) + [
        _span("scheduler.device_round", 10.9, 0.4),  # 0.1 inside
        _span("scheduler.device_round", 12.0, 1.0),  # disjoint
    ]
    cons = wall_conservation(recs)
    row = cons["heights"][3]
    assert row["verify_device_ms"] == pytest.approx(100.0)
    covered = sum(row[f"{b}_ms"] for b in CONSERVATION_BUCKETS)
    assert covered == pytest.approx(row["wall_ms"], abs=1e-6)


def test_check_conservation_rejects_bad_sum():
    cons = wall_conservation(_height_records(2, 0.0))
    assert check_conservation(cons) == []
    cons["heights"][2]["gossip_ms"] += 300.0  # bucket no longer sums
    errs = check_conservation(cons)
    assert errs and "height 2" in errs[0]
    assert check_conservation({"nope": 1}) == ["wall_conservation.aggregate missing"]
    # empty capture (no step spans) is valid — nothing to conserve
    assert check_conservation(wall_conservation([])) == []


def test_conservation_table_renders():
    text = conservation_table(wall_conservation(_height_records(4, 0.0)))
    assert "dark" in text and "wall_ms" in text and "4" in text
    assert "(no step spans" in conservation_table(wall_conservation([]))


# --- the dark_time detector -------------------------------------------------


def test_dark_time_detector_floor_and_burn():
    det = DarkTimeDetector(
        BurnRateSLO("dark_time", objective=0.9, min_events=4), floor=0.05
    )
    for i in range(8):
        det.observe_height(float(i), 0.01)  # conserved heights: ok
    assert det.verdict(8.0) == OK
    for i in range(8, 16):
        det.observe_height(float(i), 0.5)  # half the wall is unowned
    # 8 bad of 16 against a 10% budget = 5x burn: warn, not yet page
    assert det.verdict(16.0) == pytest.approx(1)  # WARN
    for i in range(16, 48):
        det.observe_height(float(i), 0.5)
    # sustained: 40/48 bad = 8.3x burn on both windows -> critical
    assert det.verdict(48.0) == CRITICAL
    assert det.last_bad == 0.5
    assert det.last_threshold == 0.05


def test_monitor_conservation_pull_seam():
    tnow = [100.0]
    mon = HealthMonitor(clock=lambda: tnow[0], dark_time_floor=0.05)
    tracer = obs.Tracer(enabled=True)
    base = tracer.epoch
    # heights 1-2 complete and conserved; height 2 carries a dark gap;
    # height 3 is the tip (in progress — must not be judged)
    for r in (
        _height_records(1, 0.0)
        + [
            _span("cs.new_height", 1.0, 0.2, height=2),
            _span("cs.commit", 1.5, 0.5, height=2),  # gap [1.2, 1.5]
        ]
        + [_span("cs.new_height", 2.0, 0.1, height=3)]
    ):
        tracer.add_span(
            r["name"], base + r["t0"], r["dur"], height=r["height"]
        )
    mon.bind_tracer(tracer)
    mon.sample()
    slo = mon.dark_time.slo
    assert slo._total == 2  # heights 1 and 2, never the tip
    assert mon.dark_time.last_bad == pytest.approx(0.3, abs=1e-3)
    mon.sample()
    assert slo._total == 2  # already-judged heights are not re-fed
    # a disabled tracer is a no-op seam
    mon2 = HealthMonitor(clock=lambda: tnow[0])
    mon2.bind_tracer(obs.Tracer(enabled=False))
    mon2.sample()
    assert mon2.dark_time.slo._total == 0


# --- wire trace-context codec ----------------------------------------------


def test_wire_trace_ctx_codec_and_legacy_frames():
    from tendermint_tpu.crypto.batch_verifier import SigItem
    from tendermint_tpu.parallel.verify_service import (
        _HDR,
        _Cursor,
        decode_submit,
        decode_submit_fn,
        decode_trace_ctx,
        encode_submit,
        encode_submit_fn,
    )

    items = [SigItem(b"\x01" * 32, b"m" * 32, b"\x02" * 64, "ed25519")]
    # traced frame round-trips the ctx
    frame = encode_submit(7, items, "consensus", ctx=(42, 1, "nodeA"))
    cur = _Cursor(frame)
    _typ, req_id = _HDR.unpack(cur.take(_HDR.size))
    out_items, klass = decode_submit(cur)
    ctx = decode_trace_ctx(cur, req_id)
    assert klass == "consensus" and len(out_items) == 1
    assert ctx == (42, 1, "nodeA", 7)
    # legacy frame (no trailer): ctx is None, decode unchanged
    cur = _Cursor(encode_submit(8, items, "blocksync"))
    _HDR.unpack(cur.take(_HDR.size))
    _, klass = decode_submit(cur)
    assert klass == "blocksync"
    assert decode_trace_ctx(cur, 8) is None
    # fn lane carries the same trailer
    cur = _Cursor(
        encode_submit_fn(
            9, "bls_agg", [(b"a" * 32, b"b" * 32)], "consensus",
            ctx=(5, 0, "w1"),
        )
    )
    _HDR.unpack(cur.take(_HDR.size))
    engine, fn_items, klass = decode_submit_fn(cur)
    assert engine == "bls_agg" and len(fn_items) == 1
    assert decode_trace_ctx(cur, 9) == (5, 0, "w1", 9)


# --- cross-process propagation e2e ------------------------------------------


class _AllTrueVerifier:
    def verify(self, items):
        return np.ones(len(items), dtype=bool)


def test_service_records_client_span_context_e2e(tmp_path):
    """The acceptance path minus the consensus net: a node-side client
    stamps span context on a UDS submission, the SERVICE process's ring
    records queue/device sub-spans under it, its dump endpoint serves
    them, and the cluster merge lands them in the per-height timeline
    next to the client's own records — with the service rebased through
    the raw-wall-anchor fallback (it has no NTP peer table)."""
    import urllib.request

    from tendermint_tpu.crypto.batch_verifier import SigItem
    from tendermint_tpu.parallel.verify_service import (
        RemoteVerifyScheduler,
        ServiceThread,
    )

    svc_tracer = obs.Tracer(enabled=True)
    cli_tracer = obs.Tracer(enabled=True)
    path = str(tmp_path / "vs.sock")
    svc = ServiceThread(
        path, verifier=_AllTrueVerifier(), tracer=svc_tracer, stats_port=0
    )
    svc.start()
    try:

        async def run():
            client = RemoteVerifyScheduler(
                path,
                verifier=_AllTrueVerifier(),
                tracer=cli_tracer,
                origin="nodeA",
            )
            await client.start()
            for _ in range(200):
                if client.connected:
                    break
                await asyncio.sleep(0.02)
            assert client.connected, "client never attached"
            obs.set_height_hint(42, 1)
            items = [
                SigItem(b"\x01" * 32, b"m" * 32, b"\x02" * 64, "ed25519")
            ] * 3
            verdicts = await client.submit(items, "consensus")
            assert verdicts.all()
            await client.stop()

        asyncio.run(run())
        port = svc.server.stats_port
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/dump_traces", timeout=10
        ) as resp:
            svc_dump = json.load(resp)
    finally:
        svc.stop()
        obs.set_height_hint(0, 0)

    # client side: the round trip under the stamped height
    cli_recs = [r.to_json() for r in cli_tracer.records()]
    ipc = [r for r in cli_recs if r["name"] == "verify.ipc"]
    assert ipc and ipc[0]["height"] == 42 and ipc[0]["round"] == 1
    assert ipc[0]["fields"]["origin"] == "nodeA"

    # service side: queue/device sub-spans recorded by the service
    # process under the SAME context
    by_name = {r["name"]: r for r in svc_dump["records"]}
    for want in ("verify.device", "verify.service"):
        assert want in by_name, sorted(by_name)
        assert by_name[want]["height"] == 42
        assert by_name[want]["fields"]["origin"] == "nodeA"
        assert by_name[want]["fields"]["req"] == ipc[0]["fields"]["req"]
    assert svc_dump["node_id"].startswith("verify-service-")

    # cluster merge: validator dump + service dump on one timeline
    node_dump = obs.normalize_dump(
        {
            "node_id": "AAAA",
            "moniker": "nodeA",
            "epoch_wall_ns": cli_tracer.epoch_wall_ns,
            "records": cli_recs,
            "peer_clock": {},
        }
    )
    sdump = obs.normalize_dump(svc_dump)
    ref, offsets, merged = obs.merge_records([node_dump, sdump])
    assert offsets[sdump["node_id"]]["source"] == "wall_anchor"
    merged_h42 = {
        r["name"] for r in merged if r.get("height") == 42
    }
    assert {"verify.ipc", "verify.device", "verify.service"} <= merged_h42

    # the causal join: RTT >= service handle time; wire overhead named
    flow = obs.verify_flow(merged)
    assert flow["joined"] == 1
    row = flow["heights"]["42"]
    assert row["rows"] == 3
    assert row["ipc_ms"] >= row["device_ms"]
    assert row["wire_ms"] >= 0.0
    # and the cluster report carries/renders the section
    report = obs.cluster_report([node_dump, sdump])
    assert report["verify_flow"]["joined"] == 1
    assert "verify flow" in obs.report_text(report)


def test_multi_round_submission_sums_not_overwrites():
    """A submission larger than max_batch dispatches as several device
    rounds, each recording queue/device sub-spans under the SAME
    (origin, req): verify_flow must accumulate them, and the rounds'
    queue spans must not re-bill earlier rounds' device time — the
    summed sub-spans stay inside the client-observed elapsed."""
    from tendermint_tpu.crypto.batch_verifier import SigItem
    from tendermint_tpu.parallel.scheduler import VerifyScheduler

    tracer = obs.Tracer(enabled=True)
    sched = VerifyScheduler(
        verifier=_AllTrueVerifier(), max_batch=2, tracer=tracer
    )

    async def run():
        await sched.start()
        items = [
            SigItem(b"\x01" * 32, b"m" * 32, b"\x02" * 64, "ed25519")
        ] * 5
        t0 = asyncio.get_running_loop().time()
        verdicts = await sched.submit(
            items, "consensus", ctx=(11, 0, "nodeA", 99)
        )
        elapsed = asyncio.get_running_loop().time() - t0
        await sched.stop()
        return verdicts, elapsed

    verdicts, elapsed = asyncio.run(run())
    assert verdicts.all() and len(verdicts) == 5
    recs = [r.to_json() for r in tracer.records()]
    devs = [r for r in recs if r["name"] == "verify.device"]
    queues = [r for r in recs if r["name"] == "verify.queue"]
    assert len(devs) == 3  # 5 items / max_batch 2
    assert all(r["fields"]["req"] == 99 for r in devs)
    # no queue span overlaps any device span of the same submission
    # (tolerance: to_json rounds t0/dur to microseconds, so adjacent
    # spans can appear to overlap by up to ~2 us)
    for q in queues:
        for d in devs:
            assert (
                q["t0"] + q["dur"] <= d["t0"] + 5e-6
                or d["t0"] + d["dur"] <= q["t0"] + 5e-6
            ), (q, d)
    # the summed sub-spans fit inside the observed elapsed (the
    # conservation property verify_flow's join relies on)
    total = sum(r["dur"] for r in devs + queues)
    assert total <= elapsed + 1e-5  # durs are us-rounded in to_json

    # verify_flow accumulates the rounds instead of keeping the last
    merged = [dict(r, node="svc", node_id="S") for r in recs] + [
        dict(
            _span(
                "verify.ipc", 0.0, elapsed, height=11,
                origin="nodeA", req=99, n=5,
            ),
            node="nodeA",
            node_id="A",
        )
    ]
    flow = obs.verify_flow(merged)
    row = flow["heights"]["11"]
    assert row["device_ms"] == pytest.approx(
        sum(r["dur"] for r in devs) * 1e3, rel=1e-6
    )
    assert row["queue_ms"] == pytest.approx(
        sum(r["dur"] for r in queues) * 1e3, rel=1e-6
    )


# --- cluster offsets under a partitioned peer graph (satellite) -------------


def _dump(node_id, records=(), epoch_wall_ns=0, peer_clock=None, name=""):
    return obs.normalize_dump(
        {
            "node_id": node_id,
            "moniker": name or node_id,
            "epoch_wall_ns": epoch_wall_ns,
            "records": list(records),
            "peer_clock": peer_clock or {},
        }
    )


def test_partitioned_peer_graph_falls_back_to_wall_anchor():
    """Satellite: offset estimation when the NTP peer graph is
    partitioned — an island with no path to the reference must ride its
    raw wall anchor, and the merge must still rebase its records
    correctly through the epoch difference."""
    # island 1: A <-> B via NTP (B's clock +100 ms)
    a = _dump(
        "A",
        [_span("cs.propose", 1.0, 0.1, height=7)],
        epoch_wall_ns=1_000_000_000,
        peer_clock={"B": {"offset_s": 0.1, "rtt_s": 0.002, "samples": 4}},
    )
    b = _dump("B", epoch_wall_ns=1_100_000_000)
    # island 2: C has NO peer table and nobody measures it; its wall
    # anchor is 2.0 s ahead of A's, and its record at local t0=1.0
    # happened at the same wall instant as A's t0=3.0
    c = _dump(
        "C",
        [_span("verify.device", 1.0, 0.05, height=7)],
        epoch_wall_ns=3_000_000_000,
    )
    offsets = obs.estimate_offsets([a, b, c])
    assert offsets["A"]["source"] == "reference"
    assert offsets["B"]["source"] == "ntp_graph"
    assert offsets["B"]["offset_s"] == pytest.approx(0.1)
    assert offsets["C"]["source"] == "wall_anchor"
    assert offsets["C"]["offset_s"] == 0.0

    _, _, merged = obs.merge_records([a, b, c])
    t_by_node = {m["node"]: m["t0"] for m in merged}
    # C's record rebased purely via the anchors: 1.0 + (3.0 - 1.0)
    assert t_by_node["C"] == pytest.approx(3.0, abs=1e-9)
    assert t_by_node["A"] == pytest.approx(1.0, abs=1e-9)
    # the report builds over the partitioned merge without error
    report = obs.cluster_report([a, b, c])
    assert report["offsets"]["C"]["source"] == "wall_anchor"


# --- RPC surface ------------------------------------------------------------


def test_dump_traces_conservation_and_injected_empty_tracer():
    from tendermint_tpu.rpc.core import RPCCore

    # an injected-but-EMPTY tracer is falsy (Tracer has __len__): the
    # route must still dump THIS ring, not the process default (the
    # PR 4 falsy-tracer bug class, swept per the PR 15 satellite)
    tracer = obs.Tracer(enabled=True)
    core = RPCCore(SimpleNamespace(tracer=tracer))
    dump = core.dump_traces()
    assert dump["enabled"] is True and dump["records"] == []

    base = tracer.epoch
    for r in _height_records(6, 0.0):
        tracer.add_span(r["name"], base + r["t0"], r["dur"], height=6)
    dump = core.dump_traces()
    cons = dump["conservation"]
    assert cons["schema"] == obs.CONSERVATION_SCHEMA
    assert cons["heights"]["6"]["dark_time_ms"] == pytest.approx(0.0)
    assert json.loads(json.dumps(dump))  # artifact-grade JSON


# --- bench_trend: schema validation + dark gate (satellite) -----------------


def _artifact(round_no, dark_fraction, tamper=False):
    recs = _height_records(1, 0.0)
    if dark_fraction:
        recs = [
            _span("cs.new_height", 0.0, 1.0 - dark_fraction, height=1),
            _span("cs.commit", 1.0, 0.001, height=1),
        ]
    block = wall_conservation(recs)
    if tamper:
        block["heights"][1]["gossip_ms"] += 500.0
    return {
        "metric": "ed25519_vote_verify_throughput",
        "value": 70000.0,
        "unit": "sigs/s/chip",
        "meta": {"backend": "cpu", "device_count": 1},
        "wall_conservation": block,
    }


def test_bench_trend_conservation_validation_and_gate(tmp_path):
    import tools.bench_trend as bt

    ok = tmp_path / "BENCH_r90.json"
    ok.write_text(json.dumps(_artifact(90, 0.0)))
    rows, skipped, cons = bt.ingest([str(ok)])
    assert rows and not skipped
    assert cons and cons[0]["dark_fraction"] <= 0.001
    assert bt.check_dark(cons, threshold=0.05) == []

    # buckets that fail the sum check reject the artifact's rows
    bad = tmp_path / "BENCH_r91.json"
    bad.write_text(json.dumps(_artifact(91, 0.0, tamper=True)))
    rows, skipped, _ = bt.ingest([str(bad)])
    assert not rows and skipped
    assert "conservation violation" in skipped[0]["reason"]

    # dark fraction past the threshold fails the gate on the LATEST
    # round only (older rounds already landed)
    dark = tmp_path / "BENCH_r92.json"
    dark.write_text(json.dumps(_artifact(92, 0.5)))
    _, _, cons = bt.ingest([str(ok), str(dark)])
    fails = bt.check_dark(cons, threshold=0.05)
    assert len(fails) == 1 and fails[0]["file"] == "BENCH_r92.json"

    # CLI contract: rc=1 with the dark-gate failure named
    out = subprocess.run(
        [
            sys.executable, "tools/bench_trend.py", "--check", "--no-scan",
            str(ok), str(dark),
        ],
        capture_output=True,
        text=True,
        cwd="/root/repo",
        timeout=120,
    )
    assert out.returncode == 1, out.stderr
    assert "dark-time gate" in out.stderr
    # ...and rc=0 once the dark artifact is out of the set
    out = subprocess.run(
        [
            sys.executable, "tools/bench_trend.py", "--check", "--no-scan",
            str(ok),
        ],
        capture_output=True,
        text=True,
        cwd="/root/repo",
        timeout=120,
    )
    assert out.returncode == 0, out.stderr


# --- the 4-validator acceptance ---------------------------------------------


def test_four_validator_conservation_acceptance():
    """ISSUE 15 acceptance: on the 4-validator net with tracing on,
    the attribution buckets sum to >= 95% of measured wall per height
    (dark_time <= 5%), judged from one node's ring (sharing a ring
    across nodes would overlap their height windows)."""
    vs, pvs = make_validators(4)
    genesis = make_genesis(vs)
    tracer = obs.Tracer(enabled=True, ring_size=1 << 15)
    prev_default = obs.default_tracer()
    obs.set_default_tracer(tracer)

    async def run():
        nodes = [
            make_node(
                vs,
                pv,
                genesis,
                tracer=(tracer if i == 0 else obs.Tracer(enabled=False)),
            )
            for i, pv in enumerate(pvs)
        ]
        css = [n[0] for n in nodes]
        wire_net(css)
        for cs in css:
            await cs.start()
        await asyncio.gather(
            *(cs.wait_for_height(4, timeout=60) for cs in css)
        )
        for cs in css:
            await cs.stop()

    try:
        asyncio.run(run())
    finally:
        obs.set_default_tracer(prev_default)

    recs = [r.to_json() for r in tracer.records()]
    cons = wall_conservation(recs)
    agg = cons["aggregate"]
    assert agg["n_heights"] >= 3
    assert agg["conserved"] is True
    assert check_conservation(cons) == []
    # judge completed heights (the tip's window may still be open at
    # stop time); every one must be >= 95% explained
    tip = max(cons["heights"])
    complete = {
        h: v for h, v in cons["heights"].items() if h < tip
    }
    assert complete
    for h, row in complete.items():
        assert row["dark_fraction"] <= 0.05, (
            f"height {h}: {row['dark_fraction']:.1%} of "
            f"{row['wall_ms']:.1f} ms wall is dark: {row}"
        )
    # every height row carries the full bucket schema (the harness
    # runs a NilWAL, so the wal_fsync column exists but stays 0 here;
    # the carve plumbing itself is pinned by the synthetic tests)
    for row in cons["heights"].values():
        for b in CONSERVATION_BUCKETS:
            assert f"{b}_ms" in row
