"""Prewarm manifest tooling + program-shape budget regression.

`perf`-marked (and slow: device compiles): tier-1-adjacent, selected
with `pytest -m perf`. Guards the §10 fix — the bench verify family
must keep compiling from a bounded bucket ladder, and tools/prewarm.py
must keep producing a manifest that covers it."""

import json
import subprocess
import sys

import pytest

pytestmark = [pytest.mark.perf, pytest.mark.slow]


def test_build_manifest_and_budget(tmp_path):
    from tools.prewarm import build_manifest, check_budget

    manifest = build_manifest(ladder=(8, 32), tiers=("small", "generic"))
    assert manifest["ladder"] == [8, 32]
    assert {(e["tier"], e["bucket"]) for e in manifest["entries"]} == {
        ("small", 8), ("small", 32), ("generic", 8), ("generic", 32),
    }
    assert check_budget(manifest, budget=8) == []
    assert check_budget(manifest, budget=1)  # 2 shapes/tier > 1

    # round-trips as the JSON artifact the node's warm thread writes
    path = tmp_path / "prewarm_manifest.json"
    path.write_text(json.dumps(manifest))
    loaded = json.loads(path.read_text())
    assert loaded["entries"] == manifest["entries"]


def test_bench_verify_family_shape_budget():
    """Regression: the verify shapes the bench family dispatches (vote
    buckets, commit buckets, replay windows, bisection batches) stay
    within a fixed per-tier program budget on a fresh registry."""
    from tendermint_tpu.crypto import ed25519 as host
    from tendermint_tpu.crypto.batch_verifier import BatchVerifier, SigItem
    from tendermint_tpu.crypto.shape_registry import ShapeRegistry

    reg = ShapeRegistry()
    v = BatchVerifier(
        min_device_batch=0, bigtable_min=1 << 30, shape_registry=reg
    )
    keys = [host.PrivKey.from_secret(b"fam%d" % i) for i in range(8)]
    # the family's characteristic sizes: single votes, vote bursts,
    # 128-validator commits, multi-commit replay windows
    for n in (1, 4, 21, 64, 127, 128, 96, 33):
        items = []
        for i in range(n):
            k = keys[i % len(keys)]
            msg = b"fam-%d-%d" % (n, i)
            items.append(SigItem(k.public_key().data, msg, k.sign(msg)))
        assert v.verify(items).all()
    shapes = reg.shapes_by_tier()
    for tier, tier_shapes in shapes.items():
        assert len(tier_shapes) <= 8, (
            f"bench verify family exceeded the shape budget in tier "
            f"{tier}: {tier_shapes}"
        )
    assert reg.buckets_by_tier()["small"] == (8, 32, 128)


def test_prewarm_manifest_devices_variants():
    """Under a mesh the ladder prewarms per reachable device variant:
    rungs whose batches can only arrive below mesh_min_rows load the
    replicated (devices=1) program, rungs reachable at/above it also
    load the sharded one; the manifest records the topology."""
    from tools.prewarm import build_manifest, check_topology

    manifest = build_manifest(
        ladder=(8, 32),
        tiers=("small",),
        devices=4,
        mesh_backend="cpu",
        mesh_min_rows=16,
    )
    assert manifest["device_count"] == 4
    assert manifest["mesh_min_rows"] == 16
    shapes = {
        (e["tier"], e["bucket"], e["devices"])
        for e in manifest["entries"]
    }
    # rung 8: only n in 1..8 (< 16) lands there -> unsharded only;
    # rung 32: n in 9..15 unsharded AND n in 16..32 sharded
    assert shapes == {
        ("small", 8, 1),
        ("small", 32, 1),
        ("small", 32, 4),
    }
    assert check_topology(manifest, 4) == []
    assert check_topology(manifest, 8), "device-count drift must fail"
    assert check_topology(manifest, 4, expected_min_rows=16) == []
    assert check_topology(
        manifest, 4, expected_min_rows=1024
    ), "mesh_min_rows drift changes the reachable program set"


def test_prewarm_verify_topology_mismatch(tmp_path):
    """--verify against a manifest built for a larger mesh than the
    live one exits non-zero BEFORE rebuilding anything — a node
    warm-started on the wrong topology fails loudly."""
    out = tmp_path / "m.json"
    out.write_text(
        json.dumps(
            {
                "created_unix": 0,
                "ladder": [8],
                "tiers": ["small"],
                "device_count": 4,
                "mesh_min_rows": 16,
                "entries": [],
            }
        )
    )
    import os

    env = {k: v for k, v in os.environ.items()}
    env["JAX_PLATFORMS"] = "cpu"
    # no forced host device count -> 1 live cpu device != 4
    env["XLA_FLAGS"] = " ".join(
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    r = subprocess.run(
        [
            sys.executable,
            "tools/prewarm.py",
            "--out",
            str(out),
            "--verify",
        ],
        capture_output=True,
        text=True,
        timeout=560,
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert r.returncode == 1, r.stdout + r.stderr
    assert "TOPOLOGY MISMATCH" in r.stdout


def test_prewarm_cli_smoke(tmp_path):
    """tools/prewarm.py end-to-end: build then --verify on a tiny
    ladder, both rc=0, manifest on disk."""
    out = tmp_path / "m.json"
    cmd = [
        sys.executable,
        "tools/prewarm.py",
        "--out", str(out),
        "--ladder", "8",
        "--tiers", "small",
    ]
    env = {"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin:/usr/local/bin"}
    import os

    env.update({k: v for k, v in os.environ.items() if k not in env})
    r = subprocess.run(
        cmd, capture_output=True, text=True, timeout=560, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert r.returncode == 0, r.stdout + r.stderr
    manifest = json.loads(out.read_text())
    assert manifest["entries"][0]["tier"] == "small"
    r2 = subprocess.run(
        cmd + ["--verify", "--reload-threshold", "300"],
        capture_output=True, text=True, timeout=560, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert "verify OK" in r2.stdout
