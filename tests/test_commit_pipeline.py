"""Commit-pipeline tests: group-commit WAL, write-behind block store,
pipelined finalize equivalence, and crash-recovery at every pipeline
stage boundary (chaos-marked).

Crash simulation: a FreezableKV drops writes after the test "pulls the
plug", so the durable snapshot a restart sees is exactly what a real
crash would leave — WAL end-height written (real fsynced file), block
save and/or state save lost. The restarted node must converge to the
identical app hash and state as the serial (unpipelined) path.
"""

from __future__ import annotations

import asyncio
import threading
import time

import pytest

from tendermint_tpu.abci.client import LocalClient
from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.consensus.commit_pipeline import CommitPipeline
from tendermint_tpu.consensus.replay import Handshaker
from tendermint_tpu.consensus.state_machine import (
    ConsensusConfig,
    ConsensusState,
)
from tendermint_tpu.consensus.wal import (
    GroupCommitWAL,
    NilWAL,
    WAL,
    WALMessage,
    decode_records,
)
from tendermint_tpu.l2node.mock import MockL2Node
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.state import State
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.store.block_store import (
    BlockStore,
    WriteBehindBlockStore,
)
from tendermint_tpu.store.kv import MemKV

from tests.helpers import make_genesis, make_validators


# --- crash plumbing ---------------------------------------------------------


class FreezableKV:
    """MemKV wrapper whose writes can be 'lost': after freeze(), set/
    write_batch silently drop — the durable image stays at the freeze
    point, exactly like writes still queued at crash time."""

    def __init__(self, inner=None, freeze_batches_only: bool = False):
        self.inner = inner or MemKV()
        self.frozen = False
        # freeze only write_batch (multi-key saves) while single-key
        # set() still lands — carves the "responses saved, state record
        # lost" mid-apply window
        self.freeze_batches_only = freeze_batches_only

    def freeze(self) -> None:
        self.frozen = True

    def get(self, key):
        return self.inner.get(key)

    def set(self, key, value):
        if self.frozen and not self.freeze_batches_only:
            return
        self.inner.set(key, value)

    def delete(self, key):
        if self.frozen:
            return
        self.inner.delete(key)

    def write_batch(self, sets, deletes):
        if self.frozen:
            return
        self.inner.write_batch(sets, deletes)

    def iterate(self, start=b"", end=None):
        return self.inner.iterate(start, end)

    def close(self):
        self.inner.close()


def _build_node(
    genesis,
    pv,
    wal_path,
    *,
    pipelined: bool,
    app=None,
    l2=None,
    block_kv=None,
    state_kv=None,
    tracer=None,
    metrics=None,
):
    """One consensus node over explicit stores (restartable)."""
    app = app or KVStoreApplication()
    l2 = l2 or MockL2Node()
    block_kv = block_kv if block_kv is not None else MemKV()
    state_kv = state_kv if state_kv is not None else MemKV()
    state_store = StateStore(state_kv)
    if pipelined:
        block_store = WriteBehindBlockStore(
            block_kv, max_inflight=4, metrics=metrics, tracer=tracer
        )
        wal = GroupCommitWAL(
            wal_path, flush_interval=0.001, metrics=metrics, tracer=tracer
        )
        pipeline = CommitPipeline(metrics=metrics, tracer=tracer)
    else:
        block_store = BlockStore(block_kv)
        wal = WAL(wal_path)
        pipeline = None
    state = state_store.load()
    if state is None:
        state = State.from_genesis(genesis)
        state_store.bootstrap(state)
    executor = BlockExecutor(
        state_store, block_store, LocalClient(app), l2
    )
    cs = ConsensusState(
        ConsensusConfig.test_config(),
        state,
        executor,
        block_store,
        l2,
        priv_validator=pv,
        wal=wal,
        commit_pipeline=pipeline,
    )
    return cs, app, l2, block_store, state_store, executor


async def _handshake(cs, genesis, executor, state_store, block_store):
    hs = Handshaker(state_store, block_store, genesis, executor)
    cs.state = await hs.handshake(cs.state)
    return hs


# --- group-commit WAL -------------------------------------------------------


pytestmark = pytest.mark.pipeline


def test_group_wal_write_sync_durable_and_decodable(tmp_path):
    path = str(tmp_path / "wal")
    wal = GroupCommitWAL(path, flush_interval=0.001)
    for i in range(10):
        wal.write_sync(WALMessage("consensus", b"m%d" % i))
    wal.write_end_height(1)
    wal.close()
    with open(path, "rb") as f:
        msgs = list(decode_records(f.read()))
    assert [m.data for m in msgs[:10]] == [b"m%d" % i for i in range(10)]
    assert msgs[10].kind == "end_height"
    # every write_sync returned only after a covering fsync
    assert wal.fsync_count >= 1


def test_group_wal_coalesces_concurrent_fsyncs(tmp_path):
    wal = GroupCommitWAL(
        str(tmp_path / "wal"), flush_interval=0.05
    )
    n = 8
    start = threading.Barrier(n)

    def writer(i):
        start.wait()
        wal.write_sync(WALMessage("consensus", b"c%d" % i))

    threads = [
        threading.Thread(target=writer, args=(i,)) for i in range(n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    coalesced_fsyncs = wal.fsync_count
    wal.close()
    # 8 concurrent write_syncs share the flush thread's fsync(s):
    # strictly fewer syncs than writers (the serial path pays one each)
    assert 1 <= coalesced_fsyncs < n
    with open(str(tmp_path / "wal"), "rb") as f:
        assert len(list(decode_records(f.read()))) == n


def test_group_wal_abarrier(tmp_path):
    wal = GroupCommitWAL(str(tmp_path / "wal"), flush_interval=0.001)

    async def run():
        wal.write(WALMessage("consensus", b"x"))
        await wal.abarrier()
        # covered: a reopened reader sees the record
        with open(str(tmp_path / "wal"), "rb") as f:
            return list(decode_records(f.read()))

    msgs = asyncio.run(run())
    wal.close()
    assert len(msgs) == 1 and msgs[0].data == b"x"


def test_group_wal_search_end_height(tmp_path):
    wal = GroupCommitWAL(str(tmp_path / "wal"), flush_interval=0.0)
    wal.write_sync(WALMessage("consensus", b"h1"))
    wal.write_end_height(1)
    wal.write_sync(WALMessage("consensus", b"h2-partial"))
    wal.barrier()
    after = wal.search_for_end_height(1)
    wal.close()
    assert [m.data for m in after] == [b"h2-partial"]


# --- write-behind block store ----------------------------------------------


def _mini_chain(n):
    """n tiny consecutive blocks + part sets + seen commits."""
    vs, pvs = make_validators(1)
    genesis = make_genesis(vs)
    out = []

    async def run():
        app = KVStoreApplication()
        l2 = MockL2Node()
        state_store = StateStore(MemKV())
        state = State.from_genesis(genesis)
        state_store.bootstrap(state)
        bs = BlockStore(MemKV())
        ex = BlockExecutor(state_store, bs, LocalClient(app), l2)
        cs = ConsensusState(
            ConsensusConfig.test_config(), state, ex, bs, l2,
            priv_validator=pvs[0], wal=NilWAL(),
        )
        await cs.start()
        await cs.wait_for_height(n, timeout=30)
        await cs.stop()
        for h in range(1, n + 1):
            block = bs.load_block(h)
            out.append(
                (block, block.make_part_set(), bs.load_seen_commit(h))
            )

    asyncio.run(run())
    return out


def test_write_behind_store_overlay_and_durability():
    chain = _mini_chain(3)
    kv = MemKV()
    store = WriteBehindBlockStore(kv, max_inflight=2)
    for block, parts, seen in chain:
        store.save_block(block, parts, seen)
        h = block.header.height
        # the logical view serves the pending save immediately
        assert store.height == h
        assert store.load_block(h).hash() == block.hash()
        assert store.load_seen_commit(h) is not None
        assert store.load_block_meta(h).block_id.hash == block.hash()
    store.wait_durable()
    assert store.durable_height == 3
    assert store.save_queue_depth == 0
    store.stop()
    # a cold store over the same KV sees the full durable chain
    reopened = BlockStore(kv)
    assert reopened.height == 3
    for block, _, _ in chain:
        assert (
            reopened.load_block(block.header.height).hash() == block.hash()
        )


def test_write_behind_store_rejects_gap():
    chain = _mini_chain(2)
    store = WriteBehindBlockStore(MemKV())
    store.save_block(*chain[0])
    with pytest.raises(ValueError):
        store.save_block(*chain[0])  # height 1 again while at 1
    store.stop()


def test_write_behind_store_durable_range_trails_enqueue():
    """The on-disk base/height record only ever covers fully-persisted
    heights: a crash with saves queued replays like crash-before-save."""
    chain = _mini_chain(2)
    kv = FreezableKV()
    store = WriteBehindBlockStore(kv, max_inflight=4)
    store.save_block(*chain[0])
    store.wait_durable()
    kv.freeze()  # queue drains into dropped writes from here on
    store.save_block(*chain[1])
    store.wait_durable()
    store.stop()
    reopened = BlockStore(kv.inner)
    assert reopened.height == 1  # height 2 never became durable
    assert reopened.load_block(2) is None


# --- pipelined finalize equivalence ----------------------------------------


def _run_chain(tmp_path, name, pipelined, heights):
    vs, pvs = make_validators(1)
    genesis = make_genesis(vs)

    async def run():
        cs, app, l2, bs, ss, ex = _build_node(
            genesis, pvs[0], str(tmp_path / name), pipelined=pipelined
        )
        await cs.start()
        await cs.wait_for_height(heights, timeout=60)
        await cs.stop()
        bs.stop()
        cs.wal.close()
        return cs, app, bs

    return asyncio.run(run())


def test_pipelined_chain_matches_serial_app_hash(tmp_path):
    """Same genesis, same deterministic L2 txs: the pipelined node must
    land on the identical app hash and results as the serial path."""
    heights = 4
    cs_s, app_s, bs_s = _run_chain(tmp_path, "wal-serial", False, heights)
    cs_p, app_p, bs_p = _run_chain(tmp_path, "wal-piped", True, heights)
    assert cs_p.state.last_block_height >= heights
    assert cs_p._applied_height >= heights
    s, p = cs_s.state, cs_p.state
    assert p.app_hash == s.app_hash
    assert p.last_results_hash == s.last_results_hash
    assert p.validators.hash() == s.validators.hash()
    # the pipeline actually ran (not silently degraded to serial)
    assert cs_p.pipeline.applied_heights >= heights
    assert cs_p.pipeline.error is None
    # blocks durable and identical content-wise (headers differ by time)
    for h in range(1, heights + 1):
        assert bs_p.load_block(h).data.txs == bs_s.load_block(h).data.txs


def test_pipeline_wait_span_and_depth_gauge(tmp_path):
    """The app-hash future is awaited through the instrumented barrier:
    depth gauge returns to 0 and the wait histogram saw samples."""
    from tendermint_tpu.libs.metrics import ConsensusMetrics, Registry
    from tendermint_tpu import obs

    vs, pvs = make_validators(1)
    genesis = make_genesis(vs)
    metrics = ConsensusMetrics(Registry("pipetest"))
    tracer = obs.Tracer(enabled=True, ring_size=4096)

    async def run():
        cs, app, l2, bs, ss, ex = _build_node(
            genesis, pvs[0], str(tmp_path / "wal"), pipelined=True,
            tracer=tracer, metrics=metrics,
        )
        cs.metrics = metrics
        cs.tracer = tracer
        await cs.start()
        await cs.wait_for_height(3, timeout=60)
        await cs.stop()
        bs.stop()
        cs.wal.close()

    asyncio.run(run())
    assert metrics.commit_pipeline_depth.value() == 0
    names = {r.name for r in tracer.records()}
    assert "wal.group_fsync" in names
    assert "store.save_block_async" in names


def test_pipeline_wait_records_span_and_histogram():
    """wait_applied under a genuinely in-flight apply: the barrier
    records the commit.pipeline_wait span + histogram sample, and the
    depth gauge tracks the in-flight task."""
    from tendermint_tpu.libs.metrics import ConsensusMetrics, Registry
    from tendermint_tpu import obs

    metrics = ConsensusMetrics(Registry("pipewait"))
    tracer = obs.Tracer(enabled=True, ring_size=128)
    pipe = CommitPipeline(metrics=metrics, tracer=tracer)

    async def run():
        gate = asyncio.Event()

        async def slow_apply():
            assert metrics.commit_pipeline_depth.value() == 1
            await gate.wait()
            return "applied-state"

        pipe.begin(7, slow_apply)
        assert pipe.inflight_height == 7
        asyncio.get_running_loop().call_later(0.02, gate.set)
        out = await pipe.wait_applied()
        assert out == "applied-state"
        # resolved barrier: second wait is a no-op returning None
        assert await pipe.wait_applied() is None

    asyncio.run(run())
    assert metrics.commit_pipeline_depth.value() == 0
    spans = [r for r in tracer.records() if r.name == "commit.pipeline_wait"]
    assert len(spans) == 1
    hist = metrics.commit_pipeline_wait_seconds
    assert sum(s.total for s in hist._series.values()) == 1


def test_pipeline_failed_apply_wedges():
    """A failed background finalization latches: every later barrier
    raises instead of silently running on a half-applied state."""
    pipe = CommitPipeline()

    async def run():
        async def bad_apply():
            raise RuntimeError("apply exploded")

        task = pipe.begin(3, bad_apply)
        with pytest.raises(RuntimeError):
            await pipe.wait_applied()
        assert pipe.error is not None
        with pytest.raises(RuntimeError):
            await pipe.wait_applied()
        await pipe.drain()

    asyncio.run(run())


# --- crash-recovery at each pipeline stage boundary (chaos) -----------------


def _crash_and_recover(tmp_path, freeze_block_kv, freeze_state_kv,
                       batches_only=False, reuse_app=False):
    """Run a pipelined node to height 2 durably, freeze the chosen KVs
    (writes after this are 'lost'), run one more height, crash (abandon
    without clean stop), then restart from the durable image + real WAL
    and converge to height 4. Returns (restarted cs, app)."""
    vs, pvs = make_validators(1)
    genesis = make_genesis(vs)
    block_kv = FreezableKV()
    state_kv = FreezableKV(freeze_batches_only=batches_only)
    app = KVStoreApplication()
    wal_path = str(tmp_path / "wal")

    async def first_run():
        cs, _, l2, bs, ss, ex = _build_node(
            genesis, pvs[0], wal_path, pipelined=True,
            app=app, block_kv=block_kv, state_kv=state_kv,
        )
        await _handshake(cs, genesis, ex, ss, bs)
        await cs.start()
        await cs.wait_for_height(2, timeout=60)
        bs.wait_durable()
        if freeze_block_kv:
            block_kv.freeze()
        if freeze_state_kv:
            state_kv.freeze()
        await cs.wait_for_height(3, timeout=60)
        # crash: stop the loops but leave stores/WAL exactly as-is
        # (the frozen KVs already dropped the 'in-flight' writes)
        await cs.stop()
        bs.stop()
        cs.wal.close()

    asyncio.run(first_run())

    async def second_run():
        cs, app2, l2, bs, ss, ex = _build_node(
            genesis, pvs[0], wal_path, pipelined=True,
            app=app if reuse_app else None,
            block_kv=block_kv.inner, state_kv=state_kv.inner,
        )
        await _handshake(cs, genesis, ex, ss, bs)
        await cs.start()
        await cs.wait_for_height(4, timeout=60)
        await cs.stop()
        bs.stop()
        cs.wal.close()
        return cs, app2

    return asyncio.run(second_run())


def _serial_reference(tmp_path, heights=4):
    cs, app, bs = _run_chain(tmp_path, "wal-ref", False, heights)
    return cs.state


@pytest.mark.chaos
def test_crash_after_wal_end_height_block_save_lost(tmp_path):
    """Stage boundary 1: WAL end-height durable, block save + apply
    lost. Replay must re-drive the height to the serial outcome."""
    cs, app = _crash_and_recover(
        tmp_path, freeze_block_kv=True, freeze_state_kv=True
    )
    ref = _serial_reference(tmp_path)
    assert cs.state.last_block_height >= 4
    assert cs.state.app_hash == ref.app_hash
    assert cs.state.last_results_hash == ref.last_results_hash


@pytest.mark.chaos
def test_crash_after_block_save_apply_lost(tmp_path):
    """Stage boundary 2: WAL end-height + block durable, apply/state
    save lost. Handshake applies the final stored block."""
    cs, app = _crash_and_recover(
        tmp_path, freeze_block_kv=False, freeze_state_kv=True
    )
    ref = _serial_reference(tmp_path)
    assert cs.state.last_block_height >= 4
    assert cs.state.app_hash == ref.app_hash
    assert cs.state.last_results_hash == ref.last_results_hash


@pytest.mark.chaos
def test_crash_mid_apply_app_committed_state_lost(tmp_path):
    """Stage boundary 3 (mid-apply): the app committed the block but
    the state record was lost. The handshake must rebuild state from
    the saved ABCI responses WITHOUT double-executing the block (the
    surviving app's hash must match the serial chain's)."""
    cs, app = _crash_and_recover(
        tmp_path,
        freeze_block_kv=False,
        freeze_state_kv=True,
        batches_only=True,  # responses (set) land, state batch lost
        reuse_app=True,  # the app process survived the crash
    )
    ref = _serial_reference(tmp_path)
    assert cs.state.last_block_height >= 4
    assert cs.state.app_hash == ref.app_hash
    assert cs.state.last_results_hash == ref.last_results_hash


@pytest.mark.chaos
def test_pipelined_restart_clean(tmp_path):
    """No crash window at all: clean stop + restart through handshake
    and WAL catchup, pipelined both times."""
    vs, pvs = make_validators(1)
    genesis = make_genesis(vs)
    block_kv, state_kv = MemKV(), MemKV()
    app = KVStoreApplication()
    wal_path = str(tmp_path / "wal")

    async def run_to(height):
        cs, _, l2, bs, ss, ex = _build_node(
            genesis, pvs[0], wal_path, pipelined=True,
            app=app, block_kv=block_kv, state_kv=state_kv,
        )
        await _handshake(cs, genesis, ex, ss, bs)
        await cs.start()
        await cs.wait_for_height(height, timeout=60)
        await cs.stop()
        bs.stop()
        cs.wal.close()
        return cs

    cs1 = asyncio.run(run_to(2))
    assert cs1.state.last_block_height >= 2
    cs2 = asyncio.run(run_to(4))
    assert cs2.state.last_block_height >= 4
    assert cs2.pipeline.error is None


def test_group_wal_fsync_failure_latches_not_fake_durable(tmp_path):
    """A failing fsync must RAISE at the barrier (and on later writes),
    never report the records durable (double-sign risk on replay)."""
    wal = GroupCommitWAL(str(tmp_path / "wal"), flush_interval=0.0)

    def boom():
        raise OSError("disk on fire")

    wal._group.sync = boom
    with pytest.raises(RuntimeError):
        wal.write_sync(WALMessage("consensus", b"x"))
    with pytest.raises(RuntimeError):
        wal.write(WALMessage("consensus", b"y"))

    async def arun():
        with pytest.raises(RuntimeError):
            # uncovered records + latched error -> raise, not hang
            await wal.abarrier()

    asyncio.run(arun())
    wal._closed = True  # skip the drain (sync is broken)
    wal._flusher.join(timeout=2)


def test_write_behind_store_never_persists_past_a_failed_save():
    """A failed save latches AND stops persistence: later queued heights
    must not advance the durable range over the hole (handshake replay
    would hit 'missing block' forever)."""
    chain = _mini_chain(3)
    kv = MemKV()
    store = WriteBehindBlockStore(kv, max_inflight=4)
    store.save_block(*chain[0])
    store.wait_durable()
    real_batch = kv.write_batch
    calls = {"n": 0}

    def flaky(sets, deletes):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("transient kv failure")
        real_batch(sets, deletes)

    kv.write_batch = flaky
    store.save_block(*chain[1])  # fails in the worker, latches
    store.save_block(*chain[2])  # must be DISCARDED, not persisted
    with pytest.raises(RuntimeError):
        store.wait_durable()
    with pytest.raises(RuntimeError):
        store.save_block(*chain[2])  # latched error rejects new saves
    store.stop()
    reopened = BlockStore(kv)
    assert reopened.height == 1  # range never advanced over the hole
    assert reopened.load_block(2) is None
    assert reopened.load_block(3) is None


def test_responses_roundtrip_validator_updates(tmp_path):
    """The saved-responses crash-recovery path must rebuild the same
    next validator set: val/param updates ride the blob."""
    from tendermint_tpu.state.execution import ABCIResponses

    r = ABCIResponses()
    r.val_updates = [("ed25519", b"\x01" * 32, 7)]
    r.param_updates = {"block": {"max_bytes": 123}}
    back = ABCIResponses.decode(r.encode())
    assert back.val_updates == [("ed25519", b"\x01" * 32, 7)]
    assert back.param_updates == {"block": {"max_bytes": 123}}
    assert back.end_block.consensus_param_updates == r.param_updates


@pytest.mark.chaos
def test_crash_mid_apply_with_validator_update(tmp_path):
    """Finding-3 regression: crash in the 'app committed, state lost'
    window at a height that carries an L2 validator update — recovery
    must apply the update (validators present at the right height)."""
    from tendermint_tpu.crypto import ed25519 as hosted

    vs, pvs = make_validators(1)
    genesis = make_genesis(vs)
    block_kv = FreezableKV()
    state_kv = FreezableKV(freeze_batches_only=True)
    app = KVStoreApplication()
    wal_path = str(tmp_path / "wal")
    new_key = hosted.PrivKey.from_secret(b"joiner").public_key()
    l2 = MockL2Node()
    # the L2 injects a validator update at height 3 (the crash height)
    l2.validator_updates[3] = [("ed25519", new_key.data, 5)]

    async def first_run():
        cs, _, _, bs, ss, ex = _build_node(
            genesis, pvs[0], wal_path, pipelined=True,
            app=app, l2=l2, block_kv=block_kv, state_kv=state_kv,
        )
        await _handshake(cs, genesis, ex, ss, bs)
        await cs.start()
        await cs.wait_for_height(2, timeout=60)
        bs.wait_durable()
        state_kv.freeze()  # state batches lost from here (responses land)
        await cs.wait_for_height(3, timeout=60)
        await cs.stop()
        bs.stop()
        cs.wal.close()

    asyncio.run(first_run())

    async def second_run():
        cs, _, _, bs, ss, ex = _build_node(
            genesis, pvs[0], wal_path, pipelined=True,
            app=app, l2=l2,
            block_kv=block_kv.inner, state_kv=state_kv.inner,
        )
        await _handshake(cs, genesis, ex, ss, bs)
        return cs

    cs = asyncio.run(second_run())
    assert cs.state.last_block_height >= 3
    # the update at height 3 lands in next_validators (effective H+2)
    addrs = {v.address for v in cs.state.next_validators.validators}
    assert new_key.address() in addrs


def test_wal_write_failure_drops_batch_keeps_routine(tmp_path):
    """Receive-routine isolation: a WAL failure mid-run must not kill
    consensus — un-logged internal messages are dropped, the loop
    survives, and (after the WAL heals) the chain keeps committing."""
    vs, pvs = make_validators(1)
    genesis = make_genesis(vs)

    async def run():
        cs, app, l2, bs, ss, ex = _build_node(
            genesis, pvs[0], str(tmp_path / "wal"), pipelined=True
        )
        await cs.start()
        await cs.wait_for_height(1, timeout=60)
        # poison ONE barrier round, then heal
        real = cs.wal.abarrier
        state = {"n": 0}

        async def flaky():
            if state["n"] == 0:
                state["n"] += 1
                raise RuntimeError("transient barrier failure")
            await real()

        cs.wal.abarrier = flaky
        await cs.wait_for_height(3, timeout=60)
        assert cs._receive_task is not None and not cs._receive_task.done()
        await cs.stop()
        bs.stop()
        cs.wal.close()
        return state["n"]

    assert asyncio.run(run()) == 1


def test_prune_waits_for_saves_below_boundary():
    """Pruning must not delete heights whose write-behind save is still
    queued — the late save would resurrect pruned blocks and corrupt
    the on-disk range record."""
    chain = _mini_chain(3)
    kv = MemKV()
    gate = threading.Event()
    real_batch = kv.write_batch
    stalled = {"first": True}

    def gated(sets, deletes):
        if stalled["first"]:
            stalled["first"] = False
            gate.wait(5)  # stall height 1's save until released
        real_batch(sets, deletes)

    kv.write_batch = gated
    store = WriteBehindBlockStore(kv, max_inflight=4)
    for entry in chain:
        store.save_block(*entry)
    done = {"pruned": None}

    def prune():
        done["pruned"] = store.prune_blocks(3)  # retain 3: delete 1, 2

    t = threading.Thread(target=prune)
    t.start()
    time.sleep(0.1)
    assert t.is_alive()  # blocked: heights 1-2 not durable yet
    gate.set()
    t.join(10)
    assert done["pruned"] == 2
    store.wait_durable()
    store.stop()
    reopened = BlockStore(kv)
    assert reopened.base == 3 and reopened.height == 3
    assert reopened.load_block(3) is not None
    assert reopened.load_block(1) is None and reopened.load_block(2) is None


def test_apply_waits_block_durability_before_state_save():
    """Durable state must never outrun the durable block: apply_block
    barriers on the write-behind store before persisting state."""
    vs, pvs = make_validators(1)
    genesis = make_genesis(vs)
    events = []

    class SpyBlockStore(BlockStore):
        def wait_durable(self, height=None, timeout=None):
            events.append(("wait_durable", height))

    class SpyStateStore(StateStore):
        def save(self, state):
            events.append(("state_save", state.last_block_height))
            super().save(state)

    async def run():
        app = KVStoreApplication()
        l2 = MockL2Node()
        state_store = SpyStateStore(MemKV())
        state = State.from_genesis(genesis)
        state_store.bootstrap(state)
        bs = SpyBlockStore(MemKV())
        ex = BlockExecutor(state_store, bs, LocalClient(app), l2)
        cs = ConsensusState(
            ConsensusConfig.test_config(), state, ex, bs, l2,
            priv_validator=pvs[0], wal=NilWAL(),
        )
        await cs.start()
        await cs.wait_for_height(1, timeout=30)
        await cs.stop()

    asyncio.run(run())
    # the block-durability barrier for height 1 precedes its state save
    assert ("wait_durable", 1) in events
    assert events.index(("wait_durable", 1)) < events.index(
        ("state_save", 1)
    )
