"""Consensus state machine e2e: the reconstruction of the test net the
fork deleted (consensus/common_test.go, SURVEY.md §4.1) — in-proc
validators wired through broadcast hooks, no p2p."""

import asyncio

import pytest

from tendermint_tpu.abci.client import LocalClient
from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.consensus.state_machine import (
    ConsensusConfig,
    ConsensusState,
)
from tendermint_tpu.consensus.messages import (
    BlockPartMessage,
    ProposalMessage,
    VoteMessage,
)
from tendermint_tpu.l2node.mock import MockL2Node
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.state import State
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.store.block_store import BlockStore
from tendermint_tpu.store.kv import MemKV
from tendermint_tpu.types.priv_validator import MockPV

from .helpers import CHAIN_ID, make_genesis, make_validators


def make_node(
    vs,
    pv,
    genesis,
    l2=None,
    config=None,
    upgrade_height=0,
    on_upgrade=None,
    bls_signer=None,
    metrics=None,
    tracer=None,
    verifier=None,
    health=None,
    wal=None,
    commit_pipeline=None,
):
    l2 = l2 or MockL2Node()
    app = KVStoreApplication()
    state = State.from_genesis(genesis)
    state_store = StateStore(MemKV())
    state_store.bootstrap(state)
    block_store = BlockStore(MemKV())
    executor = BlockExecutor(state_store, block_store, LocalClient(app), l2)
    cs = ConsensusState(
        config or ConsensusConfig.test_config(),
        state,
        executor,
        block_store,
        l2,
        priv_validator=pv,
        upgrade_height=upgrade_height,
        on_upgrade=on_upgrade,
        bls_signer=bls_signer,
        metrics=metrics,
        tracer=tracer,
        verifier=verifier,
        health=health,
        wal=wal,
        commit_pipeline=commit_pipeline,
    )
    return cs, app, l2, block_store, state_store


def wire_net(nodes):
    """Full-mesh gossip of self-produced messages (in-proc harness)."""
    for i, n in enumerate(nodes):
        def hook(msg, i=i):
            for j, other in enumerate(nodes):
                if j != i:
                    other.peer_msg_queue.put_nowait((msg, f"node{i}"))

        n.broadcast_hook = hook


def test_single_validator_chain():
    vs, pvs = make_validators(1)
    genesis = make_genesis(vs)

    async def run():
        cs, app, l2, bs, ss = make_node(vs, pvs[0], genesis)
        await cs.start()
        await cs.wait_for_height(3, timeout=20)
        await cs.stop()
        assert cs.state.last_block_height >= 3
        assert bs.height >= 3
        assert len(l2.delivered) >= 3
        # blocks chain correctly
        b2 = bs.load_block(2)
        b3 = bs.load_block(3)
        assert b3.header.last_block_id.hash == b2.hash()
        assert b3.last_commit is not None
        # commits verify against the validator set
        vs_now = ss.load_validators(2)
        vs_now.verify_commit_light(
            CHAIN_ID, b3.header.last_block_id, 2, b3.last_commit
        )

    asyncio.run(run())


def test_four_validator_net():
    vs, pvs = make_validators(4)
    genesis = make_genesis(vs)

    async def run():
        nodes = [make_node(vs, pv, genesis) for pv in pvs]
        css = [n[0] for n in nodes]
        wire_net(css)
        for cs in css:
            await cs.start()
        await asyncio.gather(*(cs.wait_for_height(3, timeout=30) for cs in css))
        for cs in css:
            await cs.stop()
        hashes = {cs.block_store.load_block(3).hash() for cs in css}
        assert len(hashes) == 1, "nodes disagree on block 3"
        for cs in css:
            assert cs.state.last_block_height >= 3

    asyncio.run(run())


def test_net_survives_one_faulty_node():
    """3 of 4 validators are enough for progress (one node never starts)."""
    vs, pvs = make_validators(4)
    genesis = make_genesis(vs)

    async def run():
        nodes = [make_node(vs, pv, genesis) for pv in pvs]
        css = [n[0] for n in nodes]
        wire_net(css)
        for cs in css[:3]:  # node 3 stays down
            await cs.start()
        await asyncio.gather(
            *(cs.wait_for_height(2, timeout=40) for cs in css[:3])
        )
        for cs in css[:3]:
            await cs.stop()
        for cs in css[:3]:
            assert cs.state.last_block_height >= 2
        # commits at height 2 include an absent signature for node 3
        b = css[0].block_store.load_block(3)
        if b is None:
            commit = css[0].block_store.load_seen_commit(2)
        else:
            commit = b.last_commit
        assert any(cs_.is_absent() for cs_ in commit.signatures)

    asyncio.run(run())


def _bls_setup(pvs):
    """Real BLS keys per validator + registry-backed verifier."""
    from tendermint_tpu.crypto import bls_signatures as bls

    registry = bls.BLSKeyRegistry()
    signers = []
    for i, pv in enumerate(pvs):
        priv = 7919 + i  # deterministic test keys
        pub = bls.pubkey_from_priv(priv)
        registry.register(pv.get_pub_key().data, pub)
        signers.append(bls.signer_for(priv))
    return registry, signers


def test_batch_point_bls_flow():
    """Every 2nd block is a batch point: header carries the batch hash,
    precommits carry REAL BLS12-381 signatures over it, the L2 node
    verifies each one (2-pairing check) and receives CommitBatch with the
    aggregated BLS data (morph capability, SURVEY.md delta 2)."""
    from tendermint_tpu.crypto import bls_signatures as bls

    vs, pvs = make_validators(1)
    genesis = make_genesis(vs)
    registry, signers = _bls_setup(pvs)
    l2 = MockL2Node(batch_blocks_interval=2, bls_verifier=registry.verifier())

    async def run():
        cs, app, l2_, bs, ss = make_node(
            vs, pvs[0], genesis, l2=l2, bls_signer=signers[0]
        )
        await cs.start()
        await cs.wait_for_height(4, timeout=30)
        await cs.stop()
        batch_blocks = [
            bs.load_block(h)
            for h in range(1, 5)
            if bs.load_block(h).header.batch_hash
        ]
        assert batch_blocks, "no batch points produced"
        assert l2.committed_batches, "no batches committed to L2"
        batch_hash, bls_datas = l2.committed_batches[0]
        assert bls_datas, "no BLS data in committed batch"
        assert l2.bls_appended  # AppendBlsData was called per precommit
        # the batch-point block's data carries the sealed batch header
        assert batch_blocks[0].data.l2_batch_header

        # the committed signatures are genuine: they verify against the
        # registered keys over the batch hash, and a flipped byte fails
        pub = bls.public_key_from_bytes(
            bls.public_key_to_bytes(bls.pubkey_from_priv(7919)), True
        )
        sig_bytes = bls_datas[0].signature
        sig = bls.g1_from_bytes(sig_bytes)
        assert bls.verify(sig, batch_hash, pub)
        bad = bytearray(sig_bytes)
        bad[7] ^= 1
        assert not registry.verifier()(
            pvs[0].get_pub_key().data, batch_hash, bytes(bad)
        ), "flipped BLS byte must not verify"

    asyncio.run(run())


def test_batch_point_rejects_invalid_bls():
    """A vote whose BLS signature doesn't verify is rejected at the batch
    point (state_machine addVote BLS path; ref consensus/state.go:2362-2379)."""
    from tendermint_tpu.crypto import bls_signatures as bls

    vs, pvs = make_validators(1)
    genesis = make_genesis(pvs and vs)
    registry, signers = _bls_setup(pvs)
    l2 = MockL2Node(batch_blocks_interval=1, bls_verifier=registry.verifier())

    async def run():
        # signer produces garbage BLS bytes -> the node's own precommit is
        # rejected at the batch point and the chain cannot commit height 1
        cs, app, l2_, bs, ss = make_node(
            vs,
            pvs[0],
            genesis,
            l2=l2,
            bls_signer=lambda bh: b"\x01" * 96,
        )
        await cs.start()
        # height 1 is never a batch point (reference state.go:1350-1352),
        # so it commits; height 2 is the first batch point and must stall
        # on the garbage BLS signature
        await cs.wait_for_height(1, timeout=10)
        with pytest.raises(asyncio.TimeoutError):
            await cs.wait_for_height(2, timeout=1.5)
        await cs.stop()
        assert not l2.committed_batches

    asyncio.run(run())


def test_upgrade_switch_stops_bft():
    vs, pvs = make_validators(1)
    genesis = make_genesis(vs)
    upgraded = []

    async def run():
        cs, *_ = make_node(
            vs,
            pvs[0],
            genesis,
            upgrade_height=2,
            on_upgrade=lambda st: upgraded.append(st.last_block_height),
        )
        await cs.start()
        await cs.wait_for_height(2, timeout=20)
        await asyncio.sleep(0.5)  # give it room to (wrongly) keep going
        await cs.stop()
        assert upgraded == [2]
        assert cs.state.last_block_height == 2  # BFT stopped at upgrade

    asyncio.run(run())


def test_batch_start_survives_restart():
    """get_batch_start rebuilds the batch cache from the block store after
    a restart (VERDICT round-1 item: 'batch-point state won't survive
    restarts mid-batch'; reference consensus/batch.go:67-99)."""
    from tendermint_tpu.consensus.batch import BatchCache, get_batch_start
    from tendermint_tpu.types.params import ConsensusParams

    vs, pvs = make_validators(1)
    genesis = make_genesis(vs)
    # interval-based batching via on-chain params: every 3 blocks
    genesis.consensus_params.batch.blocks_interval = 3
    registry, signers = _bls_setup(pvs)
    l2 = MockL2Node(bls_verifier=registry.verifier())

    async def run():
        cs, app, _, bs, ss = make_node(
            vs, pvs[0], genesis, l2=l2, bls_signer=signers[0]
        )
        await cs.start()
        await cs.wait_for_height(7, timeout=30)
        await cs.stop()
        return cs, bs

    cs, bs = asyncio.run(run())
    batch_points = [
        h for h in range(1, 8) if bs.load_block(h).is_batch_point()
    ]
    assert batch_points, "no interval batch points sealed"
    assert 1 not in batch_points  # height 1 never seals (reference :1350)

    # a FRESH cache (simulated restart) must find the same batch start by
    # walking the block store
    fresh = BatchCache()
    start_h, _ = get_batch_start(
        fresh, 8, 1, genesis.genesis_time_ns, bs
    )
    assert start_h == max(batch_points)
    assert fresh.blocks_since_last_batch_point[0].header.height == start_h


def test_height_vote_set_grants_catchup_rounds():
    """A vote for a round beyond current+1 must be accepted on first
    arrival (up to 2 catchup rounds per peer) — the reference's
    peerCatchupRounds (height_vote_set.go addVote). This is the gossip
    recovery path: a restarted node at round 0 receives the commit's
    round-2 precommits from survivors; rejecting them pending a maj23
    claim deadlocks catchup (VERDICT r2 weak #8)."""
    import pytest as _pytest

    from tendermint_tpu.consensus.height_vote_set import HeightVoteSet
    from tendermint_tpu.types.block_id import BlockID, PartSetHeader
    from tendermint_tpu.types.vote import Vote, VoteType

    vs, pvs = make_validators(4)
    hvs = HeightVoteSet("test-chain", 5, vs)
    bid = BlockID(b"\x11" * 32, PartSetHeader(1, b"\x22" * 32))

    def make_vote(i, round_):
        v = Vote(
            type=VoteType.PRECOMMIT,
            height=5,
            round=round_,
            block_id=bid,
            timestamp_ns=1000 + i,
            validator_address=pvs[i].get_pub_key().address(),
            validator_index=i,
        )
        pvs[i].sign_vote("test-chain", v)
        return v

    # round 2 while hvs.round == 0: granted as peer catchup round
    assert hvs.add_vote(make_vote(0, 2), peer_id="peerA", verified=True)
    assert hvs.add_vote(make_vote(1, 2), peer_id="peerA", verified=True)
    # a second catchup round from the same peer: still allowed (max 2)
    assert hvs.add_vote(make_vote(0, 4), peer_id="peerA", verified=True)
    # a third distinct catchup round from the same peer: rejected
    with _pytest.raises(ValueError):
        hvs.add_vote(make_vote(0, 6), peer_id="peerA", verified=True)
    # 2/3 at the catchup round is visible for the commit path
    assert hvs.add_vote(make_vote(2, 2), peer_id="peerB", verified=True)
    _, ok = hvs.precommits(2).two_thirds_majority()
    assert ok
