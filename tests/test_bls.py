"""BLS12-381 curve library + signature scheme tests.

No external vectors are reachable in this environment, so correctness rests
on algebraic invariants (bilinearity, group orders, subgroup membership of
hash outputs) plus scheme-level roundtrips mirroring the reference's test
shape (/root/reference/blssignatures/bls_signatures_test.go: sign/verify,
aggregate same/different messages, PoP, serialization roundtrips).
"""

import random

import pytest

from tendermint_tpu.crypto import bls12_381 as c
from tendermint_tpu.crypto import bls_signatures as bls
from tendermint_tpu.crypto.keccak import keccak256


# --- keccak ---------------------------------------------------------------


def test_keccak_known_vectors():
    # ERC-20 selectors/topics — globally pinned constants
    assert keccak256(b"transfer(address,uint256)")[:4].hex() == "a9059cbb"
    assert keccak256(b"balanceOf(address)")[:4].hex() == "70a08231"
    assert (
        keccak256(b"Transfer(address,address,uint256)").hex()
        == "ddf252ad1be2c89b69c2b068fc378daa952ba7f163c4a11628f55a4df523b3ef"
    )


def test_keccak_multiblock():
    # > one rate block (136 bytes)
    out = keccak256(b"a" * 300)
    assert len(out) == 32
    assert out != keccak256(b"a" * 299)


# --- curve layer ----------------------------------------------------------


def test_generators_have_order_r():
    assert c.g1_on_curve(c.G1_GEN)
    assert c.g2_on_curve(c.G2_GEN)
    assert c.g1_is_inf(c.g1_mul_raw(c.G1_GEN, c.R))
    assert c.g2_is_inf(c.g2_mul_raw(c.G2_GEN, c.R))


def test_g1_group_law():
    p2 = c.g1_add(c.G1_GEN, c.G1_GEN)
    assert c.g1_eq(p2, c.g1_double(c.G1_GEN))
    assert c.g1_eq(c.g1_mul(c.G1_GEN, 5), c.g1_add(p2, c.g1_add(p2, c.G1_GEN)))
    assert c.g1_is_inf(c.g1_add(c.G1_GEN, c.g1_neg(c.G1_GEN)))


def test_f12_inverse_and_frobenius():
    random.seed(7)
    a = tuple((random.randrange(c.P), random.randrange(c.P)) for _ in range(6))
    assert c.f12_eq(c.f12_mul(a, c.f12_inv(a)), c.F12_ONE)
    x = a
    for _ in range(12):
        x = c.f12_frob(x)
    assert c.f12_eq(x, a)


def test_pairing_bilinear():
    e1 = c.pairing(c.G1_GEN, c.G2_GEN)
    assert not c.f12_eq(e1, c.F12_ONE)

    def f12_pow(x, e):
        r = c.F12_ONE
        while e:
            if e & 1:
                r = c.f12_mul(r, x)
            x = c.f12_sqr(x)
            e >>= 1
        return r

    a, b = 31337, 271828
    eab = c.pairing(c.g1_mul(c.G1_GEN, a), c.g2_mul(c.G2_GEN, b))
    assert c.f12_eq(eab, f12_pow(e1, a * b))
    assert c.f12_eq(f12_pow(e1, c.R), c.F12_ONE)


def test_hash_to_g1_subgroup():
    for m in (b"", b"batch-hash", b"x" * 100):
        p = bls.hash_to_g1(m)
        assert c.g1_on_curve(p)
        assert c.g1_in_subgroup(p)
    # domain separation: key-validation hash differs
    assert not c.g1_eq(bls.hash_to_g1(b"m"), bls.hash_to_g1(b"m", True))


# --- scheme ---------------------------------------------------------------


@pytest.fixture(scope="module")
def keypair():
    priv = 0x1234567890ABCDEF_FEDCBA0987654321 % c.R
    return priv, bls.pubkey_from_priv(priv)


def test_sign_verify(keypair):
    priv, pub = keypair
    sig = bls.sign(priv, b"the batch hash")
    assert bls.verify(sig, b"the batch hash", pub)
    assert not bls.verify(sig, b"another message", pub)


def test_flipped_byte_rejected(keypair):
    """VERDICT round-1 item 3's 'done' criterion at the crypto layer."""
    priv, pub = keypair
    sig = bls.sign(priv, b"msg")
    raw = bytearray(bls.g1_to_bytes(sig))
    raw[5] ^= 1
    try:
        bad = bls.g1_from_bytes(bytes(raw))
    except bls.BLSError:
        return  # off-curve: rejected at decode — also a pass
    assert not bls.verify(bad, b"msg", pub)


def test_proof_of_possession(keypair):
    priv, pub = keypair
    assert pub.validity_proof is not None
    # a proof for a different key must not validate
    other = bls.pubkey_from_priv(99991)
    with pytest.raises(bls.BLSError):
        bls.new_public_key(pub.key, other.validity_proof)


def test_aggregate_same_message():
    privs = [11111 + i for i in range(4)]
    pubs = [bls.pubkey_from_priv(s) for s in privs]
    msg = b"common batch hash"
    agg = bls.aggregate_signatures([bls.sign(s, msg) for s in privs])
    assert bls.verify_aggregated_same_message(agg, msg, pubs)
    assert not bls.verify_aggregated_same_message(agg, b"other", pubs)


def test_aggregate_different_messages():
    privs = [22222 + i for i in range(3)]
    pubs = [bls.pubkey_from_priv(s) for s in privs]
    msgs = [b"m1", b"m2", b"m3"]
    agg = bls.aggregate_signatures(
        [bls.sign(s, m) for s, m in zip(privs, msgs)]
    )
    assert bls.verify_aggregated_different_messages(agg, msgs, pubs)
    assert not bls.verify_aggregated_different_messages(
        agg, [b"m1", b"m2", b"WRONG"], pubs
    )
    with pytest.raises(bls.BLSError):
        bls.verify_aggregated_different_messages(agg, msgs[:2], pubs)


def test_serialization_roundtrips(keypair):
    priv, pub = keypair
    sig = bls.sign(priv, b"ser")
    assert bls.g1_from_bytes(bls.g1_to_bytes(sig)) == c.g1_to_affine(sig) + (1,)
    b2 = bls.g2_to_bytes(pub.key)
    assert c.g2_eq(bls.g2_from_bytes(b2), pub.key)
    # proof-prefixed public key bytes
    pb = bls.public_key_to_bytes(pub)
    back = bls.public_key_from_bytes(pb, trusted_source=False)
    assert c.g2_eq(back.key, pub.key)
    # trusted form (no proof)
    tb = bls.public_key_to_bytes(pub.to_trusted())
    assert tb[0] == 0
    with pytest.raises(bls.BLSError):
        bls.public_key_from_bytes(tb, trusted_source=False)
    assert c.g2_eq(bls.public_key_from_bytes(tb, True).key, pub.key)
    # priv key bytes
    assert bls.priv_key_from_bytes(bls.priv_key_to_bytes(priv)) == priv


def test_infinity_encodings():
    assert bls.g1_to_bytes(c.G1_INF) == b"\x00" * 96
    assert c.g1_is_inf(bls.g1_from_bytes(b"\x00" * 96))
    assert bls.g2_to_bytes(c.G2_INF) == b"\x00" * 192


def test_non_subgroup_point_rejected():
    # find an on-curve G1 point NOT in the r-subgroup (cofactor > 1)
    x = 3
    while True:
        rhs = (x * x * x + 4) % c.P
        y = pow(rhs, (c.P + 1) // 4, c.P)
        if y * y % c.P == rhs:
            pt = (x, y, 1)
            if not c.g1_in_subgroup(pt):
                break
        x += 1
    raw = x.to_bytes(48, "big") + y.to_bytes(48, "big")
    with pytest.raises(bls.BLSError):
        bls.g1_from_bytes(raw)


def test_key_file_roundtrip(tmp_path):
    path = str(tmp_path / "bls_key.json")
    k = bls.load_or_gen_bls_key(path)
    k2 = bls.load_or_gen_bls_key(path)
    assert k.priv_key == k2.priv_key and k.pub_key == k2.pub_key
    priv = bls.priv_key_from_bytes(k.priv_key)
    pub = bls.public_key_from_bytes(k.pub_key, trusted_source=False)
    sig = bls.sign(priv, b"from file")
    assert bls.verify(sig, b"from file", pub)


def test_aggregate_many_signatures_one_verify():
    """BASELINE config 3's shape: many validators BLS-sign one batch
    hash; ONE aggregated signature + aggregated key verifies with 2
    pairings (reference AggregateSignatures/AggregatePublicKeys +
    VerifyAggregatedSameMessage, bls_signatures.go:129-149).

    16 distinct keys here (keygen/signing dominate test wall-time — pure
    host Fp math — so the count is kept small; the aggregation/verification
    cost is INDEPENDENT of the signer count — that independence is the
    property this test pins. The >64-signature device tree-reduction path
    is covered by tests/test_ops_bls_g1.py.)"""
    import time

    n = 16
    privs = [104729 + 7 * i for i in range(n)]
    pubs = [bls.pubkey_from_priv(p) for p in privs]
    msg = b"sealed-batch-hash"
    sigs = [bls.sign(p, msg) for p in privs]

    agg = bls.aggregate_signatures(sigs)
    t0 = time.perf_counter()
    assert bls.verify_aggregated_same_message(agg, msg, pubs)
    dt_agg = time.perf_counter() - t0

    # one flipped contribution breaks the aggregate
    bad_sigs = list(sigs)
    bad_sigs[9] = bls.sign(privs[9], b"different message")
    assert not bls.verify_aggregated_same_message(
        bls.aggregate_signatures(bad_sigs), msg, pubs
    )
    # aggregate missing one signer's key fails
    assert not bls.verify_aggregated_same_message(agg, msg, pubs[:-1])
    # the verify cost must not scale with n (2 pairings total): allow 3x
    # headroom over a single-signature verify
    t0 = time.perf_counter()
    assert bls.verify(sigs[0], msg, pubs[0])
    dt_one = time.perf_counter() - t0
    assert dt_agg < 3 * dt_one + 0.5



def test_verify_batch_same_message_verdicts():
    """Batched per-signature verdicts (the round-burst path the reactor's
    BLS micro-batcher uses): all-valid costs 2 pairings; invalid entries
    are isolated by bisection without condemning their neighbors."""
    n = 8
    privs = [7919 + 13 * i for i in range(n)]
    pubs = [bls.pubkey_from_priv(p) for p in privs]
    msg = b"round-batch-hash"
    sigs = [bls.sign(p, msg) for p in privs]

    assert bls.verify_batch_same_message(msg, pubs, sigs) == [True] * n

    # two bad entries (wrong message, wrong key) among good ones
    bad = list(sigs)
    bad[2] = bls.sign(privs[2], b"other message")
    bad[5] = bls.sign(privs[4], msg)
    got = bls.verify_batch_same_message(msg, pubs, bad)
    assert got == [i not in (2, 5) for i in range(n)]

    # empty + singleton edges
    assert bls.verify_batch_same_message(msg, [], []) == []
    assert bls.verify_batch_same_message(msg, [pubs[0]], [sigs[0]]) == [True]


def test_verify_batch_rejects_cancelling_pair():
    """Two colluding signers submit sig1+D and sig2-D: the UNWEIGHTED sum
    is unchanged (so a naive aggregate check would accept), but each
    signature is individually invalid. The random-linear-combination
    coefficients must catch both (bls_signatures._BATCH_COEFF_BITS)."""
    from tendermint_tpu.crypto import bls12_381 as c

    privs = [31337, 31339, 31341]
    pubs = [bls.pubkey_from_priv(p) for p in privs]
    msg = b"cancellation-attack"
    sigs = [bls.sign(p, msg) for p in privs]

    d = c.g1_mul(c.G1_GEN, 987654321)
    forged = [c.g1_add(sigs[0], d), c.g1_add(sigs[1], c.g1_neg(d)), sigs[2]]
    # sanity: the unweighted aggregate still verifies — the attack shape
    agg = bls.aggregate_signatures(forged)
    assert bls.verify_aggregated_same_message(agg, msg, pubs)

    got = bls.verify_batch_same_message(msg, pubs, forged)
    assert got == [False, False, True]


def test_registry_batch_verifier_unknown_key_and_bad_encoding():
    privs = [271, 277]
    pubs = [bls.pubkey_from_priv(p) for p in privs]
    reg = bls.BLSKeyRegistry()
    reg.register(b"tm0", pubs[0])
    reg.register(b"tm1", pubs[1])
    msg = b"batch"
    s0 = bls.g1_to_bytes(bls.sign(privs[0], msg))
    s1 = bls.g1_to_bytes(bls.sign(privs[1], msg))
    vb = reg.batch_verifier()
    assert vb([b"tm0", b"tm1"], msg, [s0, s1]) == [True, True]
    # unknown key -> None (not a crypto rejection: registry lag must not
    # punish the relaying peer), garbage encoding / swapped sig -> False
    assert vb([b"tmX", b"tm1", b"tm0"], msg, [s0, b"\x01" * 96, s1]) == [
        None,
        False,
        False,
    ]
    v1 = reg.verifier()
    assert v1(b"tmX", msg, s0) is None
    assert v1(b"tm0", msg, s1) is False
    assert v1(b"tm0", msg, s0) is True
