"""WAL crash-consistency + privval double-sign protection tests."""

import asyncio

import pytest

from tendermint_tpu.consensus.wal import (
    KIND_END_HEIGHT,
    NilWAL,
    WAL,
    WALMessage,
    decode_records,
    encode_record,
)
from tendermint_tpu.privval.file_pv import DoubleSignError, FilePV
from tendermint_tpu.privval.signer import (
    SignerClient,
    SignerListenerEndpoint,
    SignerServer,
)
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.part_set import PartSetHeader
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.vote import Vote, VoteType

import hashlib

CHAIN = "wal-chain"


def bid(seed=b"b"):
    return BlockID(
        hashlib.sha256(seed).digest(),
        PartSetHeader(1, hashlib.sha256(seed + b"p").digest()),
    )


# --- wal ------------------------------------------------------------------


def test_wal_write_and_replay(tmp_path):
    wal = WAL(str(tmp_path / "wal"))
    wal.write(WALMessage("vote", b"v1"))
    wal.write(WALMessage("vote", b"v2"))
    wal.write_end_height(1)
    wal.write(WALMessage("proposal", b"p2"))
    wal.write(WALMessage("vote", b"v3"))
    wal.flush_and_sync()
    tail = wal.search_for_end_height(1)
    assert [m.kind for m in tail] == ["proposal", "vote"]
    assert [m.data for m in tail] == [b"p2", b"v3"]
    assert wal.search_for_end_height(7) is None
    all_msgs = wal.search_for_end_height(0)
    assert len(all_msgs) == 5
    wal.close()


def test_wal_torn_write_is_tolerated(tmp_path):
    path = str(tmp_path / "wal")
    wal = WAL(path)
    wal.write(WALMessage("vote", b"complete"))
    wal.flush_and_sync()
    wal.close()
    # simulate crash mid-write: append half a record
    rec = encode_record(WALMessage("vote", b"torn"))
    with open(path, "ab") as f:
        f.write(rec[: len(rec) // 2])
    wal2 = WAL(path)
    msgs = wal2.search_for_end_height(0)
    assert [m.data for m in msgs] == [b"complete"]
    # repair truncates the torn tail, then writes append cleanly
    dropped = wal2.repair()
    assert dropped > 0
    wal2.write(WALMessage("vote", b"after-repair"))
    wal2.flush_and_sync()
    assert [m.data for m in wal2.search_for_end_height(0)] == [
        b"complete",
        b"after-repair",
    ]
    wal2.close()


def test_wal_corruption_detected(tmp_path):
    path = str(tmp_path / "wal")
    wal = WAL(path)
    wal.write(WALMessage("vote", b"data"))
    wal.flush_and_sync()
    wal.close()
    raw = bytearray(open(path, "rb").read())
    raw[-1] ^= 0xFF  # flip a payload byte -> crc mismatch
    with pytest.raises(Exception):
        list(decode_records(bytes(raw), lenient=False))
    assert list(decode_records(bytes(raw), lenient=True)) == []


# --- file pv --------------------------------------------------------------


def make_vote(height, round_, vtype, block_id, ts=1000):
    return Vote(
        type=vtype,
        height=height,
        round=round_,
        block_id=block_id,
        timestamp_ns=ts,
        validator_address=b"\x00" * 20,
        validator_index=0,
    )


def test_filepv_persistence(tmp_path):
    kp, sp = str(tmp_path / "key.json"), str(tmp_path / "state.json")
    pv = FilePV.generate(kp, sp)
    v = make_vote(1, 0, VoteType.PREVOTE, bid())
    pv.sign_vote(CHAIN, v)
    assert pv.get_pub_key().verify(v.sign_bytes(CHAIN), v.signature)
    # reload: same key, same last-sign state
    pv2 = FilePV.load(kp, sp)
    assert pv2.get_pub_key().data == pv.get_pub_key().data
    assert pv2.last_state.height == 1
    assert pv2.last_state.step == 2


def test_filepv_blocks_double_sign(tmp_path):
    pv = FilePV.generate(str(tmp_path / "k"), str(tmp_path / "s"))
    v1 = make_vote(5, 0, VoteType.PREVOTE, bid(b"x"))
    pv.sign_vote(CHAIN, v1)
    # same HRS, different block: refused
    v2 = make_vote(5, 0, VoteType.PREVOTE, bid(b"y"))
    with pytest.raises(DoubleSignError):
        pv.sign_vote(CHAIN, v2)
    # height regression: refused
    with pytest.raises(DoubleSignError):
        pv.sign_vote(CHAIN, make_vote(4, 0, VoteType.PREVOTE, bid(b"x")))
    # step regression (precommit then prevote): refused
    pv.sign_vote(CHAIN, make_vote(5, 0, VoteType.PRECOMMIT, bid(b"x")))
    with pytest.raises(DoubleSignError):
        pv.sign_vote(CHAIN, make_vote(5, 0, VoteType.PREVOTE, bid(b"x")))


def test_filepv_idempotent_resign(tmp_path):
    pv = FilePV.generate(str(tmp_path / "k"), str(tmp_path / "s"))
    v1 = make_vote(5, 0, VoteType.PREVOTE, bid(), ts=1000)
    pv.sign_vote(CHAIN, v1)
    # identical vote re-signed -> same signature (crash replay path)
    v2 = make_vote(5, 0, VoteType.PREVOTE, bid(), ts=1000)
    pv.sign_vote(CHAIN, v2)
    assert v2.signature == v1.signature
    # same vote, different timestamp -> previous sig + previous timestamp
    v3 = make_vote(5, 0, VoteType.PREVOTE, bid(), ts=2000)
    pv.sign_vote(CHAIN, v3)
    assert v3.signature == v1.signature
    assert v3.timestamp_ns == 1000


def test_filepv_proposal(tmp_path):
    pv = FilePV.generate(str(tmp_path / "k"), str(tmp_path / "s"))
    p = Proposal(height=2, round=0, pol_round=-1, block_id=bid(), timestamp_ns=5)
    pv.sign_proposal(CHAIN, p)
    assert pv.get_pub_key().verify(p.sign_bytes(CHAIN), p.signature)
    with pytest.raises(DoubleSignError):
        pv.sign_proposal(
            CHAIN,
            Proposal(
                height=2, round=0, pol_round=-1, block_id=bid(b"z"), timestamp_ns=5
            ),
        )


# --- remote signer --------------------------------------------------------


def test_remote_signer_roundtrip(tmp_path):
    async def run():
        pv = FilePV.generate(str(tmp_path / "k"), str(tmp_path / "s"))
        ep = SignerListenerEndpoint()
        await ep.start()
        signer = SignerServer(pv, "127.0.0.1", ep.port)
        await signer.start()
        await ep.wait_for_signer()
        client = SignerClient(ep)
        assert await client.ping()
        pub = await client.get_pub_key()
        assert pub.data == pv.get_pub_key().data
        v = make_vote(1, 0, VoteType.PREVOTE, bid())
        await client.sign_vote(CHAIN, v)
        assert pub.verify(v.sign_bytes(CHAIN), v.signature)
        # double sign through the wire is refused too
        v2 = make_vote(1, 0, VoteType.PREVOTE, bid(b"other"))
        with pytest.raises(Exception, match="DoubleSign"):
            await client.sign_vote(CHAIN, v2)
        await signer.stop()
        await ep.stop()

    asyncio.run(run())
