"""Device BLS12-381 pairing kernel vs the host-validated curve library.

The host module (crypto/bls12_381.py) is the correctness root — its own
algebraic self-checks (bilinearity, subgroup orders, the final-exp
decomposition assert at import) pin it; here every device stage must be
BIT-EXACT against it, plus worst-case limb-bound stresses for the raw
accumulation scheme (ops/bls_pairing.py module docstring).
"""

import random

import numpy as np
import pytest

import jax.numpy as jnp

from tendermint_tpu.crypto import bls12_381 as h
from tendermint_tpu.ops import bls_pairing as bp


rng = random.Random(0xB15)


def rf2():
    return (rng.randrange(h.P), rng.randrange(h.P))


def rf12():
    return tuple(rf2() for _ in range(6))


def runitary():
    """A random element of the cyclotomic subgroup (easy part on host)."""
    f = rf12()
    u = h.f12_mul(h.f12_conj(f), h.f12_inv(f))
    return h.f12_mul(h.f12_frob_n(u, 2), u)


def test_f2_ops_match_host():
    a, b = rf2(), rf2()
    da = jnp.asarray(bp.f2_from_host(a))
    db = jnp.asarray(bp.f2_from_host(b))

    def out(x):
        return bp.f2_to_host(np.asarray(bp.f2_canonical(x)))

    assert out(bp.f2_mul(da, db)) == h.f2_mul(a, b)
    assert out(bp.f2_sqr(da)) == h.f2_sqr(a)
    assert out(bp.f2_add(da, db)) == h.f2_add(a, b)
    assert out(bp.f2_sub(da, db)) == h.f2_sub(a, b)
    assert out(bp.f2_mul_xi(da)) == h.f2_mul(a, h.XI)
    assert out(bp.f2_inv(da)) == h.f2_inv(a)
    assert out(bp.f2_conj(da)) == h.f2_conj(a)


def test_f12_ops_match_host():
    A, B = rf12(), rf12()
    dA = jnp.asarray(bp.f12_from_host(A))
    dB = jnp.asarray(bp.f12_from_host(B))
    assert bp.f12_to_host(bp.f12_mul(dA, dB)) == h.f12_mul(A, B)
    assert bp.f12_to_host(bp.f12_sqr(dA)) == h.f12_sqr(A)
    assert bp.f12_to_host(bp.f12_inv(dA)) == h.f12_inv(A)
    assert bp.f12_to_host(bp.f12_frob(dA)) == h.f12_frob(A)
    assert bp.f12_to_host(bp.f12_conj(dA)) == h.f12_conj(A)


def test_cyclo_sqr_matches_generic_on_unitary():
    u = runitary()
    du = jnp.asarray(bp.f12_from_host(u))
    assert bp.f12_to_host(bp.f12_cyclo_sqr(du)) == h.f12_sqr(u)


def test_f12_mul_worst_case_limb_bounds():
    """The raw-accumulation discipline under adversarial inputs: every
    limb at the loose-invariant max (2047). The product must still be
    exactly right (no int32 overflow, no bias underflow in the xi-fold)
    and the OUTPUT must remain a valid loose input (limbs small enough
    to feed another mul/sub) — proven by squaring the output again."""
    worst = np.full((6, 2, 48), 2047, dtype=np.int32)
    A = tuple(
        (bp.fe.to_int(worst[i, 0]) % h.P, bp.fe.to_int(worst[i, 1]) % h.P)
        for i in range(6)
    )
    dA = jnp.asarray(worst)
    got = bp.f12_mul(dA, dA)
    assert bp.f12_to_host(got) == h.f12_mul(A, A)
    limbs = np.asarray(got)
    assert limbs.max() < 2048 and limbs.min() >= 0, (
        f"f12_mul output limbs out of loose range: "
        f"[{limbs.min()}, {limbs.max()}]"
    )
    # chainable: product-of-products still exact
    AA = h.f12_mul(A, A)
    assert bp.f12_to_host(bp.f12_sqr(got)) == h.f12_mul(AA, AA)


def test_vecfield_matmul_conv_bit_exact():
    """mul_style='matmul' is the same column sums as 'slices' — raw
    outputs identical on random AND worst-case loose inputs."""
    from tendermint_tpu.ops import vecfield

    fs = vecfield.make_field(h.P, 48, mul_style="slices")
    fm = vecfield.make_field(h.P, 48, mul_style="matmul")
    cases = [
        np.random.default_rng(5).integers(0, 2048, (4, 48), np.int32),
        np.full((4, 48), 2047, dtype=np.int32),
    ]
    for a in cases:
        b = a[::-1].copy()
        out_s = np.asarray(fs.mul(jnp.asarray(a), jnp.asarray(b)))
        out_m = np.asarray(fm.mul(jnp.asarray(a), jnp.asarray(b)))
        assert (out_s == out_m).all()


def test_device_pairing_matches_host():
    """Full pipeline: miller (denominator-scaled Jacobian lines) + final
    exp (GS cyclotomic) == host pairing (the cube of the optimal ate,
    identical normalization)."""
    p1 = h.g1_mul(h.G1_GEN, 7)
    q1 = h.g2_mul(h.G2_GEN, 11)
    assert bp.pairing_value([(p1, q1)]) == h.pairing(p1, q1)


def test_device_pairing_bilinear():
    """e(aP, Q) == e(P, aQ) computed entirely on device."""
    a = 99991
    va = bp.pairing_value([(h.g1_mul(h.G1_GEN, a), h.G2_GEN)])
    vb = bp.pairing_value([(h.G1_GEN, h.g2_mul(h.G2_GEN, a))])
    assert va == vb


def test_device_check_pairs_accept_reject():
    a = 123457
    pa = h.g1_mul(h.G1_GEN, a)
    qa = h.g2_mul(h.G2_GEN, a)
    assert bp.check_pairs([(pa, h.G2_GEN), (h.g1_neg(h.G1_GEN), qa)])
    assert not bp.check_pairs(
        [(pa, h.G2_GEN), (h.g1_neg(h.G1_GEN), h.g2_mul(h.G2_GEN, a + 1))]
    )
    # infinity pairs contribute factor 1 (host miller_loop semantics)
    assert bp.check_pairs([(h.G1_INF, h.G2_GEN)])


def test_bls_verify_routes_through_device(monkeypatch):
    """TM_TPU_BLS_PAIRING_DEVICE=1 routes the signature scheme's
    2-pairing check through the kernel: good signature verifies, bad
    rejects — the aggregate row end-to-end on device."""
    from tendermint_tpu.crypto import bls_signatures as bls

    monkeypatch.setenv("TM_TPU_BLS_PAIRING_DEVICE", "1")
    sk = 0x42424242424242424242424242424242
    pk = bls.pubkey_from_priv(sk)
    msg = b"device-pairing-route"
    sig = bls.sign(sk, msg)
    assert bls.verify(sig, msg, pk)
    assert not bls.verify(sig, msg + b"!", pk)
