"""sr25519 stack: merlin (published vector), ristretto255 (RFC 9496
vectors), schnorrkel sign/verify semantics."""

import numpy as np
import pytest

from tendermint_tpu.crypto import ristretto, sr25519
from tendermint_tpu.crypto.ed25519 import BASEPOINT as B
from tendermint_tpu.crypto.ed25519 import P, point_add, scalar_mult
from tendermint_tpu.crypto.merlin import Transcript


def test_merlin_conformance_vector():
    """The Merlin crate's own equivalence test vector."""
    t = Transcript(b"test protocol")
    t.append_message(b"some label", b"some data")
    c = t.challenge_bytes(b"challenge", 32)
    assert (
        c.hex()
        == "d5a21972d0d5fe320c0d263fac7fffb8145aa640af6e9bca177c03c7efcf0615"
    )


def test_ristretto_small_multiples():
    """RFC 9496 appendix A: encodings of 0..4 times the generator."""
    expected = [
        "0000000000000000000000000000000000000000000000000000000000000000",
        "e2f2ae0a6abc4e71a884a961c500515f58e30b6aa582dd8db6a65945e08d2d76",
        "6a493210f7499cd17fecb510ae0cea23a110e8d5b901f8acadd3095c73a3b919",
        "94741f5d5d52755ece4f23f044ee27d5d1ea1e2bd196b462166b16152a9d0259",
        "da80862773358b466ffadfe0b3293ab3d9fd53c5ea6c955358f568322daf6a57",
    ]
    pt = (0, 1, 1, 0)
    for exp in expected:
        assert ristretto.encode(pt).hex() == exp
        pt = point_add(pt, B)


def test_ristretto_decode_roundtrip_and_rejects():
    for i in range(1, 6):
        p = scalar_mult(i, B)
        enc = ristretto.encode(p)
        dec = ristretto.decode(enc)
        assert dec is not None and ristretto.equal(dec, p)
        assert ristretto.encode(dec) == enc
    # non-canonical: s >= p
    assert ristretto.decode((P + 1).to_bytes(32, "little")) is None
    # negative: odd s
    assert ristretto.decode((1).to_bytes(32, "little")) is None
    # RFC 9496: invalid encoding (not on curve)
    bad = bytes.fromhex(
        "26948d35ca62e643e26a83177332e6b6afeb9d08e4268b650f1f5bbd8d81d371"
    )
    assert ristretto.decode(bad) is None


def test_sign_verify_roundtrip():
    k = sr25519.PrivKey.from_secret(b"validator-1")
    pub = k.public_key()
    msg = b"vote sign bytes"
    sig = k.sign(msg)
    assert len(sig) == 64 and sig[63] & 0x80
    assert pub.verify(msg, sig)
    # wrong message
    assert not pub.verify(msg + b"x", sig)
    # flipped signature byte
    bad = bytearray(sig)
    bad[0] ^= 1
    assert not pub.verify(msg, bytes(bad))
    # marker bit cleared -> not a schnorrkel sig
    bad = bytearray(sig)
    bad[63] &= 0x7F
    assert not pub.verify(msg, bytes(bad))
    # wrong key
    assert not sr25519.PrivKey.from_secret(b"other").public_key().verify(
        msg, sig
    )


def test_expand_ed25519_shape():
    import hashlib

    mini = b"\x01" * 32
    scalar, nonce = sr25519.expand_ed25519(mini)
    # clamped (bit 254 set, low 3 bits clear) then divided by the cofactor:
    # scalar * 8 must reconstruct the clamped SHA-512 prefix exactly
    h = bytearray(hashlib.sha512(mini).digest()[:32])
    h[0] &= 248
    h[31] &= 63
    h[31] |= 64
    assert scalar * 8 == int.from_bytes(bytes(h), "little")
    assert 2**251 <= scalar < 2**252
    assert nonce == hashlib.sha512(mini).digest()[32:]
    assert len(nonce) == 32


def test_pubkey_deterministic_and_sized():
    k = sr25519.PrivKey.from_bytes(b"\x07" * 32)
    p1, p2 = k.public_key(), k.public_key()
    assert p1 == p2 and len(p1.data) == 32
    assert len(k.public_key().address()) == 20


def test_sr25519_verify_golden_fixture():
    """Pin signature VERIFICATION behavior against a committed fixture.

    No cross-implementation KAT is possible in this offline environment
    (no schnorrkel build anywhere in the image, and the reference's
    sr25519_test.go ships no vectors — only sign/verify round-trips); the
    merlin transcript and ristretto255 layers below this ARE vector-tested
    against their published RFC/conformance vectors. This fixture freezes
    our transcript flow ("substrate" ctx labels, witness derivation) so an
    accidental change to sign/verify internals fails loudly instead of
    silently rejecting real-world signatures after a refactor."""
    import json
    import os

    from tendermint_tpu.crypto import sr25519

    path = os.path.join(
        os.path.dirname(__file__), "sr25519_golden.json"
    )
    priv = sr25519.PrivKey.from_secret(b"golden-seed")
    msg = b"golden message"
    pub = priv.public_key()
    if not os.path.exists(path):
        # deterministic signature: sign uses a transcript-derived witness
        # with external randomness; for the fixture we need stability, so
        # record pub + a signature produced NOW and only pin VERIFY.
        sig = priv.sign(msg)
        with open(path, "w") as f:
            json.dump(
                {
                    "pub": pub.data.hex(),
                    "msg": msg.hex(),
                    "sig": sig.hex(),
                },
                f,
                indent=2,
            )
    with open(path) as f:
        d = json.load(f)
    assert bytes.fromhex(d["pub"]) == pub.data, (
        "key derivation drifted: the same seed produces a different pubkey"
    )
    assert sr25519.PubKey(bytes.fromhex(d["pub"])).verify(
        bytes.fromhex(d["msg"]), bytes.fromhex(d["sig"])
    ), "verify no longer accepts a signature produced by an earlier build"
