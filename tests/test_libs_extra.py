"""clist, flowrate, math, cmap, ethutil (reference libs/ + ethutil/)."""

import asyncio

import pytest

from tendermint_tpu.ethutil import (
    LegacyTx,
    decode_txs,
    encode_transactions,
    rlp_decode,
    rlp_encode,
)
from tendermint_tpu.libs.clist import CList
from tendermint_tpu.libs.cmap import CMap
from tendermint_tpu.libs.flowrate import Monitor
from tendermint_tpu.libs.math import (
    ErrOverflow,
    Fraction,
    MAX_INT64,
    safe_add_int64,
    safe_mul_int64,
)


def test_clist_push_remove_iterate():
    async def run():
        cl = CList()
        e1 = cl.push_back("a")
        e2 = cl.push_back("b")
        cl.push_back("c")
        assert list(cl) == ["a", "b", "c"]
        cl.remove(e2)
        assert list(cl) == ["a", "c"]
        assert len(cl) == 2
        # waiting cursor wakes when a next element arrives
        got = []

        async def reader():
            el = await cl.front_wait()
            while el is not None:
                got.append(el.value)
                if len(got) == 3:
                    return
                el = await el.next_wait()

        t = asyncio.create_task(reader())
        await asyncio.sleep(0.01)
        cl.push_back("d")
        await asyncio.wait_for(t, 2)
        assert got == ["a", "c", "d"]

    asyncio.run(run())


def test_flowrate_tracks_rate():
    m = Monitor(sample_period=0.0)  # sample on every update
    m.update(1000)
    st = m.status()
    assert st.bytes_total == 1000
    assert st.avg_rate > 0
    assert m.limit(500, max_rate=0) == 500  # unlimited


def test_safe_math_and_fraction():
    assert safe_add_int64(1, 2) == 3
    with pytest.raises(ErrOverflow):
        safe_add_int64(MAX_INT64, 1)
    with pytest.raises(ErrOverflow):
        safe_mul_int64(MAX_INT64, 2)
    f = Fraction.parse("1/3")
    assert f.numerator == 1 and f.denominator == 3
    assert abs(float(f) - 1 / 3) < 1e-12
    with pytest.raises(ZeroDivisionError):
        Fraction(1, 0)


def test_cmap():
    m = CMap()
    m.set("a", 1)
    assert m.get("a") == 1 and m.has("a") and m.size() == 1
    m.delete("a")
    assert not m.has("a")


# --- ethutil ----------------------------------------------------------------


def test_rlp_roundtrip():
    cases = [b"", b"\x01", b"dog", b"x" * 100, [b"cat", [b"a", b""]], []]
    for c in cases:
        enc = rlp_encode(c)
        dec, rest = rlp_decode(enc)
        assert rest == b""
        assert dec == c
    # canonical single-byte encoding
    assert rlp_encode(b"\x05") == b"\x05"
    assert rlp_encode(0) == b"\x80"
    assert rlp_encode(1024) == b"\x82\x04\x00"


def test_legacy_tx_sign_recover_roundtrip():
    from tendermint_tpu.crypto import secp256k1

    key = secp256k1.PrivKey.from_secret(b"eth-sender")
    pt = secp256k1.decompress_point(key.public_key().data)
    addr = secp256k1.eth_address(pt)

    tx = LegacyTx(
        nonce=7,
        gas_price=10**9,
        gas=21000,
        to=b"\x11" * 20,
        value=10**18,
        data=b"",
    )
    tx.sign(key.secret, chain_id=2818)  # morph chain id
    assert tx.chain_id() == 2818
    assert tx.sender() == addr

    # wire roundtrip preserves sender recovery
    blob = encode_transactions([tx, tx])
    txs = decode_txs(blob)
    assert len(txs) == 2
    for t in txs:
        assert t.sender() == addr
        assert t.nonce == 7 and t.value == 10**18

    # tampered payload recovers a different sender
    bad = decode_txs(blob)[0]
    bad.value = 5
    assert bad.sender() != addr
