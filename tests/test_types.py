"""Core types tests — the reconstruction of the test suite the fork
commented out (SURVEY.md §4.1: types/ block/vote/vote_set/validator_set
tests all dead in the reference)."""

import pytest

from tendermint_tpu.crypto import ed25519, merkle
from tendermint_tpu.types import (
    Block,
    BlockID,
    BlockIDFlag,
    Commit,
    CommitSig,
    Data,
    DuplicateVoteEvidence,
    Header,
    PartSetHeader,
    Proposal,
    Validator,
    ValidatorSet,
    Vote,
    VoteSet,
    VoteType,
)
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.params import ConsensusParams
from tendermint_tpu.types.part_set import PartSet
from tendermint_tpu.types.priv_validator import MockPV
from tendermint_tpu.types.vote_set import ConflictingVoteError

CHAIN_ID = "test-chain"


def make_valset(n, power=10):
    pvs = [MockPV.from_secret(b"val%d" % i) for i in range(n)]
    vals = [Validator(pv.get_pub_key(), power) for pv in pvs]
    vs = ValidatorSet(vals)
    # order privvals to match the sorted set
    by_addr = {pv.get_pub_key().address(): pv for pv in pvs}
    ordered = [by_addr[v.address] for v in vs.validators]
    return vs, ordered


def make_block_id(seed=b"blk"):
    import hashlib

    h = hashlib.sha256(seed).digest()
    ph = hashlib.sha256(seed + b"p").digest()
    return BlockID(hash=h, part_set_header=PartSetHeader(total=1, hash=ph))


def make_vote(pv, vs, height, round_, vtype, block_id, ts=1_700_000_000_000_000_000):
    addr = pv.get_pub_key().address()
    idx, _ = vs.get_by_address(addr)
    v = Vote(
        type=vtype,
        height=height,
        round=round_,
        block_id=block_id,
        timestamp_ns=ts,
        validator_address=addr,
        validator_index=idx,
    )
    pv.sign_vote(CHAIN_ID, v)
    return v


# --- merkle ---------------------------------------------------------------


def test_merkle_proofs():
    items = [b"a", b"bb", b"ccc", b"dddd", b"eeeee"]
    root, proofs = merkle.proofs_from_byte_slices(items)
    assert root == merkle.hash_from_byte_slices(items)
    for i, item in enumerate(items):
        assert proofs[i].verify(root, item)
        assert not proofs[i].verify(root, item + b"!")
    # single and empty
    r1 = merkle.hash_from_byte_slices([b"x"])
    assert r1 == merkle.leaf_hash(b"x")
    import hashlib

    assert merkle.hash_from_byte_slices([]) == hashlib.sha256(b"").digest()


# --- part sets ------------------------------------------------------------


def test_part_set_roundtrip():
    data = bytes(range(256)) * 1000  # 256 KB -> 4 parts
    ps = PartSet.from_data(data)
    assert ps.total == 4 and ps.is_complete()
    # reassemble from gossiped parts
    ps2 = PartSet(ps.header)
    for i in [2, 0, 3, 1]:
        assert ps2.add_part(ps.get_part(i))
    assert ps2.is_complete()
    assert ps2.get_bytes() == data


def test_part_set_rejects_bad_proof():
    ps = PartSet.from_data(b"hello world")
    part = ps.get_part(0)
    ps2 = PartSet(PartSetHeader(total=1, hash=b"\x00" * 32))
    with pytest.raises(ValueError):
        ps2.add_part(part)


# --- vote sign bytes / encode --------------------------------------------


def test_vote_roundtrip_and_verify():
    vs, pvs = make_valset(4)
    bid = make_block_id()
    v = make_vote(pvs[0], vs, 5, 0, VoteType.PREVOTE, bid)
    assert v.verify(CHAIN_ID, pvs[0].get_pub_key())
    assert not v.verify("other-chain", pvs[0].get_pub_key())
    rt = Vote.decode(v.encode())
    assert rt == v


def test_proposal_sign_bytes():
    pv = MockPV.from_secret(b"p")
    prop = Proposal(
        height=3,
        round=1,
        pol_round=-1,
        block_id=make_block_id(),
        timestamp_ns=123456789,
    )
    pv.sign_proposal(CHAIN_ID, prop)
    assert pv.get_pub_key().verify(prop.sign_bytes(CHAIN_ID), prop.signature)
    rt = Proposal.decode(prop.encode())
    assert rt == prop


# --- header / block -------------------------------------------------------


def make_header(vs, height=3):
    return Header(
        chain_id=CHAIN_ID,
        height=height,
        time_ns=1_700_000_000_000_000_000,
        last_block_id=make_block_id(b"prev"),
        validators_hash=vs.hash(),
        next_validators_hash=vs.hash(),
        consensus_hash=ConsensusParams().hash(),
        app_hash=b"\x01" * 32,
        proposer_address=vs.validators[0].address,
    )


def test_block_roundtrip():
    vs, pvs = make_valset(4)
    bid = make_block_id()
    commit = Commit(
        height=2,
        round=0,
        block_id=bid,
        signatures=[
            CommitSig(
                BlockIDFlag.COMMIT,
                vs.validators[i].address,
                1_700_000_000_000_000_000 + i,
                b"\x01" * 64,
            )
            for i in range(4)
        ],
    )
    block = Block(
        header=make_header(vs),
        data=Data(txs=[b"tx1", b"tx2"], l2_block_meta=b"meta"),
        last_commit=commit,
    )
    block.fill_header()
    block.validate_basic()
    rt = Block.decode(block.encode())
    assert rt.hash() == block.hash()
    assert rt.data.txs == [b"tx1", b"tx2"]
    assert rt.last_commit.hash() == commit.hash()
    # header hash covers batch_hash (morph capability)
    b2 = Block.decode(block.encode())
    b2.header.batch_hash = b"\x07" * 32
    assert b2.hash() != block.hash()


def test_block_validate_catches_tampering():
    vs, _ = make_valset(1)
    block = Block(header=make_header(vs, height=1), data=Data(txs=[b"tx"]))
    block.fill_header()
    block.validate_basic()
    block.data.txs.append(b"evil")
    block.data._hash = None
    with pytest.raises(ValueError):
        block.validate_basic()


# --- validator set --------------------------------------------------------


def test_proposer_rotation_weighted():
    vs, _ = make_valset(3)
    # equal powers -> round robin over 3 proposers, deterministic
    seq = []
    c = vs.copy()
    for _ in range(6):
        seq.append(c.get_proposer().address)
        c.increment_proposer_priority(1)
    assert set(seq[:3]) == {v.address for v in vs.validators}
    assert seq[:3] == seq[3:6]


def test_proposer_rotation_proportional():
    pv1, pv2 = MockPV.from_secret(b"a"), MockPV.from_secret(b"b")
    v1 = Validator(pv1.get_pub_key(), 90)
    v2 = Validator(pv2.get_pub_key(), 10)
    vs = ValidatorSet([v1, v2])
    counts = {v1.address: 0, v2.address: 0}
    c = vs.copy()
    for _ in range(100):
        counts[c.get_proposer().address] += 1
        c.increment_proposer_priority(1)
    assert counts[v1.address] == 90
    assert counts[v2.address] == 10


def test_validator_set_updates():
    vs, _ = make_valset(3)
    total0 = vs.total_voting_power()
    new_pv = MockPV.from_secret(b"newval")
    vs.update_with_change_set([Validator(new_pv.get_pub_key(), 5)])
    assert vs.size() == 4
    assert vs.total_voting_power() == total0 + 5
    # power update
    vs.update_with_change_set([Validator(new_pv.get_pub_key(), 7)])
    assert vs.total_voting_power() == total0 + 7
    # removal
    vs.update_with_change_set([Validator(new_pv.get_pub_key(), 0)])
    assert vs.size() == 3
    with pytest.raises(ValueError):
        vs.update_with_change_set([Validator(new_pv.get_pub_key(), 0)])


def test_validator_set_encode_roundtrip():
    vs, _ = make_valset(3)
    vs.increment_proposer_priority(2)
    rt = ValidatorSet.decode(vs.encode())
    assert rt.hash() == vs.hash()
    assert [v.proposer_priority for v in rt.validators] == [
        v.proposer_priority for v in vs.validators
    ]
    assert rt.get_proposer().address == vs.get_proposer().address


# --- vote set -------------------------------------------------------------


def test_vote_set_two_thirds():
    vs, pvs = make_valset(4)
    voteset = VoteSet(CHAIN_ID, 1, 0, VoteType.PREVOTE, vs)
    bid = make_block_id()
    assert not voteset.has_two_thirds_any()
    for i in range(3):
        added = voteset.add_vote(
            make_vote(pvs[i], vs, 1, 0, VoteType.PREVOTE, bid)
        )
        assert added
    maj, ok = voteset.two_thirds_majority()
    assert ok and maj == bid
    # duplicate returns False
    assert not voteset.add_vote(
        make_vote(pvs[0], vs, 1, 0, VoteType.PREVOTE, bid)
    )


def test_vote_set_rejects_bad_signature():
    vs, pvs = make_valset(4)
    voteset = VoteSet(CHAIN_ID, 1, 0, VoteType.PREVOTE, vs)
    v = make_vote(pvs[0], vs, 1, 0, VoteType.PREVOTE, make_block_id())
    v.signature = bytes(64)
    with pytest.raises(ValueError):
        voteset.add_vote(v)


def test_vote_set_conflict_detected():
    vs, pvs = make_valset(4)
    voteset = VoteSet(CHAIN_ID, 1, 0, VoteType.PREVOTE, vs)
    v1 = make_vote(pvs[0], vs, 1, 0, VoteType.PREVOTE, make_block_id(b"x"))
    v2 = make_vote(pvs[0], vs, 1, 0, VoteType.PREVOTE, make_block_id(b"y"))
    voteset.add_vote(v1)
    with pytest.raises(ConflictingVoteError) as ei:
        voteset.add_vote(v2)
    ev = DuplicateVoteEvidence.from_votes(
        ei.value.existing, ei.value.new, vs.total_voting_power(), 10, 0
    )
    ev.validate_basic()


def test_vote_set_make_commit():
    vs, pvs = make_valset(4)
    voteset = VoteSet(CHAIN_ID, 1, 0, VoteType.PRECOMMIT, vs)
    bid = make_block_id()
    for i in range(3):
        voteset.add_vote(make_vote(pvs[i], vs, 1, 0, VoteType.PRECOMMIT, bid))
    # one nil vote
    voteset.add_vote(make_vote(pvs[3], vs, 1, 0, VoteType.PRECOMMIT, BlockID()))
    commit = voteset.make_commit()
    assert commit.size() == 4
    flags = [cs.block_id_flag for cs in commit.signatures]
    assert flags.count(BlockIDFlag.COMMIT) == 3
    assert flags.count(BlockIDFlag.NIL) == 1
    rt = Commit.decode(commit.encode())
    assert rt.hash() == commit.hash()


# --- commit verification via the TPU batch path ---------------------------


def make_commit_for(vs, pvs, height, bid, nil_indices=()):
    voteset = VoteSet(CHAIN_ID, height, 0, VoteType.PRECOMMIT, vs)
    for i, pv in enumerate(pvs):
        target = BlockID() if i in nil_indices else bid
        voteset.add_vote(
            make_vote(pv, vs, height, 0, VoteType.PRECOMMIT, target)
        )
    return voteset.make_commit()


def test_verify_commit_light():
    vs, pvs = make_valset(4)
    bid = make_block_id()
    commit = make_commit_for(vs, pvs, 3, bid, nil_indices=(3,))
    vs.verify_commit_light(CHAIN_ID, bid, 3, commit)
    vs.verify_commit(CHAIN_ID, bid, 3, commit)
    vs.verify_commit_light_trusting(CHAIN_ID, commit)


def test_verify_commit_insufficient_power():
    vs, pvs = make_valset(4)
    bid = make_block_id()
    commit = make_commit_for(vs, pvs, 3, bid)
    for i in (1, 2, 3):  # demote to NIL: signatures no longer count
        commit.signatures[i].block_id_flag = BlockIDFlag.NIL
    with pytest.raises(ValueError, match="insufficient"):
        vs.verify_commit_light(CHAIN_ID, bid, 3, commit)


def test_make_commit_rejects_nil_majority():
    vs, pvs = make_valset(4)
    voteset = VoteSet(CHAIN_ID, 1, 0, VoteType.PRECOMMIT, vs)
    for pv in pvs:
        voteset.add_vote(make_vote(pv, vs, 1, 0, VoteType.PRECOMMIT, BlockID()))
    with pytest.raises(ValueError, match="nil"):
        voteset.make_commit()


def test_verify_commit_rejects_tampered_sig():
    vs, pvs = make_valset(4)
    bid = make_block_id()
    commit = make_commit_for(vs, pvs, 3, bid)
    commit.signatures[1].signature = bytes(64)
    with pytest.raises(ValueError, match="wrong signature"):
        vs.verify_commit(CHAIN_ID, bid, 3, commit)
    # light variant: masked tally still has 3/4 power -> passes
    vs.verify_commit_light(CHAIN_ID, bid, 3, commit)
    commit.signatures[2].signature = bytes(64)
    with pytest.raises(ValueError, match="insufficient"):
        vs.verify_commit_light(CHAIN_ID, bid, 3, commit)


def test_verify_commit_shape_checks():
    vs, pvs = make_valset(4)
    bid = make_block_id()
    commit = make_commit_for(vs, pvs, 3, bid)
    with pytest.raises(ValueError, match="height"):
        vs.verify_commit_light(CHAIN_ID, bid, 4, commit)
    with pytest.raises(ValueError, match="block id"):
        vs.verify_commit_light(CHAIN_ID, make_block_id(b"z"), 3, commit)
    small, _ = make_valset(3)
    with pytest.raises(ValueError, match="size"):
        small.verify_commit_light(CHAIN_ID, bid, 3, commit)


def test_vote_sign_bytes_matches_canonical_encoder():
    """The cached-parts fast path in Commit.vote_sign_bytes must stay
    byte-identical to a direct CanonicalVoteEncoder.vote encode for every
    signature variant (commit bid, nil bid, distinct timestamps) and
    across chain ids (the cache is keyed on both)."""
    from tendermint_tpu.types import canonical

    vs, pvs = make_valset(4)
    bid = make_block_id()
    commit = make_commit_for(vs, pvs, 3, bid, nil_indices=(2,))
    # make the timestamps visibly distinct (incl. a 0-nanos boundary)
    commit.signatures[0].timestamp_ns = 1_700_000_000_000_000_000
    commit.signatures[1].timestamp_ns = 1_700_000_001_000_000_000
    commit.signatures[2].timestamp_ns = 1_700_000_002_500_000_000
    for chain_id in (CHAIN_ID, "other-chain"):
        for i, cs in enumerate(commit.signatures):
            if cs.is_absent():
                continue
            sbid = cs.block_id(commit.block_id)
            want = canonical.CanonicalVoteEncoder.vote(
                canonical.PRECOMMIT_TYPE,
                commit.height,
                commit.round,
                canonical.canonical_block_id(
                    sbid.hash,
                    sbid.part_set_header.total,
                    sbid.part_set_header.hash,
                ),
                cs.timestamp_ns,
                chain_id,
            )
            assert commit.vote_sign_bytes(chain_id, i) == want, (
                f"sign-bytes diverged for sig {i} on {chain_id}"
            )


# --- genesis / params -----------------------------------------------------


def test_genesis_roundtrip(tmp_path):
    vs, pvs = make_valset(2)
    doc = GenesisDoc(
        chain_id=CHAIN_ID,
        validators=[
            GenesisValidator("ed25519", v.pub_key.data, v.voting_power)
            for v in vs.validators
        ],
        app_state={"accounts": []},
    )
    doc.validate_and_complete()
    path = str(tmp_path / "genesis.json")
    doc.save_as(path)
    rt = GenesisDoc.from_file(path)
    assert rt.chain_id == CHAIN_ID
    assert rt.validator_set().hash() == vs.hash()
    assert rt.hash() == doc.hash()


def test_consensus_params_update():
    p = ConsensusParams()
    p.validate()
    p2 = p.update({"block": {"max_bytes": 1024}, "batch": {"blocks_interval": 5}})
    assert p2.block.max_bytes == 1024
    assert p2.batch.blocks_interval == 5
    assert p.block.max_bytes != 1024  # original untouched
    with pytest.raises(ValueError):
        p.update({"block": {"max_bytes": -5}})
