"""Crash-recovery tests: handshake replay + WAL catchup across restarts."""

import asyncio

import pytest

from tendermint_tpu.abci.client import LocalClient
from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.consensus.replay import Handshaker
from tendermint_tpu.consensus.state_machine import (
    ConsensusConfig,
    ConsensusState,
)
from tendermint_tpu.consensus.wal import WAL
from tendermint_tpu.l2node.mock import MockL2Node
from tendermint_tpu.privval.file_pv import FilePV
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.state import State
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.store.block_store import BlockStore
from tendermint_tpu.store.kv import MemKV
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

CHAIN_ID = "replay-chain"


def test_node_restarts_and_continues(tmp_path):
    """Run to height 2, 'crash', restart on the same stores + WAL + app,
    and continue to height 4. The restart path exercises Handshaker (app
    behind the store) and WAL catchup."""

    kv_block = MemKV()
    kv_state = MemKV()
    app = KVStoreApplication()  # in-proc app survives 'restart' like a
    # separate app process would
    l2 = MockL2Node()
    pv_path = (str(tmp_path / "pv_key"), str(tmp_path / "pv_state"))
    wal_path = str(tmp_path / "wal" / "wal")

    pv = FilePV.generate(*pv_path)
    genesis = GenesisDoc(
        chain_id=CHAIN_ID,
        genesis_time_ns=1,
        validators=[
            GenesisValidator("ed25519", pv.get_pub_key().data, 10)
        ],
    )
    genesis.validate_and_complete()

    def build():
        state_store = StateStore(kv_state)
        block_store = BlockStore(kv_block)
        executor = BlockExecutor(
            state_store, block_store, LocalClient(app), l2
        )
        return state_store, block_store, executor

    async def first_run():
        state_store, block_store, executor = build()
        state = State.from_genesis(genesis)
        handshaker = Handshaker(state_store, block_store, genesis, executor)
        state = await handshaker.handshake(state)
        cs = ConsensusState(
            ConsensusConfig.test_config(),
            state,
            executor,
            block_store,
            l2,
            priv_validator=FilePV.load(*pv_path),
            wal=WAL(wal_path),
        )
        await cs.start()
        await cs.wait_for_height(2, timeout=20)
        await cs.stop()  # crash here (stores + WAL keep their contents)
        cs.wal.close()
        return cs.state.last_block_height

    async def second_run():
        state_store, block_store, executor = build()
        state = state_store.load()
        assert state is not None and state.last_block_height >= 2
        handshaker = Handshaker(state_store, block_store, genesis, executor)
        state = await handshaker.handshake(state)
        cs = ConsensusState(
            ConsensusConfig.test_config(),
            state,
            executor,
            block_store,
            l2,
            priv_validator=FilePV.load(*pv_path),
            wal=WAL(wal_path),
        )
        await cs.start()
        await cs.wait_for_height(4, timeout=20)
        await cs.stop()
        cs.wal.close()
        return cs.state.last_block_height, block_store

    h1 = asyncio.run(first_run())
    assert h1 >= 2
    h2, block_store = asyncio.run(second_run())
    assert h2 >= 4
    # the chain is contiguous across the restart
    for h in range(2, 5):
        b = block_store.load_block(h)
        prev = block_store.load_block(h - 1)
        assert b.header.last_block_id.hash == prev.hash()


def test_handshake_replays_into_fresh_app(tmp_path):
    """Blocks exist in the store but the app restarts empty: handshake
    must replay all blocks into the app (reference ReplayBlocks case)."""

    kv_block = MemKV()
    kv_state = MemKV()
    l2 = MockL2Node()
    pv = FilePV.generate(str(tmp_path / "k"), str(tmp_path / "s"))
    genesis = GenesisDoc(
        chain_id=CHAIN_ID,
        genesis_time_ns=1,
        validators=[GenesisValidator("ed25519", pv.get_pub_key().data, 10)],
    )
    genesis.validate_and_complete()

    async def produce():
        app = KVStoreApplication()
        state_store = StateStore(kv_state)
        block_store = BlockStore(kv_block)
        executor = BlockExecutor(state_store, block_store, LocalClient(app), l2)
        state = await Handshaker(
            state_store, block_store, genesis, executor
        ).handshake(State.from_genesis(genesis))
        cs = ConsensusState(
            ConsensusConfig.test_config(),
            state,
            executor,
            block_store,
            l2,
            priv_validator=pv,
        )
        await cs.start()
        await cs.wait_for_height(3, timeout=20)
        await cs.stop()
        return app.info().last_block_height

    app_h = asyncio.run(produce())
    assert app_h >= 3

    async def restart_with_fresh_app():
        fresh_app = KVStoreApplication()  # lost all state
        state_store = StateStore(kv_state)
        block_store = BlockStore(kv_block)
        executor = BlockExecutor(
            state_store, block_store, LocalClient(fresh_app), l2
        )
        state = state_store.load()
        hs = Handshaker(state_store, block_store, genesis, executor)
        state = await hs.handshake(state)
        return fresh_app.info().last_block_height, hs.n_blocks_replayed, state

    fresh_h, replayed, state = asyncio.run(restart_with_fresh_app())
    assert replayed >= 3
    assert fresh_h >= 3
    assert state.last_block_height == fresh_h


def test_blocksync_switchover_skips_wal_catchup(tmp_path):
    """Regression (mp e2e stall): blocksync advances state PAST the WAL's
    last end-height barrier; consensus.start() must refuse to replay (the
    lock state is unrecoverable) but start(skip_wal_catchup=True) — the
    reference's SwitchToConsensus skipWAL path — must start cleanly and
    re-anchor the WAL so the NEXT plain restart replays fine."""
    from tests.helpers import make_genesis, make_validators
    from tests.test_consensus import make_node

    vs, pvs = make_validators(1)
    genesis = make_genesis(vs)

    async def run():
        wal_path = str(tmp_path / "cs.wal")
        wal = WAL(wal_path)
        # WAL saw heights up to 2...
        wal.write_end_height(1)
        wal.write_end_height(2)
        wal.flush_and_sync()

        cs, app, l2, bs, ss = make_node(vs, pvs[0], genesis)
        cs.wal = wal
        # ...but (simulated) blocksync moved state to 5
        cs.state.last_block_height = 5
        with pytest.raises(RuntimeError):
            await cs.start()
        await cs.stop()

        cs2, app, l2, bs, ss = make_node(vs, pvs[0], genesis)
        cs2.wal = WAL(wal_path)
        cs2.state.last_block_height = 5
        await cs2.start(skip_wal_catchup=True)
        assert cs2.rs.height == 6
        await cs2.stop()

        # the skip wrote an end-height barrier: a plain restart replays
        cs3, app, l2, bs, ss = make_node(vs, pvs[0], genesis)
        cs3.wal = WAL(wal_path)
        cs3.state.last_block_height = 5
        await cs3.start()  # must NOT raise now
        assert cs3.rs.height == 6
        await cs3.stop()

    asyncio.run(run())
