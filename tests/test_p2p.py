"""P2P tests: secret connection, mconn multiplexing, switch, pex."""

import asyncio

import pytest

from tendermint_tpu.crypto import aead, ed25519
from tendermint_tpu.p2p.key import NodeKey
from tendermint_tpu.p2p.mconn import ChannelDescriptor, MConnection
from tendermint_tpu.p2p.node_info import NodeInfo
from tendermint_tpu.p2p.pex import AddrBook, PEXReactor
from tendermint_tpu.p2p.secret_connection import SecretConnection
from tendermint_tpu.p2p.switch import Reactor, Switch
from tendermint_tpu.p2p.transport import (
    MultiplexTransport,
    NetAddress,
    Peer,
)

NETWORK = "p2p-test-chain"


async def _pipe_pair():
    """Two connected (reader, writer) pairs over localhost TCP."""
    accepted = asyncio.Queue()

    async def on_conn(r, w):
        await accepted.put((r, w))

    server = await asyncio.start_server(on_conn, "127.0.0.1", 0)
    port = server.sockets[0].getsockname()[1]
    r1, w1 = await asyncio.open_connection("127.0.0.1", port)
    r2, w2 = await accepted.get()
    return (r1, w1), (r2, w2), server


def test_secret_connection_handshake_and_data():
    async def run():
        (r1, w1), (r2, w2), server = await _pipe_pair()
        k1, k2 = ed25519.PrivKey.generate(), ed25519.PrivKey.generate()
        c1, c2 = await asyncio.gather(
            SecretConnection.make(r1, w1, k1),
            SecretConnection.make(r2, w2, k2),
        )
        # authenticated identities
        assert c1.remote_pubkey.data == k2.public_key().data
        assert c2.remote_pubkey.data == k1.public_key().data
        # bidirectional data incl. multi-frame messages
        await c1.write(b"hello")
        assert await c2.read_exactly(5) == b"hello"
        big = bytes(range(256)) * 20  # 5120 bytes -> 6 frames
        await c2.write(big)
        assert await c1.read_exactly(len(big)) == big
        c1.close(); c2.close(); server.close()

    asyncio.run(run())


def test_secret_connection_rejects_tampering():
    async def run():
        (r1, w1), (r2, w2), server = await _pipe_pair()
        k1, k2 = ed25519.PrivKey.generate(), ed25519.PrivKey.generate()
        c1, c2 = await asyncio.gather(
            SecretConnection.make(r1, w1, k1),
            SecretConnection.make(r2, w2, k2),
        )
        # inject a corrupted frame directly into the raw socket
        from tendermint_tpu.p2p.secret_connection import SEALED_FRAME_SIZE

        w1.write(b"\x00" * SEALED_FRAME_SIZE)
        await w1.drain()
        with pytest.raises(ValueError):
            await c2.read()
        c1.close(); c2.close(); server.close()

    asyncio.run(run())


def test_secretconn_mitm_eph_substitution_fails():
    """Ephemeral-key-substitution MITM (the attack shape the handshake's
    security argument rules out — see secret_connection.py docstring):
    the attacker completes a full DH with EACH side using its own
    ephemeral keys, holds both legs' symmetric keys, and faithfully
    re-encrypts the auth payloads across legs. Both honest sides must
    reject: the relayed signature covers the OTHER leg's challenge."""
    from tendermint_tpu.p2p.secret_connection import (
        HKDF_INFO,
        SecretConnection,
        _hkdf_sha256,
        _Nonce,
    )
    from tendermint_tpu.crypto import aead as _aead, x25519

    async def run():
        # two real socket pairs: A<->M and M<->B
        (ra_a, wa_a), (ra_m, wa_m), srv_a = await _pipe_pair()
        (rb_m, wb_m), (rb_b, wb_b), srv_b = await _pipe_pair()
        ka, kb = ed25519.PrivKey.generate(), ed25519.PrivKey.generate()

        async def mitm():
            # leg 1: DH with A using the attacker's ephemeral
            e1_priv, e1_pub = x25519.generate_keypair()
            a_eph = await ra_m.readexactly(32)
            wa_m.write(e1_pub)
            await wa_m.drain()
            s1 = x25519.shared_secret(e1_priv, a_eph)
            lo, hi = sorted([e1_pub, a_eph])
            m1 = _hkdf_sha256(s1 + lo + hi, HKDF_INFO, 96)
            k1a, k1b = m1[:32], m1[32:64]
            # attacker's send key toward A mirrors A's recv key
            m_send1, m_recv1 = (k1b, k1a) if a_eph == lo else (k1a, k1b)
            # leg 2: DH with B
            e2_priv, e2_pub = x25519.generate_keypair()
            wb_m.write(e2_pub)
            await wb_m.drain()
            b_eph = await rb_m.readexactly(32)
            s2 = x25519.shared_secret(e2_priv, b_eph)
            lo2, hi2 = sorted([e2_pub, b_eph])
            m2 = _hkdf_sha256(s2 + lo2 + hi2, HKDF_INFO, 96)
            k2a, k2b = m2[:32], m2[32:64]
            m_send2, m_recv2 = (k2b, k2a) if b_eph == lo2 else (k2a, k2b)

            async def relay(r, w, recv_key, send_key):
                from tendermint_tpu.p2p.secret_connection import (
                    SEALED_FRAME_SIZE,
                )
                rn, sn = _Nonce(), _Nonce()
                try:
                    while True:
                        sealed = await r.readexactly(SEALED_FRAME_SIZE)
                        frame = _aead.open_(recv_key, rn.use(), sealed)
                        w.write(_aead.seal(send_key, sn.use(), frame))
                        await w.drain()
                except (asyncio.IncompleteReadError, ConnectionError):
                    pass

            await asyncio.gather(
                relay(ra_m, wb_m, m_recv1, m_send2),
                relay(rb_m, wa_m, m_recv2, m_send1),
                return_exceptions=True,
            )

        mt = asyncio.create_task(mitm())

        async def a_side():
            return await SecretConnection.make(ra_a, wa_a, ka)

        async def b_side():
            return await SecretConnection.make(rb_b, wb_b, kb)

        results = await asyncio.gather(
            a_side(), b_side(), return_exceptions=True
        )
        mt.cancel()
        srv_a.close(); srv_b.close()
        return results

    results = asyncio.run(run())
    for r in results:
        assert isinstance(r, ValueError), f"MITM not detected: {r!r}"
        assert "challenge auth failed" in str(r)


def test_mconn_multiplexing_priorities():
    async def run():
        (r1, w1), (r2, w2), server = await _pipe_pair()
        k1, k2 = ed25519.PrivKey.generate(), ed25519.PrivKey.generate()
        c1, c2 = await asyncio.gather(
            SecretConnection.make(r1, w1, k1),
            SecretConnection.make(r2, w2, k2),
        )
        got = asyncio.Queue()

        async def on_recv(ch, msg):
            await got.put((ch, msg))

        descs = [
            ChannelDescriptor(id=0x20, priority=5),
            ChannelDescriptor(id=0x21, priority=10),
        ]
        m1 = MConnection(c1, descs, lambda ch, m: asyncio.sleep(0))
        m2 = MConnection(c2, descs, on_recv)
        m1.start(); m2.start()
        # interleave channels; large message forces multi-packet reassembly
        big = b"B" * 5000
        assert m1.send(0x20, b"small")
        assert m1.send(0x21, big)
        seen = {}
        for _ in range(2):
            ch, msg = await asyncio.wait_for(got.get(), 5)
            seen[ch] = msg
        assert seen[0x20] == b"small"
        assert seen[0x21] == big
        await m1.stop(); await m2.stop(); server.close()

    asyncio.run(run())


def test_mconn_send_rate_throttling():
    """send_rate caps sustained throughput (reference connection.go
    flowrate Limit in sendRoutine): pushing ~3x the per-second budget
    must take measurably longer than an unthrottled send."""
    import time as _time

    async def run():
        (r1, w1), (r2, w2), server = await _pipe_pair()
        k1, k2 = ed25519.PrivKey.generate(), ed25519.PrivKey.generate()
        c1, c2 = await asyncio.gather(
            SecretConnection.make(r1, w1, k1),
            SecretConnection.make(r2, w2, k2),
        )
        got = asyncio.Queue()

        async def on_recv(ch, msg):
            await got.put(msg)

        descs = [ChannelDescriptor(id=0x20)]
        m1 = MConnection(c1, descs, lambda ch, m: asyncio.sleep(0),
                         send_rate=20000)
        m2 = MConnection(c2, descs, on_recv, recv_rate=0)
        m1.start(); m2.start()
        # 60 KB at a 20 kB/s cap: the token bucket's one-window burst
        # (20 KB) goes instantly, the remaining 40 KB must take >= ~2s
        payload = b"T" * 60000
        t0 = _time.monotonic()
        assert m1.send(0x20, payload)
        msg = await asyncio.wait_for(got.get(), 15)
        elapsed = _time.monotonic() - t0
        assert msg == payload
        assert elapsed > 1.0, f"send not throttled ({elapsed:.2f}s)"
        # sustained-rate property: burst + rate*elapsed bounds the bytes
        st = m1.send_monitor.status()
        assert st.bytes_total >= len(payload)
        assert st.bytes_total <= 20000 * (elapsed + 1.5) + 20000
        await m1.stop(); await m2.stop(); server.close()

    asyncio.run(run())


def _make_switch(name: str, reactors=None, network=NETWORK):
    nk = NodeKey.generate()
    transport = None
    sw = None

    def node_info():
        return NodeInfo(
            node_id=nk.id,
            listen_addr=f"127.0.0.1:{transport.listen_port}",
            network=network,
            moniker=name,
            channels=sw.channels() if sw else b"",
        )

    transport = MultiplexTransport(nk, node_info)
    sw = Switch(transport)
    for rname, r in (reactors or {}).items():
        sw.add_reactor(rname, r)
    return nk, transport, sw


class EchoReactor(Reactor):
    CH = 0x31

    def __init__(self):
        super().__init__("echo")
        self.received = asyncio.Queue()

    def get_channels(self):
        return [ChannelDescriptor(id=self.CH)]

    async def receive(self, channel_id, peer, msg):
        await self.received.put((peer.id, msg))


def test_switch_connect_and_route():
    async def run():
        e1, e2 = EchoReactor(), EchoReactor()
        nk1, t1, sw1 = _make_switch("n1", {"echo": e1})
        nk2, t2, sw2 = _make_switch("n2", {"echo": e2})
        await t1.listen(); await t2.listen()
        await sw1.start(); await sw2.start()
        peer = await sw1.dial_peer(
            NetAddress(nk2.id, "127.0.0.1", t2.listen_port)
        )
        assert peer is not None
        for _ in range(50):  # inbound side registers asynchronously
            if sw2.num_peers() == 1:
                break
            await asyncio.sleep(0.05)
        assert sw2.num_peers() == 1
        # route a message n1 -> n2 over the echo channel
        assert peer.send(EchoReactor.CH, b"ping over channel")
        pid, msg = await asyncio.wait_for(e2.received.get(), 5)
        assert pid == nk1.id and msg == b"ping over channel"
        # broadcast the other way
        sw2.broadcast(EchoReactor.CH, b"bcast")
        pid, msg = await asyncio.wait_for(e1.received.get(), 5)
        assert msg == b"bcast"
        await sw1.stop(); await sw2.stop()

    asyncio.run(run())


def test_switch_rejects_wrong_network():
    async def run():
        nk1, t1, sw1 = _make_switch("n1", network="chain-A")
        nk2, t2, sw2 = _make_switch("n2", network="chain-B")
        await t1.listen(); await t2.listen()
        await sw1.start(); await sw2.start()
        with pytest.raises(ValueError, match="network"):
            await sw1.dial_peer(NetAddress(nk2.id, "127.0.0.1", t2.listen_port))
        assert sw1.num_peers() == 0
        await sw1.stop(); await sw2.stop()

    asyncio.run(run())


def test_switch_detects_id_mismatch():
    async def run():
        nk1, t1, sw1 = _make_switch("n1")
        nk2, t2, sw2 = _make_switch("n2")
        await t1.listen(); await t2.listen()
        await sw1.start(); await sw2.start()
        wrong_id = NodeKey.generate().id
        with pytest.raises(ValueError, match="authenticated"):
            await sw1.dial_peer(
                NetAddress(wrong_id, "127.0.0.1", t2.listen_port)
            )
        await sw1.stop(); await sw2.stop()

    asyncio.run(run())


def test_addrbook_persistence(tmp_path):
    path = str(tmp_path / "addrbook.json")
    book = AddrBook(path, our_id="f" * 40)
    a1 = NetAddress("a" * 40, "10.0.0.1", 26656)
    a2 = NetAddress("b" * 40, "10.0.0.2", 26656)
    assert book.add_address(a1)
    assert not book.add_address(a1)  # dup
    assert book.add_address(a2)
    assert not book.add_address(NetAddress("f" * 40, "1.1.1.1", 1))  # self
    book.mark_good(a1.id)
    book.mark_attempt(a2.id)
    book.save()
    book2 = AddrBook(path, our_id="f" * 40)
    assert book2.size() == 2
    picked = book2.pick_address(exclude=set())
    assert picked is not None
    sel = book2.get_selection()
    assert len(sel) == 2


def test_pex_gossip_discovers_peers():
    """n3 knows only n1; n1 knows n2; pex spreads the addresses until n3
    connects to n2 as well."""

    async def run():
        books = [AddrBook() for _ in range(3)]
        pexes = [PEXReactor(books[i], target_outbound=5) for i in range(3)]
        nodes = [
            _make_switch(f"n{i}", {"pex": pexes[i]}) for i in range(3)
        ]
        for i, (nk, t, sw) in enumerate(nodes):
            books[i]._our_id = nk.id
            await t.listen()
            await sw.start()
        (nk1, t1, sw1), (nk2, t2, sw2), (nk3, t3, sw3) = nodes
        # seed address books
        books[0].add_address(NetAddress(nk2.id, "127.0.0.1", t2.listen_port))
        books[2].add_address(NetAddress(nk1.id, "127.0.0.1", t1.listen_port))
        for _ in range(100):
            if nk2.id in sw3.peers:
                break
            await asyncio.sleep(0.1)
        assert nk2.id in sw3.peers, "pex did not propagate n2's address to n3"
        for _, _, sw in nodes:
            await sw.stop()

    asyncio.run(run())


def test_addrbook_hashed_buckets_and_promotion(tmp_path):
    """Hashed-bucket address book (reference addrbook.go): placement is
    bucketed by keyed hash, markGood promotes NEW->OLD, old entries are
    only displaced by demotion, and the book round-trips through disk."""
    from tendermint_tpu.p2p.addrbook import AddrBook
    from tendermint_tpu.p2p.transport import NetAddress

    path = str(tmp_path / "addrbook.json")
    book = AddrBook(path, our_id="f" * 40)

    def addr(i, host="10.%d.%d.1"):
        nid = ("%040x" % i)
        return NetAddress.parse(f"{nid}@{host % (i % 250, i % 200)}:26656")

    src = addr(9999, host="172.16.%d.%d")
    for i in range(200):
        assert book.add_address(addr(i), src=src)
    assert book.size() == 200
    assert book.n_new() == 200 and book.n_old() == 0
    # addresses from ONE source group concentrate in <= 32 new buckets
    used = sum(1 for b in book._new if b)
    assert used <= 32, f"one source spread over {used} buckets"

    # re-adding our own id / duplicates is refused
    assert not book.add_address(
        NetAddress.parse(("f" * 40) + "@1.2.3.4:1"), src=src
    )

    # promotion: proven peers move to old buckets and survive floods
    for i in range(50):
        book.mark_good("%040x" % i)
    assert book.n_old() == 50
    for i in range(1000, 1400):
        book.add_address(addr(i), src=src)
    assert book.n_old() == 50  # flood displaced no proven peer

    # pick with heavy old bias returns a proven address
    picked = book.pick_address(exclude=set(), bias_new=0)
    assert picked is not None
    assert int(picked.id, 16) < 50

    book.save()
    book2 = AddrBook(path, our_id="f" * 40)
    assert book2.size() == book.size()
    assert book2.n_old() == 50


def test_trust_metric_pd_behavior():
    """PD trust metric (reference p2p/trust/metric.go): perfect history
    stays 1.0; bad bursts drop the score immediately (falling derivative
    weight 1.0); recovery is gradual through the integral term."""
    from tendermint_tpu.p2p.trust import TrustMetric, TrustMetricStore

    tm = TrustMetric()
    for _ in range(10):
        tm.good_event()
        tm.tick()
    assert tm.value() > 0.99

    # a burst of bad behavior: immediate drop below 0.6
    for _ in range(20):
        tm.bad_event()
    v_after_bad = tm.value()
    assert v_after_bad < 0.6
    tm.tick()

    # recovery is monotone but not instant
    vals = []
    for _ in range(6):
        for _ in range(5):
            tm.good_event()
        tm.tick()
        vals.append(tm.value())
    assert vals[-1] > vals[0] > v_after_bad
    assert vals[-1] < 1.0  # the bad interval still echoes in history

    # store: pause on disconnect freezes counting; persistence roundtrip
    store = TrustMetricStore()
    m = store.get_metric("peer1")
    m.bad_event()
    store.peer_disconnected("peer1")
    frozen = m.value()
    store.tick_all()
    assert m.value() == frozen
