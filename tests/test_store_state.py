"""Stores, state, executor, ABCI, and L2 bridge tests."""

import asyncio

import pytest

from tendermint_tpu.abci.client import LocalClient, SocketClient, SocketServer
from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.l2node.mock import MockL2Node
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.state import State
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.store.block_store import BlockStore
from tendermint_tpu.store.kv import MemKV, SqliteKV
from tendermint_tpu.types.block_id import BlockID

from .helpers import CHAIN_ID, T0, make_genesis, make_validators, sign_commit


# --- kv -------------------------------------------------------------------


@pytest.mark.parametrize("make_db", [MemKV, lambda: None])
def test_kv_roundtrip(make_db, tmp_path):
    db = make_db() or SqliteKV(str(tmp_path / "kv.db"))
    db.set(b"a", b"1")
    db.set(b"b", b"2")
    db.set(b"c", b"3")
    assert db.get(b"b") == b"2"
    assert db.get(b"zz") is None
    db.delete(b"b")
    assert db.get(b"b") is None
    db.write_batch([(b"d", b"4"), (b"e", b"5")], [b"a"])
    assert [k for k, _ in db.iterate()] == [b"c", b"d", b"e"]
    assert [k for k, _ in db.iterate(b"d")] == [b"d", b"e"]
    assert [k for k, _ in db.iterate(b"", b"d")] == [b"c"]
    db.close()


# --- chain fixture --------------------------------------------------------


def build_chain(n_blocks=3, n_vals=3):
    """A valid chain of blocks + commits via the executor-independent
    path: state transitions computed with a MockL2Node + kvstore app."""
    vs, pvs = make_validators(n_vals)
    genesis = make_genesis(vs)
    state = State.from_genesis(genesis)
    l2 = MockL2Node()
    app = KVStoreApplication()
    state_store = StateStore(MemKV())
    block_store = BlockStore(MemKV())
    executor = BlockExecutor(
        state_store, block_store, LocalClient(app), l2
    )

    async def run():
        nonlocal state
        res = await executor._app.init_chain(
            CHAIN_ID, {}, [], {}, genesis.initial_height
        )
        state.app_hash = res.app_hash
        state_store.bootstrap(state)
        last_commit = None
        blocks = []
        for h in range(1, n_blocks + 1):
            bd = l2.request_block_data(h)
            proposer = state.validators.get_proposer()
            block = executor.create_proposal_block(
                h, state, last_commit, proposer.address, bd, T0 + h * 10**9
            )
            ps = block.make_part_set()
            bid = BlockID(block.hash(), ps.header)
            seen_commit = sign_commit(vs, pvs, h, 0, bid, time_ns=T0 + h * 10**9)
            block_store.save_block(block, ps, seen_commit)
            state = await executor.apply_block(state, bid, block)
            blocks.append((block, bid, seen_commit))
            last_commit = seen_commit
        return blocks

    blocks = asyncio.run(run())
    return vs, pvs, state, block_store, state_store, blocks, l2, app


def test_executor_applies_chain():
    vs, pvs, state, block_store, state_store, blocks, l2, app = build_chain(3)
    assert state.last_block_height == 3
    assert block_store.height == 3 and block_store.base == 1
    assert len(l2.delivered) == 3
    # app executed the txs: one app commit per block
    assert app._height == 3
    # stored state round-trips
    loaded = state_store.load()
    assert loaded.last_block_height == 3
    assert loaded.validators.hash() == state.validators.hash()
    assert loaded.app_hash == state.app_hash
    # validator sets by height are retrievable
    assert state_store.load_validators(2).hash() == vs.hash()


def test_block_store_roundtrip_and_prune():
    vs, pvs, state, block_store, state_store, blocks, _, _ = build_chain(3)
    b2 = block_store.load_block(2)
    assert b2.hash() == blocks[1][0].hash()
    meta = block_store.load_block_meta(2)
    assert meta.block_id == blocks[1][1]
    assert block_store.load_seen_commit(2).hash() == blocks[1][2].hash()
    # commit for height 1 came from block 2's last_commit
    assert block_store.load_block_commit(1).hash() == blocks[0][2].hash()
    assert block_store.load_block_by_hash(b2.hash()).header.height == 2
    # prune below 3
    assert block_store.prune_blocks(3) == 2
    assert block_store.base == 3
    assert block_store.load_block(2) is None
    assert block_store.load_block(3) is not None


def test_block_store_rewind():
    _, _, _, block_store, _, blocks, _, _ = build_chain(3)
    assert block_store.prune_blocks_since(1) == 2
    assert block_store.height == 1
    assert block_store.load_block(2) is None
    assert block_store.load_block(1) is not None


def test_state_store_rollback():
    vs, pvs, state, block_store, state_store, blocks, _, _ = build_chain(3)
    rolled = state_store.rollback(block_store)
    assert rolled.last_block_height == 2
    assert rolled.app_hash == blocks[2][0].header.app_hash
    assert state_store.load().last_block_height == 2


def test_executor_rejects_invalid_block():
    vs, pvs, state, block_store, state_store, blocks, l2, app = build_chain(2)
    block, bid, _ = blocks[1]
    # replaying an old block against the new state must fail (wrong height)
    with pytest.raises(ValueError):
        asyncio.run(
            BlockExecutor(
                state_store, block_store, LocalClient(app), l2
            ).apply_block(state, bid, block)
        )


# --- abci socket ----------------------------------------------------------


def test_abci_socket_roundtrip():
    async def run():
        app = KVStoreApplication()
        server = SocketServer(app, port=0)
        await server.start()
        client = SocketClient(port=server.port)
        await client.connect()
        assert await client.echo("hi") == "hi"
        info = await client.info()
        assert info.data == "kvstore"
        r = await client.deliver_tx(b"k=v")
        assert r.is_ok()
        c = await client.commit()
        assert len(c.data) == 32
        q = await client.query("/key", b"k", 0, False)
        assert q.value == b"v"
        # pipelining: several in-flight calls keep FIFO order
        outs = await asyncio.gather(
            *(client.echo(f"m{i}") for i in range(5))
        )
        assert outs == [f"m{i}" for i in range(5)]
        await client.close()
        await server.stop()

    asyncio.run(run())


def test_abci_grpc_roundtrip():
    """Same surface as the socket transport, over gRPC (reference
    abci/client/grpc_client.go + abci/server/grpc_server.go)."""
    from tendermint_tpu.abci.grpc_transport import GRPCClient, GRPCServer

    async def run():
        app = KVStoreApplication()
        server = GRPCServer(app, port=0)
        await server.start()
        client = GRPCClient(port=server.port)
        await client.connect()
        assert await client.echo("hi") == "hi"
        info = await client.info()
        assert info.data == "kvstore"
        r = await client.deliver_tx(b"k=v")
        assert r.is_ok()
        c = await client.commit()
        assert len(c.data) == 32
        q = await client.query("/key", b"k", 0, False)
        assert q.value == b"v"
        # concurrent in-flight calls (grpc multiplexes; results line up)
        outs = await asyncio.gather(
            *(client.echo(f"m{i}") for i in range(5))
        )
        assert outs == [f"m{i}" for i in range(5)]
        # snapshot methods cross the wire too
        snaps = await client.list_snapshots()
        assert isinstance(snaps, list)
        # app-side exceptions surface as clean client errors
        import pytest as _pytest

        with _pytest.raises(RuntimeError):
            await client.call("info", "unexpected-extra-arg")
        await client.close()
        await server.stop()

    asyncio.run(run())


def test_abci_grpc_via_proxy_appconns():
    """AppConns over the grpc creator: three named connections against
    one external app process (proxy/multi_app_conn.py)."""
    from tendermint_tpu.abci.grpc_transport import (
        GRPCServer,
        grpc_client_creator,
    )
    from tendermint_tpu.proxy.multi_app_conn import AppConns

    async def run():
        app = KVStoreApplication()
        server = GRPCServer(app, port=0)
        await server.start()
        conns = AppConns(grpc_client_creator("127.0.0.1", server.port))
        await conns.start()
        assert (await conns.consensus.info()).data == "kvstore"
        r = await conns.consensus.deliver_tx(b"x=y")
        assert r.is_ok()
        q = await conns.query.query("/key", b"x", 0, False)
        assert q.value == b"y"
        assert isinstance(await conns.snapshot.list_snapshots(), list)
        await conns.stop()
        await server.stop()

    asyncio.run(run())


# --- l2 mock batching -----------------------------------------------------


def test_mock_l2_batching():
    l2 = MockL2Node(batch_blocks_interval=3)
    assert not l2.calculate_batch_size_with_proposal_block(b"b1", False)
    l2.pack_current_block(b"b1")
    l2.pack_current_block(b"b2")
    # third block hits the interval -> batch point
    assert l2.calculate_batch_size_with_proposal_block(b"b3", False)
    h, header = l2.seal_batch()
    assert l2.batch_hash(header) == h
    l2.commit_batch(b"b3", [])
    assert len(l2.committed_batches) == 1
    assert l2.open_batch_blocks == [b"b3"]


def test_sql_event_sink_schema_and_idempotency():
    """Reference psql sink parity (state/indexer/sink/psql): relational
    schema incl. the joined views, idempotent re-indexing, and plain-SQL
    queryability; sqlite3 stands in for the postgres driver (PEP 249)."""
    import pytest as _pytest

    from tendermint_tpu.state.sink_sql import SQLEventSink
    from tendermint_tpu.state.txindex import TxResult

    sink = SQLEventSink(chain_id="sink-chain")
    sink.index_block(
        5,
        [("block_header", [("num_txs", "2"), ("proposer", "AA")])],
    )
    # idempotent: re-index of the same height is a no-op
    sink.index_block(5, [("block_header", [("num_txs", "999")])])

    res = TxResult(height=5, index=0, tx=b"k=v", code=0, log="", events=[])
    sink.index_tx(res, [("transfer", [("sender", "alice")])])
    sink.index_tx(res, [("transfer", [("sender", "mallory")])])  # no-op

    cur = sink._conn.cursor()
    cur.execute("SELECT COUNT(*) FROM blocks")
    assert cur.fetchone()[0] == 1
    cur.execute("SELECT height, type, key, value FROM block_events")
    rows = cur.fetchall()
    assert ("5", "block_header", "num_txs", "2") == tuple(
        str(c) for c in rows[0]
    )
    cur.execute("SELECT key, value, composite_key FROM tx_events")
    assert cur.fetchall() == [("sender", "alice", "transfer.sender")]
    with _pytest.raises(NotImplementedError):
        sink.search_txs("q")
    sink.close()
