"""Runnable node: config, init, assembly, RPC queries, CLI.

Reference: node/node_test.go + rpc tests, compressed: a node must init
from files, run a kvstore chain, and answer the core RPC routes.
"""

import asyncio
import json
import os

import pytest

from tendermint_tpu.config import Config
from tendermint_tpu.node import Node, init_files
from tendermint_tpu.rpc.light_provider import RPCClient


def make_test_config(tmp_path, **consensus_overrides) -> Config:
    cfg = Config.test_config()
    cfg.root_dir = str(tmp_path)
    cfg.rpc.laddr = "tcp://127.0.0.1:0"  # ephemeral port
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    for k, v in consensus_overrides.items():
        setattr(cfg.consensus, k, v)
    return cfg


def test_config_toml_roundtrip(tmp_path):
    cfg = make_test_config(tmp_path)
    cfg.consensus.switch_height = 77
    cfg.p2p.persistent_peers = "id1@1.2.3.4:26656"
    path = cfg.save()
    assert os.path.exists(path)
    loaded = Config.load(str(tmp_path))
    assert loaded.consensus.switch_height == 77
    assert loaded.p2p.persistent_peers == "id1@1.2.3.4:26656"
    assert loaded.consensus.timeout_commit == cfg.consensus.timeout_commit
    loaded.validate_basic()


def test_init_files_idempotent(tmp_path):
    cfg = make_test_config(tmp_path)
    doc1 = init_files(cfg)
    doc2 = init_files(cfg)  # second run loads, not regenerates
    assert doc1.chain_id == doc2.chain_id
    assert os.path.exists(cfg.genesis_file)
    assert os.path.exists(cfg.node_key_file)
    assert os.path.exists(cfg.priv_validator_key_file)


def test_node_runs_chain_and_serves_rpc(tmp_path):
    """The VERDICT item-7 'done' criterion: init && start runs a kvstore
    chain queryable over /status, /block (+ abci_query, validators...)."""
    cfg = make_test_config(tmp_path)
    init_files(cfg)
    node = Node(cfg)

    async def run():
        await node.start()
        await node.consensus.wait_for_height(3, timeout=60)
        rpc = RPCClient(f"127.0.0.1:{node.rpc_server.port}")

        status = await rpc.call("status")
        assert status["sync_info"]["latest_block_height"] >= 3
        assert status["node_info"]["id"] == node.node_key.id

        block = await rpc.call("block", height=2)
        assert block["block"]["header"]["height"] == 2
        got_hash = block["block_id"]["hash"]

        byhash = await rpc.call("block_by_hash", hash=got_hash)
        assert byhash["block"]["header"]["height"] == 2

        vals = await rpc.call("validators", height=2)
        assert vals["count"] == 1

        commit = await rpc.call("commit", height=2)
        assert commit["signed_header"]["commit"]["height"] == 2

        abci = await rpc.call("abci_info")
        assert abci["response"]["data"] == "kvstore"

        # health carries identity + verdict now (PR 11), not the
        # reference's `{}` stub
        h = await rpc.call("health")
        assert h["node_id"] == node.node_key.id
        assert int(h["latest_block_height"]) >= 3
        assert h["catching_up"] is False
        assert h["monitored"] is True
        assert h["status"] in ("ok", "warn", "critical")

        gen = await rpc.call("genesis")
        assert gen["genesis"]["chain_id"] == node.genesis.chain_id

        bc = await rpc.call("blockchain")
        assert bc["last_height"] >= 3 and bc["block_metas"]

        cp = await rpc.call("consensus_params")
        assert cp["consensus_params"]["evidence"]["max_age_num_blocks"] > 0

        hdr = await rpc.call("header", height=2)
        assert hdr["header"]["height"] == 2

        hdr2 = await rpc.call("header_by_hash", hash=got_hash)
        assert hdr2["header"]["height"] == 2

        gc = await rpc.call("genesis_chunked", chunk=0)
        assert gc["chunk"] == 0 and gc["total"] >= 1
        import base64 as _b64
        import json as _json

        joined = b""
        for i in range(gc["total"]):
            part = await rpc.call("genesis_chunked", chunk=i)
            joined += _b64.b64decode(part["data"])
        assert _json.loads(joined)["chain_id"] == node.genesis.chain_id

        # unsafe routes are absent unless rpc.unsafe is set
        try:
            await rpc.call("dial_peers", peers=[])
            raised = False
        except Exception:
            raised = True
        assert raised

        await node.stop()

    asyncio.run(run())


def test_node_tx_indexing_and_search(tmp_path):
    """Txs committed by the chain are indexed and searchable
    (state/txindex; reference tx_search route)."""
    cfg = make_test_config(tmp_path)
    init_files(cfg)
    node = Node(cfg)
    node.l2_node.inject_txs([b"alpha=1", b"bravo=2"])

    async def run():
        await node.start()
        await node.consensus.wait_for_height(3, timeout=60)
        await asyncio.sleep(0.2)  # indexer drains the event bus
        rpc = RPCClient(f"127.0.0.1:{node.rpc_server.port}")
        res = await rpc.call("tx_search", query="app.creator=kvstore")
        assert res["total_count"] > 0
        one = res["txs"][0]
        got = await rpc.call("tx", hash=one["hash"])
        assert got["height"] == one["height"]
        # the committed tx is queryable through the app too
        q = await rpc.call("abci_query", path="", data=b"alpha".hex())
        assert bytes.fromhex(q["response"]["value"]) == b"1"
        await node.stop()

    asyncio.run(run())


def test_node_restart_resumes(tmp_path):
    """Stop at height>=2, restart from disk, continue the chain
    (handshake/replay + durable stores; reference replay tests)."""
    cfg = make_test_config(tmp_path)
    init_files(cfg)
    cfg.base.db_backend = "sqlite"

    async def run1():
        node = Node(cfg)
        await node.start()
        await node.consensus.wait_for_height(2, timeout=60)
        await node.stop()
        return node.block_store.height

    h1 = asyncio.run(run1())
    assert h1 >= 2

    async def run2():
        node = Node(cfg)
        await node.start()
        await node.consensus.wait_for_height(h1 + 2, timeout=60)
        await node.stop()
        return node.block_store.height

    h2 = asyncio.run(run2())
    assert h2 >= h1 + 2


def test_websocket_subscription(tmp_path):
    """ws subscribe to NewBlock events (reference ws_handler + subscribe
    route)."""
    cfg = make_test_config(tmp_path)
    init_files(cfg)
    node = Node(cfg)

    async def run():
        await node.start()
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", node.rpc_server.port
        )
        # ws handshake
        writer.write(
            b"GET /websocket HTTP/1.1\r\nHost: x\r\nUpgrade: websocket\r\n"
            b"Connection: Upgrade\r\nSec-WebSocket-Key: dGhlIHNhbXBsZQ==\r\n"
            b"Sec-WebSocket-Version: 13\r\n\r\n"
        )
        await writer.drain()
        line = await reader.readline()
        assert b"101" in line
        while (await reader.readline()) not in (b"\r\n", b"\n", b""):
            pass
        # subscribe to new blocks

        def frame(payload: bytes) -> bytes:
            # client frames must be masked
            import os as _os
            import struct

            mask = _os.urandom(4)
            masked = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
            n = len(payload)
            assert n < 126
            return bytes([0x81, 0x80 | n]) + mask + masked

        sub = json.dumps(
            {
                "jsonrpc": "2.0",
                "id": 1,
                "method": "subscribe",
                "params": {"query": "tm.event = 'NewBlock'"},
            }
        ).encode()
        writer.write(frame(sub))
        await writer.drain()

        async def read_ws_json():
            h = await reader.readexactly(2)
            n = h[1] & 0x7F
            if n == 126:
                import struct

                n = struct.unpack(">H", await reader.readexactly(2))[0]
            payload = await reader.readexactly(n)
            return json.loads(payload)

        ack = await read_ws_json()
        assert ack["id"] == 1
        ev = await asyncio.wait_for(read_ws_json(), 30)
        assert ev["result"]["query"] == "tm.event = 'NewBlock'"
        assert ev["result"]["data"]["type"] == "block"
        writer.close()
        await node.stop()

    asyncio.run(run())


def test_cli_commands(tmp_path):
    from tendermint_tpu.__main__ import main

    home = str(tmp_path / "clihome")
    assert main(["--home", home, "init", "--chain-id", "cli-chain"]) == 0
    assert os.path.exists(os.path.join(home, "config", "genesis.json"))
    assert os.path.exists(os.path.join(home, "config", "config.toml"))
    assert main(["--home", home, "show-node-id"]) == 0
    assert main(["--home", home, "show-validator"]) == 0
    assert main(["--home", home, "version"]) == 0
    out = str(tmp_path / "net")
    assert main(["--home", home, "testnet", "--v", "3", "--output", out]) == 0
    for i in range(3):
        assert os.path.exists(
            os.path.join(out, f"node{i}", "config", "genesis.json")
        )
    # all three nodes share one genesis
    docs = {
        open(os.path.join(out, f"node{i}", "config", "genesis.json")).read()
        for i in range(3)
    }
    assert len(docs) == 1
    assert main(["--home", home, "unsafe-reset-all"]) == 0


def test_prometheus_metrics_served(tmp_path):
    """Consensus metrics exposed in text exposition format
    (reference node.go:1062-1065 prometheus server)."""
    cfg = make_test_config(tmp_path)
    cfg.instrumentation.prometheus = True
    cfg.instrumentation.prometheus_listen_addr = "127.0.0.1:0"
    init_files(cfg)
    node = Node(cfg)

    async def run():
        await node.start()
        await node.consensus.wait_for_height(2, timeout=60)
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", node.metrics_server.port
        )
        writer.write(b"GET /metrics HTTP/1.1\r\nHost: m\r\n\r\n")
        await writer.drain()
        data = await reader.read(65536)
        writer.close()
        await node.stop()
        return data.decode()

    body = asyncio.run(run())
    assert "tendermint_consensus_height" in body
    # the height gauge tracked the chain
    line = [
        ln for ln in body.splitlines()
        if ln.startswith("tendermint_consensus_height ")
    ][0]
    assert float(line.split()[-1]) >= 2


def test_node_commits_batch_point_with_bls(tmp_path):
    """VERDICT r2 item-1 'done' criterion: an ASSEMBLED Node (not a
    hand-wired ConsensusState) dual-signs batch-point precommits with the
    BLS key loaded from config.bls_key_file, the L2 node verifies them,
    and CommitBatch receives BLS data whose aggregate verifies."""
    from tendermint_tpu.crypto import bls_signatures as bls
    from tendermint_tpu.l2node.mock import MockL2Node
    from tendermint_tpu.privval.file_pv import FilePV

    cfg = make_test_config(tmp_path)
    init_files(cfg)

    # the L2 side knows the staked BLS keys ahead of time (the real Morph
    # node resolves them from the sequencer-set contract)
    key = bls.load_or_gen_bls_key(cfg.bls_key_file)
    pv = FilePV.load_or_generate(
        cfg.priv_validator_key_file, cfg.priv_validator_state_file
    )
    registry = bls.BLSKeyRegistry()
    registry.register(
        pv.get_pub_key().data,
        bls.public_key_from_bytes(key.pub_key, trusted_source=True),
    )
    l2 = MockL2Node(
        batch_blocks_interval=2,
        bls_verifier=registry.verifier(),
        bls_batch_verifier=registry.batch_verifier(),
    )
    node = Node(cfg, l2_node=l2)

    async def run():
        await node.start()
        try:
            await node.consensus.wait_for_height(4, timeout=90)
        finally:
            await node.stop()

    asyncio.run(run())

    assert l2.committed_batches, "no batch committed through the node"
    batch_hash, bls_datas = l2.committed_batches[0]
    assert bls_datas, "batch committed without BLS data"
    pub = bls.public_key_from_bytes(key.pub_key, trusted_source=True)
    sigs = [bls.g1_from_bytes(d.signature) for d in bls_datas]
    agg = bls.aggregate_signatures(sigs)
    assert bls.verify_aggregated_same_message(
        agg, batch_hash, [pub] * len(sigs)
    )


def test_node_upgrade_switch_to_sequencer(tmp_path):
    """The assembled Node's upgrade path (reference node.go + upgrade/):
    BFT commits up to switch_height, consensus stops, and StateV2 takes
    over producing BlockV2s through the same L2 node."""
    from tendermint_tpu.crypto import secp256k1

    cfg = make_test_config(tmp_path, switch_height=3)
    # sequencer identity: a local secp key this node signs V2 blocks with
    key = secp256k1.PrivKey.from_secret(b"seq-node-test")
    os.makedirs(str(tmp_path / "config"), exist_ok=True)
    with open(str(tmp_path / "config" / "sequencer_key"), "w") as f:
        f.write(key.bytes().hex())
    cfg.sequencer.sequencer_key_file = "config/sequencer_key"
    cfg.sequencer.block_interval = 0.1
    init_files(cfg)
    node = Node(cfg)

    async def run():
        await node.start()
        try:
            await node.consensus.wait_for_height(3, timeout=60)
            # the switch fires on the commit of switch_height; wait for
            # sequencer mode + at least 2 produced V2 blocks
            for _ in range(200):
                if (
                    node.sequencer_reactor.sequencer_started
                    and node.state_v2.latest_height()
                    >= node.consensus.state.last_block_height + 2
                ):
                    break
                await asyncio.sleep(0.1)
            assert node.sequencer_reactor.sequencer_started, (
                "sequencer routines never started after switch_height"
            )
            assert not node.consensus.is_running
            assert node.state_v2.is_sequencer_mode()
            assert (
                node.state_v2.latest_height()
                >= node.consensus.state.last_block_height + 2
            ), "no V2 blocks produced after the switch"
        finally:
            await node.stop()

    asyncio.run(run())


def test_tpu_config_section_roundtrip_and_validation(tmp_path):
    """[tpu] mesh axes are first-class config (SURVEY §2.3): TOML
    roundtrip + validate_basic constraints."""
    cfg = make_test_config(tmp_path)
    cfg.tpu.ici_parallelism = 8
    cfg.tpu.dcn_parallelism = 2
    cfg.tpu.mesh_backend = "cpu"
    cfg.tpu.coordinator_address = "10.0.0.1:1234"
    cfg.tpu.num_processes = 2
    cfg.tpu.process_id = 1
    cfg.validate_basic()
    cfg.save()
    loaded = Config.load(str(tmp_path))
    assert loaded.tpu.ici_parallelism == 8
    assert loaded.tpu.dcn_parallelism == 2
    assert loaded.tpu.mesh_backend == "cpu"
    assert loaded.tpu.coordinator_address == "10.0.0.1:1234"
    assert loaded.tpu.num_processes == 2 and loaded.tpu.process_id == 1

    import pytest as _pytest

    bad = make_test_config(tmp_path)
    bad.tpu.ici_parallelism = -1
    with _pytest.raises(ValueError):
        bad.tpu.validate_basic()
    bad = make_test_config(tmp_path)
    bad.tpu.dcn_parallelism = 2
    bad.tpu.num_processes = 2
    bad.tpu.coordinator_address = ""
    with _pytest.raises(ValueError):
        bad.tpu.validate_basic()
    bad = make_test_config(tmp_path)
    bad.tpu.num_processes = 2
    bad.tpu.process_id = 2
    with _pytest.raises(ValueError):
        bad.tpu.validate_basic()
