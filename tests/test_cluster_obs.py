"""Cluster observability: peer clock offsets, trace merge, quorum
attribution.

Acceptance surface of the cluster-tracing PR: the timestamped ping/pong
produces per-peer NTP offset/RTT estimates, `obs.cluster` merges
per-validator dumps onto one timeline via minimum-RTT offset paths (so a
biased link can't skew the merge), and on a live 4-validator net with a
chaos-injected 50 ms one-way delay on a single link the merged report
estimates every node's offset within ±10 ms and names the delayed
link — and the validator behind it — as the quorum-closing straggler.
"""

import asyncio
import json
import struct
import subprocess
import sys
import time

import pytest

from tendermint_tpu import obs
from tendermint_tpu.libs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricCardinalityError,
    OTHER_LABEL,
    bounded_label,
)
from tendermint_tpu.p2p.mconn import MConnection, _PONG_FMT

pytestmark = pytest.mark.obs


# --- tracer wall-anchor re-anchoring (drift bound) -------------------------


def test_tracer_reanchor_bounds_drift():
    t = obs.Tracer(enabled=True, reanchor_interval_s=0.01)
    # simulate 5 s of accumulated monotonic-vs-wall drift in the anchor
    t.epoch_wall_ns -= 5_000_000_000
    time.sleep(0.02)
    t.event("tick")  # recording path must refresh the stale anchor
    reconstructed = t.epoch_wall_ns + int(
        (time.perf_counter() - t.epoch) * 1e9
    )
    assert abs(reconstructed - time.time_ns()) < 100_000_000  # < 100 ms
    assert t.wall_anchor_age_s() < 1.0


def test_tracer_reanchor_manual_and_disabled():
    t = obs.Tracer(enabled=True, reanchor_interval_s=0.0)  # auto off
    t.epoch_wall_ns -= 5_000_000_000
    t.event("tick")
    drift = abs(
        t.epoch_wall_ns
        + int((time.perf_counter() - t.epoch) * 1e9)
        - time.time_ns()
    )
    assert drift > 4_000_000_000  # interval 0 never re-anchors
    t.reanchor()
    drift = abs(
        t.epoch_wall_ns
        + int((time.perf_counter() - t.epoch) * 1e9)
        - time.time_ns()
    )
    assert drift < 100_000_000


# --- label-cardinality bounding --------------------------------------------


def test_metric_cardinality_cap_raises():
    c = Counter("t_card_counter", "h", ("x",), max_series=3)
    for i in range(3):
        c.inc(x=str(i))
    with pytest.raises(MetricCardinalityError) as ei:
        c.inc(x="overflow")
    assert "t_card_counter" in str(ei.value)
    c.inc(x="0")  # existing series still fine
    assert c.value(x="0") == 2.0

    g = Gauge("t_card_gauge", "h", ("x",), max_series=2)
    g.set(1, x="a")
    g.set(2, x="b")
    with pytest.raises(MetricCardinalityError):
        g.set(3, x="c")

    h = Histogram("t_card_hist", "h", labels=("x",), max_series=2)
    h.observe(0.1, x="a")
    h.observe(0.2, x="b")
    with pytest.raises(MetricCardinalityError):
        h.observe(0.3, x="c")
    # unlabeled metrics are a single series: never capped
    u = Counter("t_card_plain", "h", max_series=1)
    for _ in range(5):
        u.inc()


def test_bounded_label_topk():
    fam = "t_bounded_label_family"
    assert bounded_label(fam, "p1", k=2) == "p1"
    assert bounded_label(fam, "p2", k=2) == "p2"
    assert bounded_label(fam, "p3", k=2) == OTHER_LABEL
    # admitted values stay admitted; the long tail shares one bucket
    assert bounded_label(fam, "p1", k=2) == "p1"
    assert bounded_label(fam, "p4", k=2) == OTHER_LABEL


# --- mconn NTP sample math -------------------------------------------------


def test_mconn_pong_sample_math():
    mc = MConnection(None, [], None, peer_id="")
    t1w, t1m = time.time_ns(), time.perf_counter_ns()
    offset_ns = 500_000_000  # peer clock runs 0.5 s ahead
    t2 = t1w + offset_ns
    t3 = t2
    mc._on_pong(struct.pack(_PONG_FMT, t1w, t1m, t2, t3))
    assert mc.clock_samples == 1
    assert 0.4 < mc.clock_offset_s < 0.6
    assert 0.0 <= mc.rtt_s < 0.1
    # EWMA folds further samples instead of replacing
    t1w, t1m = time.time_ns(), time.perf_counter_ns()
    t2 = t1w + offset_ns
    mc._on_pong(struct.pack(_PONG_FMT, t1w, t1m, t2, t2))
    assert mc.clock_samples == 2
    assert 0.4 < mc.clock_offset_s < 0.6
    # short/legacy payloads are ignored, not an error
    mc._on_pong(b"")
    mc._on_pong(b"\x00" * 8)
    assert mc.clock_samples == 2
    # the min-RTT clock filter is a sliding window: a wall-clock step
    # ages out of the filter instead of pinning a stale offset forever
    for _ in range(20):
        t1w, t1m = time.time_ns(), time.perf_counter_ns()
        mc._on_pong(struct.pack(_PONG_FMT, t1w, t1m, t1w, t1w))  # offset ~0
    assert abs(mc.min_rtt_offset_s) < 0.1  # the 0.5 s samples expired
    # a pre-extension ping gets a bare pong; a stamped one gets echoes
    assert mc._pong_packet(b"") == bytes([0xFF, 1])
    stamped = mc._pong_packet(struct.pack("<qq", t1w, t1m))
    assert len(stamped) == 2 + struct.calcsize(_PONG_FMT)
    e1w, e1m, e2, e3 = struct.unpack_from(_PONG_FMT, stamped[2:])
    assert (e1w, e1m) == (t1w, t1m) and e2 <= e3


# --- offset estimation: min-RTT paths route around a biased link -----------


def _dump(node_id, records=(), peer_clock=None, epoch_wall_ns=0, name=""):
    return obs.normalize_dump(
        {
            "node_id": node_id,
            "moniker": name or node_id,
            "epoch_wall_ns": epoch_wall_ns,
            "records": list(records),
            "peer_clock": peer_clock or {},
        }
    )


def test_estimate_offsets_min_rtt_path_avoids_biased_link():
    # direct A-B link has 50 ms asymmetric delay: its NTP estimate is
    # biased +25 ms; the clean A-C-B path must win
    a = _dump(
        "A",
        peer_clock={
            "B": {"offset_s": 0.025, "rtt_s": 0.100, "samples": 9},
            "C": {"offset_s": 0.0005, "rtt_s": 0.002, "samples": 9},
        },
    )
    b = _dump(
        "B",
        peer_clock={"A": {"offset_s": -0.025, "rtt_s": 0.100, "samples": 9}},
    )
    c = _dump(
        "C",
        peer_clock={"B": {"offset_s": -0.0002, "rtt_s": 0.002, "samples": 9}},
    )
    offs = obs.estimate_offsets([a, b, c])
    assert offs["A"]["source"] == "reference"
    assert offs["B"]["source"] == "ntp_graph" and offs["B"]["hops"] == 2
    assert abs(offs["B"]["offset_s"]) < 0.005  # NOT the 25 ms direct bias
    assert abs(offs["C"]["offset_s"]) < 0.005
    # a node with no NTP path falls back to its wall anchor
    d = _dump("D")
    offs = obs.estimate_offsets([a, b, c, d])
    assert offs["D"]["source"] == "wall_anchor"


def test_merge_records_rebases_onto_reference_timeline():
    # same instant seen by two nodes whose tracers were born 1 s apart
    # and whose clocks differ by a known offset
    rec = {"name": "x", "t0": 2.0, "dur": 0.0, "height": 1, "round": 0,
           "kind": "event"}
    a = _dump("A", [rec], epoch_wall_ns=10_000_000_000)
    b = _dump(
        "B",
        [dict(rec, t0=0.5)],
        # B's ring started 1.5 s after A's (true time) and B's clock
        # runs 0.25 s ahead: t0=0.5 on B is the same true instant as
        # t0=2.0 on A
        epoch_wall_ns=11_500_000_000 + 250_000_000,
        peer_clock={"A": {"offset_s": -0.25, "rtt_s": 0.001, "samples": 5}},
    )
    _, offsets, merged = obs.merge_records([a, b])
    assert abs(offsets["B"]["offset_s"] - 0.25) < 1e-6
    t_by_node = {m["node"]: m["t0"] for m in merged}
    assert abs(t_by_node["A"] - t_by_node["B"]) < 1e-6


# --- cluster-report JSON schema (golden) -----------------------------------

REPORT_KEYS = {
    "schema", "reference", "nodes", "offsets", "heights", "links",
    "stragglers", "verify_flow",
}
NODE_KEYS = {"name", "node_id", "records"}
OFFSET_KEYS = {"offset_s", "rtt_s", "hops", "source"}
HEIGHT_KEYS = {"proposer", "proposal_gossip_ms", "quorum_close", "slowest"}
SLOWEST_KEYS = {"node", "closer_index", "close_lag_ms", "commit_wait_ms"}
QUORUM_KEYS = {"closer_index", "close_lag_ms", "round"}
LINK_KEYS = {
    "src", "dst", "min_lag_ms", "median_lag_ms", "p95_lag_ms", "samples",
}
STRAGGLER_KEYS = {
    "validator_index", "quorum_closes", "close_share",
    "median_close_lag_ms", "median_arrival_lag_ms",
}


def _synthetic_dumps():
    def ev(name, t0, h, **fields):
        return {"name": name, "t0": t0, "dur": 0.0, "height": h,
                "round": 0, "kind": "event", "fields": fields}

    a_recs = [
        ev("gossip.send", 1.00, 1, type="proposal", peer="*"),
        ev("quorum.vote", 1.02, 1, type="precommit", val=0, lag_ms=0.0),
        ev("quorum.close", 1.04, 1, type="precommit", closer=1,
           lag_ms=20.0),
    ]
    b_recs = [
        ev("gossip.recv", 1.01, 1, type="proposal", peer="A"),
        ev("quorum.vote", 1.03, 1, type="precommit", val=1, lag_ms=0.0),
        ev("quorum.close", 1.09, 1, type="precommit", closer=0,
           lag_ms=60.0),
    ]
    a = _dump("A", a_recs, peer_clock={
        "B": {"offset_s": 0.0, "rtt_s": 0.002, "samples": 4}
    })
    b = _dump("B", b_recs)
    return [a, b]


def test_merge_dedupes_duplicate_monikers():
    # fleet config templates often stamp every node with one moniker;
    # report keys must stay distinct or offsets/links silently collide
    a = _dump("A", name="val")
    b = _dump(
        "B",
        name="val",
        peer_clock={"A": {"offset_s": 0.1, "rtt_s": 0.001, "samples": 3}},
    )
    report = obs.cluster_report([a, b])
    assert sorted(report["offsets"]) == ["val", "val#2"]
    assert report["offsets"]["val"]["source"] == "reference"
    assert abs(report["offsets"]["val#2"]["offset_s"] + 0.1) < 1e-9


def test_cluster_report_schema_golden():
    report = obs.cluster_report(_synthetic_dumps())
    assert set(report) == REPORT_KEYS
    assert report["schema"] == "tm-tpu/cluster-report/v2"
    assert report["reference"] == "A"
    assert [set(n) for n in report["nodes"]] == [NODE_KEYS, NODE_KEYS]
    assert all(set(o) == OFFSET_KEYS for o in report["offsets"].values())
    assert set(report["heights"]) == {"1"}
    h1 = report["heights"]["1"]
    assert set(h1) == HEIGHT_KEYS
    assert h1["proposer"] == "A"
    assert set(h1["slowest"]) == SLOWEST_KEYS
    assert all(set(q) == QUORUM_KEYS for q in h1["quorum_close"].values())
    assert h1["slowest"]["node"] == "B"
    assert h1["slowest"]["closer_index"] == 0
    assert [set(l) for l in report["links"]] == [LINK_KEYS]
    assert report["links"][0]["src"] == "A"
    assert report["links"][0]["dst"] == "B"
    assert report["links"][0]["median_lag_ms"] == pytest.approx(10.0)
    assert all(set(s) == STRAGGLER_KEYS for s in report["stragglers"])
    # report_text renders without error and names the straggler
    text = obs.report_text(report)
    assert "cluster report" in text and "val" in text
    # the report round-trips through JSON (soak artifact requirement)
    assert json.loads(json.dumps(report)) == report


# --- the live-net acceptance test ------------------------------------------


def test_cluster_trace_recovers_injected_delay(tmp_path):
    """4 validators over real encrypted p2p; chaos injects a 50 ms
    ONE-WAY delay on the single link heavy->victim, where the heavy
    validator's vote is required by every 2/3 quorum (voting powers
    40/20/20/20). The merged cluster report must (a) estimate every
    node's clock offset within ±10 ms — the min-RTT offset paths must
    route AROUND the delayed link, whose direct NTP estimate is biased
    by ~25 ms — (b) rank heavy->victim as the slowest link at ~50 ms,
    and (c) name the heavy validator as the victim's quorum-closing
    straggler."""
    from tendermint_tpu.chaos.link import LinkPolicy
    from tendermint_tpu.chaos.network import ChaosNetwork

    from .chaos_harness import (
        build_chaos_handles,
        node_dump,
        start_mesh,
        stop_mesh,
    )

    handles = build_chaos_handles(
        tracer_factory=lambda name: obs.Tracer(enabled=True),
        ping_interval=0.15,
        powers=(40, 20, 20, 20),
    )
    vals = handles[0].cs.state.validators.validators
    heavy_idx = max(range(len(vals)), key=lambda i: vals[i].voting_power)
    victim_idx = (heavy_idx + 1) % len(handles)
    heavy, victim = f"n{heavy_idx}", f"n{victim_idx}"

    async def run():
        net = ChaosNetwork(seed=5)
        for h in handles:
            net.install(h)
        await start_mesh(handles)
        try:
            # warm up first (jit compiles, ping samples on every link),
            # THEN inject the delay and clear the rings so the analyzed
            # records are all from the degraded regime
            await asyncio.gather(
                *(h.cs.wait_for_height(2, timeout=60) for h in handles)
            )
            await asyncio.sleep(0.8)
            net.set_link_policy(
                heavy, victim,
                LinkPolicy(latency_s=0.05),
                reverse=LinkPolicy(),
            )
            for h in handles:
                h.cs.tracer.clear()
            # 6 more heights in the degraded regime, wherever the
            # warmup left the chain
            h_clear = max(h.cs.state.last_block_height for h in handles)
            await asyncio.gather(
                *(
                    h.cs.wait_for_height(h_clear + 6, timeout=60)
                    for h in handles
                )
            )
            return h_clear, [node_dump(h) for h in handles]
        finally:
            await stop_mesh(handles)

    h_clear, raw_dumps = asyncio.run(run())
    # heights straddling the ring clear have partial record sets (a
    # receive whose send was erased); analyze only fully-traced heights
    for d in raw_dumps:
        d["records"] = [
            r
            for r in d["records"]
            if r.get("height", 0) == 0 or r["height"] >= h_clear + 2
        ]
    dumps = [obs.normalize_dump(d) for d in raw_dumps]
    report = obs.cluster_report(dumps)

    # (a) offsets: true offset is 0 (one process, one clock); estimates
    # must come out within ±10 ms DESPITE the 50 ms asymmetric link
    for name, off in report["offsets"].items():
        assert abs(off["offset_s"]) < 0.010, (name, off)
    # every non-reference node found an NTP path
    ntp = [o for o in report["offsets"].values() if o["source"] == "ntp_graph"]
    assert len(ntp) == len(handles) - 1

    # (b) the delayed link tops the one-way link ranking, with the
    # min-lag propagation estimate recovering the injected 50 ms
    links = report["links"]
    assert links, "no gossip send/recv pairs joined"
    top = links[0]
    assert (top["src"], top["dst"]) == (heavy, victim), links[:4]
    # 50 ms injected + event-loop scheduling noise on top; in either
    # case well separated from the clean links' noise floor
    assert 0.040 * 1e3 <= top["min_lag_ms"] <= 0.130 * 1e3
    for e in links[1:]:
        assert e["min_lag_ms"] < 35.0, e

    # (c) the victim's per-height quorum close names the heavy
    # validator as ITS quorum-closing straggler: the heavy vote is
    # required by every 2/3 and is the one crossing the delayed link
    heights = report["heights"]
    victim_closes = [
        p["quorum_close"][victim]
        for p in heights.values()
        if victim in p["quorum_close"]
    ]
    assert len(victim_closes) >= 3, heights
    named = sum(
        1 for q in victim_closes if q["closer_index"] == heavy_idx
    )
    assert named >= (len(victim_closes) + 1) // 2, victim_closes
    # and the straggler ranking carries the heavy validator with at
    # least those closes
    heavy_row = next(
        s
        for s in report["stragglers"]
        if s["validator_index"] == heavy_idx
    )
    assert heavy_row["quorum_closes"] >= named

    # the CLI merges the same dumps: slowest-path text + Perfetto trace
    paths = []
    for d in raw_dumps:
        p = tmp_path / f"{d['moniker']}.json"
        p.write_text(json.dumps(d))
        paths.append(str(p))
    merged_path = tmp_path / "merged_trace.json"
    out = subprocess.run(
        [
            sys.executable, "tools/cluster_trace.py", *paths,
            "--out", str(merged_path),
        ],
        capture_output=True, text=True, cwd="/root/repo", timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert "cluster report" in out.stdout
    assert heavy in out.stdout and victim in out.stdout
    trace = json.loads(merged_path.read_text())
    names = {e.get("name") for e in trace["traceEvents"]}
    assert "gossip.recv" in names and "quorum.close" in names
    pids = {e.get("pid") for e in trace["traceEvents"] if e.get("ph") == "X"}
    assert len(pids) == len(handles)  # one Perfetto process per node


# --- multi-dump trace_report (side-by-side columns) ------------------------


def test_trace_report_side_by_side(tmp_path):
    docs = {}
    for name, shift in (("nodeA", 0.0), ("nodeB", 0.002)):
        t = obs.Tracer(enabled=True)
        base = t.epoch
        t.add_span("cs.propose", base + shift, 0.05, height=1)
        t.add_span("cs.commit", base + 0.05 + shift, 0.15, height=1)
        t.event("chaos.partition", name="split")
        docs[name] = {
            "records": [r.to_json() for r in t.records()],
            "moniker": name,
        }
    paths = []
    for name, doc in docs.items():
        p = tmp_path / f"{name}.json"
        p.write_text(json.dumps(doc))
        paths.append(str(p))
    out = subprocess.run(
        [sys.executable, "tools/trace_report.py", *paths],
        capture_output=True, text=True, cwd="/root/repo", timeout=60,
    )
    assert out.returncode == 0, out.stderr
    assert "nodeA" in out.stdout and "nodeB" in out.stdout
    assert "cs.propose" in out.stdout
    assert "! annotations" in out.stdout
    assert "latency attribution" in out.stdout
