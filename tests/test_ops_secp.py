"""Device secp256k1 kernel vs the host oracle (crypto/secp256k1.py).

Mirrors the test strategy of test_ops_bls_g1.py: field bounds pinned by
randomized + worst-case stress against python ints, group ops checked
limb-for-limb against the host Jacobian oracle, and the full verify
kernel differentially tested on real signatures (valid, corrupted,
cross-key) — including the x >= n wrapped mod-n comparison branch's
guard."""

import hashlib
import random

import numpy as np

import jax
import jax.numpy as jnp

from tendermint_tpu.crypto import secp256k1 as host
from tendermint_tpu.ops import secp256k1_kernel as k

fe = k.fe
P = k.P
rng = random.Random(42)

# jitted helpers: eager per-op dispatch makes the limb arithmetic
# pathologically slow on CPU; one compiled program per shape instead
_mulc = jax.jit(lambda a, b: fe.canonical(fe.mul(a, b)))
_mul = jax.jit(fe.mul)
_addc = jax.jit(lambda a, b: fe.canonical(fe.add(a, b)))
_subc = jax.jit(lambda a, b: fe.canonical(fe.sub(a, b)))
_negc = jax.jit(lambda a: fe.canonical(fe.neg(a)))
_invmanyc = jax.jit(lambda a: fe.canonical(fe.invert_many(a)))
_addpts = jax.jit(k.add_points)
_dbl = jax.jit(k.double)
_canon = jax.jit(fe.canonical)
_isinf = jax.jit(k.is_inf)


def _rand_fe():
    return rng.randrange(P)


# --- field -----------------------------------------------------------------


def test_field_mul_random_and_worst_case():
    for _ in range(25):
        a, b = _rand_fe(), _rand_fe()
        got = fe.to_int(
            np.asarray(
                _mulc(jnp.asarray(fe.from_int(a)), jnp.asarray(fe.from_int(b)))
            )
        )
        assert got == a * b % P
    # worst case: every limb at the loose bound (2^11 - 1)
    worst = jnp.full((fe.NLIMBS,), (1 << 11) - 1, dtype=jnp.int32)
    wv = fe.to_int(np.asarray(worst))
    got = fe.to_int(np.asarray(_mulc(worst, worst)))
    assert got == wv * wv % P
    # the loose invariant survives a mul chain at the bound
    x = worst
    val = wv
    for _ in range(6):
        x = _mul(x, x)
        val = val * val % P
        assert int(np.asarray(x).max()) < (1 << 11), "loose bound violated"
    assert fe.to_int(np.asarray(_canon(x))) == val


def test_field_add_sub_neg_invert():
    for _ in range(10):
        a, b = _rand_fe(), _rand_fe()
        ja, jb = jnp.asarray(fe.from_int(a)), jnp.asarray(fe.from_int(b))
        assert fe.to_int(np.asarray(_addc(ja, jb))) == (a + b) % P
        assert fe.to_int(np.asarray(_subc(ja, jb))) == (a - b) % P
        assert fe.to_int(np.asarray(_negc(ja))) == (-a) % P
    # batched inversion (the Montgomery trick + one Fermat chain)
    vals = [_rand_fe() for _ in range(7)] + [0]
    arr = jnp.asarray(np.stack([fe.from_int(v) for v in vals]))
    inv = np.asarray(_invmanyc(arr))
    for v, row in zip(vals, inv):
        got = fe.to_int(row)
        assert got == (pow(v, P - 2, P) if v else 0)


# --- group law -------------------------------------------------------------


def _host_affine(pt_jac_limbs):
    arr = np.asarray(_canon(jnp.asarray(pt_jac_limbs)))
    x, y, z = (fe.to_int(arr[i]) for i in range(3))
    if z == 0:
        return None
    return host._to_affine((x, y, z))


def test_group_ops_match_host_oracle():
    pts = []
    for _ in range(6):
        d = rng.randrange(1, host.N)
        pts.append(host._to_affine(host._jmul(d, (k.GX, k.GY, 1))))
    for a in pts[:3]:
        for b in pts[3:]:
            ja = jnp.asarray(k.from_affine_host(*a))
            jb = jnp.asarray(k.from_affine_host(*b))
            got = _host_affine(_addpts(ja, jb))
            want = host._to_affine(host._jadd((*a, 1), (*b, 1)))
            assert got == want
    # doubling, doubling-by-add, infinity identities
    ja = jnp.asarray(k.from_affine_host(*pts[0]))
    assert _host_affine(_dbl(ja)) == host._to_affine(
        host._jdouble((*pts[0], 1))
    )
    assert _host_affine(_addpts(ja, ja)) == host._to_affine(
        host._jdouble((*pts[0], 1))
    )
    inf = k.identity(())
    assert _host_affine(_addpts(ja, inf)) == pts[0]
    assert _host_affine(_addpts(inf, ja)) == pts[0]
    # P + (-P) = infinity
    negp = (pts[0][0], P - pts[0][1])
    jn_ = jnp.asarray(k.from_affine_host(*negp))
    assert bool(np.asarray(_isinf(_addpts(ja, jn_))))


# --- full verify -----------------------------------------------------------


def test_verify_kernel_differential_via_batch_verifier(monkeypatch):
    """End to end through the BatchVerifier's TM_TPU_SECP_DEVICE route:
    host prep (parse/low-S/u1-u2/decompress) + device joint ladder must
    agree with the host verify on valid, corrupted, wrong-message,
    cross-key, and malformed rows."""
    from tendermint_tpu.crypto.batch_verifier import BatchVerifier, SigItem

    monkeypatch.setenv("TM_TPU_SECP_DEVICE", "1")
    privs = [host.PrivKey.from_secret(b"dev%d" % i) for i in range(7)]
    items = []
    expect = []
    for i, pv in enumerate(privs):
        msg = b"msg%d" % i
        sig = pv.sign(msg)
        pub = pv.public_key().data
        items.append(SigItem(pub, msg, sig, "secp256k1"))
        expect.append(True)
        bad = sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]
        items.append(SigItem(pub, msg, bad, "secp256k1"))
        expect.append(
            host.verify_digest(
                hashlib.sha256(msg).digest(),
                bad,
                host.decompress_point(pub),
            )
        )
        items.append(SigItem(pub, b"other", sig, "secp256k1"))
        expect.append(False)
        other = privs[(i + 1) % 7].public_key().data
        items.append(SigItem(other, msg, sig, "secp256k1"))
        expect.append(False)
    # malformed rows: short signature, garbage pubkey
    items.append(SigItem(privs[0].public_key().data, b"m", b"\x01" * 10,
                         "secp256k1"))
    expect.append(False)
    items.append(SigItem(b"\x02" + b"\x00" * 32, b"m",
                         privs[0].sign(b"m"), "secp256k1"))
    expect.append(False)
    assert len(items) >= 30  # the >=32 gate rounds to the 32 bucket
    items += [items[0], items[1]]
    expect += [expect[0], expect[1]]
    got = BatchVerifier().verify(items)
    assert got.tolist() == expect, (
        f"device/host divergence: {got.tolist()} vs {expect}"
    )


def test_verify_wrapped_mod_n_guard():
    """x(R) in [n, p) exercises the wrapped comparison; and a forged
    r = (x - n + 2^256) pattern with x < n must NOT be accepted (the
    borrow guard)."""
    # craft: pick k until x(kG) >= n (probability ~ (p-n)/p is tiny for
    # secp256k1, so instead verify the guard logic directly on the
    # comparison path with synthetic x values)
    x_small = 5  # x < n
    fake_r = (x_small - host.N) % (1 << 256)  # the wrap-around pattern
    x_aff = jnp.asarray(fe.from_int(x_small))[None, :]
    r_le = jnp.asarray(
        np.frombuffer(fake_r.to_bytes(32, "big"), np.uint8)[::-1].astype(
            np.int32
        )
    )[None, :]
    x_min_n, borrow = fe._scan_carry(x_aff - jnp.asarray(k._N_LIMBS))
    wrapped = (np.asarray(borrow) == 0) & bool(
        np.asarray(jnp.all(x_min_n == r_le, axis=-1))[0]
    )
    assert not bool(np.asarray(wrapped)[0] if np.ndim(wrapped) else wrapped), (
        "borrow guard failed: negative difference matched forged r"
    )
    # positive side: x in [n, p) with r = x - n must take the wrapped
    # branch (a break here would fail genuine x >= n signatures on the
    # device only — a cross-backend consensus split no real signature
    # would surface, P(x >= n) ~ 2^-128)
    x_big = host.N + 12345
    assert x_big < P
    r_true = x_big - host.N
    x_aff2 = jnp.asarray(fe.from_int(x_big))[None, :]
    r_le2 = jnp.asarray(
        np.frombuffer(r_true.to_bytes(32, "big"), np.uint8)[::-1].astype(
            np.int32
        )
    )[None, :]
    d2 = bool(np.asarray(jnp.all(x_aff2 == r_le2, axis=-1))[0])
    xmn2, borrow2 = fe._scan_carry(x_aff2 - jnp.asarray(k._N_LIMBS))
    w2 = (int(np.asarray(borrow2)[0]) == 0) and bool(
        np.asarray(jnp.all(xmn2 == r_le2, axis=-1))[0]
    )
    assert not d2 and w2, "wrapped accept path broken for x >= n"
