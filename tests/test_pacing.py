"""Adaptive consensus pacing (consensus/pacing.py + obs/quantile.py).

Quick tier: sketch units, controller AIMD/clamp semantics, schedule
determinism, config round-trip, a 4-validator in-proc net that actually
tightens its commit wait, and the pacing_report CLI smoke.

Chaos tier (also quick, marked chaos like the PR5 e2e): a 50 ms
straggler link on the weighted-quorum topology forces the victim's
controller to back off and cover the injected tail within K heights,
without stalling consensus past what the static config would allow.
"""

from __future__ import annotations

import asyncio
import json
import subprocess
import sys
import time

import pytest

from tendermint_tpu import obs
from tendermint_tpu.config.config import Config, ConsensusTimeoutsConfig
from tendermint_tpu.consensus.pacing import (
    PACING_STEPS,
    PacingConfig,
    PacingController,
)
from tendermint_tpu.consensus.state_machine import ConsensusConfig
from tendermint_tpu.consensus.ticker import TimeoutInfo, TimeoutTicker
from tendermint_tpu.obs.quantile import StreamingQuantile
from tendermint_tpu.obs.report import pct
from tendermint_tpu.types.vote import VoteType

pytestmark = pytest.mark.pacing


# --- obs/quantile.py: the streaming sketch ---------------------------------


def test_sketch_exact_within_window():
    s = StreamingQuantile(window=8)
    xs = [5.0, 1.0, 9.0, 3.0, 7.0]
    s.extend(xs)
    assert len(s) == 5 and s.count == 5
    # agrees bit-for-bit with the shared list-percentile rule
    for q in (0.0, 0.5, 0.9, 0.95, 0.99, 1.0):
        assert s.quantile(q) == pct(xs, q)
    assert s.max() == 9.0


def test_sketch_window_evicts_old_samples():
    s = StreamingQuantile(window=4)
    s.extend([100.0, 100.0, 100.0, 100.0])
    assert s.quantile(0.5) == 100.0
    s.extend([1.0, 1.0, 1.0, 1.0])  # old regime fully aged out
    assert s.quantile(0.99) == 1.0
    assert s.count == 8 and len(s) == 4


def test_sketch_empty_and_reset():
    s = StreamingQuantile(window=4)
    assert s.quantile(0.5) == 0.0 and s.max() == 0.0
    s.add(2.0)
    s.reset()
    assert len(s) == 0 and s.count == 0 and s.quantile(0.5) == 0.0
    with pytest.raises(ValueError):
        StreamingQuantile(window=0)


def test_sketch_snapshot_shape():
    s = StreamingQuantile(window=16)
    s.extend(float(i) for i in range(10))
    snap = s.snapshot()
    assert snap["count"] == 10 and snap["window_fill"] == 10
    assert snap["p50"] == 5.0 and snap["max"] == 9.0


# --- controller semantics --------------------------------------------------


def _controller(**over) -> PacingController:
    static = ConsensusConfig(
        timeout_propose=0.4,
        timeout_prevote=0.2,
        timeout_precommit=0.2,
        timeout_commit=0.1,
    )
    kw = dict(
        tail_quantile=0.95,
        safety_margin=1.25,
        headroom_s=0.002,
        min_factor=0.05,
        window=32,
        min_samples=4,
        backoff_step=0.5,
        recover_step=0.25,
    )
    kw.update(over)
    return PacingController(static, PacingConfig(**kw))


def test_controller_static_until_min_samples():
    pc = _controller()
    # no samples, full backoff: exactly the static schedule
    assert pc.propose(0) == 0.4
    assert pc.commit_wait() == 0.1
    for _ in range(3):  # below min_samples
        pc.observe_post_quorum_straggler(VoteType.PRECOMMIT, 0.001)
    for _ in range(10):
        pc.on_height_committed(1, 0)  # decay backoff fully
    assert pc.commit_wait() == 0.1  # still static: not enough samples


def test_controller_tightens_to_learned_tail():
    pc = _controller()
    for _ in range(8):
        pc.observe_post_quorum_straggler(VoteType.PRECOMMIT, 0.004)
        pc.observe_vote_arrival(VoteType.PREVOTE, 0.003)
        pc.observe_vote_arrival(VoteType.PRECOMMIT, 0.003)
        pc.observe_proposal_complete(0.01)
    for _ in range(4):  # 4 clean commits: backoff 1.0 -> 0.0
        pc.on_height_committed(1, 0)
    # learned = tail * margin + headroom, all way below static
    assert pc.commit_wait() == pytest.approx(0.004 * 1.25 + 0.002)
    assert pc.propose(0) == pytest.approx(0.4 * 0.05)  # floor: 20 ms
    assert pc.prevote(0) == pytest.approx(0.2 * 0.05)
    snap = pc.snapshot()
    assert snap["steps"]["commit"]["backoff"] == 0.0


def test_controller_floor_and_ceiling_clamps():
    pc = _controller()
    for _ in range(8):
        pc.observe_post_quorum_straggler(VoteType.PRECOMMIT, 1e-9)
        pc.observe_vote_arrival(VoteType.PREVOTE, 10.0)  # above static
    for _ in range(4):
        pc.on_height_committed(1, 0)
    # floor of last resort: min_factor * static
    assert pc.commit_wait() == pytest.approx(0.1 * 0.05)
    # hard ceiling: never above the static value
    assert pc.prevote(0) == 0.2


def test_controller_aimd_backoff_and_recovery():
    pc = _controller()
    for _ in range(8):
        pc.observe_proposal_complete(0.004)
    for _ in range(4):
        pc.on_height_committed(1, 0)
    tight = pc.propose(0)
    assert tight == pytest.approx(0.4 * 0.05)
    # a fired timeout jumps multiplicatively toward static
    pc.on_timeout_fired("propose")
    assert pc.snapshot()["steps"]["propose"]["backoff"] == 0.5
    backed_off = pc.propose(0)
    assert backed_off == pytest.approx(tight + 0.5 * (0.4 - tight))
    pc.on_timeout_fired("propose")
    assert pc.snapshot()["steps"]["propose"]["backoff"] == 1.0
    assert pc.propose(0) == 0.4  # fully static again
    # the height whose timeout fired is NOT a success for that step,
    # even if it still committed at round 0 — no decay yet
    pc.on_height_committed(2, 0)
    assert pc.snapshot()["steps"]["propose"]["backoff"] == 1.0
    # recovery is additive (slow): the next clean commit steps 0.25 back
    pc.on_height_committed(3, 0)
    assert pc.snapshot()["steps"]["propose"]["backoff"] == 0.75


def test_controller_per_step_failure_isolation():
    """A flapping propose schedule must not freeze the OTHER steps'
    recovery: only the failed step skips its decay on the commit."""
    pc = _controller()
    # two clean commits: every step decays 1.0 -> 0.5
    pc.on_height_committed(1, 0)
    pc.on_height_committed(2, 0)
    assert all(
        pc.snapshot()["steps"][s]["backoff"] == 0.5 for s in PACING_STEPS
    )
    pc.on_timeout_fired("propose")  # propose doubles to 1.0, flagged
    pc.on_height_committed(3, 0)
    snap = pc.snapshot()["steps"]
    # propose failed this height: no decay. Everyone else decays.
    assert snap["propose"]["backoff"] == 1.0
    assert snap["prevote"]["backoff"] == 0.25
    assert snap["precommit"]["backoff"] == 0.25
    assert snap["commit"]["backoff"] == 0.25
    # a round advance fails EVERY step (jump floor 0.5), and the
    # round-1 commit that follows clears flags but never decays
    pc.on_round_advance(1)
    pc.on_height_committed(4, 1)
    snap = pc.snapshot()["steps"]
    assert snap["propose"]["backoff"] == 1.0
    assert all(snap[s]["backoff"] == 0.5 for s in PACING_STEPS[1:])


def test_controller_round_advance_backs_off_everything():
    pc = _controller()
    for _ in range(8):
        pc.observe_proposal_complete(0.004)
        pc.observe_vote_arrival(VoteType.PREVOTE, 0.003)
        pc.observe_vote_arrival(VoteType.PRECOMMIT, 0.003)
        pc.observe_post_quorum_straggler(VoteType.PRECOMMIT, 0.002)
    for _ in range(4):
        pc.on_height_committed(1, 0)
    assert all(
        pc.snapshot()["steps"][s]["backoff"] == 0.0 for s in PACING_STEPS
    )
    pc.on_round_advance(1)
    assert all(
        pc.snapshot()["steps"][s]["backoff"] == 0.5 for s in PACING_STEPS
    )
    # a round-0 query during back-off interpolates; round > 0 is ALWAYS
    # the static per-round escalation (reference semantics preserved)
    assert pc.propose(1) == 0.4 + 0.5  # static + delta * 1
    assert pc.prevote(2) == 0.2 + 0.5 * 2


def test_controller_commit_height_decision_events():
    tracer = obs.Tracer(enabled=True)
    static = ConsensusConfig(adaptive_timeouts=True)
    pc = PacingController.from_config(static, tracer=tracer)
    pc.on_height_committed(7, 0)
    decisions = [
        r for r in tracer.records() if r.name == "pacing.decision"
    ]
    assert {d.fields["step"] for d in decisions} == set(PACING_STEPS)
    assert all(d.height == 7 for d in decisions)
    d = decisions[0].fields
    assert {"learned_ms", "static_ms", "effective_ms", "backoff"} <= set(d)


def test_schedule_determinism_identical_streams():
    """Two controllers fed the same sample/event stream must emit the
    SAME timeout schedule — the property that lets a trace replay
    reproduce a node's pacing decisions exactly."""

    def drive(pc: PacingController) -> list[float]:
        out = []
        lag = 0.0037
        for h in range(40):
            lag = (lag * 1.31) % 0.05  # deterministic pseudo-noise
            pc.observe_proposal_complete(lag + 0.001)
            pc.observe_vote_arrival(VoteType.PREVOTE, lag)
            pc.observe_vote_arrival(VoteType.PRECOMMIT, lag * 0.7)
            pc.observe_post_quorum_straggler(VoteType.PRECOMMIT, lag / 3)
            if h % 11 == 5:
                pc.on_timeout_fired("propose")
            if h % 17 == 3:
                pc.on_round_advance(1)
            pc.on_height_committed(h + 1, 1 if h % 17 == 3 else 0)
            out += [
                pc.propose(0),
                pc.prevote(0),
                pc.precommit(0),
                pc.commit_wait(),
            ]
        return out

    a, b = _controller(), _controller()
    assert drive(a) == drive(b)
    assert a.snapshot() == b.snapshot()


def test_controller_reset_learning_returns_to_static():
    """The WAL-catchup hook: dropping the learned distributions sends
    schedules back to static (until fresh samples), while back-off
    levels — event history, not distribution state — survive."""
    pc = _controller()
    for _ in range(8):
        pc.observe_post_quorum_straggler(VoteType.PRECOMMIT, 1e-6)
    for _ in range(4):
        pc.on_height_committed(1, 0)
    assert pc.commit_wait() < 0.1
    pc.on_timeout_fired("propose")
    pc.reset_learning()
    assert pc.commit_wait() == 0.1  # static again: no samples
    assert pc.snapshot()["steps"]["propose"]["backoff"] == 0.5


def test_pacing_config_validation():
    for bad in (
        dict(tail_quantile=0.0),
        dict(tail_quantile=1.5),
        dict(safety_margin=0.5),
        dict(min_factor=0.0),
        dict(min_factor=1.5),
        dict(window=1),
        dict(min_samples=0),
        dict(backoff_step=0.0),
        dict(recover_step=1.5),
        dict(headroom_s=-1.0),
    ):
        with pytest.raises(ValueError):
            _controller(**bad)


# --- ticker on_fire wiring -------------------------------------------------


def test_ticker_on_fire_sees_only_expiries():
    async def run():
        fired: list[TimeoutInfo] = []
        t = TimeoutTicker(on_fire=fired.append)
        t.schedule(TimeoutInfo(0.01, 1, 0, 3))
        await asyncio.sleep(0.05)
        assert [ti.step for ti in fired] == [3]
        assert t.tock_queue.get_nowait().step == 3
        # a replaced schedule is cancelled before expiry: only the
        # replacement reaches the observer
        t.schedule(TimeoutInfo(0.2, 1, 0, 4))
        t.schedule(TimeoutInfo(0.01, 1, 0, 5))
        await asyncio.sleep(0.05)
        assert [ti.step for ti in fired] == [3, 5]
        assert t.tock_queue.get_nowait().step == 5
        # a raising observer must not lose the tock
        t.set_on_fire(lambda ti: 1 / 0)
        t.schedule(TimeoutInfo(0.01, 1, 0, 6))
        await asyncio.sleep(0.05)
        assert t.tock_queue.get_nowait().step == 6
        t.stop()

    asyncio.run(run())


# --- [consensus] adaptive_timeouts config round-trip -----------------------


_ADAPTIVE_OVERRIDES = {
    "adaptive_timeouts": True,
    "adaptive_tail_quantile": 0.9,
    "adaptive_safety_margin": 1.5,
    "adaptive_headroom": 0.004,
    "adaptive_min_factor": 0.1,
    "adaptive_window": 33,
    "adaptive_min_samples": 5,
    "adaptive_backoff_step": 0.4,
    "adaptive_recover_step": 0.2,
}


def test_config_adaptive_knobs_roundtrip(tmp_path):
    c = Config.default()
    c.root_dir = str(tmp_path)
    for k, v in _ADAPTIVE_OVERRIDES.items():
        setattr(c.consensus, k, v)
    c.save()
    c2 = Config.load(str(tmp_path))
    for k, v in _ADAPTIVE_OVERRIDES.items():
        assert getattr(c2.consensus, k) == v, k
    smc = c2.consensus.to_state_machine_config()
    for k, v in _ADAPTIVE_OVERRIDES.items():
        assert getattr(smc, k) == v, k


def test_config_serialization_list_covers_sm_config():
    """The silent-drop guard: every field of the state-machine
    ConsensusConfig must be registered in the ConsensusTimeoutsConfig
    serialization list (a knob added to one side but not the other
    would vanish on a config-file round trip)."""
    from dataclasses import fields

    sm_fields = {f.name for f in fields(ConsensusConfig)}
    listed = set(ConsensusTimeoutsConfig._SM_FIELDS)
    assert listed == sm_fields
    # and every listed knob exists on the TOML side too
    toml_fields = {f.name for f in fields(ConsensusTimeoutsConfig)}
    assert listed <= toml_fields


def test_config_adaptive_validation_surfaces_at_load():
    c = Config.default()
    c.consensus.adaptive_timeouts = True
    c.consensus.adaptive_tail_quantile = 2.0
    with pytest.raises(ValueError, match="tail_quantile"):
        c.validate_basic()
    # knobs are not validated while the feature is off (a stale file
    # section must not brick a node that disabled pacing)
    c.consensus.adaptive_timeouts = False
    c.validate_basic()


# --- live net: the loop actually closes ------------------------------------


def _adaptive_cfg(**over) -> ConsensusConfig:
    kw = dict(
        timeout_propose=0.4,
        timeout_propose_delta=0.1,
        timeout_prevote=0.2,
        timeout_prevote_delta=0.1,
        timeout_precommit=0.2,
        timeout_precommit_delta=0.1,
        timeout_commit=0.1,
        skip_timeout_commit=False,
        adaptive_timeouts=True,
        adaptive_window=64,
        adaptive_min_samples=4,
        adaptive_recover_step=0.25,
        adaptive_tail_quantile=0.95,
    )
    kw.update(over)
    return ConsensusConfig(**kw)


def test_four_validator_net_tightens_commit_wait():
    """In-proc 4-validator net with adaptive pacing: the chain commits,
    the commit controller collects straggler samples through BOTH feed
    paths (same-height post-quorum and the LastCommit branch), and the
    effective commit wait drops below the static floor once learned."""
    from tests.helpers import make_genesis, make_validators
    from tests.test_consensus import make_node, wire_net

    cfg = _adaptive_cfg()
    tracer = obs.Tracer(enabled=True, ring_size=16384)

    async def run():
        vs, pvs = make_validators(4)
        genesis = make_genesis(vs)
        nodes = [
            make_node(
                vs,
                pv,
                genesis,
                config=cfg,
                tracer=tracer if i == 0 else obs.Tracer(enabled=False),
            )
            for i, pv in enumerate(pvs)
        ]
        css = [n[0] for n in nodes]
        wire_net(css)
        for cs in css:
            await cs.start()
        await asyncio.gather(
            *(cs.wait_for_height(8, timeout=120) for cs in css)
        )
        snaps = [cs.pacing.snapshot() for cs in css]
        for cs in css:
            await cs.stop()
        return snaps

    snaps = asyncio.run(run())
    for snap in snaps:
        commit = snap["steps"]["commit"]
        # both straggler feed paths ran: ~1 sample/height
        assert commit["samples"] >= 4, snap
        # the learned tail sits below the static floor (this box's
        # straggler lag is tens of ms; static is 100 ms) and the
        # effective wait left the ceiling
        assert commit["learned_s"] < 0.1, snap
        assert commit["effective_s"] < 0.1, snap
        assert snap["steps"]["prevote"]["samples"] >= 8, snap
    # node 0's tracer carries the per-height decision events
    decisions = [
        r.to_json()
        for r in tracer.records()
        if r.name == "pacing.decision"
    ]
    assert len(decisions) >= 4 * 4  # 4 steps x >=4 heights
    from tendermint_tpu.obs import pacing_decisions

    summary = pacing_decisions(
        [r.to_json() for r in tracer.records()]
    )
    assert summary["commit"]["static_ms"] == pytest.approx(100.0)
    assert summary["commit"]["learned_ms_last"] < 100.0


def test_late_straggler_feeds_commit_sketch():
    """A previous-height precommit arriving too late even for the
    LastCommit window is dropped — but its arrival lag must STILL feed
    the commit controller (exactly once per validator), or a tightened
    commit wait could never observe the widened tail of a degrading
    validator (the controller would censor its own input stream)."""
    from tendermint_tpu.consensus.state_machine import Step
    from tendermint_tpu.types.block_id import BlockID
    from tendermint_tpu.types.part_set import PartSetHeader
    from tendermint_tpu.types.vote import Vote
    from tests.helpers import make_genesis, make_validators
    from tests.test_consensus import make_node

    vs, pvs = make_validators(4)
    genesis = make_genesis(vs)
    cs = make_node(vs, pvs[0], genesis, config=_adaptive_cfg())[0]
    # mid-height 2, already past NEW_HEIGHT: the LastCommit window for
    # height-1 stragglers is closed
    cs.rs.height = 2
    cs.rs.step = Step.PROPOSE
    cs._last_quorum_close_pc = time.perf_counter() - 0.123
    vote = Vote(
        type=VoteType.PRECOMMIT,
        height=1,
        round=0,
        block_id=BlockID(b"h" * 32, PartSetHeader(1, b"p" * 32)),
        timestamp_ns=1,
        validator_address=vs.validators[1].address,
        validator_index=1,
    )

    async def run():
        assert not await cs._add_vote(vote, "", pre_verified=True)
        # gossip re-delivery: same validator feeds only once
        assert not await cs._add_vote(vote, "", pre_verified=True)

    asyncio.run(run())
    commit = cs.pacing.snapshot()["steps"]["commit"]
    assert commit["samples"] == 1
    # the sample is the true arrival lag behind the quorum close
    assert cs.pacing._steps["commit"].sketch.max() >= 0.123
    missed = [
        r
        for r in cs.tracer.records()
        if r.name == "pacing.straggler_missed"
    ]
    # tracer defaults off in this harness unless TM_TPU_TRACE is set;
    # the event only exists when tracing is on
    assert len(missed) <= 1


def test_adaptive_metrics_gauges():
    """The pacing gauges/counters exist under the documented names and
    carry per-step labels."""
    from tendermint_tpu.libs.metrics import ConsensusMetrics, Registry

    reg = Registry("pacing_gauges")
    m = ConsensusMetrics(reg)
    static = ConsensusConfig(adaptive_timeouts=True)
    pc = PacingController.from_config(static, metrics=m)
    pc.commit_wait()
    pc.on_timeout_fired("propose")
    pc.on_height_committed(1, 0)
    expo = reg.render()
    assert 'consensus_adaptive_timeout_seconds{step="commit"}' in expo
    assert 'consensus_pacing_timeouts_fired_total{step="propose"} 1' in expo
    assert 'consensus_pacing_backoff{step="propose"}' in expo
    # rounds > 0 export the schedule actually in effect (the static
    # escalation), not a stale round-0 value
    pc.propose(2)
    expo = reg.render()
    assert 'consensus_adaptive_timeout_seconds{step="propose"} 4' in expo
    # the commit wait's NEW_HEIGHT expiry fires every healthy height:
    # no failure tally exists for it
    assert "commit" not in pc.snapshot()["fired"]


# --- chaos: the controller backs off to cover an injected tail -------------


@pytest.mark.chaos
def test_chaos_straggler_forces_backoff_without_stall(tmp_path):
    """The PR5 quorum topology (powers 40/20/20/20: the heavy
    validator's vote is required by every 2/3) with adaptive pacing on
    every node. Phase 1 runs clean so the controllers tighten; then
    chaos injects a 50 ms one-way delay on heavy->victim. Within the
    K=10 chaos heights the victim's controllers must LEARN the injected
    tail (heavy's votes arrive ~50 ms behind the first vote at the
    victim, every height), consensus must keep committing on all nodes,
    and no height may take longer than the static config would allow
    (round 0 + one full retry round + the commit wait)."""
    from tendermint_tpu.chaos.link import LinkPolicy
    from tendermint_tpu.chaos.network import ChaosNetwork

    from .chaos_harness import (
        build_chaos_handles,
        node_dump,
        start_mesh,
        stop_mesh,
    )

    cfg = _adaptive_cfg(
        # keep back-off sticky enough to observe at phase end
        adaptive_recover_step=0.1,
    )
    handles = build_chaos_handles(
        tracer_factory=lambda name: obs.Tracer(enabled=True),
        ping_interval=0.5,
        powers=(40, 20, 20, 20),
        config=cfg,
    )
    vals = handles[0].cs.state.validators.validators
    heavy_idx = max(
        range(len(vals)), key=lambda i: vals[i].voting_power
    )
    victim_idx = (heavy_idx + 1) % len(handles)
    heavy, victim = f"n{heavy_idx}", f"n{victim_idx}"
    K = 10

    async def run():
        net = ChaosNetwork(seed=11)
        for h in handles:
            net.install(h)
        await start_mesh(handles)
        try:
            # phase 1: clean heights — controllers earn tightness
            await asyncio.gather(
                *(h.cs.wait_for_height(4, timeout=120) for h in handles)
            )
            pre = handles[victim_idx].cs.pacing.snapshot()
            net.set_link_policy(
                heavy,
                victim,
                LinkPolicy(latency_s=0.05),
                reverse=LinkPolicy(),
            )
            for h in handles:
                h.cs.tracer.clear()
            h_clear = max(
                h.cs.state.last_block_height for h in handles
            )
            t0 = time.perf_counter()
            await asyncio.gather(
                *(
                    h.cs.wait_for_height(h_clear + K, timeout=180)
                    for h in handles
                )
            )
            chaos_wall = time.perf_counter() - t0
            post = handles[victim_idx].cs.pacing.snapshot()
            dump = node_dump(handles[victim_idx])
            hashes = {
                h.block_store.load_block(h_clear + K).hash()
                for h in handles
            }
            return pre, post, dump, hashes, chaos_wall, h_clear
        finally:
            await stop_mesh(handles)

    pre, post, dump, hashes, chaos_wall, h_clear = asyncio.run(run())

    # liveness + agreement through the degraded regime
    assert len(hashes) == 1, "nodes disagree under the straggler link"

    # the victim LEARNED the injected tail: heavy's prevote arrives
    # ~50 ms behind the victim's first prevote every height, so the
    # p95 arrival tail (x1.25 margin) must now cover the injection
    assert post["steps"]["prevote"]["samples"] > pre["steps"]["prevote"][
        "samples"
    ]
    assert post["steps"]["prevote"]["learned_s"] >= 0.05, post
    # and the schedule it would set covers the tail while respecting
    # the static ceiling
    assert 0.05 <= post["steps"]["prevote"]["effective_s"] <= 0.2, post

    # never slower than the static config would allow: per-height wall
    # bounded by one full round-0 schedule + one retry round + the
    # commit wait + a generous compute allowance for this host
    att = obs.wall_attribution(dump["records"])
    walls = [
        v["wall_ms"]
        for h, v in att["heights"].items()
        if h > h_clear + 1  # first post-clear height straddles the clear
    ]
    assert walls, att
    static_allowance_ms = (
        (cfg.propose(0) + cfg.prevote(0) + cfg.precommit(0))
        + (cfg.propose(1) + cfg.prevote(1) + cfg.precommit(1))
        + cfg.timeout_commit
    ) * 1e3 + 1500.0
    assert max(walls) <= static_allowance_ms, (max(walls), walls)

    # report smoke on the real dump: the attribution + decision tables
    # render from exactly this artifact
    p = tmp_path / "victim_dump.json"
    p.write_text(json.dumps(dump))
    out = subprocess.run(
        [sys.executable, "tools/pacing_report.py", str(p)],
        capture_output=True,
        text=True,
        cwd="/root/repo",
        timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert "timeout floor" in out.stdout
    assert "pacing decisions" in out.stdout


# --- tools/pacing_report.py CLI smoke --------------------------------------


def test_pacing_report_cli_smoke(tmp_path):
    # hand-built records: synthetic timestamps must stay inside each
    # height's window (a real tracer would stamp events with "now")
    records = []
    for h in (2, 3):
        off = (h - 2) * 0.1
        for name, t0, dur in (
            ("cs.new_height", off, 0.04),
            ("cs.propose", off + 0.04, 0.01),
            ("cs.prevote", off + 0.05, 0.005),
            ("cs.precommit", off + 0.055, 0.005),
            ("cs.commit", off + 0.06, 0.002),
        ):
            records.append(
                {
                    "name": name,
                    "t0": t0,
                    "dur": dur,
                    "height": h,
                    "round": 0,
                    "kind": "span",
                }
            )
        records.append(
            {
                "name": "pacing.decision",
                "t0": off + 0.061,
                "dur": 0.0,
                "height": h,
                "round": 0,
                "kind": "event",
                "fields": {
                    "step": "commit",
                    "learned_ms": 5.0,
                    "static_ms": 40.0,
                    "effective_ms": 12.0,
                    "backoff": 0.2,
                    "samples": 30,
                },
            }
        )
    doc = {"moniker": "n0", "records": records}
    p = tmp_path / "dump.json"
    p.write_text(json.dumps(doc))

    out = subprocess.run(
        [sys.executable, "tools/pacing_report.py", str(p)],
        capture_output=True,
        text=True,
        cwd="/root/repo",
        timeout=60,
    )
    assert out.returncode == 0, out.stderr
    assert "timeout floor" in out.stdout
    assert "commit" in out.stdout

    out = subprocess.run(
        [sys.executable, "tools/pacing_report.py", str(p), "--json"],
        capture_output=True,
        text=True,
        cwd="/root/repo",
        timeout=60,
    )
    assert out.returncode == 0, out.stderr
    doc = json.loads(out.stdout)
    rep = doc["n0"]
    assert rep["wall"]["aggregate"]["n_heights"] == 2
    assert rep["pacing"]["commit"]["static_ms"] == 40.0
    # the floor bucket is the cs.new_height window here: 40 of 62 ms
    agg = rep["wall"]["aggregate"]
    assert agg["floor_share"] == pytest.approx(40.0 / 62.0, abs=0.01)


# --- persistence (learned-tail warm starts) ---------------------------------


def test_tails_roundtrip_restores_schedule(tmp_path):
    """save_tails/load_tails: a fresh controller that loads a trained
    one's file derives the identical schedule — no re-learning heights,
    no min_samples gating on restart."""
    path = str(tmp_path / "cs.wal.pacing.json")
    pc = _controller()
    for _ in range(16):
        pc.observe_post_quorum_straggler(VoteType.PRECOMMIT, 0.004)
        pc.observe_vote_arrival(VoteType.PREVOTE, 0.002)
        pc.observe_vote_arrival(VoteType.PRECOMMIT, 0.003)
        pc.observe_proposal_complete(0.005)
    for h in range(8):
        pc.on_height_committed(h, 0)  # decay backoff
    assert pc.commit_wait() < 0.1  # actually learned something
    assert pc.save_tails(path)

    fresh = _controller()
    assert fresh.commit_wait() == 0.1  # static before the load
    assert fresh.load_tails(path)
    for step in PACING_STEPS:
        assert fresh._steps[step].snapshot() == pc._steps[step].snapshot()
    assert fresh.commit_wait() == pc.commit_wait()


def test_tails_load_tolerates_missing_and_corrupt(tmp_path):
    pc = _controller()
    assert not pc.load_tails(str(tmp_path / "nope.json"))
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert not pc.load_tails(str(bad))
    bad.write_text(json.dumps({"schema": "something-else", "steps": {}}))
    assert not pc.load_tails(str(bad))
    # junk inside one step must not poison the controller
    blob = pc.state_dict()
    blob["steps"]["commit"]["samples"] = ["zebra"]
    pc2 = _controller()
    pc2.load_state(blob)
    assert pc2.commit_wait() == 0.1  # commit stayed static
    # unconfigured controller: both directions are clean no-ops
    assert not pc.save_tails()
    assert not pc.load_tails()


def test_tails_survive_state_machine_restart(tmp_path):
    """Integration: a ConsensusState with persist_path saves on stop and
    the next incarnation warm-starts with the learned commit wait."""
    from tests.helpers import make_genesis, make_validators

    from .test_consensus import make_node

    path = str(tmp_path / "cs.wal.pacing.json")
    vs, pvs = make_validators(1)
    genesis = make_genesis(vs)
    cfg = ConsensusConfig.test_config()
    cfg.adaptive_timeouts = True
    cfg.adaptive_min_samples = 2

    async def first():
        cs, *_ = make_node(vs, pvs[0], genesis, config=cfg)
        cs.pacing.persist_path = path
        for _ in range(8):
            cs.pacing.observe_post_quorum_straggler(
                VoteType.PRECOMMIT, 0.001
            )
        await cs.start()
        await cs.wait_for_height(2, timeout=30)
        await cs.stop()
        return cs.pacing.snapshot()["steps"]["commit"]["samples"]

    samples = asyncio.run(first())
    assert samples >= 8

    async def second():
        cs, *_ = make_node(vs, pvs[0], genesis, config=cfg)
        cs.pacing.persist_path = path
        await cs.start()
        restored = cs.pacing.snapshot()["steps"]["commit"]["samples"]
        await cs.stop()
        return restored

    assert asyncio.run(second()) >= samples
