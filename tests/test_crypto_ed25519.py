"""Host reference ed25519 against RFC 8032 §7.1 test vectors."""

import hashlib

from tendermint_tpu.crypto import ed25519

# (seed, pubkey, msg, sig) hex — RFC 8032 §7.1 TEST 1-3
RFC8032_VECTORS = [
    (
        "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60",
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a",
        "",
        "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e065224901555fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b",
    ),
    (
        "4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb",
        "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c",
        "72",
        "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00",
    ),
    (
        "c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7",
        "fc51cd8e6218a1a38da47ed00230f0580816ed13ba3303ac5deb911548908025",
        "af82",
        "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db5ac3ac18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027beceea1ec40a",
    ),
]


def test_rfc8032_vectors():
    for seed_hex, pk_hex, msg_hex, sig_hex in RFC8032_VECTORS:
        sk = ed25519.PrivKey(bytes.fromhex(seed_hex))
        pk = sk.public_key()
        msg = bytes.fromhex(msg_hex)
        assert pk.data.hex() == pk_hex
        sig = sk.sign(msg)
        assert sig.hex() == sig_hex
        assert pk.verify(msg, sig)


def test_sign_verify_roundtrip_and_tamper():
    sk = ed25519.PrivKey.from_secret(b"validator-0")
    pk = sk.public_key()
    msg = b"canonical vote sign bytes"
    sig = sk.sign(msg)
    assert pk.verify(msg, sig)
    assert not pk.verify(msg + b"x", sig)
    assert not pk.verify(msg, sig[:-1] + bytes([sig[-1] ^ 1]))
    other = ed25519.PrivKey.from_secret(b"validator-1").public_key()
    assert not other.verify(msg, sig)


def test_reject_high_s():
    sk = ed25519.PrivKey.from_secret(b"v")
    pk = sk.public_key()
    msg = b"m"
    sig = sk.sign(msg)
    s = int.from_bytes(sig[32:], "little")
    # s + L is the classic malleability twin; must be rejected.
    bad = sig[:32] + int.to_bytes(s + ed25519.L, 32, "little")
    assert not pk.verify(msg, bad)


def test_reject_bad_pubkey_encoding():
    sk = ed25519.PrivKey.from_secret(b"v")
    msg = b"m"
    sig = sk.sign(msg)
    # y = p (non-canonical encoding of 0) must be rejected.
    bad_pk = int.to_bytes(ed25519.P, 32, "little")
    assert not ed25519.verify(bad_pk, msg, sig)
    # a y with no corresponding x
    y = 2
    while ed25519._recover_x(y, 0) is not None:
        y += 1
    assert not ed25519.verify(int.to_bytes(y, 32, "little"), msg, sig)


def test_address():
    pk = ed25519.PrivKey.from_secret(b"v").public_key()
    assert pk.address() == hashlib.sha256(pk.data).digest()[:20]
    assert len(pk.address()) == 20


def test_fast_scalar_paths_match_generic_oracle():
    """The host fast paths (fixed-base comb, 4-bit windowed multiply)
    must agree with the generic double-and-add `scalar_mult`, which
    stays untouched as the oracle the device kernels also verify
    against. Deterministic scalars: edge cases + pseudorandom sweep."""
    G = ed25519.BASEPOINT
    scalars = [0, 1, 2, ed25519.L - 1, ed25519.L, ed25519.L + 1]
    for i in range(24):
        scalars.append(
            int.from_bytes(hashlib.sha512(b"k%d" % i).digest(), "little")
            % (2 * ed25519.L)
        )
    A = ed25519.point_decompress(
        ed25519.PrivKey.from_secret(b"oracle").public_key().data
    )
    for k in scalars:
        want_base = ed25519.point_compress(ed25519.scalar_mult(k, G))
        got_base = ed25519.point_compress(ed25519.scalar_mult_base(k))
        assert got_base == want_base, f"scalar_mult_base diverged at {k}"
        want_var = ed25519.point_compress(ed25519.scalar_mult(k, A))
        got_var = ed25519.point_compress(ed25519._window_mult(k, A))
        assert got_var == want_var, f"_window_mult diverged at {k}"


def test_cached_seed_expansion_keeps_keys_distinct():
    """The lru-cached seed expansion / pubkey decompression must never
    cross-contaminate keys: distinct seeds produce distinct, correctly
    verifying keypairs even when interleaved (cache hit path)."""
    keys = [ed25519.PrivKey.from_secret(b"cache%d" % i) for i in range(4)]
    msgs = [b"payload-%d" % i for i in range(4)]
    sigs = [k.sign(m) for k, m in zip(keys, msgs)]
    # interleave verifies to exercise cache hits across keys
    for _ in range(2):
        for k, m, s in zip(keys, msgs, sigs):
            assert ed25519.verify(k.public_key().data, m, s)
        for k, m, s in zip(keys, msgs, sigs):
            # wrong key must still fail on the cached decompression
            other = keys[(keys.index(k) + 1) % len(keys)]
            assert not ed25519.verify(other.public_key().data, m, s)
