"""Differential tests: TPU batch verifier vs the host RFC 8032 oracle.

Mirrors the adversarial cases the reference's serial path handles in
crypto/ed25519 + x/crypto (bad points, malleable s, wrong everything)."""

import numpy as np
import pytest

from tendermint_tpu.crypto import ed25519 as host
from tendermint_tpu.crypto.batch_verifier import BatchVerifier, SigItem

# differential tests must exercise the DEVICE kernel even for tiny
# batches — min_device_batch=0 disables the host fast path that
# production uses for latency
_verifier = BatchVerifier(min_device_batch=0)


def default_verifier():
    return _verifier


def _keypairs(n, seed=b"bv"):
    ks = [host.PrivKey.from_secret(seed + bytes([i])) for i in range(n)]
    return ks


def test_valid_batch_accepts():
    keys = _keypairs(5)
    items = []
    for i, k in enumerate(keys):
        msg = b"vote-sign-bytes-%d" % i
        items.append(SigItem(k.public_key().data, msg, k.sign(msg)))
    got = default_verifier().verify(items)
    assert got.all()


def test_adversarial_rows_match_oracle():
    k = _keypairs(1)[0]
    pub = k.public_key().data
    msg = b"canonical vote"
    sig = k.sign(msg)

    # s' = s + L: same point equation, must be rejected (malleability)
    s_int = int.from_bytes(sig[32:], "little")
    sig_malleable = sig[:32] + (s_int + host.L).to_bytes(32, "little")

    items = [
        SigItem(pub, msg, sig),  # valid
        SigItem(pub, b"other msg", sig),  # wrong msg
        SigItem(pub, msg, sig[:32] + bytes(32)),  # s = 0 forgery
        SigItem(pub, msg, bytes(32) + sig[32:]),  # wrong R
        SigItem(pub, msg, sig_malleable),  # s >= L
        SigItem(host.P.to_bytes(32, "little"), msg, sig),  # bad pubkey (y=p)
        SigItem(bytes(31) + b"\x01", msg, sig),  # pubkey not on curve? oracle says
        SigItem(pub, msg, b"short"),  # malformed sig length
    ]
    got = default_verifier().verify(items)
    want = [host.verify(it.pubkey, it.msg, it.sig) for it in items]
    assert got.tolist() == want
    assert want[0] is True and not any(want[1:6]) and want[7] is False


def test_identity_pubkey_agrees_with_oracle():
    # y=1 encodes the identity point; Go x/crypto accepts sigs where R=[s]B.
    ident_pub = (1).to_bytes(32, "little")
    s = 12345
    R = host.point_compress(host.scalar_mult(s, host.BASEPOINT))
    sig = R + s.to_bytes(32, "little")
    msg = b"torsion"
    got = default_verifier().verify_one(ident_pub, msg, sig)
    assert got == host.verify(ident_pub, msg, sig)
    assert got is True  # documents the cofactorless-verify behavior


def test_mixed_large_batch():
    keys = _keypairs(11)
    items, want = [], []
    for i, k in enumerate(keys):
        msg = b"m%d" % i
        sig = k.sign(msg)
        if i % 3 == 1:
            sig = sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]  # corrupt s
        if i % 3 == 2:
            msg = msg + b"!"  # corrupt msg after signing
        items.append(SigItem(k.public_key().data, msg, sig))
        want.append(host.verify(items[-1].pubkey, items[-1].msg, items[-1].sig))
    got = default_verifier().verify(items)
    assert got.tolist() == want


def test_empty_batch():
    assert default_verifier().verify([]).shape == (0,)


def test_mixed_key_types_partition():
    """ed25519 rides the device; secp256k1/sr25519 partition to host and
    the bitmap re-interleaves (BASELINE config 4, mixed-key commits)."""
    from tendermint_tpu.crypto import secp256k1, sr25519

    ed = _keypairs(8)
    ks = secp256k1.PrivKey.from_secret(b"s1")
    kr = sr25519.PrivKey.from_secret(b"r1")
    items, want = [], []
    for i, k in enumerate(ed):
        msg = b"ed%d" % i
        items.append(SigItem(k.public_key().data, msg, k.sign(msg)))
        want.append(True)
    items.insert(3, SigItem(ks.public_key().data, b"secp", ks.sign(b"secp"),
                            key_type="secp256k1"))
    want.insert(3, True)
    items.insert(6, SigItem(kr.public_key().data, b"sr", kr.sign(b"sr"),
                            key_type="sr25519"))
    want.insert(6, True)
    # corrupt the sr25519 row's message
    items.append(SigItem(kr.public_key().data, b"sr!", kr.sign(b"sr"),
                         key_type="sr25519"))
    want.append(False)
    got = default_verifier().verify(items)
    assert got.tolist() == want


def test_device_challenge_path_matches_oracle():
    """device_challenge_min=0 forces SHA-512 challenges on device (the
    fused bulk-replay path); results must match the host oracle exactly,
    including rejects."""
    v = BatchVerifier(
        min_device_batch=0, device_challenge_min=0, bigtable_min=0
    )
    keys = _keypairs(9)
    items, want = [], []
    for i, k in enumerate(keys):
        msg = (b"bulk-%d " % i) * (i + 1)  # ragged lengths
        sig = k.sign(msg)
        if i % 3 == 1:
            msg = msg + b"?"  # tamper after signing
        if i % 3 == 2:
            sig = bytes([sig[0] ^ 1]) + sig[1:]  # corrupt R
        items.append(SigItem(k.public_key().data, msg, sig))
        want.append(host.verify(items[-1].pubkey, msg, items[-1].sig))
    got = v.verify(items)
    assert got.tolist() == want
    assert any(want) and not all(want)


def test_malformed_only_batch_rejects():
    """A device-size batch with zero well-formed rows returns all-False
    (no crash on the lazily-allocated table store)."""
    items = [
        SigItem(b"\x00" * 31, b"m%d" % i, b"\x00" * 64) for i in range(9)
    ]
    got = BatchVerifier().verify(items)
    assert got.shape == (9,) and not got.any()
