"""Differential tests: TPU batch verifier vs the host RFC 8032 oracle.

Mirrors the adversarial cases the reference's serial path handles in
crypto/ed25519 + x/crypto (bad points, malleable s, wrong everything)."""

import numpy as np
import pytest

from tendermint_tpu.crypto import ed25519 as host
from tendermint_tpu.crypto.batch_verifier import BatchVerifier, SigItem

# differential tests must exercise the DEVICE kernel even for tiny
# batches — min_device_batch=0 disables the host fast path that
# production uses for latency
_verifier = BatchVerifier(min_device_batch=0)


def default_verifier():
    return _verifier


def _keypairs(n, seed=b"bv"):
    ks = [host.PrivKey.from_secret(seed + bytes([i])) for i in range(n)]
    return ks


def test_valid_batch_accepts():
    keys = _keypairs(5)
    items = []
    for i, k in enumerate(keys):
        msg = b"vote-sign-bytes-%d" % i
        items.append(SigItem(k.public_key().data, msg, k.sign(msg)))
    got = default_verifier().verify(items)
    assert got.all()


def test_adversarial_rows_match_oracle():
    k = _keypairs(1)[0]
    pub = k.public_key().data
    msg = b"canonical vote"
    sig = k.sign(msg)

    # s' = s + L: same point equation, must be rejected (malleability)
    s_int = int.from_bytes(sig[32:], "little")
    sig_malleable = sig[:32] + (s_int + host.L).to_bytes(32, "little")

    items = [
        SigItem(pub, msg, sig),  # valid
        SigItem(pub, b"other msg", sig),  # wrong msg
        SigItem(pub, msg, sig[:32] + bytes(32)),  # s = 0 forgery
        SigItem(pub, msg, bytes(32) + sig[32:]),  # wrong R
        SigItem(pub, msg, sig_malleable),  # s >= L
        SigItem(host.P.to_bytes(32, "little"), msg, sig),  # bad pubkey (y=p)
        SigItem(bytes(31) + b"\x01", msg, sig),  # pubkey not on curve? oracle says
        SigItem(pub, msg, b"short"),  # malformed sig length
    ]
    got = default_verifier().verify(items)
    want = [host.verify(it.pubkey, it.msg, it.sig) for it in items]
    assert got.tolist() == want
    assert want[0] is True and not any(want[1:6]) and want[7] is False


def test_identity_pubkey_agrees_with_oracle():
    # y=1 encodes the identity point; Go x/crypto accepts sigs where R=[s]B.
    ident_pub = (1).to_bytes(32, "little")
    s = 12345
    R = host.point_compress(host.scalar_mult(s, host.BASEPOINT))
    sig = R + s.to_bytes(32, "little")
    msg = b"torsion"
    got = default_verifier().verify_one(ident_pub, msg, sig)
    assert got == host.verify(ident_pub, msg, sig)
    assert got is True  # documents the cofactorless-verify behavior


def test_mixed_large_batch():
    keys = _keypairs(11)
    items, want = [], []
    for i, k in enumerate(keys):
        msg = b"m%d" % i
        sig = k.sign(msg)
        if i % 3 == 1:
            sig = sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]  # corrupt s
        if i % 3 == 2:
            msg = msg + b"!"  # corrupt msg after signing
        items.append(SigItem(k.public_key().data, msg, sig))
        want.append(host.verify(items[-1].pubkey, items[-1].msg, items[-1].sig))
    got = default_verifier().verify(items)
    assert got.tolist() == want


def test_empty_batch():
    assert default_verifier().verify([]).shape == (0,)


def test_mixed_key_types_partition():
    """ed25519 rides the device; secp256k1/sr25519 partition to host and
    the bitmap re-interleaves (BASELINE config 4, mixed-key commits)."""
    from tendermint_tpu.crypto import secp256k1, sr25519

    ed = _keypairs(8)
    ks = secp256k1.PrivKey.from_secret(b"s1")
    kr = sr25519.PrivKey.from_secret(b"r1")
    items, want = [], []
    for i, k in enumerate(ed):
        msg = b"ed%d" % i
        items.append(SigItem(k.public_key().data, msg, k.sign(msg)))
        want.append(True)
    items.insert(3, SigItem(ks.public_key().data, b"secp", ks.sign(b"secp"),
                            key_type="secp256k1"))
    want.insert(3, True)
    items.insert(6, SigItem(kr.public_key().data, b"sr", kr.sign(b"sr"),
                            key_type="sr25519"))
    want.insert(6, True)
    # corrupt the sr25519 row's message
    items.append(SigItem(kr.public_key().data, b"sr!", kr.sign(b"sr"),
                         key_type="sr25519"))
    want.append(False)
    got = default_verifier().verify(items)
    assert got.tolist() == want


def test_device_challenge_path_matches_oracle():
    """device_challenge_min=0 forces SHA-512 challenges on device (the
    fused bulk-replay path); results must match the host oracle exactly,
    including rejects."""
    v = BatchVerifier(
        min_device_batch=0, device_challenge_min=0, bigtable_min=0
    )
    keys = _keypairs(9)
    items, want = [], []
    for i, k in enumerate(keys):
        msg = (b"bulk-%d " % i) * (i + 1)  # ragged lengths
        sig = k.sign(msg)
        if i % 3 == 1:
            msg = msg + b"?"  # tamper after signing
        if i % 3 == 2:
            sig = bytes([sig[0] ^ 1]) + sig[1:]  # corrupt R
        items.append(SigItem(k.public_key().data, msg, sig))
        want.append(host.verify(items[-1].pubkey, msg, items[-1].sig))
    got = v.verify(items)
    assert got.tolist() == want
    assert any(want) and not all(want)


def test_malformed_only_batch_rejects():
    """A device-size batch with zero well-formed rows returns all-False
    (no crash on the lazily-allocated table store)."""
    items = [
        SigItem(b"\x00" * 31, b"m%d" % i, b"\x00" * 64) for i in range(9)
    ]
    got = BatchVerifier().verify(items)
    assert got.shape == (9,) and not got.any()


def _adversarial_items():
    """Valid + adversarial rows incl. non-canonical and small-order
    inputs (the pad-inertness satellite's required coverage)."""
    k = _keypairs(1, seed=b"pad")[0]
    pub = k.public_key().data
    msg = b"padded lane probe"
    sig = k.sign(msg)
    s_int = int.from_bytes(sig[32:], "little")
    ident = (1).to_bytes(32, "little")  # small-order (identity) pubkey
    s = 777
    ident_sig = (
        host.point_compress(host.scalar_mult(s, host.BASEPOINT))
        + s.to_bytes(32, "little")
    )
    return [
        SigItem(pub, msg, sig),  # valid
        SigItem(pub, msg, sig[:32] + (s_int + host.L).to_bytes(32, "little")),
        SigItem(pub, b"other", sig),  # wrong msg
        SigItem(pub, msg, bytes(32) + sig[32:]),  # zero R
        SigItem(ident, b"torsion", ident_sig),  # small-order pubkey
        SigItem(host.P.to_bytes(32, "little"), msg, sig),  # y = p pubkey
        SigItem(pub, msg, b"short"),  # malformed length
    ]


def test_pad_to_bucket_is_verdict_inert():
    """Padded lanes never flip a real verdict: the same rows verified
    alone (bucket 8) and embedded in a larger batch (bucket 32, i.e. a
    different padded program + different pad-lane count) produce
    bit-identical verdicts, equal to the unpadded serial host reference
    — adversarial rows included. This is the tentpole's safety
    obligation: cross-subsystem coalescing changes every batch's padding
    but must never change an answer."""
    adv = _adversarial_items()
    want = [host.verify(it.pubkey, it.msg, it.sig) for it in adv]
    # the module _verifier has min_device_batch=0, so this 7-item batch
    # runs the bucket-8 DEVICE program — assert that, don't assume it
    before = _verifier._registry.snapshot()
    small = default_verifier().verify(adv)
    after = _verifier._registry.snapshot()
    assert (
        after["device_dispatch_count"] > before["device_dispatch_count"]
    ), "bucket-8 arm fell back to the host path"
    assert small.tolist() == want

    filler_keys = _keypairs(20, seed=b"fill")
    filler = [
        SigItem(k.public_key().data, b"fill%d" % i, k.sign(b"fill%d" % i))
        for i, k in enumerate(filler_keys)
    ]
    big = default_verifier().verify(adv + filler)
    assert big[: len(adv)].tolist() == want
    assert big[len(adv):].all()
    # and in a different position within the coalesced batch
    mixed = default_verifier().verify(filler + adv)
    assert mixed[len(filler):].tolist() == want


def test_shape_budget_bounded_with_bit_identical_verdicts():
    """The acceptance counter test: a node-lifetime's worth of ad-hoc
    batch sizes runs from the bounded bucket ladder — ≤ 8 distinct
    program shapes per tier on a fresh registry — while every verdict
    stays bit-identical to the serial host reference."""
    from tendermint_tpu.crypto.shape_registry import ShapeRegistry

    reg = ShapeRegistry()
    v = BatchVerifier(
        min_device_batch=0, bigtable_min=1 << 30, shape_registry=reg
    )
    keys = _keypairs(16, seed=b"budget")
    sizes = [1, 2, 5, 8, 9, 17, 31, 32, 33, 64, 100, 128]
    for n in sizes:
        items, want = [], []
        for i in range(n):
            k = keys[i % len(keys)]
            msg = b"h%d-%d" % (n, i)
            sig = k.sign(msg)
            if i % 5 == 3:
                sig = b"\x00" * 64  # forged row
            items.append(SigItem(k.public_key().data, msg, sig))
            want.append(host.verify(items[-1].pubkey, msg, sig))
        assert v.verify(items).tolist() == want
    # 12 ad-hoc sizes collapsed onto the ladder's small rungs, all at
    # the initial 128-row table allocation (one program per rung)
    assert reg.distinct_shapes("small") <= 8
    assert reg.buckets_by_tier()["small"] == (8, 32, 128)
    assert reg.shapes_by_tier()["small"] == (
        (8, 128, 1), (32, 128, 1), (128, 128, 1),
    )
    assert reg.dispatch_count() >= len(sizes)
    for tier, shapes in reg.shapes_by_tier().items():
        assert len(shapes) <= 8, f"tier {tier} exceeded budget: {shapes}"


def test_prewarm_buckets_covers_ladder_and_is_inert():
    """prewarm_buckets executes one program per (tier, ladder rung)
    without touching the table caches or producing accepts; a
    subsequent real verify reuses the recorded shapes (no new smalls)."""
    from tendermint_tpu.crypto.shape_registry import ShapeRegistry

    reg = ShapeRegistry(ladder=(8, 32))
    v = BatchVerifier(
        min_device_batch=0, bigtable_min=1 << 30, shape_registry=reg
    )
    entries = v.prewarm_buckets(tiers=("small", "generic"))
    assert {(e["tier"], e["bucket"]) for e in entries} == {
        ("small", 8), ("small", 32), ("generic", 8), ("generic", 32),
    }
    assert all(e["seconds"] >= 0 for e in entries)
    small_before = reg.shapes_by_tier()["small"]
    dispatches_before = reg.dispatch_count()
    k = _keypairs(1, seed=b"pw")[0]
    got = v.verify(
        [SigItem(k.public_key().data, b"post-warm", k.sign(b"post-warm"))]
    )
    assert got.tolist() == [True]
    # bucket 8 small was prewarmed: the verify added dispatches (incl.
    # its one-time build_small table build) but no new SMALL-tier shape
    assert reg.shapes_by_tier()["small"] == small_before
    assert reg.dispatch_count() > dispatches_before
