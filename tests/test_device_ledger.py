"""Device-cost ledger plane (PR 12): DispatchLedger accounting,
scheduler wiring, fill-efficiency health detector, on-demand profiling
hooks, the dump/profile RPC routes, and tools/device_report rendering.

The acceptance contracts pinned here:
- ledger totals reconcile with the shape-registry dispatch counters
  when a real BatchVerifier drives the rounds (same totals);
- recording overhead is far under 2% of the ~60-100 ms dispatch floor;
- the profiler-unavailable path is a STRUCTURED RPC error, not a crash.
"""

import asyncio
import json
import os
import threading
import time

import numpy as np
import pytest

from tendermint_tpu import obs
from tendermint_tpu.crypto import ed25519 as host
from tendermint_tpu.crypto.batch_verifier import BatchVerifier, SigItem
from tendermint_tpu.crypto.shape_registry import ShapeRegistry
from tendermint_tpu.libs.metrics import Registry, SchedulerMetrics
from tendermint_tpu.obs.health import OK, WARN, BurnRateSLO, HealthMonitor
from tendermint_tpu.obs.ledger import DispatchLedger
from tendermint_tpu.obs.profiler import ProfileCapture, ProfilerUnavailable
from tendermint_tpu.parallel.scheduler import VerifyScheduler
from tendermint_tpu.rpc.core import RPCCore
from tendermint_tpu.rpc.server import RPCError

pytestmark = pytest.mark.ledger

BAD = b"\x00" * 64


def _item(i: int, ok: bool = True) -> SigItem:
    return SigItem(b"\x01" * 32, b"m%d" % i, b"\x02" * 64 if ok else BAD)


class StubVerifier:
    def __init__(self, delay: float = 0.0):
        self.delay = delay
        self.batches = []

    def verify(self, items):
        if self.delay:
            time.sleep(self.delay)
        self.batches.append(list(items))
        return np.array([it.sig != BAD for it in items])


def _sched(stub=None, ledger=None, **kw) -> VerifyScheduler:
    return VerifyScheduler(
        verifier=stub or StubVerifier(),
        metrics=SchedulerMetrics(Registry("test")),
        ledger=ledger or DispatchLedger(),
        **kw,
    )


# --- DispatchLedger accounting ----------------------------------------------


def test_ledger_totals_and_per_class_attribution():
    led = DispatchLedger()
    # round 1: two classes share a 64-bucket round, 48 rows requested
    led.record_round(
        1.0,
        class_rows={"consensus": 32, "blocksync": 16},
        requested=48,
        dispatched=64,
        submissions=2,
        class_subs={"consensus": 1, "blocksync": 1},
        queue_wait_s=0.004,
        class_queue_wait={"consensus": 0.001, "blocksync": 0.003},
        host_prep_s=0.002,
        device_s=0.100,
    )
    # round 2: single-class full bucket
    led.record_round(
        2.0,
        class_rows={"consensus": 64},
        requested=64,
        dispatched=64,
        submissions=4,
        device_s=0.060,
    )
    # fn-lane round: books whole, no bucket padding attributable
    led.record_round(
        3.0,
        class_rows={"sequencer": 17},
        requested=17,
        dispatched=17,
        submissions=1,
        device_s=0.010,
        engine="fn",
    )
    s = led.summary()
    assert s["rounds"] == 3
    assert s["fn_rounds"] == 1
    assert s["rows_requested"] == 112  # sig rounds only
    assert s["rows_dispatched"] == 128
    assert s["fn_rows"] == 17
    assert s["padding_rows"] == 16
    assert s["fill_ratio"] == round(112 / 128, 4)
    assert s["device_seconds"] == pytest.approx(0.170)
    # device time attributed by row share: consensus got 32/48 of round
    # 1 plus all of round 2; fn round books whole to sequencer
    pc = s["per_class"]
    assert pc["consensus"]["device_seconds"] == pytest.approx(
        0.100 * (32 / 48) + 0.060, abs=1e-6
    )
    assert pc["blocksync"]["device_seconds"] == pytest.approx(
        0.100 * (16 / 48), abs=1e-6
    )
    assert pc["sequencer"]["device_seconds"] == pytest.approx(0.010)
    # shares sum to ~1.0 over the whole ledger
    assert sum(v["device_share"] for v in pc.values()) == pytest.approx(
        1.0, abs=0.01
    )
    # single-class rounds credit submissions without class_subs
    assert pc["consensus"]["submissions"] == 1 + 4
    assert pc["blocksync"]["queue_wait_seconds"] == pytest.approx(0.003)
    # amortization curve: the 64 bucket saw 2 rounds, 6 submissions
    assert s["by_bucket"]["64"] == {
        "rounds": 2, "rows_requested": 112, "submissions": 6,
    }
    assert s["requests_per_dispatch"] == pytest.approx(7 / 3, abs=1e-3)


def test_ledger_fill_percentiles_and_entry_ring():
    led = DispatchLedger(max_entries=8)
    for i in range(20):
        # fill alternates 0.25 / 1.0
        req = 16 if i % 2 else 64
        led.record_round(
            float(i), class_rows={"consensus": req}, requested=req,
            dispatched=64, device_s=0.001,
        )
    s = led.summary()
    # totals are exact despite the 8-entry ring...
    assert s["rounds"] == 20
    assert s["rows_dispatched"] == 20 * 64
    # ...while the fill window honestly flags the truncation
    assert s["fill_window_truncated"] is True
    assert len(led.entries()) == 8
    # percentiles over retained entries: half at 0.25, half at 1.0
    assert s["fill_ratio_p50"] in (0.25, 1.0)
    assert s["fill_ratio_p95"] == 1.0
    # entries() respects since_seq and limit
    assert [e["seq"] for e in led.entries(since_seq=18)] == [18, 19]
    assert len(led.entries(limit=3)) == 3


def test_ledger_mark_and_span_summary():
    led = DispatchLedger()
    led.record_round(
        1.0, class_rows={"light": 8}, requested=8, dispatched=8,
        device_s=0.5,
    )
    mark = led.mark()
    led.record_round(
        2.0, class_rows={"consensus": 24}, requested=24, dispatched=64,
        submissions=3, device_s=0.2,
    )
    s = led.summary(since=mark)
    # the span covers only the post-mark round
    assert s["rounds"] == 1
    assert s["rows_requested"] == 24
    assert s["padding_rows"] == 40
    assert s["device_seconds"] == pytest.approx(0.2)
    assert list(s["per_class"]) == ["consensus"]
    assert s["per_class"]["consensus"]["device_share"] == pytest.approx(
        1.0
    )
    # the span rebuild carries submissions and queue wait, not just
    # rows/device time — a single-class round's submissions belong to
    # its class even without an explicit class_subs map
    assert s["per_class"]["consensus"]["submissions"] == 3
    assert s["fill_window_truncated"] is False
    # ...and explicit per-class wait survives the span view too
    led.record_round(
        3.0, class_rows={"consensus": 4, "light": 4}, requested=8,
        dispatched=8, submissions=2,
        class_subs={"consensus": 1, "light": 1},
        class_queue_wait={"consensus": 0.002, "light": 0.005},
        device_s=0.1,
    )
    s2 = led.summary(since=mark)
    assert s2["per_class"]["light"]["queue_wait_seconds"] == pytest.approx(
        0.005
    )
    assert s2["per_class"]["light"]["submissions"] == 1


def test_ledger_thread_safety_under_concurrent_records():
    led = DispatchLedger()

    def hammer(klass):
        for i in range(500):
            led.record_round(
                float(i), class_rows={klass: 4}, requested=4,
                dispatched=8, device_s=0.001,
            )

    threads = [
        threading.Thread(target=hammer, args=(k,))
        for k in ("a", "b", "c")
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    s = led.summary()
    assert s["rounds"] == 1500
    assert s["rows_requested"] == 6000
    # seq ids never collide: the ring's newest entries are distinct
    seqs = [e["seq"] for e in led.entries()]
    assert len(seqs) == len(set(seqs))


# --- scheduler wiring --------------------------------------------------------


def test_scheduler_records_sig_and_fn_rounds():
    led = DispatchLedger()
    stub = StubVerifier(delay=0.02)
    s = _sched(stub, ledger=led)

    async def run():
        await s.start()
        first = asyncio.create_task(s.submit([_item(0)], "consensus"))
        await asyncio.sleep(0.005)
        await asyncio.gather(
            s.submit([_item(1), _item(2)], "consensus"),
            s.submit([_item(3)], "blocksync"),
            first,
        )
        await s.submit_fn(
            list(range(5)), lambda xs: [True] * len(xs), "sequencer"
        )
        await s.stop()

    asyncio.run(run())
    summ = led.summary()
    assert summ["rounds"] == 3  # solo round + coalesced round + fn
    assert summ["fn_rounds"] == 1
    assert summ["fn_rows"] == 5
    # the coalesced round carries both classes with their real rows
    coalesced = [
        e for e in led.entries()
        if e["engine"] == "sig" and len(e["classes"]) == 2
    ]
    assert len(coalesced) == 1
    assert coalesced[0]["rows"] == {"consensus": 2, "blocksync": 1}
    assert coalesced[0]["submissions"] == 2
    assert coalesced[0]["queue_wait_s"] > 0
    assert coalesced[0]["device_s"] > 0
    # tm_* accounting surface: device seconds per class, padding counter
    per_class = summ["per_class"]
    assert s.metrics.device_seconds.value(
        klass="consensus"
    ) == pytest.approx(per_class["consensus"]["device_seconds"], rel=0.05)
    assert s.metrics.padding_rows.value() == summ["padding_rows"]


def test_scheduler_dispatch_log_size_configurable():
    s = _sched(dispatch_log_size=4)
    assert s.dispatch_log.maxlen == 4
    # ledger is the accounting source of truth past the ring cap: the
    # docstring note is load-bearing, the behavior is what we pin
    led = s.ledger

    async def run():
        await s.start()
        for i in range(10):
            await s.submit([_item(i)], "consensus")
        await s.stop()

    asyncio.run(run())
    assert len(s.dispatch_log) == 4  # ring truncated
    assert led.summary()["rounds"] == 10  # ledger did not


def test_scheduler_ledger_reconciles_with_shape_registry():
    """Acceptance: ledger totals reconcile with the shape-registry
    dispatch counters — in steady state (key tables warm) every
    scheduler sig round is exactly one registry-recorded device
    dispatch, and the padded bucket the ledger booked is the bucket the
    verifier dispatched. (A COLD run records extra registry dispatches
    for the table-build programs — real device work that is not a
    scheduler round; warming first makes the comparison exact.)"""
    reg = ShapeRegistry()
    bv = BatchVerifier(min_device_batch=0, shape_registry=reg)
    led = DispatchLedger()
    s = VerifyScheduler(
        verifier=bv,
        metrics=SchedulerMetrics(Registry("test")),
        ledger=led,
    )
    k = host.PrivKey.from_secret(b"ledger-reconcile")
    pub = k.public_key().data

    def items(n, tag):
        return [
            SigItem(pub, b"%s-%d" % (tag, i), k.sign(b"%s-%d" % (tag, i)))
            for i in range(n)
        ]

    async def run():
        await s.start()
        # warm: builds the key's device table (its own registry
        # dispatch) and compiles the 8-bucket program
        assert (await s.submit(items(2, b"warm"), "consensus")).all()
        before = reg.snapshot()
        mark = led.mark()
        assert (await s.submit(items(5, b"a"), "consensus")).all()
        assert (await s.submit(items(11, b"b"), "blocksync")).all()
        await s.stop()
        return before, mark

    before, mark = asyncio.run(run())
    after = reg.snapshot()
    summ = led.summary(since=mark)
    sig_rounds = summ["rounds"] - summ["fn_rounds"]
    dispatches = (
        after["device_dispatch_count"] - before["device_dispatch_count"]
    )
    assert sig_rounds == dispatches == 2
    assert summ["rows_requested"] == 16
    # the ledger's dispatched rows are the verifier's padded buckets
    assert summ["rows_dispatched"] == sum(
        reg.bucket_for(n) for n in (5, 11)
    )
    assert summ["padding_rows"] == summ["rows_dispatched"] - 16


def test_ledger_recording_overhead_microbench():
    """Acceptance: ledger recording adds <2% to dispatch wall time. The
    dispatch floor is ~60-100 ms (PERF_ANALYSIS §10); 2% is >=1.2 ms
    per round. One record_round must land orders of magnitude under
    that — pin <=120 us/call mean so even a 60 ms round pays <0.2%."""
    led = DispatchLedger()
    class_rows = {"consensus": 48, "blocksync": 16}
    n = 2000
    t0 = time.perf_counter()
    for i in range(n):
        led.record_round(
            float(i),
            class_rows=class_rows,
            requested=64,
            dispatched=64,
            submissions=2,
            class_subs={"consensus": 1, "blocksync": 1},
            queue_wait_s=0.001,
            class_queue_wait={"consensus": 0.001, "blocksync": 0.002},
            host_prep_s=0.001,
            device_s=0.06,
        )
    per_call = (time.perf_counter() - t0) / n
    assert led.summary()["rounds"] == n
    assert per_call < 120e-6, (
        f"record_round {per_call * 1e6:.1f} us/call — ledger recording "
        "must stay noise against the ~60 ms dispatch floor"
    )


# --- fill-efficiency health detector ----------------------------------------


def test_fill_efficiency_detector_floor_and_min_rows():
    from tendermint_tpu.obs.health import FillEfficiencyDetector

    def slo():
        return BurnRateSLO(
            "fill", objective=0.8, short_window=30.0, long_window=300.0
        )

    det = FillEfficiencyDetector(slo(), floor=0.1, min_rows=256)
    # tiny intervals are never judged: a small committee's padded vote
    # rounds are a latency choice, not pageable waste
    t = 0.0
    for _ in range(20):
        t += 1.0
        det.observe_interval(t, 1.0, 64.0)  # fill 0.016 but 64 rows
    assert det.verdict(t) == OK
    # sustained 5%-full buckets at volume flags
    det2 = FillEfficiencyDetector(slo(), floor=0.1, min_rows=256)
    t = 0.0
    for _ in range(20):
        t += 1.0
        det2.observe_interval(t, 100.0, 2048.0)
    assert det2.verdict(t) >= WARN
    # healthy fill at volume stays OK
    det3 = FillEfficiencyDetector(slo(), floor=0.1, min_rows=256)
    t = 0.0
    for _ in range(20):
        t += 1.0
        det3.observe_interval(t, 1800.0, 2048.0)
    assert det3.verdict(t) == OK


def test_monitor_ledger_seam_flags_fill_floor():
    led = DispatchLedger()
    mon = HealthMonitor(
        tracer=obs.Tracer(enabled=True), fill_floor=0.1, fill_min_rows=256
    )
    mon.bind_ledger(led)
    t = 0.0
    for i in range(20):
        t += 1.0
        # each tick moves 2048 dispatched rows at 5% fill
        led.record_round(
            t, class_rows={"blocksync": 102}, requested=102,
            dispatched=2048, device_s=0.01,
        )
        mon.sample(t)
    assert mon.detectors["fill_efficiency"].verdict(t) >= WARN
    assert mon.subsystem_verdicts(t)["scheduler"] >= WARN
    # the verdict document names the detector
    doc = mon.verdict(t)
    assert "fill_efficiency" in doc["subsystems"]["scheduler"]["detectors"]


# --- profiling hooks ---------------------------------------------------------


def test_profile_capture_session_lifecycle(tmp_path):
    cap = ProfileCapture(str(tmp_path), sample_interval_s=0.002)
    assert cap.active is False
    started = cap.start(label="test", device=False)
    assert cap.active is True
    assert started["id"] == "profile_0001"
    assert started["device_trace"] == {"enabled": False}
    # a second start is the structured profiler-unavailable error
    with pytest.raises(ProfilerUnavailable):
        cap.start()
    # give the sampler a few ticks on this (busy) thread
    deadline = time.monotonic() + 0.2
    while time.monotonic() < deadline:
        sum(range(100))
    session = cap.stop()
    assert cap.active is False
    assert session["duration_s"] >= 0.0
    lp = session["loop_profile"]
    assert lp["samples"] >= 1
    assert os.path.exists(lp["path"])
    with open(lp["path"]) as f:
        doc = json.load(f)
    assert doc["samples"] == lp["samples"]
    assert doc["stacks"] and doc["stacks"][0]["count"] >= 1
    # stop with nothing running is the same structured error
    with pytest.raises(ProfilerUnavailable):
        cap.stop()
    # ids are monotonic across sessions
    assert cap.start(device=False)["id"] == "profile_0002"
    cap.stop()


def test_profile_capture_device_trace_guarded(tmp_path):
    """device=True must never raise out of start/stop: on a backend or
    environment where the jax profiler can't run, unavailability is a
    structured field inside device_trace."""
    cap = ProfileCapture(str(tmp_path), sample_interval_s=0.005)
    started = cap.start(device=True)
    assert "device_trace" in started
    assert isinstance(started["device_trace"].get("enabled"), bool)
    session = cap.stop()
    dt = session["device_trace"]
    if not dt["enabled"]:
        assert "error" in dt  # degraded structurally, not thrown


# --- RPC routes --------------------------------------------------------------


class _StubSched:
    def __init__(self, ledger):
        self.ledger = ledger


class _StubNode:
    class config:
        class rpc:
            unsafe = False

    def __init__(self, ledger=None, profiler=None):
        if ledger is not None:
            self.verify_scheduler = _StubSched(ledger)
        else:
            self.verify_scheduler = None
        if profiler is not None:
            self.profiler = profiler


def test_dump_dispatch_ledger_route(tmp_path):
    led = DispatchLedger()
    led.record_round(
        1.0, class_rows={"consensus": 6, "light": 2}, requested=8,
        dispatched=8, submissions=2, device_s=0.004,
    )
    core = RPCCore(_StubNode(ledger=led))
    out = core.dump_dispatch_ledger()
    assert out["enabled"] is True
    assert out["summary"]["rounds"] == 1
    assert out["summary"]["per_class"]["consensus"]["rows"] == 6
    assert len(out["entries"]) == 1
    assert "device_dispatch_count" in out["shape_registry"]
    # entries param caps the detail view
    for i in range(5):
        led.record_round(
            2.0 + i, class_rows={"consensus": 8}, requested=8,
            dispatched=8, device_s=0.001,
        )
    assert len(core.dump_dispatch_ledger(entries=3)["entries"]) == 3
    # entries=0 means summary-only, not "the whole ring"
    assert core.dump_dispatch_ledger(entries=0)["entries"] == []
    with pytest.raises(RPCError) as ei:
        core.dump_dispatch_ledger(entries="nope")
    assert ei.value.code == -32602


def test_profile_rpc_routes_and_structured_errors(tmp_path):
    cap = ProfileCapture(str(tmp_path), sample_interval_s=0.005)
    core = RPCCore(_StubNode(profiler=cap))
    routes = core.routes()
    assert "profile_start" in routes and "profile_stop" in routes
    # stop with no session: the profiler-unavailable structured error
    with pytest.raises(RPCError) as ei:
        core.profile_stop()
    assert ei.value.code == -32000
    assert "profiler unavailable" in str(ei.value.message)
    started = core.profile_start(label="rpc", device=False)
    assert started["started"] is True
    # double start: same structured error class
    with pytest.raises(RPCError) as ei:
        core.profile_start()
    assert ei.value.code == -32000
    time.sleep(0.03)
    stopped = core.profile_stop()
    assert stopped["stopped"] is True
    assert "loop_profile" in stopped
    # a node assembled WITHOUT a profiler does not expose the routes
    bare = RPCCore(_StubNode())
    assert "profile_start" not in bare.routes()
    assert "dump_dispatch_ledger" in bare.routes()
    # ...and its ledger dump reports the scheduler-less state honestly
    assert bare.dump_dispatch_ledger()["enabled"] is False


# --- tools/device_report -----------------------------------------------------


def _sample_summary():
    led = DispatchLedger()
    led.record_round(
        1.0, class_rows={"consensus": 48, "blocksync": 16},
        requested=64, dispatched=64, submissions=3, device_s=0.12,
        queue_wait_s=0.002,
    )
    led.record_round(
        2.0, class_rows={"lightserve": 100}, requested=100,
        dispatched=512, submissions=40, device_s=0.05,
    )
    return led.summary()


def test_device_report_extracts_every_supported_shape():
    from tools.device_report import extract_summary

    summary = _sample_summary()
    rpc_doc = {"result": {"enabled": True, "summary": summary}}
    bench_doc = {"metric": "x", "device_cost": summary}
    for doc in (rpc_doc, bench_doc, summary):
        assert extract_summary(doc)["rounds"] == 2
    with pytest.raises(ValueError):
        extract_summary({"metric": "x"})
    with pytest.raises(ValueError):
        extract_summary({"device_cost": {"no_rounds_key": 1}})


def test_device_report_renders_tables():
    from tools.device_report import report_text

    text = report_text(_sample_summary(), name="unit")
    assert "device-cost ledger: unit" in text
    # per-class table sorted by device share, lightserve's padding shows
    for token in (
        "consensus", "blocksync", "lightserve", "amortization curve",
        "fill p50",
    ):
        assert token in text
    # padding called out: 512-bucket round was 100/512 full
    assert "412 rows" in text
    # empty summary renders honestly
    assert "no scheduler rounds" in report_text(
        DispatchLedger().summary()
    )


def test_device_report_cli_roundtrip(tmp_path, capsys, monkeypatch):
    from tools import device_report

    art = {"metric": "bench", "device_cost": _sample_summary()}
    p = tmp_path / "BENCH_x.json"
    p.write_text(json.dumps(art))
    monkeypatch.setattr(
        "sys.argv", ["device_report.py", str(p), "--json"]
    )
    rc = device_report.main()
    assert rc == 0
    out = capsys.readouterr().out
    assert json.loads(out)["BENCH_x.json"]["rounds"] == 2
    # a document with no device-cost block is a clean nonzero exit
    bad = tmp_path / "nope.json"
    bad.write_text("{}")
    monkeypatch.setattr("sys.argv", ["device_report.py", str(bad)])
    assert device_report.main() == 1


# --- bench/trend integration -------------------------------------------------


def test_bench_trend_ingests_device_cost_block():
    import tools.bench_trend as bt

    payload = {
        "metric": "x_throughput",
        "value": 1.0,
        "device_cost": dict(
            _sample_summary(), fill_ratio_p50=0.9, fill_ratio_p95=0.2
        ),
    }
    rows = bt._ledger_rows(payload)
    by_metric = {r["metric"]: r for r in rows}
    assert by_metric["scheduler_fill_ratio_p50"]["value"] == 0.9
    assert by_metric["scheduler_fill_ratio_p95"]["value"] == 0.2
    frac = by_metric["scheduler_padding_fraction"]["value"]
    assert frac == pytest.approx(412 / 576, abs=1e-4)
    # padding regresses UPWARD: direction must be "lower is better"
    assert bt.direction_of("scheduler_padding_fraction") == "lower"
    assert bt.family_of("scheduler_padding_fraction") == "scheduler"
    # a zero-round block emits nothing (no eternal fill-0 regression)
    assert bt._ledger_rows({"device_cost": DispatchLedger().summary()}) == []
    # ...and so does a span of ONLY fn-lane rounds, whose fill
    # percentiles are a meaningless 0.0
    fn_led = DispatchLedger()
    fn_led.record_round(
        1.0, class_rows={"sequencer": 9}, requested=9, dispatched=9,
        device_s=0.01, engine="fn",
    )
    assert bt._ledger_rows({"device_cost": fn_led.summary()}) == []
    # and the rows ride _metric_rows as non-headline entries
    pairs = bt._metric_rows(payload)
    assert any(
        r["metric"] == "scheduler_padding_fraction" and not headline
        for r, headline in pairs
    )
