"""Tests for tmjson (amino JSON), HexBytes, rand, timer, sync watchdog."""

import asyncio
import dataclasses
from datetime import datetime, timezone

import pytest

from tendermint_tpu.libs import rand as tmrand
from tendermint_tpu.libs import tmjson
from tendermint_tpu.libs.bytes import HexBytes
from tendermint_tpu.libs.timer import ThrottleTimer


# --- tmjson ---------------------------------------------------------------


def test_int64_as_string_int32_as_number():
    """Reference doc.go: int64(64) -> "64", int32(32) -> 32."""
    assert tmjson.marshal(64) == b'"64"'
    assert tmjson.marshal(tmjson.Int32(32)) == b"32"
    assert tmjson.unmarshal(b'"64"', int) == 64
    assert tmjson.unmarshal(b"32", tmjson.Int32) == 32


def test_bytes_base64_hexbytes_hex():
    assert tmjson.marshal(b"\x01\x02\x03") == b'"AQID"'
    assert tmjson.marshal(HexBytes(b"\xde\xad")) == b'"DEAD"'
    assert tmjson.unmarshal(b'"AQID"', bytes) == b"\x01\x02\x03"
    assert tmjson.unmarshal(b'"DEAD"', HexBytes) == b"\xde\xad"


def test_time_rfc3339nano_utc():
    t = datetime(2026, 1, 2, 3, 4, 5, 600000, tzinfo=timezone.utc)
    raw = tmjson.marshal(t)
    assert raw == b'"2026-01-02T03:04:05.600000Z"'
    assert tmjson.unmarshal(raw, datetime) == t


@dataclasses.dataclass
class _Car:
    wheels: int = 4
    name: str = ""


@dataclasses.dataclass
class _Garage:
    vehicle: object = None


def test_interface_envelope_roundtrip():
    """Registered types wrap as {"type","value"} (types.go:17-31) and
    decode back to the class from the envelope alone."""
    tmjson.register_type(_Car, "test/Car")
    raw = tmjson.marshal(_Car(wheels=4, name="benz"))
    assert tmjson.unmarshal(raw) == _Car(wheels=4, name="benz")
    data = tmjson.unmarshal(raw, None)
    assert data.wheels == 4
    # nested inside an unregistered struct
    g = tmjson.unmarshal(tmjson.marshal(_Garage(vehicle=_Car(name="vw"))),
                         _Garage)
    assert g.vehicle == _Car(name="vw")


def test_register_conflict_rejected():
    with pytest.raises(ValueError):
        tmjson.register_type(_Garage, "test/Car")


def test_maps_require_string_keys():
    assert tmjson.marshal({"a": 1}) == b'{"a":"1"}'
    with pytest.raises(TypeError):
        tmjson.marshal({True: 1})


# --- HexBytes -------------------------------------------------------------


def test_hexbytes_str_and_fingerprint():
    h = HexBytes(bytes.fromhex("deadbeef"))
    assert str(h) == "DEADBEEF"
    assert h.fingerprint() == bytes.fromhex("deadbeef0000")


# --- rand -----------------------------------------------------------------


def test_rand_deterministic_after_seed():
    tmrand.seed(42)
    a = (tmrand.rand_str(12), tmrand.rand_bytes(8), tmrand.rand_intn(100))
    tmrand.seed(42)
    b = (tmrand.rand_str(12), tmrand.rand_bytes(8), tmrand.rand_intn(100))
    assert a == b
    assert len(a[0]) == 12 and a[0].isalnum()
    assert sorted(tmrand.rand_perm(10)) == list(range(10))


# --- ThrottleTimer --------------------------------------------------------


def test_throttle_timer_coalesces_burst():
    """A burst of set() calls fires once (throttle_timer.go:10-14)."""
    fires = []

    async def run():
        async def cb():
            fires.append(asyncio.get_running_loop().time())

        t = ThrottleTimer("test", 0.05, cb)
        for _ in range(10):
            t.set()
        await asyncio.sleep(0.12)
        assert len(fires) == 1
        # a second burst fires again
        t.set()
        t.set()
        await asyncio.sleep(0.12)
        assert len(fires) == 2
        t.stop()
        t.set()
        await asyncio.sleep(0.08)
        assert len(fires) == 2  # stopped: no more fires

    asyncio.run(run())


# --- sync watchdog --------------------------------------------------------


def test_watchdog_detects_blocked_loop(capsys):
    from tendermint_tpu.libs.sync import EventLoopWatchdog

    async def run():
        wd = EventLoopWatchdog(interval=0.05, misses=2)
        wd.start()
        import time

        time.sleep(0.4)  # block the loop (the bug class being detected)
        await asyncio.sleep(0.1)
        wd.stop()

    asyncio.run(run())
    err = capsys.readouterr().err
    assert "event loop stalled" in err
