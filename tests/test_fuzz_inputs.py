"""Seeded mutation fuzzers for the hostile-byte decoder surfaces.

The reference ships go-fuzz harnesses for the addrbook, PEX/secret-
connection inputs, and the JSON-RPC parser (test/fuzz/p2p/*,
test/fuzz/rpc/jsonrpc/ in /root/reference). These are the framework's
equivalents, shaped for CI: deterministic seeds, >=10k iterations per
target, bounded wall-clock. Two invariants per target:

  1. no uncaught exception — hostile bytes produce a bounded, typed
     failure (or a clean parse), never a raw decoder traceback;
  2. no acceptance of corrupted authenticated data — anything protected
     by a MAC/CRC that was actually mutated must be rejected.
"""

import asyncio
import json
import random
import struct
import zlib

from tendermint_tpu.consensus.wal import (
    WALCorruption,
    WALMessage,
    decode_records,
    encode_record,
)
from tendermint_tpu.crypto import aead
from tendermint_tpu.p2p.addrbook import AddrBook
from tendermint_tpu.p2p.mconn import ChannelDescriptor, MConnection
from tendermint_tpu.p2p.transport import NetAddress

ITERS = 10_000


def _mutate(rng: random.Random, data: bytes, max_mutations: int = 8) -> bytes:
    """Byte-level mutation: flips, overwrites, truncations, insertions."""
    b = bytearray(data)
    for _ in range(rng.randint(1, max_mutations)):
        op = rng.randrange(4)
        if op == 0 and b:  # flip a bit
            i = rng.randrange(len(b))
            b[i] ^= 1 << rng.randrange(8)
        elif op == 1 and b:  # overwrite a byte
            b[rng.randrange(len(b))] = rng.randrange(256)
        elif op == 2 and len(b) > 1:  # truncate
            b = b[: rng.randrange(1, len(b))]
        else:  # insert garbage
            i = rng.randrange(len(b) + 1)
            b[i:i] = bytes(rng.randrange(256) for _ in range(rng.randint(1, 4)))
    return bytes(b)


# --- WAL records -----------------------------------------------------------


def test_fuzz_wal_records():
    rng = random.Random(0xA1)
    base = b"".join(
        encode_record(WALMessage(kind=k, data=d, timestamp_ns=1))
        for k, d in [
            ("proposal", b"\x08\x01\x12\x04abcd"),
            ("vote", b"\x0a\x20" + bytes(32)),
            ("end_height", b""),
        ]
    )
    for i in range(ITERS):
        data = _mutate(rng, base)
        # strict mode: every outcome is a full decode or WALCorruption
        try:
            strict = list(decode_records(data, lenient=False))
        except WALCorruption:
            strict = None
        # lenient mode must NEVER raise (torn tails are expected)
        lenient = list(decode_records(data, lenient=True))
        if strict is not None:
            assert lenient == strict
        # CRC acceptance check: every surviving record's payload must
        # re-encode to a CRC-consistent record (the decoder only yields
        # CRC-verified payloads)
        for m in lenient:
            assert isinstance(m.kind, str)
            assert isinstance(m.data, bytes)


def test_fuzz_wal_crafted_crc_valid():
    """CRC-valid but structurally hostile payloads (an attacker editing
    the WAL can fix up CRCs) must surface as WALCorruption, not raw
    decoder exceptions."""
    rng = random.Random(0xBEEF)
    for i in range(ITERS):
        payload = bytes(
            rng.randrange(256) for _ in range(rng.randint(0, 24))
        )
        rec = struct.pack(">I", zlib.crc32(payload)) + struct.pack(
            ">I", len(payload)
        ) + payload
        try:
            list(decode_records(rec, lenient=False))
        except WALCorruption:
            pass
        assert list(decode_records(rec, lenient=True)) is not None


# --- secret-connection frames ----------------------------------------------


def test_fuzz_secretconn_frames():
    """Mutated sealed frames must NEVER open: ChaCha20-Poly1305 auth is
    the wire trust boundary (secret_connection.py _read_frame)."""
    rng = random.Random(0x5EC)
    key = bytes(range(32))
    nonce = b"\x00" * 12
    from tendermint_tpu.p2p.secret_connection import (
        DATA_MAX_SIZE,
        TOTAL_FRAME_SIZE,
    )

    frame = struct.pack("<I", DATA_MAX_SIZE) + bytes(DATA_MAX_SIZE)
    frame += b"\x00" * (TOTAL_FRAME_SIZE - len(frame))
    sealed = aead.seal(key, nonce, frame)
    opened = 0
    for i in range(ITERS):
        mutated = _mutate(rng, sealed, max_mutations=4)
        if mutated == sealed:
            continue
        try:
            aead.open_(key, nonce, mutated)
            opened += 1
        except ValueError:
            pass
    assert opened == 0, f"{opened} corrupted frames accepted"
    # the unmutated frame still opens (the loop above wasn't vacuous)
    assert aead.open_(key, nonce, sealed) == frame


# --- mconn packets ---------------------------------------------------------


class _ScriptedConn:
    """Feeds scripted packets to MConnection; records writes."""

    def __init__(self, packets):
        self.packets = list(packets)
        self.wrote = []
        self.closed = False

    async def read(self):
        if not self.packets:
            await asyncio.sleep(3600)
        return self.packets.pop(0)

    async def write(self, data):
        self.wrote.append(data)

    def close(self):
        self.closed = True


def test_fuzz_mconn_packets():
    """Hostile packet streams either deliver messages or kill the
    connection via on_error — nothing else escapes. ~30% of packets are
    raw garbage; the rest are mutations of valid channel-0x20 packets so
    reassembly and capacity paths get exercised too."""
    rng = random.Random(0xC04)
    results = {"recv": 0, "err": 0}

    async def run():
        i = 0
        while i < ITERS:
            batch = []
            for _ in range(min(64, ITERS - i)):
                i += 1
                if rng.random() < 0.3:
                    batch.append(
                        bytes(
                            rng.randrange(256)
                            for _ in range(rng.randint(0, 40))
                        )
                    )
                else:
                    valid = bytes([0x20, rng.randint(0, 1)]) + bytes(
                        rng.randrange(256) for _ in range(rng.randint(0, 30))
                    )
                    batch.append(_mutate(rng, valid, max_mutations=3))
            conn = _ScriptedConn(batch)
            died = asyncio.Event()

            async def on_recv(ch, msg):
                results["recv"] += 1

            async def on_err(err):
                results["err"] += 1
                died.set()

            m = MConnection(
                conn,
                [ChannelDescriptor(id=0x20, recv_message_capacity=256)],
                on_recv,
                on_err,
                ping_interval=3600,
            )
            m.start()
            # drain: either the batch empties or the connection dies
            for _ in range(2000):
                if died.is_set() or not conn.packets:
                    break
                await asyncio.sleep(0)
            await m.stop()

    asyncio.run(run())
    assert results["recv"] > 0, "no message ever delivered (vacuous fuzz)"
    assert results["err"] > 0, "no hostile stream ever killed a connection"


# --- addrbook JSON ---------------------------------------------------------


def test_fuzz_addrbook_json(tmp_path):
    """A corrupt on-disk address book (any byte damage) must never wedge
    startup: AddrBook loads what it can or starts empty."""
    rng = random.Random(0xADD)
    path = tmp_path / "addrbook.json"
    book = AddrBook(str(path))
    for i in range(12):
        book.add_address(
            NetAddress(f"{i:02x}" * 20, f"10.0.0.{i + 1}", 26656 + i)
        )
    book.save()
    base = path.read_bytes()
    for i in range(ITERS):
        path.write_bytes(_mutate(rng, base, max_mutations=6))
        b2 = AddrBook(str(path))  # must not raise
        assert b2.size() >= 0
    # pristine book still loads fully
    path.write_bytes(base)
    assert AddrBook(str(path)).size() == book.size()


# --- JSON-RPC requests -----------------------------------------------------


def test_fuzz_jsonrpc_requests():
    """Mutated HTTP bodies / GET targets always produce a JSON-RPC
    response object (or batch), never an exception."""
    from tendermint_tpu.rpc.server import RPCServer

    rng = random.Random(0x19C)

    class _Core:
        def routes(self):
            return {
                "echo": lambda **kw: kw,
                "boom": self._boom,
                "health": lambda: {},
            }

        def _boom(self, **kw):
            raise RuntimeError("handler exploded")

    srv = RPCServer.__new__(RPCServer)
    srv.core = _Core()

    seeds = [
        b'{"jsonrpc":"2.0","id":1,"method":"echo","params":{"a":1}}',
        b'{"jsonrpc":"2.0","id":2,"method":"boom","params":{}}',
        b'[{"method":"health"},{"method":"echo","params":[1,2]}]',
        b'{"method":"nope"}',
        b"5",
        b'"text"',
        b'{"method":5,"params":"x"}',
    ]

    async def run():
        for i in range(ITERS):
            body = _mutate(rng, seeds[i % len(seeds)], max_mutations=6)
            resp = await srv._dispatch_http("POST", "/", body)
            assert isinstance(resp, (dict, list))
            # GET path with hostile target
            target = "/" + "".join(
                chr(rng.randrange(32, 127)) for _ in range(rng.randint(0, 20))
            )
            resp = await srv._dispatch_http("GET", target, b"")
            assert isinstance(resp, dict)

    asyncio.run(run())


def test_fuzz_websocket_messages():
    """Hostile-shape WS messages (non-object requests, non-object
    params, unhashable queries) get JSON-RPC errors or are ignored — the
    connection task must survive every one and then serve a valid
    subscribe."""
    from tendermint_tpu.rpc.server import RPCServer, _ws_frame

    class _Sub:
        async def next(self):
            await asyncio.sleep(3600)

    class _Core:
        def routes(self):
            return {"health": lambda: {}}

        def subscribe_ws(self, cid, q):
            return _Sub()

        def unsubscribe_ws(self, cid, q):
            pass

        def encode_event(self, msg):
            return {}

    srv = RPCServer.__new__(RPCServer)
    srv.core = _Core()
    srv._ws_tasks = set()
    srv._conns = set()

    hostile = [
        b"5",
        b'"x"',
        b"[1,2]",
        b'{"method":"subscribe","params":"notdict"}',
        b'{"method":"subscribe","params":{"query":[1]}}',
        b'{"method":"unsubscribe","params":{"query":{"a":1}}}',
        b'{"method":"subscribe","params":{"query":5}}',
        b'{"method":5}',
        b"\xff\xfe not json",
    ]

    async def run():
        server = await asyncio.start_server(
            srv._handle_conn, "127.0.0.1", 0
        )
        port = server.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(
            b"GET / HTTP/1.1\r\nUpgrade: websocket\r\n"
            b"Sec-WebSocket-Key: dGVzdA==\r\n\r\n"
        )
        await writer.drain()
        await reader.readuntil(b"\r\n\r\n")  # 101 response
        for msg in hostile:
            writer.write(_ws_frame(msg))
        # after all the abuse, a valid subscribe must still answer
        writer.write(
            _ws_frame(
                b'{"id":9,"method":"subscribe",'
                b'"params":{"query":"tm.event=\'NewBlock\'"}}'
            )
        )
        await writer.drain()
        deadline = asyncio.get_running_loop().time() + 10
        ok = False
        while asyncio.get_running_loop().time() < deadline:
            frame = await asyncio.wait_for(reader.read(4096), 10)
            assert frame, "server dropped the connection on hostile input"
            if b'"id": 9' in frame or b'"id":9' in frame:
                ok = True
                break
        assert ok, "valid subscribe never answered after hostile messages"
        writer.close()
        server.close()
        await server.wait_closed()
        for t in srv._ws_tasks:
            t.cancel()

    asyncio.run(run())
