"""Unit tests for the host utility runtime (libs layer)."""

import asyncio
import os
from io import BytesIO

import pytest

from tendermint_tpu.libs import autofile, bits, protoio, pubsub
from tendermint_tpu.libs.events import EventSwitch
from tendermint_tpu.libs.service import Service


# --- protoio --------------------------------------------------------------


def test_uvarint_roundtrip():
    for n in [0, 1, 127, 128, 300, 2**32, 2**63 - 1]:
        buf = BytesIO(protoio.write_uvarint(n))
        assert protoio.read_uvarint(buf) == n


def test_delimited_roundtrip():
    payload = b"canonical vote bytes"
    framed = protoio.marshal_delimited(payload)
    assert protoio.read_delimited(BytesIO(framed)) == payload


def test_field_encoding_roundtrip():
    msg = (
        protoio.field_varint(1, 42)
        + protoio.field_bytes(2, b"hash")
        + protoio.field_sfixed64(3, -7)
        + protoio.field_varint(4, 0)  # zero omitted
    )
    fields = protoio.decode_fields(msg)
    assert fields[1] == [42]
    assert fields[2] == [b"hash"]
    assert fields[3] == [-7]
    assert 4 not in fields


def test_negative_varint_is_64bit_twos_complement():
    data = protoio.write_varint(-1)
    assert protoio.read_uvarint(BytesIO(data)) == 2**64 - 1


# --- bits -----------------------------------------------------------------


def test_bitarray_ops():
    a = bits.BitArray.from_indices(10, [1, 3, 5])
    b = bits.BitArray.from_indices(10, [3, 4])
    assert a.get(3) and not a.get(2)
    assert a.or_(b).ones() == [1, 3, 4, 5]
    assert a.and_(b).ones() == [3]
    assert a.sub(b).ones() == [1, 5]
    assert a.not_().ones() == [0, 2, 4, 6, 7, 8, 9]
    assert a.num_set() == 3
    rt = bits.BitArray.from_bytes(10, a.to_bytes())
    assert rt == a
    idx, ok = a.pick_random()
    assert ok and idx in (1, 3, 5)
    assert not bits.BitArray(4).pick_random()[1]


def test_bitarray_out_of_range():
    a = bits.BitArray(4)
    assert not a.set(4, True)
    assert not a.get(-1)


# --- pubsub query ---------------------------------------------------------


def test_query_matching():
    q = pubsub.Query("tm.event = 'NewBlock' AND block.height > 5")
    assert q.matches({"tm.event": ["NewBlock"], "block.height": ["6"]})
    assert not q.matches({"tm.event": ["NewBlock"], "block.height": ["5"]})
    assert not q.matches({"tm.event": ["Tx"], "block.height": ["6"]})
    assert not q.matches({"tm.event": ["NewBlock"]})


def test_query_exists_contains():
    q = pubsub.Query("account.owner EXISTS AND tx.hash CONTAINS 'abc'")
    assert q.matches({"account.owner": ["x"], "tx.hash": ["zzabczz"]})
    assert not q.matches({"tx.hash": ["zzabczz"]})


def test_pubsub_publish_subscribe():
    async def run():
        srv = pubsub.PubSubServer()
        sub = srv.subscribe("client1", pubsub.Query("tm.event = 'Tx'"))
        await srv.publish("blk", {"tm.event": ["NewBlock"]})
        await srv.publish("tx1", {"tm.event": ["Tx"]})
        msg = await asyncio.wait_for(sub.next(), 1)
        assert msg.data == "tx1"
        srv.unsubscribe_all("client1")
        with pytest.raises(pubsub.SubscriptionCancelled):
            await sub.next()

    asyncio.run(run())


# --- events ---------------------------------------------------------------


def test_event_switch():
    sw = EventSwitch()
    got = []
    sw.add_listener("l1", "step", got.append)
    sw.fire_event("step", 1)
    sw.remove_listener("l1")
    sw.fire_event("step", 2)
    assert got == [1]


# --- service --------------------------------------------------------------


def test_service_lifecycle():
    async def run():
        class S(Service):
            started = stopped = False

            async def on_start(self):
                self.started = True

            async def on_stop(self):
                self.stopped = True

        s = S("test")
        await s.start()
        assert s.is_running and s.started
        with pytest.raises(RuntimeError):
            await s.start()
        await s.stop()
        assert s.stopped and not s.is_running
        await s.wait_stopped()

    asyncio.run(run())


# --- autofile -------------------------------------------------------------


def test_autofile_rotation(tmp_path):
    head = str(tmp_path / "wal")
    g = autofile.Group(head, head_size_limit=100)
    for i in range(30):
        g.write(b"x" * 10)
        g.check_head_size_limit()
    g.sync()
    assert g.max_index() >= 0  # rotated at least once
    data = g.read_all()
    assert data == b"x" * 300
    g.close()


def test_autofile_total_size_prune(tmp_path):
    head = str(tmp_path / "wal")
    g = autofile.Group(head, head_size_limit=50, total_size_limit=120)
    for _ in range(40):
        g.write(b"y" * 10)
        g.check_head_size_limit()
    total = len(g.read_all())
    assert total <= 170  # oldest chunks pruned
    g.close()
