"""RPC clients (HTTP keep-alive, websocket, local) + light proxy over a
live node.

Reference: rpc/client/http tests + light/proxy — the clients drive the
same route table the server exposes (rpc/core/routes.go:10-43), and the
proxy answers /commit //validators only after light verification.
"""

import asyncio

import pytest

from tendermint_tpu.node.node import Node, init_files
from tendermint_tpu.rpc.client import (
    HTTPClient,
    LocalClient,
    RPCClientError,
    WSClient,
)

from .test_node import make_test_config


def test_http_ws_local_clients(tmp_path):
    cfg = make_test_config(tmp_path)
    init_files(cfg)
    node = Node(cfg)

    async def run():
        await node.start()
        await node.consensus.wait_for_height(3, timeout=60)
        addr = f"127.0.0.1:{node.rpc_server.port}"

        # --- HTTP keep-alive: several calls on one connection
        http = HTTPClient(addr)
        status = await http.status()
        assert int(status["sync_info"]["latest_block_height"]) >= 3
        block = await http.block(height=2)
        assert block["block"]["header"]["height"] == 2
        commit = await http.commit(height=2)
        assert commit["signed_header"]["commit"]["height"] == 2
        vals = await http.validators(height=2)
        assert vals["count"] >= 1
        with pytest.raises(RPCClientError):
            await http.call("nope_not_a_route")
        await http.close()

        # --- local client: same surface, no socket
        local = LocalClient(node)
        st2 = await local.status()
        assert (
            st2["node_info"]["id"] == status["node_info"]["id"]
        )

        # --- websocket: rpc over ws + event subscription
        ws = WSClient(addr)
        await ws.connect()
        # health is no longer the reference's `{}` stub: it carries the
        # node identity, sync position, and the monitor verdict
        h = await ws.call("health")
        assert h["node_id"] == status["node_info"]["id"]
        assert int(h["latest_block_height"]) >= 3
        assert h["catching_up"] is False
        assert h["monitored"] is True
        assert h["status"] in ("ok", "warn", "critical")
        dump = await ws.call("dump_health")
        assert dump["enabled"] is True
        assert "consensus" in dump["subsystems"]
        assert "quorum_lag" in dump["subsystems"]["consensus"]["detectors"]
        # device-cost ledger route (PR 12): the dump carries the
        # summary block, the recent entries, and the shape-registry
        # counters it reconciles against
        led = await ws.call("dump_dispatch_ledger")
        assert led["enabled"] is True
        assert "per_class" in led["summary"]
        assert "fill_ratio_p50" in led["summary"]
        assert "device_dispatch_count" in led["shape_registry"]
        assert isinstance(led["entries"], list)
        events = await ws.subscribe("tm.event = 'NewBlock'")
        ev = await asyncio.wait_for(events.__anext__(), 30)
        assert ev["query"] == "tm.event = 'NewBlock'"
        assert int(ev["data"]["value"]["header"]["height"]) >= 1
        await ws.unsubscribe("tm.event = 'NewBlock'")
        await ws.close()

        await node.stop()

    asyncio.run(run())


def test_light_proxy_serves_verified_data(tmp_path):
    """LightProxy: /commit and /validators come from the light client's
    verification; /abci_query forwards (reference light/proxy/routes.go)."""
    from tendermint_tpu.light.client import LightClient, TrustOptions
    from tendermint_tpu.light.proxy import LightProxy
    from tendermint_tpu.light.store import LightStore
    from tendermint_tpu.rpc.light_provider import RPCProvider
    from tendermint_tpu.store.kv import MemKV

    cfg = make_test_config(tmp_path)
    init_files(cfg)
    node = Node(cfg)

    async def run():
        await node.start()
        await node.consensus.wait_for_height(3, timeout=60)
        addr = f"127.0.0.1:{node.rpc_server.port}"

        # trust root: height 1 from the node itself
        http = HTTPClient(addr)
        c1 = await http.commit(height=1)
        root_hash = bytes.fromhex(
            c1["signed_header"]["header_hash"]
        ) if "header_hash" in c1["signed_header"] else None
        if root_hash is None:
            b1 = await http.block(height=1)
            root_hash = bytes.fromhex(b1["block_id"]["hash"])
        chain_id = node.genesis.chain_id

        provider = RPCProvider(chain_id, addr)
        lc = LightClient(
            chain_id,
            TrustOptions(3600 * 10**9, 1, root_hash),
            provider,
            [RPCProvider(chain_id, addr)],
            LightStore(MemKV()),
        )
        proxy = LightProxy(lc, addr, listen_port=0)
        await proxy.start()

        pc = HTTPClient(f"127.0.0.1:{proxy.listen_port}")
        commit = await pc.commit(height=2)
        assert commit["canonical"] is True
        assert commit["signed_header"]["header"]["height"] == 2

        vals = await pc.validators(height=2)
        assert vals["count"] >= 1

        # block forwarding cross-checks the verified hash
        blk = await pc.block(height=2)
        assert blk["block"]["header"]["height"] == 2

        st = await pc.status()
        assert st["sync_info"]["latest_block_height"] >= 2

        await pc.close()
        await http.close()
        await proxy.stop()
        await node.stop()

    asyncio.run(run())


def test_openapi_doc_matches_route_table():
    """rpc/openapi.yaml (reference rpc/openapi/openapi.yaml role) must
    list exactly the live route table — doc drift fails here."""
    import os
    import re

    from tendermint_tpu.rpc.core import RPCCore

    class _N:
        # any assembled serving plane exposes the lightserve proof
        # routes; the doc describes the full surface, so the stub
        # carries one — likewise the profiler behind the
        # profile_start/profile_stop hooks
        lightserve = object()
        profiler = object()

        class config:
            class rpc:
                unsafe = True

    live = set(RPCCore(_N()).routes())
    path = os.path.join(
        os.path.dirname(__file__),
        "..",
        "tendermint_tpu",
        "rpc",
        "openapi.yaml",
    )
    doc = set(re.findall(r"^\s+- ([a-z_]+)\s+#", open(path).read(), re.M))
    assert live == doc, (
        f"openapi drift: missing={sorted(live - doc)} "
        f"stale={sorted(doc - live)}"
    )
