"""Light client: bisection, sequential, backwards, detector, providers.

Mirrors the reference suite (light/client_test.go 20 tests + detector_test.go
7 tests) in compressed form over an in-memory chain generator.
"""

import asyncio

import pytest

from tendermint_tpu.light import LightBlock, LightClient, TrustOptions
from tendermint_tpu.light.client import (
    ErrLightClientAttack,
    LightClientError,
)
from tendermint_tpu.light.store import LightStore
from tendermint_tpu.store.kv import MemKV
from tendermint_tpu.types.block import Header
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.validator import Validator
from tendermint_tpu.types.validator_set import ValidatorSet
from tendermint_tpu.types.priv_validator import MockPV
from tendermint_tpu.types.vote import Vote, VoteType
from tendermint_tpu.types.vote_set import VoteSet

CHAIN_ID = "light-chain"
T0 = 1_700_000_000 * 1_000_000_000
BLOCK_NS = 1_000_000_000  # 1s blocks
PERIOD = 3600 * 1_000_000_000  # 1h trusting period


def make_chain(n, n_vals=4, seed=b"light", fork_at=None, fork_seed=b"fork"):
    """n LightBlocks with a static validator set; optionally fork from
    height `fork_at` (different app hashes => different header hashes)."""
    pvs = [MockPV.from_secret(seed + b"%d" % i) for i in range(n_vals)]
    vs = ValidatorSet([Validator(pv.get_pub_key(), 10) for pv in pvs])
    by_addr = {pv.get_pub_key().address(): pv for pv in pvs}
    ordered = [by_addr[v.address] for v in vs.validators]

    blocks = []
    last_id = BlockID()
    for h in range(1, n + 1):
        forked = fork_at is not None and h >= fork_at
        header = Header(
            chain_id=CHAIN_ID,
            height=h,
            time_ns=T0 + h * BLOCK_NS,
            last_block_id=last_id,
            validators_hash=vs.hash(),
            next_validators_hash=vs.hash(),
            app_hash=(fork_seed if forked else b"app") + b"-%d" % h,
            proposer_address=vs.validators[0].address,
        )
        bid = BlockID(header.hash(), part_set_header=__import__(
            "tendermint_tpu.types.part_set", fromlist=["PartSetHeader"]
        ).PartSetHeader(1, header.hash()))
        votes = VoteSet(CHAIN_ID, h, 0, VoteType.PRECOMMIT, vs)
        for i, pv in enumerate(ordered):
            v = Vote(
                type=VoteType.PRECOMMIT,
                height=h,
                round=0,
                block_id=bid,
                timestamp_ns=header.time_ns,
                validator_address=pv.get_pub_key().address(),
                validator_index=i,
            )
            pv.sign_vote(CHAIN_ID, v)
            votes.add_vote(v, verified=True)
        blocks.append(LightBlock(header, votes.make_commit(), vs))
        last_id = bid
    return blocks


class MockProvider:
    def __init__(self, blocks, name="primary", fail_heights=()):
        self.blocks = {b.height: b for b in blocks}
        self.name = name
        self.fail_heights = set(fail_heights)
        self.requests = []

    async def light_block(self, height):
        if height == 0:
            height = max(self.blocks)
        self.requests.append(height)
        if height in self.fail_heights:
            return None
        return self.blocks.get(height)

    def id(self):
        return self.name


def make_client(chain, *, witnesses=None, store=None, now=None, **kw):
    primary = MockProvider(chain)
    witnesses = witnesses if witnesses is not None else [
        MockProvider(chain, name="witness-0")
    ]
    store = store or LightStore(MemKV())
    trust = TrustOptions(PERIOD, 1, chain[0].header.hash())
    return LightClient(
        CHAIN_ID,
        trust,
        primary,
        witnesses,
        store,
        now_ns=now or (lambda: T0 + 200 * BLOCK_NS),
        **kw,
    )


def test_bisection_verifies_distant_header():
    chain = make_chain(100)
    c = make_client(chain)
    lb = asyncio.run(c.verify_light_block_at_height(100))
    assert lb.height == 100
    # bisection must NOT fetch every height (static valset -> direct jump)
    assert len(c.primary.requests) < 20
    assert c.last_trusted_height() == 100


def test_sequential_verifies_every_header():
    chain = make_chain(10)
    c = make_client(chain, sequential=True)
    lb = asyncio.run(c.verify_light_block_at_height(10))
    assert lb.height == 10
    assert len([h for h in c.primary.requests if h <= 10]) >= 9


def test_expired_trusting_period_rejected():
    chain = make_chain(10)
    # now is far beyond T0 + period
    c = make_client(chain, now=lambda: T0 + PERIOD + 1000 * BLOCK_NS)
    with pytest.raises((LightClientError, Exception)):
        asyncio.run(c.verify_light_block_at_height(10))


def test_backwards_verification():
    chain = make_chain(50)
    c = make_client(chain)
    asyncio.run(c.verify_light_block_at_height(50))
    lb = asyncio.run(c.verify_light_block_at_height(20))
    assert lb.height == 20
    # hash-chain walked down from 50
    assert c.store.get(20) is not None


def test_detector_catches_forked_primary():
    """Primary serves a forked chain; honest witness diverges -> the
    client must detect the fork and surface attack evidence
    (reference detector_test.go TestLightClientAttackEvidence)."""
    honest = make_chain(40)
    forked = make_chain(40, fork_at=21)
    # primary is byzantine (forked), witness honest: common prefix 1..20
    store = LightStore(MemKV())
    trust = TrustOptions(PERIOD, 1, honest[0].header.hash())
    c = LightClient(
        CHAIN_ID,
        trust,
        MockProvider(forked, name="byzantine-primary"),
        [MockProvider(honest, name="honest-witness")],
        store,
        now_ns=lambda: T0 + 200 * BLOCK_NS,
    )
    with pytest.raises(ErrLightClientAttack) as ei:
        asyncio.run(c.verify_light_block_at_height(40))
    ev = ei.value.evidence
    assert ev.common_height <= 20
    assert ev.total_voting_power == 40
    # the evidence must package the PRIMARY's forked block (the one honest
    # full nodes will find conflicting), not the witness's honest block
    conflicting = Header.decode(ev.conflicting_header)
    assert conflicting.hash() != honest[conflicting.height - 1].header.hash()
    assert (
        conflicting.hash() == forked[conflicting.height - 1].header.hash()
    )


def test_conflicting_witness_at_trust_root_is_hard_error():
    """A witness that disagrees at the trust root is a misconfiguration
    (reference compareFirstHeaderWithWitnesses :1156 returns the error)."""
    chain = make_chain(30)
    garbage = make_chain(30, seed=b"other")  # different chain entirely
    c = make_client(
        chain, witnesses=[MockProvider(garbage, name="bad-witness")]
    )
    with pytest.raises(LightClientError, match="trust root"):
        asyncio.run(c.verify_light_block_at_height(30))


def test_bad_witness_removed_good_witness_matches():
    """A witness serving an unverifiable conflicting block is removed;
    the good witness cross-references fine (reference detector.go:76-83)."""
    import copy

    chain = make_chain(30)
    bad_chain = list(chain)
    # corrupt the tip: header tampered, commit no longer signs it
    tampered = copy.deepcopy(chain[29])
    tampered.header.app_hash = b"tampered"
    tampered.header._hash = None  # invalidate the cached header hash
    bad_chain[29] = tampered
    c = make_client(
        chain,
        witnesses=[
            MockProvider(bad_chain, name="bad-witness"),
            MockProvider(chain, name="good-witness"),
        ],
    )
    lb = asyncio.run(c.verify_light_block_at_height(30))
    assert lb.height == 30
    assert [w.id() for w in c.witnesses] == ["good-witness"]


def test_primary_replaced_when_missing_blocks():
    chain = make_chain(30)
    primary = MockProvider(chain, fail_heights={30})
    store = LightStore(MemKV())
    trust = TrustOptions(PERIOD, 1, chain[0].header.hash())
    c = LightClient(
        CHAIN_ID,
        trust,
        primary,
        [
            MockProvider(chain, name="witness-0"),
            MockProvider(chain, name="witness-1"),
        ],
        store,
        now_ns=lambda: T0 + 200 * BLOCK_NS,
    )
    lb = asyncio.run(c.verify_light_block_at_height(30))
    assert lb.height == 30
    assert c.primary.id() == "witness-0"
    # the demoted primary joined the witness set
    assert "primary" in [w.id() for w in c.witnesses]


def test_store_pruning_bounds_size():
    chain = make_chain(60)
    c = make_client(chain, pruning_size=5, sequential=True)
    asyncio.run(c.verify_light_block_at_height(60))
    assert len(c.store.heights()) <= 5
    assert c.last_trusted_height() == 60


def test_restart_resumes_from_store():
    chain = make_chain(20)
    kv = MemKV()
    c1 = make_client(chain, store=LightStore(kv))
    asyncio.run(c1.verify_light_block_at_height(20))
    # new client over the same kv, no trust options needed
    c2 = LightClient(
        CHAIN_ID,
        None,
        MockProvider(chain),
        [MockProvider(chain, name="w")],
        LightStore(kv),
        trusting_period_ns=PERIOD,
        now_ns=lambda: T0 + 200 * BLOCK_NS,
    )
    lb = asyncio.run(c2.initialize())
    assert lb.height == 20
