"""Verify-as-a-service (parallel/verify_service.py): the split-brain
deployment where one device-owning scheduler process serves a whole
committee over UDS IPC.

Covers the wire protocol, cross-CLIENT round coalescing (the in-proc
proof of the cross-PROCESS design), per-client FIFO, the wire fn lanes
(bls_agg / secp_recover), the degradation contract (socket death
mid-flight resolves every pending submission through the LOCAL verifier
with a structured event — never a hang, never a dropped verdict),
reconnect-with-backoff, the service's stats/dump surface, node assembly
under `[scheduler] remote_socket`, the ipc_round_trip health detector,
the chaos kill/restart liveness property, and the satellite tooling
(testnet generator flag, device-report tenant table, bench-trend
ingestion). One test crosses a REAL process boundary via the
`python -m tendermint_tpu verify-service` entrypoint.
"""

from __future__ import annotations

import asyncio
import json
import os
import threading
import time

import numpy as np
import pytest

from tendermint_tpu import obs
from tendermint_tpu.crypto.batch_verifier import SigItem
from tendermint_tpu.parallel.scheduler import (
    VerifyScheduler,
    set_default_scheduler,
)
from tendermint_tpu.parallel.verify_service import (
    MSG_ERROR,
    MSG_STATS,
    MSG_STATS_RESULT,
    MSG_SUBMIT,
    RemoteVerifyScheduler,
    ServiceThread,
    WireError,
    _Cursor,
    _HDR,
    decode_fn_results,
    decode_submit,
    decode_submit_fn,
    decode_verdicts,
    encode_error,
    encode_fn_results,
    encode_submit,
    encode_submit_fn,
    encode_verdicts,
    read_frame,
    write_frame,
)

pytestmark = pytest.mark.verify_service


class SigTagVerifier:
    """Deterministic stub: verdict = sig starts with b'1' (per-item, so
    alignment bugs across coalesced slices are visible)."""

    def verify(self, items):
        return np.array(
            [it.sig[:1] == b"1" for it in items], dtype=bool
        )


class GateVerifier:
    """Blocks every round on an externally-held gate: submissions
    arriving while a round is in flight must coalesce into the next."""

    def __init__(self):
        self.gate = threading.Event()

    def verify(self, items):
        assert self.gate.wait(30), "gate never released"
        return np.ones(len(items), dtype=bool)


def sig_items(n: int, good=lambda i: True) -> list[SigItem]:
    return [
        SigItem(
            b"p" * 32,
            b"m%06d" % i + b"\x00" * 26,
            (b"1" if good(i) else b"0") + b"s" * 63,
        )
        for i in range(n)
    ]


def service(tmp_path, verifier=None, **kw) -> ServiceThread:
    """ServiceThread on a fresh socket. verifier None = the SigTag
    stub (protocol tests); False = the real process verifier (live-net
    tests, whose votes carry genuine signatures)."""
    os.makedirs(str(tmp_path), exist_ok=True)
    path = os.path.join(str(tmp_path), "verify.sock")
    if verifier is None:
        verifier = SigTagVerifier()
    sched = (
        VerifyScheduler()
        if verifier is False
        else VerifyScheduler(verifier=verifier)
    )
    svc = ServiceThread(path, scheduler=sched, **kw)
    svc.start()
    return svc


async def connect(path, **kw) -> RemoteVerifyScheduler:
    remote = RemoteVerifyScheduler(path, retry_base=0.02, **kw)
    await remote.start()
    deadline = time.monotonic() + 15
    while not remote.connected and time.monotonic() < deadline:
        await asyncio.sleep(0.01)
    assert remote.connected, "client never attached"
    return remote


# --- wire protocol ----------------------------------------------------------


def test_wire_codec_roundtrips():
    items = [
        SigItem(b"p" * 32, b"m" * 40, b"s" * 64),
        SigItem(b"q" * 33, b"", b"t" * 64, "secp256k1"),
    ]
    cur = _Cursor(encode_submit(7, items, "blocksync"))
    typ, req = _HDR.unpack(cur.take(_HDR.size))
    assert (typ, req) == (MSG_SUBMIT, 7)
    got, klass = decode_submit(cur)
    assert klass == "blocksync"
    assert [
        (i.pubkey, i.msg, i.sig, i.key_type) for i in got
    ] == [(i.pubkey, i.msg, i.sig, i.key_type) for i in items]

    verdicts = np.array([True, False, True, True, False] * 3)
    cur = _Cursor(encode_verdicts(9, verdicts))
    cur.take(_HDR.size)
    assert decode_verdicts(cur).tolist() == verdicts.tolist()
    cur = _Cursor(encode_verdicts(9, np.zeros(0, dtype=bool)))
    cur.take(_HDR.size)
    assert decode_verdicts(cur).size == 0

    fn_items = [(b"a" * 96, b"h" * 32, b"c" * 96), (b"d" * 32,)]
    cur = _Cursor(encode_submit_fn(3, "bls_agg", fn_items, "consensus"))
    cur.take(_HDR.size)
    engine, got_fn, klass = decode_submit_fn(cur)
    assert (engine, klass) == ("bls_agg", "consensus")
    assert got_fn == fn_items

    results = [True, False, None, b"addr-bytes"]
    cur = _Cursor(encode_fn_results(4, results))
    cur.take(_HDR.size)
    assert decode_fn_results(cur) == results

    cur = _Cursor(encode_error(5, "boom"))
    typ, req = _HDR.unpack(cur.take(_HDR.size))
    assert (typ, req) == (MSG_ERROR, 5)
    assert cur.bytes32() == b"boom"


def test_wire_codec_rejects_malformed():
    # truncated frame
    cur = _Cursor(encode_submit(1, sig_items(2), "consensus")[:-3])
    cur.take(_HDR.size)
    with pytest.raises(WireError):
        decode_submit(cur)
    # unknown fn-result tag
    cur = _Cursor(_HDR.pack(4, 1) + b"\x00\x00\x00\x01\x09")
    cur.take(_HDR.size)
    with pytest.raises(WireError):
        decode_fn_results(cur)


def test_read_frame_caps_oversized(tmp_path):
    """An over-cap length prefix errors the connection instead of
    allocating the attacker's buffer."""

    async def run():
        path = os.path.join(str(tmp_path), "x.sock")

        async def handler(reader, writer):
            try:
                await read_frame(reader)
            except WireError:
                writer.write(b"CAPPED")
                await writer.drain()
            writer.close()

        server = await asyncio.start_unix_server(handler, path=path)
        reader, writer = await asyncio.open_unix_connection(path)
        writer.write((1 << 31).to_bytes(4, "big"))
        await writer.drain()
        got = await asyncio.wait_for(reader.read(16), 10)
        writer.close()
        server.close()
        await server.wait_closed()
        return got

    assert asyncio.run(run()) == b"CAPPED"


# --- submit path ------------------------------------------------------------


def test_submit_verdict_alignment(tmp_path):
    """Per-item verdicts come back aligned to the submission order."""
    svc = service(tmp_path)
    try:

        async def run():
            remote = await connect(svc.server.path)
            v = await remote.submit(
                sig_items(10, good=lambda i: i % 2 == 0), "consensus"
            )
            await remote.stop()
            return v

        v = asyncio.run(run())
        assert v.tolist() == [i % 2 == 0 for i in range(10)]
    finally:
        svc.stop()


def test_cross_client_coalescing(tmp_path):
    """Submissions from DIFFERENT client connections land in one padded
    device round — the cross-process design, proven in-proc: round 1
    blocks on the gate, clients B and C submit meanwhile, and the
    service's ledger shows a round carrying >= 2 submissions."""
    gate = GateVerifier()
    svc = service(tmp_path, verifier=gate)
    try:

        async def run():
            a = await connect(svc.server.path)
            b = await connect(svc.server.path)
            c = await connect(svc.server.path)
            fut_a = asyncio.ensure_future(
                a.submit(sig_items(4), "consensus")
            )
            # wait until A's round is in flight server-side, then land
            # B and C while the gate holds it
            deadline = time.monotonic() + 10
            while (
                sum(
                    s["submissions"]
                    for s in svc.server.client_stats.values()
                )
                < 1
                and time.monotonic() < deadline
            ):
                await asyncio.sleep(0.01)
            fut_b = asyncio.ensure_future(
                b.submit(sig_items(3), "consensus")
            )
            fut_c = asyncio.ensure_future(
                c.submit(sig_items(5), "consensus")
            )
            deadline = time.monotonic() + 10
            while (
                sum(
                    s["submissions"]
                    for s in svc.server.client_stats.values()
                )
                < 3
                and time.monotonic() < deadline
            ):
                await asyncio.sleep(0.01)
            gate.gate.set()
            va, vb, vc = await asyncio.wait_for(
                asyncio.gather(fut_a, fut_b, fut_c), 30
            )
            for r in (a, b, c):
                await r.stop()
            return va, vb, vc

        va, vb, vc = asyncio.run(run())
        assert va.all() and vb.all() and vc.all()
        assert len(va) == 4 and len(vb) == 3 and len(vc) == 5
        entries = svc.server.scheduler.ledger.entries()
        coalesced = [e for e in entries if e["submissions"] >= 2]
        assert coalesced, f"no cross-client round in {entries}"
        # three tenants in the bill
        assert len(svc.server.client_stats) == 3
        assert all(
            s["rows"] > 0 for s in svc.server.client_stats.values()
        )
    finally:
        svc.stop()


def test_per_client_fifo(tmp_path):
    """One client's submissions resolve in submission order even when
    the first round blocks and the rest queue behind it."""
    gate = GateVerifier()
    svc = service(tmp_path, verifier=gate)
    try:

        async def run():
            remote = await connect(svc.server.path)
            order = []

            async def one(i):
                await remote.submit(sig_items(2 + i), "consensus")
                order.append(i)

            tasks = [asyncio.ensure_future(one(i)) for i in range(5)]
            await asyncio.sleep(0.2)
            gate.gate.set()
            await asyncio.wait_for(asyncio.gather(*tasks), 30)
            await remote.stop()
            return order

        assert asyncio.run(run()) == [0, 1, 2, 3, 4]
    finally:
        svc.stop()


# --- wire fn lanes ----------------------------------------------------------


def test_fn_lane_bls_agg_real_keys(tmp_path):
    from tendermint_tpu.crypto import bls_signatures as bls

    svc = service(tmp_path)
    try:
        h = b"h" * 32
        items = []
        for i in range(3):
            priv = 6007 + i
            items.append(
                (
                    bls.public_key_to_bytes(bls.pubkey_from_priv(priv)),
                    h,
                    bls.signer_for(priv)(h),
                )
            )
        # forged: valid point, wrong signer for this key
        items.append((items[0][0], h, items[1][2]))

        async def run():
            remote = await connect(svc.server.path)
            res = await remote.submit_wire_fn(
                "bls_agg", items, "consensus"
            )
            await remote.stop()
            return res

        assert asyncio.run(run()) == [True, True, True, False]
    finally:
        svc.stop()


def test_fn_lane_secp_recover(tmp_path):
    import hashlib

    from tendermint_tpu.crypto import secp256k1 as secp

    svc = service(tmp_path)
    try:
        key = secp.PrivKey.from_secret(b"vs-sequencer-key")
        digest = hashlib.sha256(b"blockv2-sign-bytes").digest()
        sig = secp.eth_sign(digest, key.secret)
        addr = secp.eth_address(
            secp.decompress_point(key.public_key().data)
        )

        async def run():
            remote = await connect(svc.server.path)
            res = await remote.submit_wire_fn(
                "secp_recover",
                [(digest, sig), (digest, b"\x00" * 65)],
                "sequencer",
            )
            await remote.stop()
            return res

        got = asyncio.run(run())
        assert got[0] == addr
        assert got[1] == b""
    finally:
        svc.stop()


def test_unknown_fn_engine_degrades_to_fallback(tmp_path):
    svc = service(tmp_path)
    try:

        async def run():
            tracer = obs.Tracer(enabled=True)
            remote = await connect(svc.server.path, tracer=tracer)
            res = await asyncio.wait_for(
                remote.submit_wire_fn(
                    "no_such_engine",
                    [(b"x" * 32,)],
                    "consensus",
                    fallback=lambda: ["local"],
                ),
                15,
            )
            stats = remote.ipc_stats()
            await remote.stop()
            events = [
                r
                for r in tracer.records()
                if r.name == "verify_service.degrade"
            ]
            return res, stats, events

        res, stats, events = asyncio.run(run())
        assert res == ["local"]
        assert stats["degrades"] == 1
        assert events and "service error" in (
            events[0].to_json()["fields"]["reason"]
        )
    finally:
        svc.stop()


# --- degradation contract ---------------------------------------------------


class LocalZeroVerifier:
    """Local fallback with a distinguishable verdict (all-False)."""

    def verify(self, items):
        return np.zeros(len(items), dtype=bool)


def test_kill_mid_flight_degrades_then_reattaches(tmp_path):
    """The acceptance property: a client-side fault (service dies with
    submissions in flight) degrades to local verify with a structured
    event — never a hang, never a dropped verdict — and the client
    re-attaches when the service returns."""
    gate = GateVerifier()
    svc = service(tmp_path, verifier=gate)
    path = svc.server.path
    tracer = obs.Tracer(enabled=True)

    async def run():
        remote = await connect(
            path, verifier=LocalZeroVerifier(), tracer=tracer
        )
        fut = asyncio.ensure_future(
            remote.submit(sig_items(3), "consensus")
        )
        deadline = time.monotonic() + 10
        while (
            not svc.server.client_stats
            or not any(
                s["submissions"]
                for s in svc.server.client_stats.values()
            )
        ) and time.monotonic() < deadline:
            await asyncio.sleep(0.01)
        svc.stop()  # mid-flight: the gate still holds the round
        v = await asyncio.wait_for(fut, 15)
        assert v.tolist() == [False, False, False]  # LOCAL verdicts
        stats1 = remote.ipc_stats()
        # while down, submissions run local without waiting
        v2 = await asyncio.wait_for(
            remote.submit(sig_items(2), "consensus"), 15
        )
        assert v2.tolist() == [False, False]
        # service returns on the same socket -> transparent re-attach
        svc2 = ServiceThread(
            path, scheduler=VerifyScheduler(verifier=SigTagVerifier())
        )
        svc2.start()
        try:
            deadline = time.monotonic() + 15
            while (
                not remote.connected and time.monotonic() < deadline
            ):
                await asyncio.sleep(0.02)
            assert remote.connected, "never re-attached"
            v3 = await asyncio.wait_for(
                remote.submit(sig_items(2), "consensus"), 15
            )
            assert v3.tolist() == [True, True]  # REMOTE verdicts again
            stats2 = remote.ipc_stats()
        finally:
            await remote.stop()
            svc2.stop()
        return stats1, stats2

    stats1, stats2 = asyncio.run(run())
    assert stats1["degrades"] == 1 and not stats1["connected"]
    assert stats2["degrades"] == 2
    assert stats2["reconnects"] == 2
    assert stats2["remote_submissions"] > stats1["remote_submissions"]
    events = [
        r.to_json()
        for r in tracer.records()
        if r.name == "verify_service.degrade"
    ]
    assert len(events) == 2
    assert events[0]["fields"]["reason"] == "connection lost mid-flight"
    assert events[1]["fields"]["reason"] == "service unreachable"


def test_unreachable_service_runs_local_without_hang(tmp_path):
    path = os.path.join(str(tmp_path), "never-exists.sock")

    async def run():
        remote = RemoteVerifyScheduler(
            path, verifier=LocalZeroVerifier(), retry_base=0.02
        )
        await remote.start()
        v = await asyncio.wait_for(
            remote.submit(sig_items(4), "consensus"), 10
        )
        stats = remote.ipc_stats()
        await remote.stop()
        return v, stats

    v, stats = asyncio.run(run())
    assert v.tolist() == [False] * 4
    assert stats["degrades"] == 1 and stats["remote_submissions"] == 0


def test_sync_surface_from_worker_thread(tmp_path):
    """submit_sync / the classed adapter route worker-thread callers
    over the wire (the VoteBatcher/blocksync shape); on-loop callers
    degrade to direct local dispatch like the in-proc scheduler."""
    svc = service(tmp_path)
    try:

        async def run():
            remote = await connect(
                svc.server.path, verifier=LocalZeroVerifier()
            )
            loop = asyncio.get_running_loop()
            classed = remote.classed("evidence")
            v_thread = await loop.run_in_executor(
                None, classed.verify, sig_items(3)
            )
            # ON the loop thread: must not block the loop -> local path
            v_loop = remote.submit_sync(sig_items(2), "consensus")
            stats = remote.ipc_stats()
            await remote.stop()
            return v_thread, v_loop, stats

        v_thread, v_loop, stats = asyncio.run(run())
        assert v_thread.tolist() == [True] * 3  # remote stub verdicts
        assert v_loop.tolist() == [False] * 2  # local zero verifier
        assert stats["remote_submissions"] == 1
        per_class = svc.server.scheduler.ledger.summary()["per_class"]
        assert "evidence" in per_class
    finally:
        svc.stop()


# --- stats / dump surface ---------------------------------------------------


def test_stats_frame_and_http_surface(tmp_path):
    svc = service(tmp_path, stats_port=0)
    try:
        port = svc.server.stats_port
        assert port and port > 0

        async def run():
            remote = await connect(svc.server.path)
            await remote.submit(sig_items(5), "consensus")
            # raw STATS frame
            reader, writer = await asyncio.open_unix_connection(
                svc.server.path
            )
            write_frame(writer, _HDR.pack(MSG_STATS, 42))
            await writer.drain()
            frame = await asyncio.wait_for(read_frame(reader), 10)
            cur = _Cursor(frame)
            typ, req = _HDR.unpack(cur.take(_HDR.size))
            assert (typ, req) == (MSG_STATS_RESULT, 42)
            dump = json.loads(cur.bytes32())
            writer.close()

            # HTTP: /metrics + /dump_dispatch_ledger + 404
            async def http_get(target):
                r, w = await asyncio.open_connection("127.0.0.1", port)
                w.write(
                    f"GET {target} HTTP/1.1\r\nHost: x\r\n\r\n".encode()
                )
                await w.drain()
                data = await asyncio.wait_for(r.read(), 10)
                w.close()
                head, _, body = data.partition(b"\r\n\r\n")
                return head.split(b" ", 2)[1], body

            code_m, metrics_body = await http_get("/metrics")
            code_d, dump_body = await http_get("/dump_dispatch_ledger")
            code_404, _ = await http_get("/nope")
            await remote.stop()
            return dump, code_m, metrics_body, code_d, dump_body, code_404

        dump, code_m, metrics_body, code_d, dump_body, code_404 = (
            asyncio.run(run())
        )
        assert dump["summary"]["rows_requested"] >= 5
        assert dump["per_client"]  # tenant table rides the dump
        assert dump["service"]["pid"] == os.getpid()
        assert code_m == b"200" and b"# TYPE" in metrics_body
        assert code_d == b"200"
        http_dump = json.loads(dump_body)
        assert http_dump["summary"]["rows_requested"] >= 5
        assert code_404 == b"404"
    finally:
        svc.stop()


def test_tenant_table_bounded(tmp_path):
    """A flapping client that never submits leaves no entry; past
    max_client_stats the oldest CLOSED billable rows fold into one
    `_closed` aggregate, so the table (and every dump) stays bounded
    while no tenant's spend ever leaves the bill."""
    svc = service(tmp_path)
    try:
        svc.server.max_client_stats = 4

        async def run():
            # 5 idle connect/disconnect cycles: no residue
            for _ in range(5):
                reader, writer = await asyncio.open_unix_connection(
                    svc.server.path
                )
                writer.close()
            await asyncio.sleep(0.2)
            idle_entries = len(svc.server.client_stats)
            # 8 sequential submitting clients: table stays bounded
            for _ in range(8):
                remote = await connect(svc.server.path)
                await remote.submit(sig_items(2), "consensus")
                await remote.stop()
                await asyncio.sleep(0.05)
            await asyncio.sleep(0.2)
            return idle_entries

        idle_entries = asyncio.run(run())
        assert idle_entries == 0
        table = svc.server.client_stats
        assert len(table) <= svc.server.max_client_stats + 2
        agg = table.get("_closed")
        live_rows = sum(
            v["rows"] for k, v in table.items() if k != "_closed"
        )
        folded = agg["rows"] if agg else 0
        assert live_rows + folded == 16  # 8 clients x 2 rows, all billed
        if agg:
            assert agg["clients"] >= 1
    finally:
        svc.stop()


# --- node assembly ----------------------------------------------------------


def test_node_assembly_remote_socket(tmp_path):
    """A full Node under `[scheduler] remote_socket` builds the client,
    binds the ipc health seam, commits heights against the shared
    service, and its verify plane answers over the wire."""
    from tendermint_tpu.node.node import Node, init_files

    from .test_node import make_test_config

    # REAL verifier: the node's votes carry genuine signatures and the
    # service must accept them for the net to advance
    svc = service(tmp_path / "svc", verifier=False)
    try:
        cfg = make_test_config(tmp_path / "node")
        cfg.scheduler.remote_socket = svc.server.path
        init_files(cfg)
        node = Node(cfg)
        assert isinstance(node.verify_scheduler, RemoteVerifyScheduler)
        assert (
            node.health_monitor._remote_scheduler
            is node.verify_scheduler
        )

        from tendermint_tpu.crypto import ed25519

        pk = ed25519.PrivKey.from_secret(b"node-remote-e2e")
        msg = b"explicit-item" + b"\x00" * 19
        good = SigItem(pk.public_key().data, msg, pk.sign(msg))
        forged = SigItem(pk.public_key().data, msg, b"\x00" * 64)

        async def run():
            await node.start()
            try:
                await node.consensus.wait_for_height(2, timeout=90)
                v = await asyncio.wait_for(
                    node.verify_scheduler.submit(
                        [good, forged, good], "consensus"
                    ),
                    60,
                )
                stats = node.verify_scheduler.ipc_stats()
            finally:
                await node.stop()
            return v, stats

        v, stats = asyncio.run(run())
        assert v.tolist() == [True, False, True]  # real verdicts, wire
        assert stats["remote_submissions"] >= 1
        assert stats["connected"]
    finally:
        set_default_scheduler(None)
        svc.stop()


# --- ipc_round_trip health detector -----------------------------------------


def test_ipc_detector_learns_then_flags_drift():
    from tendermint_tpu.obs.health import (
        OK,
        WARN,
        BurnRateSLO,
        IpcRoundTripDetector,
    )

    det = IpcRoundTripDetector(
        BurnRateSLO(
            "ipc_round_trip",
            objective=0.8,
            short_window=30.0,
            long_window=300.0,
        )
    )
    t = 0.0
    for _ in range(16):  # learn a ~2 ms baseline
        t += 1.0
        det.observe_interval(t, mean_rtt_s=0.002)
    assert det.verdict(t) == OK
    assert det.threshold() == pytest.approx(0.008)
    for _ in range(12):  # 10x the learned median, sustained
        t += 1.0
        det.observe_interval(t, mean_rtt_s=0.02)
    assert det.verdict(t) >= WARN
    assert det.last_threshold == pytest.approx(0.008)
    # drifted samples never taught the baseline
    assert det.threshold() == pytest.approx(0.008)


def test_ipc_detector_pages_on_degrades():
    from tendermint_tpu.obs.health import (
        WARN,
        BurnRateSLO,
        IpcRoundTripDetector,
    )

    det = IpcRoundTripDetector(
        BurnRateSLO(
            "ipc_round_trip",
            objective=0.8,
            short_window=30.0,
            long_window=300.0,
        )
    )
    t = 0.0
    for _ in range(8):  # every interval saw local-degrade fallbacks
        t += 1.0
        det.observe_interval(t, mean_rtt_s=None, degrades=3)
    assert det.verdict(t) >= WARN


def test_monitor_remote_scheduler_seam():
    """bind_remote_scheduler pulls ipc_stats() deltas: first sample is
    baseline-only, then interval means + degrades feed the detector and
    the verdict document carries it under the scheduler subsystem."""
    from tendermint_tpu.obs.health import HealthMonitor, WARN

    class FakeRemote:
        def __init__(self):
            self.stats = {
                "rtt_count": 0,
                "rtt_sum_s": 0.0,
                "remote_submissions": 0,
                "degrades": 0,
                "reconnects": 1,
                "connected": True,
            }

        def ipc_stats(self):
            return dict(self.stats)

    mon = HealthMonitor(tracer=obs.Tracer(enabled=True))
    remote = FakeRemote()
    mon.bind_remote_scheduler(remote)
    t = 0.0
    mon.sample(t)  # first sample: baseline only
    det = mon.detectors["ipc_round_trip"]
    assert det.subsystem == "scheduler"
    for _ in range(16):  # healthy 2 ms intervals
        t += 1.0
        remote.stats["rtt_count"] += 10
        remote.stats["rtt_sum_s"] += 10 * 0.002
        mon.sample(t)
    assert mon.subsystem_verdicts(t)["scheduler"] == 0
    for _ in range(12):  # service wedges: degrades + drifted RTT
        t += 1.0
        remote.stats["rtt_count"] += 10
        remote.stats["rtt_sum_s"] += 10 * 0.05
        remote.stats["degrades"] += 4
        mon.sample(t)
    assert mon.detectors["ipc_round_trip"].verdict(t) >= WARN
    assert mon.subsystem_verdicts(t)["scheduler"] >= WARN
    doc = mon.verdict(t)
    assert "ipc_round_trip" in doc["subsystems"]["scheduler"]["detectors"]


def test_health_config_ipc_knob():
    from tendermint_tpu.config.config import HealthConfig
    from tendermint_tpu.obs.health import HealthMonitor

    hc = HealthConfig(ipc_drift_factor=7.0)
    hc.validate_basic()
    mon = HealthMonitor.from_config(hc, stall_ceiling_s=10.0)
    assert mon.ipc_round_trip.drift_factor == 7.0
    with pytest.raises(ValueError):
        HealthConfig(ipc_drift_factor=0.0).validate_basic()


# --- chaos: kill/restart the service under a live net -----------------------


@pytest.mark.chaos
def test_chaos_net_survives_service_kill_and_restart(tmp_path):
    """The liveness property: a 4-validator net whose verify plane
    rides a shared service keeps committing when the service is killed
    mid-net (every node degrades to local verify with structured
    events) and re-attaches when it returns."""
    from tests.chaos_harness import (
        ChaosVerifyService,
        build_chaos_handles,
        start_mesh,
        stop_mesh,
    )

    # REAL verifier: the net's votes carry genuine signatures
    chaos_svc = ChaosVerifyService(
        os.path.join(str(tmp_path), "svc.sock"),
        scheduler=VerifyScheduler(),
    )
    chaos_svc.start()
    tracer = obs.Tracer(enabled=True)
    handles = build_chaos_handles(4)

    async def run():
        remote = await connect(
            chaos_svc.path, verifier=None, tracer=tracer
        )
        set_default_scheduler(remote)
        try:
            await start_mesh(handles)
            # generous: the first service dispatch may pay a bucket
            # compile, and every vote chunk round-trips the socket
            await asyncio.gather(
                *(h.cs.wait_for_height(2, timeout=180) for h in handles)
            )
            sub_before = remote.ipc_stats()["remote_submissions"]
            assert sub_before > 0, "net never verified over the wire"
            # kill mid-net: liveness must not depend on the service
            chaos_svc.kill()
            base = max(h.cs.rs.height for h in handles)
            await asyncio.gather(
                *(
                    h.cs.wait_for_height(base + 2, timeout=90)
                    for h in handles
                )
            )
            stats_down = remote.ipc_stats()
            # service returns: the clients re-attach and resume
            chaos_svc.restart()
            deadline = time.monotonic() + 30
            while (
                not remote.connected and time.monotonic() < deadline
            ):
                await asyncio.sleep(0.05)
            assert remote.connected, "client never re-attached"
            base = max(h.cs.rs.height for h in handles)
            await asyncio.gather(
                *(
                    h.cs.wait_for_height(base + 2, timeout=90)
                    for h in handles
                )
            )
            stats_up = remote.ipc_stats()
            return stats_down, stats_up
        finally:
            await stop_mesh(handles)
            set_default_scheduler(None)
            await remote.stop()

    try:
        stats_down, stats_up = asyncio.run(run())
    finally:
        chaos_svc.kill()
    assert stats_down["degrades"] > 0, "kill never exercised degrade"
    assert stats_up["reconnects"] >= 2
    assert (
        stats_up["remote_submissions"]
        > stats_down["remote_submissions"]
    ), "no remote submissions after re-attach"
    events = [
        r for r in tracer.records() if r.name == "verify_service.degrade"
    ]
    assert events, "degrades left no structured event"


# --- real process boundary ---------------------------------------------------


def test_cli_service_process_end_to_end(tmp_path):
    """`python -m tendermint_tpu verify-service` across a REAL process
    boundary: readiness handshake, real ed25519 verdicts over the wire,
    and the service-side dump."""
    from tendermint_tpu.crypto import ed25519
    from tools.verify_service_bench import _service_dump, _spawn_service

    sock = os.path.join(str(tmp_path), "cli.sock")
    proc = _spawn_service(sock, max_batch=256, timeout=120)
    try:

        async def run():
            remote = await connect(sock)
            pk = ed25519.PrivKey.from_secret(b"cli-e2e")
            msg = b"vote-bytes" + b"\x00" * 22
            good = SigItem(pk.public_key().data, msg, pk.sign(msg))
            bad = SigItem(pk.public_key().data, msg, b"\x00" * 64)
            v = await asyncio.wait_for(
                remote.submit([good, bad, good], "consensus"), 240
            )
            dump = await _service_dump(sock)
            await remote.stop()
            return v, dump

        v, dump = asyncio.run(run())
        assert v.tolist() == [True, False, True]
        assert dump["summary"]["rows_requested"] >= 3
        assert dump["per_client"]
        assert dump["service"]["pid"] == proc.pid
    finally:
        proc.terminate()
        proc.wait(timeout=30)


# --- satellites: tooling -----------------------------------------------------


def test_testnet_generator_stamps_remote_socket(tmp_path):
    import socket as socket_mod

    from tendermint_tpu.config import Config
    from tools.testnet_generator import generate_manifest, materialize

    def free_ports(k):
        socks, ports = [], []
        for _ in range(k):
            s = socket_mod.socket()
            s.bind(("127.0.0.1", 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
        for s in socks:
            s.close()
        return ports

    manifest = generate_manifest(11, n_validators=2)
    layout = materialize(
        manifest,
        str(tmp_path / "net"),
        free_ports,
        verify_service="shared/verify.sock",
    )
    assert layout
    expect = os.path.abspath("shared/verify.sock")
    for spec in layout.values():
        cfg = Config.load(spec["home"])
        assert cfg.scheduler.remote_socket == expect


def test_device_report_renders_tenant_table():
    from tools.device_report import extract_summary, report_text

    dump = {
        "enabled": True,
        "service": {"socket": "/tmp/v.sock", "pid": 1},
        "summary": {
            "rounds": 10,
            "fn_rounds": 2,
            "sharded_rounds": 0,
            "rows_requested": 90,
            "rows_dispatched": 128,
            "padding_rows": 38,
            "fill_ratio_p50": 0.7,
            "fill_ratio_p95": 0.9,
            "requests_per_dispatch": 2.5,
            "device_seconds": 0.5,
            "queue_wait_seconds": 0.1,
            "host_prep_seconds": 0.01,
            "per_class": {
                "consensus": {
                    "rows": 90,
                    "device_seconds": 0.5,
                    "device_share": 1.0,
                    "rounds": 10,
                    "submissions": 25,
                    "queue_wait_seconds": 0.1,
                }
            },
            "by_bucket": {},
        },
        "per_client": {
            "client-1": {
                "submissions": 20,
                "rows": 70,
                "fn_submissions": 2,
                "fn_items": 8,
            },
            "client-2": {
                "submissions": 5,
                "rows": 20,
                "fn_submissions": 0,
                "fn_items": 0,
            },
        },
    }
    summary = extract_summary(dump)
    assert summary["per_client"]
    text = report_text(summary, name="service")
    assert "tenants (2 clients" in text
    assert "client-1" in text and "client-2" in text
    # biggest tenant first
    assert text.index("client-1") < text.index("client-2")


def test_bench_trend_ingests_verify_service_family(tmp_path):
    from tools.bench_trend import (
        TIER1_FAMILIES,
        build_groups,
        check_gate,
        direction_of,
        family_of,
        ingest,
    )

    assert family_of("verify_service_wall_per_height_n32") == (
        "verify_service"
    )
    assert "verify_service" in TIER1_FAMILIES
    assert (
        direction_of("verify_service_wall_per_height_n32", "ms/height")
        == "lower"
    )
    assert (
        direction_of(
            "verify_service_requests_per_dispatch_n32", "submissions"
        )
        == "higher"
    )

    def artifact(round_, wall):
        return {
            "metric": "verify_service_wall_per_height_n32",
            "value": wall,
            "unit": "ms/height",
            "meta": {"backend": "cpu", "device_count": 1},
            "extra_metrics": [
                {
                    "metric": "verify_service_requests_per_dispatch_n32",
                    "value": 3.0,
                    "unit": "submissions per round",
                }
            ],
        }

    p1 = tmp_path / "BENCH_r90.json"
    p2 = tmp_path / "BENCH_r91.json"
    p1.write_text(json.dumps(artifact(90, 1000.0)))
    p2.write_text(json.dumps(artifact(91, 1300.0)))  # 30% worse
    rows, skipped, _ = ingest([str(p1), str(p2)])
    assert not skipped
    groups = build_groups(rows)
    head = next(
        g
        for g in groups
        if g["metric"] == "verify_service_wall_per_height_n32"
    )
    assert head["family"] == "verify_service" and head["headline"]
    failures, _ = check_gate(groups, threshold=0.15)
    assert any(
        f["metric"] == "verify_service_wall_per_height_n32"
        for f in failures
    )


# --- the multi-process harness itself ----------------------------------------


@pytest.mark.slow
def test_verify_service_bench_harness_smoke():
    """run_size across real OS processes at a tiny committee: real
    ed25519 + BLS verdicts, zero degrades, service ledger attached."""
    from tools.verify_service_bench import run_size

    row = run_size(2, heights=1, warm=1, max_procs=2)
    assert "error" not in row, row
    assert row["sig_verify"] == "real"
    assert row["processes"] == 2
    assert row["degrades"] == 0
    # measured window only (the warm height is excluded by design)
    assert row["remote_submissions"] >= 4  # 2 nodes x 2 lanes x 1 h
    assert row["service_ledger"]["rows_requested"] >= 8  # incl. warm
    assert row["per_client_tenants"] >= 2
