"""Differential tests: batched TPU curve ops vs the pure-python host oracle."""

import hashlib

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from tendermint_tpu.crypto import ed25519 as host
from tendermint_tpu.ops import curve25519 as curve
from tendermint_tpu.ops import field25519 as fe

# jit everything once — eager dispatch of these deep graphs is pathologically
# slow on the CPU test platform, and jit also exercises the real path.
_add = jax.jit(curve.add)
_double = jax.jit(curve.double)
_compress = jax.jit(curve.compress)
_decompress = jax.jit(curve.decompress)
_smul_base = jax.jit(curve.scalar_mult_base)
_smul_var = jax.jit(curve.scalar_mult_var)


def _rand_points(n, seed=0):
    """n pseudorandom curve points (as host points) via hashing to scalars."""
    pts = []
    for i in range(n):
        s = int.from_bytes(hashlib.sha512(bytes([seed, i])).digest(), "little")
        pts.append(host.scalar_mult(s % host.L, host.BASEPOINT))
    return pts


def _to_batch(pts):
    return jnp.asarray(np.stack([curve.from_host_point(p) for p in pts]))


def _assert_points_equal(dev_pts, host_pts):
    enc = np.asarray(_compress(dev_pts))
    for i, hp in enumerate(host_pts):
        assert bytes(enc[i].tobytes()) == host.point_compress(hp), f"idx {i}"


def test_add_double_match_host():
    ps = _rand_points(4, seed=1)
    qs = _rand_points(4, seed=2)
    dev_sum = _add(_to_batch(ps), _to_batch(qs))
    _assert_points_equal(dev_sum, [host.point_add(p, q) for p, q in zip(ps, qs)])
    dev_dbl = _double(_to_batch(ps))
    _assert_points_equal(dev_dbl, [host.point_add(p, p) for p in ps])


def test_add_identity_and_self():
    ps = _rand_points(2, seed=3)
    batch = _to_batch(ps)
    _assert_points_equal(_add(batch, curve.identity((2,))), ps)
    # unified add must handle P+P (completeness)
    _assert_points_equal(_add(batch, batch), [host.point_add(p, p) for p in ps])


def test_compress_decompress_roundtrip():
    ps = _rand_points(4, seed=4)
    enc = np.stack(
        [np.frombuffer(host.point_compress(p), dtype=np.uint8) for p in ps]
    )
    pt, valid = _decompress(jnp.asarray(enc))
    assert np.asarray(valid).all()
    _assert_points_equal(pt, ps)


def test_decompress_rejects_bad_encodings():
    bad = np.zeros((3, 32), dtype=np.uint8)
    # y = p (non-canonical encoding of 0)
    bad[0] = np.frombuffer(host.P.to_bytes(32, "little"), dtype=np.uint8)
    # y = 2 is not on the curve (x^2 = (y^2-1)/(dy^2+1) is non-square for y=2)
    bad[1, 0] = 2
    # x=0 point (y=1) with sign bit set
    bad[2, 0] = 1
    bad[2, 31] = 0x80
    _, valid = _decompress(jnp.asarray(bad))
    valid = np.asarray(valid)
    assert not valid[0]
    assert not valid[2]
    # row 1: mirror the host oracle
    assert valid[1] == (host.point_decompress(bytes(bad[1].tobytes())) is not None)


def test_scalar_mult_base_matches_host():
    scalars = [0, 1, 2, host.L - 1, 2**256 - 1]
    sb = np.stack(
        [
            np.frombuffer(s.to_bytes(32, "little"), dtype=np.uint8)
            for s in scalars
        ]
    )
    out = _smul_base(jnp.asarray(sb))
    _assert_points_equal(
        out, [host.scalar_mult(s, host.BASEPOINT) for s in scalars]
    )


def test_scalar_mult_var_matches_host():
    pts = _rand_points(3, seed=5)
    scalars = [7, host.L - 2, 2**255 + 12345]
    sb = np.stack(
        [
            np.frombuffer(s.to_bytes(32, "little"), dtype=np.uint8)
            for s in scalars
        ]
    )
    out = _smul_var(jnp.asarray(sb), _to_batch(pts))
    _assert_points_equal(
        out, [host.scalar_mult(s, p) for s, p in zip(scalars, pts)]
    )


def test_scalar_mult_var_bigtable_matches_host():
    """Fixed-window (doubling-free) variable-base path, both table forms."""
    pts = _rand_points(3, seed=9)
    scalars = [0, host.L - 1, 2**256 - 19]
    sb = jnp.asarray(
        np.stack(
            [
                np.frombuffer(s.to_bytes(32, "little"), dtype=np.uint8)
                for s in scalars
            ]
        )
    )
    tables = jax.jit(curve.big_window_table)(_to_batch(pts))
    expected = [host.scalar_mult(s, p) for s, p in zip(scalars, pts)]

    out = jax.jit(curve.scalar_mult_var_bigtable)(sb, tables)
    _assert_points_equal(out, expected)

    # cache form: rows permuted, gathered back by index
    idx = jnp.asarray(np.array([2, 0, 1], dtype=np.int32))
    cache = jnp.take(tables, idx, axis=0)  # cache[j] = tables[idx[j]]
    inv = jnp.asarray(np.array([1, 2, 0], dtype=np.int32))
    out2 = jax.jit(curve.scalar_mult_var_bigcache)(sb, cache, inv)
    _assert_points_equal(out2, expected)


def test_bigcache_mxu_matches_gather_path():
    """The one-hot-matmul (MXU) formulation of the fixed-window lookup
    must be bit-identical to the gather path for valid and invalid rows
    (it is selected on real silicon via TM_TPU_MXU_GATHER=1)."""
    import jax
    import jax.numpy as jnp

    from __graft_entry__ import _make_batch
    from tendermint_tpu.ops.ed25519_batch import (
        neg_pubkey_bigtable,
        verify_prehashed_bigcache,
        verify_prehashed_bigcache_mxu,
    )

    n = 8
    pub, rb, sb, kb, s_ok = _make_batch(n)
    sb[2] ^= 1  # corrupt one row
    tables, valid = jax.jit(neg_pubkey_bigtable)(jnp.asarray(pub))
    idx = jnp.arange(n, dtype=jnp.int32)
    args = (
        tables,
        valid,
        idx,
        jnp.asarray(rb),
        jnp.asarray(sb),
        jnp.asarray(kb),
        jnp.asarray(s_ok),
    )
    out_g = np.asarray(jax.jit(verify_prehashed_bigcache)(*args))
    out_m = np.asarray(jax.jit(verify_prehashed_bigcache_mxu)(*args))
    assert (out_g == out_m).all()
    assert out_g[0] and not out_g[2]
