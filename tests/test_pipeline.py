"""QC-chained height pipelining (PERF_ANALYSIS §22): provisional entry
into H+1 on H's precommit quorum close, with H's apply/save/fsync chained
behind the WAL durability barrier in the background.

Covers the pieces the serial suites can't: the next-height holding
buffer (peers running one height ahead), overlap-aware wall conservation
on a live pipelined net, chained-QC justification on the wire, crash
recovery across the pipelined boundary (H+1's proposal signed, H's
decision not yet durable — the double-sign window), and a legacy
non-pipelined peer following a pipelined chain over real p2p.
"""

from __future__ import annotations

import asyncio
import hashlib
import time
from io import BytesIO

import pytest

from tendermint_tpu import obs
from tendermint_tpu.abci.client import LocalClient
from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.consensus.commit_pipeline import CommitPipeline
from tendermint_tpu.consensus.replay import Handshaker
from tendermint_tpu.consensus.state_machine import (
    ConsensusConfig,
    ConsensusState,
)
from tendermint_tpu.consensus.messages import VoteMessage
from tendermint_tpu.consensus.wal import (
    GroupCommitWAL,
    KIND_END_HEIGHT,
    decode_records,
    encode_record,
)
from tendermint_tpu.crypto import bls_signatures as bls
from tendermint_tpu.crypto.bls12_381 import R
from tendermint_tpu.l2node.mock import MockL2Node
from tendermint_tpu.libs import protoio as pio
from tendermint_tpu.privval.file_pv import FilePV, STEP_PROPOSE
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.state import State
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.store.block_store import WriteBehindBlockStore
from tendermint_tpu.store.kv import MemKV
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.part_set import PartSetHeader
from tendermint_tpu.types.validator import Validator
from tendermint_tpu.types.validator_set import ValidatorSet
from tendermint_tpu.types.vote import Vote, VoteType

from .helpers import (
    CHAIN_ID,
    make_genesis,
    make_qc_validators,
    make_validators,
)
from .test_consensus import make_node, wire_net

pytestmark = pytest.mark.pipeline


def _pipelined_config(**overrides) -> ConsensusConfig:
    cfg = ConsensusConfig.test_config()
    cfg.pipelined_heights = True
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg


# --- construction -----------------------------------------------------------


def test_pipelined_config_self_constructs_pipeline():
    """pipelined_heights with no injected CommitPipeline must still get
    one: without it the 'pipelined' finalize silently degrades to the
    serial path (the background-overlap half of the feature vanishes)."""
    vs, pvs = make_validators(1)
    genesis = make_genesis(vs)
    cs, *_ = make_node(vs, pvs[0], genesis, config=_pipelined_config())
    assert cs.pipeline is not None
    # and the flag off means no self-construction
    cs2, *_ = make_node(vs, pvs[0], genesis)
    assert cs2.pipeline is None


# --- next-height holding buffer ---------------------------------------------


def _vote_msg(height: int) -> VoteMessage:
    return VoteMessage(
        Vote(
            type=VoteType.PREVOTE,
            height=height,
            round=0,
            block_id=BlockID(b"\x00" * 32, PartSetHeader()),
            timestamp_ns=0,
            validator_address=b"\x00" * 20,
            validator_index=0,
        )
    )


def test_next_height_buffer_holds_caps_and_drains():
    """H+1 traffic is held (not dropped) while this node still closes H,
    the buffer is hard-capped against future-height floods, and the
    drain discards stale (already-decided) entries while re-feeding
    current-height ones."""
    vs, pvs = make_validators(2)
    genesis = make_genesis(vs)
    cs, *_ = make_node(vs, pvs[0], genesis, config=_pipelined_config())

    async def run():
        cs.rs.height = 5
        # H+1 is held before any verification (the signature is junk)
        await cs._handle_msg(_vote_msg(6), "peer")
        assert len(cs._next_height_buf) == 1
        # hard cap: a byzantine flood must not grow memory
        cs._NEXT_HEIGHT_BUF_CAP = 3
        for _ in range(5):
            await cs._handle_msg(_vote_msg(6), "peer")
        assert len(cs._next_height_buf) == 3
        # a stale entry (height already decided by the time we drain)
        cs._buffer_next_height_msg(_vote_msg(2), "peer")
        cs.rs.height = 6
        await cs._drain_next_height_buf()
        # everything re-fed or discarded; nothing wedged in the buffer
        assert cs._next_height_buf == []

    asyncio.run(run())


def test_next_height_buffer_refuses_messages_still_ahead():
    """Draining below the buffered height re-stashes instead of feeding
    messages the state machine would reject."""
    vs, pvs = make_validators(2)
    genesis = make_genesis(vs)
    cs, *_ = make_node(vs, pvs[0], genesis, config=_pipelined_config())

    async def run():
        cs.rs.height = 5
        await cs._handle_msg(_vote_msg(6), "peer")
        assert len(cs._next_height_buf) == 1
        await cs._drain_next_height_buf()  # still at 5: nothing to feed
        assert len(cs._next_height_buf) == 1

    asyncio.run(run())


# --- live pipelined net: equivalence + overlap conservation -----------------


def _run_net(pipelined: bool, heights: int, tracer=None, n: int = 4):
    """4-validator in-proc net; returns ([cs], app_hash set at `heights`)."""
    vs, pvs = make_validators(n)
    genesis = make_genesis(vs)
    cfg = _pipelined_config() if pipelined else ConsensusConfig.test_config()

    async def run():
        nodes = [
            make_node(
                vs,
                pv,
                genesis,
                config=cfg,
                tracer=(tracer if i == 0 else None),
            )
            for i, pv in enumerate(pvs)
        ]
        css = [nd[0] for nd in nodes]
        wire_net(css)
        for cs in css:
            await cs.start()
        await asyncio.gather(
            *(cs.wait_for_height(heights, timeout=90) for cs in css)
        )
        for cs in css:
            await cs.stop()
        return css

    css = asyncio.run(run())
    blocks = {cs.block_store.load_block(heights).hash() for cs in css}
    assert len(blocks) == 1, "pipelined net diverged"
    return css


def test_pipelined_net_matches_serial_app_hash():
    """The pipelined and serial nets must commit identical chains: same
    per-height app hash, every node in agreement."""
    H = 4
    piped = _run_net(True, H)
    serial = _run_net(False, H)
    for cs in piped:
        assert cs.pipeline is not None
    ph = piped[0].block_store.load_block(H).header.app_hash
    sh = serial[0].block_store.load_block(H).header.app_hash
    assert ph == sh, "pipelined chain diverged from serial"


def test_pipelined_net_conserves_wall_with_overlap_credit():
    """Overlap-aware conservation on a live pipelined net: every
    completed height's buckets sum to wall + booked pipeline_overlap_ms
    (never silently exceeding the wall), and the validator passes."""
    tracer = obs.Tracer(enabled=True, ring_size=65536)
    _run_net(True, 5, tracer=tracer)
    recs = [r.to_json() for r in tracer.records()]
    cons = obs.wall_conservation(recs)
    rows = cons.get("heights", {})
    assert rows, "no conservation rows from the pipelined run"
    assert obs.check_conservation(cons) == []
    assert cons["aggregate"]["conserved"] is True
    for h, row in rows.items():
        assert "pipeline_overlap_ms" in row
        assert row["pipeline_overlap_ms"] >= 0.0


# --- chained QC justification -----------------------------------------------


def test_pipelined_chain_carries_chained_qc():
    """With the QC plane on, a pipelined 4-validator chain ships every
    block's justification: last_qc assembled from the previous height's
    precommit quorum (chained behind the commit on the proposer), and it
    verifies against the committed validator set."""
    vs, pvs, privs = make_qc_validators(4, seed=b"pipeqc")
    genesis = make_genesis(vs)
    cfg = _pipelined_config(quorum_certificates=True)
    H = 4

    async def run():
        nodes = []
        for pv in pvs:
            addr = pv.get_pub_key().address()
            cs, app, l2, bs, ss = make_node(
                vs,
                pv,
                genesis,
                config=cfg,
                bls_signer=bls.signer_for(privs[addr]),
            )
            cs.executor.qc_enabled = True
            nodes.append(cs)
        wire_net(nodes)
        for cs in nodes:
            await cs.start()
        await asyncio.gather(
            *(cs.wait_for_height(H, timeout=90) for cs in nodes)
        )
        for cs in nodes:
            await cs.stop()
        return nodes

    nodes = asyncio.run(run())
    hashes = {cs.block_store.load_block(H).hash() for cs in nodes}
    assert len(hashes) == 1
    bs = nodes[0].block_store
    for h in range(2, H):
        blk = bs.load_block(h + 1)
        assert blk.last_qc is not None, f"height {h + 1} shipped without qc"
        assert blk.last_qc.height == h
        vs.verify_commit_qc(CHAIN_ID, blk.last_qc.block_id, h, blk.last_qc)


# --- crash across the pipelined boundary ------------------------------------


class _RecordingPV:
    """FilePV wrapper recording every signature it hands out, keyed by
    (height, round, step) — the double-sign ledger both incarnations of
    the crash test share. `freeze_at=H` refuses any signing past
    (H, 0, propose): it pins the privval at the exact crash instant the
    test wants (H's proposal signed, nothing later)."""

    def __init__(self, inner: FilePV, book: dict, freeze_at=None):
        self.inner = inner
        self.book = book
        self.freeze_at = freeze_at

    def get_pub_key(self):
        return self.inner.get_pub_key()

    def sign_proposal(self, chain_id, proposal):
        if self.freeze_at is not None and (
            proposal.height > self.freeze_at
            or (proposal.height == self.freeze_at and proposal.round > 0)
        ):
            raise RuntimeError("crash window: signing frozen")
        self.inner.sign_proposal(chain_id, proposal)
        self.book.setdefault(
            (proposal.height, proposal.round, "proposal"), set()
        ).add(bytes(proposal.signature))

    def sign_vote(self, chain_id, vote):
        if self.freeze_at is not None and vote.height >= self.freeze_at:
            raise RuntimeError("crash window: signing frozen")
        self.inner.sign_vote(chain_id, vote)
        self.book.setdefault(
            (vote.height, vote.round, int(vote.type)), set()
        ).add(bytes(vote.signature))


def _crash_node(genesis, pv, wal_path, block_kv, state_kv, bls_scalar):
    """Pipelined + QC single-validator node over explicit restartable
    stores and a real on-disk group-commit WAL."""
    app = KVStoreApplication()
    l2 = MockL2Node()
    state_store = StateStore(state_kv)
    block_store = WriteBehindBlockStore(block_kv, max_inflight=4)
    wal = GroupCommitWAL(wal_path, flush_interval=0.001)
    state = state_store.load()
    if state is None:
        state = State.from_genesis(genesis)
        state_store.bootstrap(state)
    executor = BlockExecutor(state_store, block_store, LocalClient(app), l2)
    executor.qc_enabled = True
    cfg = _pipelined_config(quorum_certificates=True)
    cs = ConsensusState(
        cfg,
        state,
        executor,
        block_store,
        l2,
        priv_validator=pv,
        wal=wal,
        commit_pipeline=CommitPipeline(),
        bls_signer=bls.signer_for(bls_scalar),
    )
    return cs, block_store, state_store


def _truncate_wal_after_end_height(path: str, h: int) -> None:
    """Cut the WAL file to the prefix ending at end_height(h) — the
    durable image of a crash whose later records never got fsynced
    (group commit loses a suffix, never the middle)."""
    data = open(path, "rb").read()
    off = 0
    cut = None
    for m in decode_records(data, lenient=True):
        off += len(encode_record(m))
        if (
            m.kind == KIND_END_HEIGHT
            and pio.read_uvarint(BytesIO(m.data)) == h
        ):
            cut = off
            break
    assert cut is not None, f"no end_height({h}) record in the WAL"
    with open(path, "r+b") as f:
        f.truncate(cut)


@pytest.mark.chaos
def test_crash_between_next_propose_and_durable_boundary(tmp_path):
    """THE pipelined-boundary crash window: the node signed H+1's
    proposal (privval state advanced — that write is synchronous and
    survives) while H's decision is not yet in the stores and the H+1
    records never reached disk. Restart must replay H from the WAL,
    re-enter H+1, and continue WITHOUT double-signing (the privval
    refuses the conflicting re-proposal; the round advances instead)
    and WITHOUT skipping a height — and the chained-QC justification
    re-derives across the boundary."""
    CRASH_H = 4
    kp, sp = str(tmp_path / "pv_key.json"), str(tmp_path / "pv_state.json")
    wal_path = str(tmp_path / "wal")
    fpv = FilePV.generate(kp, sp)
    scalar = (
        int.from_bytes(hashlib.sha256(b"crash-bls").digest(), "big")
        % (R - 1)
        + 1
    )
    pub = bls.pubkey_from_priv(scalar)
    vs = ValidatorSet(
        [
            Validator(
                fpv.get_pub_key(), 10, bls_pub_key=bls.g2_to_bytes(pub.key)
            )
        ]
    )
    genesis = make_genesis(vs)
    book: dict = {}
    block_kv, state_kv = MemKV(), MemKV()

    async def first_run():
        pv = _RecordingPV(FilePV.load(kp, sp), book, freeze_at=CRASH_H)
        cs, bs, ss = _crash_node(
            genesis, pv, wal_path, block_kv, state_kv, scalar
        )
        hs = Handshaker(ss, bs, genesis, cs.executor)
        cs.state = await hs.handshake(cs.state)
        await cs.start()
        await cs.wait_for_height(2, timeout=60)
        bs.wait_durable()
        # the durable crash image of the STORES: everything the
        # write-behind worker and the background apply had persisted by
        # now — later saves are the writes the crash loses
        snap_block = {k: v for k, v in block_kv.iterate()}
        snap_state = {k: v for k, v in state_kv.iterate()}
        deadline = time.monotonic() + 60
        while (CRASH_H, 0, "proposal") not in book:
            assert time.monotonic() < deadline, "H+1 proposal never signed"
            await asyncio.sleep(0.005)
        await cs.stop()
        bs.stop()
        cs.wal.close()
        return snap_block, snap_state

    snap_block, snap_state = asyncio.run(first_run())
    # the privval froze at exactly (CRASH_H, 0, propose) — the window
    pv_check = FilePV.load(kp, sp)
    assert pv_check.last_state.height == CRASH_H
    assert pv_check.last_state.step == STEP_PROPOSE
    # crash image: WAL durable through end_height(H-1) only (the H+1
    # proposal record and anything later lost with the unsynced suffix)
    _truncate_wal_after_end_height(wal_path, CRASH_H - 1)

    async def second_run():
        block_kv2, state_kv2 = MemKV(), MemKV()
        for k, v in snap_block.items():
            block_kv2.set(k, v)
        for k, v in snap_state.items():
            state_kv2.set(k, v)
        pv = _RecordingPV(FilePV.load(kp, sp), book)
        cs, bs, ss = _crash_node(
            genesis, pv, wal_path, block_kv2, state_kv2, scalar
        )
        hs = Handshaker(ss, bs, genesis, cs.executor)
        cs.state = await hs.handshake(cs.state)
        await cs.start()  # WAL catchup replays H-1's tail, re-drives H
        await cs.wait_for_height(CRASH_H + 2, timeout=90)
        await cs.stop()
        bs.stop()
        cs.wal.close()
        return cs, bs

    cs, bs = asyncio.run(second_run())
    # no height skip: the chain is contiguous through the boundary
    assert cs.state.last_block_height >= CRASH_H + 2
    for h in range(2, CRASH_H + 3):
        blk = bs.load_block(h)
        prev = bs.load_block(h - 1)
        assert blk is not None, f"height {h} missing after replay"
        assert blk.header.last_block_id.hash == prev.hash(), (
            f"chain broken at {h}"
        )
    # no double-sign: every (height, round, step) ever signed got
    # exactly ONE signature across both incarnations
    for key, sigs in book.items():
        assert len(sigs) == 1, f"double sign at {key}: {len(sigs)} sigs"
    assert (CRASH_H, 0, "proposal") in book
    # the conflicting re-proposal was REFUSED, so the boundary height
    # committed at a later round (liveness via round advance, not
    # equivocation)
    assert bs.load_seen_commit(CRASH_H).round >= 1
    # chained-QC justification re-derived across the boundary
    blk = bs.load_block(CRASH_H + 1)
    assert blk.last_qc is not None
    assert blk.last_qc.height == CRASH_H
    vs.verify_commit_qc(CHAIN_ID, blk.last_qc.block_id, CRASH_H, blk.last_qc)


# --- legacy interop ---------------------------------------------------------


def test_legacy_peer_follows_pipelined_chain():
    """A non-pipelined peer in a majority-pipelined committee must keep
    up over real p2p: pipelined peers run one height ahead while the
    legacy node still finalizes serially, so it leans on the reactor's
    catchup gossip (stored block parts + reconstructed commit votes)
    for anything it missed live."""
    from .test_consensus_reactor import build_p2p_node, connect_full_mesh

    vs, pvs = make_validators(4)
    genesis = make_genesis(vs)
    H = 3

    async def run():
        nodes = []
        for i, pv in enumerate(pvs):
            cfg = (
                _pipelined_config()
                if i < 3
                else ConsensusConfig.test_config()
            )
            nodes.append(build_p2p_node(vs, pv, genesis, config=cfg))
        for cs, nk, t, sw in nodes:
            await t.listen()
            await sw.start()
        await connect_full_mesh(nodes)
        for cs, *_ in nodes:
            await cs.start()
        await asyncio.gather(
            *(cs.wait_for_height(H, timeout=90) for cs, *_ in nodes)
        )
        hashes = {cs.block_store.load_block(H).hash() for cs, *_ in nodes}
        legacy = nodes[3][0]
        assert legacy.pipeline is None
        assert not legacy.config.pipelined_heights
        for cs, nk, t, sw in nodes:
            await cs.stop()
            await sw.stop()
        return hashes

    hashes = asyncio.run(run())
    assert len(hashes) == 1, "legacy peer diverged from the pipelined chain"


# --- soak -------------------------------------------------------------------


@pytest.mark.slow
def test_pipelined_soak_conserves_and_overlaps():
    """Longer pipelined run: 12 heights on the 4-validator net with
    tracing on — every completed height stays conserved under overlap
    accounting, the net never diverges, and the run actually books
    background overlap (the feature is exercised, not just enabled)."""
    tracer = obs.Tracer(enabled=True, ring_size=65536)
    css = _run_net(True, 12, tracer=tracer)
    recs = [r.to_json() for r in tracer.records()]
    cons = obs.wall_conservation(recs)
    rows = cons.get("heights", {})
    assert len(rows) >= 8
    assert obs.check_conservation(cons) == []
    agg = cons.get("aggregate", {})
    assert agg.get("dark_fraction", 1.0) <= 0.05
    assert (
        sum(r.get("pipeline_overlap_ms", 0.0) for r in rows.values()) > 0.0
    )
    for cs in css:
        assert cs.state.last_block_height >= 12
