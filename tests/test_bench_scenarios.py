"""The bench.py scenario generators at CI-sized shapes.

The driver runs bench.py on the real chip at full size; these tests pin
the *logic* — the lazy rotating light chain actually forces bisection,
the churn harness verifies correctly through a rotation — so a capture
failure on the chip can only be performance, not correctness.
"""

import pytest


def test_lazy_rotating_chain_forces_bisection():
    """Half-set rotation every `rotate_every` heights makes regions two
    apart share no keys, so the client cannot one-shot the trust jump:
    4 regions must cost >= 4 light-block fetches (a static set costs 2
    — target + trust root)."""
    import bench

    rate, reqs, dt = bench._bench_light_bisection_1k(
        n_heights=64, n_vals=8, rotate_every=16
    )
    assert reqs >= 4, f"rotation did not force bisection: {reqs} reqs"
    assert rate > 0


def test_churn_harness_verifies_through_rotation():
    import bench

    rate, dt = bench._bench_churn_throughput()
    assert rate > 0


def test_table_build_metrics_shape():
    import bench

    ms = bench._bench_table_build()
    names = {m["metric"] for m in ms}
    assert names == {
        "ed25519_table_build_cold_per_key",
        "ed25519_table_build_hit_per_key",
    }
    for m in ms:
        assert m["value"] > 0 and m["vs_baseline"] > 0
