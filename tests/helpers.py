"""Shared test factories — the analog of the reference's internal/test
builders (internal/test/block.go, vote.go, ...)."""

from __future__ import annotations

import time

from tendermint_tpu.types.block import Block, Commit
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.priv_validator import MockPV
from tendermint_tpu.types.validator import Validator
from tendermint_tpu.types.validator_set import ValidatorSet
from tendermint_tpu.types.vote import Vote, VoteType
from tendermint_tpu.types.vote_set import VoteSet

CHAIN_ID = "test-chain"
T0 = 1_700_000_000_000_000_000


def make_validators(n: int, power: int = 10, seed: bytes = b"val"):
    """(ValidatorSet, [MockPV]) with privvals ordered to match the set."""
    pvs = [MockPV.from_secret(seed + b"%d" % i) for i in range(n)]
    vs = ValidatorSet([Validator(pv.get_pub_key(), power) for pv in pvs])
    by_addr = {pv.get_pub_key().address(): pv for pv in pvs}
    return vs, [by_addr[v.address] for v in vs.validators]


def make_weighted_validators(powers, seed: bytes = b"val"):
    """Like make_validators but with per-validator voting powers; the
    returned privvals are ordered to match the SORTED set, so pvs[i] is
    validator index i (a quorum-attribution test needs one validator
    whose vote every 2/3 requires)."""
    pvs = [MockPV.from_secret(seed + b"%d" % i) for i in range(len(powers))]
    vs = ValidatorSet(
        [Validator(pv.get_pub_key(), p) for pv, p in zip(pvs, powers)]
    )
    by_addr = {pv.get_pub_key().address(): pv for pv in pvs}
    return vs, [by_addr[v.address] for v in vs.validators]


def make_genesis(vs: ValidatorSet, chain_id: str = CHAIN_ID) -> GenesisDoc:
    doc = GenesisDoc(
        chain_id=chain_id,
        genesis_time_ns=T0,
        validators=[
            GenesisValidator(
                "ed25519", v.pub_key.data, v.voting_power,
                bls_pub_key=v.bls_pub_key,
            )
            for v in vs.validators
        ],
    )
    doc.validate_and_complete()
    return doc


def make_qc_validators(n: int, power: int = 10, seed: bytes = b"val"):
    """(ValidatorSet, [MockPV], {address: bls_priv}) — a QC-capable
    committee: every validator carries a BLS key committed into the set
    hash, and the returned scalar map signs QC contributions.
    Deterministic in `seed` (BLS scalars derive from it, not from
    generate_priv_key), so two calls build the same committee."""
    from tendermint_tpu.crypto import bls_signatures as bls
    from tendermint_tpu.crypto.bls12_381 import R

    pvs = [MockPV.from_secret(seed + b"%d" % i) for i in range(n)]
    vals, privs = [], {}
    for i, pv in enumerate(pvs):
        import hashlib

        scalar = (
            int.from_bytes(
                hashlib.sha256(seed + b"bls%d" % i).digest(), "big"
            )
            % (R - 1)
            + 1
        )
        pub = bls.pubkey_from_priv(scalar)
        addr = pv.get_pub_key().address()
        privs[addr] = scalar
        vals.append(
            Validator(
                pv.get_pub_key(), power,
                bls_pub_key=bls.g2_to_bytes(pub.key),
            )
        )
    vs = ValidatorSet(vals)
    by_addr = {pv.get_pub_key().address(): pv for pv in pvs}
    return vs, [by_addr[v.address] for v in vs.validators], privs


def sign_commit(
    vs: ValidatorSet,
    pvs: list,
    height: int,
    round_: int,
    block_id: BlockID,
    chain_id: str = CHAIN_ID,
    time_ns: int = T0,
    bls_privs: dict | None = None,
) -> Commit:
    """All validators precommit block_id; returns the Commit. With
    `bls_privs` (make_qc_validators' scalar map) every vote also
    carries a QC dual-signature, so the commit compresses via
    assemble_qc."""
    votes = VoteSet(chain_id, height, round_, VoteType.PRECOMMIT, vs)
    qc_msg = None
    if bls_privs is not None:
        from tendermint_tpu.types.quorum_cert import qc_sign_bytes

        qc_msg = qc_sign_bytes(chain_id, height, round_, block_id)
    for i, pv in enumerate(pvs):
        v = Vote(
            type=VoteType.PRECOMMIT,
            height=height,
            round=round_,
            block_id=block_id,
            timestamp_ns=time_ns + i,
            validator_address=pv.get_pub_key().address(),
            validator_index=i,
        )
        pv.sign_vote(chain_id, v)
        if qc_msg is not None:
            from tendermint_tpu.crypto import bls_signatures as bls

            v.qc_signature = bls.g1_to_bytes(
                bls.sign(bls_privs[v.validator_address], qc_msg)
            )
        votes.add_vote(v, verified=True)
    return votes.make_commit()
