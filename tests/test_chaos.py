"""Chaos subsystem: seeded determinism, partition/heal liveness,
kill/restart recovery, retry jitter/backoff, backend-outage degradation.

Deterministic by construction (every random draw comes from the scenario
seed), so the whole module stays inside the tier-1 `not slow` budget.
Select with `-m chaos`.
"""

from __future__ import annotations

import asyncio
import json
import random
import sys

import pytest

from tendermint_tpu.chaos import (
    ChaosConn,
    ChaosNetwork,
    FaultTrace,
    LinkPolicy,
    Scenario,
    ScenarioRunner,
    Step,
    fallback_artifact,
    link_rng,
    probe_backend,
)
from tendermint_tpu.chaos.scenario import random_scenario

pytestmark = pytest.mark.chaos


# --- link model (unit) ------------------------------------------------------


class _SinkConn:
    """Fake SecretConnection capturing written frames."""

    def __init__(self):
        self.frames: list[bytes] = []
        self.closed = False

    async def write(self, data: bytes) -> None:
        self.frames.append(data)

    async def read(self) -> bytes:  # pragma: no cover - never used
        await asyncio.sleep(3600)

    def close(self) -> None:
        self.closed = True


def _packets(n_msgs: int, ch: int = 0x20, payload: bytes = b"x" * 40):
    """n single-packet mconn messages on one channel."""
    return [bytes([ch, 1]) + payload + b"%03d" % i for i in range(n_msgs)]


async def _drive(policy: LinkPolicy, seed: int, n_msgs: int = 40):
    sink = _SinkConn()
    conn = ChaosConn(
        sink, policy, link_rng(seed, "a", "b"), link_id="a>b"
    )
    for pkt in _packets(n_msgs):
        await conn.write(pkt)
    # wait until everything scheduled has been pumped out
    deadline = asyncio.get_running_loop().time() + 10.0
    expected = sum(
        1 + e[6] for e in conn.trace.entries if e[3] == "deliver"
    )
    while len(sink.frames) < expected:
        if asyncio.get_running_loop().time() > deadline:
            raise TimeoutError("chaos pump stalled")
        await asyncio.sleep(0.01)
    conn.close()
    return sink, conn


def test_link_trace_deterministic():
    """Same seed + same message sequence => byte-identical fault trace;
    a different seed diverges."""
    policy = LinkPolicy(
        latency_s=0.001, jitter_s=0.004, drop=0.25, duplicate=0.15
    )

    async def run(seed):
        _, conn = await _drive(policy, seed)
        return conn.trace.to_jsonl()

    t1 = asyncio.run(run(7))
    t2 = asyncio.run(run(7))
    t3 = asyncio.run(run(8))
    assert t1 == t2, "same-seed fault traces diverged"
    assert t1 != t3, "different seeds produced identical traces"
    # and the trace actually contains both outcomes at a 25% drop rate
    kinds = {json.loads(line)[3] for line in t1.splitlines()}
    assert kinds == {"drop", "deliver"}


def test_link_drop_all_and_duplicate_all():
    async def run():
        sink_drop, _ = await _drive(LinkPolicy(drop=1.0), seed=1, n_msgs=10)
        assert sink_drop.frames == []
        sink_dup, _ = await _drive(
            LinkPolicy(duplicate=1.0), seed=1, n_msgs=10
        )
        assert len(sink_dup.frames) == 20
        # FIFO preserved under latency+jitter when reorder is off
        sink_fifo, _ = await _drive(
            LinkPolicy(latency_s=0.002, jitter_s=0.01), seed=3, n_msgs=15
        )
        assert sink_fifo.frames == _packets(15)

    asyncio.run(run())


def test_link_multiplexed_messages_stay_coherent():
    """Interleaved multi-packet messages on two channels keep per-message
    packet runs contiguous per channel (reassembly-safe shaping)."""

    async def run():
        sink = _SinkConn()
        conn = ChaosConn(
            sink,
            LinkPolicy(latency_s=0.001, jitter_s=0.003),
            link_rng(5, "a", "b"),
        )
        # channel 0x20 message in two packets, interleaved with a
        # channel 0x30 single-packet message
        await conn.write(bytes([0x20, 0]) + b"part1")
        await conn.write(bytes([0x30, 1]) + b"other")
        await conn.write(bytes([0x20, 1]) + b"part2")
        while len(sink.frames) < 3:
            await asyncio.sleep(0.01)
        conn.close()
        # the 0x20 frames must be adjacent (one scheduling unit)
        idx = [i for i, f in enumerate(sink.frames) if f[0] == 0x20]
        assert idx[1] == idx[0] + 1
        assert sink.frames[idx[0]][2:] == b"part1"
        assert sink.frames[idx[1]][2:] == b"part2"

    asyncio.run(run())


def test_link_policy_updates_apply_to_live_conn():
    """set_link/set_default_policy mid-scenario must reshape connections
    that are ALREADY established: ChaosConn re-resolves its policy per
    message through policy_fn."""

    async def run():
        sink = _SinkConn()
        policies = {"cur": LinkPolicy()}
        conn = ChaosConn(
            sink,
            policies["cur"],
            link_rng(1, "a", "b"),
            policy_fn=lambda: policies["cur"],
        )
        pkts = _packets(3)
        await conn.write(pkts[0])  # noop: passes straight through
        assert sink.frames == [pkts[0]]
        policies["cur"] = LinkPolicy(drop=1.0)
        await conn.write(pkts[1])  # dropped by the NEW policy, same conn
        policies["cur"] = LinkPolicy()
        await conn.write(pkts[2])
        assert sink.frames == [pkts[0], pkts[2]]
        conn.close()

    asyncio.run(run())


# --- dial retry jitter (p2p/switch.py satellite) ----------------------------


class _DeadTransport:
    """Transport whose dials always fail and that never accepts."""

    def __init__(self):
        self.listen_port = 0
        self.dials = 0

    async def accept(self):
        await asyncio.sleep(3600)

    async def dial(self, addr):
        self.dials += 1
        raise ConnectionError("unreachable")

    async def close(self):
        pass

    def _node_info_fn(self):  # pragma: no cover - never reached
        raise AssertionError


class _RecordingRng(random.Random):
    def __init__(self, seed):
        super().__init__(seed)
        self.ceilings: list[float] = []

    def uniform(self, a, b):
        self.ceilings.append(b)
        return 0.001  # keep the test fast; the draw itself is recorded


def test_dial_retry_full_jitter_cap_and_gave_up_event():
    from tendermint_tpu.p2p.switch import (
        EVENT_PEER_DIAL_GAVE_UP,
        Switch,
    )
    from tendermint_tpu.p2p.transport import NetAddress

    async def run():
        transport = _DeadTransport()
        rng = _RecordingRng(42)
        sw = Switch(transport, max_dial_attempts=6, dial_rng=rng)
        gave_up = []
        sw.events.add_listener(
            "t", EVENT_PEER_DIAL_GAVE_UP, gave_up.append
        )
        await sw.start()
        addr = NetAddress("deadbeef", "127.0.0.1", 1)
        await sw._dial_with_retry(addr)
        await sw.stop()
        return transport, rng, gave_up, addr

    transport, rng, gave_up, addr = asyncio.run(run())
    assert transport.dials == 6, "attempt cap not enforced"
    # full-jitter ceilings: 0.2·2ⁿ capped at 10 — and the sleep is a
    # uniform draw below the ceiling, not the fixed lockstep schedule
    assert rng.ceilings == [
        min(10.0, 0.2 * 2**n) for n in range(1, 6)
    ]
    assert gave_up == [addr], "terminal gave-up event not fired"


# --- statesync chunk backoff + rotation -------------------------------------


def test_chunk_retry_backoff_and_last_sender():
    from tendermint_tpu.statesync.chunks import ChunkQueue

    now = [0.0]
    q = ChunkQueue(2, now=lambda: now[0])
    assert q.allocate() == 0
    assert q.allocate() == 1
    q.note_request(0, "pA")
    q.retry(0)
    # immediately after a failure the chunk is backing off
    assert q.allocate() is None
    assert q.last_sender(0) == "pA"
    assert q.retries(0) == 1
    now[0] = 0.11  # past the 0.1s first backoff
    assert q.allocate() == 0
    q.retry(0, "pB")
    assert q.last_sender(0) == "pB"
    now[0] = 0.25  # second backoff doubles to 0.2s: not yet elapsed
    assert q.allocate() is None
    now[0] = 0.45
    assert q.allocate() == 0


def test_chunk_fetch_rotates_away_from_failing_peer():
    from tendermint_tpu.statesync.chunks import ChunkQueue
    from tendermint_tpu.statesync.syncer import Syncer, _DiscoveredSnapshot

    class _Peer:
        def __init__(self, pid):
            self.id = pid

    class _Snap:
        height, format, chunks, hash = 5, 1, 1, b"h"

    requests = []

    async def run():
        syncer = Syncer(
            app_snapshot_conn=None,
            state_provider=None,
            request_chunk=lambda peer, h, f, i: requests.append(
                (peer.id, i)
            ),
        )
        d = _DiscoveredSnapshot(_Snap())
        d.peers = [_Peer("pA"), _Peer("pB")]
        q = ChunkQueue(1)
        # chunk 0 was fetched from pA and failed
        assert q.allocate() == 0
        q.note_request(0, "pA")
        q.retry(0, "pA")
        task = asyncio.create_task(syncer._fetch_chunks(d, q))
        deadline = asyncio.get_running_loop().time() + 5.0
        while not requests:
            if asyncio.get_running_loop().time() > deadline:
                break
            await asyncio.sleep(0.02)
        task.cancel()
        try:
            await task
        except (asyncio.CancelledError, Exception):
            pass

    asyncio.run(run())
    assert requests, "refetch never happened"
    assert requests[0][0] == "pB", "refetch did not rotate off the failing peer"


# --- backend guard ----------------------------------------------------------


def test_backend_guard_probe_classification():
    ok = probe_backend(
        probe_cmd=[sys.executable, "-c", "print('cpu')"], timeout_s=30
    )
    assert ok.available and ok.backend == "cpu" and ok.kind == "ok"

    hang = probe_backend(
        probe_cmd=[sys.executable, "-c", "import time; time.sleep(30)"],
        timeout_s=0.5,
    )
    assert not hang.available and hang.kind == "timeout" and hang.rc == 124

    tunnel = probe_backend(
        probe_cmd=[
            sys.executable,
            "-c",
            "import sys; sys.stderr.write(\"Unable to initialize backend "
            "'axon': UNAVAILABLE\"); sys.exit(1)",
        ],
        timeout_s=30,
    )
    assert not tunnel.available and tunnel.kind == "tunnel_down"

    broken = probe_backend(
        probe_cmd=[
            sys.executable,
            "-c",
            "import sys; sys.stderr.write('ImportError: no jax'); sys.exit(2)",
        ],
        timeout_s=30,
    )
    assert not broken.available and broken.kind == "backend_error"

    art = fallback_artifact(tunnel, fallback="cpu", extra={"metric": "m"})
    assert {"rc", "error", "backend", "fallback"} <= set(art)
    json.dumps(art)  # must be serializable as-is


def test_multichip_capture_artifact_always_parseable(monkeypatch):
    import __graft_entry__
    from tools import multichip_capture

    art = multichip_capture.capture(0)  # 0 devices: dryrun asserts fast
    # success or failure, the artifact must carry the structured keys
    assert {"n_devices", "rc", "ok", "error", "backend", "fallback"} <= set(
        art
    )

    def boom(n):
        raise RuntimeError("sanitized dryrun child exceeded 1500s (hang)")

    monkeypatch.setattr(__graft_entry__, "dryrun_multichip", boom)
    art = multichip_capture.capture(8)
    assert art["ok"] is False and art["rc"] == 124
    assert art["kind"] == "timeout"
    json.dumps(art)


@pytest.mark.parametrize("forced_platform", ["tpu"])
def test_bench_degrades_to_structured_json_when_backend_unavailable(
    forced_platform, tmp_path
):
    """The acceptance scenario: bench.py with the device backend forced
    unavailable exits 0 and prints a parseable structured artifact (the
    CPU re-capture is disabled here to stay in the quick tier — its
    probe/exec path is covered by the guard unit tests)."""
    import os
    import subprocess

    env = dict(os.environ)
    env.update(
        {
            "JAX_PLATFORMS": forced_platform,  # no such plugin -> probe fails
            "TM_TPU_BENCH_NO_FALLBACK": "1",
            # the tpu probe hangs until the guard kills it — keep the
            # bound tight so this stays inside the quick-tier budget
            "TM_TPU_BACKEND_GUARD_TIMEOUT": "8",
        }
    )
    proc = subprocess.run(
        [sys.executable, "bench.py"],
        capture_output=True,
        text=True,
        timeout=300,
        env=env,
        cwd="/root/repo",
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    art = json.loads(line)
    assert {"rc", "error", "backend", "fallback"} <= set(art)
    assert art["fallback"] == "none"
    assert art["tunnel_down"] is True


def test_bench_require_backend_fails_structured():
    """--require-backend tpu on a CPU-only environment: non-zero exit,
    structured {"rc","error","backend"} artifact with a meta block, NO
    fallback row — the r04-r06 silent-CPU-capture regression class can
    no longer produce a green bench run."""
    import os
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # probe succeeds, backend != tpu
    proc = subprocess.run(
        [sys.executable, "bench.py", "--require-backend", "tpu"],
        capture_output=True,
        text=True,
        timeout=300,
        env=env,
        cwd="/root/repo",
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr[-2000:]
    art = json.loads(proc.stdout.strip().splitlines()[-1])
    assert art["rc"] == 1
    assert art["backend"] == "cpu"
    assert art["fallback"] == "none"
    assert art["kind"] == "backend_mismatch"
    assert art["required_backend"] == "tpu"
    assert "meta" in art  # provenance stamp rides every artifact
    # and the same contract on the multichip capture
    proc = subprocess.run(
        [
            sys.executable,
            "tools/multichip_capture.py",
            "4",
            "--require-backend",
            "tpu",
        ],
        capture_output=True,
        text=True,
        timeout=300,
        env=env,
        cwd="/root/repo",
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr[-2000:]
    art = json.loads(proc.stdout.strip().splitlines()[-1])
    assert art["ok"] is False and art["fallback"] == "none"
    assert art["kind"] == "backend_mismatch"
    assert "meta" in art  # provenance stamps the MULTICHIP family too
    # and the sequencer_stream family honors the same contract (it is
    # wall-clock/CPU-valid, but an operator pinning a backend must get
    # the structured failure, never a silent CPU row)
    proc = subprocess.run(
        [
            sys.executable,
            "bench.py",
            "--family",
            "sequencer_stream",
            "--require-backend",
            "tpu",
        ],
        capture_output=True,
        text=True,
        timeout=300,
        env=env,
        cwd="/root/repo",
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr[-2000:]
    art = json.loads(proc.stdout.strip().splitlines()[-1])
    assert art["rc"] == 1 and art["fallback"] == "none"
    assert art["kind"] == "backend_mismatch"
    assert art["required_backend"] == "tpu"
    assert "meta" in art


# --- scenario e2e on a 4-validator mesh -------------------------------------


def _mesh():
    from tests.chaos_harness import build_chaos_handles

    return build_chaos_handles(4)


def _run_storm(seed: int, until: int):
    from tests.chaos_harness import chain_hashes, start_mesh, stop_mesh

    scenario = Scenario(
        seed=seed,
        steps=[
            Step(
                at_height=2,
                action="clock_skew",
                params={"node": "n3", "scale": 1.2},
            ),
        ],
        default_policy=LinkPolicy(
            latency_s=0.005, jitter_s=0.01, drop=0.02, duplicate=0.02
        ),
    )

    async def run():
        handles = _mesh()
        runner = ScenarioRunner(handles, scenario)
        await start_mesh(handles)
        try:
            heights = await runner.run(until_height=until, timeout=120)
            hashes = await chain_hashes(handles, until - 1)
        finally:
            await stop_mesh(handles)
        return runner.plan_jsonl(), heights, hashes

    return asyncio.run(run())


def test_scenario_determinism_latency_drop_storm():
    """Same seed => byte-identical scenario plan trace and identical
    committed-height sequences up to the target on a real 4-validator
    p2p mesh under a latency+drop+duplicate storm."""
    until = 4
    plan1, heights1, hashes1 = _run_storm(seed=7, until=until)
    plan2, heights2, hashes2 = _run_storm(seed=7, until=until)
    assert plan1 == plan2, "same-seed scenario plans diverged"
    want = list(range(1, until + 1))
    for heights in (heights1, heights2):
        for name, seq in heights.items():
            assert seq[:until] == want, f"{name} missed heights: {seq}"
    assert len(hashes1) == 1 and len(hashes2) == 1, "chains diverged"
    # different seed => different plan bytes (seed is recorded)
    plan3, _, _ = _run_storm(seed=8, until=2)
    assert plan1 != plan3


def test_partition_heal_liveness():
    """2|2 split: neither half can commit (no 2/3 of 4); after heal all
    four reconverge on one chain and resume committing."""
    from tests.chaos_harness import chain_hashes, start_mesh, stop_mesh

    async def run():
        handles = _mesh()
        net = ChaosNetwork(seed=11)
        for h in handles:
            net.install(h)
        await start_mesh(handles)
        try:
            await asyncio.gather(
                *(h.cs.wait_for_height(2, timeout=60) for h in handles)
            )
            await net.partition(
                "split", [["n0", "n1"], ["n2", "n3"]]
            )
            # cross-group links must be down
            for h in handles:
                for peer_id in h.switch.peers:
                    other = net._name_for(peer_id)
                    assert net.allowed(h.name, other), (
                        f"live cross-partition conn {h.name}<->{other}"
                    )
            await asyncio.sleep(1.0)  # let in-flight commits settle
            stalled = [h.block_store.height for h in handles]
            await asyncio.sleep(2.0)
            assert [
                h.block_store.height for h in handles
            ] == stalled, "a 2|2 partition committed blocks"

            await net.heal("split")
            target = max(stalled) + 2
            await asyncio.gather(
                *(
                    h.cs.wait_for_height(target, timeout=90)
                    for h in handles
                )
            )
            hashes = await chain_hashes(handles, target)
            assert len(hashes) == 1, "nodes diverged after heal"
        finally:
            await stop_mesh(handles)

    asyncio.run(run())


def test_kill_restart_scenario_recovers():
    """Seeded kill/restart timeline: node n3 dies at height 2, restarts
    4s in with fresh p2p around the same state, and the whole mesh
    (including n3) reaches the target on one chain."""
    from tests.chaos_harness import chain_hashes, start_mesh, stop_mesh

    scenario = Scenario(
        seed=13,
        steps=[
            Step(at_height=2, action="kill", params={"node": "n3"}),
            # after=0: never restart before the kill has fired, even if
            # the mesh takes >4s to reach height 2
            Step(
                at_time=4.0,
                action="restart",
                params={"node": "n3"},
                after=0,
            ),
        ],
    )

    async def run():
        handles = _mesh()
        runner = ScenarioRunner(handles, scenario)
        await start_mesh(handles)
        try:
            heights = await runner.run(until_height=4, timeout=120)
            assert all(seq[:4] == [1, 2, 3, 4] for seq in heights.values())
            hashes = await chain_hashes(handles, 3)
            assert len(hashes) == 1, "chains diverged after kill/restart"
        finally:
            await stop_mesh(handles)

    asyncio.run(run())


def test_random_scenario_is_seed_stable():
    names = ["n0", "n1", "n2", "n3"]
    s1 = random_scenario(99, names)
    s2 = random_scenario(99, names)
    s3 = random_scenario(100, names)
    as_plan = lambda s: [st.resolved(i) for i, st in enumerate(s.steps)] + [
        s.default_policy
    ]
    assert as_plan(s1) == as_plan(s2)
    assert as_plan(s1) != as_plan(s3)
