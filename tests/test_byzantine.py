"""Byzantine-validator consensus tests (VERDICT r3 missing #1).

Reconstruction of the reference's dead byzantine suite
(consensus/byzantine_test.go in /root/reference; SURVEY.md §4.1 makes
rebuilding it this repo's deliverable): a 4-validator in-proc net where
the 4th validator is actively malicious — it never runs the honest state
machine, and a driver hooked into the honest nodes' gossip injects
signed equivocations at the live (height, round). Each scenario asserts
the three byzantine-fault-tolerance properties:

  safety   — honest nodes never commit different blocks at a height
  evidence — the equivocation is captured, pooled, proposed, and lands
             in a committed block as DuplicateVoteEvidence
  liveness — the chain keeps advancing with 3/4 honest power
"""

import asyncio
import time

from tendermint_tpu.abci.client import LocalClient
from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.consensus.messages import ProposalMessage, VoteMessage
from tendermint_tpu.consensus.state_machine import (
    ConsensusConfig,
    ConsensusState,
)
from tendermint_tpu.evidence import EvidencePool
from tendermint_tpu.l2node.mock import MockL2Node
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.state import State
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.store.block_store import BlockStore
from tendermint_tpu.store.kv import MemKV
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.evidence import DuplicateVoteEvidence
from tendermint_tpu.types.part_set import PartSetHeader
from tendermint_tpu.types.vote import Vote, VoteType

from .helpers import CHAIN_ID, make_genesis, make_validators

BYZ = 3  # validator index of the byzantine actor


def _make_honest_node(pv, genesis):
    """Full node with an evidence pool wired through the executor, so
    captured equivocations flow into proposed blocks."""
    l2 = MockL2Node()
    app = KVStoreApplication()
    state = State.from_genesis(genesis)
    state_store = StateStore(MemKV())
    state_store.bootstrap(state)
    block_store = BlockStore(MemKV())
    pool = EvidencePool(MemKV(), state_store, block_store)
    executor = BlockExecutor(
        state_store, block_store, LocalClient(app), l2, evidence_pool=pool
    )
    cs = ConsensusState(
        ConsensusConfig.test_config(),
        state,
        executor,
        block_store,
        l2,
        priv_validator=pv,
        evidence_pool=pool,
    )
    return cs, pool, block_store


def _byz_vote(pv, vtype, height, round_, block_id):
    v = Vote(
        type=vtype,
        height=height,
        round=round_,
        block_id=block_id,
        timestamp_ns=time.time_ns(),
        validator_address=pv.get_pub_key().address(),
        validator_index=BYZ,
    )
    pv.sign_vote(CHAIN_ID, v)
    return v


def _fake_block_id():
    h = b"\xbb" * 32
    return BlockID(hash=h, part_set_header=PartSetHeader(1, h))


def _wire(css, observer=None):
    """Full-mesh gossip of self-produced messages; `observer(i, msg)`
    sees every broadcast (the byzantine driver's tap)."""
    for i, n in enumerate(css):

        def hook(msg, i=i):
            for j, other in enumerate(css):
                if j != i:
                    other.peer_msg_queue.put_nowait((msg, f"node{i}"))
            if observer is not None:
                observer(i, msg)

        n.broadcast_hook = hook


def _inject(cs, vote):
    cs.peer_msg_queue.put_nowait((VoteMessage(vote), "byzantine"))


def _assert_no_fork(css, up_to_height):
    for h in range(1, up_to_height + 1):
        hashes = {
            cs.block_store.load_block(h).hash()
            for cs in css
            if cs.block_store.load_block(h) is not None
        }
        assert len(hashes) <= 1, f"honest nodes forked at height {h}"


def _committed_byz_evidence(block_store, byz_addr, up_to_height):
    for h in range(2, up_to_height + 1):
        blk = block_store.load_block(h)
        if blk is None:
            continue
        for ev in blk.evidence:
            if (
                isinstance(ev, DuplicateVoteEvidence)
                and ev.vote_a.validator_address == byz_addr
            ):
                return ev
    return None


def test_equivocating_precommits_yield_committed_evidence():
    """The byzantine validator precommits two different blocks at the
    same (height, round), relayed to every honest node. Safety holds,
    the duplicate-vote evidence commits, and the chain keeps moving
    (reference byzantine_test.go's double-sign shape)."""
    vs, pvs = make_validators(4)
    genesis = make_genesis(vs)
    byz_pv = pvs[BYZ]
    byz_addr = byz_pv.get_pub_key().address()

    nodes = [_make_honest_node(pv, genesis) for pv in pvs[:3]]
    css = [n[0] for n in nodes]
    injected: set = set()

    def byz_driver(i, msg):
        # fire once per height, at PROPOSAL time (the start of the round):
        # the byzantine validator "precommits" both the proposed block and
        # a fake one at the same (h, r) to the whole net. Injecting on a
        # late-round trigger (an observed precommit) is flaky under CPU
        # contention — votes for an already-committed height are discarded
        # (state_machine._add_vote), never captured as evidence.
        if not isinstance(msg, ProposalMessage):
            return
        p = msg.proposal
        if p.height in injected:
            return
        injected.add(p.height)
        va = _byz_vote(byz_pv, VoteType.PRECOMMIT, p.height, p.round, p.block_id)
        vb = _byz_vote(
            byz_pv, VoteType.PRECOMMIT, p.height, p.round, _fake_block_id()
        )
        for cs in css:
            _inject(cs, va)
            _inject(cs, vb)

    _wire(css, observer=byz_driver)

    async def run():
        for cs in css:
            await cs.start()
        await asyncio.gather(*(cs.wait_for_height(5, timeout=90) for cs in css))
        # Evidence needs a proposal slot after capture: on a loaded box
        # the injection can fire late (height 4+), so keep the chain
        # running until the evidence commits (bounded) instead of
        # hard-stopping at height 5.
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if (
                injected
                and _committed_byz_evidence(
                    css[0].block_store, byz_addr, css[0].state.last_block_height
                )
                is not None
            ):
                break
            await asyncio.sleep(0.25)
        for cs in css:
            await cs.stop()

    asyncio.run(run())
    assert injected, "byzantine driver never fired"
    top = max(cs.state.last_block_height for cs in css)
    _assert_no_fork(css, top)
    ev = _committed_byz_evidence(css[0].block_store, byz_addr, top)
    assert ev is not None, "byzantine equivocation never committed as evidence"
    assert ev.vote_a.block_id != ev.vote_b.block_id
    for cs in css:
        assert cs.state.last_block_height >= 5, "liveness lost"


def test_split_prevotes_no_fork():
    """Conflicting prevotes targeted at different peers (the classic
    split-vote attack): the real proposal hash goes to nodes {0,1}, a
    fabricated hash to nodes {1,2}. Node 1 sees both and captures the
    equivocation; no honest pair ever commits different blocks."""
    vs, pvs = make_validators(4)
    genesis = make_genesis(vs)
    byz_pv = pvs[BYZ]
    byz_addr = byz_pv.get_pub_key().address()

    nodes = [_make_honest_node(pv, genesis) for pv in pvs[:3]]
    css = [n[0] for n in nodes]
    injected: set = set()

    def byz_driver(i, msg):
        if not isinstance(msg, ProposalMessage):
            return
        p = msg.proposal
        key = (p.height, p.round)
        if key in injected or len(injected) >= 3:
            return
        injected.add(key)
        real = _byz_vote(
            byz_pv, VoteType.PREVOTE, p.height, p.round, p.block_id
        )
        fake = _byz_vote(
            byz_pv, VoteType.PREVOTE, p.height, p.round, _fake_block_id()
        )
        _inject(css[0], real)
        _inject(css[1], real)
        _inject(css[1], fake)
        _inject(css[2], fake)

    _wire(css, observer=byz_driver)

    async def run():
        for cs in css:
            await cs.start()
        await asyncio.gather(*(cs.wait_for_height(4, timeout=90) for cs in css))
        for cs in css:
            await cs.stop()

    asyncio.run(run())
    assert injected, "byzantine driver never fired"
    _assert_no_fork(css, 4)
    for cs in css:
        assert cs.state.last_block_height >= 4, "liveness lost"
    # node 1 received both conflicting prevotes: the equivocation must be
    # captured and eventually committed by some honest proposer
    ev = _committed_byz_evidence(css[1].block_store, byz_addr, 4)
    assert ev is not None, "split prevotes never captured as evidence"
    assert ev.vote_a.type == VoteType.PREVOTE


def test_byzantine_proposer_rounds_skipped():
    """The byzantine validator is silent whenever it is the proposer
    (forcing round changes) while still equivocating precommits in other
    rounds. The honest majority must ride through its proposer slots:
    liveness and agreement hold across a window that includes byzantine
    proposer rounds."""
    vs, pvs = make_validators(4)
    genesis = make_genesis(vs)
    byz_pv = pvs[BYZ]
    byz_addr = byz_pv.get_pub_key().address()

    nodes = [_make_honest_node(pv, genesis) for pv in pvs[:3]]
    css = [n[0] for n in nodes]
    injected: set = set()

    def byz_driver(i, msg):
        # equivocating precommits injected at proposal time, once per
        # height (same stale-height rationale as the equivocation test
        # above)
        if not isinstance(msg, ProposalMessage):
            return
        p = msg.proposal
        if p.height in injected:
            return
        injected.add(p.height)
        va = _byz_vote(byz_pv, VoteType.PRECOMMIT, p.height, p.round, p.block_id)
        vb = _byz_vote(
            byz_pv, VoteType.PRECOMMIT, p.height, p.round, _fake_block_id()
        )
        for cs in css:
            _inject(cs, va)
            _inject(cs, vb)

    _wire(css, observer=byz_driver)

    async def run():
        for cs in css:
            await cs.start()
        # 6 heights with round-robin proposers guarantees at least one
        # byzantine proposer slot (4 validators)
        await asyncio.gather(*(cs.wait_for_height(6, timeout=120) for cs in css))
        # Evidence needs a proposal slot after capture: on a loaded box
        # the injection can fire late, so keep the chain running until
        # the evidence commits (bounded) instead of hard-stopping at 6
        # (same deflake as the equivocation test above).
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if (
                injected
                and _committed_byz_evidence(
                    css[0].block_store, byz_addr, css[0].state.last_block_height
                )
                is not None
            ):
                break
            await asyncio.sleep(0.25)
        for cs in css:
            await cs.stop()

    asyncio.run(run())
    top = max(cs.state.last_block_height for cs in css)
    _assert_no_fork(css, top)
    for cs in css:
        assert cs.state.last_block_height >= 6, "liveness lost"
    # at least one commit must carry a non-zero round (the byzantine
    # proposer's slot timed out and the net recovered in a later round)
    rounds = []
    for h in range(1, top + 1):
        blk = css[0].block_store.load_block(h + 1)
        if blk is not None and blk.last_commit is not None:
            rounds.append(blk.last_commit.round)
        else:
            sc = css[0].block_store.load_seen_commit(h)
            if sc is not None:
                rounds.append(sc.round)
    assert any(r > 0 for r in rounds), (
        f"no round ever advanced past 0 ({rounds}) — byzantine proposer "
        "slots were never exercised"
    )
    ev = _committed_byz_evidence(css[0].block_store, byz_addr, top)
    assert ev is not None, "equivocation evidence missing"
