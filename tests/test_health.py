"""Live health plane (tendermint_tpu/obs/health.py) + bench-trend gate
(tools/bench_trend.py).

Three layers, mirroring the PR 7 pacing suite:

- deterministic detector/SLO units on synthetic timestamped streams —
  no clock reads anywhere: every feed and every verdict passes an
  explicit `t`, so two monitors fed the same stream are bit-identical;

- monitor-level wiring: pull-seam sampling over REAL libs.metrics
  objects (histogram-delta -> SLO event stream), incident emission into
  the tracer ring, tm_health_status / tm_slo_burn_rate gauge export,
  and the verdict document the health/dump_health RPCs serve;

- the chaos e2e (marked chaos, quick tier): a 50 ms straggler link on
  the PR 5 weighted-quorum topology must flip the victim's quorum-lag
  detector to warn — and only consensus-plane detectors — within K=10
  heights, with the `health.incident` record landing in the node's
  dump_traces ring and zero false-critical on the clean phase;

plus the bench-trajectory regression gate: unit tests of the backend
partition / direction / gate math, and CLI smoke over the checked-in
BENCH_r01–r11 artifacts (exit 0; the honest-CPU rows sit at ~3% of the
r02/r03 TPU captures and must NOT flag) and over a synthetic
20%-regressed row on a matching backend (exit non-zero).
"""

import asyncio
import json
import os
import subprocess
import sys
import time

import pytest

from tendermint_tpu import obs
from tendermint_tpu.libs.metrics import (
    Counter,
    HealthMetrics,
    Histogram,
    Registry,
    SchedulerMetrics,
)
from tendermint_tpu.obs.health import (
    CRITICAL,
    OK,
    WARN,
    BurnRateSLO,
    EventLoopLagDetector,
    HealthMonitor,
    LatencyDriftDetector,
    PeerFlapDetector,
    QuorumLagDetector,
    RoundChurnDetector,
    SchedulerSaturationDetector,
    StalledRoundDetector,
)

pytestmark = pytest.mark.health

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _slo(objective=0.9, short=30.0, long=300.0, **kw):
    return BurnRateSLO(
        "t", objective=objective, short_window=short, long_window=long, **kw
    )


# --- burn-rate window math --------------------------------------------------


def test_burn_rate_multiwindow_math():
    slo = _slo(objective=0.9, min_events=4)
    # 10 good events: zero burn, ok
    for i in range(10):
        slo.observe(float(i), bad=0)
    assert slo.burn(10.0) == 0.0
    assert slo.verdict(10.0) == OK
    # 3 bad of the next 10: 3/20 = 0.15 bad fraction over a 10% budget
    # -> burn 1.5 in both windows -> warn, below the 6x critical gate
    for i in range(10, 20):
        slo.observe(float(i), bad=1 if i % 3 == 0 else 0)
    t = 20.0
    assert slo.burn(t) == pytest.approx((3 / 20) / 0.1)
    assert slo.verdict(t) == WARN


def test_burn_rate_critical_requires_both_windows():
    slo = _slo(objective=0.9, min_events=4)
    # an all-bad burst: burn 10x in both windows -> critical
    for i in range(8):
        slo.observe(float(i), bad=1)
    assert slo.verdict(8.0) == CRITICAL
    # 40 s later the short window holds no events (burn 0) while the
    # long window still carries the burst: a recovered incident
    # un-pages as the short window drains
    t = 45.0
    assert slo.burn(t, slo.long_window) > 1.0
    assert slo.burn(t, slo.short_window) == 0.0
    assert slo.verdict(t) == OK


def test_burn_rate_min_events_and_prune():
    slo = _slo(objective=0.9, min_events=4)
    for i in range(3):
        slo.observe(float(i), bad=1)
    # under min_events the verdict stays ok no matter the burn
    assert slo.verdict(3.0) == OK
    slo.observe(3.0, bad=1)
    assert slo.verdict(3.5) == CRITICAL
    # everything ages past the long window -> pruned -> ok again
    assert slo.verdict(400.0) == OK
    assert len(slo._events) == 0


def test_burn_rate_validates_params():
    with pytest.raises(ValueError):
        BurnRateSLO("x", objective=1.0)
    with pytest.raises(ValueError):
        BurnRateSLO("x", short_window=60.0, long_window=30.0)


# --- detectors on synthetic streams ----------------------------------------


def test_round_churn_detector():
    det = RoundChurnDetector(_slo(objective=0.9))
    for i in range(10):
        det.observe_height(float(i), round_=0)
    assert det.verdict(10.0) == OK
    # 2 churned heights in the next 10 -> burn 2x -> warn
    for i in range(10, 20):
        det.observe_height(float(i), round_=1 if i < 12 else 0)
    assert det.verdict(20.0) == WARN
    assert det.last_value == 0.0  # last height committed at round 0


def test_round_churn_sustained_goes_critical():
    det = RoundChurnDetector(_slo(objective=0.9))
    for i in range(10):
        det.observe_height(float(i), round_=2)
    assert det.verdict(10.0) == CRITICAL


def test_stalled_round_direct_critical_without_events():
    det = StalledRoundDetector(_slo(objective=0.9), ceiling_s=20.0)
    det.arm(0.0)
    # a burn window over zero events never fires — the stall must page
    # through the direct condition
    assert det.verdict(10.0) == OK
    assert det.verdict(25.0) == CRITICAL
    assert det.last_value == 25.0
    # a commit resets the stall clock
    det.observe_height(26.0)
    assert det.verdict(30.0) == OK
    # near-stall intervals feed the SLO: repeated slow heights warn
    t = 26.0
    for _ in range(8):
        t += 21.0
        det.observe_height(t)
    assert det.slo.verdict(t) == CRITICAL  # every interval over ceiling


def test_stalled_round_near_stall_warns_before_paging():
    # intervals past near_stall_fraction x ceiling but UNDER the
    # ceiling book bad SLO events: the committee slipping toward the
    # stall warns while the direct page stays quiet
    det = StalledRoundDetector(_slo(objective=0.9), ceiling_s=20.0)
    det.arm(0.0)
    t = 0.0
    for _ in range(8):
        t += 12.0  # > 10 (near-stall bar), < 20 (page bar)
        det.observe_height(t)
        assert det._direct(t) == OK  # never pages
    assert det.slo.verdict(t) >= WARN
    assert det.verdict(t) >= WARN
    # healthy cadence books good events and recovers as windows drain
    det2 = StalledRoundDetector(_slo(objective=0.9), ceiling_s=20.0)
    det2.arm(0.0)
    t = 0.0
    for _ in range(8):
        t += 5.0
        det2.observe_height(t)
    assert det2.verdict(t) == OK


def test_quorum_lag_warmup_learns_before_judging():
    det = QuorumLagDetector(
        _slo(objective=0.9, min_events=8), floor_s=0.025, min_baseline=16
    )
    # the first min_baseline samples are learning-only: even lags far
    # over the floor record NO SLO events (you can't call an anomaly
    # before a baseline exists — the clean gossip plane's genuine
    # trickle spread would false-flag against the static floor)
    for i in range(16):
        det.observe_lag(float(i), 0.06)
    assert len(det.slo._events) == 0
    assert det.verdict(16.0) == OK
    # post-warmup the learned tail IS the bar: 2 x p95(60 ms) = 120 ms
    assert det.threshold() == pytest.approx(0.12)
    det.observe_lag(17.0, 0.06)  # inside the learned spread: good
    assert det.slo._events[-1][1] == 0


def test_quorum_lag_baseline_not_poisoned_by_straggler():
    det = QuorumLagDetector(
        _slo(objective=0.9, min_events=8),
        floor_s=0.025,
        margin=4.0,
        min_baseline=16,
    )
    # clean phase: sub-ms arrivals learn the baseline (16 warmup + 4
    # judged-good)
    for i in range(20):
        det.observe_lag(float(i), 0.001)
    assert det.verdict(20.0) == OK
    thr_before = det.threshold()
    assert thr_before == pytest.approx(0.025)  # floor dominates
    # straggler phase: one of three arrivals comes 50 ms late
    t = 20.0
    for i in range(12):
        t += 1.0
        det.observe_lag(t, 0.05)
        det.observe_lag(t, 0.001)
        det.observe_lag(t, 0.001)
    assert det.verdict(t) == WARN
    # the bad samples were never admitted to the baseline: a persistent
    # straggler keeps flagging instead of teaching the detector that
    # 50 ms is normal
    assert det.threshold() == pytest.approx(thr_before)
    assert det.snapshot(t)["baseline_p95"] < 0.01
    assert det.last_threshold == pytest.approx(0.025)
    assert det.snapshot(t)["last_bad"] == pytest.approx(0.05)


def test_scheduler_saturation_detector():
    det = SchedulerSaturationDetector(
        _slo(objective=0.8), depth_floor=256
    )
    # shallow queue: never saturated regardless of fill
    for i in range(10):
        det.observe_sample(float(i), 10.0, 1.0, 0)
    assert det.verdict(10.0) == OK
    # deep queue with no dispatch progress -> saturated -> warn
    t = 10.0
    for i in range(10):
        t += 1.0
        det.observe_sample(t, 500.0, 1.0, 0)
    assert det.verdict(t) >= WARN
    # deep queue but dispatches advancing with partial fill = the
    # device is draining a burst, not saturated
    det2 = SchedulerSaturationDetector(
        _slo(objective=0.8), depth_floor=256
    )
    for i in range(10):
        det2.observe_sample(float(i), 500.0, 0.5, 3)
    assert det2.verdict(10.0) == OK


def test_latency_drift_detector_learns_then_flags():
    det = LatencyDriftDetector(
        _slo(objective=0.8), drift_factor=4.0, abs_floor_s=0.001
    )
    # below min_baseline the threshold is inf: nothing can flag
    for i in range(8):
        det.observe_mean(float(i), 0.002)
    assert det.verdict(8.0) == OK
    thr = det.threshold()
    assert thr == pytest.approx(0.008)  # 4 x the 2 ms median
    # a degrading disk: interval means drift to 20 ms
    t = 8.0
    for i in range(10):
        t += 1.0
        det.observe_mean(t, 0.02)
    assert det.verdict(t) >= WARN
    # drifted samples never join the baseline
    assert det.threshold() == pytest.approx(thr)


def test_peer_flap_detector():
    det = PeerFlapDetector(_slo(objective=0.8))
    for i, n in enumerate((4, 4, 4, 4, 4, 4)):
        det.observe_count(float(i), n)
    assert det.verdict(6.0) == OK
    # connect/drop cycling: every drop is a bad event
    t = 6.0
    for n in (3, 4, 2, 4, 1, 4, 2, 3):
        t += 1.0
        det.observe_count(t, n)
    assert det.verdict(t) >= WARN
    # a STABLE small peer set is fine — flap is churn, not size
    det2 = PeerFlapDetector(_slo(objective=0.8))
    for i in range(10):
        det2.observe_count(float(i), 1)
    assert det2.verdict(10.0) == OK


def test_event_loop_lag_detector():
    det = EventLoopLagDetector(_slo(objective=0.9, min_events=8),
                               lag_warn_s=0.05)
    for i in range(20):
        det.observe_lag(float(i), 0.002)
    assert det.verdict(20.0) == OK
    # the loop-bound regime: sustained lag dominates BOTH windows (the
    # long window needs >= 60% bad against the 10% budget to cross the
    # 6x critical gate)
    t = 20.0
    for i in range(60):
        t += 1.0
        det.observe_lag(t, 0.2)
    assert det.verdict(t) == CRITICAL


# --- monitor: pull seams over real metric objects ---------------------------


def _monitor(**kw):
    kw.setdefault("tracer", obs.Tracer(enabled=True))
    return HealthMonitor(**kw)


def test_monitor_scheduler_seam():
    reg = Registry()
    sm = SchedulerMetrics(reg)
    mon = _monitor()
    mon.bind_scheduler(sm)
    sm.queue_depth.inc(500, klass="consensus")
    sm.batch_fill_ratio.set(1.0)
    t = 0.0
    for i in range(10):
        t += 1.0
        mon.sample(t)  # depth 500, fill 1.0, no dispatch progress
    assert mon.detectors["scheduler_saturation"].verdict(t) >= WARN
    assert mon.subsystem_verdicts(t)["scheduler"] >= WARN


def test_monitor_wal_drift_seam():
    reg = Registry()
    hist = reg.histogram(
        "wal_fsync_seconds", "", buckets=(0.001, 0.01, 0.1, float("inf"))
    )
    mon = _monitor()
    mon.bind_wal(hist)
    t = 0.0
    mon.sample(t)  # establishes the cumulative baseline
    # healthy disk: 2 ms fsyncs, interval means learn the baseline
    for i in range(10):
        for _ in range(4):
            hist.observe(0.002)
        t += 1.0
        mon.sample(t)
    assert mon.detectors["wal_fsync_drift"].verdict(t) == OK
    # the disk degrades: 30 ms interval means, > 4 x the 2 ms median
    for i in range(10):
        for _ in range(4):
            hist.observe(0.03)
        t += 1.0
        mon.sample(t)
    assert mon.detectors["wal_fsync_drift"].verdict(t) >= WARN
    assert mon.subsystem_verdicts(t)["wal"] >= WARN


def test_monitor_sequencer_slo_seam():
    reg = Registry()
    hist = reg.histogram(
        "sequencer_apply_latency_seconds",
        "",
        buckets=(0.01, 0.05, 0.1, 0.5, 1.0, float("inf")),
    )
    mon = _monitor()
    mon.bind_sequencer(hist)
    t = 0.0
    mon.sample(t)
    # 20 applies inside the 100 ms target: good
    for _ in range(20):
        hist.observe(0.02)
    t += 1.0
    mon.sample(t)
    assert mon.detectors["sequencer_apply_slo"].verdict(t) == OK
    # the polling-floor regression: applies land at 500 ms
    for i in range(3):
        for _ in range(20):
            hist.observe(0.5)
        t += 1.0
        mon.sample(t)
    assert mon.detectors["sequencer_apply_slo"].verdict(t) == CRITICAL
    assert mon.subsystem_verdicts(t)["sequencer"] == CRITICAL


def test_monitor_lightserve_hit_rate_seam():
    reg = Registry()

    class LS:
        cache_hits = reg.counter("ls_hits", "")
        cache_misses = reg.counter("ls_misses", "")

    mon = _monitor()
    mon.bind_lightserve(LS())
    t = 0.0
    LS.cache_hits.inc(100)
    mon.sample(t)
    t += 1.0
    mon.sample(t)  # no new traffic: no event recorded
    assert mon.detectors["lightserve_hit_rate"].verdict(t) == OK
    # hit rate collapses to 50% against the 0.9 floor
    for i in range(3):
        LS.cache_hits.inc(50)
        LS.cache_misses.inc(50)
        t += 1.0
        mon.sample(t)
    assert mon.detectors["lightserve_hit_rate"].verdict(t) >= WARN


def test_monitor_peer_seam_and_status_rollup():
    class Sw:
        peers = {}

    mon = _monitor()
    mon.bind_switch(Sw())
    t = 0.0
    sizes = [4, 4, 3, 4, 2, 4, 1, 4, 2, 4, 1, 4]
    for n in sizes:
        Sw.peers = {i: None for i in range(n)}
        t += 1.0
        mon.sample(t)
    assert mon.detectors["peer_flap"].verdict(t) >= WARN
    verdicts = mon.subsystem_verdicts(t)
    assert verdicts["p2p"] >= WARN
    assert mon.status(t) >= WARN
    # untouched subsystems stay ok in the roll-up
    assert verdicts["consensus"] == OK
    assert verdicts["runtime"] == OK


def test_monitor_seam_isolation_and_detector_thresholds():
    """A pull seam that raises every tick (a bound metrics object
    changing shape) must not starve the seams bound after it or the
    end-of-tick evaluation — the watchdog-fails-dark class — and the
    floor/flap detectors must carry the bar they judged against, not
    the 0.0 Detector default."""

    class BrokenDepth:
        def total(self):
            raise AttributeError("metrics object changed shape")

    class BrokenSched:
        queue_depth = BrokenDepth()

    class Sw:
        peers = {}

    mon = _monitor()
    mon.bind_scheduler(BrokenSched())  # first seam in the pull order
    mon.bind_switch(Sw())  # last seam in the pull order
    t = 0.0
    sizes = [4, 4, 3, 4, 2, 4, 1, 4, 2, 4, 1, 4]
    for n in sizes:
        Sw.peers = {i: None for i in range(n)}
        t += 1.0
        mon.sample(t)  # scheduler raises every tick; p2p still feeds
    assert mon.detectors["peer_flap"].verdict(t) >= WARN
    # evaluation still ran: the flap transition emitted its incident
    assert any(i["detector"] == "peer_flap" for i in mon.incidents)
    # the flap threshold is the count the drop came FROM, surviving
    # the recovery ticks in between
    assert mon.detectors["peer_flap"].last_threshold == 4.0
    # the hit-rate floor detector's threshold IS its SLO objective
    ls = mon.detectors["lightserve_hit_rate"]
    assert ls.last_threshold == ls.slo.objective > 0.0


def test_status_query_pages_unstarted_stall():
    """A node stalled from genesis: start() never called, no feeds at
    all. The first status query arms the stall clock; a query past the
    ceiling must page CRITICAL — and status()/verdict() must agree
    (the soak divergence artifact carries both)."""
    mon = _monitor(stall_ceiling_s=10.0)
    assert mon.status(0.0) == OK  # arms at first evaluation
    assert mon.status(5.0) == OK
    assert mon.status(11.0) == CRITICAL
    assert mon.subsystem_verdicts(11.0)["consensus"] == CRITICAL
    doc = mon.verdict(11.0)
    assert doc["status"] == "critical"
    assert any(i["detector"] == "stalled_round" for i in mon.incidents)
    # a commit recovers it on the next query
    mon.observe_height_committed(7, 0, t=12.0)
    assert mon.status(12.5) == OK


# --- monitor: incidents, gauges, verdict document ---------------------------


def _drive_quorum_warn(mon, t0=0.0):
    """Deterministic OK->WARN flip of the quorum-lag detector: 40
    clean sub-ms arrivals (32 warmup + 8 judged good), then a quarter
    of the stream straggling at 50 ms against the 25 ms floor — ~4x
    the 5% budget: warn, under the 6x critical gate."""
    t = t0
    for i in range(40):
        t += 0.1
        mon.observe_vote_arrival(1, 0.001, t=t)
    for i in range(12):
        t += 0.1
        mon.observe_vote_arrival(1, 0.05, t=t)
        for _ in range(3):
            mon.observe_vote_arrival(1, 0.001, t=t)
    mon.observe_height_committed(5, 0, t=t)  # commits trigger _evaluate
    return t


def test_incident_emission_into_tracer_and_gauges():
    tracer = obs.Tracer(enabled=True)
    reg = Registry()
    hm = HealthMetrics(reg)
    mon = HealthMonitor(tracer=tracer, metrics=hm)
    t = _drive_quorum_warn(mon)

    assert mon.detectors["quorum_lag"].verdict(t) == WARN
    # the transition emitted exactly one structured incident
    incidents = [r for r in tracer.records() if r.name == "health.incident"]
    assert len(incidents) == 1
    f = incidents[0].fields
    assert f["slo"] == "quorum_lag"
    assert f["subsystem"] == "consensus"
    assert (f["from"], f["to"]) == ("ok", "warn")
    # the escalation carries the OFFENDING lag (the 50 ms straggler),
    # not whatever good sample arrived after it
    assert f["value"] == pytest.approx(0.05)
    assert f["value"] > f["threshold"] > 0
    assert mon.incidents[-1]["detector"] == "quorum_lag"

    # gauges carry the roll-up: tm_health_status{subsystem="consensus"}
    # >= warn, burn rate exported per slo, incident counted
    assert hm.status.value(subsystem="consensus") >= WARN
    assert hm.burn_rate.value(slo="quorum_lag") >= 1.0
    assert hm.incidents.value(subsystem="consensus") == 1
    body = reg.render()
    assert 'tm_health_status{subsystem="consensus"}' in body
    assert 'tm_slo_burn_rate{slo="quorum_lag"}' in body

    # recovery: the stream goes quiet, both windows drain, the detector
    # un-pages and the ok transition is ALSO an incident record
    mon.observe_height_committed(6, 0, t=t + 400.0)
    incidents = [r for r in tracer.records() if r.name == "health.incident"]
    assert incidents[-1].fields["to"] == "ok"
    assert hm.status.value(subsystem="consensus") == OK


def test_verdict_document_shape():
    mon = HealthMonitor(tracer=obs.Tracer(enabled=True))
    t = _drive_quorum_warn(mon)
    doc = mon.verdict(t)
    assert doc["status"] == "warn" and doc["code"] == WARN
    assert set(doc["subsystems"]) == {
        "consensus", "scheduler", "wal", "sequencer", "lightserve",
        "p2p", "runtime",
    }
    cons = doc["subsystems"]["consensus"]
    assert cons["status"] == "warn"
    assert cons["detectors"]["quorum_lag"]["status"] == "warn"
    assert cons["detectors"]["quorum_lag"]["burn_long"] >= 1.0
    assert cons["detectors"]["round_churn"]["status"] == "ok"
    assert doc["incidents"][-1]["to"] == "warn"
    # stall pages through verdict() even with no event feed at all
    mon2 = HealthMonitor(tracer=obs.Tracer(enabled=True),
                         stall_ceiling_s=20.0)
    mon2.stalled_round.arm(0.0)
    doc2 = mon2.verdict(25.0)
    assert doc2["subsystems"]["consensus"]["status"] == "critical"


def test_monitor_determinism_on_identical_streams():
    def drive(mon):
        t = 0.0
        for i in range(30):
            t += 0.5
            mon.observe_vote_arrival(1, 0.05 if i % 3 == 0 else 0.001, t=t)
            if i % 5 == 4:
                mon.observe_height_committed(i // 5 + 1, i % 2, t=t)
        return mon.verdict(t)

    a = drive(HealthMonitor(tracer=obs.Tracer(enabled=True)))
    b = drive(HealthMonitor(tracer=obs.Tracer(enabled=True)))
    assert a == b


def test_monitor_from_config_and_validation():
    from tendermint_tpu.config.config import HealthConfig

    hc = HealthConfig()
    hc.validate_basic()
    mon = HealthMonitor.from_config(hc, stall_ceiling_s=12.5)
    assert mon.stalled_round.ceiling_s == 12.5
    assert mon.quorum_lag.floor_s == hc.quorum_lag_floor
    assert mon.interval == hc.interval
    for field, bad in (
        ("interval", 0.0),
        ("short_window", 400.0),  # > long_window
        ("cache_hit_floor", 1.5),
        ("stall_factor", -1.0),
        ("scheduler_depth_floor", 0),
    ):
        broken = HealthConfig(**{field: bad})
        with pytest.raises(ValueError):
            broken.validate_basic()


def test_heartbeat_probe_measures_loop_lag():
    """The event-loop lag probe: a blocking callback makes the
    heartbeat's sleep overshoot, and the overshoot lands in the
    detector's SLO stream (the PR 9 loop-bound regime, measured)."""

    async def run():
        mon = HealthMonitor(
            tracer=obs.Tracer(enabled=True),
            interval=10.0,  # keep the sample loop out of the way
            heartbeat_interval=0.02,
        )
        await mon.start()
        try:
            await asyncio.sleep(0.1)  # a few clean beats
            clean = len(mon.event_loop_lag.slo._events)
            assert clean >= 2
            time.sleep(0.25)  # block the loop: the next beat is late
            await asyncio.sleep(0.05)
            # the overshoot was recorded as a bad event (clean beats
            # may have followed and moved last_value on)
            assert mon.event_loop_lag.last_bad >= 0.1
            assert any(
                b for _, b, _ in mon.event_loop_lag.slo._events
            )
        finally:
            await mon.stop()
        assert not mon._tasks

    asyncio.run(run())


# --- chaos e2e: the straggler flips exactly the quorum-lag detector ---------


@pytest.mark.chaos
def test_chaos_straggler_flips_quorum_lag_to_warn():
    """PR 5 weighted-quorum topology (powers 40/20/20/20: the heavy
    validator's vote is required by every 2/3) with a live health plane
    on every node. Phase 1 runs clean — zero false-critical, quorum-lag
    ok everywhere while the baselines learn the committee's genuine
    clean arrival spread (gossip-tick vote trickle: ~100 ms p95 on this
    in-proc harness — measured, which is WHY the detector learns its
    bar instead of trusting a static floor, and why the injection must
    sit above that spread and shape every one of the straggler's
    outbound links: a single shaped link is masked by mesh relay).
    Phase 2 makes the heavy validator a straggler (400 ms added to all
    its outbound links): within K=10 heights the victim's quorum-lag
    detector must flip to warn — the lag is phase-absorbed on vote
    types where the whole committee waited on heavy (everyone's
    precommit shifts together when its prevote was the late one), so
    the straggler shows on ~10% of the victim's pre-quorum arrivals:
    ~2x the 5% budget, over the warn gate and far under the critical
    one (measured: 10 bad of ~97 judged, stable across seeds). The
    transition must land a
    `health.incident` record in the victim's dump_traces ring, and
    tm_health_status{subsystem="consensus"} must read >= warn — while
    nothing ever reaches critical and every non-consensus subsystem
    stays ok (the straggler is a consensus-plane fault)."""
    from tendermint_tpu.chaos.link import LinkPolicy
    from tendermint_tpu.chaos.network import ChaosNetwork

    from .chaos_harness import (
        build_chaos_handles,
        node_dump,
        start_mesh,
        stop_mesh,
    )

    monitors: dict[str, HealthMonitor] = {}
    registries: dict[str, Registry] = {}

    def health_factory(name, tracer):
        reg = Registry()
        monitors[name] = HealthMonitor(
            tracer=tracer, metrics=HealthMetrics(reg)
        )
        registries[name] = reg
        return monitors[name]

    handles = build_chaos_handles(
        tracer_factory=lambda name: obs.Tracer(enabled=True),
        ping_interval=0.5,
        powers=(40, 20, 20, 20),
        health_factory=health_factory,
    )
    vals = handles[0].cs.state.validators.validators
    heavy_idx = max(range(len(vals)), key=lambda i: vals[i].voting_power)
    victim_idx = (heavy_idx + 1) % len(handles)
    heavy, victim = f"n{heavy_idx}", f"n{victim_idx}"
    K = 10

    async def run():
        net = ChaosNetwork(seed=7)
        for h in handles:
            net.install(h)
        await start_mesh(handles)
        try:
            # phase 1: clean heights — baselines learn, nothing flags.
            # 8 heights put every node's arrival count comfortably past
            # the 32-sample learning-only warmup (~6-8 pre-quorum
            # arrivals per height per node): if warmup straddled the
            # fault injection, the straggler's lags would be ADMITTED
            # to the baseline and teach the detector the fault
            await asyncio.gather(
                *(h.cs.wait_for_height(8, timeout=120) for h in handles)
            )
            for name, m in monitors.items():
                assert (
                    len(m.quorum_lag._baseline)
                    >= m.quorum_lag.min_baseline
                ), f"{name}: quorum-lag baseline warmup incomplete"
            clean = {
                name: m.verdict() for name, m in monitors.items()
            }
            # phase 2: the heavy validator straggles on EVERY outbound
            # link — its votes/proposals leave late no matter which
            # relay path carries them to the committee. 400 ms clears
            # the worst learned bar a host-stuttered clean phase can
            # set (2 x p95 ~ 0.3 s observed under CI contention); the
            # round churn it may force on heavy-proposed heights is
            # itself a consensus-plane warn the assertions tolerate
            for h in handles:
                if h.name != heavy:
                    net.set_link_policy(
                        heavy,
                        h.name,
                        LinkPolicy(latency_s=0.4),
                        reverse=LinkPolicy(),
                    )
            h_clear = max(h.cs.state.last_block_height for h in handles)
            await asyncio.gather(
                *(
                    h.cs.wait_for_height(h_clear + K, timeout=180)
                    for h in handles
                )
            )
            dump = node_dump(handles[victim_idx])
            hashes = {
                h.block_store.load_block(h_clear + K).hash()
                for h in handles
            }
            post = {name: m.verdict() for name, m in monitors.items()}
            return clean, post, dump, hashes
        finally:
            await stop_mesh(handles)

    clean, post, dump, hashes = asyncio.run(run())

    # liveness + agreement through the degraded regime
    assert len(hashes) == 1, "nodes disagree under the straggler link"

    # clean phase: zero false-critical anywhere (the acceptance bar —
    # NOT "zero warn": a genuinely stuttering host produces genuine
    # 250 ms+ arrival spreads with no fault injected, and a warn there
    # is a true positive, observed roughly once per ten CI runs)
    for name, doc in clean.items():
        assert doc["status"] != "critical", (name, doc)
        for sub, entry in doc["subsystems"].items():
            for det, state in entry["detectors"].items():
                assert state["status"] != "critical", (name, det, state)

    # chaos phase: the victim's quorum-lag detector is at warn — and
    # warn only (~10% of pre-quorum arrivals flag, ~2x the 5% budget,
    # under the 6x critical gate)
    vdoc = post[victim]
    vdet = vdoc["subsystems"]["consensus"]["detectors"]["quorum_lag"]
    assert vdet["status"] == "warn", vdoc
    assert vdet["last_bad"] > 0.3, vdet  # the observed straggler lag
    # the learned bar sits between the floor and the injection: the
    # baseline covered the clean trickle without swallowing the fault
    assert 0.025 <= vdet["threshold"] < 0.4, vdet

    # nothing reached critical on any node, and every warned detector
    # is consensus-plane (quorum_lag, or round_churn when the straggler
    # forced a retry round) — no cross-subsystem false positives
    for name, doc in post.items():
        assert doc["status"] != "critical", (name, doc)
        for sub, entry in doc["subsystems"].items():
            for det, state in entry["detectors"].items():
                if state["status"] != "ok":
                    assert det in ("quorum_lag", "round_churn"), (
                        name, det, state,
                    )
                    assert sub == "consensus"

    # the incident landed in the victim's dump_traces ring: flight
    # dumps now carry WHY (detector, threshold, observed value)
    incidents = [
        r for r in dump["records"] if r["name"] == "health.incident"
    ]
    assert any(
        r["fields"]["slo"] == "quorum_lag" and r["fields"]["to"] == "warn"
        for r in incidents
    ), incidents

    # and the gauge surface agrees: tm_health_status >= warn for the
    # consensus subsystem, ok for every other
    status = registries[victim].render()
    g = monitors[victim].metrics.status
    assert g.value(subsystem="consensus") >= WARN
    assert 'tm_health_status{subsystem="consensus"}' in status
    for sub in ("scheduler", "wal", "sequencer", "lightserve", "p2p",
                "runtime"):
        assert g.value(subsystem=sub) == OK, sub


# --- bench-trend: backend-partitioned regression gate -----------------------


def _bt():
    sys.path.insert(0, REPO)
    from tools import bench_trend

    return bench_trend


def test_trend_family_and_direction_classification():
    bt = _bt()
    assert bt.family_of("ed25519_vote_verify_throughput") == "crypto"
    assert bt.family_of("consensus_pacing_wall_per_height") == (
        "consensus_pacing"
    )
    assert bt.family_of("sequencer_stream_blocks_per_s") == (
        "sequencer_stream"
    )
    assert bt.family_of("lightserve_clients_per_s") == "lightserve"
    assert bt.direction_of("ed25519_vote_verify_throughput") == "higher"
    assert bt.direction_of("consensus_pacing_wall_per_height") == "lower"
    assert bt.direction_of("sequencer_apply_latency_p95") == "lower"
    assert bt.direction_of("bls_aggregate_verify_1k") == "lower"  # override


def test_trend_backend_partition_and_gate_math(tmp_path):
    bt = _bt()

    def art(name, metric, value, backend, rnd, extra=None):
        p = tmp_path / f"BENCH_{name}_r{rnd:02d}.json"
        doc = {
            "metric": metric,
            "value": value,
            "unit": "sigs/s",
            "meta": {"backend": backend, "device_count": 1},
        }
        if extra:
            doc["extra_metrics"] = extra
        p.write_text(json.dumps(doc))
        return str(p)

    files = [
        art("tpu_a", "ed25519_vote_verify_throughput", 77000.0, "tpu", 2),
        art("tpu_b", "ed25519_vote_verify_throughput", 75000.0, "tpu", 3),
        art("cpu_a", "ed25519_vote_verify_throughput", 2300.0, "cpu", 4),
        art("cpu_b", "ed25519_vote_verify_throughput", 2250.0, "cpu", 6),
    ]
    rows, skipped, _ = bt.ingest(files)
    assert not skipped and len(rows) == 4
    groups = bt.build_groups(rows)
    # rows partition by backend: the 2.3k CPU rows NEVER compare
    # against the 77k TPU captures
    assert len(groups) == 2
    by_backend = {g["backend"]: g for g in groups}
    assert by_backend["cpu"]["best"] == 2300.0
    assert by_backend["cpu"]["regression"] == pytest.approx(
        (2300.0 - 2250.0) / 2300.0, abs=1e-4
    )
    assert by_backend["tpu"]["regression"] == pytest.approx(
        (77000.0 - 75000.0) / 77000.0, abs=1e-4
    )
    failures, warnings = bt.check_gate(groups, threshold=0.15)
    assert not failures and not warnings

    # a 20% same-backend regression of a tier-1 headline fails the gate
    files.append(
        art("cpu_c", "ed25519_vote_verify_throughput", 1840.0, "cpu", 7)
    )
    rows, _, _ = bt.ingest(files)
    failures, _ = bt.check_gate(bt.build_groups(rows), threshold=0.15)
    assert len(failures) == 1
    assert failures[0]["backend"] == "cpu"
    assert failures[0]["regression"] > 0.15

    # extra-metric regressions warn instead of failing (strict flips)
    files = files[:4] + [
        art(
            "cpu_x",
            "ed25519_vote_verify_throughput",
            2290.0,
            "cpu",
            8,
            extra=[
                {"metric": "ed25519_commit10k_latency", "value": 100.0,
                 "unit": "ms"},
            ],
        ),
        art(
            "cpu_y",
            "ed25519_vote_verify_throughput",
            2280.0,
            "cpu",
            9,
            extra=[
                {"metric": "ed25519_commit10k_latency", "value": 150.0,
                 "unit": "ms"},
            ],
        ),
    ]
    rows, _, _ = bt.ingest(files)
    failures, warnings = bt.check_gate(bt.build_groups(rows), 0.15)
    assert not failures and len(warnings) == 1
    failures, warnings = bt.check_gate(
        bt.build_groups(rows), 0.15, strict=True
    )
    assert len(failures) == 1 and not warnings


def test_trend_ingest_normalizes_historical_shapes(tmp_path):
    bt = _bt()
    # r01–r04 wrapped shape with a capture tail naming the platform
    wrapped = tmp_path / "BENCH_r90.json"
    wrapped.write_text(json.dumps({
        "rc": 0,
        "tail": "WARNING ... Platform 'axon' is experimental",
        "parsed": {"metric": "ed25519_vote_verify_throughput",
                   "value": 70000.0, "unit": "sigs/s/chip"},
    }))
    # structured backend-mismatch failure: a skip, never a value
    failed = tmp_path / "BENCH_r91.json"
    failed.write_text(json.dumps({
        "rc": 1, "error": "no TPU endpoint", "kind": "backend_mismatch",
        "backend": "cpu",
    }))
    # unreadable artifact: a skip, not a crash
    broken = tmp_path / "BENCH_r92.json"
    broken.write_text("{not json")
    rows, skipped, _ = bt.ingest([str(wrapped), str(failed), str(broken)])
    assert len(rows) == 1
    assert rows[0]["backend"] == "tpu"  # inferred from the tail
    assert rows[0]["round"] == 90
    assert {s["file"] for s in skipped} == {
        "BENCH_r91.json", "BENCH_r92.json",
    }


def test_trend_cli_check_over_checked_in_artifacts(tmp_path):
    """The acceptance gate: --check over BENCH_r01–r11 + MULTICHIP_r*
    exits 0 — the honest-CPU rows (ed25519 vote verify ~2.1k sigs/s)
    must NOT flag against the r02/r03 TPU captures (77k) because the
    backend partition keeps them in separate groups — and exits
    non-zero when fed a synthetic 20%-regressed row on a MATCHING
    backend."""
    bt = _bt()
    out = subprocess.run(
        [sys.executable, "tools/bench_trend.py", "--check", "--json"],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    doc = json.loads(out.stdout)
    assert doc["check"]["ok"] is True
    # both backend groups of the same metric coexist, 33x apart
    groups = {
        (g["metric"], g["backend"]): g for g in doc["groups"]
    }
    tpu = groups[("ed25519_vote_verify_throughput", "tpu")]
    cpu = groups[("ed25519_vote_verify_throughput", "cpu")]
    assert tpu["best"] > 10 * cpu["best"]
    assert tpu["regression"] <= 0.15 and cpu["regression"] <= 0.15

    # synthetic regression: consensus_pacing wall/height 25% WORSE on
    # the same (cpu, 1-device) group as the checked-in r08 capture
    reg_row = tmp_path / "BENCH_r99.json"
    reg_row.write_text(json.dumps({
        "metric": "consensus_pacing_wall_per_height",
        "value": 567.4,  # r08 recorded 453.9 ms/height
        "unit": "ms/height",
        "meta": {"backend": "cpu", "device_count": 1},
    }))
    out = subprocess.run(
        [sys.executable, "tools/bench_trend.py", "--check", str(reg_row)],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert out.returncode == 1
    assert "consensus_pacing_wall_per_height" in out.stderr
    assert "FAIL tier-1 regression" in out.stderr

    # the SAME row on a different backend cannot flag: partition holds
    mismatched = tmp_path / "BENCH_r98.json"
    mismatched.write_text(json.dumps({
        "metric": "consensus_pacing_wall_per_height",
        "value": 567.4,
        "unit": "ms/height",
        "meta": {"backend": "tpu", "device_count": 1},
    }))
    out = subprocess.run(
        [sys.executable, "tools/bench_trend.py", "--check",
         str(mismatched)],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert out.returncode == 0, out.stderr


def test_trend_write_renders_tables(tmp_path):
    """--write produces TREND.md + TREND.json; the table marks the
    tier-1 families and the skip section lists failure artifacts."""
    out = subprocess.run(
        [sys.executable, "tools/bench_trend.py", "--write", "--dir",
         str(tmp_path), "--no-scan",
         os.path.join(REPO, "BENCH_r08.json"),
         os.path.join(REPO, "BENCH_r07.json")],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    md = (tmp_path / "TREND.md").read_text()
    assert "consensus_pacing (tier-1)" in md
    assert "BENCH_r07.json" in md  # the structured failure is a skip
    doc = json.loads((tmp_path / "TREND.json").read_text())
    assert doc["schema"] == "tm-tpu/bench-trend/v1"
    assert doc["skipped"] and doc["groups"]
