"""Randomized-manifest e2e (reference test/e2e/generator + runner).

The generator's determinism and topology constraints are unit-checked;
then one seeded manifest is booted across real processes — randomized
topology, full nodes, and a perturbation schedule — asserting liveness
and cross-node agreement. CI runs a fixed seed (deterministic shapes);
`TM_TPU_E2E_SEED` overrides it to explore other topologies."""

import os
import signal
import sys


sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from testnet_generator import (  # noqa: E402
    TOPOLOGIES,
    generate_manifest,
    materialize,
    peer_indices,
)

from .test_e2e_multiprocess import (  # noqa: E402
    _free_ports,
    _height,
    _rpc,
    _spawn,
    _wait_heights,
)


def test_manifest_determinism_and_constraints():
    for seed in range(24):
        m1 = generate_manifest(seed)
        m2 = generate_manifest(seed)
        assert m1 == m2, f"seed {seed} not deterministic"
        vals = [n for n in m1["nodes"] if n["mode"] == "validator"]
        assert len(vals) >= 4
        assert m1["topology"] in TOPOLOGIES
        # at most one perturbed validator (BFT margin of f=1 at 4-5 vals)
        assert sum(n["perturb"] != "none" for n in vals) <= 1
    # seeds actually vary the shapes
    shapes = {
        (
            generate_manifest(s)["topology"],
            len(generate_manifest(s)["nodes"]),
        )
        for s in range(24)
    }
    assert len(shapes) > 3, f"generator barely varies: {shapes}"


def test_topologies_are_connected():
    """Every topology yields a connected peer graph (so gossip reaches
    everyone) for all generated sizes."""
    for topo in TOPOLOGIES:
        for n in (4, 5, 6, 7):
            adj = {i: set(peer_indices(topo, i, n)) for i in range(n)}
            # persistent peers dial both ways: undirected closure
            for i, ps in list(adj.items()):
                for j in ps:
                    adj[j].add(i)
            seen = {0}
            stack = [0]
            while stack:
                for j in adj[stack.pop()]:
                    if j not in seen:
                        seen.add(j)
                        stack.append(j)
            assert seen == set(range(n)), f"{topo} n={n} disconnected"


def test_randomized_manifest_net_runs(tmp_path):
    seed = int(os.environ.get("TM_TPU_E2E_SEED", "7"))
    manifest = generate_manifest(seed)
    layout = materialize(manifest, str(tmp_path / "net"), _free_ports)

    procs = {}
    try:
        for name, spec in layout.items():
            procs[name] = _spawn(spec["home"])
        rpc_ports = [s["rpc_port"] for s in layout.values()]
        val_ports = [
            s["rpc_port"]
            for s in layout.values()
            if s["mode"] == "validator"
        ]
        _wait_heights(
            val_ports, manifest["initial_height_target"], deadline_s=180
        )

        # perturbation schedule
        for name, spec in layout.items():
            if spec["perturb"] == "kill_restart":
                os.kill(procs[name].pid, signal.SIGKILL)
                procs[name].wait(timeout=30)
                survivors = [
                    s["rpc_port"]
                    for n2, s in layout.items()
                    if n2 != name and s["mode"] == "validator"
                ]
                target = max(_height(p) for p in survivors) + 2
                _wait_heights(survivors, target, deadline_s=150)
                procs[name] = _spawn(spec["home"])
                catchup = max(_height(p) for p in survivors) + 1
                _wait_heights([spec["rpc_port"]], catchup, deadline_s=180)

        # everyone (validators AND full nodes) reaches a common height
        # and agrees on the block hash there
        target = max(_height(p) for p in val_ports)
        _wait_heights(rpc_ports, target, deadline_s=180)
        h = min(_height(p) for p in rpc_ports)
        hashes = {
            _rpc(p, "block", height=h)["block_id"]["hash"]
            for p in rpc_ports
        }
        assert len(hashes) == 1, (
            f"seed {seed} ({manifest['topology']}): fork at height {h}"
        )
    finally:
        for p in procs.values():
            if p.poll() is None:
                os.killpg(p.pid, signal.SIGKILL)
