"""Blocksync: pool mechanics, windowed batched verify, p2p fast-sync e2e.

VERDICT round-1 weak item 4: blocksync shipped untested. These drive the
pool + reactor verify-then-apply loop over real p2p, including the
multi-block batched commit path (SURVEY.md §3.4).
"""

import asyncio

import pytest

from tendermint_tpu.blocksync.pool import BlockPool
from tendermint_tpu.blocksync.reactor import BlocksyncReactor
from tendermint_tpu.l2node.mock import MockL2Node
from tendermint_tpu.p2p.key import NodeKey
from tendermint_tpu.p2p.node_info import NodeInfo
from tendermint_tpu.p2p.switch import Switch
from tendermint_tpu.p2p.transport import MultiplexTransport, NetAddress

from .helpers import make_genesis, make_validators
from .test_consensus import make_node

NETWORK = "bsync-chain"


# --- pool ------------------------------------------------------------------


def _fake_block(h):
    class B:
        def __init__(self, height):
            self.header = type("H", (), {"height": height})()
            self.last_commit = object()  # non-None for window pairing

    return B(h)


def test_pool_requests_and_windows():
    sent = []
    pool = BlockPool(
        start_height=1,
        send_request=lambda pid, h: sent.append((pid, h)) or True,
        on_peer_error=lambda pid, reason: None,
    )
    pool.set_peer_range("p1", 0, 10)
    pool.make_requests()
    assert sent, "no requests made"
    for h in range(1, 6):
        pool.add_block("p1", _fake_block(h))
    # window requires each block's successor to be present; entries are
    # (block, successor_commit, successor_qc) — qc None on legacy blocks
    w = pool.peek_window(10)
    assert [b.header.height for b, _c, _qc in w] == [1, 2, 3, 4]
    assert all(qc is None for _b, _c, qc in w)
    pool.pop_request()
    w = pool.peek_window(2)
    assert [b.header.height for b, _c, _qc in w] == [2, 3]


def test_pool_redo_punishes_peer():
    errors = []
    pool = BlockPool(
        start_height=1,
        send_request=lambda pid, h: True,
        on_peer_error=lambda pid, reason: errors.append(pid),
    )
    pool.set_peer_range("bad", 0, 5)
    pool.make_requests()
    pool.add_block("bad", _fake_block(1))
    pool.add_block("bad", _fake_block(2))
    pool.redo_request(1, "bad block")
    assert "bad" in errors


def test_slow_peer_banned_sync_completes_via_fast_peer():
    """Flowrate peer quality (reference pool.go:522 minRecvRate): a
    slow-but-alive peer trickling data below MIN_RECV_RATE is banned —
    not merely timed out — and its heights reassign to a healthy peer so
    sync completes instead of throttling indefinitely."""
    import time as _time

    from tendermint_tpu.blocksync import pool as pool_mod

    from tendermint_tpu.libs.flowrate import Monitor

    sent = []
    errors = []
    pool = BlockPool(
        start_height=1,
        send_request=lambda pid, h: sent.append((pid, h)) or True,
        on_peer_error=lambda pid, reason: errors.append((pid, reason)),
    )
    # only the slow peer advertises the range at first: every request
    # lands on it
    pool.set_peer_range("slow", 0, 6)
    pool.make_requests()
    assert all(pid == "slow" for pid, _ in sent)

    # production peers use the reference's 1s/40s flowrate window so
    # multi-second block transfers don't decay a healthy rate; the test
    # swaps in a compressed window to exercise the ban logic quickly
    slow = pool._peers["slow"]
    assert slow.recv_monitor._sample == pool_mod.RATE_SAMPLE == 1.0
    assert slow.recv_monitor._window == pool_mod.RATE_WINDOW == 40.0
    slow.recv_monitor = Monitor(sample_period=0.02, window=0.1)

    # the slow peer trickles: one tiny block, then sustained dribble well
    # below MIN_RECV_RATE while requests stay pending
    pool.add_block("slow", _fake_block(1), size=64)
    for _ in range(6):
        _time.sleep(0.03)
        slow.recv_monitor.update(8)
    rate = slow.recv_monitor.status().cur_rate
    assert 0.0 < rate < pool_mod.MIN_RECV_RATE

    pool.set_peer_range("fast", 0, 6)
    sent.clear()
    pool.make_requests()  # rate check runs here
    assert ("slow", "peer is not sending us data fast enough") in errors
    assert "slow" not in pool._peers, "slow peer still in the pool"

    # the orphaned heights were reassigned to the fast peer...
    assert sent and all(pid == "fast" for pid, _ in sent)
    # ...and a healthy delivery rate completes the sync window
    for h in range(2, 7):
        pool.add_block("fast", _fake_block(h), size=4096)
    w = pool.peek_window(10)
    assert [b.header.height for b, _c, _qc in w] == [1, 2, 3, 4, 5]
    assert "fast" in {p.peer_id for p in pool._peers.values()}


def test_fast_peer_not_banned_by_rate_check():
    """A peer sustaining a healthy rate passes check_peer_rates, and a
    peer that never sent anything is left to the timeout path (cur_rate
    is exactly 0.0 until the first block). 'fast' registers alone first
    so every height deterministically lands on it."""
    errors = []
    pool = BlockPool(
        start_height=1,
        send_request=lambda pid, h: True,
        on_peer_error=lambda pid, reason: errors.append(pid),
    )
    pool.set_peer_range("fast", 0, 6)
    pool.make_requests()
    fast = pool._peers["fast"]
    assert fast.pending, "no heights assigned to the fast peer"
    pool.set_peer_range("silent", 0, 6)
    for h in range(1, 4):
        assert pool.add_block("fast", _fake_block(h), size=1 << 20)
    assert fast.recv_monitor.status().bytes_total >= 3 << 20
    pool.check_peer_rates()
    assert errors == []
    assert "fast" in pool._peers and "silent" in pool._peers


# --- batched multi-commit verification -------------------------------------


def test_verify_commits_light_batches_many_heights():
    """One device batch covers many commits; invalid ones flagged
    individually (ValidatorSet.verify_commits_light)."""
    from tendermint_tpu.crypto.batch_verifier import BatchVerifier
    from tendermint_tpu.types.block_id import BlockID
    from tendermint_tpu.types.part_set import PartSetHeader

    from .helpers import CHAIN_ID, sign_commit

    vs, pvs = make_validators(4)
    entries = []
    for h in range(1, 9):
        bid = BlockID(bytes([h]) * 32, PartSetHeader(1, bytes([h]) * 32))
        commit = sign_commit(vs, pvs, h, 0, bid)
        entries.append((bid, h, commit))
    # corrupt height 5's commit
    bad = entries[4][2]
    bad.signatures[0].signature = b"\x00" * 64
    bad.signatures[1].signature = b"\x00" * 64
    bad.signatures[2].signature = b"\x00" * 64

    verifier = BatchVerifier(min_device_batch=1 << 30)  # host path
    verdicts = vs.verify_commits_light(CHAIN_ID, entries, verifier=verifier)
    assert verdicts == [True] * 4 + [False] + [True] * 3


# --- e2e fast sync over p2p -------------------------------------------------


def _build_source_chain(n_heights):
    """Run a single-validator chain to height n (in-proc) and return the
    pieces a syncing node needs."""
    vs, pvs = make_validators(1)
    genesis = make_genesis(vs)

    async def run():
        cs, app, l2, bs, ss = make_node(vs, pvs[0], genesis)
        await cs.start()
        await cs.wait_for_height(n_heights, timeout=60)
        await cs.stop()
        return cs, bs

    cs, bs = asyncio.run(run())
    return vs, pvs, genesis, bs


def test_fast_sync_over_p2p_catches_up():
    """A fresh node blocksyncs a 8-height chain from a serving peer and
    hands off to consensus (reference poolRoutine verify-then-apply +
    SwitchToConsensus :461-485)."""
    vs, pvs, genesis, src_bs = _build_source_chain(8)

    def build_switch(reactors):
        nk = NodeKey.generate()
        transport = None
        sw = None

        def node_info():
            return NodeInfo(
                node_id=nk.id,
                listen_addr=f"127.0.0.1:{transport.listen_port}",
                network=NETWORK,
                channels=sw.channels() if sw else b"",
            )

        transport = MultiplexTransport(nk, node_info)
        sw = Switch(transport)
        for name, r in reactors.items():
            sw.add_reactor(name, r)
        return nk, transport, sw

    async def run():
        # server: a reactor with the full block store (inactive pool)
        from tendermint_tpu.state.state import State

        caught_up = []
        srv_cs, srv_app, srv_l2, srv_bs2, srv_ss = make_node(
            vs, pvs[0], genesis
        )
        server_r = BlocksyncReactor(
            srv_cs.state, srv_cs.executor, src_bs, srv_l2, active=False
        )
        snk, st_, ssw = build_switch({"blocksync": server_r})

        # client: fresh node syncing from genesis
        cli_cs, cli_app, cli_l2, cli_bs, cli_ss = make_node(
            vs, pvs[0], genesis
        )

        async def on_caught_up(state):
            caught_up.append(state.last_block_height)

        client_r = BlocksyncReactor(
            cli_cs.state,
            cli_cs.executor,
            cli_bs,
            cli_l2,
            on_caught_up=on_caught_up,
            active=False,
        )
        cnk, ct, csw = build_switch({"blocksync": client_r})
        for t, sw in ((st_, ssw), (ct, csw)):
            await t.listen()
            await sw.start()
        await csw.dial_peer(NetAddress(snk.id, "127.0.0.1", st_.listen_port))
        await asyncio.sleep(0.2)
        client_r.start_sync()
        for _ in range(200):
            await asyncio.sleep(0.05)
            if caught_up:
                break
        h = cli_bs.height
        applied = client_r.blocks_applied
        for sw in (ssw, csw):
            await sw.stop()
        return h, applied, caught_up

    h, applied, caught_up = asyncio.run(run())
    # blocks 1..7 apply (8 needs a successor commit; it arrives via
    # consensus after handoff)
    assert h >= 7, f"client only reached height {h}"
    assert applied >= 7
    assert caught_up, "on_caught_up never fired"


def test_fast_sync_rejects_tampered_block():
    """A peer serving a tampered block is punished and the height is
    re-requested (reference redo + StopPeerForError)."""
    import copy

    vs, pvs, genesis, src_bs = _build_source_chain(5)

    async def run():
        cli_cs, cli_app, cli_l2, cli_bs, cli_ss = make_node(
            vs, pvs[0], genesis
        )
        reactor = BlocksyncReactor(
            cli_cs.state, cli_cs.executor, cli_bs, cli_l2, active=False
        )
        errors = []
        reactor.pool._on_peer_error = lambda pid, reason: errors.append(pid)
        reactor.pool.set_peer_range("evil", 0, 5)
        reactor.pool.make_requests()
        # evil serves block 1 with tampered txs, plus honest block 2
        b1 = copy.deepcopy(src_bs.load_block(1))
        b1.data.txs = [b"forged=1"]
        b1.data._hash = None
        b1.header._hash = None  # content changed -> hash changes
        reactor.pool.add_block("evil", b1)
        reactor.pool.add_block("evil", src_bs.load_block(2))
        await reactor._process_ready_blocks()
        return errors, cli_bs.height

    errors, h = asyncio.run(run())
    assert "evil" in errors, "tampered block did not punish the peer"
    assert h == 0, "tampered block was applied"
