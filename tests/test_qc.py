"""Quorum-certificate plane: codec goldens, aggregate verify vs the
N-sig path (bit-for-bit verdict agreement incl. forged-aggregate and
sub-quorum rejections), the qc_verify engine in BOTH scheduler runtimes
(in-proc fn lane + verify-service wire), per-engine ledger accounting /
fn fill honesty, QC-compressed light proofs, and mixed-mode blocksync
interop (a legacy consumer syncs a QC chain; a QC consumer verifies one
pairing per block)."""

import asyncio
import os
import threading

import pytest

from tendermint_tpu.crypto import bls_signatures as bls
from tendermint_tpu.libs.bits import BitArray
from tendermint_tpu.types.block import Block, BlockIDFlag, Commit, CommitSig
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.part_set import PartSetHeader
from tendermint_tpu.types.quorum_cert import (
    QuorumCertificate,
    assemble_qc,
    qc_sign_bytes,
)
from tendermint_tpu.types.validator import Validator
from tendermint_tpu.types.validator_set import ValidatorSet
from tendermint_tpu.types.vote import Vote

from .helpers import CHAIN_ID, make_genesis, make_qc_validators, sign_commit

pytestmark = pytest.mark.qc


def _bid(tag: int) -> BlockID:
    return BlockID(bytes([tag]) * 32, PartSetHeader(1, bytes([tag + 1]) * 32))


@pytest.fixture(scope="module")
def committee():
    """(valset, privvals, bls_privs) — 4 QC-capable validators."""
    return make_qc_validators(4, seed=b"qcplane")


@pytest.fixture(scope="module")
def qc_commit(committee):
    vs, pvs, privs = committee
    bid = _bid(7)
    commit = sign_commit(vs, pvs, 5, 0, bid, bls_privs=privs)
    return bid, commit


# --- wire codec -------------------------------------------------------------


def test_qc_codec_roundtrip_golden():
    """Bit-for-bit wire stability: the QC encoding is a cross-process
    contract (blocks, store records, RPC proofs), pinned by a golden."""
    qc = QuorumCertificate(
        height=9,
        round=1,
        block_id=_bid(3),
        signers=BitArray.from_indices(5, [0, 2, 4]),
        agg_signature=bytes(range(96)),
    )
    enc = qc.encode()
    back = QuorumCertificate.decode(enc)
    assert back == qc
    assert back.encode() == enc
    # height=9, round+1=2, block_id message, size=5, bitset 0b10101,
    # then the 96 aggregate bytes — the cross-process wire golden
    golden = (
        "080910021a480a20" + "03" * 32 + "122408011220" + "04" * 32
        + "20052a011532" + "60" + bytes(range(96)).hex()
    )
    assert enc.hex() == golden
    assert back.signers.ones() == [0, 2, 4]
    assert back.num_signers() == 3


def test_vote_commit_block_wire_carry_qc(qc_commit, committee):
    vs, pvs, privs = committee
    bid, commit = qc_commit
    # votes round-trip the qc signature (field 10)
    v = Vote.decode(
        Vote(
            type=2, height=5, round=0, block_id=bid,
            timestamp_ns=1, validator_address=b"a" * 20,
            validator_index=0, signature=b"s" * 64,
            qc_signature=b"q" * 96,
        ).encode()
    )
    assert v.qc_signature == b"q" * 96
    # commit sigs retained the contributions (the assemble-on-demand
    # source) and survive the codec
    assert all(
        cs.qc_signature for cs in commit.signatures if cs.for_block()
    )
    c2 = Commit.decode(commit.encode())
    assert [cs.qc_signature for cs in c2.signatures] == [
        cs.qc_signature for cs in commit.signatures
    ]
    # blocks carry last_qc next to the commit; legacy blocks (no field
    # 5) decode to last_qc=None
    qc = assemble_qc(CHAIN_ID, commit, vs)
    from tendermint_tpu.types.block import Data, Header

    blk = Block(
        header=Header(chain_id=CHAIN_ID, height=6, validators_hash=b"v" * 32),
        data=Data(),
        last_commit=commit,
        last_qc=qc,
    )
    b2 = Block.decode(blk.encode())
    assert b2.last_qc is not None
    assert b2.last_qc.encode() == qc.encode()
    legacy = Block(
        header=Header(chain_id=CHAIN_ID, height=6, validators_hash=b"v" * 32),
        data=Data(),
        last_commit=commit,
    )
    assert Block.decode(legacy.encode()).last_qc is None


def test_bls_key_in_validator_hash_and_legacy_hash_stable(committee):
    vs, _, _ = committee
    # a set WITHOUT bls keys hashes exactly as before the field existed
    bare = ValidatorSet(
        [Validator(v.pub_key, v.voting_power) for v in vs.validators]
    )
    stripped = ValidatorSet(
        [Validator(v.pub_key, v.voting_power, bls_pub_key=b"")
         for v in vs.validators]
    )
    assert bare.hash() == stripped.hash()
    # adding the key changes membership identity (it is committed)
    assert vs.hash() != bare.hash()
    # and survives the set codec
    vs2 = ValidatorSet.decode(vs.encode())
    assert vs2.hash() == vs.hash()
    assert all(v.bls_pub_key for v in vs2.validators)
    assert vs2.qc_capable()


# --- assemble + verify ------------------------------------------------------


def test_qc_agrees_with_commit_light(qc_commit, committee):
    """Same commit, both planes: the N-sig verdict and the one-pairing
    QC verdict must agree."""
    vs, _, _ = committee
    bid, commit = qc_commit
    vs.verify_commit_light(CHAIN_ID, bid, 5, commit)  # N-sig path
    qc = assemble_qc(CHAIN_ID, commit, vs)
    assert qc is not None and qc.num_signers() == 4
    vs.verify_commit_qc(CHAIN_ID, bid, 5, qc)  # one pairing
    # bulk: one engine submission for many entries
    assert vs.verify_commits_qc(
        CHAIN_ID, [(bid, 5, qc), (bid, 5, qc)]
    ) == [True, True]
    # trusting (the skipping-verification half): same set overlap
    vs.verify_commit_qc_trusting(CHAIN_ID, qc, vs)


def test_forged_aggregate_rejected(qc_commit, committee):
    vs, _, _ = committee
    bid, commit = qc_commit
    qc = assemble_qc(CHAIN_ID, commit, vs)
    forged = QuorumCertificate.decode(qc.encode())
    forged.agg_signature = bls.g1_to_bytes(
        bls.sign(12345, qc.sign_bytes(CHAIN_ID))
    )
    with pytest.raises(ValueError, match="aggregate"):
        vs.verify_commit_qc(CHAIN_ID, bid, 5, forged)
    assert vs.verify_commits_qc(CHAIN_ID, [(bid, 5, forged)]) == [False]
    # garbage bytes are a False verdict, not an engine error
    forged.agg_signature = b"\xff" * 96
    assert vs.verify_commits_qc(CHAIN_ID, [(bid, 5, forged)]) == [False]


def test_sub_quorum_bitset_rejected(qc_commit, committee):
    vs, _, _ = committee
    bid, commit = qc_commit
    qc = assemble_qc(CHAIN_ID, commit, vs)
    sub = QuorumCertificate.decode(qc.encode())
    sub.signers = BitArray.from_indices(4, [0, 1])  # 20/40 <= 2/3
    with pytest.raises(ValueError, match="voting power"):
        vs.verify_commit_qc(CHAIN_ID, bid, 5, sub)
    # a wrong-size bitset (different committee) is a shape error
    sub.signers = BitArray.from_indices(5, [0, 1, 2, 3, 4])
    with pytest.raises(ValueError, match="bitset size"):
        vs.verify_commit_qc(CHAIN_ID, bid, 5, sub)


def test_assemble_isolates_corrupt_contribution(committee):
    """A byzantine validator's garbage qc_signature (its ed25519 vote
    was fine) is bisected out; the QC ships with the surviving 3/4."""
    vs, pvs, privs = committee
    bid = _bid(9)
    commit = sign_commit(vs, pvs, 7, 0, bid, bls_privs=privs)
    commit.signatures[1] = CommitSig(
        block_id_flag=commit.signatures[1].block_id_flag,
        validator_address=commit.signatures[1].validator_address,
        timestamp_ns=commit.signatures[1].timestamp_ns,
        signature=commit.signatures[1].signature,
        qc_signature=bls.g1_to_bytes(bls.sign(999, b"wrong message")),
    )
    qc = assemble_qc(CHAIN_ID, commit, vs)
    assert qc is not None
    assert qc.num_signers() == 3 and not qc.signers.get(1)
    vs.verify_commit_qc(CHAIN_ID, bid, 7, qc)
    # two corrupt contributions push the survivors to 2/4 <= 2/3: no QC
    commit.signatures[2] = CommitSig(
        block_id_flag=commit.signatures[2].block_id_flag,
        validator_address=commit.signatures[2].validator_address,
        timestamp_ns=commit.signatures[2].timestamp_ns,
        signature=commit.signatures[2].signature,
        qc_signature=b"\x00" * 95,  # unparseable
    )
    assert assemble_qc(CHAIN_ID, commit, vs) is None


def test_non_capable_set_refuses_qc(qc_commit, committee):
    vs, pvs, privs = committee
    bid, commit = qc_commit
    qc = assemble_qc(CHAIN_ID, commit, vs)
    bare = ValidatorSet(
        [Validator(v.pub_key, v.voting_power) for v in vs.validators]
    )
    assert not bare.qc_capable()
    with pytest.raises(ValueError, match="bls key"):
        bare.verify_commit_qc(CHAIN_ID, bid, 5, qc)
    # a legacy commit (no qc signatures) cannot assemble
    plain = sign_commit(vs, pvs, 5, 0, bid)
    assert assemble_qc(CHAIN_ID, plain, vs) is None


# --- the qc_verify engine in both runtimes ----------------------------------


def _qc_item(vs, qc, chain_id=CHAIN_ID):
    keys = b"".join(
        vs.validators[i].bls_pub_key for i in qc.signers.ones()
    )
    return (qc.sign_bytes(chain_id), qc.agg_signature, keys)


def test_qc_engine_direct_and_batch(qc_commit, committee):
    from tendermint_tpu.crypto.bls_signatures import verify_qc_items

    vs, _, _ = committee
    bid, commit = qc_commit
    qc = assemble_qc(CHAIN_ID, commit, vs)
    good = _qc_item(vs, qc)
    bad = (good[0], bls.g1_to_bytes(bls.sign(4, good[0])), good[2])
    unparseable = (good[0], b"\x11" * 96, good[2])
    # the whole round is one RLC multi-pairing; bisect isolates bads
    assert verify_qc_items([good, bad, good, unparseable]) == [
        True, False, True, False,
    ]


def test_qc_engine_in_scheduler_fn_lane(qc_commit, committee):
    """submit_wire_fn_sync('qc_verify') coalesces through the in-proc
    scheduler and books a per-engine ledger row."""
    from tendermint_tpu.obs.ledger import DispatchLedger
    from tendermint_tpu.parallel.scheduler import VerifyScheduler

    vs, _, _ = committee
    bid, commit = qc_commit
    qc = assemble_qc(CHAIN_ID, commit, vs)
    ledger = DispatchLedger()
    sched = VerifyScheduler(ledger=ledger)

    async def run():
        await sched.start()
        loop = asyncio.get_running_loop()

        def worker():
            return sched.submit_wire_fn_sync(
                "qc_verify", [_qc_item(vs, qc)], "blocksync"
            )

        res = await loop.run_in_executor(None, worker)
        await sched.stop()
        return res

    assert asyncio.run(run()) == [True]
    summ = ledger.summary()
    eng = summ["per_engine"]["qc_verify"]
    assert eng["rounds"] == 1 and eng["rows_requested"] == 1
    assert eng["requests_per_dispatch"] == 1.0
    # unknown engines take the fallback, not an exception
    sched2 = VerifyScheduler()
    assert sched2.submit_wire_fn_sync(
        "nope", [()], "light", fallback=lambda: ["fb"]
    ) == ["fb"]


def test_qc_engine_on_verify_service_wire(tmp_path, qc_commit, committee):
    """The cross-process half: qc_verify in the service's wire-engine
    table, verdicts over the UDS."""
    from tendermint_tpu.parallel.verify_service import (
        RemoteVerifyScheduler,
        ServiceThread,
    )

    vs, _, _ = committee
    bid, commit = qc_commit
    qc = assemble_qc(CHAIN_ID, commit, vs)
    good = _qc_item(vs, qc)
    bad = (good[0], bls.g1_to_bytes(bls.sign(4, good[0])), good[2])
    path = os.path.join(str(tmp_path), "qc.sock")
    svc = ServiceThread(path)
    svc.start()
    try:

        async def run():
            remote = RemoteVerifyScheduler(path, retry_base=0.02)
            await remote.start()
            deadline = asyncio.get_running_loop().time() + 15
            while not remote.connected:
                await asyncio.sleep(0.01)
                assert asyncio.get_running_loop().time() < deadline
            res = await remote.submit_wire_fn(
                "qc_verify", [good, bad], "blocksync"
            )
            await remote.stop()
            return res

        assert asyncio.run(run()) == [True, False]
        # the service's ledger billed the round under its engine name
        summ = svc.server.scheduler.ledger.summary()
        assert "qc_verify" in summ["per_engine"]
    finally:
        svc.stop()


# --- ledger satellites ------------------------------------------------------


def test_ledger_per_engine_rpd_and_fn_fill():
    """Satellites 1+2: requests_per_dispatch broken out per engine
    (the global number is diluted by one-submission fn rounds), and fn
    rounds book their TRUE internal bucket — on the fn axis, never
    blended into the sig fill distribution."""
    from tendermint_tpu.obs.ledger import DispatchLedger

    led = DispatchLedger()
    mark = led.mark()
    # sig plane: 2 rounds, 3 submissions -> rpd 1.5
    led.record_round(
        1.0, class_rows={"consensus": 90}, requested=90, dispatched=128,
        submissions=2, device_s=0.2,
    )
    led.record_round(
        2.0, class_rows={"blocksync": 50}, requested=50, dispatched=64,
        submissions=1, device_s=0.1,
    )
    # fn plane: one 150-item bls_agg round padding internally to 256
    led.record_round(
        3.0, class_rows={"consensus": 150}, requested=150, dispatched=256,
        submissions=1, device_s=0.05, engine="bls_agg",
    )
    # qc plane: 8 aggregate checks, no padding
    led.record_round(
        4.0, class_rows={"blocksync": 8}, requested=8, dispatched=8,
        submissions=1, device_s=0.01, engine="qc_verify",
    )
    for summ in (led.summary(), led.summary(since=mark)):
        eng = summ["per_engine"]
        assert eng["sig"]["requests_per_dispatch"] == 1.5
        assert eng["sig"]["rows_dispatched"] == 192
        assert eng["bls_agg"]["fill_ratio"] == round(150 / 256, 4)
        assert eng["qc_verify"]["fill_ratio"] == 1.0
        # the sig-plane distribution excludes every fn engine
        assert summ["fill_ratio_p50"] >= 0.70
        # honest fn bucket never leaks into the sig padding totals
        assert summ["padding_rows"] == (128 - 90) + (64 - 50)
    # totals (the health seam) stay sig-only too
    t = led.totals()
    assert t["rows_requested"] == 140 and t["rows_dispatched"] == 192


def test_scheduler_books_fn_internal_bucket(qc_commit, committee):
    """A real fn round through the scheduler lands in the ledger with
    the engine's internal_rows bucket and sets the per-engine gauge."""
    from tendermint_tpu.libs.metrics import Registry, SchedulerMetrics
    from tendermint_tpu.obs.ledger import DispatchLedger
    from tendermint_tpu.parallel.engines import _engine_bls_agg
    from tendermint_tpu.parallel.scheduler import VerifyScheduler

    h = b"h" * 32
    items = []
    for i in range(3):
        priv = 7001 + i
        items.append(
            (
                bls.public_key_to_bytes(bls.pubkey_from_priv(priv)),
                h,
                bls.signer_for(priv)(h),
            )
        )
    reg = Registry()
    ledger = DispatchLedger()
    sched = VerifyScheduler(
        ledger=ledger, metrics=SchedulerMetrics(reg)
    )

    async def run():
        await sched.start()
        res = await sched.submit_fn(
            items, _engine_bls_agg, "consensus", engine="bls_agg"
        )
        await sched.stop()
        return res

    assert asyncio.run(run()) == [True, True, True]
    (entry,) = ledger.entries()
    assert entry["engine"] == "bls_agg"
    assert entry["requested"] == 3
    assert entry["dispatched"] == 8  # 3-signer group pads to the 8 rung
    assert 'tm_scheduler_fn_fill_ratio{engine="bls_agg"} 0.375' in (
        reg.render()
    )


# --- light plane ------------------------------------------------------------


def _light_chain(n_vals, heights, seed=b"lq"):
    """QC-capable chain of LightBlocks (commit + qc both attached)."""
    from tendermint_tpu.light.types import LightBlock
    from tendermint_tpu.types.block import Data, Header

    vs, pvs, privs = make_qc_validators(n_vals, seed=seed)
    out = []
    prev_bid = BlockID()
    t0 = 1_700_000_000_000_000_000
    for h in heights:
        header = Header(
            chain_id=CHAIN_ID,
            height=h,
            time_ns=t0 + h * 1_000_000_000,
            last_block_id=prev_bid,
            validators_hash=vs.hash(),
            next_validators_hash=vs.hash(),
            data_hash=Data().hash(),
        )
        bid = BlockID(header.hash(), PartSetHeader(1, bytes([h % 251]) * 32))
        commit = sign_commit(
            vs, pvs, h, 0, bid, time_ns=t0 + h * 1_000_000_000,
            bls_privs=privs,
        )
        qc = assemble_qc(CHAIN_ID, commit, vs)
        assert qc is not None
        out.append(LightBlock(header, commit, vs, qc=qc))
        prev_bid = bid
    return vs, out, t0


def test_light_verify_qc_compressed_proofs():
    """verify_adjacent + verify (skipping) accept qc-only light blocks
    (commit=None) — and reject a tampered aggregate."""
    from tendermint_tpu.light import verifier as lv
    from tendermint_tpu.light.types import LightBlock

    vs, chain, t0 = _light_chain(4, [1, 2, 5])
    period = 3600 * 10**9
    now = t0 + 6 * 10**9

    def compressed(lb):
        return LightBlock(lb.header, None, lb.validators, qc=lb.qc)

    lv.verify_adjacent(chain[0], compressed(chain[1]), period, now)
    lv.verify(chain[0], compressed(chain[2]), period, now)  # skipping
    # verdict parity with the commit path
    lv.verify_adjacent(chain[0], chain[1], period, now)
    # tampered aggregate on the compressed proof: rejected
    bad = compressed(chain[1])
    bad.qc = QuorumCertificate.decode(bad.qc.encode())
    bad.qc.agg_signature = bls.g1_to_bytes(bls.sign(3, b"zzz"))
    with pytest.raises(lv.VerificationError):
        lv.verify_adjacent(chain[0], bad, period, now)
    # a compressed proof with NO qc is unverifiable, not silently ok
    naked = compressed(chain[1])
    naked.qc = None
    with pytest.raises((lv.VerificationError, ValueError)):
        lv.verify_adjacent(chain[0], naked, period, now)


@pytest.mark.slow
def test_qc_proof_size_compression_at_100():
    """Acceptance: light_block proof bytes reduced >= 5x at 100
    validators (the full-commit payload vs the qc-compressed one)."""
    from tendermint_tpu.light.types import LightBlock

    vs, chain, _ = _light_chain(100, [1], seed=b"lq100")
    lb = chain[0]
    full = LightBlock(lb.header, lb.commit, lb.validators).proof_bytes()
    qc_only = LightBlock(
        lb.header, None, lb.validators, qc=lb.qc
    ).proof_bytes()
    assert full / qc_only >= 5.0, (full, qc_only)
    # and the compressed proof still verifies
    vs.verify_commit_qc(CHAIN_ID, lb.qc.block_id, 1, lb.qc)


def test_lightserve_serves_qc_proofs():
    """The cache attaches the canonical QC (block h+1's last_qc) and
    get_compressed drops the CommitSigs; the serve verifier keys qc and
    commit proofs separately."""
    from tendermint_tpu.lightserve.cache import LightBlockCache

    vs, chain, _ = _light_chain(4, [1, 2, 3])

    class Meta:
        def __init__(self, lb):
            self.header = lb.header

    class FakeBlockStore:
        height = 3

        def load_block_meta(self, h):
            return Meta(chain[h - 1]) if 1 <= h <= 3 else None

        def load_block_commit(self, h):
            return chain[h - 1].commit if 1 <= h <= 2 else None

        def load_seen_commit(self, h):
            return chain[h - 1].commit if h == 3 else None

        def load_block_qc(self, h):
            return chain[h - 1].qc if 1 <= h <= 2 else None

    class FakeStateStore:
        def load_validators(self, h):
            return vs

    cache = LightBlockCache(FakeBlockStore(), FakeStateStore(), CHAIN_ID)
    lb = cache.get(1)
    assert lb.qc is not None and lb.commit is not None
    comp = cache.get_compressed(1)
    assert comp.commit is None and comp.qc is not None
    assert comp.proof_bytes() < lb.proof_bytes() / 2
    comp.validate_basic(CHAIN_ID)
    # the tip has no canonical QC: compressed falls back to the full proof
    tip = cache.get_compressed(3)
    assert tip.commit is not None and tip.qc is None


# --- live consensus + mixed-mode blocksync ----------------------------------


def _qc_node(vs, pv, genesis, privs, qc=True):
    from tendermint_tpu.consensus.state_machine import ConsensusConfig

    from .test_consensus import make_node

    cfg = ConsensusConfig.test_config()
    cfg.quorum_certificates = qc
    addr = pv.get_pub_key().address()
    cs, app, l2, bs, ss = make_node(
        vs, pv, genesis,
        config=cfg,
        bls_signer=bls.signer_for(privs[addr]),
    )
    cs.executor.qc_enabled = qc
    return cs, app, l2, bs, ss


def test_live_chain_produces_and_stores_qcs():
    """A QC-enabled single-validator chain: every committed block past
    the first carries last_qc, the store serves the canonical QC, and
    replayed validation rides the QC path."""
    vs, pvs, privs = make_qc_validators(1, seed=b"live1")
    genesis = make_genesis(vs)

    async def run():
        cs, app, l2, bs, ss = _qc_node(vs, pvs[0], genesis, privs)
        await cs.start()
        await cs.wait_for_height(4, timeout=30)
        await cs.stop()
        return bs

    bs = asyncio.run(run())
    for h in range(2, 4):
        blk = bs.load_block(h + 1)
        assert blk.last_qc is not None, f"height {h+1} shipped without qc"
        assert blk.last_qc.height == h
        stored = bs.load_block_qc(h)
        assert stored is not None and stored.encode() == blk.last_qc.encode()
        # the stored QC verifies against the committed set
        vs.verify_commit_qc(
            CHAIN_ID, blk.last_qc.block_id, h, blk.last_qc
        )


def _sync_consumer(vs, pvs, privs, genesis, src_bs, n_heights, qc_enabled):
    """Drive a BlocksyncReactor's pool directly (no p2p) over the source
    chain; returns the reactor after it applied everything."""
    from tendermint_tpu.blocksync.reactor import BlocksyncReactor

    async def run():
        cs, app, l2, bs, ss = _qc_node(
            vs, pvs[0], genesis, privs, qc=qc_enabled
        )
        reactor = BlocksyncReactor(
            cs.state, cs.executor, bs, l2, qc_enabled=qc_enabled
        )
        reactor.pool.set_peer_range("src", 0, n_heights)
        reactor.pool.make_requests()
        for h in range(1, n_heights + 1):
            assert reactor.pool.add_block(
                "src", src_bs.load_block(h), size=1024
            )
        while reactor.pool.height <= n_heights - 1:
            before = reactor.pool.height
            await reactor._process_ready_blocks()
            if reactor.pool.height == before:
                break  # no progress: fail below
        return reactor

    return asyncio.run(run())


def test_mixed_mode_interop_legacy_and_qc_consumers():
    """Acceptance: a legacy peer (quorum_certificates off) syncs a chain
    produced by QC-capable proposers via the N-sig path, and a
    QC-capable peer syncs the same chain verifying aggregates."""
    vs, pvs, privs = make_qc_validators(1, seed=b"mixed")
    genesis = make_genesis(vs)
    heights = 6

    async def produce():
        cs, app, l2, bs, ss = _qc_node(vs, pvs[0], genesis, privs)
        await cs.start()
        await cs.wait_for_height(heights, timeout=40)
        await cs.stop()
        return bs

    src_bs = asyncio.run(produce())
    assert src_bs.load_block(heights).last_qc is not None

    legacy = _sync_consumer(
        vs, pvs, privs, genesis, src_bs, heights - 1, qc_enabled=False
    )
    assert legacy.blocks_applied == heights - 2
    assert legacy.qc_verified_blocks == 0

    qc_peer = _sync_consumer(
        vs, pvs, privs, genesis, src_bs, heights - 1, qc_enabled=True
    )
    assert qc_peer.blocks_applied == heights - 2
    # every applied block was proven by its aggregate, not N sigs
    assert qc_peer.qc_verified_blocks >= heights - 2


def test_tampered_qc_in_transit_changes_block_id():
    """A relay that rewrites a block's QC rewrote the block BYTES: the
    re-encoded part set no longer matches the BlockID the committee
    signed, so the tamper is caught by the existing commit shape check
    (redo + peer punishment), never by trusting the bad aggregate."""
    vs, pvs, privs = make_qc_validators(1, seed=b"corrupt")
    genesis = make_genesis(vs)

    async def produce():
        cs, app, l2, bs, ss = _qc_node(vs, pvs[0], genesis, privs)
        await cs.start()
        await cs.wait_for_height(4, timeout=40)
        await cs.stop()
        return bs

    src_bs = asyncio.run(produce())
    from tendermint_tpu.types.block_id import BlockID

    blk = src_bs.load_block(3)  # carries the qc for height 2
    victim = src_bs.load_block(2)
    fid = BlockID(victim.hash(), victim.make_part_set().header)
    blk.last_qc = QuorumCertificate.decode(blk.last_qc.encode())
    blk.last_qc.agg_signature = bls.g1_to_bytes(bls.sign(13, b"garbage"))
    blk._part_set = None  # the tampered relay re-frames the bytes
    tampered_id = BlockID(blk.hash(), blk.make_part_set().header)
    # same header hash (qc is not header-hashed), DIFFERENT part bytes:
    # the signed BlockID pins the original proof
    assert tampered_id.hash == src_bs.load_block(3).hash()
    assert tampered_id != src_bs.load_block(3).block_id()
    # and the bad aggregate itself never verifies
    with pytest.raises(ValueError):
        vs.verify_commit_qc(CHAIN_ID, fid, 2, blk.last_qc)


def test_window_falls_back_when_qc_verdicts_fail(monkeypatch):
    """The windowed fallback: if the qc_verify engine rejects (or is
    unavailable), the window re-judges on the N-sig path instead of
    stalling — the full commit is authoritative."""
    vs, pvs, privs = make_qc_validators(1, seed=b"fb")
    genesis = make_genesis(vs)
    heights = 5

    async def produce():
        cs, app, l2, bs, ss = _qc_node(vs, pvs[0], genesis, privs)
        await cs.start()
        await cs.wait_for_height(heights, timeout=40)
        await cs.stop()
        return bs

    src_bs = asyncio.run(produce())
    monkeypatch.setattr(
        ValidatorSet,
        "verify_commits_qc",
        lambda self, chain_id, entries, engine=None: [False] * len(entries),
    )
    consumer = _sync_consumer(
        vs, pvs, privs, genesis, src_bs, heights - 1, qc_enabled=True
    )
    assert consumer.blocks_applied == heights - 2
    assert consumer.qc_verified_blocks == 0  # every window re-judged


def test_bench_trend_ingests_qc_catchup():
    """Satellite: the qc_catchup family gates like every other plane —
    headline blocksync_commits_per_s@100, direction higher, tier-1."""
    import importlib
    import sys as _sys

    _sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), "..", "tools")
    )
    bt = importlib.import_module("bench_trend")
    assert bt.family_of("blocksync_commits_per_s@100") == "qc_catchup"
    assert bt.family_of("qc_verify_wall_per_block_n100") == "qc_catchup"
    assert bt.family_of("qc_proof_compression_n32") == "qc_catchup"
    # plain blocksync metrics keep their family
    assert bt.family_of("blocksync_replay_throughput") == "blocksync"
    assert "qc_catchup" in bt.TIER1_FAMILIES
    assert bt.direction_of("blocksync_commits_per_s@100", "commits/s") == (
        "higher"
    )
    assert bt.direction_of(
        "qc_verify_wall_per_block_n100", "ms/block"
    ) == "lower"
    # a synthetic regressed headline fails the gate
    rows = [
        {
            "file": f"BENCH_r{r}.json", "round": r,
            "metric": "blocksync_commits_per_s@100", "value": v,
            "unit": "commits/s", "family": "qc_catchup",
            "direction": "higher", "backend": "cpu", "devices": 1,
            "headline": True,
        }
        for r, v in ((1, 775.0), (2, 300.0))
    ]
    groups = bt.build_groups(rows)
    failures, _warnings = bt.check_gate(groups, threshold=0.15)
    assert failures, "regressed qc headline did not gate"


def test_rpc_light_block_qc_param(qc_commit, committee):
    """The light_block route's proof=qc negotiation (handler-level):
    compressed shape drops the commit, carries the qc, and unknown
    formats are -32602."""
    from tendermint_tpu.lightserve.cache import LightBlockCache
    from tendermint_tpu.rpc.core import RPCCore
    from tendermint_tpu.rpc.server import RPCError

    vs, chain, _ = _light_chain(4, [1, 2, 3], seed=b"rpcqc")

    class Meta:
        def __init__(self, lb):
            self.header = lb.header

    class FakeBlockStore:
        height = 3

        def load_block_meta(self, h):
            return Meta(chain[h - 1]) if 1 <= h <= 3 else None

        def load_block_commit(self, h):
            return chain[h - 1].commit if 1 <= h <= 2 else None

        def load_seen_commit(self, h):
            return chain[h - 1].commit if h == 3 else None

        def load_block_qc(self, h):
            return chain[h - 1].qc if 1 <= h <= 2 else None

    class FakeStateStore:
        def load_validators(self, h):
            return vs

    class FakePlane:
        cache = LightBlockCache(FakeBlockStore(), FakeStateStore(), CHAIN_ID)

    class FakeNode:
        lightserve = FakePlane()

    core = RPCCore.__new__(RPCCore)
    core.node = FakeNode()
    full = core.light_block(height=1)["light_block"]
    assert full["signed_header"]["commit"] is not None
    assert "qc" in full  # full proofs on QC chains carry it alongside
    comp = core.light_block(height=1, proof="qc")["light_block"]
    assert comp["signed_header"]["commit"] is None
    assert comp["qc"]["agg_signature"]
    # provider-side parse round-trips the compressed proof
    from tendermint_tpu.rpc.light_provider import (
        header_from_json,
        qc_from_json,
        validators_from_json,
    )

    qc = qc_from_json(comp["qc"])
    assert qc.encode() == chain[0].qc.encode()
    hdr = header_from_json(comp["signed_header"]["header"])
    assert hdr.hash() == chain[0].header.hash()
    vals = validators_from_json(comp["validator_set"]["validators"])
    assert vals.hash() == vs.hash()
    assert vals.qc_capable()
    with pytest.raises(RPCError):
        core.light_block(height=1, proof="zstd")


def test_legacy_chain_syncs_on_qc_consumer():
    """The other direction of mixed mode: a QC-enabled consumer syncs a
    chain whose proposers never attached QCs — transparent fallback to
    the N-sig window."""
    vs, pvs, privs = make_qc_validators(1, seed=b"legacysrc")
    genesis = make_genesis(vs)
    heights = 5

    async def produce():
        cs, app, l2, bs, ss = _qc_node(
            vs, pvs[0], genesis, privs, qc=False
        )
        await cs.start()
        await cs.wait_for_height(heights, timeout=40)
        await cs.stop()
        return bs

    src_bs = asyncio.run(produce())
    assert src_bs.load_block(heights).last_qc is None
    consumer = _sync_consumer(
        vs, pvs, privs, genesis, src_bs, heights - 1, qc_enabled=True
    )
    assert consumer.blocks_applied == heights - 2
    assert consumer.qc_verified_blocks == 0


def test_l2_rotation_carries_bls_key_into_next_qc_bitset():
    """Satellite regression (PERF_ANALYSIS §22): a BLS pubkey riding an
    L2 validator update (state/execution 4-column val_updates) reaches
    the stored set, flips it QC-capable, and the rotated-keyed member
    lands in the next quorum certificate's signer bitset."""
    from tendermint_tpu.consensus.state_machine import ConsensusConfig
    from tendermint_tpu.l2node.mock import MockL2Node

    from .test_consensus import make_node, wire_net

    vs, pvs, privs = make_qc_validators(4, seed=b"rotate")
    # strip one member's BLS key from genesis: the set starts NOT
    # qc_capable, so no height can carry a QC until the rotation lands
    bare = vs.validators[2]
    key_backfill = bare.bls_pub_key
    bare.bls_pub_key = b""
    genesis = make_genesis(vs)
    rotate_h, last_h = 3, 9
    # the update applied at rotate_h becomes next_validators(rotate_h+1)
    # = validators(rotate_h+2): first QC-capable height
    capable_h = rotate_h + 2

    async def run():
        nodes = []
        cfg = ConsensusConfig.test_config()
        cfg.quorum_certificates = True
        for pv in pvs:
            l2 = MockL2Node()
            # every replica delivers the same rotation: the sitting
            # member's ed25519 identity + unchanged power, now with its
            # BLS key in the 4th column
            l2.validator_updates[rotate_h] = [
                ("ed25519", bare.pub_key.data, bare.voting_power,
                 key_backfill)
            ]
            addr = pv.get_pub_key().address()
            cs, app, _, bs, ss = make_node(
                vs, pv, genesis, l2=l2, config=cfg,
                bls_signer=bls.signer_for(privs[addr]),
            )
            cs.executor.qc_enabled = True
            nodes.append((cs, bs, ss))
        css = [n[0] for n in nodes]
        wire_net(css)
        for cs in css:
            await cs.start()
        await asyncio.gather(
            *(cs.wait_for_height(last_h, timeout=60) for cs in css)
        )
        for cs in css:
            await cs.stop()
        return nodes[0][1], nodes[0][2]

    bs, ss = asyncio.run(run())
    rot_idx = 2
    # pre-rotation heights can never carry a QC (set not capable)
    for h in range(2, capable_h):
        blk = bs.load_block(h + 1)
        assert blk.last_qc is None, f"height {h} got a QC pre-rotation"
    # post-rotation: some height in [capable_h, last_h) carries one
    # (round-0 proposer assembly is best-effort, so scan the window)
    carried = [
        bs.load_block(h + 1).last_qc
        for h in range(capable_h, last_h - 1)
        if bs.load_block(h + 1) and bs.load_block(h + 1).last_qc
    ]
    assert carried, "no QC produced after the rotation landed"
    qc = carried[0]
    set_at = ss.load_validators(qc.height)
    assert set_at is not None and set_at.qc_capable()
    assert set_at.validators[rot_idx].bls_pub_key == key_backfill
    assert qc.signers.get(rot_idx), (
        "rotated-keyed validator missing from the QC bitset"
    )
    set_at.verify_commit_qc(CHAIN_ID, qc.block_id, qc.height, qc)
