"""Vote micro-batcher: batching dynamics, ordering, consensus integration."""

import asyncio

import numpy as np

from tendermint_tpu.consensus.vote_batcher import VoteBatcher
from tendermint_tpu.crypto import ed25519 as host
from tendermint_tpu.crypto.batch_verifier import BatchVerifier


class SlowStubVerifier:
    """Deterministic stand-in: records batch sizes, adds device-ish
    latency so queued submissions coalesce into the next batch."""

    def __init__(self, delay=0.02):
        self.delay = delay
        self.batches = []

    def verify(self, items):
        import time

        time.sleep(self.delay)  # runs in the executor thread
        self.batches.append(len(items))
        return np.array([it.sig != b"BAD" * 21 + b"B" for it in items])


def test_batches_coalesce_under_load():
    """While one device call is in flight, arriving votes form the next
    batch — ≥8-vote batches must emerge from 32 rapid submissions
    (VERDICT round-1 item 4's 'demonstrably runs in batches >= 8')."""
    stub = SlowStubVerifier()
    batcher = VoteBatcher(verifier=stub)

    async def run():
        subs = [
            asyncio.create_task(
                batcher.submit(b"\x01" * 32, b"msg%d" % i, b"\x02" * 64)
            )
            for i in range(32)
        ]
        results = await asyncio.gather(*subs)
        batcher.stop()
        return results

    results = asyncio.run(run())
    assert all(results)
    assert max(stub.batches) >= 8, f"batches never coalesced: {stub.batches}"
    assert sum(stub.batches) == 32


def test_latency_adapts_with_concurrency():
    """The adaptivity contract (SURVEY §7.3 hard part 3), measured: at
    concurrency 1 a vote rides a batch of 1; at concurrency 256 batches
    grow to the verifier's appetite and the p99 per-vote latency stays
    FAR below the serial-drain model (256 sequential verifier calls).
    bench.py records the real-device p50/p99 numbers; this pins the
    mechanism with a deterministic stub."""
    import time

    stub = SlowStubVerifier(delay=0.02)
    batcher = VoteBatcher(verifier=stub)
    lat: dict[int, list] = {}

    async def one(i):
        t0 = time.monotonic()
        ok = await batcher.submit(b"\x01" * 32, b"m%d" % i, b"\x02" * 64)
        assert ok
        return time.monotonic() - t0

    async def run():
        # concurrency 1
        lat[1] = [await one(0) for _ in range(4)]
        single_max_batch = max(batcher.batch_sizes)
        # concurrency 256
        lat[256] = await asyncio.gather(*(one(i) for i in range(256)))
        batcher.stop()
        return single_max_batch

    single_max_batch = asyncio.run(run())
    assert single_max_batch == 1, "light load must ride batches of 1"
    assert max(batcher.batch_sizes) >= 64, (
        f"batch telemetry never adapted: {list(batcher.batch_sizes)}"
    )
    p99 = sorted(lat[256])[int(0.99 * 255)]
    serial_drain = 256 * stub.delay  # 5.12s if votes were verified 1-by-1
    assert p99 < serial_drain / 4, (
        f"p99 {p99:.3f}s not amortized vs serial {serial_drain:.2f}s"
    )


def test_results_resolve_in_submission_order():
    stub = SlowStubVerifier(delay=0.01)
    batcher = VoteBatcher(verifier=stub)
    order = []

    async def submit_one(i):
        sig = b"BAD" * 21 + b"B" if i % 3 == 0 else b"\x02" * 64
        ok = await batcher.submit(b"\x01" * 32, b"m%d" % i, sig)
        order.append((i, ok))

    async def run():
        await asyncio.gather(*(submit_one(i) for i in range(24)))
        batcher.stop()

    asyncio.run(run())
    assert [i for i, _ in order] == list(range(24))
    for i, ok in order:
        assert ok == (i % 3 != 0)


def test_real_signatures_through_batcher():
    """End-to-end with the real BatchVerifier (host fast path: the device
    kernel is covered by test_batch_verifier)."""
    verifier = BatchVerifier(min_device_batch=1 << 30)
    batcher = VoteBatcher(verifier=verifier)
    keys = [host.PrivKey.from_secret(b"vb%d" % i) for i in range(6)]

    async def run():
        tasks = []
        for i, k in enumerate(keys):
            msg = b"vote-%d" % i
            sig = k.sign(msg) if i != 3 else b"\x00" * 64
            tasks.append(
                asyncio.create_task(
                    batcher.submit(k.public_key().data, msg, sig)
                )
            )
        out = await asyncio.gather(*tasks)
        batcher.stop()
        return out

    out = asyncio.run(run())
    assert out == [True, True, True, False, True, True]


def test_consensus_net_with_batcher_over_p2p():
    """The reactor's vote path routes through the micro-batcher and the
    4-node net still reaches consensus with pre-verified inserts."""
    from .test_consensus_reactor import build_p2p_node, connect_full_mesh
    from .helpers import make_genesis, make_validators

    vs, pvs = make_validators(4)
    genesis = make_genesis(vs)

    async def run():
        nodes = [build_p2p_node(vs, pv, genesis) for pv in pvs]
        for cs, nk, t, sw in nodes:
            await t.listen()
            await sw.start()
        await connect_full_mesh(nodes)
        for cs, *_ in nodes:
            await cs.start()
        await asyncio.gather(
            *(cs.wait_for_height(2, timeout=60) for cs, *_ in nodes)
        )
        # every node's reactor ran votes through its batcher
        sizes = []
        for _, _, _, sw in nodes:
            r = sw.reactors["consensus"]
            sizes.extend(r.vote_batcher.batch_sizes)
        hashes = {cs.block_store.load_block(2).hash() for cs, *_ in nodes}
        for cs, nk, t, sw in nodes:
            await cs.stop()
            await sw.stop()
        return sizes, hashes

    sizes, hashes = asyncio.run(run())
    assert len(hashes) == 1, "nodes disagree"
    assert sum(sizes) > 0, "no votes flowed through the micro-batcher"
