"""e2e perturbation harness: kill/restart nodes mid-consensus.

Reference: test/e2e/ runner perturbations (kill/restart/disconnect,
runner/perturb.go) compressed to in-proc form over real p2p nodes. The
assertions mirror the e2e suite: all live nodes keep committing the same
chain, and a restarted node recovers via WAL replay + gossip catchup.
"""

import asyncio

import pytest

from tendermint_tpu.p2p.fuzz import FuzzConnConfig, FuzzedWriter

from .helpers import make_genesis, make_validators
from .test_consensus_reactor import build_p2p_node, connect_full_mesh


def test_node_kill_and_rejoin_recovers():
    """Kill one of four validators; the rest keep committing (BFT with
    3/4); the node rejoins with a fresh reactor and catches up via
    gossip (reference perturb 'kill' + catchup)."""
    vs, pvs = make_validators(4)
    genesis = make_genesis(vs)

    async def run():
        nodes = [build_p2p_node(vs, pv, genesis) for pv in pvs]
        for cs, nk, t, sw in nodes:
            await t.listen()
            await sw.start()
        await connect_full_mesh(nodes)
        for cs, *_ in nodes:
            await cs.start()
        await asyncio.gather(
            *(cs.wait_for_height(2, timeout=150) for cs, *_ in nodes)
        )

        # perturb: kill node 3 entirely (consensus + switch)
        dead_cs, _, dead_t, dead_sw = nodes[3]
        await dead_cs.stop()
        await dead_sw.stop()

        # the remaining 3/4 keep committing
        survivors = nodes[:3]
        target = max(cs.rs.height for cs, *_ in survivors) + 2
        await asyncio.gather(
            *(cs.wait_for_height(target, timeout=150) for cs, *_ in survivors)
        )

        # rejoin: fresh p2p node, same privval + stores (restart semantics)
        from tests.test_consensus_reactor import NETWORK
        from tendermint_tpu.consensus.reactor import ConsensusReactor
        from tendermint_tpu.p2p.key import NodeKey
        from tendermint_tpu.p2p.node_info import NodeInfo
        from tendermint_tpu.p2p.switch import Switch
        from tendermint_tpu.p2p.transport import (
            MultiplexTransport,
            NetAddress,
        )

        nk = NodeKey.generate()
        transport = None
        sw = None

        def node_info():
            return NodeInfo(
                node_id=nk.id,
                listen_addr=f"127.0.0.1:{transport.listen_port}",
                network=NETWORK,
                channels=sw.channels() if sw else b"",
            )

        transport = MultiplexTransport(nk, node_info)
        sw = Switch(transport)
        sw.add_reactor("consensus", ConsensusReactor(dead_cs))
        await transport.listen()
        await sw.start()
        for _, onk, ot, osw in survivors:
            await sw.dial_peer(
                NetAddress(onk.id, "127.0.0.1", ot.listen_port)
            )
        await dead_cs.start()

        # the rejoined node catches up past the survivors' progress
        catchup_target = max(cs.rs.height for cs, *_ in survivors) + 1
        await dead_cs.wait_for_height(catchup_target, timeout=150)

        # all four agree on the chain
        h = min(
            catchup_target,
            *(cs.block_store.height for cs, *_ in survivors),
        )
        hashes = {
            n[0].block_store.load_block(h).hash()
            for n in survivors + [nodes[3]]
        }
        for cs, *_ in survivors:
            await cs.stop()
        await dead_cs.stop()
        for _, _, _, s in survivors:
            await s.stop()
        await sw.stop()
        return hashes

    hashes = asyncio.run(run())
    assert len(hashes) == 1, "nodes diverged after kill/rejoin"


def test_consensus_survives_lossy_links():
    """Consensus proceeds over drop-fuzzed connections (reference
    FuzzedConnection, p2p/fuzz.go:14). A dropped frame desyncs the
    SecretConnection nonce counter, so the AEAD kills the whole
    connection — survival comes from the switch REDIALING persistent
    peers (reference switch.go reconnectAttempts), not from tolerating
    the loss in-stream. Hence persistent dials + a low drop rate."""
    import random

    import tendermint_tpu.p2p.transport as transport_mod

    vs, pvs = make_validators(4)
    genesis = make_genesis(vs)
    rng = random.Random(42)
    cfg = FuzzConnConfig(mode="drop", prob_drop_rw=0.005)
    wrapped = []

    # monkeypatch the mconn send path: wrap writers of new connections
    orig_init = transport_mod.Peer.__init__

    def fuzzing_init(self, node_info, sconn, mconn, outbound, socket_addr):
        orig_init(self, node_info, sconn, mconn, outbound, socket_addr)
        w = FuzzedWriter(sconn._writer, cfg, rng)
        sconn._writer = w
        wrapped.append(w)

    transport_mod.Peer.__init__ = fuzzing_init
    try:

        async def run():
            from tendermint_tpu.p2p.transport import NetAddress

            nodes = [build_p2p_node(vs, pv, genesis) for pv in pvs]
            for cs, nk, t, sw in nodes:
                await t.listen()
                await sw.start()
            # persistent full mesh: dropped connections get redialed
            for i, (_, _, _, sw_i) in enumerate(nodes):
                sw_i.dial_peers_async(
                    [
                        NetAddress(nk_j.id, "127.0.0.1", t_j.listen_port)
                        for j, (_, nk_j, t_j, _) in enumerate(nodes)
                        if j != i
                    ],
                    persistent=True,
                )
            for cs, *_ in nodes:
                await cs.start()
            await asyncio.gather(
                *(cs.wait_for_height(3, timeout=180) for cs, *_ in nodes)
            )
            hashes = {
                cs.block_store.load_block(3).hash() for cs, *_ in nodes
            }
            for cs, nk, t, sw in nodes:
                await cs.stop()
                await sw.stop()
            return hashes

        hashes = asyncio.run(run())
    finally:
        transport_mod.Peer.__init__ = orig_init

    assert len(hashes) == 1, "nodes disagree under lossy links"
    assert any(w.dropped for w in wrapped), "fuzzer never dropped a frame"
