"""Mesh-sharded verify plane on the forced-host virtual CPU mesh.

The conftest forces `--xla_force_host_platform_device_count=8` with
JAX_PLATFORMS=cpu, so a 4-device mesh here is the ISSUE-6 forced-host
topology without TPU hardware. `perf`-marked (and slow: device
compiles) like test_prewarm — the acceptance suite for the multi-chip
dispatch rounds:

- sharded verdicts bit-identical to the single-device path for EVERY
  ladder bucket (pad/shard/gather round-trip is verdict-inert);
- uneven tails (n not divisible by the device count) pad per-device
  and never flip a verdict;
- `mesh_min_rows` keeps small rounds single-device (replicated — no
  shard/gather latency tax on live consensus);
- the registry's per-mesh shape count stays within the program budget;
- a coalesced scheduler round dispatches as ONE sharded round with the
  `sharded`/`devices` telemetry;
- tools/multichip_capture.py drives this same path end-to-end in a
  4-device child process.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

pytestmark = [pytest.mark.perf, pytest.mark.slow]

N_DEV = 4
N_KEYS = 64


def _mesh4():
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices("cpu")[:N_DEV]), ("batch",))


_BASE: list = []


def _base_items():
    """Signed base rows, built lazily so tier-1 collection (which
    imports but deselects this module) never pays the host signing."""
    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.crypto.batch_verifier import SigItem

    if not _BASE:
        for i in range(N_KEYS):
            sk = ed25519.PrivKey.from_secret(b"meshshard-%d" % i)
            msg = b"mesh-vote-%d" % i
            _BASE.append(
                SigItem(sk.public_key().data, msg, sk.sign(msg))
            )
    return _BASE


def _items(n: int, corrupt=()):
    """n rows tiled from the signed base set, with chosen rows' sigs
    bit-flipped (well-formed length, invalid signature)."""
    from tendermint_tpu.crypto.batch_verifier import SigItem

    base = _base_items()
    reps = (n + N_KEYS - 1) // N_KEYS
    out = list((base * reps)[:n])
    for i in corrupt:
        it = out[i]
        bad = it.sig[:50] + bytes([it.sig[50] ^ 1]) + it.sig[51:]
        out[i] = SigItem(it.pubkey, it.msg, bad)
    return out


@pytest.fixture(scope="module")
def regs_and_verifiers():
    """One meshless and one always-sharding mesh verifier, each with an
    isolated registry; module-scoped so the ladder's programs compile
    once."""
    from tendermint_tpu.crypto.batch_verifier import BatchVerifier
    from tendermint_tpu.crypto.shape_registry import ShapeRegistry

    reg_solo, reg_mesh = ShapeRegistry(), ShapeRegistry()
    v_solo = BatchVerifier(
        min_device_batch=0, bigtable_min=1 << 30, shape_registry=reg_solo
    )
    v_mesh = BatchVerifier(
        mesh=_mesh4(),
        min_device_batch=0,
        bigtable_min=1 << 30,
        shape_registry=reg_mesh,
        mesh_min_rows=1,  # shard every bucket: the round-trip under test
    )
    return reg_solo, v_solo, reg_mesh, v_mesh


def test_sharded_bit_identical_every_ladder_bucket(regs_and_verifiers):
    """For every rung of the canonical ladder, the 4-way sharded round
    and the single-device round produce bit-identical verdict bitmaps,
    equal to the constructed truth (corrupted rows rejected)."""
    reg_solo, v_solo, reg_mesh, v_mesh = regs_and_verifiers
    for b in reg_mesh.ladder:
        n = b  # fill the bucket exactly
        corrupt = sorted({1 % n, n // 3, n - 1})
        items = _items(n, corrupt=corrupt)
        want = [i not in corrupt for i in range(n)]
        got_mesh = np.asarray(v_mesh.verify(items))
        got_solo = np.asarray(v_solo.verify(items))
        assert got_mesh.tolist() == want, f"mesh verdicts wrong at {b}"
        assert (got_mesh == got_solo).all(), (
            f"sharded verdicts diverge from single-device at bucket {b}"
        )
    # every bulk dispatch actually sharded (devices=4 shapes recorded)
    small = reg_mesh.shapes_by_tier()["small"]
    assert {d for _, _, d in small} == {N_DEV}
    assert reg_mesh.sharded_dispatch_count() >= len(reg_mesh.ladder)


def test_uneven_tail_pads_per_device(regs_and_verifiers):
    """n not divisible by the device count: the bucket is rounded up to
    a multiple of 4, the tail rows are verdict-inert padding, and no
    real verdict moves. Runs sizes straddling rung boundaries."""
    reg_solo, v_solo, reg_mesh, v_mesh = regs_and_verifiers
    for n in (13, 129, 510, 2043):
        corrupt = sorted({0, n // 2, n - 1})
        items = _items(n, corrupt=corrupt)
        want = [i not in corrupt for i in range(n)]
        got = np.asarray(v_mesh.verify(items))
        assert got.tolist() == want, f"uneven tail flipped verdicts at n={n}"
        assert len(got) == n
        # the padded bucket divides evenly across devices
        b = reg_mesh.bucket_for(n, multiple_of=N_DEV)
        assert b % N_DEV == 0 and b >= n


def test_mesh_min_rows_keeps_small_rounds_single_device():
    """Rounds below mesh_min_rows prepare with devices=1 (replicated —
    single-chip latency), at/above with devices=N; no dispatch needed
    to decide, so this pins the routing logic itself."""
    from tendermint_tpu.crypto.batch_verifier import BatchVerifier
    from tendermint_tpu.crypto.shape_registry import ShapeRegistry

    v = BatchVerifier(
        mesh=_mesh4(),
        min_device_batch=0,
        bigtable_min=1 << 30,
        shape_registry=ShapeRegistry(),
        mesh_min_rows=1024,
    )
    assert v.mesh_devices == N_DEV
    assert v.shards_for(1) == 1
    assert v.shards_for(1023) == 1
    assert v.shards_for(1024) == N_DEV
    assert v.prepare(_items(16)).devices == 1
    assert v.prepare(_items(1024)).devices == N_DEV
    # env default wiring: None reads TM_TPU_MESH_MIN_ROWS
    os.environ["TM_TPU_MESH_MIN_ROWS"] = "64"
    try:
        v2 = BatchVerifier(
            mesh=_mesh4(),
            shape_registry=ShapeRegistry(),
        )
        assert v2.shards_for(63) == 1 and v2.shards_for(64) == N_DEV
    finally:
        del os.environ["TM_TPU_MESH_MIN_ROWS"]
    # UNSET env must land on the built-in default, not shard-everything
    # (regression: `get(.., "0") or default` kept the truthy "0")
    from tendermint_tpu.crypto.batch_verifier import DEFAULT_MESH_MIN_ROWS

    assert "TM_TPU_MESH_MIN_ROWS" not in os.environ
    v3 = BatchVerifier(mesh=_mesh4(), shape_registry=ShapeRegistry())
    assert v3._mesh_min_rows == DEFAULT_MESH_MIN_ROWS
    assert v3.shards_for(16) == 1


def test_per_mesh_shape_count_within_budget(regs_and_verifiers):
    """After the full-ladder sweep, the registry stays within the
    program budget per (tier, device-variant) — the mesh doubles the
    reachable families, not the per-family ladder."""
    reg_solo, _, reg_mesh, _ = regs_and_verifiers
    for reg in (reg_solo, reg_mesh):
        for tier, shapes in reg.shapes_by_tier().items():
            by_dev: dict[int, int] = {}
            for _, _, d in shapes:
                by_dev[d] = by_dev.get(d, 0) + 1
            for d, count in by_dev.items():
                assert count <= 8, (
                    f"tier {tier} devices={d} exceeded the shape "
                    f"budget: {shapes}"
                )


def test_scheduler_round_dispatches_sharded(regs_and_verifiers):
    """Coalesced submissions from two classes ride ONE sharded round:
    the dispatch log and device_round telemetry carry sharded/devices,
    and the verify_mesh_devices gauge reflects the mesh."""
    import asyncio

    from tendermint_tpu.libs.metrics import Registry, SchedulerMetrics
    from tendermint_tpu.parallel.scheduler import VerifyScheduler

    _, _, reg_mesh, v_mesh = regs_and_verifiers
    metrics = SchedulerMetrics(Registry("mesh_test"))
    s = VerifyScheduler(v_mesh, max_batch=16384, metrics=metrics)
    items_a = _items(96)
    items_b = _items(32, corrupt=(3,))

    async def run():
        await s.start()
        # occupy the device so the next two coalesce into one round
        first = asyncio.create_task(s.submit(_items(8), "consensus"))
        await asyncio.sleep(0.01)
        a, b = await asyncio.gather(
            s.submit(items_a, "consensus"),
            s.submit(items_b, "blocksync"),
        )
        await first
        await s.stop()
        return a, b

    a, b = asyncio.run(run())
    assert np.asarray(a).all()
    assert np.asarray(b).tolist() == [i != 3 for i in range(32)]
    assert metrics.mesh_devices.value() == N_DEV
    sharded = [d for d in s.dispatch_log if d.get("sharded")]
    assert sharded, f"no sharded round in {list(s.dispatch_log)}"
    assert sharded[-1]["devices"] == N_DEV
    assert metrics.dispatch_sharded.value() >= 1


def test_multichip_capture_forced_host_4dev(tmp_path):
    """tools/multichip_capture.py end-to-end in a child process forced
    to 4 host devices: the artifact's series covers 1/2/4 devices from
    the scheduler dispatch path, sharded rounds recorded, meta stamps
    the cpu backend (a fallback row can never pass as a device row)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    env["XLA_FLAGS"] = (
        " ".join(
            f for f in flags.split()
            if "xla_force_host_platform_device_count" not in f
        )
        + " --xla_force_host_platform_device_count=4"
    ).strip()
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [
            sys.executable,
            os.path.join(root, "tools", "multichip_capture.py"),
            "4",
            "--bucket", "128",
            "--mesh-min-rows", "8",
            "--mesh-backend", "cpu",
            "--no-dryrun",
        ],
        capture_output=True,
        text=True,
        timeout=560,
        env=env,
        cwd=root,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    art = json.loads(r.stdout.strip().splitlines()[-1])
    assert art["ok"], art
    assert art["meta"]["backend"] == "cpu"
    assert art["meta"]["device_count"] == 4
    devs = [s["devices"] for s in art["series"]]
    assert devs == [1, 2, 4]
    multi = [s for s in art["series"] if s["devices"] > 1]
    assert all(s["sharded"] and s["sharded_dispatches"] > 0 for s in multi)
    assert set(art["scaling_vs_1chip"]) == {"2", "4"}
