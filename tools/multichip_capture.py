"""Multichip capture that ALWAYS emits one parseable JSON artifact.

Two stages, one artifact:

1. **dryrun** (`capture`) — `__graft_entry__.dryrun_multichip(n)`
   compile-checks the sharded verification step in a sanitized
   subprocess (the round-4 lesson: a dead tunnel endpoint must produce
   a structured artifact, not an rc=124 traceback tail).
2. **sharded throughput** (`sharded_capture`) — drives the SAME
   dispatch path the node runs: SigItem batches submitted to a
   `VerifyScheduler` over a `BatchVerifier` built on a
   `parallel.build_mesh` mesh, measured per device count on the bulk
   bucket. No ad-hoc pmap loop — MULTICHIP and BENCH numbers come from
   the scheduler/verifier code path itself, so a scaling number here is
   a scaling number in the node.

The artifact line:

    {"n_devices", "rc", "ok", "error", "backend", "fallback",
     "elapsed_s", "meta": {backend, device_count, jax_version},
     "series": [{"devices", "sigs_per_s", "sharded_dispatches"}...],
     "scaling_vs_1chip": {...}}

`--require-backend tpu` exits non-zero with a structured artifact (no
fallback row) when the probed backend differs — same honesty contract
as bench.py. Exit code is otherwise 0: infrastructure state lives IN
the artifact, so the driver never has to scrape tracebacks.

Usage: python tools/multichip_capture.py [n_devices]
           [--bucket 16384] [--require-backend tpu]
           [--mesh-backend cpu] [--mesh-min-rows N] [--no-dryrun]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tendermint_tpu.libs.jax_cache import set_compile_cache_env  # noqa: E402

set_compile_cache_env()


def capture(n_devices: int) -> dict:
    """Run the sharded compile dryrun and build the artifact dict (no
    printing, no exits — unit-testable)."""
    from tendermint_tpu.chaos.backend_guard import classify_failure

    t0 = time.perf_counter()
    try:
        from __graft_entry__ import dryrun_multichip

        dryrun_multichip(n_devices)
        return {
            "n_devices": n_devices,
            "rc": 0,
            "ok": True,
            "error": "",
            "backend": "cpu",  # the dryrun pins the sanitized CPU mesh
            "fallback": "none",
            "elapsed_s": round(time.perf_counter() - t0, 1),
        }
    except BaseException as e:  # noqa: BLE001 - artifact must always emit
        msg = str(e)[-1200:]
        rc = 124 if "exceeded" in msg else 1
        return {
            "n_devices": n_devices,
            "rc": rc,
            "ok": False,
            "error": msg,
            "backend": None,
            "fallback": "none",
            "kind": classify_failure(msg, rc),
            "elapsed_s": round(time.perf_counter() - t0, 1),
            "meta": _meta(live=False),
        }


def _make_items(n: int, n_unique: int = 128) -> list:
    """n SigItems from n_unique distinct signers (realistic validator
    set; rows repeat like a multi-height replay batch)."""
    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.crypto.batch_verifier import SigItem

    base = []
    for i in range(min(n, n_unique)):
        sk = ed25519.PrivKey.from_secret(b"multichip-%d" % i)
        msg = b"multichip-vote-%d" % i
        base.append(SigItem(sk.public_key().data, msg, sk.sign(msg)))
    reps = (n + len(base) - 1) // len(base)
    return (base * reps)[:n]


def _measure_devices(
    items: list,
    devices: int,
    bucket: int,
    mesh_backend: str = "",
    mesh_min_rows: int | None = None,
    iters: int = 3,
    depth: int = 4,
) -> dict:
    """Throughput of the scheduler's dispatch path on a `devices`-chip
    mesh: warm the verify tables and the program, then best-of-iters
    over `depth` pipelined scheduler rounds of the full bucket."""
    import asyncio

    import numpy as np

    from tendermint_tpu.crypto.batch_verifier import BatchVerifier
    from tendermint_tpu.crypto.shape_registry import ShapeRegistry
    from tendermint_tpu.parallel import build_mesh
    from tendermint_tpu.parallel.scheduler import VerifyScheduler

    mesh = (
        build_mesh(ici_parallelism=devices, mesh_backend=mesh_backend)
        if devices > 1
        else None
    )
    reg = ShapeRegistry()
    verifier = BatchVerifier(
        mesh=mesh,
        min_device_batch=0,
        shape_registry=reg,
        mesh_min_rows=mesh_min_rows,
    )
    verifier.warm(
        list({it.pubkey for it in items}), bulk=True
    )  # table build outside the clock, like a running node

    async def run() -> float:
        sched = VerifyScheduler(verifier, max_batch=bucket)
        await sched.start()
        out = await sched.submit(items)  # warm: program compile/load
        assert np.asarray(out).all(), "multichip warm batch failed"
        best = float("inf")
        for _ in range(iters):
            t0 = time.perf_counter()
            outs = await asyncio.gather(
                *(sched.submit(items) for _ in range(depth))
            )
            dt = time.perf_counter() - t0
            for o in outs:
                assert np.asarray(o).all(), "multichip batch failed"
            best = min(best, dt / depth)
        await sched.stop()
        return best

    dt = asyncio.run(run())
    return {
        "devices": devices,
        "sigs_per_s": round(len(items) / dt, 1),
        "ms_per_round": round(dt * 1e3, 1),
        "sharded_dispatches": reg.sharded_dispatch_count(),
        "sharded": verifier.shards_for(len(items)) > 1,
    }


def sharded_capture(
    max_devices: int,
    bucket: int = 16384,
    mesh_backend: str = "",
    mesh_min_rows: int | None = None,
) -> dict:
    """Measure the scheduler dispatch path at 1, 2, 4, ... devices up
    to min(max_devices, visible). Returns {series, scaling_vs_1chip}."""
    import jax

    avail = len(jax.devices(mesh_backend or None))
    counts = [1]
    d = 2
    while d <= min(max_devices, avail):
        counts.append(d)
        d *= 2
    top = min(max_devices, avail)
    if top > 1 and top not in counts:
        counts.append(top)
    items = _make_items(bucket)
    series = [
        _measure_devices(
            items, d, bucket,
            mesh_backend=mesh_backend, mesh_min_rows=mesh_min_rows,
        )
        for d in counts
    ]
    base = series[0]["sigs_per_s"] or 1.0
    return {
        "bucket": bucket,
        "metric": "ed25519_vote_verify_throughput_multichip",
        "unit": "sigs/s",
        "series": series,
        "scaling_vs_1chip": {
            str(s["devices"]): round(s["sigs_per_s"] / base, 3)
            for s in series
            if s["devices"] > 1
        },
        "devices_visible": avail,
    }


def _cpu_fallback(n: int, first: dict, argv_tail: list[str]) -> dict | None:
    """Infrastructure outage (tunnel_down/timeout): retry the capture
    once in a child whose environment has the tunnel plugin site fully
    scrubbed, JAX_PLATFORMS pinned to cpu and the device count forced,
    so the SHARDED path still runs — same fallback contract as
    bench.py's `_degrade`, and the meta block marks the row cpu.
    Returns the merged artifact or None."""
    import subprocess

    from tendermint_tpu.chaos.backend_guard import sanitized_env

    env = sanitized_env(platform="cpu")
    env["TM_TPU_MULTICHIP_CHILD"] = "1"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={max(n, 1)}"
        ).strip()
    timeout_s = float(
        os.environ.get("TM_TPU_MULTICHIP_FALLBACK_TIMEOUT", "1800")
    )
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), str(n)]
            + argv_tail,
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
    except subprocess.TimeoutExpired:
        return None
    parsed = None
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            parsed = json.loads(line)
            break
        except ValueError:
            continue
    if proc.returncode == 0 and isinstance(parsed, dict) and parsed.get("ok"):
        # rc=0: the outage lives in the artifact, the capture itself is
        # good data from the sanitized CPU mesh
        parsed.update(
            {
                "rc": 0,
                "fallback": "cpu",
                "error": first.get("error", ""),
                "kind": first.get("kind", ""),
            }
        )
        return parsed
    return None


def _meta(live: bool = True) -> dict:
    from tendermint_tpu.chaos.backend_guard import meta_block

    return meta_block(live=live)


def main() -> int:
    ap = argparse.ArgumentParser(
        description="multichip sharded-dispatch capture"
    )
    ap.add_argument("n_devices", nargs="?", type=int, default=8)
    ap.add_argument("--bucket", type=int, default=16384)
    ap.add_argument(
        "--require-backend",
        default=os.environ.get("TM_TPU_BENCH_REQUIRE_BACKEND", ""),
        help="fail (structured artifact, non-zero exit, no fallback) "
        "unless the probed backend equals this platform",
    )
    ap.add_argument("--mesh-backend", default="")
    ap.add_argument("--mesh-min-rows", type=int, default=0)
    ap.add_argument(
        "--no-dryrun",
        action="store_true",
        help="skip the sanitized compile dryrun stage",
    )
    args = ap.parse_args()
    n = args.n_devices
    argv_tail = ["--bucket", str(args.bucket)]
    if args.mesh_min_rows:
        argv_tail += ["--mesh-min-rows", str(args.mesh_min_rows)]

    is_child = os.environ.get("TM_TPU_MULTICHIP_CHILD") == "1"
    if args.require_backend and not is_child:
        from tendermint_tpu.chaos.backend_guard import probe_backend

        status = probe_backend()
        got = status.backend if status.available else None
        if got != args.require_backend:
            print(
                json.dumps(
                    {
                        "n_devices": n,
                        "rc": 1,
                        "ok": False,
                        "error": (
                            status.error
                            if not status.available
                            else f"probed backend {got!r} != required "
                            f"{args.require_backend!r}"
                        ),
                        "backend": got,
                        "kind": (
                            status.kind
                            if not status.available
                            else "backend_mismatch"
                        ),
                        "fallback": "none",
                        "required_backend": args.require_backend,
                        "meta": _meta(live=False),
                    }
                )
            )
            return 1

    t0 = time.perf_counter()
    if args.no_dryrun:
        art = {
            "n_devices": n, "rc": 0, "ok": True, "error": "",
            "backend": None, "fallback": "none", "elapsed_s": 0.0,
        }
    else:
        art = capture(n)
    if not art["ok"] and args.require_backend:
        # the honesty contract: with --require-backend a late outage
        # (probe passed, dispatch died) must NOT degrade to a CPU row —
        # structured failure, non-zero exit, no fallback
        art["required_backend"] = args.require_backend
        print(json.dumps(art))
        return 1
    if (
        not art["ok"]
        and art.get("kind") in ("tunnel_down", "timeout")
        and not is_child
    ):
        merged = _cpu_fallback(n, art, argv_tail)
        if merged is not None:
            print(json.dumps(merged))
            return 0
        print(json.dumps(art))
        return 0
    if not art["ok"]:
        print(json.dumps(art))
        return 0

    # dryrun compiled: measure the real scheduler dispatch path
    try:
        art.update(
            sharded_capture(
                n,
                bucket=args.bucket,
                mesh_backend=args.mesh_backend,
                mesh_min_rows=args.mesh_min_rows or None,
            )
        )
        art["meta"] = _meta()
        art["backend"] = art["meta"]["backend"]
    except BaseException as e:  # noqa: BLE001 - artifact must always emit
        from tendermint_tpu.chaos.backend_guard import classify_failure

        msg = str(e)[-1200:]
        art.update(
            {
                "rc": 1,
                "ok": False,
                "error": f"sharded capture failed: {msg}",
                "kind": classify_failure(msg, 1),
                "meta": _meta(live=False),
            }
        )
    art["elapsed_s"] = round(time.perf_counter() - t0, 1)
    if not art["ok"] and args.require_backend:
        art["required_backend"] = args.require_backend
        print(json.dumps(art))
        return 1
    print(json.dumps(art))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
