"""Multichip dryrun capture that ALWAYS emits one parseable JSON artifact.

The round-4 MULTICHIP artifact was `{"rc": 124, "tail": "<traceback>"}` —
the driver timed out waiting on a jax init that hung on a dead tunnel
endpoint. This wrapper runs `__graft_entry__.dryrun_multichip(n)` (which
already sandboxes the mesh body in a sanitized subprocess) and prints one
structured line:

    {"n_devices", "rc", "ok", "error", "backend", "fallback", "elapsed_s"}

exit code is always 0: infrastructure state lives IN the artifact, so the
driver never has to scrape tracebacks again.

Usage: python tools/multichip_capture.py [n_devices]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def capture(n_devices: int) -> dict:
    """Run the sharded dryrun and build the artifact dict (no printing,
    no exits — unit-testable)."""
    from tendermint_tpu.chaos.backend_guard import classify_failure

    t0 = time.perf_counter()
    try:
        from __graft_entry__ import dryrun_multichip

        dryrun_multichip(n_devices)
        return {
            "n_devices": n_devices,
            "rc": 0,
            "ok": True,
            "error": "",
            "backend": "cpu",  # the dryrun pins the sanitized CPU mesh
            "fallback": "none",
            "elapsed_s": round(time.perf_counter() - t0, 1),
        }
    except BaseException as e:  # noqa: BLE001 - artifact must always emit
        msg = str(e)[-1200:]
        rc = 124 if "exceeded" in msg else 1
        return {
            "n_devices": n_devices,
            "rc": rc,
            "ok": False,
            "error": msg,
            "backend": None,
            "fallback": "none",
            "kind": classify_failure(msg, rc),
            "elapsed_s": round(time.perf_counter() - t0, 1),
        }


def _cpu_fallback(n: int, first: dict) -> dict | None:
    """Infrastructure outage (tunnel_down/timeout): retry the capture
    once in a child whose environment has the tunnel plugin site fully
    scrubbed and JAX_PLATFORMS pinned to cpu — same fallback contract
    as bench.py's `_degrade`. Returns the merged artifact or None."""
    import subprocess

    from tendermint_tpu.chaos.backend_guard import sanitized_env

    env = sanitized_env(platform="cpu")
    env["TM_TPU_MULTICHIP_CHILD"] = "1"
    timeout_s = float(
        os.environ.get("TM_TPU_MULTICHIP_FALLBACK_TIMEOUT", "1800")
    )
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), str(n)],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
    except subprocess.TimeoutExpired:
        return None
    parsed = None
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            parsed = json.loads(line)
            break
        except ValueError:
            continue
    if proc.returncode == 0 and isinstance(parsed, dict) and parsed.get("ok"):
        # rc=0: the outage lives in the artifact, the capture itself is
        # good data from the sanitized CPU mesh
        parsed.update(
            {
                "rc": 0,
                "fallback": "cpu",
                "error": first.get("error", ""),
                "kind": first.get("kind", ""),
            }
        )
        return parsed
    return None


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    art = capture(n)
    if (
        not art["ok"]
        and art.get("kind") in ("tunnel_down", "timeout")
        and os.environ.get("TM_TPU_MULTICHIP_CHILD") != "1"
    ):
        merged = _cpu_fallback(n, art)
        if merged is not None:
            print(json.dumps(merged))
            return
    print(json.dumps(art))


if __name__ == "__main__":
    main()
