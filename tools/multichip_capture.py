"""Multichip dryrun capture that ALWAYS emits one parseable JSON artifact.

The round-4 MULTICHIP artifact was `{"rc": 124, "tail": "<traceback>"}` —
the driver timed out waiting on a jax init that hung on a dead tunnel
endpoint. This wrapper runs `__graft_entry__.dryrun_multichip(n)` (which
already sandboxes the mesh body in a sanitized subprocess) and prints one
structured line:

    {"n_devices", "rc", "ok", "error", "backend", "fallback", "elapsed_s"}

exit code is always 0: infrastructure state lives IN the artifact, so the
driver never has to scrape tracebacks again.

Usage: python tools/multichip_capture.py [n_devices]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def capture(n_devices: int) -> dict:
    """Run the sharded dryrun and build the artifact dict (no printing,
    no exits — unit-testable)."""
    from tendermint_tpu.chaos.backend_guard import classify_failure

    t0 = time.perf_counter()
    try:
        from __graft_entry__ import dryrun_multichip

        dryrun_multichip(n_devices)
        return {
            "n_devices": n_devices,
            "rc": 0,
            "ok": True,
            "error": "",
            "backend": "cpu",  # the dryrun pins the sanitized CPU mesh
            "fallback": "none",
            "elapsed_s": round(time.perf_counter() - t0, 1),
        }
    except BaseException as e:  # noqa: BLE001 - artifact must always emit
        msg = str(e)[-1200:]
        rc = 124 if "exceeded" in msg else 1
        return {
            "n_devices": n_devices,
            "rc": rc,
            "ok": False,
            "error": msg,
            "backend": None,
            "fallback": "none",
            "kind": classify_failure(msg, rc),
            "elapsed_s": round(time.perf_counter() - t0, 1),
        }


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    print(json.dumps(capture(n)))


if __name__ == "__main__":
    main()
