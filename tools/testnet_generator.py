"""Randomized testnet manifest generator (reference test/e2e/generator).

Produces seeded, deterministic testnet manifests — validator count,
full-node count, peer topology, per-node knobs, and a perturbation
schedule — and materializes them into runnable node homes using the same
`testnet` scaffolding the fixed mp-e2e scenarios use. The e2e runner
(tests/test_e2e_generator.py) picks a seed, boots the manifest across
real processes, applies the perturbations, and asserts liveness +
agreement, so every CI run exercises a (deterministically) different
topology.

Usage:
    python tools/testnet_generator.py SEED [OUTDIR]
prints the manifest; with OUTDIR it also materializes the homes.
"""

from __future__ import annotations

import json
import os
import random
import subprocess
import sys

TOPOLOGIES = ("mesh", "ring", "star")
PERTURBATIONS = ("none", "kill_restart")


def generate_manifest(seed: int) -> dict:
    """Deterministic manifest for `seed` (same seed -> same manifest)."""
    rng = random.Random(seed)
    n_validators = rng.choice((4, 4, 5))  # quorum-friendly sizes
    n_fulls = rng.randint(0, 2)
    topology = rng.choice(TOPOLOGIES)
    nodes = []
    for i in range(n_validators):
        nodes.append(
            {
                "name": f"validator{i:02d}",
                "mode": "validator",
                # at most one perturbed validator: BFT tolerates f=1 of 4
                "perturb": "none",
                "send_rate": rng.choice((0, 5120000)),
            }
        )
    victim = rng.randrange(n_validators)
    nodes[victim]["perturb"] = rng.choice(PERTURBATIONS)
    for i in range(n_fulls):
        nodes.append(
            {
                "name": f"full{i:02d}",
                "mode": "full",
                "perturb": "none",
                "send_rate": 0,
            }
        )
    return {
        "seed": seed,
        "topology": topology,
        "initial_height_target": 3,
        "nodes": nodes,
    }


def peer_indices(topology: str, i: int, n: int) -> list[int]:
    """Which nodes index i lists as persistent peers."""
    if topology == "mesh":
        return [j for j in range(n) if j != i]
    if topology == "ring":
        return [(i + 1) % n, (i - 1) % n] if n > 2 else [1 - i]
    if topology == "star":
        return [0] if i != 0 else list(range(1, n))
    raise ValueError(f"unknown topology {topology!r}")


def materialize(manifest: dict, base: str, free_ports) -> dict:
    """Create node homes for the manifest. `free_ports(n)` supplies
    distinct free localhost ports. Returns
    {name: {home, rpc_port, p2p_port, perturb, mode}}."""
    from tendermint_tpu.config import Config
    from tendermint_tpu.p2p.key import NodeKey

    nodes = manifest["nodes"]
    validators = [n for n in nodes if n["mode"] == "validator"]
    chain_id = f"gen-{manifest['seed']}"
    rc = subprocess.run(
        [
            sys.executable,
            "-m",
            "tendermint_tpu",
            "testnet",
            "--v",
            str(len(validators)),
            "--output",
            base,
            "--chain-id",
            chain_id,
        ],
        capture_output=True,
        timeout=120,
    )
    if rc.returncode != 0:
        raise RuntimeError(f"testnet scaffold failed: {rc.stderr.decode()}")

    n = len(nodes)
    ports = free_ports(2 * n)
    p2p_ports, rpc_ports = ports[:n], ports[n:]
    out = {}
    homes = []
    for i, spec in enumerate(nodes):
        if spec["mode"] == "validator":
            home = os.path.join(base, f"node{len(homes)}")
        else:
            # full node: fresh home + the shared genesis, own keys
            home = os.path.join(base, spec["name"])
            cfg = Config()
            cfg.root_dir = home
            cfg.ensure_dirs()
            import shutil

            shutil.copy(
                os.path.join(base, "node0", "config", "genesis.json"),
                os.path.join(home, "config", "genesis.json"),
            )
            cfg.save()
        homes.append(home)
        out[spec["name"]] = {
            "home": home,
            "p2p_port": p2p_ports[i],
            "rpc_port": rpc_ports[i],
            "mode": spec["mode"],
            "perturb": spec["perturb"],
        }

    ids = [
        NodeKey.load_or_generate(
            os.path.join(h, "config", "node_key.json")
        ).id
        for h in homes
    ]
    for i, spec in enumerate(nodes):
        cfg = Config.load(homes[i])
        cfg.root_dir = homes[i]
        cfg.p2p.laddr = f"tcp://127.0.0.1:{p2p_ports[i]}"
        cfg.rpc.laddr = f"tcp://127.0.0.1:{rpc_ports[i]}"
        cfg.p2p.send_rate = spec.get("send_rate", 0)
        peers = peer_indices(manifest["topology"], i, n)
        cfg.p2p.persistent_peers = ",".join(
            f"{ids[j]}@127.0.0.1:{p2p_ports[j]}" for j in peers
        )
        cfg.save()
    return out


def main(argv) -> int:
    if len(argv) < 2:
        print(__doc__)
        return 1
    seed = int(argv[1])
    manifest = generate_manifest(seed)
    print(json.dumps(manifest, indent=2))
    if len(argv) > 2:
        import socket

        def free_ports(k):
            socks, ports = [], []
            for _ in range(k):
                s = socket.socket()
                s.bind(("127.0.0.1", 0))
                socks.append(s)
                ports.append(s.getsockname()[1])
            for s in socks:
                s.close()
            return ports

        layout = materialize(manifest, argv[2], free_ports)
        print(json.dumps(layout, indent=2))
    return 0


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    sys.exit(main(sys.argv))
