"""Randomized testnet manifest generator (reference test/e2e/generator).

Produces seeded, deterministic testnet manifests — validator count,
full-node count, peer topology, per-node knobs, and a perturbation
schedule — and materializes them into runnable node homes using the same
`testnet` scaffolding the fixed mp-e2e scenarios use. The e2e runner
(tests/test_e2e_generator.py) picks a seed, boots the manifest across
real processes, applies the perturbations, and asserts liveness +
agreement, so every CI run exercises a (deterministically) different
topology.

Usage:
    python tools/testnet_generator.py SEED [OUTDIR]
        [--validators N] [--power-dist {equal,zipf}]
prints the manifest; with OUTDIR it also materializes the homes.

Committee-scale configs are one command (`--validators 150
--power-dist zipf`): the validator count overrides the random
quorum-friendly default, powers follow the chosen distribution (zipf =
rank-k power ~ 1000/k, the weighted-committee shape), topology switches
to ring past the full-mesh knee, and materialization patches every
node's genesis with the per-validator powers.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import subprocess
import sys

TOPOLOGIES = ("mesh", "ring", "star")
PERTURBATIONS = ("none", "kill_restart")
POWER_DISTS = ("equal", "zipf")

# past this validator count a generated manifest defaults to the ring
# topology: full-mesh persistent-peer lists grow O(n) per node and
# O(n^2) connections across the net
FULL_MESH_MAX_VALIDATORS = 16


def power_for(dist: str, rank: int, base: int = 1000) -> int:
    """Voting power of the rank-th validator under `dist` (min 1)."""
    if dist == "equal":
        return base
    if dist == "zipf":
        return max(1, base // (rank + 1))
    raise ValueError(f"unknown power dist {dist!r}")


def generate_manifest(
    seed: int,
    n_validators: int | None = None,
    power_dist: str = "equal",
) -> dict:
    """Deterministic manifest for `seed` (same seed + args -> same
    manifest). `n_validators` overrides the random quorum-friendly
    count; `power_dist` assigns per-validator voting powers."""
    if power_dist not in POWER_DISTS:
        raise ValueError(f"unknown power dist {power_dist!r}")
    rng = random.Random(seed)
    explicit_n = n_validators is not None
    if not explicit_n:
        n_validators = rng.choice((4, 4, 5))  # quorum-friendly sizes
    if n_validators < 1:
        raise ValueError("need at least one validator")
    n_fulls = 0 if explicit_n else rng.randint(0, 2)
    if explicit_n and n_validators > FULL_MESH_MAX_VALIDATORS:
        topology = "ring"
    else:
        topology = rng.choice(TOPOLOGIES)
    nodes = []
    for i in range(n_validators):
        nodes.append(
            {
                "name": f"validator{i:02d}",
                "mode": "validator",
                # at most one perturbed validator: BFT tolerates f=1 of 4
                "perturb": "none",
                "send_rate": rng.choice((0, 5120000)),
                "power": power_for(power_dist, i),
            }
        )
    victim = rng.randrange(n_validators)
    nodes[victim]["perturb"] = rng.choice(PERTURBATIONS)
    for i in range(n_fulls):
        nodes.append(
            {
                "name": f"full{i:02d}",
                "mode": "full",
                "perturb": "none",
                "send_rate": 0,
            }
        )
    return {
        "seed": seed,
        "topology": topology,
        "power_dist": power_dist,
        "initial_height_target": 3,
        "nodes": nodes,
    }


def peer_indices(topology: str, i: int, n: int) -> list[int]:
    """Which nodes index i lists as persistent peers."""
    if topology == "mesh":
        return [j for j in range(n) if j != i]
    if topology == "ring":
        return [(i + 1) % n, (i - 1) % n] if n > 2 else [1 - i]
    if topology == "star":
        return [0] if i != 0 else list(range(1, n))
    raise ValueError(f"unknown topology {topology!r}")


def materialize(
    manifest: dict, base: str, free_ports, verify_service: str = "",
    quorum_certificates: bool = False,
) -> dict:
    """Create node homes for the manifest. `free_ports(n)` supplies
    distinct free localhost ports. `verify_service` (a UDS path) stamps
    `[scheduler] remote_socket` across every home, so the whole
    generated net submits its verify work to one shared device-owning
    service process (`python -m tendermint_tpu verify-service --socket
    <path>`). Returns {name: {home, rpc_port, p2p_port, perturb,
    mode}}."""
    from tendermint_tpu.config import Config
    from tendermint_tpu.p2p.key import NodeKey

    nodes = manifest["nodes"]
    validators = [n for n in nodes if n["mode"] == "validator"]
    chain_id = f"gen-{manifest['seed']}"
    rc = subprocess.run(
        [
            sys.executable,
            "-m",
            "tendermint_tpu",
            "testnet",
            "--v",
            str(len(validators)),
            "--output",
            base,
            "--chain-id",
            chain_id,
        ],
        capture_output=True,
        timeout=120,
    )
    if rc.returncode != 0:
        raise RuntimeError(f"testnet scaffold failed: {rc.stderr.decode()}")

    n = len(nodes)
    ports = free_ports(2 * n)
    p2p_ports, rpc_ports = ports[:n], ports[n:]
    out = {}
    homes = []
    for i, spec in enumerate(nodes):
        if spec["mode"] == "validator":
            home = os.path.join(base, f"node{len(homes)}")
        else:
            # full node: fresh home + the shared genesis, own keys
            home = os.path.join(base, spec["name"])
            cfg = Config()
            cfg.root_dir = home
            cfg.ensure_dirs()
            import shutil

            shutil.copy(
                os.path.join(base, "node0", "config", "genesis.json"),
                os.path.join(home, "config", "genesis.json"),
            )
            cfg.save()
        homes.append(home)
        out[spec["name"]] = {
            "home": home,
            "p2p_port": p2p_ports[i],
            "rpc_port": rpc_ports[i],
            "mode": spec["mode"],
            "perturb": spec["perturb"],
        }

    powers = [n.get("power", 1000) for n in validators]
    if len(set(powers)) > 1:
        _patch_genesis_powers(homes, powers)
    if quorum_certificates:
        _stamp_qc_keys(homes, len(validators))

    ids = [
        NodeKey.load_or_generate(
            os.path.join(h, "config", "node_key.json")
        ).id
        for h in homes
    ]
    for i, spec in enumerate(nodes):
        cfg = Config.load(homes[i])
        cfg.root_dir = homes[i]
        cfg.p2p.laddr = f"tcp://127.0.0.1:{p2p_ports[i]}"
        cfg.rpc.laddr = f"tcp://127.0.0.1:{rpc_ports[i]}"
        cfg.p2p.send_rate = spec.get("send_rate", 0)
        if verify_service:
            # absolute: every home must resolve the SAME socket
            cfg.scheduler.remote_socket = os.path.abspath(verify_service)
        if quorum_certificates:
            cfg.consensus.quorum_certificates = True
        peers = peer_indices(manifest["topology"], i, n)
        cfg.p2p.persistent_peers = ",".join(
            f"{ids[j]}@127.0.0.1:{p2p_ports[j]}" for j in peers
        )
        cfg.save()
    return out


def _stamp_qc_keys(homes: list[str], n_validators: int) -> None:
    """QC-capable net: generate each validator's BLS key file now (the
    node would lazily generate it at first boot anyway) and commit the
    raw G2 public keys into EVERY home's genesis — all homes must
    rewrite the identical doc or the net splits on genesis hash, the
    _patch_genesis_powers rule."""
    from tendermint_tpu.crypto import bls_signatures as bls

    raw_keys = []
    for i in range(n_validators):
        key = bls.load_or_gen_bls_key(
            os.path.join(homes[i], "config", "bls_key.json")
        )
        pub = bls.public_key_from_bytes(key.pub_key, trusted_source=True)
        raw_keys.append(bls.g2_to_bytes(pub.key).hex())
    for home in homes:
        path = os.path.join(home, "config", "genesis.json")
        with open(path) as f:
            doc = json.load(f)
        vals = doc.get("validators", [])
        if len(vals) != n_validators:
            raise SystemExit(
                f"genesis has {len(vals)} validators, expected "
                f"{n_validators}"
            )
        for v, raw in zip(vals, raw_keys):
            v["bls_pub_key"] = raw
        with open(path, "w") as f:
            json.dump(doc, f, indent=2)


def _patch_genesis_powers(homes: list[str], powers: list[int]) -> None:
    """Rewrite every home's genesis.json with per-validator powers
    (position i in the genesis validator list gets powers[i] — the
    scaffold writes validators in creation order). All homes must carry
    the IDENTICAL doc or the nets split on genesis hash."""
    for home in homes:
        path = os.path.join(home, "config", "genesis.json")
        with open(path) as f:
            doc = json.load(f)
        vals = doc.get("validators", [])
        if len(vals) != len(powers):
            raise RuntimeError(
                f"genesis has {len(vals)} validators, manifest has "
                f"{len(powers)} powers"
            )
        for v, p in zip(vals, powers):
            v["power"] = str(p)
        with open(path, "w") as f:
            json.dump(doc, f, indent=1)


def main(argv) -> int:
    ap = argparse.ArgumentParser(
        description="randomized testnet manifest generator"
    )
    ap.add_argument("seed", type=int, help="manifest seed")
    ap.add_argument(
        "outdir", nargs="?", default="", help="materialize node homes here"
    )
    ap.add_argument(
        "--validators",
        type=int,
        default=0,
        help="validator count (0 = random quorum-friendly default); "
        "large committees (e.g. 150) switch to the ring topology",
    )
    ap.add_argument(
        "--power-dist",
        choices=POWER_DISTS,
        default="equal",
        help="voting-power distribution across the committee",
    )
    ap.add_argument(
        "--verify-service",
        default="",
        metavar="SOCKET",
        help="stamp [scheduler] remote_socket = SOCKET across every "
        "generated home: the whole net verifies through one shared "
        "verify-service process (python -m tendermint_tpu "
        "verify-service --socket SOCKET)",
    )
    ap.add_argument(
        "--qc",
        action="store_true",
        help="QC-capable net: generate per-validator BLS keys, commit "
        "them into every genesis (bls_pub_key), and stamp [consensus] "
        "quorum_certificates = true across the homes — commits then "
        "carry one aggregate certificate next to the full commit",
    )
    args = ap.parse_args(argv[1:])
    manifest = generate_manifest(
        args.seed,
        n_validators=args.validators or None,
        power_dist=args.power_dist,
    )
    print(json.dumps(manifest, indent=2))
    if args.outdir:
        import socket

        def free_ports(k):
            socks, ports = [], []
            for _ in range(k):
                s = socket.socket()
                s.bind(("127.0.0.1", 0))
                socks.append(s)
                ports.append(s.getsockname()[1])
            for s in socks:
                s.close()
            return ports

        layout = materialize(
            manifest,
            args.outdir,
            free_ports,
            verify_service=args.verify_service,
            quorum_certificates=args.qc,
        )
        print(json.dumps(layout, indent=2))
    return 0


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    sys.exit(main(sys.argv))
