"""Remote-signer conformance harness.

Reference: tools/tm-signer-harness (test_harness.go) — connects a real
remote signer to a listener endpoint and drives the conformance checks:
1. the signer reports a pubkey matching the expected validator key,
2. it signs a proposal and a vote correctly,
3. it REFUSES to double-sign (same HRS, different block),
4. it re-signs the identical payload idempotently,
5. ping keeps the connection alive.

Usage (in-proc demo): python tools/signer_harness.py
Against an external signer: python tools/signer_harness.py --listen PORT
(then point the signer at 127.0.0.1:PORT).
"""

import argparse
import asyncio
import sys

sys.path.insert(0, ".")

from tendermint_tpu.privval.signer import (  # noqa: E402
    RemoteSignerError,
    SignerClient,
    SignerListenerEndpoint,
    SignerServer,
)
from tendermint_tpu.types.block_id import BlockID  # noqa: E402
from tendermint_tpu.types.part_set import PartSetHeader  # noqa: E402
from tendermint_tpu.types.proposal import Proposal  # noqa: E402
from tendermint_tpu.types.vote import Vote, VoteType  # noqa: E402

CHAIN_ID = "harness-chain"


async def run_harness(endpoint: SignerListenerEndpoint, expected_pub=None):
    client = SignerClient(endpoint)
    passed = 0

    pub = await client.get_pub_key()
    assert pub is not None and len(pub.data) == 32, "bad pubkey"
    if expected_pub is not None:
        assert pub.data == expected_pub.data, "pubkey mismatch"
    print(f"1. pubkey ok: {pub.data.hex()[:16]}…")
    passed += 1

    bid = BlockID(b"\x01" * 32, PartSetHeader(1, b"\x01" * 32))
    prop = Proposal(height=10, round=0, pol_round=-1, block_id=bid,
                    timestamp_ns=1)
    await client.sign_proposal(CHAIN_ID, prop)
    assert prop.signature and pub.verify(
        prop.sign_bytes(CHAIN_ID), prop.signature
    ), "proposal signature invalid"
    print("2. proposal signing ok")
    passed += 1

    vote = Vote(
        type=VoteType.PRECOMMIT, height=10, round=0, block_id=bid,
        timestamp_ns=2, validator_address=pub.address(), validator_index=0,
    )
    await client.sign_vote(CHAIN_ID, vote)
    assert vote.signature and pub.verify(
        vote.sign_bytes(CHAIN_ID), vote.signature
    ), "vote signature invalid"
    print("3. vote signing ok")
    passed += 1

    conflicting = Vote(
        type=VoteType.PRECOMMIT, height=10, round=0,
        block_id=BlockID(b"\x02" * 32, PartSetHeader(1, b"\x02" * 32)),
        timestamp_ns=2, validator_address=pub.address(), validator_index=0,
    )
    try:
        await client.sign_vote(CHAIN_ID, conflicting)
        raise AssertionError("signer double-signed!")
    except RemoteSignerError:
        print("4. double-sign refused ok")
        passed += 1

    same = Vote(
        type=VoteType.PRECOMMIT, height=10, round=0, block_id=bid,
        timestamp_ns=2, validator_address=pub.address(), validator_index=0,
    )
    await client.sign_vote(CHAIN_ID, same)
    assert same.signature == vote.signature, "idempotent re-sign differs"
    print("5. idempotent re-sign ok")
    passed += 1

    assert await client.ping(), "ping failed"
    print("6. ping ok")
    passed += 1
    return passed


async def main_inproc():
    """Demo: harness against our own FilePV-backed SignerServer."""
    import tempfile

    from tendermint_tpu.privval.file_pv import FilePV

    with tempfile.TemporaryDirectory() as d:
        pv = FilePV.generate(f"{d}/key.json", f"{d}/state.json")
        endpoint = SignerListenerEndpoint()
        await endpoint.start()
        server = SignerServer(pv, "127.0.0.1", endpoint.port)
        await server.start()
        await endpoint.wait_for_signer()
        n = await run_harness(endpoint, expected_pub=pv.get_pub_key())
        await server.stop()
        await endpoint.stop()
        print(f"PASSED {n}/6 conformance checks")


async def main_listen(port: int):
    endpoint = SignerListenerEndpoint(port=port)
    await endpoint.start()
    print(f"listening for a remote signer on 127.0.0.1:{endpoint.port}…")
    await endpoint.wait_for_signer(timeout=120)
    n = await run_harness(endpoint)
    await endpoint.stop()
    print(f"PASSED {n}/6 conformance checks")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--listen", type=int, default=0,
                    help="wait for an external signer on this port")
    args = ap.parse_args()
    if args.listen:
        asyncio.run(main_listen(args.listen))
    else:
        asyncio.run(main_inproc())
