"""Bench-trajectory trend + regression gate over BENCH_r*/MULTICHIP_r*.

Every PR leaves a BENCH_rNN.json (and sometimes MULTICHIP_rNN.json)
artifact, but nothing compared them across runs: the r04–r06 "silent
perf regression" (a TPU-tunnel outage quietly turning 77k sigs/s rows
into 2k sigs/s CPU-fallback rows) was only found by archaeology. This
tool makes the trajectory first-class:

- **Ingest** every artifact round, normalizing the three historical
  shapes (wrapped `{parsed: ...}` rows from r01–r04, direct metric
  dicts from r05+, structured backend-mismatch failures from r07+) into
  flat rows; failure artifacts are recorded as skips, never as values.

- **Backend partition**: rows group by (family, metric, backend,
  device_count) and are ONLY ever compared within a group. Backend
  comes from the PR 6 `meta` stamp when present; pre-meta artifacts
  fall back to the top-level `backend` field, then to the capture tail
  (the r01–r03 tails name the accelerator platform), then to "cpu" —
  the honest default for this harness, where every unlabeled post-r04
  row WAS a CPU row. An honest CPU row can therefore never flag
  against the r02/r03 TPU captures, and a TPU recapture never
  "improves on" CPU numbers.

- **Gate** (`--check`): exit non-zero when any tier-1 family's
  HEADLINE metric (the artifact's top-level row) regressed more than
  `--threshold` (15% default) against the best-known value on the same
  backend/device-count. Regressions in `extra_metrics` rows are
  reported as warnings (they fail only under `--strict`) — the
  checked-in history contains honest host-noise swings there
  (e.g. ed25519_commit10k_latency r05→r06: +26% on an unrelated-PR
  rerun), and a gate that cries wolf gets deleted.

- **Conservation** (PR 15): artifacts carrying a `wall_conservation`
  block are schema-validated — buckets must sum to the measured wall
  per height (obs.report.check_conservation) or the artifact's rows
  are rejected outright — and `--check` additionally fails when the
  LATEST artifact's aggregate dark_time fraction exceeds
  `--dark-threshold` (0.05 default): wall time with no instrumented
  owner is a regression in the attribution plane itself.

- **Render**: TREND.md (per-family tables: best/latest/delta with the
  round each came from) + machine-readable TREND.json.

Usage:
    python tools/bench_trend.py                       # print TREND.md
    python tools/bench_trend.py --write               # write TREND.{md,json}
    python tools/bench_trend.py --check               # CI gate
    python tools/bench_trend.py --check extra_r99.json  # + synthetic rows
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

# stdlib-only import (obs/ carries no deps): the one conservation-check
# implementation bench.py stamps with and this gate validates with —
# a local copy would drift from the bucket list
from tendermint_tpu.obs.report import check_conservation  # noqa: E402

# --- metric classification --------------------------------------------------

# ordered prefix -> family (first match wins; longer prefixes first)
_FAMILY_PREFIXES = (
    ("verify_service", "verify_service"),
    ("scheduler_", "scheduler"),
    ("consensus_pipeline", "consensus_pipeline"),
    ("consensus_pacing", "consensus_pacing"),
    ("consensus_", "consensus"),
    ("lightserve", "lightserve"),
    ("light_", "light"),
    ("committee", "committee_scale"),
    ("sequencer", "sequencer_stream"),
    ("commit_", "commit_path"),
    ("wal_", "commit_path"),
    # QC round compression (PR 14): headline blocksync_commits_per_s@N
    # must classify under qc_catchup, so this prefix outranks the plain
    # blocksync family below
    ("blocksync_commits_per_s", "qc_catchup"),
    ("qc_", "qc_catchup"),
    ("blocksync", "blocksync"),
    ("quorum_", "consensus"),
    ("vote_latency", "crypto"),
    ("ed25519", "crypto"),
    ("bls_", "crypto"),
    ("sr25519", "crypto"),
    ("secp256k1", "crypto"),
    ("sha256", "crypto"),
    ("multichip", "multichip"),
)

# families whose headline rows gate CI (--check); the rest are
# informational trend lines
TIER1_FAMILIES = frozenset(
    {
        "crypto",
        # QC-chained height pipelining (PERF_ANALYSIS §22): headline is
        # effective wall-per-height with overlapped consecutive heights;
        # its conservation block books buckets > wall only by the
        # explicit pipeline_overlap_ms credit (obs.check_conservation)
        "consensus_pipeline",
        "consensus_pacing",
        "consensus",
        "lightserve",
        "light",
        "committee_scale",
        "sequencer_stream",
        # the split-brain verify plane (PR 13): headline is
        # wall-per-height at 32 validators with real crypto over IPC
        "verify_service",
        # QC round compression (PR 14): headline is
        # blocksync_commits_per_s@100 (direction higher) — a QC
        # regression gates like every other plane
        "qc_catchup",
        "commit_path",
        "blocksync",
        "multichip",
        # the device_cost fill/padding rows (never headline, so this
        # only makes them warn-level / --strict-promotable instead of
        # purely informational)
        "scheduler",
    }
)

# metric-name tokens that mean lower-is-better; everything else
# defaults to higher-is-better (throughputs, rates, reductions)
_LOWER_TOKENS = (
    "latency",
    "_ms",
    "wall",
    "_lag",
    "fsync",
    "floor_share",
    "wait",
    "critical_path",
    "_ticks",
    "per_key",
    "encodes_per",
    "_behind",
)

# oddballs the token heuristic can't classify from the name alone
_DIRECTION_OVERRIDES = {
    "bls_aggregate_verify_1k": "lower",  # ms for a 1k-signer aggregate
    "light_bisection_1k": "higher",  # sigs/s on the 1k-validator chain
    # padding fraction of dispatched rows (device_cost block): waste
    "scheduler_padding_fraction": "lower",
}


def family_of(metric: str) -> str:
    for prefix, fam in _FAMILY_PREFIXES:
        if metric.startswith(prefix):
            return fam
    return metric.split("_", 1)[0] or "other"


def direction_of(metric: str, unit: str = "") -> str:
    """'higher' or 'lower' (is better)."""
    ov = _DIRECTION_OVERRIDES.get(metric)
    if ov:
        return ov
    if any(tok in metric for tok in _LOWER_TOKENS):
        return "lower"
    u = (unit or "").strip().lower()
    if u.startswith("ms") or u == "s" or u.startswith("s for"):
        return "lower"
    return "higher"


# --- artifact normalization -------------------------------------------------

_ROUND_RE = re.compile(r"_r(\d+)\.json$")


def _round_of(path: str, fallback: int) -> int:
    m = _ROUND_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else fallback


def _infer_backend(doc: dict, payload: dict) -> str:
    """meta stamp > explicit backend field > capture-tail platform name
    > 'cpu' (the honest default: every unlabeled row this harness ever
    produced was a CPU row; the real-silicon captures name their
    platform in the tail)."""
    for d in (payload, doc):
        meta = d.get("meta")
        if isinstance(meta, dict) and meta.get("backend"):
            return str(meta["backend"])
    for d in (payload, doc):
        b = d.get("backend")
        if isinstance(b, str) and b:
            return b
    tail = str(doc.get("tail", ""))
    if "Platform 'axon'" in tail or "platform 'tpu'" in tail.lower():
        return "tpu"
    return "cpu"


def _device_count(doc: dict, payload: dict) -> int:
    for d in (payload, doc):
        meta = d.get("meta")
        if isinstance(meta, dict) and meta.get("device_count"):
            return int(meta["device_count"])
    if doc.get("n_devices"):
        return int(doc["n_devices"])
    return 1


def _ledger_rows(payload: dict) -> list[dict]:
    """Synthesized extra-metric rows from a PR 12 `device_cost` block:
    fill-efficiency percentiles + the padding fraction, warn-level like
    every other extra metric (`--strict` promotes). Only emitted when
    the family actually drove scheduler rounds — a zero-round block
    would land fill 0.0 and cry regression forever."""
    dc = payload.get("device_cost")
    if not isinstance(dc, dict):
        return []
    # guard on SIG rounds: fn-lane rounds carry no bucket fill, so a
    # span of only fn rounds would stamp fill 0.0 and cry regression
    # against any prior real fill forever
    if not (dc.get("rounds", 0) - dc.get("fn_rounds", 0)):
        return []
    rows = [
        {
            "metric": "scheduler_fill_ratio_p50",
            "value": dc.get("fill_ratio_p50"),
            "unit": "rows-requested/rows-dispatched per round, p50",
        },
        {
            "metric": "scheduler_fill_ratio_p95",
            "value": dc.get("fill_ratio_p95"),
            "unit": "rows-requested/rows-dispatched per round, p95",
        },
    ]
    disp = dc.get("rows_dispatched") or 0
    if disp:
        rows.append(
            {
                "metric": "scheduler_padding_fraction",
                "value": round(dc.get("padding_rows", 0) / disp, 4),
                "unit": "padding rows / dispatched rows",
            }
        )
    return [r for r in rows if r["value"] is not None]


def _metric_rows(payload: dict) -> list[tuple[dict, bool]]:
    """(row_dict, is_headline) pairs from one normalized payload."""
    rows = []
    if payload.get("metric") is not None and payload.get("value") is not None:
        rows.append((payload, True))
    for e in (payload.get("extra_metrics") or []) + _ledger_rows(payload):
        if (
            isinstance(e, dict)
            and e.get("metric") is not None
            and e.get("value") is not None
        ):
            rows.append((e, False))
    # multichip per-device-count series (PR 6 capture format)
    for e in payload.get("series") or []:
        if (
            isinstance(e, dict)
            and e.get("metric") is not None
            and e.get("value") is not None
        ):
            rows.append((e, True))
    return rows


def _conservation_of(payload: dict, name: str):
    """(dark_row, violation) from a payload's `wall_conservation`
    block (PR 15). Artifacts without the block — everything before
    r14 — return (None, None): the audit is only enforced where the
    bench claimed to have run it. A block whose buckets do NOT sum to
    the measured wall is a schema violation: the artifact's rows are
    rejected outright (a row whose own attribution doesn't reconcile
    cannot be trusted as a measurement)."""
    block = payload.get("wall_conservation")
    if block is None:
        return None, None
    errs = check_conservation(block)
    if errs:
        return None, f"conservation violation: {'; '.join(errs[:3])}"
    agg = (block.get("aggregate") or {}) if isinstance(block, dict) else {}
    if not agg:
        return None, None
    return (
        {
            "file": name,
            "dark_fraction": float(agg.get("dark_fraction", 0.0)),
            "dark_fraction_max": float(
                agg.get("dark_fraction_max", 0.0)
            ),
            "n_heights": int(agg.get("n_heights", 0)),
        },
        None,
    )


def ingest(
    paths: list[str],
) -> tuple[list[dict], list[dict], list[dict]]:
    """Normalize artifacts into (rows, skipped, conservation)."""
    rows: list[dict] = []
    skipped: list[dict] = []
    conservation: list[dict] = []
    for i, path in enumerate(paths):
        name = os.path.basename(path)
        rnd = _round_of(path, fallback=1000 + i)
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            skipped.append({"file": name, "reason": f"unreadable: {e}"})
            continue
        if not isinstance(doc, dict):
            skipped.append({"file": name, "reason": "not an object"})
            continue
        payload = doc
        if "parsed" in doc:  # r01–r04 wrapped shape
            payload = doc["parsed"]
            if not isinstance(payload, dict) or doc.get("rc"):
                skipped.append(
                    {
                        "file": name,
                        "reason": f"failed run (rc={doc.get('rc')})",
                    }
                )
                continue
        if payload.get("kind") == "backend_mismatch" or (
            payload.get("error") and payload.get("metric") is None
        ):
            skipped.append(
                {
                    "file": name,
                    "reason": (
                        f"structured failure: "
                        f"{payload.get('kind') or payload.get('error')}"
                    ),
                }
            )
            continue
        dark_row, violation = _conservation_of(payload, name)
        if violation:
            skipped.append({"file": name, "reason": violation})
            continue
        if dark_row is not None:
            dark_row["round"] = rnd
            conservation.append(dark_row)
        pairs = _metric_rows(payload)
        if not pairs:
            skipped.append(
                {"file": name, "reason": "no metric rows (dryrun/capture)"}
            )
            continue
        backend = _infer_backend(doc, payload)
        devices = _device_count(doc, payload)
        for row, headline in pairs:
            metric = str(row["metric"])
            try:
                value = float(row["value"])
            except (TypeError, ValueError):
                continue
            meta = row.get("meta")
            rows.append(
                {
                    "file": name,
                    "round": rnd,
                    "metric": metric,
                    "value": value,
                    "unit": row.get("unit", ""),
                    "family": family_of(metric),
                    "direction": direction_of(metric, row.get("unit", "")),
                    "backend": (
                        str(meta["backend"])
                        if isinstance(meta, dict) and meta.get("backend")
                        else backend
                    ),
                    "devices": (
                        int(row["devices"])
                        if row.get("devices")
                        else devices
                    ),
                    "headline": headline,
                }
            )
    return rows, skipped, conservation


# --- trajectory + gate ------------------------------------------------------


def build_groups(rows: list[dict]) -> list[dict]:
    """Group rows by (family, metric, backend, devices); compute
    best-known / latest / regression."""
    by_key: dict[tuple, list[dict]] = {}
    for r in rows:
        by_key.setdefault(
            (r["family"], r["metric"], r["backend"], r["devices"]), []
        ).append(r)
    groups = []
    for (fam, metric, backend, devices), rs in sorted(by_key.items()):
        rs = sorted(rs, key=lambda r: r["round"])
        latest = rs[-1]
        direction = latest["direction"]
        if direction == "higher":
            best = max(rs, key=lambda r: r["value"])
            reg = (
                (best["value"] - latest["value"]) / best["value"]
                if best["value"]
                else 0.0
            )
        else:
            best = min(rs, key=lambda r: r["value"])
            reg = (
                (latest["value"] - best["value"]) / best["value"]
                if best["value"]
                else 0.0
            )
        groups.append(
            {
                "family": fam,
                "metric": metric,
                "backend": backend,
                "devices": devices,
                "direction": direction,
                "n_rows": len(rs),
                "best": best["value"],
                "best_round": best["round"],
                "latest": latest["value"],
                "latest_round": latest["round"],
                "headline": latest["headline"],
                # positive = latest is worse than best-known
                "regression": round(max(0.0, reg), 4),
            }
        )
    return groups


def check_gate(
    groups: list[dict], threshold: float, strict: bool = False
) -> tuple[list[dict], list[dict]]:
    """(failures, warnings): tier-1 headline regressions past the
    threshold fail; extra-metric regressions warn (fail iff strict)."""
    failures, warnings = [], []
    for g in groups:
        if g["regression"] <= threshold:
            continue
        if g["n_rows"] < 2:
            continue  # a single capture cannot regress against itself
        if g["family"] in TIER1_FAMILIES and g["headline"]:
            failures.append(g)
        elif g["family"] in TIER1_FAMILIES:
            (failures if strict else warnings).append(g)
    return failures, warnings


def check_dark(
    conservation: list[dict], threshold: float
) -> list[dict]:
    """Absolute dark-time gate: the LATEST round carrying a
    conservation block must keep its aggregate dark fraction under
    `threshold` — wall time with no instrumented owner is a regression
    in the attribution plane itself, regardless of how fast the run
    was. (Not a vs-best comparison: dark near zero is the steady state,
    and judging noise around zero in relative terms would cry wolf.)"""
    if not conservation:
        return []
    latest = max(conservation, key=lambda c: c["round"])
    if latest["dark_fraction"] > threshold:
        return [dict(latest, threshold=threshold)]
    return []


# --- rendering --------------------------------------------------------------


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return f"{v:.4g}" if abs(v) < 1 else f"{v:,.1f}"


def render_md(
    groups: list[dict],
    skipped: list[dict],
    files: list[str],
    threshold: float,
) -> str:
    lines = [
        "# Bench trajectory (tools/bench_trend.py)",
        "",
        f"Ingested {len(files)} artifacts; rows compare ONLY within "
        "their (family, metric, backend, devices) group — CPU rows "
        "never judge TPU captures or vice versa. `Δbest` is how far "
        "the latest capture sits from the best-known on the same "
        f"backend (gate threshold {threshold:.0%} on tier-1 headline "
        "rows).",
        "",
    ]
    by_family: dict[str, list[dict]] = {}
    for g in groups:
        by_family.setdefault(g["family"], []).append(g)
    for fam in sorted(by_family):
        gs = by_family[fam]
        tier = "tier-1" if fam in TIER1_FAMILIES else "info"
        lines.append(f"## {fam} ({tier})")
        lines.append("")
        lines.append(
            "| metric | backend | dev | dir | best (r) | latest (r) "
            "| Δbest |"
        )
        lines.append("|---|---|---|---|---|---|---|")
        for g in gs:
            delta = (
                f"**-{g['regression']:.1%}**"
                if g["regression"] > threshold and g["n_rows"] > 1
                else (
                    f"-{g['regression']:.1%}"
                    if g["regression"] > 0
                    else "="
                )
            )
            mark = "" if g["headline"] else " *(extra)*"
            lines.append(
                f"| {g['metric']}{mark} | {g['backend']} | "
                f"{g['devices']} | {g['direction']} | "
                f"{_fmt(g['best'])} (r{g['best_round']:02d}) | "
                f"{_fmt(g['latest'])} (r{g['latest_round']:02d}) | "
                f"{delta} |"
            )
        lines.append("")
    if skipped:
        lines.append("## Skipped artifacts")
        lines.append("")
        for s in skipped:
            lines.append(f"- `{s['file']}`: {s['reason']}")
        lines.append("")
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(
        description="bench-artifact trajectory + backend-partitioned "
        "regression gate"
    )
    ap.add_argument(
        "files",
        nargs="*",
        help="extra artifact files appended to the --dir scan "
        "(synthetic rows, out-of-tree captures)",
    )
    ap.add_argument(
        "--dir",
        default=REPO_ROOT,
        help="directory scanned for BENCH_r*.json / MULTICHIP_r*.json "
        "(default: repo root)",
    )
    ap.add_argument(
        "--no-scan",
        action="store_true",
        help="ingest ONLY the positional files (skip the --dir scan)",
    )
    ap.add_argument(
        "--threshold",
        type=float,
        default=0.15,
        help="regression fraction that fails --check (default 0.15)",
    )
    ap.add_argument(
        "--check",
        action="store_true",
        help="exit non-zero on tier-1 headline regressions past the "
        "threshold",
    )
    ap.add_argument(
        "--strict",
        action="store_true",
        help="--check also fails on extra-metric regressions",
    )
    ap.add_argument(
        "--dark-threshold",
        type=float,
        default=0.05,
        help="max aggregate dark_time fraction the latest artifact's "
        "wall_conservation block may carry under --check "
        "(default 0.05)",
    )
    ap.add_argument(
        "--write",
        action="store_true",
        help="write TREND.md + TREND.json into --dir",
    )
    ap.add_argument("--json", action="store_true", help="print TREND.json")
    args = ap.parse_args()

    files: list[str] = []
    if not args.no_scan:
        files += sorted(glob.glob(os.path.join(args.dir, "BENCH_r*.json")))
        files += sorted(
            glob.glob(os.path.join(args.dir, "MULTICHIP_r*.json"))
        )
    files += args.files
    if not files:
        print("no artifacts found", file=sys.stderr)
        return 2

    rows, skipped, conservation = ingest(files)
    groups = build_groups(rows)
    failures, warnings = check_gate(
        groups, args.threshold, strict=args.strict
    )
    dark_failures = check_dark(conservation, args.dark_threshold)
    doc = {
        "schema": "tm-tpu/bench-trend/v1",
        "threshold": args.threshold,
        "files": [os.path.basename(f) for f in files],
        "rows": rows,
        "groups": groups,
        "skipped": skipped,
        "conservation": {
            "dark_threshold": args.dark_threshold,
            "blocks": conservation,
            "failures": dark_failures,
        },
        "check": {
            "failures": failures,
            "warnings": warnings,
            "dark_failures": dark_failures,
            "ok": not failures and not dark_failures,
        },
    }
    md = render_md(groups, skipped, files, args.threshold)

    if args.write:
        with open(os.path.join(args.dir, "TREND.md"), "w") as f:
            f.write(md + "\n")
        with open(os.path.join(args.dir, "TREND.json"), "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(
            f"wrote {os.path.join(args.dir, 'TREND.md')} and TREND.json",
            file=sys.stderr,
        )
    elif args.json:
        print(json.dumps(doc, indent=1, sort_keys=True))
    else:
        print(md)

    for w in warnings:
        print(
            f"# WARN extra-metric regression: {w['metric']} "
            f"[{w['backend']} x{w['devices']}] best {_fmt(w['best'])} "
            f"(r{w['best_round']:02d}) -> latest {_fmt(w['latest'])} "
            f"(r{w['latest_round']:02d}), -{w['regression']:.1%}",
            file=sys.stderr,
        )
    if args.check:
        if dark_failures:
            for d in dark_failures:
                print(
                    f"# FAIL dark-time gate: {d['file']} "
                    f"dark_fraction {d['dark_fraction']:.3f} > "
                    f"{args.dark_threshold:.3f} over {d['n_heights']} "
                    f"heights (worst height "
                    f"{d['dark_fraction_max']:.3f}) — wall time with "
                    "no instrumented owner",
                    file=sys.stderr,
                )
            if not failures:
                return 1
        if failures:
            for g in failures:
                print(
                    f"# FAIL tier-1 regression: {g['metric']} "
                    f"[{g['backend']} x{g['devices']}] best "
                    f"{_fmt(g['best'])} (r{g['best_round']:02d}) -> "
                    f"latest {_fmt(g['latest'])} "
                    f"(r{g['latest_round']:02d}), -{g['regression']:.1%} "
                    f"> {args.threshold:.0%}",
                    file=sys.stderr,
                )
            return 1
        print(
            f"# bench-trend check ok: {len(groups)} metric groups, "
            f"{len(warnings)} extra-metric warnings, 0 tier-1 headline "
            f"regressions",
            file=sys.stderr,
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
