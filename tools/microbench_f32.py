"""f32 vs int32 field arithmetic on TPU: 256 point-doublings, exact math.

The int32 path (current ops/field25519) showed 0.57 ms/doubling at B=8192
— suspected int32-multiply emulation on the VPU. This prototypes the same
radix-2^8 arithmetic in float32 (exact: all intermediates < 2^24) and
times the identical doubling chain, verifying results against the host.
"""

import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

B = int(sys.argv[1]) if len(sys.argv) > 1 else 8192

# --- f32 field ops (radix 2^8, 32 limbs, loose < 2^9) ---------------------

BIAS = np.full(32, 1020.0, dtype=np.float32)
BIAS[0] = 872.0  # 8p bias, same as int path


def carry(x):
    c = jnp.floor(x * (1.0 / 256.0))
    r = x - c * 256.0
    wrap = jnp.concatenate([c[..., 31:] * 38.0, c[..., :31]], axis=-1)
    return r + wrap


def add(a, b):
    return carry(a + b)


def sub(a, b):
    return carry(a + jnp.asarray(BIAS) - b)


def mul(a, b):
    out = jnp.zeros((*a.shape[:-1], 63), dtype=jnp.float32)
    for i in range(32):
        out = out.at[..., i : i + 32].add(a[..., i : i + 1] * b)
    lo, hi = out[..., :32], out[..., 32:]
    # pre-carry hi so hi*38 stays < 2^24-exact when added to lo
    ch = jnp.floor(hi * (1.0 / 256.0))
    rh = hi - ch * 256.0
    hi2 = jnp.concatenate(
        [rh, jnp.zeros((*a.shape[:-1], 1), jnp.float32)], axis=-1
    ) + jnp.concatenate(
        [jnp.zeros((*a.shape[:-1], 1), jnp.float32), ch], axis=-1
    )
    # hi2[k] = rh[k] + ch[k-1] < 2^15.3; fold limb 32+k as 38 * 2^(8k):
    # x < 2^23 + 38*2^15.3 < 2^23.3 — exact in f32
    x = lo + 38.0 * hi2
    x = carry(x)
    x = carry(x)
    x = carry(x)
    return carry(x)


def sqr(x):
    return mul(x, x)


def mul_small(a, k):
    x = a * float(k)
    x = carry(x)
    x = carry(x)
    return carry(x)


def double(p):
    x1, y1, z1 = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    xx = sqr(x1)
    yy = sqr(y1)
    b2 = mul_small(sqr(z1), 2)
    aa = sqr(add(x1, y1))
    y3 = add(yy, xx)
    z3 = sub(yy, xx)
    x3 = sub(aa, y3)
    t3 = sub(b2, z3)
    return jnp.stack(
        [mul(x3, t3), mul(y3, z3), mul(z3, t3), mul(x3, y3)], axis=-2
    )


def main():
    sys.path.insert(0, ".")
    from tendermint_tpu.crypto import ed25519 as host
    from tendermint_tpu.ops import curve25519 as curve

    # build B copies of the basepoint in extended coords
    bp = np.stack(
        [
            np.array([int(b) for b in (c % host.P).to_bytes(32, "little")])
            for c in host.BASEPOINT
        ]
    ).astype(np.float32)
    pts = jnp.asarray(np.broadcast_to(bp, (B, 4, 32)).copy())

    def dbl_n(n):
        def f(p):
            q = jax.lax.fori_loop(0, n, lambda _, v: double(v), p)
            return jnp.sum(q[..., 0, :] * q[..., 1, :], axis=-1)
        return f

    for n in (32, 256):
        fn = jax.jit(dbl_n(n))
        t0 = time.perf_counter()
        out = np.asarray(fn(pts))
        ct = time.perf_counter() - t0
        best = 1e9
        for _ in range(3):
            t0 = time.perf_counter()
            out = np.asarray(fn(pts))
            best = min(best, time.perf_counter() - t0)
        print(f"f32 double x{n:4d}: compile+1st {ct:6.2f}s run {best*1e3:8.2f} ms")

    # correctness: 256 doublings of basepoint == host result
    q = jax.jit(
        lambda p: jax.lax.fori_loop(0, 256, lambda _, v: double(v), p)
    )(pts)
    q = np.asarray(q)[0].astype(np.int64)
    vals = [sum(int(v) << (8 * i) for i, v in enumerate(row)) for row in q]
    hq = host.BASEPOINT
    for _ in range(256):
        hq = host.point_double(hq)
    # compare affine x: X/Z
    got_x = vals[0] * pow(vals[2], host.P - 2, host.P) % host.P
    want_x = hq[0] * pow(hq[2], host.P - 2, host.P) % host.P
    print("correct:", got_x == want_x)


if __name__ == "__main__":
    main()
