"""Executor microbenchmark — the evidence base for PERF_ANALYSIS.md.

Measures the harness accelerator's cost model directly (call overhead,
per-op cost at trivial/realistic widths, sequential tiny-op chains,
batch-size scaling of the generic ed25519 verifier) so that every
below-baseline number in bench.py can be attributed to a measured
executor characteristic rather than asserted away.

Run on an idle box (background load corrupts every number):

    python tools/bench_executor.py            # real chip via axon
    JAX_PLATFORMS=cpu python tools/bench_executor.py   # host XLA

Prints one JSON object; PERF_ANALYSIS.md quotes a stored run.
"""

from __future__ import annotations

import json
import time

import numpy as np


def _best(fn, *args, n=4):
    import jax

    r = fn(*args)
    np.asarray(jax.tree_util.tree_leaves(r)[0][:1])
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        r = fn(*args)
        np.asarray(jax.tree_util.tree_leaves(r)[0][:1])
        best = min(best, time.perf_counter() - t0)
    return best


def main() -> None:
    import jax
    import jax.numpy as jnp

    import tendermint_tpu.ops.field25519 as fe
    from tendermint_tpu.crypto import ed25519 as hosted
    from tendermint_tpu.ops import ed25519_batch as ed

    out: dict = {"platform": jax.devices()[0].platform}

    # 1. fixed per-call overhead: trivial op + result transfer
    triv = jax.jit(lambda x: x + 1)
    out["call_overhead_ms"] = round(
        _best(triv, jnp.zeros((8192, 32), jnp.int32)) * 1e3, 1
    )

    # 2. one packed field multiplication at verifier width
    m = jax.jit(fe.mul)
    a = jnp.ones((8192, 4, 32), jnp.int32)
    out["packed_fe_mul_standalone_ms"] = round(_best(m, a, a) * 1e3, 1)

    # 3. sequential tiny-op chain: single-element Fermat inversion
    #    (~265 dependent [32]-wide muls inside ONE jit); per-op cost is
    #    net of the fixed dispatch overhead measured above
    inv1 = jax.jit(fe.invert)
    x1 = jnp.asarray(fe.from_int(12345678901234567890))
    dt = _best(inv1, x1)
    out["tiny_chain_265_ops_ms"] = round(dt * 1e3, 1)
    net = max(0.0, dt - out["call_overhead_ms"] / 1e3)
    out["tiny_op_in_graph_us"] = round(net / 265 * 1e6, 1)

    # 4. in-graph marginal fe.mul cost (chain lengths 5 vs 50)
    def chain(n):
        def f(x):
            for _ in range(n):
                x = fe.mul(x, x)
            return x

        return jax.jit(f)

    rng = np.random.default_rng(1)
    ar = jnp.asarray(rng.integers(0, 256, (8192, 4, 32)), dtype=jnp.int32)
    t5, t50 = _best(chain(5), ar), _best(chain(50), ar)
    out["marginal_fe_mul_in_graph_ms"] = round((t50 - t5) / 45 * 1e3, 2)

    # 5. per-loop-iteration cost with a table gather: the 64-iteration
    #    window ladder, net of dispatch overhead — what fori_loop bodies
    #    that gather actually pay (the verifier's dominant term)
    from tendermint_tpu.ops import curve25519 as curve

    rng2 = np.random.default_rng(2)
    kb = jnp.asarray(
        rng2.integers(0, 256, (8192, 32)).astype(np.uint8)
    )
    pkb = np.tile(
        np.frombuffer(hosted.PrivKey.generate().public_key().data, np.uint8),
        (8192, 1),
    )
    apt, _ = jax.jit(curve.decompress)(jnp.asarray(pkb))
    tab = jax.jit(curve.window_table)(curve.neg(apt))
    dt = _best(jax.jit(curve.scalar_mult_var_table), kb, tab)
    net = max(0.0, dt - out["call_overhead_ms"] / 1e3)
    out["window_ladder_64iter_net_ms"] = round(net * 1e3, 1)
    out["loop_iter_with_gather_ms"] = round(net / 64 * 1e3, 2)

    # 6. generic verifier batch scaling (linear => volume-bound,
    #    flat => dispatch-bound)
    p1 = hosted.PrivKey.generate().public_key()
    full = jax.jit(ed.verify_prehashed)
    scaling = {}
    for B in (4096, 8192, 16384):
        pk = np.tile(np.frombuffer(p1.data, np.uint8), (B, 1))
        rb = rng.integers(0, 256, (B, 32)).astype(np.uint8)
        sb = rng.integers(0, 128, (B, 32)).astype(np.uint8)
        kb = rng.integers(0, 256, (B, 32)).astype(np.uint8)
        sok = np.ones(B, bool)
        args = tuple(jnp.asarray(v) for v in (pk, rb, sb, kb, sok))
        dt = _best(full, *args, n=3)
        scaling[str(B)] = {
            "ms": round(dt * 1e3, 1),
            "sigs_per_s": round(B / dt),
        }
    out["generic_verify_scaling"] = scaling

    print(json.dumps(out))


if __name__ == "__main__":
    main()
