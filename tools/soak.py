"""Chaos soak loop — randomized seeded scenarios for a bounded wall-clock.

Each iteration draws a scenario from `chaos.random_scenario(seed, ...)`
(partition/heal or blackhole/heal plus a latency/drop storm, all derived
from the seed), runs it on a fresh in-proc 4-validator mesh, and checks
that every live node reconverges on ONE chain at the target height. On
any divergence/stall the loop STOPS and dumps the failing seed plus the
resolved plan trace, so the failure replays locally with:

    TM_TPU_CHAOS_SEED=<seed> python tools/soak.py --iters 1

Usage:
    python tools/soak.py [--budget SECONDS] [--iters N] [--nodes N]
                         [--height H] [--seed S]

Exit code: 0 if every completed iteration converged, 1 on the first
divergence (artifact JSON on stdout either way).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tendermint_tpu import obs
from tendermint_tpu.chaos import ScenarioRunner, random_scenario
from tendermint_tpu.chaos.scenario import default_seed


async def run_one(seed: int, n_nodes: int, height: int, timeout: float) -> dict:
    from tests.chaos_harness import (
        build_chaos_handles,
        chain_hashes,
        start_mesh,
        stop_mesh,
    )

    # flight recorder on for every iteration: a diverging seed ships with
    # its per-height step timeline, not just the scenario plan
    tracer = obs.default_tracer()
    tracer.enabled = True
    tracer.clear()

    handles = build_chaos_handles(n_nodes)
    scenario = random_scenario(seed, [h.name for h in handles])
    runner = ScenarioRunner(handles, scenario)
    await start_mesh(handles)
    try:
        heights = await runner.run(until_height=height, timeout=timeout)
        hashes = await chain_hashes(handles, height - 1)
        converged = len(hashes) == 1 and all(
            seq[:height] == list(range(1, height + 1))
            for name, seq in heights.items()
            if runner.nodes[name].alive
        )
        records = [r.to_json() for r in tracer.records()]
        out = {
            "seed": seed,
            "ok": converged,
            "heights": {k: (v[-1] if v else 0) for k, v in heights.items()},
            "forks": len(hashes),
            "latency_attribution": obs.attribution(records),
            "plan": runner.plan_jsonl().decode(),
        }
        if not converged:
            out["trace_report"] = obs.ascii_timeline(records)
        return out
    except TimeoutError as e:
        records = [r.to_json() for r in tracer.records()]
        return {
            "seed": seed,
            "ok": False,
            "error": str(e),
            "latency_attribution": obs.attribution(records),
            "trace_report": obs.ascii_timeline(records),
            "plan": runner.plan_jsonl().decode(),
        }
    finally:
        await stop_mesh(handles)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--budget", type=float, default=300.0,
                    help="wall-clock budget in seconds (default 300)")
    ap.add_argument("--iters", type=int, default=0,
                    help="max iterations (0 = budget-bound only)")
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--height", type=int, default=4,
                    help="target committed height per iteration")
    ap.add_argument("--seed", type=int, default=None,
                    help="starting seed (default TM_TPU_CHAOS_SEED or 0)")
    args = ap.parse_args()

    seed = args.seed if args.seed is not None else default_seed()
    start = time.monotonic()
    results = []
    it = 0
    while True:
        if args.iters and it >= args.iters:
            break
        remaining = args.budget - (time.monotonic() - start)
        if remaining <= 0:
            break
        res = asyncio.run(
            run_one(seed + it, args.nodes, args.height,
                    timeout=min(120.0, max(10.0, remaining)))
        )
        results.append({k: v for k, v in res.items() if k != "plan"})
        status = "ok" if res["ok"] else "DIVERGED"
        print(f"# iter {it} seed={res['seed']}: {status}", file=sys.stderr)
        if not res["ok"]:
            print(
                f"# REPLAY: TM_TPU_CHAOS_SEED={res['seed']} "
                f"python tools/soak.py --iters 1",
                file=sys.stderr,
            )
            if res.get("trace_report"):
                print(res["trace_report"], file=sys.stderr)
            print(json.dumps(res))
            return 1
        it += 1

    print(json.dumps({
        "ok": True,
        "iterations": it,
        "elapsed_s": round(time.monotonic() - start, 1),
        "results": results,
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
