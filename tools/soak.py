"""Chaos soak loop — randomized seeded scenarios for a bounded wall-clock.

Each iteration draws a scenario from `chaos.random_scenario(seed, ...)`
(partition/heal or blackhole/heal plus a latency/drop storm, all derived
from the seed), runs it on a fresh in-proc 4-validator mesh, and checks
that every live node reconverges on ONE chain at the target height. On
any divergence/stall the loop STOPS and dumps the failing seed plus the
resolved plan trace, so the failure replays locally with:

    TM_TPU_CHAOS_SEED=<seed> python tools/soak.py --iters 1

Usage:
    python tools/soak.py [--budget SECONDS] [--iters N] [--nodes N]
                         [--height H] [--seed S]

Exit code: 0 if every completed iteration converged, 1 on the first
divergence (artifact JSON on stdout either way).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tendermint_tpu import obs
from tendermint_tpu.chaos import ScenarioRunner, random_scenario
from tendermint_tpu.chaos.scenario import default_seed


def _collect_dumps(handles, chaos_tracer) -> list[dict]:
    """Per-node dump_traces-shaped dicts, plus the process-wide ring's
    chaos/link annotations as a pseudo node."""
    from tests.chaos_harness import node_dump

    dumps = [obs.normalize_dump(node_dump(h)) for h in handles]
    chaos_records = [r.to_json() for r in chaos_tracer.records()]
    if chaos_records:
        dumps.append(
            obs.normalize_dump(
                {
                    "node_id": "_chaos",
                    "moniker": "_chaos",
                    "epoch_wall_ns": chaos_tracer.epoch_wall_ns,
                    "records": chaos_records,
                    "peer_clock": {},
                }
            )
        )
    return dumps


def _health_statuses(handles) -> dict:
    """Per-node rolled-up health status — the one-line summary every
    iteration carries (a chaos run that degrades a subsystem without
    diverging still shows up here)."""
    return {
        h.name: obs.VERDICT_NAMES[h.cs.health.status()]
        for h in handles
        if getattr(h.cs, "health", None) is not None
    }


def _health_verdicts(handles) -> dict:
    """Full per-node health verdicts (detector SLO state + incident
    log) for the divergence artifact: next to the merged trace the
    verdict says WHICH plane degraded before the fork/stall."""
    return {
        h.name: h.cs.health.verdict()
        for h in handles
        if getattr(h.cs, "health", None) is not None
    }


def _ledger_summaries(handles) -> dict:
    """Per-node device-cost ledger summaries (obs/ledger.py) for the
    divergence artifact: next to the health verdicts (WHICH plane
    degraded) the ledger says what the DEVICE was doing — per-class
    device-seconds, fill efficiency, padding waste. Harness nodes whose
    verify path owns a scheduler report their own ledger; the process
    default ledger rides as "_process" either way (the in-proc mesh
    funnels any installed scheduler's rounds there), so a verify plane
    that did nothing shows zero rounds honestly instead of being
    absent."""
    from tests.chaos_harness import node_ledger

    out = {}
    for h in handles:
        led = node_ledger(h)
        if led is not None:
            out[h.name] = led.summary()
    out["_process"] = obs.default_ledger().summary()
    return out


def _merge(dumps: list[dict]):
    """Rebase the dumps onto one timeline with explicit wall-anchor
    offsets — one process, one clock, so the anchors ARE ground truth
    and NTP estimation over chaos-delayed links would only import bias.
    Only run on the divergence path: the happy path's attribution never
    reads timestamps, so the rebase+sort would be wasted work there."""
    from tendermint_tpu.obs.cluster import wall_anchor_offsets

    return obs.merge_records(dumps, offsets=wall_anchor_offsets(dumps))


async def run_one(seed: int, n_nodes: int, height: int, timeout: float) -> dict:
    from tests.chaos_harness import (
        build_chaos_handles,
        chain_hashes,
        start_mesh,
        stop_mesh,
    )

    # flight recorder on for every iteration: a diverging seed ships
    # with its per-height step timeline, not just the scenario plan.
    # Each node gets its OWN ring (cluster tracing) so a divergence also
    # ships the merged cross-validator report; the process-wide default
    # ring keeps collecting the chaos/link annotations.
    tracer = obs.default_tracer()
    tracer.enabled = True
    tracer.clear()

    # each node also carries a live health plane: the quorum-lag /
    # round-churn / stall detectors watch the same run the chaos
    # scenario shapes, incidents land in the per-node rings (so the
    # divergence dump says WHY next to WHAT), and the final verdicts
    # ride the artifact
    handles = build_chaos_handles(
        n_nodes,
        tracer_factory=lambda name: obs.Tracer(enabled=True),
        ping_interval=1.0,
        health_factory=lambda name, node_tracer: obs.HealthMonitor(
            tracer=node_tracer
        ),
    )
    scenario = random_scenario(seed, [h.name for h in handles])
    runner = ScenarioRunner(handles, scenario)
    await start_mesh(handles)
    try:
        heights = await runner.run(until_height=height, timeout=timeout)
        hashes = await chain_hashes(handles, height - 1)
        converged = len(hashes) == 1 and all(
            seq[:height] == list(range(1, height + 1))
            for name, seq in heights.items()
            if runner.nodes[name].alive
        )
        dumps = _collect_dumps(handles, tracer)
        all_records = [r for d in dumps for r in d["records"]]
        out = {
            "seed": seed,
            "ok": converged,
            "heights": {k: (v[-1] if v else 0) for k, v in heights.items()},
            "forks": len(hashes),
            "latency_attribution": obs.attribution(all_records),
            "health": _health_statuses(handles),
            "plan": runner.plan_jsonl().decode(),
        }
        if not converged:
            merge = _merge(dumps)
            out["trace_report"] = obs.ascii_timeline(merge[2])
            out["cluster_report"] = obs.cluster_report(dumps, merge=merge)
            out["health_verdicts"] = _health_verdicts(handles)
            out["dispatch_ledger"] = _ledger_summaries(handles)
        return out
    except TimeoutError as e:
        dumps = _collect_dumps(handles, tracer)
        merge = _merge(dumps)
        return {
            "seed": seed,
            "ok": False,
            "error": str(e),
            "latency_attribution": obs.attribution(merge[2]),
            "trace_report": obs.ascii_timeline(merge[2]),
            "cluster_report": obs.cluster_report(dumps, merge=merge),
            "health": _health_statuses(handles),
            "health_verdicts": _health_verdicts(handles),
            "dispatch_ledger": _ledger_summaries(handles),
            "plan": runner.plan_jsonl().decode(),
        }
    finally:
        await stop_mesh(handles)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--budget", type=float, default=300.0,
                    help="wall-clock budget in seconds (default 300)")
    ap.add_argument("--iters", type=int, default=0,
                    help="max iterations (0 = budget-bound only)")
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--height", type=int, default=4,
                    help="target committed height per iteration")
    ap.add_argument("--seed", type=int, default=None,
                    help="starting seed (default TM_TPU_CHAOS_SEED or 0)")
    args = ap.parse_args()

    seed = args.seed if args.seed is not None else default_seed()
    start = time.monotonic()
    results = []
    it = 0
    while True:
        if args.iters and it >= args.iters:
            break
        remaining = args.budget - (time.monotonic() - start)
        if remaining <= 0:
            break
        res = asyncio.run(
            run_one(seed + it, args.nodes, args.height,
                    timeout=min(120.0, max(10.0, remaining)))
        )
        results.append({k: v for k, v in res.items() if k != "plan"})
        status = "ok" if res["ok"] else "DIVERGED"
        print(f"# iter {it} seed={res['seed']}: {status}", file=sys.stderr)
        if not res["ok"]:
            print(
                f"# REPLAY: TM_TPU_CHAOS_SEED={res['seed']} "
                f"python tools/soak.py --iters 1",
                file=sys.stderr,
            )
            if res.get("trace_report"):
                print(res["trace_report"], file=sys.stderr)
            if res.get("cluster_report"):
                print(obs.report_text(res["cluster_report"]), file=sys.stderr)
            print(json.dumps(res))
            return 1
        it += 1

    print(json.dumps({
        "ok": True,
        "iterations": it,
        "elapsed_s": round(time.monotonic() - start, 1),
        "results": results,
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
