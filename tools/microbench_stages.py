"""Per-stage timing of the current ed25519 verify kernel on the device.

Stages: decompress, scalar_mult_base, scalar_mult_var, compress, plus
isolated primitives (double, add, window-table gather) to find the
pathological op.
"""

import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

from tendermint_tpu.ops import curve25519 as curve
from tendermint_tpu.ops import field25519 as fe

B = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
ITERS = 5


def timeit(name, fn, *args, want_out=False):
    # On the tunnelled backend block_until_ready returns at enqueue time and
    # device->host transfers cost ~hundreds of ms, so: reduce the output to
    # scalars INSIDE the jit and fetch only those — the tiny transfer is the
    # true synchronization point without drowning compute in transfer time.
    def reduced(*a):
        return jax.tree.map(
            lambda x: x.sum() if hasattr(x, "sum") else x, fn(*a)
        )

    def fetch(o):
        return jax.tree.map(np.asarray, o)

    fn_r = jax.jit(reduced)
    t0 = time.perf_counter()
    fetch(fn_r(*args))
    compile_t = time.perf_counter() - t0
    best = float("inf")
    for _ in range(ITERS):
        t0 = time.perf_counter()
        fetch(fn_r(*args))
        best = min(best, time.perf_counter() - t0)
    print(f"{name:28s} compile {compile_t:7.2f}s  run {best*1e3:9.2f} ms  ({B/best/1e3:9.1f} Ksig-equiv/s)")
    if want_out:
        return jax.jit(fn)(*args)  # second compile, only when consumed
    return None


def main():
    print(f"backend={jax.default_backend()} B={B}")
    rng = np.random.default_rng(0)
    from __graft_entry__ import _make_batch

    pub, rb, sb, kb, s_ok = _make_batch(min(B, 64))
    reps = (B + pub.shape[0] - 1) // pub.shape[0]
    tile = lambda x: jnp.asarray(np.tile(x, (reps,) + (1,) * (x.ndim - 1))[:B])
    pub, rb, sb, kb = tile(pub), tile(rb), tile(sb), tile(kb)

    timeit("noop roundtrip", lambda x: x.astype(jnp.int32) + 1, s_ok_dev := jnp.asarray(np.ones(8, bool)))
    pt, ok = timeit("decompress", curve.decompress, pub, want_out=True)
    timeit("scalar_mult_base", curve.scalar_mult_base, sb)
    timeit("scalar_mult_var", curve.scalar_mult_var, kb, pt)
    timeit("compress", curve.compress, pt)
    timeit("double x1", curve.double, pt)
    timeit("add x1", curve.add, pt, pt)

    def dbl16(p):
        for _ in range(16):
            p = curve.double(p)
        return p

    timeit("double x16 unrolled", dbl16, pt)

    def dbl16_loop(p):
        return jax.lax.fori_loop(0, 16, lambda _, v: curve.double(v), p)

    timeit("double x16 fori", dbl16_loop, pt)

    # window-table gather pattern from scalar_mult_var
    entries = [curve.identity((B,)), pt]
    for _ in range(2):
        entries.append(curve.add(entries[-1], pt))
    table4 = jnp.stack(entries, axis=-3)  # [B, 4, 4, 32]
    digs = jnp.asarray(rng.integers(0, 4, (B,), dtype=np.int32))

    def gather_one(t, d):
        return jnp.take_along_axis(
            t, d[..., None, None, None], axis=-3
        ).squeeze(-3)

    timeit("table gather x1", gather_one, table4, digs)

    def onehot_select(t, d):
        mask = (d[:, None] == jnp.arange(4)[None, :]).astype(jnp.int32)
        return jnp.einsum("bk,bkcl->bcl", mask, t)

    timeit("onehot select x1", onehot_select, table4, digs)


if __name__ == "__main__":
    main()
