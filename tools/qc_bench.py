"""qc_catchup bench harness — N-sig commit verify vs one QC pairing.

In-proc committee sweep (the acceptance shape for ROADMAP item 3): for
each committee size, build a real chain segment — every commit carries n
genuine ed25519 precommit signatures AND n genuine BLS QC dual-signs,
aggregated into a QuorumCertificate — then verify the same window both
ways through one running VerifyScheduler:

- **baseline** (the current blocksync path): `verify_commits_light`, one
  coalesced sig-plane round of n x blocks ed25519 rows — device cost
  linear in committee size;
- **qc**: `verify_commits_qc` through the `qc_verify` engine, the whole
  window as ONE random-linear-combination multi-pairing — cost per
  block ~flat in committee size (2 pairings + one G2 MSM per block).

The ledger brackets each phase so the artifact's device_cost block
carries honest per-engine rows (sig vs qc_verify), and the light-proof
compression ratio (full CommitSigs vs qc + bitset) is measured on the
same chain.
"""

from __future__ import annotations

import asyncio
import hashlib
import time


def _build_committee(n: int, seed: bytes = b"qcbench"):
    from tendermint_tpu.crypto import bls_signatures as bls
    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.crypto.bls12_381 import R
    from tendermint_tpu.types.validator import Validator
    from tendermint_tpu.types.validator_set import ValidatorSet

    keys, vals, bls_privs = [], [], {}
    for i in range(n):
        priv = ed25519.PrivKey.from_secret(seed + b"%d" % i)
        scalar = (
            int.from_bytes(
                hashlib.sha256(seed + b"bls%d" % i).digest(), "big"
            )
            % (R - 1)
            + 1
        )
        pub = bls.pubkey_from_priv(scalar)
        addr = priv.public_key().address()
        bls_privs[addr] = scalar
        keys.append(priv)
        vals.append(
            Validator(
                priv.public_key(), 10,
                bls_pub_key=bls.g2_to_bytes(pub.key),
            )
        )
    vs = ValidatorSet(vals)
    by_addr = {k.public_key().address(): k for k in keys}
    ordered = [by_addr[v.address] for v in vs.validators]
    return vs, ordered, bls_privs


def _build_chain(vs, keys, bls_privs, blocks: int, chain_id: str):
    """[(block_id, height, commit, qc, light_full, light_qc)] — a
    synthetic header chain whose commits carry real dual signatures."""
    from tendermint_tpu.crypto import bls_signatures as bls
    from tendermint_tpu.light.types import LightBlock
    from tendermint_tpu.types.block import Data, Header
    from tendermint_tpu.types.block_id import BlockID
    from tendermint_tpu.types.part_set import PartSetHeader
    from tendermint_tpu.types.quorum_cert import assemble_qc, qc_sign_bytes
    from tendermint_tpu.types.vote import Vote, VoteType
    from tendermint_tpu.types.vote_set import VoteSet

    t0 = 1_700_000_000_000_000_000
    out = []
    prev = BlockID()
    for h in range(1, blocks + 1):
        header = Header(
            chain_id=chain_id,
            height=h,
            time_ns=t0 + h * 10**9,
            last_block_id=prev,
            validators_hash=vs.hash(),
            next_validators_hash=vs.hash(),
            data_hash=Data().hash(),
        )
        bid = BlockID(header.hash(), PartSetHeader(1, bytes([h % 251]) * 32))
        votes = VoteSet(chain_id, h, 0, VoteType.PRECOMMIT, vs)
        qc_msg = qc_sign_bytes(chain_id, h, 0, bid)
        for i, key in enumerate(keys):
            v = Vote(
                type=VoteType.PRECOMMIT,
                height=h,
                round=0,
                block_id=bid,
                timestamp_ns=t0 + h * 10**9 + i,
                validator_address=key.public_key().address(),
                validator_index=i,
            )
            v.signature = key.sign(v.sign_bytes(chain_id))
            v.qc_signature = bls.g1_to_bytes(
                bls.sign(bls_privs[v.validator_address], qc_msg)
            )
            votes.add_vote(v, verified=True)
        commit = votes.make_commit()
        qc = assemble_qc(chain_id, commit, vs)
        assert qc is not None, "bench chain failed to aggregate a QC"
        out.append(
            (
                bid,
                h,
                commit,
                qc,
                LightBlock(header, commit, vs),
                LightBlock(header, None, vs, qc=qc),
            )
        )
        prev = bid
    return out


def _best_wall(fn, iters: int = 3) -> float:
    best = float("inf")
    for _ in range(iters):
        t = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t)
    return best


def run_qc_catchup(
    sizes=(4, 32, 100), blocks: int = 8, chain_id: str = "qc-bench"
) -> dict:
    """Per-size rows + the flatness/compression claims. Runs every
    verify through one VerifyScheduler (worker-thread submits, so both
    planes coalesce exactly like blocksync's executor path does)."""
    from tendermint_tpu.obs.ledger import default_ledger
    from tendermint_tpu.parallel.scheduler import VerifyScheduler

    rows = []
    for n in sizes:
        vs, keys, bls_privs = _build_committee(n)
        chain = _build_chain(vs, keys, bls_privs, blocks, chain_id)
        sig_entries = [(bid, h, commit) for bid, h, commit, *_ in chain]
        qc_entries = [(bid, h, qc) for bid, h, _c, qc, *_ in chain]

        sched = VerifyScheduler()
        ledger = default_ledger()

        async def measure():
            await sched.start()
            loop = asyncio.get_running_loop()
            from tendermint_tpu.types.quorum_cert import qc_dispatch

            sig_verifier = sched.classed("blocksync")

            def baseline():
                verdicts = vs.verify_commits_light(
                    chain_id, sig_entries, verifier=sig_verifier
                )
                assert all(verdicts), "baseline window failed"

            engine = None

            def qc_path():
                verdicts = vs.verify_commits_qc(
                    chain_id, qc_entries, engine=engine
                )
                assert all(verdicts), "qc window failed"

            # warm both paths (compiles/tables), then bracket marks
            await loop.run_in_executor(None, baseline)
            base_mark = ledger.mark()
            base_wall = await loop.run_in_executor(
                None, _best_wall, baseline
            )
            base_cost = ledger.summary(since=base_mark)

            def scheduled_engine(items):
                return sched.submit_wire_fn_sync(
                    "qc_verify", items, "blocksync"
                )

            engine = scheduled_engine
            await loop.run_in_executor(None, qc_path)
            qc_mark = ledger.mark()
            qc_wall = await loop.run_in_executor(None, _best_wall, qc_path)
            qc_cost = ledger.summary(since=qc_mark)
            await sched.stop()
            return base_wall, base_cost, qc_wall, qc_cost

        base_wall, base_cost, qc_wall, qc_cost = asyncio.run(measure())
        full_bytes = chain[0][4].proof_bytes()
        qc_bytes = chain[0][5].proof_bytes()
        base_dev = sum(
            e.get("device_seconds", 0.0)
            for k, e in base_cost.get("per_engine", {}).items()
            if k == "sig"
        )
        qc_dev = qc_cost.get("per_engine", {}).get("qc_verify", {}).get(
            "device_seconds", 0.0
        )
        rows.append(
            {
                "validators": n,
                "blocks": blocks,
                "baseline_wall_s": round(base_wall, 6),
                "baseline_wall_per_block_ms": round(
                    base_wall / blocks * 1e3, 3
                ),
                "baseline_device_s": round(base_dev, 6),
                "qc_wall_s": round(qc_wall, 6),
                "qc_wall_per_block_ms": round(qc_wall / blocks * 1e3, 3),
                "qc_device_s": round(qc_dev, 6),
                "qc_commits_per_s": round(blocks / qc_wall, 1),
                "baseline_commits_per_s": round(blocks / base_wall, 1),
                "proof_bytes_full": full_bytes,
                "proof_bytes_qc": qc_bytes,
                "proof_compression": round(full_bytes / qc_bytes, 1),
                "qc_rounds": qc_cost.get("per_engine", {})
                .get("qc_verify", {})
                .get("rounds", 0),
            }
        )
    by_n = {r["validators"]: r for r in rows}
    lo, hi = min(sizes), max(sizes)
    return {
        "sizes": list(sizes),
        "rows": rows,
        # the flatness claim: per-block qc verify cost from the
        # smallest to the largest committee
        "qc_flatness": round(
            by_n[hi]["qc_wall_per_block_ms"]
            / max(by_n[lo]["qc_wall_per_block_ms"], 1e-9),
            2,
        ),
        "baseline_growth": round(
            by_n[hi]["baseline_wall_per_block_ms"]
            / max(by_n[lo]["baseline_wall_per_block_ms"], 1e-9),
            2,
        ),
        "proof_compression_at_max": by_n[hi]["proof_compression"],
    }


if __name__ == "__main__":
    import json

    print(json.dumps(run_qc_catchup(), indent=2))
