#!/usr/bin/env python
"""Build + verify the verification-program prewarm manifest.

PERF_ANALYSIS §10: per-process XLA program loads cost ~10-30 s EACH
through the tunnelled executor, and a cold bisect-1k run spent ~206 s
loading 44 distinct op-shape programs. The fix is two-sided: the
canonical bucket ladder (crypto/shape_registry) bounds how many
programs exist, and this tool loads them ahead of time so the
persistent compile cache holds every shape a node dispatches —
a restarted node then pays zero per-shape loads mid-height.

Modes:

  python tools/prewarm.py                      # build the manifest
  python tools/prewarm.py --verify             # re-run; report per-
                                               # bucket load times and
                                               # fail on budget breach

Build executes every (tier, bucket) verify program once with
verdict-inert padded lanes (BatchVerifier.prewarm_buckets — the same
routine the node's warm thread runs under [scheduler] prewarm=true) and
writes {created_unix, ladder, entries:[{tier,bucket,seconds}]} JSON.
Verify re-executes the manifest's ladder in a warmed-cache process: any
entry slower than --reload-threshold seconds means the persistent cache
is NOT absorbing that shape (regression), and the distinct-shape count
must stay within --budget per tier.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tendermint_tpu.libs.jax_cache import set_compile_cache_env  # noqa: E402

set_compile_cache_env()

DEFAULT_MANIFEST = "prewarm_manifest.json"


def build_manifest(
    ladder=None, tiers=("small", "big", "generic")
) -> dict:
    """Run the ladder prewarm on a fresh verifier + registry; returns
    the manifest dict (entries carry per-program wall seconds — on a
    cold cache that is compile+load, on a warm cache just load)."""
    from tendermint_tpu.crypto.batch_verifier import BatchVerifier
    from tendermint_tpu.crypto.shape_registry import (
        DEFAULT_BUCKET_LADDER,
        ShapeRegistry,
    )

    ladder = tuple(ladder) if ladder else DEFAULT_BUCKET_LADDER
    registry = ShapeRegistry(ladder)
    verifier = BatchVerifier(min_device_batch=0, shape_registry=registry)
    t0 = time.perf_counter()
    entries = verifier.prewarm_buckets(buckets=ladder, tiers=tiers)
    return {
        "created_unix": int(time.time()),
        "ladder": list(registry.ladder),
        "tiers": list(tiers),
        "entries": entries,
        "total_seconds": round(time.perf_counter() - t0, 3),
        "shapes_by_tier": registry.shapes_by_tier(),
    }


def check_budget(manifest: dict, budget: int) -> list[str]:
    """Per-tier distinct-shape budget violations (empty = pass). A
    program's shape is (bucket, rows): the cached tiers' programs vary
    with the table-store row allocation too."""
    problems = []
    by_tier: dict[str, set] = {}
    for e in manifest["entries"]:
        by_tier.setdefault(e["tier"], set()).add(
            (e["bucket"], e.get("rows", 0))
        )
    for tier, shapes in sorted(by_tier.items()):
        if len(shapes) > budget:
            problems.append(
                f"tier {tier}: {len(shapes)} distinct shapes > budget "
                f"{budget}: {sorted(shapes)}"
            )
    return problems


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument(
        "--out", default=DEFAULT_MANIFEST, help="manifest path"
    )
    ap.add_argument(
        "--ladder",
        default="",
        help="comma-separated bucket ladder (default: built-in)",
    )
    ap.add_argument(
        "--tiers",
        default="small,big,generic",
        help="comma-separated tiers to prewarm",
    )
    ap.add_argument(
        "--budget",
        type=int,
        default=8,
        help="max distinct program shapes per tier",
    )
    ap.add_argument(
        "--verify",
        action="store_true",
        help="re-run an existing manifest's ladder and report load times",
    )
    ap.add_argument(
        "--reload-threshold",
        type=float,
        default=60.0,
        help="--verify: per-program seconds above which the persistent "
        "cache is judged to not be absorbing the shape",
    )
    args = ap.parse_args()

    ladder = (
        tuple(int(x) for x in args.ladder.split(",") if x.strip())
        if args.ladder.strip()
        else None
    )
    tiers = tuple(t.strip() for t in args.tiers.split(",") if t.strip())

    if args.verify:
        if not os.path.exists(args.out):
            print(f"no manifest at {args.out}; run without --verify first")
            return 1
        with open(args.out) as f:
            prior = json.load(f)
        ladder = ladder or tuple(prior["ladder"])
        tiers = tuple(prior.get("tiers", tiers))

    manifest = build_manifest(ladder=ladder, tiers=tiers)
    for e in manifest["entries"]:
        print(
            f"  {e['tier']:>8s}  bucket {e['bucket']:>6d}  "
            f"rows {e.get('rows', 0):>5d}  {e['seconds']:7.2f}s"
        )
    print(
        f"{len(manifest['entries'])} programs, "
        f"{manifest['total_seconds']:.1f}s total"
    )

    rc = 0
    problems = check_budget(manifest, args.budget)
    for p in problems:
        print(f"BUDGET VIOLATION: {p}")
        rc = 1

    if args.verify:
        slow = [
            e
            for e in manifest["entries"]
            if e["seconds"] > args.reload_threshold
        ]
        for e in slow:
            print(
                f"RELOAD REGRESSION: {e['tier']}/{e['bucket']} took "
                f"{e['seconds']:.1f}s > {args.reload_threshold:.0f}s — "
                "persistent cache is not absorbing this shape"
            )
            rc = 1
        if not slow and not problems:
            print("verify OK: every ladder program loads within threshold")
    else:
        with open(args.out, "w") as f:
            json.dump(manifest, f, indent=1)
        print(f"wrote {args.out}")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
