#!/usr/bin/env python
"""Build + verify the verification-program prewarm manifest.

PERF_ANALYSIS §10: per-process XLA program loads cost ~10-30 s EACH
through the tunnelled executor, and a cold bisect-1k run spent ~206 s
loading 44 distinct op-shape programs. The fix is two-sided: the
canonical bucket ladder (crypto/shape_registry) bounds how many
programs exist, and this tool loads them ahead of time so the
persistent compile cache holds every shape a node dispatches —
a restarted node then pays zero per-shape loads mid-height.

Under a device mesh the ladder is AOT-loaded PER DEVICE VARIANT
(PERF_ANALYSIS §13): each rung's replicated (devices=1) and/or
row-sharded (devices=N) program, exactly the reachable set given
mesh_min_rows. The manifest records the topology (`device_count`,
`mesh_min_rows`) it was built for, and --verify fails loudly when the
live mesh disagrees — a node warm-started on a different topology
would otherwise recompile every sharded program on the hot path.

Modes:

  python tools/prewarm.py                      # build the manifest
  python tools/prewarm.py --devices 4          # build for a 4-device mesh
  python tools/prewarm.py --verify             # re-run the manifest's
                                               # ladder ON ITS TOPOLOGY;
                                               # report per-bucket load
                                               # times, fail on budget
                                               # breach or device-count
                                               # mismatch with the live
                                               # mesh

Build executes every (tier, bucket, devices) verify program once with
verdict-inert padded lanes (BatchVerifier.prewarm_buckets — the same
routine the node's warm thread runs under [scheduler] prewarm=true) and
writes {created_unix, ladder, device_count, entries:[{tier,bucket,rows,
devices,seconds}]} JSON. Verify re-executes the manifest's ladder in a
warmed-cache process: any entry slower than --reload-threshold seconds
means the persistent cache is NOT absorbing that shape (regression),
and the distinct-shape count must stay within --budget per tier.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tendermint_tpu.libs.jax_cache import set_compile_cache_env  # noqa: E402

set_compile_cache_env()

DEFAULT_MANIFEST = "prewarm_manifest.json"

# scheduler verify class -> the verifier tiers its dispatches reach.
# Every commit-verify class — including the lightserve serving plane's
# shared bisection rounds, which coalesce arbitrary swarm sizes onto
# the same ladder — runs the cached small/big tier split; a manifest
# built without those tiers leaves that class compiling on the hot
# path, so --verify checks coverage per family.
FAMILY_TIERS = {
    "consensus": ("small", "big"),
    "evidence": ("small", "big"),
    "blocksync": ("small", "big"),
    "light": ("small", "big"),
    "lightserve": ("small", "big"),
    # the sequencer streaming plane's signature checks are host-native
    # ECDSA recovers riding the scheduler's fn lane — no ladder verify
    # programs are reachable, so its tier set is empty. It is still a
    # first-class verify family: manifests record covering it, and
    # --verify --families sequencer fails against a manifest whose
    # recorded coverage predates the class (see check_families).
    "sequencer": (),
}

# committee-scale bucket rungs (PERF_ANALYSIS §16): batched vote gossip
# ships VOTE_BATCH_MAX-vote chunks (pad to 128) and whole-committee
# commit verifies at 100-200 validators land on 128/256 — a manifest
# missing these rungs leaves a committee-scale node compiling its vote
# path mid-height
COMMITTEE_BUCKETS = (128, 256)


def check_committee_rungs(manifest: dict) -> list[str]:
    """Committee-rung coverage violations (empty = pass): the manifest's
    entries must include every COMMITTEE_BUCKETS rung for at least one
    cached tier. Explicitly-partial ladders (--ladder without the
    rungs) fail here, which is the point — a committee-scale node warm-
    started from them compiles the vote path on the hot path."""
    built = {
        e["bucket"]
        for e in manifest.get("entries", ())
        if e["tier"] in ("small", "big")
    }
    missing = [b for b in COMMITTEE_BUCKETS if b not in built]
    if missing:
        return [
            f"committee-scale rung(s) {missing} not in the manifest "
            f"(built cached-tier buckets: {sorted(built)})"
        ]
    return []


def _build_mesh(devices: int, backend: str = ""):
    """Mesh over `devices` chips of the backend (0 = all visible; 1 or
    a 1-device backend = no mesh)."""
    if devices == 1:
        return None
    from tendermint_tpu.parallel import build_mesh

    return build_mesh(ici_parallelism=devices, mesh_backend=backend)


def live_device_count(backend: str = "") -> int:
    """Devices visible to the backend the node would mesh over."""
    import jax

    return len(jax.devices(backend or None))


def build_manifest(
    ladder=None,
    tiers=("small", "big", "generic"),
    devices: int = 1,
    mesh_backend: str = "",
    mesh_min_rows: int | None = None,
) -> dict:
    """Run the ladder prewarm on a fresh verifier + registry; returns
    the manifest dict (entries carry per-program wall seconds — on a
    cold cache that is compile+load, on a warm cache just load).
    `devices` > 1 (or 0 = all visible) builds the mesh verifier and
    prewarms both program families."""
    from tendermint_tpu.crypto.batch_verifier import BatchVerifier
    from tendermint_tpu.crypto.shape_registry import (
        DEFAULT_BUCKET_LADDER,
        ShapeRegistry,
    )

    ladder = tuple(ladder) if ladder else DEFAULT_BUCKET_LADDER
    registry = ShapeRegistry(ladder)
    mesh = _build_mesh(devices, mesh_backend)
    verifier = BatchVerifier(
        mesh=mesh,
        min_device_batch=0,
        shape_registry=registry,
        mesh_min_rows=mesh_min_rows,
    )
    t0 = time.perf_counter()
    entries = verifier.prewarm_buckets(buckets=ladder, tiers=tiers)
    return {
        "created_unix": int(time.time()),
        "ladder": list(registry.ladder),
        "tiers": list(tiers),
        # the scheduler verify classes this build covers (see
        # FAMILY_TIERS); --verify fails when any class a node
        # dispatches — incl. the lightserve serving plane — finds its
        # reachable tiers missing from the built entries
        "families": sorted(
            f
            for f, req in FAMILY_TIERS.items()
            if all(t in tiers for t in req)
        ),
        "device_count": verifier.mesh_devices,
        "mesh_min_rows": verifier._mesh_min_rows,
        # the backend the mesh was built on: --verify must count live
        # devices of (and rebuild against) the SAME backend, or the
        # topology check compares apples to oranges
        "mesh_backend": mesh_backend,
        "entries": entries,
        "total_seconds": round(time.perf_counter() - t0, 3),
        "shapes_by_tier": registry.shapes_by_tier(),
    }


def check_budget(manifest: dict, budget: int) -> list[str]:
    """Per-tier distinct-shape budget violations (empty = pass). A
    program's shape is (bucket, rows, devices): the cached tiers'
    programs vary with the table-store row allocation, and a mesh
    verifier's sharded family doubles the bulk rungs."""
    problems = []
    by_tier: dict[str, set] = {}
    for e in manifest["entries"]:
        by_tier.setdefault(e["tier"], set()).add(
            (e["bucket"], e.get("rows", 0), e.get("devices", 1))
        )
    for tier, shapes in sorted(by_tier.items()):
        if len(shapes) > budget:
            problems.append(
                f"tier {tier}: {len(shapes)} distinct shapes > budget "
                f"{budget}: {sorted(shapes)}"
            )
    return problems


def check_families(manifest: dict, families=None) -> list[str]:
    """Per-family tier coverage violations (empty = pass): every verify
    class the manifest claims to cover must find its reachable tiers
    among the built entries — a `--tiers generic` manifest covers NO
    commit-verify class, and a node trusting it would compile the
    lightserve swarm's shared rounds (or any commit verify) on the hot
    path."""
    problems = []
    built_tiers = {e["tier"] for e in manifest.get("entries", ())}
    claimed = manifest.get("families")
    for family in families or claimed or ():
        required = FAMILY_TIERS.get(family)
        if required is None:
            # an unknown name (operator typo in --families) must FAIL,
            # not silently report coverage that was never checked
            problems.append(
                f"family {family!r} is not a known verify class "
                f"(known: {sorted(FAMILY_TIERS)})"
            )
            continue
        if claimed is not None and family not in claimed:
            # the manifest recorded its coverage and this class is not
            # in it — a build predating the class (e.g. `sequencer`) or
            # an explicitly partial one must fail the requirement even
            # when the class has no reachable ladder tiers
            problems.append(
                f"family {family}: not covered by this manifest build "
                f"(recorded coverage: {sorted(claimed)})"
            )
            continue
        if claimed is None and not required:
            # a family with NO reachable ladder tiers (sequencer) has
            # no tier evidence to check — only recorded coverage can
            # demonstrate it, so a legacy manifest without a `families`
            # key cannot vacuously pass the requirement
            problems.append(
                f"family {family}: manifest records no family coverage "
                f"and the class has no ladder tiers to check — rebuild "
                f"with a coverage-recording prewarm"
            )
            continue
        missing = [t for t in required if t not in built_tiers]
        if missing:
            problems.append(
                f"family {family}: reachable tier(s) {missing} not in "
                f"the manifest (built tiers: {sorted(built_tiers)})"
            )
    return problems


def check_topology(
    manifest: dict,
    live_devices: int,
    expected_min_rows: int | None = None,
) -> list[str]:
    """Mismatches between the manifest's mesh topology and the live
    one (empty = pass). A manifest built for N devices prewarmed the
    devices=N sharded programs; a node now meshing over M != N would
    compile every sharded shape on the hot path — fail loudly
    instead. `expected_min_rows` (the node's configured mesh_min_rows,
    when known) must also match: it decides WHICH rungs got the
    replicated vs sharded variant, so a drifted threshold silently
    changes the reachable program set even at the same device count."""
    problems = []
    built = int(manifest.get("device_count", 1))
    if built != live_devices:
        problems.append(
            f"manifest built for {built} device(s), live mesh has "
            f"{live_devices} — sharded programs would recompile on the "
            "hot path; rebuild the manifest on this topology"
        )
    if expected_min_rows is not None:
        built_rows = manifest.get("mesh_min_rows")
        if built_rows is not None and int(built_rows) != int(
            expected_min_rows
        ):
            problems.append(
                f"manifest built with mesh_min_rows={built_rows}, "
                f"expected {expected_min_rows} — the replicated/sharded "
                "variant split differs; rebuild the manifest"
            )
    return problems


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument(
        "--out", default=DEFAULT_MANIFEST, help="manifest path"
    )
    ap.add_argument(
        "--ladder",
        default="",
        help="comma-separated bucket ladder (default: built-in)",
    )
    ap.add_argument(
        "--tiers",
        default="small,big,generic",
        help="comma-separated tiers to prewarm",
    )
    ap.add_argument(
        "--devices",
        type=int,
        default=1,
        help="mesh device count to prewarm for (0 = all visible "
        "devices of --mesh-backend; 1 = no mesh)",
    )
    ap.add_argument(
        "--mesh-backend",
        default="",
        help="jax backend for the mesh ('' = default; 'cpu' = host "
        "virtual devices)",
    )
    ap.add_argument(
        "--mesh-min-rows",
        type=int,
        default=0,
        help="rounds below this stay unsharded (0 = built-in default)",
    )
    ap.add_argument(
        "--budget",
        type=int,
        default=8,
        help="max distinct program shapes per tier",
    )
    ap.add_argument(
        "--families",
        default="",
        help="--verify: comma-separated scheduler verify classes whose "
        "reachable tiers the manifest must cover (e.g. "
        "'light,lightserve'); default: the manifest's recorded "
        "coverage, or every known class for manifests without one",
    )
    ap.add_argument(
        "--verify",
        action="store_true",
        help="re-run an existing manifest's ladder on its recorded "
        "topology; fail on budget breach, slow reloads, or live "
        "device-count mismatch",
    )
    ap.add_argument(
        "--reload-threshold",
        type=float,
        default=60.0,
        help="--verify: per-program seconds above which the persistent "
        "cache is judged to not be absorbing the shape",
    )
    args = ap.parse_args()

    ladder = (
        tuple(int(x) for x in args.ladder.split(",") if x.strip())
        if args.ladder.strip()
        else None
    )
    tiers = tuple(t.strip() for t in args.tiers.split(",") if t.strip())
    devices = args.devices
    mesh_min_rows = args.mesh_min_rows or None
    mesh_backend = args.mesh_backend

    rc = 0
    if args.verify:
        if not os.path.exists(args.out):
            print(f"no manifest at {args.out}; run without --verify first")
            return 1
        with open(args.out) as f:
            prior = json.load(f)
        ladder = ladder or tuple(prior["ladder"])
        tiers = tuple(prior.get("tiers", tiers))
        devices = int(prior.get("device_count", 1))
        # an explicit --mesh-min-rows is the node's configured value:
        # check it against what the manifest was built with; otherwise
        # re-run on the manifest's own threshold
        expected_rows = mesh_min_rows
        mesh_min_rows = prior.get("mesh_min_rows") or mesh_min_rows
        # re-run on the manifest's recorded backend (CLI flag as the
        # pre-mesh_backend-manifest fallback): the live device count and
        # the rebuilt programs must come from the SAME backend the
        # manifest was built on
        mesh_backend = prior.get("mesh_backend", args.mesh_backend)
        # topology check BEFORE the rebuild: the re-run must load the
        # manifest's programs, and a mesh of a different size can't
        live = live_device_count(mesh_backend) if devices != 1 else 1
        for p in check_topology(
            prior,
            live if devices != 1 else devices,
            expected_min_rows=expected_rows,
        ):
            print(f"TOPOLOGY MISMATCH: {p}")
            rc = 1
        if devices != 1 and live < devices:
            # can't even construct the mesh; report and bail non-zero
            return 1
        if rc:
            # a drifted threshold means the rebuild below would load a
            # DIFFERENT program set than the manifest promises — the
            # mismatch is the verdict
            return rc

    manifest = build_manifest(
        ladder=ladder,
        tiers=tiers,
        devices=devices,
        mesh_backend=mesh_backend,
        mesh_min_rows=mesh_min_rows,
    )
    for e in manifest["entries"]:
        print(
            f"  {e['tier']:>8s}  bucket {e['bucket']:>6d}  "
            f"rows {e.get('rows', 0):>5d}  "
            f"devs {e.get('devices', 1):>3d}  {e['seconds']:7.2f}s"
        )
    print(
        f"{len(manifest['entries'])} programs, "
        f"{manifest['total_seconds']:.1f}s total, "
        f"{manifest['device_count']} device(s)"
    )

    problems = check_budget(manifest, args.budget)
    for p in problems:
        print(f"BUDGET VIOLATION: {p}")
        rc = 1
    if args.verify:
        # family coverage: an explicit --families is the operator's
        # requirement; a manifest that recorded its coverage is checked
        # against that intent (an explicitly partial --tiers build
        # stays partial); a node-built / legacy manifest without the
        # key must cover EVERY class the node dispatches — including
        # the lightserve serving plane's shared rounds
        if args.families.strip():
            required = [
                f.strip() for f in args.families.split(",") if f.strip()
            ]
        elif "families" in prior:
            required = prior["families"]
        else:
            required = sorted(FAMILY_TIERS)
        family_problems = check_families(manifest, families=required)
        for p in family_problems:
            print(f"FAMILY COVERAGE: {p}")
            rc = 1
        problems = problems + family_problems
        committee_problems = check_committee_rungs(manifest)
        for p in committee_problems:
            print(f"COMMITTEE COVERAGE: {p}")
            rc = 1
        problems = problems + committee_problems

    if args.verify:
        slow = [
            e
            for e in manifest["entries"]
            if e["seconds"] > args.reload_threshold
        ]
        for e in slow:
            print(
                f"RELOAD REGRESSION: {e['tier']}/{e['bucket']}"
                f"/devs{e.get('devices', 1)} took "
                f"{e['seconds']:.1f}s > {args.reload_threshold:.0f}s — "
                "persistent cache is not absorbing this shape"
            )
            rc = 1
        if not slow and not problems and rc == 0:
            print("verify OK: every ladder program loads within threshold")
    else:
        with open(args.out, "w") as f:
            json.dump(manifest, f, indent=1)
        print(f"wrote {args.out}")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
