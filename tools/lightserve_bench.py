#!/usr/bin/env python
"""Light-client swarm bench — N-thousand clients through the serving plane.

Drives a real in-proc 4-validator consensus net for a few heights, then
points N simulated `LightClient`s (the real light/client.py, in-proc
provider — no sockets) at one node's `tendermint_tpu/lightserve` plane:

- every client syncs the same target height from the same trust root,
  so the proof cache serves each height's LightBlock from ONE assembly
  (cache hit-rate ~= 1 - heights/fetches) and the ServeVerifier
  collapses the swarm's identical bisection hops into a handful of
  executed verifications riding the scheduler's `lightserve` lane;
- a **divergent-witness** scenario syncs one client against a forked
  primary (the fork is RE-SIGNED by the net's real validator keys — a
  true 2/3-equivocation attack) with the honest plane as witness: the
  client must raise LightClientAttackEvidence and the honest node's
  evidence pool must accept it;
- a **forged-header** scenario gives a client a witness serving a
  tampered (unverifiable) block: the witness is removed, the sync
  completes.

The result records clients/s, cache hit-rate, verify dedup rate, and
the shape-registry delta (distinct_program_shapes /
device_dispatch_count) across the swarm sync — the sublinearity proof
the BENCH artifact carries (`bench.py --family lightserve`).

  python tools/lightserve_bench.py --clients 1000 --heights 8
"""

from __future__ import annotations

import argparse
import asyncio
import copy
import dataclasses
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

TRUSTING_PERIOD_NS = 3600 * 1_000_000_000


async def drive_net(heights: int, n_vals: int):
    """Run the in-proc consensus net to `heights`; returns node 0's
    (block_store, state_store) plus the committee (vs, pvs)."""
    from tests.helpers import make_genesis, make_validators
    from tests.test_consensus import make_node, wire_net

    vs, pvs = make_validators(n_vals)
    genesis = make_genesis(vs)
    nodes = [make_node(vs, pv, genesis) for pv in pvs]
    css = [n[0] for n in nodes]
    wire_net(css)
    for cs in css:
        await cs.start()
    await asyncio.gather(
        *(cs.wait_for_height(heights, timeout=180) for cs in css)
    )
    for cs in css:
        await cs.stop()
    _cs0, _app, _l2, bs, ss = nodes[0]
    return bs, ss, vs, pvs


def forked_light_chain(cache, vs, pvs, fork_at: int, tip: int) -> dict:
    """height->LightBlock for a chain that shares the honest prefix
    below `fork_at` and is RE-SIGNED by the real committee from there —
    the verifiable 2/3-equivocation fork the attack scenario needs."""
    from tests.helpers import CHAIN_ID
    from tendermint_tpu.light.types import LightBlock
    from tendermint_tpu.types.block_id import BlockID
    from tendermint_tpu.types.part_set import PartSetHeader
    from tendermint_tpu.types.vote import Vote, VoteType
    from tendermint_tpu.types.vote_set import VoteSet

    by_addr = {pv.get_pub_key().address(): pv for pv in pvs}
    ordered = [by_addr[v.address] for v in vs.validators]
    out: dict[int, LightBlock] = {}
    last_forked_id = None
    for h in range(1, tip + 1):
        honest = cache.get(h)
        if honest is None:
            raise RuntimeError(f"honest chain has no height {h}")
        if h < fork_at:
            out[h] = honest
            continue
        header = dataclasses.replace(
            honest.header,
            app_hash=b"forked-app-%d" % h,
            last_block_id=(
                last_forked_id
                if last_forked_id is not None
                else honest.header.last_block_id
            ),
            _hash=None,
        )
        bid = BlockID(
            header.hash(), PartSetHeader(1, header.hash())
        )
        votes = VoteSet(CHAIN_ID, h, 0, VoteType.PRECOMMIT, vs)
        for i, pv in enumerate(ordered):
            v = Vote(
                type=VoteType.PRECOMMIT,
                height=h,
                round=0,
                block_id=bid,
                timestamp_ns=header.time_ns,
                validator_address=pv.get_pub_key().address(),
                validator_index=i,
            )
            pv.sign_vote(CHAIN_ID, v)
            votes.add_vote(v, verified=True)
        out[h] = LightBlock(header, votes.make_commit(), vs)
        last_forked_id = bid
    return out


async def _swarm_sync(
    plane, target: int, n_clients: int, now_fn, trust
) -> dict:
    from tests.helpers import CHAIN_ID
    from tendermint_tpu.light.client import LightClient
    from tendermint_tpu.light.store import LightStore
    from tendermint_tpu.store.kv import MemKV

    async def one_client(i: int) -> bool:
        c = LightClient(
            CHAIN_ID,
            trust,
            plane.provider(),
            [plane.provider("witness-0")],
            LightStore(MemKV()),
            now_ns=now_fn,
            serve_verifier=plane.verifier,
        )
        lb = await c.verify_light_block_at_height(target)
        return lb.height == target

    t0 = time.perf_counter()
    results = await asyncio.gather(
        *(one_client(i) for i in range(n_clients))
    )
    wall = time.perf_counter() - t0
    return {
        "n_clients": n_clients,
        "synced": sum(bool(r) for r in results),
        "wall_s": round(wall, 3),
        "clients_per_s": round(n_clients / wall, 1) if wall else 0.0,
    }


async def _attack_scenarios(plane, bs, ss, vs, pvs, target, now_fn, trust):
    """Divergent-witness (verifiable fork -> evidence in the pool) and
    forged-header (tampered witness removed) scenarios."""
    from tests.helpers import CHAIN_ID
    from tests.test_light import MockProvider
    from tendermint_tpu.evidence import EvidencePool
    from tendermint_tpu.light.client import (
        ErrLightClientAttack,
        LightClient,
    )
    from tendermint_tpu.light.store import LightStore
    from tendermint_tpu.store.kv import MemKV
    from tendermint_tpu.types.evidence import LightClientAttackEvidence

    out: dict = {}
    # --- divergent witness: forked primary vs the honest plane ---------
    forked = forked_light_chain(
        plane.cache, vs, pvs, fork_at=max(2, target - 2), tip=target
    )
    c = LightClient(
        CHAIN_ID,
        trust,
        MockProvider(list(forked.values()), name="byzantine-primary"),
        [plane.provider("honest-witness")],
        LightStore(MemKV()),
        now_ns=now_fn,
    )
    detected = False
    pool_size = 0
    try:
        await c.verify_light_block_at_height(target)
    except ErrLightClientAttack as e:
        detected = True
        pool = EvidencePool(MemKV(), ss, bs)
        pool.add_evidence(e.evidence)
        pool_size = len(pool.pending_evidence())
        out["evidence_is_light_attack"] = isinstance(
            e.evidence, LightClientAttackEvidence
        )
    out["divergent_witness"] = {
        "attack_detected": detected,
        "evidence_pool_size": pool_size,
    }

    # --- forged header: tampered witness removed, sync completes -------
    tampered = copy.deepcopy(plane.cache.get(target))
    tampered.header.app_hash = b"tampered"
    tampered.header._hash = None
    bad_blocks = [
        (tampered if h == target else plane.cache.get(h))
        for h in range(1, target + 1)
    ]
    c2 = LightClient(
        CHAIN_ID,
        trust,
        plane.provider(),
        [
            MockProvider(bad_blocks, name="forged-witness"),
            plane.provider("honest-witness"),
        ],
        LightStore(MemKV()),
        now_ns=now_fn,
    )
    lb = await c2.verify_light_block_at_height(target)
    out["forged_header"] = {
        "synced": lb.height == target,
        "forged_witness_removed": (
            [w.id() for w in c2.witnesses] == ["honest-witness"]
        ),
    }
    return out


def run_swarm(
    n_clients: int = 1000,
    heights: int = 8,
    n_vals: int = 4,
    dedup_window_s: float = 60.0,
    with_attack: bool = True,
) -> dict:
    """The whole harness: net -> plane -> swarm -> attack scenarios.
    Returns one JSON-able stats dict (see module docstring)."""
    from tests.helpers import CHAIN_ID
    from tendermint_tpu.crypto.shape_registry import (
        ShapeRegistry,
        default_shape_registry,
    )
    from tendermint_tpu.libs.metrics import (
        LightServeMetrics,
        Registry,
        SchedulerMetrics,
    )
    from tendermint_tpu.light.client import TrustOptions
    from tendermint_tpu.lightserve import LightServePlane
    from tendermint_tpu.parallel.scheduler import VerifyScheduler

    async def run() -> dict:
        bs, ss, vs, pvs = await drive_net(heights, n_vals)
        # the tip's commit is still the seen commit (no canonical one
        # until height+1 exists), so the swarm targets one below it —
        # every served height is then durable and cacheable
        target = bs.height - 1
        reg = Registry("lightserve_bench")
        scheduler = VerifyScheduler(metrics=SchedulerMetrics(reg))
        await scheduler.start()
        plane = LightServePlane(
            bs,
            ss,
            CHAIN_ID,
            dedup_window_ns=int(dedup_window_s * 1e9),
            verifier=scheduler.classed("lightserve"),
            metrics=LightServeMetrics(reg),
        )
        root = plane.cache.get(1)
        trust = TrustOptions(
            TRUSTING_PERIOD_NS, 1, root.header.hash()
        )
        now_fn = time.time_ns
        before = default_shape_registry().snapshot()
        try:
            stats = await _swarm_sync(
                plane, target, n_clients, now_fn, trust
            )
            if with_attack:
                stats["scenarios"] = await _attack_scenarios(
                    plane, bs, ss, vs, pvs, target, now_fn, trust
                )
        finally:
            await scheduler.stop()
        delta = ShapeRegistry.delta(
            before, default_shape_registry().snapshot()
        )
        stats.update(
            {
                "net_heights": bs.height,
                "target_height": target,
                "n_validators": n_vals,
                "cache": plane.cache.stats(),
                "verify": plane.verifier.stats(),
                "registry_delta": delta,
                # the metrics counters, NOT dispatch_log (a deque capped
                # at 1024 — a big swarm would silently under-report)
                "scheduler_rounds": int(
                    scheduler.metrics.dispatches.value()
                ),
                "scheduler_coalesced_rounds": int(
                    scheduler.metrics.dispatch_coalesced.value()
                ),
                "requests_per_device_dispatch": round(
                    plane.verifier.requests
                    / max(1, delta["device_dispatch_count"]),
                    1,
                ),
            }
        )
        return stats

    return asyncio.run(run())


def main() -> int:
    ap = argparse.ArgumentParser(
        description="light-client swarm bench over the serving plane"
    )
    ap.add_argument("--clients", type=int, default=1000)
    ap.add_argument("--heights", type=int, default=8)
    ap.add_argument("--vals", type=int, default=4)
    ap.add_argument("--dedup-window", type=float, default=60.0)
    ap.add_argument(
        "--no-attack", action="store_true",
        help="skip the divergent-witness / forged-header scenarios",
    )
    args = ap.parse_args()
    stats = run_swarm(
        n_clients=args.clients,
        heights=args.heights,
        n_vals=args.vals,
        dedup_window_s=args.dedup_window,
        with_attack=not args.no_attack,
    )
    print(json.dumps(stats, indent=1))
    return 0 if stats["synced"] == stats["n_clients"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
