"""Wall-per-height attribution: timeout floor vs gossip vs compute.

Reads trace dumps (the `dump_traces` RPC shape, a bare record list, or
several per-validator dump files) and answers the question PERF_ANALYSIS
§12 left open: now that the commit pipeline moved finalize compute off
the critical path, WHERE does a height's remaining wall clock go — the
static timeout floor (cs.new_height / *_wait step spans), waiting on
peers (cs.propose / cs.prevote / cs.precommit), or the decision itself
(cs.commit)?

When the dump carries `pacing.decision` events (consensus/pacing.py with
[consensus] adaptive_timeouts on), the report also shows per step what
the controller LEARNED from the live arrival tail vs the static config
schedule, and where its AIMD back-off level sits — the before/after of
the adaptive-pacing loop in one table.

For the consensus family the report also renders the wall-clock
CONSERVATION audit (obs.report.wall_conservation): every height's wall
decomposed into mutually-exclusive named buckets — floor / gossip /
compute plus the carved verify IPC/queue/device, WAL fsync and commit
pipeline slices — with the unowned residue called out as `dark_time`
instead of folded into `other`. This is the ground truth the ROADMAP
item-4 controller work consumes.

Usage:
    python tools/pacing_report.py dump.json [dump2.json ...] [--json]
    curl -s localhost:26657/dump_traces | python tools/pacing_report.py -
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tendermint_tpu.obs import (
    FAMILY_WALL_SPANS,
    conservation_table,
    pacing_decisions,
    wall_attribution,
    wall_conservation,
)
from tools.trace_report import extract_records


def _load(path: str):
    if path == "-":
        return json.load(sys.stdin)
    with open(path) as f:
        return json.load(f)


def report(
    records: list[dict], n_heights: int = 64, family: str = "consensus"
) -> dict:
    out = {
        "wall": wall_attribution(records, n_heights, family=family),
        "pacing": pacing_decisions(records),
    }
    if family == "consensus":
        # the exhaustive bucket audit rides the cs.* step spans, so it
        # only applies to the consensus-classified families — item 4's
        # controller work reads the verify/WAL/pipeline buckets (and
        # the dark residue) from here
        out["conservation"] = wall_conservation(records, n_heights)
    return out


def report_text(rep: dict, name: str = "") -> str:
    lines = []
    wall = rep["wall"]
    agg = wall.get("aggregate") or {}
    title = "wall-per-height attribution"
    if name:
        title += f" — {name}"
    lines.append(title)
    if not agg:
        lines.append("  (no height spans in dump)")
        return "\n".join(lines)
    lines.append(
        f"  {agg['n_heights']} heights, wall p50 {agg['wall_ms_p50']} ms, "
        f"p95 {agg['wall_ms_p95']} ms, max {agg['wall_ms_max']} ms"
    )
    lines.append(
        f"  shares: timeout floor {agg['floor_share']:.1%}, "
        f"gossip {agg['gossip_share']:.1%}, "
        f"compute {agg['compute_share']:.1%}"
    )
    lines.append(
        f"  {'height':>8} {'wall_ms':>9} {'floor_ms':>9} {'gossip_ms':>9} "
        f"{'compute_ms':>10} {'other_ms':>9}"
    )
    for h in sorted(wall["heights"]):
        v = wall["heights"][h]
        lines.append(
            f"  {h:>8} {v['wall_ms']:>9.2f} {v['floor_ms']:>9.2f} "
            f"{v['gossip_ms']:>9.2f} {v['compute_ms']:>10.2f} "
            f"{v['other_ms']:>9.2f}"
        )
    cons = rep.get("conservation")
    if cons is not None:
        lines.append(conservation_table(cons))
    pacing = rep["pacing"]
    if pacing:
        lines.append("pacing decisions (learned vs static)")
        lines.append(
            f"  {'step':<10} {'static_ms':>9} {'learned_ms':>10} "
            f"{'eff_p50':>9} {'eff_last':>9} {'backoff':>8} {'n':>5}"
        )
        for step in ("propose", "prevote", "precommit", "commit"):
            if step not in pacing:
                continue
            p = pacing[step]
            lines.append(
                f"  {step:<10} {p['static_ms']:>9.2f} "
                f"{p['learned_ms_last']:>10.2f} "
                f"{p['effective_ms_p50']:>9.2f} "
                f"{p['effective_ms_last']:>9.2f} "
                f"{p['backoff_last']:>8.3f} {p['decisions']:>5}"
            )
    else:
        lines.append(
            "pacing decisions: none recorded (adaptive_timeouts off or "
            "tracing disabled)"
        )
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(
        description="wall-per-height attribution from trace dumps "
        "(timeout floor vs gossip vs compute + pacing decisions)"
    )
    ap.add_argument("dumps", nargs="+", help="dump file(s), or - for stdin")
    ap.add_argument(
        "--heights", type=int, default=64, help="max heights to report"
    )
    ap.add_argument(
        "--family",
        choices=sorted(FAMILY_WALL_SPANS),
        default="consensus",
        help="wall-attribution span classification: 'consensus' (cs.* "
        "step spans; also the committee_scale bench family) or "
        "'sequencer' (seq.* spans of the BlockV2 streaming plane, "
        "heights are V2 heights)",
    )
    ap.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    args = ap.parse_args()

    out = {}
    for path in args.dumps:
        doc = _load(path)
        name = (
            doc.get("moniker")
            if isinstance(doc, dict) and doc.get("moniker")
            else (os.path.splitext(os.path.basename(path))[0] if path != "-" else "stdin")
        )
        out[name] = report(
            extract_records(doc), args.heights, family=args.family
        )
    if args.json:
        print(json.dumps(out, indent=1, sort_keys=True))
    else:
        print(
            "\n\n".join(
                report_text(rep, name if len(out) > 1 else "")
                for name, rep in out.items()
            )
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
