"""Blocksync bulk-replay throughput: many blocks' commits, one device batch.

BASELINE config 4's shape ("blocksync replay, 10k blocks x 1k validators")
scaled to the harness: B blocks x V validators verified through
ValidatorSet.verify_commits_light (the windowed blocksync path) vs the
per-block loop. Usage: python tools/bench_replay.py [blocks] [validators]
"""

import sys
import time

sys.path.insert(0, ".")

from tests.helpers import CHAIN_ID, make_validators, sign_commit  # noqa: E402
from tendermint_tpu.crypto.batch_verifier import BatchVerifier  # noqa: E402
from tendermint_tpu.types.block_id import BlockID  # noqa: E402
from tendermint_tpu.types.part_set import PartSetHeader  # noqa: E402

BLOCKS = int(sys.argv[1]) if len(sys.argv) > 1 else 64
VALS = int(sys.argv[2]) if len(sys.argv) > 2 else 128


def main():
    print(f"# building {BLOCKS} commits x {VALS} validators...", flush=True)
    vs, pvs = make_validators(VALS)
    entries = []
    for h in range(1, BLOCKS + 1):
        hb = h.to_bytes(4, "big") * 8
        bid = BlockID(hb, PartSetHeader(1, hb))
        entries.append((bid, h, sign_commit(vs, pvs, h, 0, bid)))
    n_sigs = BLOCKS * VALS

    verifier = BatchVerifier()
    verifier.warm([v.pub_key.data for v in vs.validators], bulk=True)

    # warm the jit for this batch bucket
    verdicts = vs.verify_commits_light(CHAIN_ID, entries, verifier=verifier)
    assert all(verdicts)

    t0 = time.perf_counter()
    verdicts = vs.verify_commits_light(CHAIN_ID, entries, verifier=verifier)
    dt_batch = time.perf_counter() - t0
    assert all(verdicts)

    t0 = time.perf_counter()
    for bid, h, commit in entries:
        vs.verify_commit_light(CHAIN_ID, bid, h, commit, verifier=verifier)
    dt_per_block = time.perf_counter() - t0

    print(
        f"windowed (1 device batch): {n_sigs/dt_batch:,.0f} sigs/s "
        f"({dt_batch*1e3:.0f} ms for {n_sigs} sigs)"
    )
    print(
        f"per-block (1 call/commit): {n_sigs/dt_per_block:,.0f} sigs/s "
        f"({dt_per_block*1e3:.0f} ms)"
    )
    print(f"speedup: {dt_per_block/dt_batch:.1f}x")


if __name__ == "__main__":
    main()
