"""Render a flight-recorder dump as per-height ASCII step timelines.

Input: JSON from the `dump_traces` RPC route (or any file holding either
that response shape, a bare record list, or a Chrome trace export written
by `Tracer.to_chrome_trace`). Output: one step-timeline table per height
plus the aggregate latency-attribution table — the artifact a failing
soak seed ships with, so a divergence report explains where the stalled
height's time went without re-running anything.

With several dumps (one per validator) the per-height tables render
side-by-side: one duration column per node, so a step that is slow on
ONE validator stands out against its peers. For clock-rebased merging
and the slowest-path report, use tools/cluster_trace.py.

Usage:
    python tools/trace_report.py dump.json [dump2.json ...] [--heights N]
    curl -s localhost:26657/dump_traces | python tools/trace_report.py -
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tendermint_tpu.obs import (
    ascii_timeline,
    attribution_table,
    side_by_side_timeline,
)


def extract_records(doc) -> list[dict]:
    """Normalize any of the supported dump shapes to a record list."""
    if isinstance(doc, list):
        return doc
    if not isinstance(doc, dict):
        raise ValueError("unrecognized trace dump shape")
    if "records" in doc:
        return doc["records"]
    if "result" in doc and isinstance(doc["result"], dict):
        return extract_records(doc["result"])
    if "traceEvents" in doc or (
        "trace" in doc and isinstance(doc["trace"], dict)
    ):
        events = (doc.get("trace") or doc)["traceEvents"]
        return [
            {
                "name": e.get("name", ""),
                "t0": e.get("ts", 0.0) / 1e6,
                "dur": e.get("dur", 0.0) / 1e6,
                "height": (e.get("args") or {}).get("height", e.get("tid", 0)),
                "round": (e.get("args") or {}).get("round", 0),
                "kind": "span" if e.get("ph") == "X" else "event",
                "fields": {
                    k: v
                    for k, v in (e.get("args") or {}).items()
                    if k not in ("height", "round")
                },
            }
            for e in events
        ]
    raise ValueError("unrecognized trace dump shape")


def render(doc, n_heights: int = 16) -> str:
    records = extract_records(doc)
    return "\n\n".join(
        [ascii_timeline(records, n_heights), attribution_table(records)]
    )


def render_many(named_docs: dict[str, object], n_heights: int = 16) -> str:
    """Side-by-side node columns plus the pooled attribution table."""
    named_records = {
        name: extract_records(doc) for name, doc in named_docs.items()
    }
    pooled = [r for recs in named_records.values() for r in recs]
    return "\n\n".join(
        [
            side_by_side_timeline(named_records, n_heights),
            attribution_table(pooled),
        ]
    )


def _load(path: str):
    if path == "-":
        return json.load(sys.stdin)
    with open(path) as f:
        return json.load(f)


def _name_for(path: str, doc, taken: set) -> str:
    name = ""
    if isinstance(doc, dict):
        name = doc.get("moniker") or (doc.get("node_id") or "")[:12]
    if not name:
        name = os.path.splitext(os.path.basename(path))[0] or "stdin"
    base, i = name, 1
    while name in taken:
        i += 1
        name = f"{base}#{i}"
    taken.add(name)
    return name


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="+",
                    help="dump file(s), or - for stdin; several files "
                         "render side-by-side node columns")
    ap.add_argument("--heights", type=int, default=16,
                    help="show the last N heights (default 16)")
    args = ap.parse_args(argv)
    if len(args.paths) == 1:
        print(render(_load(args.paths[0]), args.heights))
        return 0
    named: dict[str, object] = {}
    taken: set = set()
    for p in args.paths:
        doc = _load(p)
        named[_name_for(p, doc, taken)] = doc
    print(render_many(named, args.heights))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
