"""Merge per-validator flight-recorder dumps into one cluster timeline.

Pulls `dump_traces` from every validator (or reads saved dump files),
estimates each node's clock offset from the ping/pong NTP tables (min-RTT
paths through the peer graph, so one delayed link can't bias the merge;
wall anchors as fallback), rebases all spans onto one reference timeline,
and emits:

- a merged Chrome trace_event JSON (one Perfetto process per node),
- the per-height "slowest path" report: proposer -> proposal gossip per
  node -> quorum-closing vote, plus link and straggler rankings.

Usage:
    python tools/cluster_trace.py dump0.json dump1.json ... [options]
    python tools/cluster_trace.py --rpc host:26657 --rpc host:26658 ...

Options:
    --out merged_trace.json   write the merged Perfetto trace
    --json report.json        write the full cluster-report JSON
    --reference NAME          reference node (default: first dump)
    --heights N               last N heights in the report (default 16)

Inputs may be raw `dump_traces` responses, JSON-RPC envelopes, or the
`{"node_id", "records", ...}` dumps tools/soak.py attaches on divergence.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tendermint_tpu import obs


def fetch_dump(addr: str, timeout: float = 10.0) -> dict:
    """Pull dump_traces from a node's JSON-RPC endpoint (host:port or a
    full http URL)."""
    url = addr if addr.startswith("http") else f"http://{addr}"
    req = urllib.request.Request(
        url,
        data=json.dumps(
            {"jsonrpc": "2.0", "id": 1, "method": "dump_traces", "params": {}}
        ).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        doc = json.load(resp)
    if "error" in doc and doc["error"]:
        raise RuntimeError(f"{addr}: RPC error {doc['error']}")
    return doc


def load_dumps(paths: list[str], rpcs: list[str]) -> list[dict]:
    dumps = []
    for p in paths:
        with open(p) as f:
            doc = json.load(f)
        name = os.path.splitext(os.path.basename(p))[0]
        dumps.append(obs.normalize_dump(doc, name=name))
    for addr in rpcs:
        dumps.append(obs.normalize_dump(fetch_dump(addr)))
    # node ids must be distinct for the offset graph; synthesize for
    # id-less dumps (hand-built files)
    seen: set[str] = set()
    for i, d in enumerate(dumps):
        if not d["node_id"] or d["node_id"] in seen:
            d["node_id"] = f"{d['node_id'] or 'anon'}#{i}"
        seen.add(d["node_id"])
    return dumps


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="*", help="dump_traces JSON files")
    ap.add_argument("--rpc", action="append", default=[],
                    help="pull dump_traces from host:port (repeatable)")
    ap.add_argument("--out", help="write merged Perfetto trace JSON here")
    ap.add_argument("--json", dest="json_out",
                    help="write the cluster-report JSON here")
    ap.add_argument("--reference", default="",
                    help="reference node name or id (default: first dump)")
    ap.add_argument("--heights", type=int, default=16)
    args = ap.parse_args(argv)
    if not args.paths and not args.rpc:
        ap.error("need at least one dump file or --rpc endpoint")

    dumps = load_dumps(args.paths, args.rpc)
    ref = ""
    if args.reference:  # accept a display name or a node id
        matches = [
            d
            for d in dumps
            if args.reference in (d["name"], d["node_id"])
        ]
        if not matches:
            ap.error(
                f"--reference {args.reference!r} matches no dump "
                f"(names: {[d['name'] for d in dumps]})"
            )
        ref = matches[0]["node_id"]
    merge = obs.merge_records(dumps, reference=ref)
    report = obs.cluster_report(dumps, n_heights=args.heights, merge=merge)
    if args.out:
        from tendermint_tpu.obs.cluster import to_chrome_trace

        with open(args.out, "w") as f:
            json.dump(to_chrome_trace(merge[2], dumps), f)
        print(f"# merged Perfetto trace -> {args.out}", file=sys.stderr)
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(report, f, indent=1)
        print(f"# cluster report JSON -> {args.json_out}", file=sys.stderr)
    print(obs.report_text(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
