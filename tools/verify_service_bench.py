"""Multi-process verify-service bench harness (bench.py --family
verify_service).

The missing measurement behind ROADMAP's verify-as-a-service item:
PR 9's committee-scale live nets stub signature verification above 32
validators because a single-process event loop cannot absorb 100 nodes'
device verifies — so the committee-crypto cost model from "Performance
of EdDSA and BLS Signatures in Committee-Based Consensus" (PAPERS.md)
had never been measured end-to-end on this stack. This harness measures
it on the production topology instead of a bigger event loop:

- ONE verify-service process (`python -m tendermint_tpu
  verify-service`) owns the device plane: the scheduler, the
  BatchVerifier, the shape registry, the DispatchLedger;
- N "node" submission loops spread across real OS processes, each with
  its OWN RemoteVerifyScheduler connection, drive one committee round
  of REAL crypto per height: n ed25519 vote verifies (genuine
  signatures over per-validator vote bytes, verified by the service's
  real BatchVerifier) plus the round's n-signer BLS dual-sign aggregate
  group on the wire fn lane (`bls_agg`: real BLS12-381 keys, one
  random-linear-combination aggregate per group). A node's height
  completes when BOTH verdict sets return all-true — the verify
  critical path of a consensus round, without the gossip plane the
  committee_scale family already prices.

Per size the harness records wall-per-height, the service-side
DispatchLedger summary (requests-per-dispatch proves CROSS-PROCESS
coalescing: submissions from different OS processes landing in one
padded device round), client-side IPC round-trip stats, and the degrade
count (must be zero on a healthy run — the artifact is dishonest
otherwise and says so).

Worker mode (`--worker`) is how the parent spawns the node processes;
the committee fixture is deterministic (seeded keys), so every process
builds identical votes without any key-distribution channel.
"""

from __future__ import annotations

import argparse
import asyncio
import hashlib
import json
import os
from typing import Optional
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

# one committee round's BLS batch point: every validator dual-signs the
# same batch hash (consensus/state.go:2560 semantics)
BATCH_HASH = hashlib.sha256(b"verify-service-bench-batch-point").digest()

# service rounds cap: on the CPU bench harness the bulk buckets past
# 2048 pay multi-minute cold XLA compiles for no extra signal (the
# amortization curve is visible at 2048); operators on real silicon
# raise it back to the 16384 knee
DEFAULT_SERVICE_MAX_BATCH = 2048


def committee_fixture(n: int):
    """Deterministic committee: n ed25519 (pub, msg, sig) vote rows and
    n BLS (pub_bytes, BATCH_HASH, sig_bytes) aggregate-group items —
    identical in every process that builds it."""
    from tendermint_tpu.crypto import bls_signatures as bls
    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.crypto.batch_verifier import SigItem

    ed_items = []
    bls_items = []
    for i in range(n):
        pk = ed25519.PrivKey.from_secret(b"vsbench-ed-%06d" % i)
        msg = b"vsbench-vote|v%06d|" % i + b"\x00" * 45  # 64B vote bytes
        ed_items.append(
            SigItem(pk.public_key().data, msg, pk.sign(msg))
        )
        priv = 90021 + i
        bls_items.append(
            (
                bls.public_key_to_bytes(bls.pubkey_from_priv(priv)),
                BATCH_HASH,
                bls.signer_for(priv)(BATCH_HASH),
            )
        )
    return ed_items, bls_items


def _local_bls_fallback(bls_items):
    """Degrade path for the wire fn lane: the same aggregate math the
    service runs, executed locally (verify_service.BUILTIN_ENGINES)."""
    from tendermint_tpu.parallel.verify_service import _engine_bls_agg

    return _engine_bls_agg(bls_items)


# --- worker ------------------------------------------------------------------


class _HeightBarrier:
    """Per-worker height alignment (generation barrier): real
    validators enter a height together — consensus itself synchronizes
    them — so the harness's node loops align per height too; without
    it, drifted nodes interleave sig and fn submissions in the service
    queue and the measurement becomes arrival noise instead of the
    verify plane."""

    def __init__(self, parties: int):
        self.parties = parties
        self._count = 0
        self._ev = asyncio.Event()

    async def wait(self) -> None:
        ev = self._ev
        self._count += 1
        if self._count >= self.parties:
            self._count = 0
            self._ev = asyncio.Event()
            ev.set()
        else:
            await ev.wait()


async def _run_node(
    socket_path: str,
    node_idx: int,
    ed_items,
    bls_items,
    warm: int,
    heights: int,
    out: dict,
    barrier: Optional[_HeightBarrier] = None,
) -> None:
    """One validator node's submission loop over its own service
    connection: per height, the round's ed25519 votes + the BLS batch
    point, barriered on both verdict sets like a consensus round."""
    from tendermint_tpu.parallel.verify_service import (
        RemoteVerifyScheduler,
    )

    remote = RemoteVerifyScheduler(socket_path)
    await remote.start()
    deadline = time.monotonic() + 60.0
    while not remote.connected and time.monotonic() < deadline:
        await asyncio.sleep(0.02)
    if not remote.connected:
        raise RuntimeError(f"node {node_idx}: service never attached")
    walls = []
    t_measure_start = None
    try:
        ipc_base = None
        for h in range(warm + heights):
            if barrier is not None:
                await barrier.wait()
            if h == warm:
                t_measure_start = time.monotonic()
                # measured-window IPC accounting: the warm heights pay
                # the service's one-off bucket compiles, and a
                # cumulative RTT mean would smear those stalls over
                # the steady-state rows
                ipc_base = remote.ipc_stats()
            t0 = time.monotonic()
            # phased like a consensus round: the round's votes verify
            # first, then the commit's BLS batch point. Phasing also
            # keeps the class queue un-interleaved — an fn round at a
            # class head ends the sig round being assembled, so a
            # node alternating sig/fn submissions would break up the
            # very cross-process coalescing this harness measures
            ed_v = await remote.submit(ed_items, "consensus")
            bls_v = await remote.submit_wire_fn(
                "bls_agg",
                bls_items,
                "consensus",
                fallback=lambda: _local_bls_fallback(bls_items),
            )
            if not all(bool(v) for v in ed_v):
                raise RuntimeError(
                    f"node {node_idx} h{h}: ed25519 verdicts not "
                    f"all-true ({int(sum(ed_v))}/{len(ed_v)})"
                )
            if not all(bool(v) for v in bls_v):
                raise RuntimeError(
                    f"node {node_idx} h{h}: BLS verdicts not all-true"
                )
            if h >= warm:
                walls.append(time.monotonic() - t0)
        final = remote.ipc_stats()
        base = ipc_base or {}
        out["nodes"].append(
            {
                "node": node_idx,
                "height_walls_s": walls,
                "t_measure_start": t_measure_start,
                "t_end": time.monotonic(),
                # measured-window deltas; degrades stays cumulative
                # (a degrade ANYWHERE in the run taints the row)
                "ipc": {
                    "rtt_count": final["rtt_count"]
                    - base.get("rtt_count", 0),
                    "rtt_sum_s": final["rtt_sum_s"]
                    - base.get("rtt_sum_s", 0.0),
                    "remote_submissions": final["remote_submissions"]
                    - base.get("remote_submissions", 0),
                    "degrades": final["degrades"],
                    "reconnects": final["reconnects"],
                    "connected": final["connected"],
                },
            }
        )
    finally:
        await remote.stop()


def run_worker(args) -> int:
    ed_items, bls_items = committee_fixture(args.validators)
    out = {"nodes": [], "error": None}

    async def run():
        barrier = _HeightBarrier(args.node_hi - args.node_lo)
        await asyncio.gather(
            *(
                _run_node(
                    args.socket,
                    idx,
                    ed_items,
                    bls_items,
                    args.warm,
                    args.heights,
                    out,
                    barrier=barrier,
                )
                for idx in range(args.node_lo, args.node_hi)
            )
        )

    try:
        asyncio.run(run())
    except Exception as e:  # structured failure, parent aggregates
        out["error"] = repr(e)
    print(json.dumps(out), flush=True)
    return 0 if out["error"] is None else 1


# --- parent orchestration ---------------------------------------------------


def _spawn_service(
    socket_path: str, max_batch: int, timeout: float = 120.0
):
    """The service process + its readiness line (ready_fd pipe)."""
    rfd, wfd = os.pipe()
    log_path = socket_path + ".log"
    with open(log_path, "wb") as log:
        proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "tendermint_tpu",
                "verify-service",
                "--socket",
                socket_path,
                "--max-batch",
                str(max_batch),
                "--ready-fd",
                str(wfd),
            ],
            pass_fds=(wfd,),
            cwd=REPO_ROOT,
            stderr=log,
        )
    os.close(wfd)
    os.set_blocking(rfd, False)
    ready = b""
    deadline = time.monotonic() + timeout
    try:
        while time.monotonic() < deadline:
            try:
                chunk = os.read(rfd, 4096)
            except BlockingIOError:
                chunk = None
            if chunk:
                ready += chunk
                break
            if chunk == b"" or proc.poll() is not None:
                break
            time.sleep(0.05)
    finally:
        os.close(rfd)
    if not ready:
        proc.terminate()
        try:
            with open(log_path, "rb") as f:
                tail = f.read()[-2000:].decode(errors="replace")
        except OSError:
            tail = ""
        raise RuntimeError(
            f"verify service never signaled ready "
            f"(rc={proc.poll()}): {tail}"
        )
    return proc


async def _service_dump(socket_path: str) -> dict:
    """One STATS frame over the UDS — the service-side ledger summary +
    tenant table, pulled while the service is still up."""
    from tendermint_tpu.parallel.verify_service import (
        MSG_STATS,
        MSG_STATS_RESULT,
        _Cursor,
        _HDR,
        read_frame,
        write_frame,
    )

    reader, writer = await asyncio.open_unix_connection(socket_path)
    try:
        write_frame(writer, _HDR.pack(MSG_STATS, 1))
        await writer.drain()
        frame = await asyncio.wait_for(read_frame(reader), timeout=30.0)
        cur = _Cursor(frame)
        typ, _ = _HDR.unpack(cur.take(_HDR.size))
        assert typ == MSG_STATS_RESULT, f"unexpected frame {typ}"
        return json.loads(cur.bytes32())
    finally:
        writer.close()


def _split_nodes(n: int, procs: int) -> list[tuple[int, int]]:
    """Contiguous [lo, hi) node ranges, sizes differing by at most 1."""
    base, rem = divmod(n, procs)
    spans, lo = [], 0
    for p in range(procs):
        hi = lo + base + (1 if p < rem else 0)
        spans.append((lo, hi))
        lo = hi
    return [s for s in spans if s[1] > s[0]]


def run_size(
    n: int,
    heights: int = 2,
    warm: int = 2,
    max_procs: int = 8,
    service_max_batch: int = DEFAULT_SERVICE_MAX_BATCH,
    sock_dir: str = "/tmp",
) -> dict:
    """One verify_service measurement row: a fresh service process + the
    n-validator committee split across min(n, max_procs) node
    processes."""
    socket_path = os.path.join(
        sock_dir, f"vsbench-{os.getpid()}-{n}.sock"
    )
    spans = _split_nodes(n, min(n, max_procs))
    service = _spawn_service(socket_path, service_max_batch)
    workers = []
    try:
        t_spawn = time.monotonic()
        for lo, hi in spans:
            workers.append(
                subprocess.Popen(
                    [
                        sys.executable,
                        os.path.abspath(__file__),
                        "--worker",
                        "--socket",
                        socket_path,
                        "--validators",
                        str(n),
                        "--node-lo",
                        str(lo),
                        "--node-hi",
                        str(hi),
                        "--heights",
                        str(heights),
                        "--warm",
                        str(warm),
                    ],
                    stdout=subprocess.PIPE,
                    text=True,
                    cwd=REPO_ROOT,
                )
            )
        # generous: cold worker first-height pays the service's bucket
        # compiles; CLOCK_MONOTONIC is host-wide so worker stamps merge
        timeout = 600 + n * 6 * (warm + heights)
        results, errors = [], []
        for w in workers:
            try:
                stdout, _ = w.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                w.kill()
                errors.append("worker timeout")
                continue
            try:
                doc = json.loads(stdout.strip().splitlines()[-1])
            except (ValueError, IndexError):
                errors.append(f"worker rc={w.returncode}: bad output")
                continue
            if doc.get("error"):
                errors.append(doc["error"])
            results.extend(doc.get("nodes", []))
        try:
            dump = asyncio.run(_service_dump(socket_path))
        except Exception as e:
            # a dead service is usually also WHY the workers errored —
            # the row must carry their errors, not just this one
            dump = {}
            errors.append(f"service dump failed: {e!r}")
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
        service.terminate()
        try:
            service.wait(timeout=30)
        except subprocess.TimeoutExpired:
            service.kill()
        try:
            os.unlink(socket_path)
        except OSError:
            pass

    if errors or len(results) != n:
        return {
            "n": n,
            "error": "; ".join(errors)
            or f"only {len(results)}/{n} node loops finished",
            "processes": len(spans),
        }
    # wall-per-height across the whole committee: first measured height
    # start to last node's finish (host-wide CLOCK_MONOTONIC)
    t_start = min(r["t_measure_start"] for r in results)
    t_end = max(r["t_end"] for r in results)
    wall_per_height = (t_end - t_start) / heights
    rtt_count = sum(r["ipc"]["rtt_count"] for r in results)
    rtt_sum = sum(r["ipc"]["rtt_sum_s"] for r in results)
    degrades = sum(r["ipc"]["degrades"] for r in results)
    summary = dump.get("summary", {})
    rounds = max(1, summary.get("rounds", 0))
    # cross-process coalescing on the SIG dispatch plane: the global
    # requests_per_dispatch is diluted by fn rounds, which are
    # one-submission-per-round by design (a BLS aggregate group is its
    # own engine round) — by_bucket covers sig rounds only
    by_bucket = summary.get("by_bucket") or {}
    sig_rounds = sum(b["rounds"] for b in by_bucket.values())
    sig_subs = sum(b["submissions"] for b in by_bucket.values())
    sig_rpd = round(sig_subs / sig_rounds, 3) if sig_rounds else 0.0
    # IPC overhead model (PERF_ANALYSIS §20): what the client pays on
    # top of the service-side work it waited for — mean RTT minus the
    # per-round device+prep mean and the per-submission queue wait
    subs = sum(
        c.get("submissions", 0)
        for c in (summary.get("per_class") or {}).values()
    )
    service_side_s = (
        summary.get("device_seconds", 0.0)
        + summary.get("host_prep_seconds", 0.0)
    ) / rounds + summary.get("queue_wait_seconds", 0.0) / max(1, subs)
    rtt_mean = rtt_sum / rtt_count if rtt_count else 0.0
    return {
        "n": n,
        "heights": heights,
        "processes": len(spans),
        "sig_verify": "real",  # ed25519 + BLS, no stub anywhere
        "wall_ms_per_height": round(wall_per_height * 1e3, 1),
        "requests_per_dispatch": sig_rpd,
        "requests_per_dispatch_all_rounds": summary.get(
            "requests_per_dispatch", 0.0
        ),
        "fill_ratio": summary.get("fill_ratio", 0.0),
        "fill_ratio_p50": summary.get("fill_ratio_p50", 0.0),
        "fill_ratio_p95": summary.get("fill_ratio_p95", 0.0),
        "ipc_rtt_mean_ms": round(rtt_mean * 1e3, 3),
        "ipc_overhead_ms": round(
            max(0.0, rtt_mean - service_side_s) * 1e3, 3
        ),
        "remote_submissions": sum(
            r["ipc"]["remote_submissions"] for r in results
        ),
        "degrades": degrades,
        "spawn_to_done_s": round(time.monotonic() - t_spawn, 1),
        "per_client_tenants": len(dump.get("per_client") or {}),
        "service_ledger": summary,
    }


def run_family(
    sizes=(4, 32, 100),
    heights: int = 2,
    warm: int = 2,
    max_procs: int = 8,
    service_max_batch: int = DEFAULT_SERVICE_MAX_BATCH,
) -> dict:
    """The bench.py --family verify_service payload: one row per
    committee size, headline wall-per-height at 32 validators."""
    rows = []
    for n in sizes:
        try:
            rows.append(
                run_size(
                    n,
                    heights=heights,
                    warm=warm,
                    max_procs=max_procs,
                    service_max_batch=service_max_batch,
                )
            )
        except Exception as e:
            rows.append({"n": n, "error": repr(e)})
        r = rows[-1]
        print(
            f"# verify_service n={n}: "
            + (
                f"wall {r['wall_ms_per_height']} ms/height, "
                f"reqs/dispatch {r['requests_per_dispatch']}, "
                f"rtt {r['ipc_rtt_mean_ms']} ms"
                if "error" not in r
                else f"FAILED {r['error']}"
            ),
            file=sys.stderr,
        )
    ok = [r for r in rows if "error" not in r]
    head = next(
        (r for r in ok if r["n"] == 32), ok[-1] if ok else None
    )
    # per-size extra_metrics rows are assembled by bench.py (the
    # artifact owner); this payload carries the raw rows
    head_n = head["n"] if head else 0
    return {
        "metric": f"verify_service_wall_per_height_n{head_n}",
        "value": head["wall_ms_per_height"] if head else 0.0,
        "unit": (
            f"ms/height: {head_n}-validator committee round of real "
            "ed25519 + BLS through ONE shared verify-service process "
            "over UDS IPC (cross-process coalesced rounds)"
        ),
        "vs_baseline": (
            head["requests_per_dispatch"] if head else 0.0
        ),
        "sizes": rows,
        "service_max_batch": service_max_batch,
        "max_procs": max_procs,
    }


def main() -> int:
    ap = argparse.ArgumentParser(
        description="multi-process verify-service bench harness"
    )
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--socket", default="")
    ap.add_argument("--validators", type=int, default=4)
    ap.add_argument("--node-lo", type=int, default=0)
    ap.add_argument("--node-hi", type=int, default=0)
    ap.add_argument("--heights", type=int, default=2)
    ap.add_argument("--warm", type=int, default=2)
    ap.add_argument("--sizes", default="4,32,100")
    ap.add_argument("--max-procs", type=int, default=8)
    ap.add_argument(
        "--service-max-batch",
        type=int,
        default=DEFAULT_SERVICE_MAX_BATCH,
    )
    args = ap.parse_args()
    if args.worker:
        return run_worker(args)
    sizes = tuple(int(s) for s in args.sizes.split(",") if s.strip())
    print(
        json.dumps(
            run_family(
                sizes=sizes,
                heights=args.heights,
                warm=args.warm,
                max_procs=args.max_procs,
                service_max_batch=args.service_max_batch,
            )
        )
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
