"""loadtime — sustained/burst tx load generation + streaming harness.

Reference: test/loadtime/ (tm-load-test based `load` + `report` reading
the blockstore, test/loadtime/README.md) and test/e2e/runner/benchmark.go
:13-76 (block-interval stats over an N-block window).

Same measurement design as the reference: each generated tx embeds its
send-time; the report walks committed blocks and computes per-tx latency
as (block time - embedded send time), plus block-interval min/avg/stddev/
max. The morph fork has no mempool — load enters through the L2 node's
block-data feed (l2node inject), which is where production txs come from
too (SURVEY.md §3.2).

Beyond the original burst tool, this grows two sustained-load pieces
(PERF_ANALYSIS §17):

- `SustainedLoadGenerator` — paced injection at a target tx/s into an
  L2 node's pending feed (the `request_block_data_v2` pull path), so a
  sequencer produces wire-rate blocks instead of one synthetic burst;
- `run_sequencer_stream` — a full-Node in-proc net (1 sequencer
  validator + N subscriber followers, star topology) that crosses
  `UpgradeBlockHeight` under load and measures blocks/s + MB/s through
  both planes (BFT gossip pre-upgrade, BlockV2 streaming post-upgrade),
  event-driven apply latency, encode-once fan-out, a chaos-shaped slow
  subscriber, and partition/heal catchup over the 0x51 sync channel.
  `bench.py --family sequencer_stream` drives it.

Usage:
    python tools/loadtime.py run     # in-proc node, burst load, report
    python tools/loadtime.py report --home <dir>   # report over a store
    python tools/loadtime.py stream --subscribers 8 --tx-rate 2000
"""

from __future__ import annotations

import argparse
import asyncio
import math
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TX_PREFIX = b"loadtime:"


def make_tx(seq: int, size: int = 128) -> bytes:
    """Payload embeds the send timestamp, as the reference's loadtime
    payload proto does (test/loadtime/payload/)."""
    head = TX_PREFIX + str(time.time_ns()).encode() + b":" + str(seq).encode()
    return head + b":" + b"x" * max(0, size - len(head) - 1)


def parse_tx_time(tx: bytes) -> int | None:
    if not tx.startswith(TX_PREFIX):
        return None
    try:
        return int(tx.split(b":", 3)[1])
    except (IndexError, ValueError):
        return None


def report_from_store(block_store, first: int = 1, last: int = 0) -> dict:
    """Latency + block-interval stats (benchmark.go:22-76 shape)."""
    last = last or block_store.height
    latencies_ms: list[float] = []
    intervals_s: list[float] = []
    n_txs = 0
    prev_time = None
    for h in range(max(first, block_store.base), last + 1):
        block = block_store.load_block(h)
        if block is None:
            continue
        bt = block.header.time_ns
        if prev_time is not None:
            intervals_s.append((bt - prev_time) / 1e9)
        prev_time = bt
        for tx in block.data.txs:
            n_txs += 1
            sent = parse_tx_time(tx)
            if sent is not None:
                latencies_ms.append((bt - sent) / 1e6)

    def stats(xs):
        if not xs:
            return {"min": 0, "avg": 0, "stddev": 0, "max": 0}
        return {
            "min": round(min(xs), 2),
            "avg": round(statistics.fmean(xs), 2),
            "stddev": round(statistics.pstdev(xs), 2) if len(xs) > 1 else 0,
            "max": round(max(xs), 2),
        }

    dur_s = (
        sum(intervals_s) if intervals_s else 0.0
    )
    return {
        "blocks": len(intervals_s) + 1 if prev_time is not None else 0,
        "txs": n_txs,
        "tx_per_s": round(n_txs / dur_s, 1) if dur_s else 0.0,
        "block_interval_s": stats(intervals_s),
        "tx_latency_ms": stats(latencies_ms),
    }


async def run_load(
    blocks: int = 10, rate: int = 50, tx_size: int = 128
) -> dict:
    """In-proc single-validator node under tx load; returns the report."""
    import tempfile

    from tendermint_tpu.config import Config
    from tendermint_tpu.l2node.mock import MockL2Node
    from tendermint_tpu.node import Node, init_files

    with tempfile.TemporaryDirectory() as home:
        cfg = Config.test_config()
        cfg.root_dir = home
        cfg.base.db_backend = "memory"
        cfg.rpc.laddr = "tcp://127.0.0.1:0"
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        init_files(cfg)
        l2 = MockL2Node(txs_per_block=0)
        node = Node(cfg, l2_node=l2)
        await node.start()
        seq = 0
        try:
            target = node.consensus.state.last_block_height + blocks
            # one burst per committed height: block cadence varies wildly
            # across machines, so pacing by wall clock makes the number
            # of committed txs (and the report) timing-dependent — pacing
            # by height guarantees >= (blocks-1)*rate txs land in blocks
            injected_at = None
            while node.consensus.state.last_block_height < target:
                h = node.consensus.state.last_block_height
                if h != injected_at:
                    burst = [make_tx(seq + i, tx_size) for i in range(rate)]
                    seq += rate
                    l2.inject_txs(burst)
                    injected_at = h
                await asyncio.sleep(0.02)
            return report_from_store(node.block_store)
        finally:
            await node.stop()


class SustainedLoadGenerator:
    """Paced tx injection at a target rate (tx/s) into an L2 node's
    pending feed — the sustained analog of the one-shot bursts above.
    Injection rides a fixed tick so the pending queue sees a steady
    arrival process instead of per-block bursts; `injected` counts
    everything fed so the harness can report offered vs committed."""

    def __init__(self, l2, rate: int, tx_size: int = 256, tick: float = 0.05):
        self.l2 = l2
        self.rate = max(1, int(rate))
        self.tx_size = tx_size
        self.tick = tick
        self.injected = 0
        self._task = None
        self._carry = 0.0

    async def _run(self) -> None:
        while True:
            self._carry += self.rate * self.tick
            n = int(self._carry)
            self._carry -= n
            if n:
                self.l2.inject_txs(
                    [
                        make_tx(self.injected + i, self.tx_size)
                        for i in range(n)
                    ]
                )
                self.injected += n
            await asyncio.sleep(self.tick)

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None


# --- sequencer streaming harness (ISSUE 10 / ROADMAP item 3) ---------------


def _pct(xs, q):
    """Shared percentile rule (obs.report.pct): the sequencer_stream
    rows must use the same index semantics as every other bench
    family's latency scalars."""
    from tendermint_tpu.obs.report import pct

    return pct(list(xs), q)


def _build_stream_node(
    home: str,
    genesis,
    *,
    switch_height: int,
    block_interval: float,
    seq_key_hex: str = "",
    seq_addr_hex: str = "",
    max_block_txs: int = 0,
):
    """One full Node for the streaming net: memory stores, no RPC/PEX,
    consensus-direct start (no configured peers — the harness dials),
    the default 10 s apply/sync fallback ticks UNTOUCHED (the plane must
    stream event-driven, not because the bench tightened the polling)."""
    import os as _os

    from tendermint_tpu.config import Config
    from tendermint_tpu.l2node.mock import MockL2Node
    from tendermint_tpu.node import Node, init_files

    cfg = Config.test_config()
    cfg.root_dir = home
    cfg.base.db_backend = "memory"
    cfg.rpc.laddr = ""
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.p2p.pex = False
    cfg.consensus.switch_height = switch_height
    cfg.sequencer.block_interval = block_interval
    if seq_key_hex:
        _os.makedirs(_os.path.join(home, "config"), exist_ok=True)
        with open(_os.path.join(home, "config", "sequencer_key"), "w") as f:
            f.write(seq_key_hex)
        cfg.sequencer.sequencer_key_file = "config/sequencer_key"
    if seq_addr_hex:
        cfg.sequencer.sequencer_addresses = seq_addr_hex
    init_files(cfg)
    # identical deterministic mocks across the net: the seeded V2 chains
    # must agree or followers reject the sequencer's parent hashes
    l2 = MockL2Node(txs_per_block=0, max_block_txs=max_block_txs)
    return Node(cfg, l2_node=l2, genesis=genesis), l2


async def _wait(cond, timeout: float, what: str) -> None:
    deadline = time.perf_counter() + timeout
    while not cond():
        if time.perf_counter() > deadline:
            raise TimeoutError(f"timed out waiting for {what}")
        await asyncio.sleep(0.02)


async def _stream_net(
    n_followers: int,
    switch_height: int,
    stream_blocks: int,
    tx_rate: int,
    tx_size: int,
    block_interval: float,
    max_block_txs: int,
    chaos_latency_s: float,
    timeout: float,
) -> dict:
    import tempfile

    from tendermint_tpu.chaos import ChaosNetwork, LinkPolicy, NodeHandle
    from tendermint_tpu.config import Config
    from tendermint_tpu.crypto import secp256k1
    from tendermint_tpu.node import init_files as _init
    from tendermint_tpu.sequencer import LocalSigner
    from tendermint_tpu.sequencer.broadcast_reactor import (
        SMALL_GAP_THRESHOLD,
    )
    from tendermint_tpu.types import block_v2 as bv2

    with tempfile.TemporaryDirectory() as root:
        # --- assemble: 1 sequencer validator + N subscriber followers --
        seq_key = secp256k1.PrivKey.from_secret(b"stream-bench-sequencer")
        seq_addr_hex = "0x" + LocalSigner(seq_key).address().hex()
        seq_home = os.path.join(root, "seq")
        os.makedirs(seq_home, exist_ok=True)
        # the sequencer's init_files generates the shared genesis (its
        # privval is the single validator)
        seq_cfg = Config.test_config()
        seq_cfg.root_dir = seq_home
        seq_cfg.base.db_backend = "memory"
        seq_cfg.rpc.laddr = ""
        seq_cfg.p2p.laddr = "tcp://127.0.0.1:0"
        genesis = _init(seq_cfg)
        seq_node, seq_l2 = _build_stream_node(
            seq_home,
            genesis,
            switch_height=switch_height,
            block_interval=block_interval,
            seq_key_hex=seq_key.bytes().hex(),
            max_block_txs=max_block_txs,
        )
        followers = []
        for i in range(n_followers):
            home = os.path.join(root, f"f{i}")
            os.makedirs(home, exist_ok=True)
            node, _l2 = _build_stream_node(
                home,
                genesis,
                switch_height=switch_height,
                block_interval=block_interval,
                seq_addr_hex=seq_addr_hex,
                max_block_txs=max_block_txs,
            )
            followers.append(node)
        nodes = [seq_node] + followers
        names = ["seq"] + [f"f{i}" for i in range(n_followers)]
        net = ChaosNetwork(seed=11)
        for name, node in zip(names, nodes):
            net.install(
                NodeHandle(
                    name=name,
                    cs=node.consensus,
                    node_key=node.node_key,
                    transport=node.transport,
                    switch=node.switch,
                    block_store=node.block_store,
                )
            )
        gen = SustainedLoadGenerator(seq_l2, rate=tx_rate, tx_size=tx_size)
        out: dict = {
            "n_followers": n_followers,
            "switch_height": switch_height,
            "tx_rate": tx_rate,
            "tx_size": tx_size,
            "block_interval": block_interval,
        }
        try:
            for node in nodes:
                await node.start()
            gen.start()
            from tendermint_tpu.p2p.transport import NetAddress

            seq_port = seq_node.transport.listen_port
            for node in followers:
                # persistent: the chaos heal in phase 5 reconnects via
                # the switch's persistent-redial machinery
                node.switch.dial_peers_async(
                    [NetAddress(seq_node.node_key.id, "127.0.0.1", seq_port)],
                    persistent=True,
                )
            await _wait(
                lambda: all(len(f.switch.peers) > 0 for f in followers),
                timeout,
                "followers to connect to the sequencer",
            )

            # --- phase 1: BFT plane to the upgrade height -------------
            t0 = time.perf_counter()
            await _wait(
                lambda: all(
                    f.consensus.state.last_block_height >= switch_height
                    for f in followers
                ),
                timeout,
                "followers to reach the upgrade height over BFT gossip",
            )
            pre_wall = time.perf_counter() - t0
            pre_bytes = 0
            for h in range(1, switch_height + 1):
                blk = seq_node.block_store.load_block(h)
                if blk is not None:
                    pre_bytes += len(blk.encode())
            out["pre_upgrade"] = {
                "blocks": switch_height,
                "wall_s": round(pre_wall, 3),
                "blocks_per_s": round(switch_height / pre_wall, 2),
                "mb_per_s": round(pre_bytes / pre_wall / 1e6, 3),
                "bytes": pre_bytes,
                "commit_pipeline": bool(seq_node.commit_pipeline),
            }

            # --- phase 2: the upgrade switch ---------------------------
            await _wait(
                lambda: all(
                    n.sequencer_reactor.sequencer_started for n in nodes
                ),
                timeout,
                "every node to switch to sequencer mode",
            )

            # --- phase 3: clean streaming window (encode-once + apply
            # latency + post-upgrade throughput) ------------------------
            for f in followers:
                f.sequencer_reactor.apply_latencies.clear()
            h0 = max(
                f.state_v2.latest_height() for f in followers
            )
            target = h0 + stream_blocks
            ser0 = bv2.serializations()
            bc0 = seq_node.sequencer_reactor.metrics.blocks_broadcast.value()
            t0 = time.perf_counter()
            await _wait(
                lambda: all(
                    f.state_v2.latest_height() >= target for f in followers
                ),
                timeout,
                f"{stream_blocks} streamed BlockV2s on every follower",
            )
            post_wall = time.perf_counter() - t0
            ser_delta = bv2.serializations() - ser0
            bcast = (
                seq_node.sequencer_reactor.metrics.blocks_broadcast.value()
                - bc0
            )
            post_bytes = 0
            for h in range(h0 + 1, target + 1):
                blk = seq_l2.get_block_by_number(h)
                if blk is not None:
                    post_bytes += len(blk.encode())
            lats = [
                lat
                for f in followers
                for lat in f.sequencer_reactor.apply_latencies
            ]
            out["post_upgrade"] = {
                "blocks": stream_blocks,
                "wall_s": round(post_wall, 3),
                "blocks_per_s": round(stream_blocks / post_wall, 2),
                "mb_per_s": round(post_bytes / post_wall / 1e6, 3),
                "fanout_mb_per_s": round(
                    post_bytes * n_followers / post_wall / 1e6, 3
                ),
                "bytes": post_bytes,
                "apply_latency_p50_ms": round(_pct(lats, 0.5) * 1e3, 2),
                "apply_latency_p95_ms": round(_pct(lats, 0.95) * 1e3, 2),
                "apply_latency_samples": len(lats),
                # one BlockV2 serialization per broadcast block is the
                # encode-once contract (star topology: nobody relays)
                "block_serializations": int(ser_delta),
                "blocks_broadcast": int(bcast),
                "encodes_per_broadcast_block": round(
                    ser_delta / max(1.0, bcast), 3
                ),
            }

            # --- phase 4: chaos slow subscriber ------------------------
            if chaos_latency_s > 0 and n_followers >= 2:
                slow = followers[0]
                healthy = followers[1:]
                net.set_link_policy(
                    "seq",
                    "f0",
                    LinkPolicy(latency_s=chaos_latency_s),
                    reverse=LinkPolicy(latency_s=chaos_latency_s),
                )
                h1 = max(f.state_v2.latest_height() for f in healthy)
                target = h1 + stream_blocks
                t0 = time.perf_counter()
                await _wait(
                    lambda: all(
                        f.state_v2.latest_height() >= target
                        for f in healthy
                    ),
                    timeout,
                    "healthy followers to stream past the shaped link",
                )
                chaos_wall = time.perf_counter() - t0
                out["chaos_slow_subscriber"] = {
                    "link_latency_ms": chaos_latency_s * 1e3,
                    "blocks": stream_blocks,
                    "healthy_wall_s": round(chaos_wall, 3),
                    "healthy_blocks_per_s": round(
                        stream_blocks / chaos_wall, 2
                    ),
                    "slow_follower_behind": int(
                        target - slow.state_v2.latest_height()
                    ),
                    "clean_blocks_per_s": out["post_upgrade"][
                        "blocks_per_s"
                    ],
                }
                net.set_link_policy(
                    "seq", "f0", LinkPolicy(), reverse=LinkPolicy()
                )

            # --- phase 5: partition + heal -> 0x51 windowed catchup ----
            lagger = followers[-1]
            await net.partition(
                "lag", [[n for n in names if n != names[-1]], [names[-1]]]
            )
            gap_from = lagger.state_v2.latest_height()
            target_gap = gap_from + SMALL_GAP_THRESHOLD + stream_blocks
            # the producer's own chain is the head; with >= 2 followers
            # also require the healthy ones to keep streaming (a lone
            # follower IS the lagger — `rest` may be empty)
            rest = [f for f in followers if f is not lagger] or [seq_node]
            await _wait(
                lambda: all(
                    f.state_v2.latest_height() >= target_gap for f in rest
                ),
                timeout,
                "a catchup backlog beyond the small-gap threshold",
            )
            lagger.sequencer_reactor.apply_latencies.clear()
            await net.heal("lag")
            await _wait(
                lambda: len(lagger.switch.peers) > 0,
                timeout,
                "the healed follower to redial the sequencer",
            )
            t0 = time.perf_counter()
            head = lambda: max(  # noqa: E731
                f.state_v2.latest_height() for f in rest
            )
            await _wait(
                lambda: lagger.state_v2.latest_height()
                >= head() - SMALL_GAP_THRESHOLD,
                timeout,
                "the healed follower to catch up over the sync channel",
            )
            catchup_wall = time.perf_counter() - t0
            clats = list(lagger.sequencer_reactor.apply_latencies)
            out["catchup_after_heal"] = {
                "blocks_behind": int(target_gap - gap_from),
                "wall_s": round(catchup_wall, 3),
                "apply_latency_p50_ms": round(_pct(clats, 0.5) * 1e3, 2),
                "apply_latency_p95_ms": round(_pct(clats, 0.95) * 1e3, 2),
                "requested_outstanding": len(
                    lagger.sequencer_reactor.requested_heights
                ),
                # the event-driven plane vs the reference's fixed tick:
                # a 10 s polling loop needs ceil(gap/window) cycles
                "polling_floor_s": 10.0,
            }
            out["injected_txs"] = gen.injected
        finally:
            await gen.stop()
            for node in nodes:
                try:
                    await node.stop()
                except Exception:
                    pass
    return out


def run_sequencer_stream(
    n_followers: int = 8,
    switch_height: int = 3,
    stream_blocks: int = 25,
    tx_rate: int = 2000,
    tx_size: int = 256,
    block_interval: float = 0.08,
    max_block_txs: int = 256,
    chaos_latency_s: float = 0.25,
    timeout: float = 240.0,
) -> dict:
    """Entry point for bench.py --family sequencer_stream and the
    `stream` CLI below. Returns the stats dict of _stream_net."""
    os.environ.setdefault("TM_TPU_SKIP_WARM", "1")
    return asyncio.run(
        _stream_net(
            n_followers=n_followers,
            switch_height=switch_height,
            stream_blocks=stream_blocks,
            tx_rate=tx_rate,
            tx_size=tx_size,
            block_interval=block_interval,
            max_block_txs=max_block_txs,
            chaos_latency_s=chaos_latency_s,
            timeout=timeout,
        )
    )


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    rp = sub.add_parser("run", help="in-proc node + burst load + report")
    rp.add_argument("--blocks", type=int, default=10)
    rp.add_argument("--rate", type=int, default=50)
    rp.add_argument("--size", type=int, default=128)
    gp = sub.add_parser("report", help="report over an existing home dir")
    gp.add_argument("--home", required=True)
    sp = sub.add_parser(
        "stream",
        help="sequencer streaming net: sustained load through the "
        "upgrade-height switch, N subscribers, chaos rows",
    )
    sp.add_argument("--subscribers", type=int, default=8)
    sp.add_argument("--switch-height", type=int, default=3)
    sp.add_argument("--stream-blocks", type=int, default=25)
    sp.add_argument("--tx-rate", type=int, default=2000)
    sp.add_argument("--tx-size", type=int, default=256)
    sp.add_argument("--block-interval", type=float, default=0.08)
    sp.add_argument("--chaos-latency-ms", type=float, default=250.0)
    args = ap.parse_args()

    import json

    if args.cmd == "run":
        rep = asyncio.run(
            run_load(blocks=args.blocks, rate=args.rate, tx_size=args.size)
        )
    elif args.cmd == "stream":
        rep = run_sequencer_stream(
            n_followers=args.subscribers,
            switch_height=args.switch_height,
            stream_blocks=args.stream_blocks,
            tx_rate=args.tx_rate,
            tx_size=args.tx_size,
            block_interval=args.block_interval,
            chaos_latency_s=args.chaos_latency_ms / 1e3,
        )
    else:
        from tendermint_tpu.store.block_store import BlockStore
        from tendermint_tpu.store.kv import SqliteKV

        bs = BlockStore(
            SqliteKV(os.path.join(args.home, "data", "blockstore.db"))
        )
        rep = report_from_store(bs)
    print(json.dumps(rep, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
