"""loadtime — tx load generation + latency report from the block store.

Reference: test/loadtime/ (tm-load-test based `load` + `report` reading
the blockstore, test/loadtime/README.md) and test/e2e/runner/benchmark.go
:13-76 (block-interval stats over an N-block window).

Same measurement design as the reference: each generated tx embeds its
send-time; the report walks committed blocks and computes per-tx latency
as (block time - embedded send time), plus block-interval min/avg/stddev/
max. The morph fork has no mempool — load enters through the L2 node's
block-data feed (l2node inject), which is where production txs come from
too (SURVEY.md §3.2).

Usage:
    python tools/loadtime.py run     # in-proc node, burst load, report
    python tools/loadtime.py report --home <dir>   # report over a store
"""

from __future__ import annotations

import argparse
import asyncio
import math
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TX_PREFIX = b"loadtime:"


def make_tx(seq: int, size: int = 128) -> bytes:
    """Payload embeds the send timestamp, as the reference's loadtime
    payload proto does (test/loadtime/payload/)."""
    head = TX_PREFIX + str(time.time_ns()).encode() + b":" + str(seq).encode()
    return head + b":" + b"x" * max(0, size - len(head) - 1)


def parse_tx_time(tx: bytes) -> int | None:
    if not tx.startswith(TX_PREFIX):
        return None
    try:
        return int(tx.split(b":", 3)[1])
    except (IndexError, ValueError):
        return None


def report_from_store(block_store, first: int = 1, last: int = 0) -> dict:
    """Latency + block-interval stats (benchmark.go:22-76 shape)."""
    last = last or block_store.height
    latencies_ms: list[float] = []
    intervals_s: list[float] = []
    n_txs = 0
    prev_time = None
    for h in range(max(first, block_store.base), last + 1):
        block = block_store.load_block(h)
        if block is None:
            continue
        bt = block.header.time_ns
        if prev_time is not None:
            intervals_s.append((bt - prev_time) / 1e9)
        prev_time = bt
        for tx in block.data.txs:
            n_txs += 1
            sent = parse_tx_time(tx)
            if sent is not None:
                latencies_ms.append((bt - sent) / 1e6)

    def stats(xs):
        if not xs:
            return {"min": 0, "avg": 0, "stddev": 0, "max": 0}
        return {
            "min": round(min(xs), 2),
            "avg": round(statistics.fmean(xs), 2),
            "stddev": round(statistics.pstdev(xs), 2) if len(xs) > 1 else 0,
            "max": round(max(xs), 2),
        }

    dur_s = (
        sum(intervals_s) if intervals_s else 0.0
    )
    return {
        "blocks": len(intervals_s) + 1 if prev_time is not None else 0,
        "txs": n_txs,
        "tx_per_s": round(n_txs / dur_s, 1) if dur_s else 0.0,
        "block_interval_s": stats(intervals_s),
        "tx_latency_ms": stats(latencies_ms),
    }


async def run_load(
    blocks: int = 10, rate: int = 50, tx_size: int = 128
) -> dict:
    """In-proc single-validator node under tx load; returns the report."""
    import tempfile

    from tendermint_tpu.config import Config
    from tendermint_tpu.l2node.mock import MockL2Node
    from tendermint_tpu.node import Node, init_files

    with tempfile.TemporaryDirectory() as home:
        cfg = Config.test_config()
        cfg.root_dir = home
        cfg.base.db_backend = "memory"
        cfg.rpc.laddr = "tcp://127.0.0.1:0"
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        init_files(cfg)
        l2 = MockL2Node(txs_per_block=0)
        node = Node(cfg, l2_node=l2)
        await node.start()
        seq = 0
        try:
            target = node.consensus.state.last_block_height + blocks
            # one burst per committed height: block cadence varies wildly
            # across machines, so pacing by wall clock makes the number
            # of committed txs (and the report) timing-dependent — pacing
            # by height guarantees >= (blocks-1)*rate txs land in blocks
            injected_at = None
            while node.consensus.state.last_block_height < target:
                h = node.consensus.state.last_block_height
                if h != injected_at:
                    burst = [make_tx(seq + i, tx_size) for i in range(rate)]
                    seq += rate
                    l2.inject_txs(burst)
                    injected_at = h
                await asyncio.sleep(0.02)
            return report_from_store(node.block_store)
        finally:
            await node.stop()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    rp = sub.add_parser("run", help="in-proc node + burst load + report")
    rp.add_argument("--blocks", type=int, default=10)
    rp.add_argument("--rate", type=int, default=50)
    rp.add_argument("--size", type=int, default=128)
    gp = sub.add_parser("report", help="report over an existing home dir")
    gp.add_argument("--home", required=True)
    args = ap.parse_args()

    import json

    if args.cmd == "run":
        rep = asyncio.run(
            run_load(blocks=args.blocks, rate=args.rate, tx_size=args.size)
        )
    else:
        from tendermint_tpu.store.block_store import BlockStore
        from tendermint_tpu.store.kv import SqliteKV

        bs = BlockStore(
            SqliteKV(os.path.join(args.home, "data", "blockstore.db"))
        )
        rep = report_from_store(bs)
    print(json.dumps(rep, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
