"""Derive the 11-isogeny kernel polynomial for BLS12-381 G1 SSWU hash-to-curve.

The SSWU map (draft-irtf-cfrg-hash-to-curve-06 §6.6.2 — the variant the
reference consumes through go-ethereum's bls12381.MapToCurve, see
/root/reference/blssignatures/bls_signatures.go:179-188) targets an
11-isogenous curve E': y^2 = x^3 + A'x + B' (simplified SWU needs a*b != 0,
and E: y^2 = x^3 + 4 has a = 0), then carries the point to E through an
11-isogeny. Public implementations bake the isogeny's rational-map
coefficient tables; with no network egress we derive the isogeny from first
principles instead:

 1. compute the 11-division polynomial psi_11 of E' (degree 60) by the
    standard recurrences, working in the ring Fp[x,y]/(y^2 - x^3 - A'x - B')
    so no manual y-parity bookkeeping is needed,
 2. find its irreducible factors of degree <= 5 over Fp (distinct-degree
    factorization with Frobenius powers composed via modular composition;
    Cantor-Zassenhaus for equal-degree splits),
 3. enumerate monic degree-5 products (a rational 11-isogeny kernel
    polynomial has degree (11-1)/2 = 5 and divides psi_11),
 4. apply Velu's formulas (via power sums of the kernel roots and Newton's
    identities) and keep the kernel whose image curve is exactly
    y^2 = x^3 + 4, i.e. E.

The winning h(x) coefficients are baked into crypto/bls12_381.py. At
runtime the isogeny maps are *evaluated* through h alone:

    T(x)   = sum t_Q/(x-x_Q)      -> expressible via h'/h and power sums
    U(x)   = sum u_Q/(x-x_Q)
    X(x)   = x + T(x) - U'(x)     (Velu x-map)
    Y(x,y) = y * X'(x)            (Velu y-map for normalized isogenies)

so no coefficient tables are required at all.

Run:  python tools/derive_iso11.py     (~2-4 min of pure-Python bigints)
"""

from __future__ import annotations

import itertools
import random

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB

# SSWU iso-curve constants for BLS12-381 G1 (hash-to-curve draft, §8.8.1):
A_ISO = 0x144698A3B8E9433D693A02C96D4982B0EA985383EE66A8D8E8981AEFD881AC98936F8DA0E0F97F5CF428082D584C1D
B_ISO = 0x12E2908D11688030018B12E8753EEE3B2016C1F0F24F4070A0B9C14FCEF35EF55A23215A316CEAA5D1CC48E98E172BE0

A_E, B_E = 0, 4  # the target curve E


# --- dense polynomials over Fp: lists of ints, low -> high ----------------

def ptrim(a):
    while a and a[-1] == 0:
        a.pop()
    return a


def padd(a, b):
    n = max(len(a), len(b))
    return ptrim(
        [((a[i] if i < len(a) else 0) + (b[i] if i < len(b) else 0)) % P for i in range(n)]
    )


def psub(a, b):
    n = max(len(a), len(b))
    return ptrim(
        [((a[i] if i < len(a) else 0) - (b[i] if i < len(b) else 0)) % P for i in range(n)]
    )


def pmul(a, b):
    if not a or not b:
        return []
    out = [0] * (len(a) + len(b) - 1)
    for i, ai in enumerate(a):
        if ai:
            for j, bj in enumerate(b):
                out[i + j] += ai * bj
    return ptrim([c % P for c in out])


def pscale(a, k):
    k %= P
    return ptrim([ai * k % P for ai in a])


def pdivmod(a, m):
    a = list(a)
    dm = len(m) - 1
    q = [0] * max(1, len(a) - dm)
    inv_lead = pow(m[-1], P - 2, P)
    while a and len(a) - 1 >= dm:
        k = len(a) - 1 - dm
        c = a[-1] * inv_lead % P
        q[k] = c
        for i, mi in enumerate(m):
            a[k + i] = (a[k + i] - c * mi) % P
        ptrim(a)
    return ptrim(q), a


def pmod(a, m):
    return pdivmod(a, m)[1]


def pmonic(a):
    return pscale(a, pow(a[-1], P - 2, P)) if a else a


def pgcd(a, b):
    a, b = list(a), list(b)
    while b:
        a, b = b, pmod(a, b)
    return pmonic(a)


def ppowmod(base, e, m):
    r = [1]
    b = pmod(base, m)
    while e:
        if e & 1:
            r = pmod(pmul(r, b), m)
        b = pmod(pmul(b, b), m)
        e >>= 1
    return r


def pcompose_mod(g, f, m):
    """g(f) mod m via Horner."""
    r = []
    for c in reversed(g):
        r = pmod(padd(pmul(r, f), [c]), m)
    return r


def pderiv(a):
    return ptrim([a[i] * i % P for i in range(1, len(a))])


# --- ring Fp[x,y]/(y^2 - B(x)) as pairs (p0, p1) = p0 + y*p1 --------------

class RB:
    __slots__ = ("p0", "p1")

    def __init__(self, p0=None, p1=None):
        self.p0 = p0 or []
        self.p1 = p1 or []

    def __mul__(self, other):
        p0 = padd(pmul(self.p0, other.p0), pmul(CURVE_B, pmul(self.p1, other.p1)))
        p1 = padd(pmul(self.p0, other.p1), pmul(self.p1, other.p0))
        return RB(p0, p1)

    def __sub__(self, other):
        return RB(psub(self.p0, other.p0), psub(self.p1, other.p1))

    def pow3(self):
        return self * self * self

    def sq(self):
        return self * self


CURVE_B: list = []  # set in main(): x^3 + a x + b


def division_psi(n, memo, a, b):
    if n in memo:
        return memo[n]
    assert n >= 5
    m = n // 2
    if n % 2 == 1:
        r = division_psi(m + 2, memo, a, b) * division_psi(m, memo, a, b).pow3() - division_psi(
            m - 1, memo, a, b
        ) * division_psi(m + 1, memo, a, b).pow3()
    else:
        inner = division_psi(m + 2, memo, a, b) * division_psi(m - 1, memo, a, b).sq() - division_psi(
            m - 2, memo, a, b
        ) * division_psi(m + 1, memo, a, b).sq()
        prod = division_psi(m, memo, a, b) * inner
        # psi_even = y*g; psi_m * inner == 2y * psi_{2m} => prod = 2*B(x)*g
        assert not prod.p0 or not prod.p1, "expected homogeneous y-part"
        if prod.p1:
            # prod = y * q  =>  psi_2m = q / 2
            r = RB([], pscale(prod.p1, pow(2, P - 2, P)))
            # ... but psi_2m must be y*g with g = q/(2) / ... check: prod = 2y psi_2m
            # prod = y*q -> psi_2m = q/(2) as coefficient of... prod=2y*(y g)=2Bg pure.
            raise AssertionError("even psi product should be pure x-part")
        q, rem = pdivmod(prod.p0, CURVE_B)
        assert not rem, "psi even: division by B(x) must be exact"
        r = RB([], pscale(q, pow(2, P - 2, P)))
    memo[n] = r
    return r


def equal_degree_split(f, d):
    """Cantor-Zassenhaus: split monic squarefree f (all factors degree d)."""
    out = [f]
    done = []
    while out:
        g = out.pop()
        if len(g) - 1 == d:
            done.append(g)
            continue
        while True:
            r = ptrim([random.randrange(P) for _ in range(len(g) - 1)])
            e = (P**d - 1) // 2
            t = psub(ppowmod(r, e, g), [1])
            h = pgcd(t, g)
            if 0 < len(h) - 1 < len(g) - 1:
                q, rem = pdivmod(g, h)
                assert not rem
                out.append(pmonic(h))
                out.append(pmonic(q))
                break
    return done


def power_sums(h, k):
    """First k power sums of the roots of monic h via Newton's identities."""
    d = len(h) - 1
    e = [1] + [0] * d
    for i in range(1, d + 1):
        e[i] = (-1) ** i * h[d - i] % P
    p = [d % P]
    for kk in range(1, k + 1):
        s = 0
        for i in range(1, min(kk, d) + 1):
            s += (-1) ** (i - 1) * e[i] * (p[kk - i] if kk - i > 0 else 1)
        if kk <= d:
            # p_k = e1 p_{k-1} - e2 p_{k-2} + ... + (-1)^{k-1} k e_k
            s = 0
            for i in range(1, kk):
                s += (-1) ** (i - 1) * e[i] * p[kk - i]
            s += (-1) ** (kk - 1) * kk * e[kk]
        else:
            s = 0
            for i in range(1, d + 1):
                s += (-1) ** (i - 1) * e[i] * p[kk - i]
        p.append(s % P)
    return p


def velu_image(a, b, h):
    """Velu image curve (A,B) for kernel polynomial h on y^2=x^3+ax+b."""
    d = len(h) - 1
    p = power_sums(h, 3)
    p1, p2, p3 = p[1], p[2], p[3]
    t = (6 * p2 + 2 * a * d) % P
    w = (10 * p3 + 6 * a * p1 + 4 * b * d) % P
    return (a - 5 * t) % P, (b - 7 * w) % P


def main():
    global CURVE_B
    a, b = A_ISO, B_ISO
    CURVE_B = ptrim([b % P, a % P, 0, 1])

    memo = {
        0: RB([], []),
        1: RB([1], []),
        2: RB([], [2]),
        3: RB(ptrim([(-a * a) % P, 12 * b % P, 6 * a % P, 0, 3]), []),
        4: RB(
            [],
            pscale(
                ptrim(
                    [
                        (-8 * b * b - a**3) % P,
                        (-4 * a * b) % P,
                        (-5 * a * a) % P,
                        20 * b % P,
                        5 * a % P,
                        0,
                        1,
                    ]
                ),
                4,
            ),
        ),
    }
    print("computing psi_11 ...")
    psi11 = division_psi(11, memo, a, b)
    assert not psi11.p1, "odd division polynomial must be pure in x"
    f = pmonic(psi11.p0)
    print("deg psi_11 =", len(f) - 1)
    assert len(f) - 1 == 60

    print("distinct-degree factorization (degrees 1..5) ...")
    frob = ppowmod([0, 1], P, f)  # x^p mod f
    fk = frob
    remaining = f
    small_factors = []  # (degree, irreducible factor)
    for d in range(1, 6):
        g = pgcd(psub(fk, [0, 1]), remaining)
        if len(g) - 1 > 0:
            print(f"  product of degree-{d} irreducibles: total degree {len(g)-1}")
            irr = equal_degree_split(g, d) if len(g) - 1 > d else [pmonic(g)]
            small_factors.extend((d, x) for x in irr)
            remaining, rem = pdivmod(remaining, g)
            assert not rem
        if d < 5:
            fk = pcompose_mod(fk, frob, f)  # x^(p^(d+1)) = (x^(p^d)) o (x^p)
    print(f"  irreducible factors of degree<=5: {[(d, len(x)-1) for d, x in small_factors]}")

    # enumerate monic products with total degree 5
    found = None
    idxs = range(len(small_factors))
    for rsize in range(1, 6):
        for combo in itertools.combinations(idxs, rsize):
            if sum(small_factors[i][0] for i in combo) != 5:
                continue
            h = [1]
            for i in combo:
                h = pmul(h, small_factors[i][1])
            img = velu_image(a, b, h)
            print("  candidate kernel -> image", (hex(img[0]), hex(img[1])))
            if img[0] == A_E:
                # image y^2 = x^3 + B_img is isomorphic to E iff
                # B_img/B_E is a 6th power: (x,y) -> (x/u^2, y/u^3)
                ratio = img[1] * pow(B_E, P - 2, P) % P
                u = sixth_root(ratio)
                if u is not None:
                    found = (h, u)
                    break
        if found:
            break

    if not found:
        print("FAILED: no degree-5 kernel maps E' to (a twist-trivial) E")
        return

    h, u = found
    print("\nSUCCESS. Kernel polynomial h(x) (monic, low->high coefficients):")
    print("ISO11_KERNEL = [")
    for c in h:
        print(f"    0x{c:096x},")
    print("]")
    print(f"ISO11_SCALE_U = 0x{u:x}  # compose Velu with (x,y)->(x/u^2, y/u^3)")

    # self-check: map a few points of E'(Fp) to E via Velu evaluation
    from_eval_check(a, b, h, u)


def sixth_root(v):
    """A 6th root of v in Fp, or None.

    The expected scaling between the Velu image y^2 = x^3 + B_img and E is a
    small integer (the isogeny degree's square root pattern — 11 here), so a
    bounded search suffices for this one-off derivation tool; a generic
    Tonelli–Shanks is deliberately avoided.
    """
    for u in range(2, 1 << 16):
        if pow(u, 6, P) == v:
            return u
    return None


def from_eval_check(a, b, h, u=1):
    d = len(h) - 1
    hp = pderiv(h)
    p = power_sums(h, 3)
    p1, p2 = p[1], p[2]

    def B_of(x):
        return (x * x % P * x + a * x + b) % P

    def isogeny_eval(x, y):
        hx = peval(h, x)
        assert hx != 0, "point in kernel"
        hpx = peval(hp, x)
        inv_h = pow(hx, P - 2, P)
        lam = hpx * inv_h % P  # h'/h at x
        # T(x) = 6*(x^2 lam - x d - p1) + 2a lam
        T = (6 * ((x * x % P) * lam - x * d - p1) + 2 * a * lam) % P
        # U(x) = 4[x^3 lam - x^2 d - x p1 - p2] + 4a[x lam - d] + 4b lam
        U = (
            4 * ((x * x % P * x % P) * lam - (x * x % P) * d - x * p1 - p2)
            + 4 * a * (x * lam - d)
            + 4 * b * lam
        ) % P
        # numerically differentiate U and T is not allowed; use closed forms:
        # lam' = h''h - h'h' over h^2... easier: full rational forms.
        # Tn/h and Un/h with Tn, Un polynomials:
        #   sum 1/(x-xq)   = h'/h
        #   sum xq/(x-xq)  = (x h' - d h)/h
        #   sum xq^2/(x-xq)= (x^2 h' - (x d + p1) h)/h
        #   sum xq^3/(x-xq)= (x^3 h' - (x^2 d + x p1 + p2) h)/h
        # so Tn = 6(x^2 h' - (xd+p1) h) + 2a h'
        #    Un = 4(x^3 h' - (x^2 d + x p1 + p2) h) + 4a(x h' - d h) + 4b h'
        # X = x + Tn/h - d/dx(Un/h) = x + (Tn h - Un' h + Un h')/h^2
        return None

    # do it with explicit polynomials
    import numpy as _np  # noqa: F401  (unused; keep host-only)

    x_ = [0, 1]
    hpoly = list(h)
    hprime = pderiv(hpoly)
    Tn = padd(
        psub(pmul([0, 0, 1], hprime), pmul(padd(pscale(x_, d), [p1]), hpoly)),
        [],
    )
    Tn = pscale(Tn, 6)
    Tn = padd(Tn, pscale(hprime, 2 * a))
    Un = pscale(
        psub(pmul([0, 0, 0, 1], hprime), pmul(padd(padd(pscale([0, 0, 1], d), pscale(x_, p1)), [p2]), hpoly)),
        4,
    )
    Un = padd(Un, pscale(psub(pmul(x_, hprime), pscale(hpoly, d)), 4 * a))
    Un = padd(Un, pscale(hprime, 4 * b))
    N2 = padd(psub(pmul(Tn, hpoly), pmul(pderiv(Un), hpoly)), pmul(Un, hprime))
    N2p = pderiv(N2)

    u2i = pow(u * u % P, P - 2, P)
    u3i = pow(u * u % P * u % P, P - 2, P)

    def xmap(x):
        hx = peval(hpoly, x)
        return (x + peval(N2, x) * pow(hx * hx % P, P - 2, P)) % P * u2i % P

    def ymap(x, y):
        hx = peval(hpoly, x)
        hpx = peval(hprime, x)
        num = (peval(N2p, x) * hx - 2 * peval(N2, x) * hpx) % P
        return y * (1 + num * pow(hx * hx % P * hx % P, P - 2, P)) % P * u3i % P

    checked = 0
    xx = 2
    while checked < 5:
        rhs = B_of(xx)
        yy = pow(rhs, (P + 1) // 4, P)
        if yy * yy % P == rhs:
            X, Y = xmap(xx), ymap(xx, yy)
            lhs = Y * Y % P
            rhs2 = (X * X % P * X + A_E * X + B_E) % P
            assert lhs == rhs2, f"isogeny image point not on E (x={xx})"
            checked += 1
        xx += 1
    print("self-check: 5 random E' points map onto E  ✓")


def peval(a, x):
    r = 0
    for c in reversed(a):
        r = (r * x + c) % P
    return r


if __name__ == "__main__":
    main()
