"""Pallas doubling chain: whole computation in VMEM per batch tile.

One grid step = 128 batch lanes; a point is [4, 32, 128] f32 in VMEM
(limbs on sublanes, batch on lanes). 256 doublings run inside the kernel
with zero HBM round-trips between field ops.
"""

import functools
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

B = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
LANES = int(sys.argv[2]) if len(sys.argv) > 2 else 256
N_DBL = 256

BIAS = np.full((32, 1), 1020.0, dtype=np.float32)
BIAS[0, 0] = 872.0
_BIAS = None  # set inside kernel trace


def carry(x):
    c = jnp.floor(x * (1.0 / 256.0))
    r = x - c * 256.0
    wrap = jnp.concatenate([c[31:, :] * 38.0, c[:31, :]], axis=0)
    return r + wrap


def add(a, b):
    return carry(a + b)


def sub(a, b):
    return carry(a + _BIAS - b)


def mul(a, b):
    # conv via padded adds (pallas lowering has no scatter-add): term i is
    # a[i]*b placed at rows i..i+31 of the 63-row accumulator
    lanes = a.shape[-1]
    out = jnp.zeros((63, lanes), dtype=jnp.float32)
    for i in range(32):
        term = a[i : i + 1, :] * b  # [32, L]
        pads = []
        if i:
            pads.append(jnp.zeros((i, lanes), jnp.float32))
        pads.append(term)
        if 31 - i:
            pads.append(jnp.zeros((31 - i, lanes), jnp.float32))
        out = out + jnp.concatenate(pads, axis=0)
    lo = out[:32]
    hi = out[32:]
    ch = jnp.floor(hi * (1.0 / 256.0))
    rh = hi - ch * 256.0
    z = jnp.zeros((1, lanes), jnp.float32)
    hi2 = jnp.concatenate([rh, z], axis=0) + jnp.concatenate([z, ch], axis=0)
    x = lo + 38.0 * hi2
    x = carry(x)
    x = carry(x)
    x = carry(x)
    return carry(x)


def sqr(x):
    return mul(x, x)


def mul_small(a, k):
    x = a * float(k)
    x = carry(x)
    return carry(x)


def double(p):
    x1, y1, z1 = p[0], p[1], p[2]
    xx = sqr(x1)
    yy = sqr(y1)
    b2 = mul_small(sqr(z1), 2)
    aa = sqr(add(x1, y1))
    y3 = add(yy, xx)
    z3 = sub(yy, xx)
    x3 = sub(aa, y3)
    t3 = sub(b2, z3)
    return jnp.stack(
        [mul(x3, t3), mul(y3, z3), mul(z3, t3), mul(x3, y3)], axis=0
    )


def kernel(in_ref, out_ref):
    global _BIAS
    # build the 8p bias in-kernel (pallas kernels cannot capture host
    # constants): limb 0 = 872, limbs 1..31 = 1020
    row = jax.lax.broadcasted_iota(jnp.int32, (32, 1), 0)
    _BIAS = jnp.where(row == 0, 872.0, 1020.0).astype(jnp.float32)
    p = in_ref[:]
    p = jax.lax.fori_loop(0, N_DBL, lambda _, v: double(v), p)
    out_ref[:] = p


@jax.jit
def dbl_chain(pts):
    # pts: [4, 32, B]
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(pts.shape, pts.dtype),
        grid=(pts.shape[-1] // LANES,),
        in_specs=[
            pl.BlockSpec(
                (4, 32, LANES), lambda i: (0, 0, i), memory_space=pltpu.VMEM
            )
        ],
        out_specs=pl.BlockSpec(
            (4, 32, LANES), lambda i: (0, 0, i), memory_space=pltpu.VMEM
        ),
    )(pts)


def main():
    sys.path.insert(0, ".")
    from tendermint_tpu.crypto import ed25519 as host

    bp = np.stack(
        [
            np.array([int(b) for b in (c % host.P).to_bytes(32, "little")])
            for c in host.BASEPOINT
        ]
    ).astype(np.float32)
    pts = jnp.asarray(np.broadcast_to(bp[:, :, None], (4, 32, B)).copy())

    t0 = time.perf_counter()
    out = np.asarray(dbl_chain(pts))
    ct = time.perf_counter() - t0
    best = 1e9
    for _ in range(5):
        t0 = time.perf_counter()
        out = np.asarray(dbl_chain(pts))
        best = min(best, time.perf_counter() - t0)
    print(
        f"pallas double x{N_DBL} B={B} lanes={LANES}: "
        f"compile+1st {ct:6.2f}s run {best*1e3:8.2f} ms"
    )

    q = out[:, :, 0].astype(np.int64)
    vals = [sum(int(v) << (8 * i) for i, v in enumerate(row)) for row in q]
    hq = host.BASEPOINT
    for _ in range(N_DBL):
        hq = host.point_double(hq)
    got_x = vals[0] * pow(vals[2], host.P - 2, host.P) % host.P
    want_x = hq[0] * pow(hq[2], host.P - 2, host.P) % host.P
    print("correct:", got_x == want_x)


if __name__ == "__main__":
    main()
