"""Microbench candidate field-mul formulations on the current backend.

Run on TPU (default) or CPU (JAX_PLATFORMS=cpu). Times one batched field
multiplication (convolution + fold + carries) for several designs:

  A. batch-minor [B, 32] radix-2^8 int32 (current design)
  B. limb-major [32, B] radix-2^8 int32
  C. limb-major [20, B] radix-2^13 int32
  D. limb-major [32, B] radix-2^8 f32 (exact: products < 2^18, sums < 2^23)
  E. MXU dot: [B,32] bf16 x shared one-hot -> conv via dot_general f32

Prints per-candidate: time per mul at B, and extrapolated Mmul/s.
"""

import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

B = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
ITERS = 20


def timeit(fn, *args):
    fn_j = jax.jit(fn)
    out = jax.block_until_ready(fn_j(*args))  # compile+warm
    best = float("inf")
    for _ in range(ITERS):
        t0 = time.perf_counter()
        jax.block_until_ready(fn_j(*args))
        best = min(best, time.perf_counter() - t0)
    return best, out


def report(name, dt, nmul=1):
    print(f"{name:40s} {dt*1e6:10.1f} us/call  {B*nmul/dt/1e6:8.2f} Mmul/s")


# --- A: batch-minor [B, 32] radix-2^8 (current) ---------------------------

def mul_a(a, b):
    out = jnp.zeros((a.shape[0], 63), dtype=jnp.int32)
    for i in range(32):
        out = out.at[:, i : i + 32].add(a[:, i : i + 1] * b)
    lo, hi = out[:, :32], out[:, 32:]
    x = lo.at[:, :31].add(hi * 38)
    for _ in range(4):
        c = x >> 8
        r = x - (c << 8)
        x = r + jnp.concatenate([c[:, 31:] * 38, c[:, :31]], axis=1)
    return x


# --- B: limb-major [32, B] radix-2^8 --------------------------------------

def mul_b(a, b):
    out = jnp.zeros((63, a.shape[1]), dtype=jnp.int32)
    for i in range(32):
        out = out.at[i : i + 32, :].add(a[i : i + 1, :] * b)
    lo, hi = out[:32], out[32:]
    x = lo.at[:31].add(hi * 38)
    for _ in range(4):
        c = x >> 8
        r = x - (c << 8)
        x = r + jnp.concatenate([c[31:] * 38, c[:31]], axis=0)
    return x


# --- C: limb-major [20, B] radix-2^13 -------------------------------------
# p = 2^255-19; 20 limbs x 13 bits = 260 bits; 2^260 = 32*2^255 = 32*19+...
# fold: 2^260 ≡ 608 (mod p). hi columns carried once before folding.

def mul_c(a, b):
    out = jnp.zeros((39, a.shape[1]), dtype=jnp.int32)
    for i in range(20):
        out = out.at[i : i + 20, :].add(a[i : i + 1, :] * b)
    # carry hi part once so hi*608 stays in int32
    hi = out[20:]
    c = hi >> 13
    hi = hi - (c << 13)
    # fold: limb k (k>=20) contributes limb_{k-20} * 608; carries go up
    x = out[:20].at[:19].add(hi * 608)
    x = x.at[0].add(c[-1] * 0)  # keep shape; top carry folded below
    carries = jnp.concatenate([jnp.zeros((1, a.shape[1]), jnp.int32), c], axis=0)[:20]
    x = x + carries * 0  # placeholder: approximate op count
    for _ in range(3):
        c2 = x >> 13
        r = x - (c2 << 13)
        x = r + jnp.concatenate([c2[19:] * 608, c2[:19]], axis=0)
    return x


# --- D: limb-major [32, B] radix-2^8 float32 ------------------------------

def mul_d(a, b):
    out = jnp.zeros((63, a.shape[1]), dtype=jnp.float32)
    for i in range(32):
        out = out.at[i : i + 32, :].add(a[i : i + 1, :] * b)
    lo, hi = out[:32], out[32:]
    x = lo.at[:31].add(hi * 38.0)
    for _ in range(4):
        c = jnp.floor(x * (1.0 / 256.0))
        r = x - c * 256.0
        x = r + jnp.concatenate([c[31:] * 38.0, c[:31]], axis=0)
    return x


# --- E: conv via shared-matrix dot (MXU attempt) --------------------------
# out[b, k] = sum_ij a[b,i] b[b,j] [i+j=k]: build outer via broadcast then
# contract the flattened 1024 dim against a constant one-hot [1024, 63].

_SEL = np.zeros((32 * 32, 63), dtype=np.float32)
for i in range(32):
    for j in range(32):
        _SEL[i * 32 + j, i + j] = 1.0


def mul_e(a, b):
    outer = (a[:, :, None] * b[:, None, :]).reshape(a.shape[0], 1024)
    out = jax.lax.dot_general(
        outer, jnp.asarray(_SEL),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    lo, hi = out[:, :32], out[:, 32:]
    x = lo.at[:, :31].add(hi * 38.0)
    for _ in range(4):
        c = jnp.floor(x * (1.0 / 256.0))
        r = x - c * 256.0
        x = r + jnp.concatenate([c[:, 31:] * 38.0, c[:, :31]], axis=1)
    return x


def main():
    print(f"backend={jax.default_backend()} devices={len(jax.devices())} B={B}")
    rng = np.random.default_rng(0)
    a8 = rng.integers(0, 256, (B, 32), dtype=np.int32)
    b8 = rng.integers(0, 256, (B, 32), dtype=np.int32)

    dt, _ = timeit(mul_a, jnp.asarray(a8), jnp.asarray(b8))
    report("A [B,32] r8 int32 (current)", dt)
    dt, _ = timeit(mul_b, jnp.asarray(a8.T), jnp.asarray(b8.T))
    report("B [32,B] r8 int32", dt)
    a13 = rng.integers(0, 1 << 13, (20, B), dtype=np.int32)
    b13 = rng.integers(0, 1 << 13, (20, B), dtype=np.int32)
    dt, _ = timeit(mul_c, jnp.asarray(a13), jnp.asarray(b13))
    # NOTE: C's fold uses *0 placeholder terms that XLA constant-folds
    # away, so this row is a LOWER BOUND on the real radix-13 cost, not a
    # faithful implementation.
    report("C [20,B] r13 int32 (lower bound)", dt)
    dt, _ = timeit(mul_d, jnp.asarray(a8.T, dtype=np.float32), jnp.asarray(b8.T, dtype=np.float32))
    report("D [32,B] r8 f32", dt)
    dt, _ = timeit(mul_e, jnp.asarray(a8, dtype=np.float32), jnp.asarray(b8, dtype=np.float32))
    report("E [B,32] r8 f32 outer+dot", dt)

    # chain of 16 muls: measures fusion/memory behavior, closer to real use
    def chain_b(a, b):
        x = a
        for _ in range(16):
            x = mul_b(x & 0xFF, b)
        return x

    dt, _ = timeit(chain_b, jnp.asarray(a8.T), jnp.asarray(b8.T))
    report("B chain x16", dt, nmul=16)

    def chain_d(a, b):
        x = a
        for _ in range(16):
            x = mul_d(x - jnp.floor(x * (1/256.)) * 256., b)
        return x

    dt, _ = timeit(chain_d, jnp.asarray(a8.T, dtype=np.float32), jnp.asarray(b8.T, dtype=np.float32))
    report("D chain x16", dt, nmul=16)


if __name__ == "__main__":
    main()
