"""Limb-major layout [..., 32, B]: batch fills the 128-lane minor dim.

vs batch-major [B, ..., 32] where the 32-limb minor dim wastes 3/4 of the
vector lanes. Same f32 arithmetic, same doubling chain.
"""

import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

B = int(sys.argv[1]) if len(sys.argv) > 1 else 8192

BIAS = np.full((32, 1), 1020.0, dtype=np.float32)
BIAS[0, 0] = 872.0


def carry(x):
    # x: [..., 32, B]
    c = jnp.floor(x * (1.0 / 256.0))
    r = x - c * 256.0
    wrap = jnp.concatenate([c[..., 31:, :] * 38.0, c[..., :31, :]], axis=-2)
    return r + wrap


def add(a, b):
    return carry(a + b)


def sub(a, b):
    return carry(a + jnp.asarray(BIAS) - b)


def mul(a, b):
    # conv over the sublane (limb) axis: 32 shifted MACs of [63, B]
    shape = (*a.shape[:-2], 63, a.shape[-1])
    out = jnp.zeros(shape, dtype=jnp.float32)
    for i in range(32):
        out = out.at[..., i : i + 32, :].add(a[..., i : i + 1, :] * b)
    lo = out[..., :32, :]
    hi = out[..., 32:, :]
    ch = jnp.floor(hi * (1.0 / 256.0))
    rh = hi - ch * 256.0
    z = jnp.zeros((*a.shape[:-2], 1, a.shape[-1]), jnp.float32)
    hi2 = jnp.concatenate([rh, z], axis=-2) + jnp.concatenate(
        [z, ch], axis=-2
    )
    x = lo + 38.0 * hi2
    x = carry(x)
    x = carry(x)
    x = carry(x)
    return carry(x)


def sqr(x):
    return mul(x, x)


def mul_small(a, k):
    x = a * float(k)
    x = carry(x)
    x = carry(x)
    return carry(x)


def double(p):
    # p: [4, 32, B]
    x1, y1, z1 = p[0], p[1], p[2]
    xx = sqr(x1)
    yy = sqr(y1)
    b2 = mul_small(sqr(z1), 2)
    aa = sqr(add(x1, y1))
    y3 = add(yy, xx)
    z3 = sub(yy, xx)
    x3 = sub(aa, y3)
    t3 = sub(b2, z3)
    return jnp.stack(
        [mul(x3, t3), mul(y3, z3), mul(z3, t3), mul(x3, y3)], axis=0
    )


def main():
    sys.path.insert(0, ".")
    from tendermint_tpu.crypto import ed25519 as host

    bp = np.stack(
        [
            np.array([int(b) for b in (c % host.P).to_bytes(32, "little")])
            for c in host.BASEPOINT
        ]
    ).astype(np.float32)  # [4, 32]
    pts = jnp.asarray(np.broadcast_to(bp[:, :, None], (4, 32, B)).copy())

    for n in (32, 256):
        fn = jax.jit(
            lambda p, n=n: jnp.sum(
                jax.lax.fori_loop(0, n, lambda _, v: double(v), p)[0],
                axis=-2,
            )
        )
        t0 = time.perf_counter()
        np.asarray(fn(pts))
        ct = time.perf_counter() - t0
        best = 1e9
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(fn(pts))
            best = min(best, time.perf_counter() - t0)
        print(f"limbmajor double x{n:4d}: compile+1st {ct:6.2f}s run {best*1e3:8.2f} ms")

    q = jax.jit(
        lambda p: jax.lax.fori_loop(0, 256, lambda _, v: double(v), p)
    )(pts)
    q = np.asarray(q)[:, :, 0].astype(np.int64)
    vals = [sum(int(v) << (8 * i) for i, v in enumerate(row)) for row in q]
    hq = host.BASEPOINT
    for _ in range(256):
        hq = host.point_double(hq)
    got_x = vals[0] * pow(vals[2], host.P - 2, host.P) % host.P
    want_x = hq[0] * pow(hq[2], host.P - 2, host.P) % host.P
    print("correct:", got_x == want_x)


if __name__ == "__main__":
    main()
