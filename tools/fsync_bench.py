"""fsync microbenchmark — single vs group-commit WAL fsync throughput.

The commit pipeline's disk-side claim is that precommit-time `write_sync`
calls sharing one fsync (consensus/wal.GroupCommitWAL) beat one fsync
per record (the serial reference path, consensus/state.go:821-828). This
tool measures both on THIS box's filesystem so PERF_ANALYSIS §12 quotes a
stored run instead of an assumption.

Shapes measured:
  - serial_write_sync: N sequential write_sync on the plain WAL
    (one fsync each — the pre-pipeline behavior),
  - group_sequential: N sequential write_sync on GroupCommitWAL (the
    barrier still waits per call; coalescing only helps if the flush
    interval captures queued writers),
  - group_concurrent_cW: N records from W writer threads on
    GroupCommitWAL (the pipeline shape: the consensus loop + the
    background finalization + replay all barriering concurrently),
  - raw_fsync: bare os.fsync on an appended fd, the floor.

Run:  python tools/fsync_bench.py [records] [outdir]
Prints one JSON object (artifact shape like tools/bench_executor.py).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tendermint_tpu.consensus.wal import (  # noqa: E402
    WAL,
    GroupCommitWAL,
    WALMessage,
)

PAYLOAD = b"x" * 256  # ~ a consensus vote record


def _bench_serial(path: str, n: int) -> dict:
    wal = WAL(path)
    t0 = time.perf_counter()
    for i in range(n):
        wal.write_sync(WALMessage("consensus", PAYLOAD))
    dt = time.perf_counter() - t0
    fsyncs = wal.fsync_count
    wal.close()
    return {
        "records_per_s": round(n / dt, 1),
        "fsyncs": fsyncs,
        "ms_per_record": round(dt / n * 1e3, 4),
    }


def _bench_group_sequential(path: str, n: int, flush_interval: float) -> dict:
    wal = GroupCommitWAL(path, flush_interval=flush_interval)
    t0 = time.perf_counter()
    for i in range(n):
        wal.write_sync(WALMessage("consensus", PAYLOAD))
    dt = time.perf_counter() - t0
    fsyncs = wal.fsync_count
    wal.close()
    return {
        "records_per_s": round(n / dt, 1),
        "fsyncs": fsyncs,
        "ms_per_record": round(dt / n * 1e3, 4),
    }


def _bench_group_concurrent(
    path: str, n: int, writers: int, flush_interval: float
) -> dict:
    wal = GroupCommitWAL(path, flush_interval=flush_interval)
    per = n // writers
    start = threading.Barrier(writers + 1)

    def w():
        start.wait()
        for _ in range(per):
            wal.write_sync(WALMessage("consensus", PAYLOAD))

    threads = [threading.Thread(target=w) for _ in range(writers)]
    for t in threads:
        t.start()
    start.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    fsyncs = wal.fsync_count
    wal.close()
    total = per * writers
    return {
        "records_per_s": round(total / dt, 1),
        "fsyncs": fsyncs,
        "records_per_fsync": round(total / max(1, fsyncs), 2),
        "ms_per_record": round(dt / total * 1e3, 4),
    }


def _bench_raw_fsync(path: str, n: int) -> dict:
    f = open(path, "ab")
    t0 = time.perf_counter()
    for _ in range(n):
        f.write(PAYLOAD)
        f.flush()
        os.fsync(f.fileno())
    dt = time.perf_counter() - t0
    f.close()
    return {
        "fsyncs_per_s": round(n / dt, 1),
        "ms_per_fsync": round(dt / n * 1e3, 4),
    }


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    outdir = sys.argv[2] if len(sys.argv) > 2 else tempfile.mkdtemp(
        prefix="fsync_bench_"
    )
    os.makedirs(outdir, exist_ok=True)
    flush_interval = float(os.environ.get("TM_TPU_FSYNC_FLUSH", "0.002"))

    out = {
        "tool": "fsync_bench",
        "records": n,
        "flush_interval_s": flush_interval,
        "dir": outdir,
        "raw_fsync": _bench_raw_fsync(os.path.join(outdir, "raw"), n),
        "serial_write_sync": _bench_serial(
            os.path.join(outdir, "serial"), n
        ),
        "group_sequential": _bench_group_sequential(
            os.path.join(outdir, "group_seq"), n, flush_interval
        ),
    }
    for writers in (2, 4, 8):
        out[f"group_concurrent_c{writers}"] = _bench_group_concurrent(
            os.path.join(outdir, f"group_c{writers}"),
            n,
            writers,
            flush_interval,
        )
    serial = out["serial_write_sync"]["fsyncs"]
    c8 = out["group_concurrent_c8"]["fsyncs"]
    out["fsync_reduction_c8"] = round(serial / max(1, c8), 2)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
