"""Device-cost report: per-class accelerator cost tables + the
requests-per-dispatch amortization curve.

Reads any surface the device-cost ledger (obs/ledger.py) lands on:

- a `dump_dispatch_ledger` RPC response (raw or `{"result": ...}`
  envelope) pulled from a live node,
- a verify-service dump (the standalone service's own
  /dump_dispatch_ledger or STATS frame, PR 13): same shape plus a
  `per_client` tenant table — the multi-tenant device bill with real
  tenants, rendered as per-client submission/row counts next to the
  per-class cost shares,
- a bench artifact carrying a `device_cost` block (every family stamps
  one since PR 12),
- a bare `device_cost`/summary dict,

and renders the questions the ledger exists to answer: which submitter
class spent which device milliseconds (and what share), at what fill
efficiency (p50/p95 of per-round rows-requested / rows-dispatched),
with how much padding waste, and how many submissions each dispatch
amortized — per padded-bucket size, so the amortization curve shows
where cross-subsystem coalescing actually pays and where mesh_min_rows
or the ladder is mispriced.

Usage:
    curl -s localhost:26657/dump_dispatch_ledger | python tools/device_report.py -
    python tools/device_report.py BENCH_r12.json [more.json ...] [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _load(path: str) -> dict:
    if path == "-":
        return json.load(sys.stdin)
    with open(path) as f:
        return json.load(f)


def extract_summary(doc: dict) -> dict:
    """The device_cost/summary block from any supported shape; raises
    ValueError when the document carries none."""
    if not isinstance(doc, dict):
        raise ValueError("not a JSON object")
    if "result" in doc and isinstance(doc["result"], dict):
        doc = doc["result"]  # JSON-RPC envelope
    for key in ("summary", "device_cost"):
        block = doc.get(key)
        if isinstance(block, dict) and "rounds" in block:
            # a verify-service dump carries the tenant table BESIDE the
            # summary; attach it so the report can render the bill
            if isinstance(doc.get("per_client"), dict):
                block = dict(block)
                block["per_client"] = doc["per_client"]
            return block
    if "rounds" in doc and "per_class" in doc:
        return doc  # already a bare summary
    raise ValueError(
        "no device-cost block found (expected a dump_dispatch_ledger "
        "response, a bench artifact with 'device_cost', or a bare "
        "summary)"
    )


def _fmt_s(v: float) -> str:
    return f"{v * 1e3:,.1f} ms" if v < 1.0 else f"{v:,.2f} s"


def report_text(summary: dict, name: str = "") -> str:
    lines = []
    title = "device-cost ledger"
    if name:
        title += f": {name}"
    lines.append(f"== {title} ==")
    rounds = summary.get("rounds", 0)
    if not rounds:
        lines.append("(no scheduler rounds recorded)")
        return "\n".join(lines)
    lines.append(
        f"rounds {rounds} (fn {summary.get('fn_rounds', 0)}, sharded "
        f"{summary.get('sharded_rounds', 0)})   device time "
        f"{_fmt_s(summary.get('device_seconds', 0.0))}   queue wait "
        f"{_fmt_s(summary.get('queue_wait_seconds', 0.0))}   host prep "
        f"{_fmt_s(summary.get('host_prep_seconds', 0.0))}"
    )
    disp = summary.get("rows_dispatched", 0)
    pad = summary.get("padding_rows", 0)
    lines.append(
        f"rows {summary.get('rows_requested', 0)} requested -> {disp} "
        f"dispatched   padding {pad} rows"
        + (f" ({pad / disp:.1%} of dispatched)" if disp else "")
        + f"   fill p50 {summary.get('fill_ratio_p50', 0.0)} "
        f"p95 {summary.get('fill_ratio_p95', 0.0)}"
    )
    lines.append(
        f"requests/dispatch {summary.get('requests_per_dispatch', 0.0)}"
    )
    if summary.get("fill_window_truncated"):
        lines.append(
            "(fill percentiles over retained ring entries only — older "
            "rounds aged out; totals above are exact)"
        )
    per_class = summary.get("per_class") or {}
    if per_class:
        lines.append("")
        lines.append(
            f"{'class':<12} {'rows':>10} {'device':>12} {'share':>7} "
            f"{'rounds':>7} {'subs':>7} {'queue wait':>12}"
        )
        for klass, acct in sorted(
            per_class.items(),
            key=lambda kv: -kv[1].get("device_seconds", 0.0),
        ):
            lines.append(
                f"{klass:<12} {acct.get('rows', 0):>10} "
                f"{_fmt_s(acct.get('device_seconds', 0.0)):>12} "
                f"{acct.get('device_share', 0.0):>6.1%} "
                f"{acct.get('rounds', 0):>7} "
                f"{acct.get('submissions', 0):>7} "
                f"{_fmt_s(acct.get('queue_wait_seconds', 0.0)):>12}"
            )
    per_engine = summary.get("per_engine") or {}
    if per_engine:
        lines.append("")
        lines.append(
            "per-engine (the honest requests/dispatch axis — fn rounds "
            "are one submission each by construction):"
        )
        lines.append(
            f"{'engine':<14} {'rounds':>7} {'rows':>10} {'disp':>10} "
            f"{'fill':>6} {'reqs/disp':>10} {'device':>12}"
        )
        for eng, acct in sorted(
            per_engine.items(),
            key=lambda kv: -kv[1].get("device_seconds", 0.0),
        ):
            lines.append(
                f"{eng:<14} {acct.get('rounds', 0):>7} "
                f"{acct.get('rows_requested', 0):>10} "
                f"{acct.get('rows_dispatched', 0):>10} "
                f"{acct.get('fill_ratio', 0.0):>6.2f} "
                f"{acct.get('requests_per_dispatch', 0.0):>10} "
                f"{_fmt_s(acct.get('device_seconds', 0.0)):>12}"
            )
    per_client = summary.get("per_client") or {}
    if per_client:
        total_rows = sum(
            c.get("rows", 0) + c.get("fn_items", 0)
            for c in per_client.values()
        )
        lines.append("")
        lines.append(
            f"tenants ({len(per_client)} clients over the service's "
            "life):"
        )
        lines.append(
            f"{'client':<12} {'subs':>7} {'rows':>10} {'fn subs':>8} "
            f"{'fn items':>9} {'row share':>10}"
        )
        for client, c in sorted(
            per_client.items(),
            key=lambda kv: -(
                kv[1].get("rows", 0) + kv[1].get("fn_items", 0)
            ),
        ):
            rows = c.get("rows", 0) + c.get("fn_items", 0)
            share = rows / total_rows if total_rows else 0.0
            lines.append(
                f"{client:<12} {c.get('submissions', 0):>7} "
                f"{c.get('rows', 0):>10} "
                f"{c.get('fn_submissions', 0):>8} "
                f"{c.get('fn_items', 0):>9} {share:>9.1%}"
            )
    by_bucket = summary.get("by_bucket") or {}
    if by_bucket:
        lines.append("")
        lines.append("amortization curve (per padded bucket):")
        lines.append(
            f"{'bucket':>8} {'rounds':>7} {'rows req':>10} {'subs':>7} "
            f"{'fill':>6} {'reqs/disp':>10}"
        )
        for bucket, b in sorted(
            by_bucket.items(), key=lambda kv: int(kv[0])
        ):
            bi = int(bucket)
            fill = b["rows_requested"] / (bi * b["rounds"]) if b[
                "rounds"
            ] else 0.0
            lines.append(
                f"{bi:>8} {b['rounds']:>7} {b['rows_requested']:>10} "
                f"{b['submissions']:>7} {fill:>6.2f} "
                f"{b['submissions'] / b['rounds']:>10.2f}"
            )
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(
        description="per-class device-cost tables + amortization curve "
        "from dump_dispatch_ledger dumps or bench artifacts"
    )
    ap.add_argument(
        "paths", nargs="+",
        help="dump/bench JSON files ('-' = stdin)",
    )
    ap.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the extracted summaries as JSON instead of tables",
    )
    args = ap.parse_args()
    out = {}
    rc = 0
    for path in args.paths:
        name = os.path.basename(path) if path != "-" else "stdin"
        try:
            summary = extract_summary(_load(path))
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"# {name}: {e}", file=sys.stderr)
            rc = 1
            continue
        out[name] = summary
        if not args.as_json:
            print(report_text(summary, name=name))
            print()
    if args.as_json:
        print(json.dumps(out, indent=1))
    return rc if out else 1


if __name__ == "__main__":
    raise SystemExit(main())
