"""Column-form field mul: does XLA fuse it into one memory pass?

The .at[i:i+32].add conv does 32 dynamic-update-slices -> ~66MB HBM
traffic per field mul (bandwidth-bound, 69us at B=8192). Column form
computes each output column as an explicit sum -> XLA can fuse the whole
conv into one elementwise kernel reading a,b once (~4MB).
"""

import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

B = int(sys.argv[1]) if len(sys.argv) > 1 else 8192

BIAS = np.full(32, 1020.0, dtype=np.float32)
BIAS[0] = 872.0


def carry(x):
    c = jnp.floor(x * (1.0 / 256.0))
    r = x - c * 256.0
    wrap = jnp.concatenate([c[..., 31:] * 38.0, c[..., :31]], axis=-1)
    return r + wrap


def add(a, b):
    return carry(a + b)


def sub(a, b):
    return carry(a + jnp.asarray(BIAS) - b)


def mul(a, b):
    # column form: fold hi columns (k >= 32) by 38 directly into lo
    # cols = sum_{i+j=k} + 38 * sum_{i+j=k+32}; bound:
    #   lo sum < 32*2^18 = 2^23, hi sum < 31*2^18 < 2^23 -> pre-carry hi
    au = [a[..., i] for i in range(32)]
    bu = [b[..., j] for j in range(32)]
    lo = []
    hi = []
    for k in range(32):
        terms = [au[i] * bu[k - i] for i in range(max(0, k - 31), k + 1)]
        lo.append(sum(terms))
    for k in range(32, 63):
        terms = [au[i] * bu[k - i] for i in range(k - 31, 32)]
        hi.append(sum(terms))
    hi.append(jnp.zeros_like(lo[0]))  # hi[31] = 0
    # pre-carry hi then fold by 38 (same bound chain as before)
    ch = [jnp.floor(h * (1.0 / 256.0)) for h in hi]
    rh = [h - c * 256.0 for h, c in zip(hi, ch)]
    hi2 = [rh[0]] + [rh[k] + ch[k - 1] for k in range(1, 32)]
    x = jnp.stack(
        [l + 38.0 * h for l, h in zip(lo, hi2)], axis=-1
    )
    x = carry(x)
    x = carry(x)
    x = carry(x)
    return carry(x)


def sqr(x):
    return mul(x, x)


def mul_small(a, k):
    x = a * float(k)
    x = carry(x)
    x = carry(x)
    return carry(x)


def double(p):
    x1, y1, z1 = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    xx = sqr(x1)
    yy = sqr(y1)
    b2 = mul_small(sqr(z1), 2)
    aa = sqr(add(x1, y1))
    y3 = add(yy, xx)
    z3 = sub(yy, xx)
    x3 = sub(aa, y3)
    t3 = sub(b2, z3)
    return jnp.stack(
        [mul(x3, t3), mul(y3, z3), mul(z3, t3), mul(x3, y3)], axis=-2
    )


def main():
    sys.path.insert(0, ".")
    from tendermint_tpu.crypto import ed25519 as host

    bp = np.stack(
        [
            np.array([int(b) for b in (c % host.P).to_bytes(32, "little")])
            for c in host.BASEPOINT
        ]
    ).astype(np.float32)
    pts = jnp.asarray(np.broadcast_to(bp, (B, 4, 32)).copy())

    for n in (32, 256):
        fn = jax.jit(
            lambda p, n=n: jnp.sum(
                jax.lax.fori_loop(0, n, lambda _, v: double(v), p)[..., 0, :],
                axis=-1,
            )
        )
        t0 = time.perf_counter()
        np.asarray(fn(pts))
        ct = time.perf_counter() - t0
        best = 1e9
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(fn(pts))
            best = min(best, time.perf_counter() - t0)
        print(f"colmul double x{n:4d}: compile+1st {ct:6.2f}s run {best*1e3:8.2f} ms")

    q = jax.jit(
        lambda p: jax.lax.fori_loop(0, 256, lambda _, v: double(v), p)
    )(pts)
    q = np.asarray(q)[0].astype(np.int64)
    vals = [sum(int(v) << (8 * i) for i, v in enumerate(row)) for row in q]
    hq = host.BASEPOINT
    for _ in range(256):
        hq = host.point_double(hq)
    got_x = vals[0] * pow(vals[2], host.P - 2, host.P) % host.P
    want_x = hq[0] * pow(hq[2], host.P - 2, host.P) % host.P
    print("correct:", got_x == want_x)


if __name__ == "__main__":
    main()
