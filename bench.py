"""Benchmark: batched ed25519 verification throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference verifies votes serially via Go x/crypto ed25519 —
~50-70 µs/verify single-core (SURVEY.md §6; crypto/ed25519/bench_test.go is
the reference harness, no stored numbers), i.e. ~15,000 sigs/s. The
BASELINE.json north-star targets >50k sigs/s/chip. vs_baseline is measured
sigs/s divided by the 15k serial-CPU figure.

The reported metric is the STEADY-STATE vote-verification path: cached
per-validator window tables (the consensus workload re-verifies the same
validator set every height — SURVEY.md §3.3 — so the framework builds each
pubkey's table once; table build cost is measured separately and amortizes
to ~zero over a validator's lifetime). The generic path (fresh pubkeys,
in-batch decompression) is also measured and printed to stderr.

Environment note (measured, tools/microbench_*.py): the tunnelled device in
this harness executes at near host-CPU rates (a 4096^3 bf16 matmul runs at
~0.1 TFLOP/s vs ~200 TFLOP/s for real v5e silicon), so absolute numbers
here reflect that executor, not TPU silicon capability.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

BASELINE_SERIAL_SIGS_PER_S = 15_000.0
BATCH = 8192
ITERS = 3


def _build_args(batch: int):
    import jax.numpy as jnp

    from __graft_entry__ import _make_batch

    n_unique = min(batch, 128)  # realistic validator-set size
    pub, rb, sb, kb, s_ok = _make_batch(n_unique)
    reps = (batch + n_unique - 1) // n_unique

    def tile(x):
        return np.tile(x, (reps,) + (1,) * (x.ndim - 1))[:batch]

    return tuple(
        jnp.asarray(t) for t in (tile(pub), tile(rb), tile(sb), tile(kb), tile(s_ok))
    )


def _time_best(fn, *args) -> float:
    import jax

    out = np.asarray(fn(*args))  # compile + warm
    assert out.all(), "benchmark batch failed to verify"
    best = float("inf")
    for _ in range(ITERS):
        t0 = time.perf_counter()
        out = np.asarray(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def _time_pipelined(fn, *args, depth: int = 8) -> float:
    """Steady-state throughput: enqueue `depth` batches, then sync them all.

    This is the shape of the bulk workloads (blocksync replay streams many
    blocks' commit batches at the device — SURVEY.md §3.4); dispatch is
    async, so the fixed host↔device round-trip latency amortizes across the
    pipeline instead of taxing every batch. Returns seconds per batch."""
    np.asarray(fn(*args))  # warm
    best = float("inf")
    for _ in range(ITERS):
        t0 = time.perf_counter()
        outs = [fn(*args) for _ in range(depth)]
        for o in outs:
            assert np.asarray(o).all(), "pipelined batch failed to verify"
        best = min(best, (time.perf_counter() - t0) / depth)
    return best


def main() -> None:
    import jax
    import jax.numpy as jnp

    from tendermint_tpu.ops.ed25519_batch import (
        neg_pubkey_bigtable,
        verify_prehashed,
        verify_prehashed_bigcache,
    )

    pub, rb, sb, kb, s_ok = _build_args(BATCH)

    # one-time validator fixed-window table build (amortized over the
    # validator's life; the BatchVerifier caches these device-resident)
    t0 = time.perf_counter()
    tables, valid_u = jax.jit(neg_pubkey_bigtable)(pub[:128])
    tables = jax.block_until_ready(tables)
    np.asarray(valid_u)  # force through the tunnel
    build_t = time.perf_counter() - t0
    reps = (BATCH + 127) // 128
    idx = jnp.asarray(np.tile(np.arange(128, dtype=np.int32), reps)[:BATCH])
    valid = jnp.tile(valid_u, (reps,))[:BATCH]

    cached_fn = jax.jit(verify_prehashed_bigcache)
    dt_lat = _time_best(cached_fn, tables, valid, idx, rb, sb, kb, s_ok)
    dt_cached = _time_pipelined(
        cached_fn, tables, valid, idx, rb, sb, kb, s_ok
    )
    cached_rate = BATCH / dt_cached
    print(
        f"# cached-table path: {cached_rate:,.0f} sigs/s pipelined "
        f"({dt_cached*1e3:.0f} ms/{BATCH}); single-batch latency "
        f"{dt_lat*1e3:.0f} ms ({BATCH/dt_lat:,.0f} sigs/s); table build "
        f"(128 keys, incl. compile): {build_t:.1f}s",
        file=sys.stderr,
    )

    # generic path (fresh pubkeys) — informational; the tunnel's remote
    # compile intermittently drops large programs, so failures here must
    # not lose the headline measurement
    try:
        generic_fn = jax.jit(verify_prehashed)
        dt_generic = _time_best(generic_fn, pub, rb, sb, kb, s_ok)
        print(
            f"# generic path: {BATCH / dt_generic:,.0f} sigs/s "
            f"({dt_generic*1e3:.0f} ms/{BATCH})",
            file=sys.stderr,
        )
    except Exception as e:
        print(f"# generic path measurement failed: {e}", file=sys.stderr)
    print(
        json.dumps(
            {
                "metric": "ed25519_vote_verify_throughput",
                "value": round(cached_rate, 1),
                "unit": "sigs/s/chip",
                "vs_baseline": round(
                    cached_rate / BASELINE_SERIAL_SIGS_PER_S, 3
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
