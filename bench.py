"""Benchmark: batched ed25519 verification throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference verifies votes serially via Go x/crypto ed25519 —
~50-70 µs/verify single-core (SURVEY.md §6; crypto/ed25519/bench_test.go is
the reference harness, no stored numbers), i.e. ~15,000 sigs/s. The
BASELINE.json north-star targets >50k sigs/s/chip. vs_baseline is measured
sigs/s divided by the 15k serial-CPU figure.

The reported metric is the STEADY-STATE vote-verification path: cached
per-validator window tables (the consensus workload re-verifies the same
validator set every height — SURVEY.md §3.3 — so the framework builds each
pubkey's table once; table build cost is measured separately and amortizes
to ~zero over a validator's lifetime). The generic path (fresh pubkeys,
in-batch decompression) is also measured and printed to stderr.

Environment note (measured, tools/microbench_*.py): the tunnelled device in
this harness executes at near host-CPU rates (a 4096^3 bf16 matmul runs at
~0.1 TFLOP/s vs ~200 TFLOP/s for real v5e silicon), so absolute numbers
here reflect that executor, not TPU silicon capability.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

BASELINE_SERIAL_SIGS_PER_S = 15_000.0
BATCH = 8192
ITERS = 3


def _build_args(batch: int):
    import jax.numpy as jnp

    from __graft_entry__ import _make_batch

    n_unique = min(batch, 128)  # realistic validator-set size
    pub, rb, sb, kb, s_ok = _make_batch(n_unique)
    reps = (batch + n_unique - 1) // n_unique

    def tile(x):
        return np.tile(x, (reps,) + (1,) * (x.ndim - 1))[:batch]

    return tuple(
        jnp.asarray(t) for t in (tile(pub), tile(rb), tile(sb), tile(kb), tile(s_ok))
    )


def _time_best(fn, *args) -> float:
    import jax

    out = np.asarray(fn(*args))  # compile + warm
    assert out.all(), "benchmark batch failed to verify"
    best = float("inf")
    for _ in range(ITERS):
        t0 = time.perf_counter()
        out = np.asarray(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def main() -> None:
    import jax
    import jax.numpy as jnp

    from tendermint_tpu.ops.ed25519_batch import (
        neg_pubkey_table,
        verify_prehashed,
        verify_prehashed_table,
    )

    pub, rb, sb, kb, s_ok = _build_args(BATCH)

    # one-time validator table build (amortized over the validator's life)
    t0 = time.perf_counter()
    tables_u, valid_u = jax.jit(neg_pubkey_table)(pub[:128])
    tables_u = jax.block_until_ready(tables_u)
    build_t = time.perf_counter() - t0
    reps = (BATCH + 127) // 128
    tables = jnp.tile(tables_u, (reps, 1, 1, 1))[:BATCH]
    valid = jnp.tile(valid_u, (reps,))[:BATCH]

    cached_fn = jax.jit(verify_prehashed_table)
    dt_cached = _time_best(cached_fn, tables, valid, rb, sb, kb, s_ok)
    cached_rate = BATCH / dt_cached
    print(
        f"# cached-table path: {cached_rate:,.0f} sigs/s "
        f"({dt_cached*1e3:.0f} ms/{BATCH}); table build (128 keys, incl. "
        f"compile): {build_t:.1f}s",
        file=sys.stderr,
    )

    # generic path (fresh pubkeys) — informational; the tunnel's remote
    # compile intermittently drops large programs, so failures here must
    # not lose the headline measurement
    try:
        generic_fn = jax.jit(verify_prehashed)
        dt_generic = _time_best(generic_fn, pub, rb, sb, kb, s_ok)
        print(
            f"# generic path: {BATCH / dt_generic:,.0f} sigs/s "
            f"({dt_generic*1e3:.0f} ms/{BATCH})",
            file=sys.stderr,
        )
    except Exception as e:
        print(f"# generic path measurement failed: {e}", file=sys.stderr)
    print(
        json.dumps(
            {
                "metric": "ed25519_vote_verify_throughput",
                "value": round(cached_rate, 1),
                "unit": "sigs/s/chip",
                "vs_baseline": round(
                    cached_rate / BASELINE_SERIAL_SIGS_PER_S, 3
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
