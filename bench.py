"""Benchmark: batched ed25519 verification throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference verifies votes serially via Go x/crypto ed25519 —
~50-70 µs/verify single-core (SURVEY.md §6; crypto/ed25519/bench_test.go is
the reference harness, no stored numbers), i.e. ~15,000 sigs/s. The
BASELINE.json north-star targets >50k sigs/s/chip. vs_baseline is measured
sigs/s divided by the 15k serial-CPU figure.
"""

from __future__ import annotations

import json
import time

import numpy as np

BASELINE_SERIAL_SIGS_PER_S = 15_000.0


def main() -> None:
    import jax
    import jax.numpy as jnp

    from __graft_entry__ import _make_batch
    from tendermint_tpu.ops.ed25519_batch import verify_prehashed

    fn = jax.jit(verify_prehashed)

    batch = 2048
    pub, rb, sb, kb, s_ok = _make_batch(min(batch, 256))
    # tile the signed rows up to the full batch (unique rows are host-bound
    # to generate; verification cost on device is identical either way)
    reps = (batch + pub.shape[0] - 1) // pub.shape[0]

    def tile(x):
        return jnp.asarray(np.tile(x, (reps,) + (1,) * (x.ndim - 1))[:batch])

    args = (tile(pub), tile(rb), tile(sb), tile(kb), tile(s_ok))

    out = np.asarray(fn(*args))  # compile + warm
    assert out.all(), "benchmark batch failed to verify"

    iters = 5
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    out.block_until_ready()
    dt = (time.perf_counter() - t0) / iters
    sigs_per_s = batch / dt

    print(
        json.dumps(
            {
                "metric": "ed25519_batch_verify_throughput",
                "value": round(sigs_per_s, 1),
                "unit": "sigs/s/chip",
                "vs_baseline": round(sigs_per_s / BASELINE_SERIAL_SIGS_PER_S, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
